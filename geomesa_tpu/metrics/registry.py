"""Lightweight metrics registry (MetricsConfig analog,
metrics/config/MetricsConfig.scala:26): counters/gauges with optional
labels, timers backed by fixed-log-bucket histograms (p50/p95/p99 in
``snapshot()``), a delimited-file reporter hook, and Prometheus text
exposition for ``GET /rest/metrics?format=prometheus``."""

from __future__ import annotations

import math
import re
import threading
import time
from bisect import bisect_left

from ..utils.properties import SystemProperty

__all__ = ["MetricsRegistry", "metrics", "sanitize_key",
           "labeled_key", "split_key", "prometheus_text",
           "METRICS_MAX_SERIES"]

# per-family labeled-series ceiling: a hostile or runaway label value
# stream (type names, principals) must not grow the registry without
# bound — past the cap new label combinations collapse into one
# all-``other`` series and ``metrics.series.dropped`` counts the loss
METRICS_MAX_SERIES = SystemProperty("geomesa.metrics.max.series", "256")

# metric-key material derived from user-controlled strings (type names,
# endpoint routes) must not corrupt the registry dump: no whitespace or
# control characters, bounded length
_KEY_BAD = re.compile(r"[^0-9A-Za-z._:/-]+")
_KEY_MAX = 64


def sanitize_key(raw: str) -> str:
    """Make untrusted text safe as a metric-key segment: collapse
    anything outside [0-9A-Za-z._:/-] (spaces, newlines, quotes, ...)
    to ``_`` and cap the length, so a hostile type name or endpoint
    string cannot break the ``/rest/metrics`` registry dump or smuggle
    newlines into delimited reports."""
    s = _KEY_BAD.sub("_", str(raw))
    if len(s) > _KEY_MAX:
        s = s[:_KEY_MAX]
    return s or "_"


def _esc_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def labeled_key(name: str, labels: dict | None) -> str:
    """Registry key for a labeled metric: ``name{k="v",...}`` with
    sorted keys, Prometheus-style escaping. Label *names* are
    sanitized like key segments; values only escaped (they end up
    inside quotes)."""
    if not labels:
        return name
    inner = ",".join(f'{sanitize_key(k)}="{_esc_label(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


_LABELED = re.compile(r"^([^{]+)\{(.*)\}$")


def split_key(key: str) -> tuple[str, str]:
    """Inverse of labeled_key at the exposition layer: returns
    (base name, label body or '')."""
    m = _LABELED.match(key)
    return (m.group(1), m.group(2)) if m else (key, "")


# Fixed log-spaced histogram bounds: sqrt(2) steps from 1µs to ~46000s
# (64 buckets + overflow). Quantiles interpolate inside the matched
# bucket, so the relative error is bounded by the step (~±20%) at a
# fixed 65-slot cost per timer — cheap enough to leave on everywhere.
_BOUNDS = tuple(1e-6 * 2 ** (i / 2) for i in range(64))


class _Timer:
    """Timer = count/sum/max + a fixed-log-bucket histogram."""

    __slots__ = ("count", "total_s", "max_s", "buckets")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.buckets = [0] * (len(_BOUNDS) + 1)

    def update(self, seconds: float):
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)
        self.buckets[bisect_left(_BOUNDS, seconds)] += 1

    @property
    def mean_ms(self) -> float:
        return (self.total_s / self.count * 1000) if self.count else 0.0

    def quantile_s(self, q: float) -> float:
        """Histogram quantile estimate in seconds: find the bucket the
        rank lands in, interpolate linearly within it."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.buckets):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = _BOUNDS[i - 1] if i > 0 else 0.0
                hi = _BOUNDS[i] if i < len(_BOUNDS) else self.max_s
                hi = min(max(hi, lo), self.max_s) if self.max_s else hi
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.max_s

    def cumulative(self) -> list:
        """Sparse cumulative bucket pairs ``[upper_bound_s, count<=bound]``
        for occupied buckets only, terminated by ``[None, total]`` (the
        ``+Inf`` bucket) — the shape Prometheus ``_bucket`` lines need."""
        out, running = [], 0
        for i, c in enumerate(self.buckets[:-1]):
            if c == 0:
                continue
            running += c
            out.append([round(_BOUNDS[i], 9), running])
        out.append([None, self.count])
        return out


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._timers: dict[str, _Timer] = {}
        self._gauges: dict[str, float] = {}
        # family name -> label bodies seen, for the cardinality guard
        self._series: dict[str, set[str]] = {}

    def _series_key(self, name: str, labels: dict | None) -> str:
        """Registry key with the per-family cardinality guard applied
        (caller holds ``self._lock``): past ``geomesa.metrics.max.series``
        distinct label bodies, a NEW combination collapses into the
        family's all-``other`` series (admitted once, so the family
        tops out at cap+1) and ``metrics.series.dropped`` counts it."""
        if not labels:
            return name
        key = labeled_key(name, labels)
        body = key[len(name) + 1:-1]
        seen = self._series.setdefault(name, set())
        if body in seen:
            return key
        try:
            cap = int(METRICS_MAX_SERIES.get() or 256)
        except (TypeError, ValueError):
            cap = 256
        if len(seen) < cap:
            seen.add(body)
            return key
        self._counters["metrics.series.dropped"] = \
            self._counters.get("metrics.series.dropped", 0) + 1
        over = labeled_key(name, {k: "other" for k in labels})
        seen.add(over[len(name) + 1:-1])
        return over

    def counter(self, name: str, inc: int = 1,
                labels: dict | None = None):
        with self._lock:
            key = self._series_key(name, labels)
            self._counters[key] = self._counters.get(key, 0) + inc

    def gauge(self, name: str, value: float,
              labels: dict | None = None):
        with self._lock:
            self._gauges[self._series_key(name, labels)] = value

    def observe(self, name: str, seconds: float,
                labels: dict | None = None):
        """Record one duration directly (for callers that measured it
        themselves)."""
        with self._lock:
            key = self._series_key(name, labels)
            self._timers.setdefault(key, _Timer()).update(seconds)

    def time(self, name: str, labels: dict | None = None):
        reg = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *exc):
                dt = time.perf_counter() - self.t0
                with reg._lock:
                    key = reg._series_key(name, labels)
                    reg._timers.setdefault(key, _Timer()).update(dt)

        return _Ctx()

    def snapshot(self) -> dict:
        """JSON-safe snapshot. Non-finite gauge values (an EWMA can
        divide to inf/nan before warm-up) map to None — json.dumps
        would otherwise emit bare ``Infinity``/``NaN``, which is not
        JSON and breaks ``/rest/metrics`` consumers."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": {k: (v if isinstance(v, (int, float))
                               and math.isfinite(v) else None)
                           for k, v in self._gauges.items()},
                "timers": {k: {"count": t.count,
                               "mean_ms": round(t.mean_ms, 3),
                               "max_ms": round(t.max_s * 1000, 3),
                               "p50_ms": round(t.quantile_s(0.50) * 1000, 3),
                               "p95_ms": round(t.quantile_s(0.95) * 1000, 3),
                               "p99_ms": round(t.quantile_s(0.99) * 1000, 3),
                               # sparse cumulative histogram: [le_s, n]
                               # pairs for occupied buckets, None = +Inf
                               "buckets": t.cumulative()}
                           for k, t in self._timers.items()},
            }

    def report_delimited(self, path: str, delimiter: str = "\t"):
        """Append a snapshot via DelimitedFileReporter (single row
        format owner; see reporters.py)."""
        from .reporters import DelimitedFileReporter
        DelimitedFileReporter(path, delimiter).report(self.snapshot())

    def prometheus_text(self) -> str:
        return prometheus_text(self.snapshot())


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _PROM_BAD.sub("_", name)
    if not n or not (n[0].isalpha() or n[0] in "_:"):
        n = "_" + n
    return "geomesa_" + n


def _prom_line(name: str, label_body: str, extra: str, value) -> str:
    body = ",".join(x for x in (label_body, extra) if x)
    return (f"{name}{{{body}}} {value!r}" if body
            else f"{name} {value!r}")


def prometheus_text(snapshot: dict) -> str:
    """Prometheus text exposition (format version 0.0.4) of a
    snapshot: counters as ``_total`` counters, gauges as gauges
    (non-finite samples dropped), timers as summaries with
    p50/p95/p99 quantiles. Labeled registry keys split back into
    name + label body; ``# TYPE`` emitted once per metric family."""
    families: dict[str, tuple[str, list[str]]] = {}

    def fam(prom: str, mtype: str) -> list[str]:
        if prom not in families:
            families[prom] = (mtype, [])
        return families[prom][1]

    for key, v in sorted(snapshot.get("counters", {}).items()):
        base, lbl = split_key(key)
        prom = _prom_name(base) + "_total"
        fam(prom, "counter").append(_prom_line(prom, lbl, "", float(v)))
    for key, v in sorted(snapshot.get("gauges", {}).items()):
        if v is None or not math.isfinite(float(v)):
            continue
        base, lbl = split_key(key)
        prom = _prom_name(base)
        fam(prom, "gauge").append(_prom_line(prom, lbl, "", float(v)))
    for key, t in sorted(snapshot.get("timers", {}).items()):
        base, lbl = split_key(key)
        prom = _prom_name(base) + "_seconds"
        lines = fam(prom, "summary")
        for q, field in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                         ("0.99", "p99_ms")):
            val = t.get(field)
            if val is None:
                continue
            lines.append(_prom_line(prom, lbl, f'quantile="{q}"',
                                    float(val) / 1000.0))
        cnt = fam(prom + "_count", "")
        cnt.append(_prom_line(prom + "_count", lbl, "",
                              float(t.get("count", 0))))
        mean = t.get("mean_ms")
        if mean is not None:
            s = fam(prom + "_sum", "")
            s.append(_prom_line(
                prom + "_sum", lbl, "",
                float(mean) / 1000.0 * float(t.get("count", 0))))
        # native histogram family alongside the summary (Grafana
        # heatmaps need cumulative ``le`` buckets, which a summary
        # cannot express). A distinct ``_hist`` family name keeps the
        # 0.0.4 one-``# TYPE``-per-family rule intact.
        bks = t.get("buckets")
        if bks:
            hprom = _prom_name(base) + "_seconds_hist"
            hl = fam(hprom, "histogram")
            for le, cum in bks:
                le_txt = "+Inf" if le is None else f"{float(le):g}"
                hl.append(_prom_line(hprom + "_bucket", lbl,
                                     f'le="{le_txt}"', float(cum)))
            hl.append(_prom_line(hprom + "_count", lbl, "",
                                 float(t.get("count", 0))))
            if mean is not None:
                hl.append(_prom_line(
                    hprom + "_sum", lbl, "",
                    float(mean) / 1000.0 * float(t.get("count", 0))))

    out: list[str] = []
    for prom, (mtype, lines) in families.items():
        if mtype:
            out.append(f"# TYPE {prom} {mtype}")
        out.extend(lines)
    return "\n".join(out) + ("\n" if out else "")


metrics = MetricsRegistry()
