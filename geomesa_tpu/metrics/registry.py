"""Lightweight metrics registry (MetricsConfig analog,
metrics/config/MetricsConfig.scala:26): counters/timers/gauges with a
snapshot API and delimited-file reporting."""

from __future__ import annotations

import re
import threading
import time

__all__ = ["MetricsRegistry", "metrics", "sanitize_key"]

# metric-key material derived from user-controlled strings (type names,
# endpoint routes) must not corrupt the registry dump: no whitespace or
# control characters, bounded length
_KEY_BAD = re.compile(r"[^0-9A-Za-z._:/-]+")
_KEY_MAX = 64


def sanitize_key(raw: str) -> str:
    """Make untrusted text safe as a metric-key segment: collapse
    anything outside [0-9A-Za-z._:/-] (spaces, newlines, quotes, ...)
    to ``_`` and cap the length, so a hostile type name or endpoint
    string cannot break the ``/rest/metrics`` registry dump or smuggle
    newlines into delimited reports."""
    s = _KEY_BAD.sub("_", str(raw))
    if len(s) > _KEY_MAX:
        s = s[:_KEY_MAX]
    return s or "_"


class _Timer:
    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def update(self, seconds: float):
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    @property
    def mean_ms(self) -> float:
        return (self.total_s / self.count * 1000) if self.count else 0.0


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._timers: dict[str, _Timer] = {}
        self._gauges: dict[str, float] = {}

    def counter(self, name: str, inc: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc

    def gauge(self, name: str, value: float):
        with self._lock:
            self._gauges[name] = value

    def time(self, name: str):
        reg = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *exc):
                dt = time.perf_counter() - self.t0
                with reg._lock:
                    reg._timers.setdefault(name, _Timer()).update(dt)

        return _Ctx()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: {"count": t.count,
                               "mean_ms": round(t.mean_ms, 3),
                               "max_ms": round(t.max_s * 1000, 3)}
                           for k, t in self._timers.items()},
            }

    def report_delimited(self, path: str, delimiter: str = "\t"):
        """Append a snapshot via DelimitedFileReporter (single row
        format owner; see reporters.py)."""
        from .reporters import DelimitedFileReporter
        DelimitedFileReporter(path, delimiter).report(self.snapshot())


metrics = MetricsRegistry()
