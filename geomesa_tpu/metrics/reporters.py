"""Metric reporters (geomesa-metrics reporters analog:
DelimitedFileReporter, graphite/ganglia from MetricsConfig.scala:26).
Reporters format a registry snapshot; PeriodicReporter drives any of
them on an interval thread."""

from __future__ import annotations

import json
import threading
import time
from typing import Callable

__all__ = ["DelimitedFileReporter", "GraphiteLineReporter",
           "JsonLineReporter", "PeriodicReporter"]


def _flatten(snapshot: dict) -> list[tuple[str, float]]:
    out: list[tuple[str, float]] = []
    for name, v in sorted(snapshot.get("counters", {}).items()):
        out.append((f"counters.{name}", float(v)))
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        # snapshot() maps non-finite gauges to None; a delimited report
        # has no null, so those samples are simply dropped
        if v is None:
            continue
        out.append((f"gauges.{name}", float(v)))
    for name, t in sorted(snapshot.get("timers", {}).items()):
        for field, val in t.items():
            # histogram bucket lists are structured, not scalar — they
            # belong to the Prometheus exposition, not delimited rows
            if val is None or not isinstance(val, (int, float)):
                continue
            out.append((f"timers.{name}.{field}", float(val)))
    return out


class DelimitedFileReporter:
    """Append TSV/CSV rows: timestamp, metric, value."""

    def __init__(self, path: str, delimiter: str = "\t"):
        self.path = path
        self.delimiter = delimiter

    def report(self, snapshot: dict):
        ts = int(time.time() * 1000)
        with open(self.path, "a") as fh:
            for name, value in _flatten(snapshot):
                fh.write(self.delimiter.join(
                    (str(ts), name, repr(value))) + "\n")


class GraphiteLineReporter:
    """Graphite plaintext protocol lines ('<path> <value> <epoch>')
    handed to a sink callable — a socket send, a file append, a test
    list. Prefix mirrors the reporter config's metric prefix."""

    def __init__(self, sink: Callable[[str], None], prefix: str = "geomesa"):
        self.sink = sink
        self.prefix = prefix

    def report(self, snapshot: dict):
        epoch = int(time.time())
        for name, value in _flatten(snapshot):
            self.sink(f"{self.prefix}.{name} {value} {epoch}")


class JsonLineReporter:
    """One JSON object per report (log aggregation friendly)."""

    def __init__(self, path: str):
        self.path = path

    def report(self, snapshot: dict):
        with open(self.path, "a") as fh:
            fh.write(json.dumps({"ts": int(time.time() * 1000),
                                 **snapshot}, sort_keys=True) + "\n")


class PeriodicReporter:
    def __init__(self, registry, reporter, interval_s: float = 60.0):
        self.registry = registry
        self.reporter = reporter
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "PeriodicReporter":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.reporter.report(self.registry.snapshot())

    def stop(self, final_report: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()  # let an in-flight report finish first
            self._thread = None
        if final_report:
            self.reporter.report(self.registry.snapshot())
