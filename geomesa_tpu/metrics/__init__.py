"""Metrics registry (geomesa-metrics / Dropwizard analog): counters,
timers and gauges with pluggable reporters."""

from .registry import MetricsRegistry, metrics, sanitize_key

__all__ = ["MetricsRegistry", "metrics", "sanitize_key"]
