"""Metrics registry (geomesa-metrics / Dropwizard analog): counters,
timers and gauges with pluggable reporters."""

from .registry import MetricsRegistry, metrics

__all__ = ["MetricsRegistry", "metrics"]
