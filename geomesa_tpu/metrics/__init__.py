"""Metrics registry (geomesa-metrics / Dropwizard analog): counters,
timers and gauges with pluggable reporters."""

from .registry import (MetricsRegistry, labeled_key, metrics,
                       prometheus_text, sanitize_key, split_key)

__all__ = ["MetricsRegistry", "metrics", "sanitize_key", "labeled_key",
           "split_key", "prometheus_text"]
