"""Runtime telemetry: compile churn, device memory, transfer bytes.

The serving layer keeps several jit/plan shape-class caches (the query
batcher's fused-scan plans, the standing-filter sets' kernel shapes,
the join prewarm) whose MISSES predict XLA retraces — the single
biggest latency cliff on an accelerator tier. This collector is the
one place those caches report to: per-domain, per-shape-class
compile-vs-hit counts, fused-dispatch wall timers, host<->device
transfer bytes, and sampled device memory (current, high-water mark,
live buffer count/bytes).

Everything lands twice: in the labeled metrics registry
(``runtime.compile{domain,class,outcome}`` counters,
``runtime.dispatch{domain,class}`` timers, ``runtime.device.bytes``
gauges, ``runtime.h2d.bytes``/``runtime.d2h.bytes`` counters) for
scraping, and in an internal table the ``GET /rest/runtime`` snapshot
serves directly.

Device memory sampling NEVER force-initializes jax: it only looks if
``jax`` is already in ``sys.modules``, prefers ``device.memory_stats()``
(absent or None on CPU backends), and falls back to summing
``jax.live_arrays()`` byte sizes — so a CPU-only tier degrades to
host-buffer accounting instead of erroring.

Kill switch: ``geomesa.runtime.enabled`` (default true) — re-read per
call, so the bench's on/off overhead phases and a live operator both
work without restarts.
"""

from __future__ import annotations

import sys
import threading
import time

from ..metrics import metrics, sanitize_key
from ..utils.properties import SystemProperty

__all__ = ["RuntimeCollector", "runtime", "RUNTIME_ENABLED"]

RUNTIME_ENABLED = SystemProperty("geomesa.runtime.enabled", "true")


def _cls(shape) -> str:
    """A shape class (tuple of type/version/pow2 caps, or anything
    else a cache keys on) as a bounded metric-safe label value."""
    if isinstance(shape, (tuple, list)):
        return sanitize_key("/".join(str(x) for x in shape))
    return sanitize_key(str(shape))


class RuntimeCollector:
    def __init__(self, registry=metrics):
        self._registry = registry
        self._lock = threading.Lock()
        # (domain, class) -> [hits, misses]
        self._compiles: dict[tuple[str, str], list] = {}
        # (domain, class) -> [count, total_s, max_s]
        self._dispatches: dict[tuple[str, str], list] = {}
        self._h2d_bytes = 0
        self._d2h_bytes = 0
        self._mem: dict[str, dict] = {}     # device label -> stats
        self._live_buffers = 0
        self._live_bytes = 0
        self._live_bytes_hwm = 0
        self._mem_samples = 0
        self._mem_sampled_at: float | None = None

    @staticmethod
    def enabled() -> bool:
        return str(RUNTIME_ENABLED.get()).lower() in ("true", "1", "yes")

    # -- cache + dispatch hooks --------------------------------------------

    def note_plan_probe(self, domain: str, shape, hit: bool):
        """One shape-class cache probe: a miss is a predicted compile."""
        if not self.enabled():
            return
        cls = _cls(shape)
        with self._lock:
            row = self._compiles.setdefault((domain, cls), [0, 0])
            row[0 if hit else 1] += 1
        self._registry.counter(
            "runtime.compile",
            labels={"domain": domain, "class": cls,
                    "outcome": "hit" if hit else "miss"})

    def note_dispatch(self, domain: str, shape, seconds: float,
                      h2d_bytes: int = 0, d2h_bytes: int = 0):
        """One device dispatch: wall seconds + transfer bytes."""
        if not self.enabled():
            return
        cls = _cls(shape)
        with self._lock:
            row = self._dispatches.setdefault((domain, cls),
                                              [0, 0.0, 0.0])
            row[0] += 1
            row[1] += seconds
            row[2] = max(row[2], seconds)
            self._h2d_bytes += int(h2d_bytes)
            self._d2h_bytes += int(d2h_bytes)
        self._registry.observe("runtime.dispatch", seconds,
                               labels={"domain": domain, "class": cls})
        if h2d_bytes:
            self._registry.counter("runtime.h2d.bytes", int(h2d_bytes))
        if d2h_bytes:
            self._registry.counter("runtime.d2h.bytes", int(d2h_bytes))

    # -- device memory -----------------------------------------------------

    def sample_device_memory(self):
        """Sample device memory if jax is already loaded (a telemetry
        thread must never be the thing that initializes a backend)."""
        if not self.enabled():
            return
        jax = sys.modules.get("jax")
        if jax is None:
            return
        per_dev: dict[str, dict] = {}
        try:
            devices = jax.devices()
        except Exception:  # noqa: BLE001 — backend may be mid-init
            return
        for d in devices:
            label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
            stats = None
            try:
                fn = getattr(d, "memory_stats", None)
                stats = fn() if callable(fn) else None
            except Exception:  # noqa: BLE001 — CPU backends raise/None
                stats = None
            if not stats:
                continue
            in_use = int(stats.get("bytes_in_use", 0) or 0)
            peak = int(stats.get("peak_bytes_in_use", in_use) or in_use)
            per_dev[label] = {"bytes_in_use": in_use,
                              "peak_bytes_in_use": peak}
        live_n = live_b = 0
        try:
            for arr in jax.live_arrays():
                live_n += 1
                live_b += int(getattr(arr, "nbytes", 0) or 0)
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            for label, st in per_dev.items():
                prev = self._mem.get(label, {})
                st["hwm_bytes"] = max(st["peak_bytes_in_use"],
                                      int(prev.get("hwm_bytes", 0)))
                self._mem[label] = st
            self._live_buffers = live_n
            self._live_bytes = live_b
            self._live_bytes_hwm = max(self._live_bytes_hwm, live_b)
            self._mem_samples += 1
            self._mem_sampled_at = time.time()
        reg = self._registry
        for label, st in per_dev.items():
            reg.gauge("runtime.device.bytes", st["bytes_in_use"],
                      labels={"device": label})
            reg.gauge("runtime.device.bytes.peak", st["peak_bytes_in_use"],
                      labels={"device": label})
        reg.gauge("runtime.device.live_buffers", live_n)
        reg.gauge("runtime.device.live_bytes", live_b)

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /rest/runtime`` document (JSON-safe)."""
        with self._lock:
            compiles: dict[str, dict] = {}
            for (domain, cls), (hits, misses) in self._compiles.items():
                compiles.setdefault(domain, {})[cls] = {
                    "hits": hits, "misses": misses}
            dispatches: dict[str, dict] = {}
            for (domain, cls), (n, tot, mx) in self._dispatches.items():
                dispatches.setdefault(domain, {})[cls] = {
                    "count": n,
                    "total_ms": round(tot * 1e3, 3),
                    "mean_ms": round(tot / n * 1e3, 3) if n else 0.0,
                    "max_ms": round(mx * 1e3, 3)}
            return {
                "enabled": self.enabled(),
                "compile": compiles,
                "dispatch": dispatches,
                "transfer": {"h2d_bytes": self._h2d_bytes,
                             "d2h_bytes": self._d2h_bytes},
                "device_memory": {
                    "devices": {k: dict(v) for k, v in self._mem.items()},
                    "live_buffers": self._live_buffers,
                    "live_bytes": self._live_bytes,
                    "live_bytes_hwm": self._live_bytes_hwm,
                    "samples": self._mem_samples,
                    "sampled_at": self._mem_sampled_at,
                },
            }

    def clear(self):
        with self._lock:
            self._compiles.clear()
            self._dispatches.clear()
            self._h2d_bytes = self._d2h_bytes = 0
            self._mem.clear()
            self._live_buffers = self._live_bytes = 0
            self._live_bytes_hwm = 0
            self._mem_samples = 0
            self._mem_sampled_at = None


runtime = RuntimeCollector()
