"""Observability: Dapper-style request tracing (spans, wire
propagation, bounded ring + JSONL export) — see trace.py for the
model. The metrics histograms live in ``geomesa_tpu.metrics``; the
audit plane in ``geomesa_tpu.audit``."""

from .trace import (TRACE_HEADER, TRACE_MAX_SPANS, TRACE_PATH,
                    TRACE_SAMPLE, TRACE_SLOW_MS, Span, Tracer, annotate,
                    current_trace_id, get_flag, set_flag, tracer)

__all__ = ["TRACE_HEADER", "TRACE_SAMPLE", "TRACE_SLOW_MS",
           "TRACE_MAX_SPANS", "TRACE_PATH", "Span", "Tracer", "tracer",
           "annotate", "set_flag", "get_flag", "current_trace_id"]
