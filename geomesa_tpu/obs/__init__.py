"""Observability: Dapper-style request tracing (spans, wire
propagation, bounded ring + JSONL export — trace.py) plus the runtime
health plane: compile/device/transfer telemetry (runtime.py), the SLO
burn-rate engine with its admission-tightening reaction loop (slo.py),
and the always-on sampling profiler + stall watchdog (prof.py). The
metrics histograms live in ``geomesa_tpu.metrics``; the audit plane in
``geomesa_tpu.audit``."""

from .prof import (PROF_HZ, ContinuousProfiler, StallWatchdog, profiler,
                   watchdog)
from .runtime import RUNTIME_ENABLED, RuntimeCollector, runtime
from .slo import (SLO_ENABLED, SLO_REACT, SloEngine, slo_engine)
from .trace import (TRACE_HEADER, TRACE_MAX_SPANS, TRACE_PATH,
                    TRACE_SAMPLE, TRACE_SLOW_MS, Span, Tracer, annotate,
                    current_trace_id, get_flag, set_flag, tracer)

__all__ = ["TRACE_HEADER", "TRACE_SAMPLE", "TRACE_SLOW_MS",
           "TRACE_MAX_SPANS", "TRACE_PATH", "Span", "Tracer", "tracer",
           "annotate", "set_flag", "get_flag", "current_trace_id",
           "RuntimeCollector", "runtime", "RUNTIME_ENABLED",
           "SloEngine", "slo_engine", "SLO_ENABLED", "SLO_REACT",
           "ContinuousProfiler", "StallWatchdog", "profiler",
           "watchdog", "PROF_HZ"]
