"""Always-on sampling profiler + stall-capturing watchdog.

**Profiler.** A daemon thread snapshots every thread's Python stack
via ``sys._current_frames()`` at ``geomesa.prof.hz`` (default 19 —
prime, so the sampler cannot phase-lock with periodic work) and folds
the stacks into a bounded trie keyed by ``file:function`` frames.
``GET /rest/profile`` serves the aggregate in collapsed-stack format
(one line per observed stack, root-first frames joined by ``;``, a
space, then the sample count) — the exact input flamegraph.pl /
speedscope / Grafana flame panels eat. The trie is capped at
``geomesa.prof.max.nodes`` (8192); past the cap, new frames collapse
into a ``<trunc>`` child so memory stays bounded under pathological
stack diversity. Overhead is one GIL-held stack walk per tick —
the bench gates the whole health plane under 5% at c=32.

**Watchdog.** Every dispatch / WAL fsync / scatter leg / ingest group
commit registers itself (op key, owning thread, start time, trace
span) for the duration of the call. Each profiler tick — or an
explicit ``check(now)`` with a fake clock — compares open ops against
``geomesa.prof.watchdog.factor`` x their op-class p99 (learned from
completed ops; ``geomesa.prof.watchdog.min.ms`` floors it). An op
past its threshold gets its owning thread's LIVE stack captured into
the op's trace span (``watchdog.stall`` annotation + ``stalled``
attr), and the span's trace is force-kept even at sample rate 0 — a
stalled query in the ring says *where it was stuck*, not just that it
was slow.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from ..metrics import metrics, sanitize_key
from ..metrics.registry import _Timer
from ..utils.properties import SystemProperty

__all__ = ["ContinuousProfiler", "StallWatchdog", "profiler", "watchdog",
           "PROF_HZ", "PROF_MAX_NODES", "WATCHDOG_FACTOR",
           "WATCHDOG_MIN_MS"]

PROF_HZ = SystemProperty("geomesa.prof.hz", "19")
PROF_MAX_NODES = SystemProperty("geomesa.prof.max.nodes", "8192")
WATCHDOG_FACTOR = SystemProperty("geomesa.prof.watchdog.factor", "8")
WATCHDOG_MIN_MS = SystemProperty("geomesa.prof.watchdog.min.ms", "100")

_MAX_DEPTH = 64


def _frame_label(frame) -> str:
    co = frame.f_code
    return f"{os.path.basename(co.co_filename)}:{co.co_name}"


def _walk_stack(frame) -> list[str]:
    """Root-first frame labels, depth-capped."""
    out: list[str] = []
    f = frame
    while f is not None and len(out) < _MAX_DEPTH:
        out.append(_frame_label(f))
        f = f.f_back
    out.reverse()
    return out


class _TrieNode:
    __slots__ = ("children", "count")

    def __init__(self):
        self.children: dict[str, _TrieNode] = {}
        self.count = 0


class ContinuousProfiler:
    """Bounded-trie sampling profiler. ``start``/``stop`` are
    refcounted (every web server holds a reference while serving), so
    two servers in one process share one sampler thread."""

    def __init__(self, registry=metrics):
        self._registry = registry
        self._lock = threading.Lock()
        self._root = _TrieNode()
        self._nodes = 1
        self._samples = 0
        self._truncated = 0
        self._refs = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @staticmethod
    def hz() -> float:
        try:
            return max(float(PROF_HZ.get() or 0.0), 0.0)
        except (TypeError, ValueError):
            return 0.0

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        with self._lock:
            self._refs += 1
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="geomesa-prof")
            self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._refs = max(self._refs - 1, 0)
            if self._refs > 0 or self._thread is None:
                return
            t = self._thread
            self._thread = None
            self._stop.set()
        t.join(timeout=2.0)

    @property
    def running(self) -> bool:
        return self._thread is not None

    def _run(self):
        last_mem = 0.0
        while not self._stop.is_set():
            hz = self.hz()   # live: hz=0 parks the thread, not kills it
            if hz <= 0:
                self._stop.wait(0.25)
                continue
            self.sample_once()
            watchdog.check()
            now = time.monotonic()
            if now - last_mem >= 1.0:
                # device memory at ~1Hz: jax.live_arrays is too heavy
                # for every tick
                last_mem = now
                from .runtime import runtime
                runtime.sample_device_memory()
            self._stop.wait(1.0 / hz)

    # -- sampling ----------------------------------------------------------

    def sample_once(self):
        me = threading.get_ident()
        frames = sys._current_frames()  # noqa: SLF001 — the documented API
        with self._lock:
            for tid, frame in frames.items():
                if tid == me:
                    continue
                self._insert(_walk_stack(frame))
            self._samples += 1
        self._registry.counter("prof.samples")

    def _insert(self, stack: list[str]):
        try:
            cap = int(PROF_MAX_NODES.get() or 8192)
        except (TypeError, ValueError):
            cap = 8192
        node = self._root
        for label in stack:
            child = node.children.get(label)
            if child is None:
                if self._nodes >= cap:
                    self._truncated += 1
                    child = node.children.get("<trunc>")
                    if child is None:
                        child = _TrieNode()
                        node.children["<trunc>"] = child
                        self._nodes += 1
                    node = child
                    break
                child = _TrieNode()
                node.children[label] = child
                self._nodes += 1
            node = child
        node.count += 1

    # -- export ------------------------------------------------------------

    def collapsed(self) -> str:
        """Collapsed-stack text: ``frame;frame;frame N`` per line."""
        lines: list[str] = []
        with self._lock:
            stack = [(self._root, [])]
            while stack:
                node, prefix = stack.pop()
                for label, ch in sorted(node.children.items(),
                                        reverse=True):
                    p = prefix + [label]
                    if ch.count:
                        lines.append(";".join(p) + f" {ch.count}")
                    stack.append((ch, p))
        return "\n".join(lines) + ("\n" if lines else "")

    def stats(self) -> dict:
        with self._lock:
            return {"running": self._thread is not None,
                    "hz": self.hz(),
                    "samples": self._samples,
                    "nodes": self._nodes,
                    "truncated": self._truncated}

    def clear(self):
        with self._lock:
            self._root = _TrieNode()
            self._nodes = 1
            self._samples = 0
            self._truncated = 0


class StallWatchdog:
    """Detects watched operations open past N x their op-class p99 and
    captures the owning thread's live stack into the op's trace span.

    ``watch(key, span=...)`` is the instrumentation contract: a cheap
    context manager that registers the op on entry and, on exit, folds
    the duration into the key's latency history (the p99 source).
    ``check(now)`` is driven by the profiler thread in production and
    called directly with a fake clock in tests."""

    _HISTORY_MIN = 4     # cold keys use the floored minimum instead

    def __init__(self, registry=metrics, clock=time.monotonic):
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._active: dict[int, dict] = {}
        self._next_id = 0
        self._history: dict[str, _Timer] = {}
        self._stalls: deque = deque(maxlen=32)
        self.stall_count = 0

    # -- instrumentation contract ------------------------------------------

    def watch(self, key: str, span=None):
        wd = self

        class _Watch:
            __slots__ = ("token",)

            def __enter__(self):
                self.token = wd._register(key, span)
                return self

            def __exit__(self, *exc):
                wd._finish(self.token)

        return _Watch()

    def _register(self, key: str, span) -> int:
        with self._lock:
            self._next_id += 1
            token = self._next_id
            self._active[token] = {
                "key": key, "span": span,
                "tid": threading.get_ident(),
                "t0": self._clock(), "captured": False}
            return token

    def _finish(self, token: int):
        with self._lock:
            op = self._active.pop(token, None)
            if op is None:
                return
            dt = self._clock() - op["t0"]
            self._history.setdefault(op["key"], _Timer()).update(dt)

    # -- detection ---------------------------------------------------------

    def threshold_s(self, key: str) -> float:
        """factor x the key's learned p99, floored at the min-ms knob;
        keys with too little history use the floor alone (scaled by
        the factor) so a cold tier still catches gross stalls."""
        try:
            factor = max(float(WATCHDOG_FACTOR.get() or 8.0), 0.0)
        except (TypeError, ValueError):
            factor = 8.0
        floor = (WATCHDOG_MIN_MS.as_float() or 100.0) / 1e3
        t = self._history.get(key)
        if t is None or t.count < self._HISTORY_MIN:
            return max(floor * max(factor, 1.0), floor)
        return max(t.quantile_s(0.99) * factor, floor)

    def check(self, now: float | None = None) -> list[dict]:
        """Scan open ops; capture (once per op) any past threshold.
        Returns the newly captured stall records."""
        try:
            if float(WATCHDOG_FACTOR.get() or 8.0) <= 0:
                return []
        except (TypeError, ValueError):
            pass
        if now is None:
            now = self._clock()
        with self._lock:
            candidates = [(tok, dict(op))
                          for tok, op in self._active.items()
                          if not op["captured"]]
        if not candidates:
            return []
        frames = sys._current_frames()  # noqa: SLF001
        captured: list[dict] = []
        for token, op in candidates:
            elapsed = now - op["t0"]
            thr = self.threshold_s(op["key"])
            if elapsed <= thr:
                continue
            frame = frames.get(op["tid"])
            stack = _walk_stack(frame) if frame is not None else []
            record = {"key": op["key"], "thread_id": op["tid"],
                      "elapsed_s": round(elapsed, 6),
                      "threshold_s": round(thr, 6),
                      "stack": stack}
            with self._lock:
                live = self._active.get(token)
                if live is None or live["captured"]:
                    continue   # finished or raced with another check
                live["captured"] = True
                self._stalls.append(record)
                self.stall_count += 1
            self._registry.counter(
                "prof.watchdog.stalls",
                labels={"op": sanitize_key(op["key"])})
            span = op["span"]
            if span is not None:
                try:
                    span.annotate("watchdog.stall",
                                  elapsed_ms=round(elapsed * 1e3, 3),
                                  threshold_ms=round(thr * 1e3, 3),
                                  stack=";".join(stack))
                    span.set_attr(stalled=True)
                    # force-keep: a stalled trace must land in the
                    # ring even at sample rate 0
                    state = getattr(span, "_state", None)
                    if state is not None:
                        state.sampled = True
                except Exception:  # noqa: BLE001 — null spans etc.
                    pass
            captured.append(record)
        return captured

    # -- surfaces ----------------------------------------------------------

    def stalls(self) -> list[dict]:
        with self._lock:
            return list(self._stalls)

    def stats(self) -> dict:
        with self._lock:
            return {"active": len(self._active),
                    "stall_count": self.stall_count,
                    "keys_learned": len(self._history),
                    "recent": list(self._stalls)}

    def clear(self):
        with self._lock:
            self._active.clear()
            self._history.clear()
            self._stalls.clear()
            self.stall_count = 0


profiler = ContinuousProfiler()
watchdog = StallWatchdog()
