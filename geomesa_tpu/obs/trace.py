"""Dapper-style request tracing (the observability substrate the
multi-host / multi-tenant roadmap items report through).

Model
-----
A **trace** is a tree of **spans** sharing one ``trace_id``. Spans are
propagated in-process through a ``contextvars.ContextVar`` (so nested
``with tracer.span(...)`` calls parent correctly across the async-free
thread-per-request server) and across the wire through the
``X-GeoMesa-Trace`` header (``trace_id:span_id:sampled``), so one trace
stitches the RemoteDataStore client leg, the web handler, and the
downstream cluster shard legs into a single tree.

Two capture policies compose:

- **head sampling** — ``geomesa.trace.sample`` (probability 0..1)
  decides at the local root whether the trace is kept regardless of
  outcome; the decision rides the wire flag so downstream processes
  keep their halves too;
- **slow-query always-capture** — every local root buffers its spans,
  and if the root exceeds ``geomesa.trace.slow.ms`` the trace is kept
  even when sampling said no. Set the threshold to 0 to disable.

Kept traces land in a bounded in-memory ring (total spans capped by
``geomesa.trace.max.spans``, oldest trace evicted whole) and are
optionally appended as JSONL to ``geomesa.trace.path``. Surfaces:
``GET /rest/trace`` (list / get-by-id) and the ``tools trace`` CLI.

Fan-in legs (the batcher's fused dispatch serving N coalesced queries,
the ingest group commit covering N staged batches) record **links** to
the waiting callers' spans; ``Tracer.graft`` additionally clones the
dispatch subtree into each follower's trace so a follower's slow-query
capture still shows where its time went.
"""

from __future__ import annotations

import contextvars
import json
import random
import threading
import time
from collections import OrderedDict

from ..utils.properties import SystemProperty

__all__ = [
    "TRACE_HEADER", "TRACE_SAMPLE", "TRACE_SLOW_MS", "TRACE_MAX_SPANS",
    "TRACE_PATH", "Span", "Tracer", "tracer", "annotate", "set_flag",
    "get_flag", "current_trace_id",
]

TRACE_HEADER = "X-GeoMesa-Trace"

TRACE_SAMPLE = SystemProperty("geomesa.trace.sample", "0")
TRACE_SLOW_MS = SystemProperty("geomesa.trace.slow.ms", "1000")
TRACE_MAX_SPANS = SystemProperty("geomesa.trace.max.spans", "8192")
TRACE_PATH = SystemProperty("geomesa.trace.path", None)


def _new_id() -> str:
    return f"{random.getrandbits(64):016x}"


class _TraceState:
    """Per-trace bookkeeping shared by every span of one local trace:
    the head-sampling decision, the finished-span buffer (kept or
    dropped wholesale when the local root ends), and the flags dict
    cross-layer instrumentation writes into (cache_hit, hedged, ...)
    so the audit hook can read them without plumbing arguments through
    every tier."""

    __slots__ = ("trace_id", "sampled", "spans", "flags", "start_ms")

    def __init__(self, trace_id: str, sampled: bool):
        self.trace_id = trace_id
        self.sampled = sampled
        self.spans: list[Span] = []
        self.flags: dict = {}
        self.start_ms = int(time.time() * 1000)


# (state, current span) — None outside any trace
_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_trace_ctx", default=None)


class Span:
    """One timed operation. Context manager: entering makes it the
    current span for the calling context; exiting records it into the
    trace buffer and, for the local root, decides keep/drop."""

    __slots__ = ("trace_id", "span_id", "parent_id", "kind", "name",
                 "start_ms", "duration_ms", "attrs", "annotations",
                 "links", "error", "_t0", "_state", "_token", "_root")

    def __init__(self, state: _TraceState, kind: str, name: str,
                 parent_id: str | None, root: bool):
        self.trace_id = state.trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.kind = kind
        self.name = name
        self.start_ms = int(time.time() * 1000)
        self.duration_ms = 0.0
        self.attrs: dict = {}
        self.annotations: list = []
        self.links: list = []
        self.error: str | None = None
        self._t0 = time.perf_counter()
        self._state = state
        self._token = None
        self._root = root

    # -- enrichment -------------------------------------------------
    def annotate(self, text: str, **attrs):
        note = {"t_ms": round((time.perf_counter() - self._t0) * 1000, 3),
                "text": str(text)}
        if attrs:
            note.update(attrs)
        self.annotations.append(note)

    def set_attr(self, **attrs):
        self.attrs.update(attrs)

    def link(self, trace_id: str, span_id: str):
        self.links.append({"trace_id": trace_id, "span_id": span_id})

    def set_flag(self, name: str, value=True):
        """Set a trace-level flag (read by the audit hook) directly on
        this span's trace — usable from callback threads that do not
        carry the caller's contextvars."""
        self._state.flags[name] = value

    # -- context protocol -------------------------------------------
    def __enter__(self):
        self._token = _CTX.set((self._state, self))
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and self.error is None:
            self.error = f"{type(exc).__name__}: {exc}"
        self.finish()
        return False

    def finish(self):
        if self._token is not None:
            try:
                _CTX.reset(self._token)
            except ValueError:
                # crossed a context boundary (finished in a different
                # context than it was entered in); current-span cleanup
                # is best-effort there
                pass
            self._token = None
        if self.duration_ms == 0.0:
            self.duration_ms = round(
                (time.perf_counter() - self._t0) * 1000, 3)
        self._state.spans.append(self)
        if self._root:
            tracer._finalize(self._state, self)

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "kind": self.kind,
             "name": self.name, "start_ms": self.start_ms,
             "duration_ms": self.duration_ms}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.annotations:
            d["annotations"] = list(self.annotations)
        if self.links:
            d["links"] = list(self.links)
        if self.error:
            d["error"] = self.error
        return d

    def _clone_into(self, state: _TraceState,
                    parent_id: str | None) -> "Span":
        c = Span.__new__(Span)
        c.trace_id = state.trace_id
        c.span_id = self.span_id      # identity preserved: the link
        c.parent_id = parent_id       # from the follower resolves it
        c.kind = self.kind
        c.name = self.name
        c.start_ms = self.start_ms
        c.duration_ms = self.duration_ms
        c.attrs = dict(self.attrs)
        c.annotations = list(self.annotations)
        c.links = list(self.links)
        c.error = self.error
        c._t0 = self._t0
        c._state = state
        c._token = None
        c._root = False
        return c


class _NullSpan:
    """No-op stand-in when tracing is inactive for this call path:
    every method is a cheap no-op so instrumentation sites never
    branch."""

    trace_id = None
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, text, **attrs):
        pass

    def set_attr(self, **attrs):
        pass

    def link(self, trace_id, span_id):
        pass

    def set_flag(self, name, value=True):
        pass

    def finish(self):
        pass


_NULL = _NullSpan()


class Tracer:
    """Process-wide tracer: span factory + bounded ring of kept
    traces."""

    def __init__(self):
        self._lock = threading.Lock()
        # trace_id -> list[span dict]; bounded by total span count
        self._traces: "OrderedDict[str, list[dict]]" = OrderedDict()
        self._span_count = 0

    # -- configuration ---------------------------------------------
    @staticmethod
    def sample_rate() -> float:
        try:
            return float(TRACE_SAMPLE.get() or 0)
        except (TypeError, ValueError):
            return 0.0

    @staticmethod
    def slow_ms() -> float:
        try:
            return float(TRACE_SLOW_MS.get() or 0)
        except (TypeError, ValueError):
            return 0.0

    def enabled(self) -> bool:
        return self.sample_rate() > 0 or self.slow_ms() > 0

    # -- span factory ----------------------------------------------
    def span(self, kind: str, name: str = "", *, root: bool = False,
             remote: str | None = None):
        """Open a span. Child spans attach to the current context and
        no-op when there is none; ``root=True`` starts a new local
        trace (serving entry points: web handler, batcher admission,
        ingest group commit); ``remote`` is an incoming
        ``X-GeoMesa-Trace`` header value continuing a wire trace."""
        cur = _CTX.get()
        if cur is not None:
            state, parent = cur
            return Span(state, kind, name or kind, parent.span_id, False)
        wire = self.extract(remote) if remote else None
        if wire is not None:
            tid, parent_id, wire_sampled = wire
            if not (wire_sampled or self.enabled()):
                return _NULL
            state = _TraceState(tid, wire_sampled or self._head_sample())
            return Span(state, kind, name or kind, parent_id, True)
        if not root or not self.enabled():
            return _NULL
        state = _TraceState(_new_id(), self._head_sample())
        return Span(state, kind, name or kind, None, True)

    def _head_sample(self) -> bool:
        rate = self.sample_rate()
        if rate <= 0:
            return False
        if rate >= 1:
            return True
        return random.random() < rate

    # -- context access --------------------------------------------
    @staticmethod
    def current():
        """(state, span) of the calling context, or None. Capture this
        to link/graft across threads (batcher followers, scatter
        legs)."""
        return _CTX.get()

    @staticmethod
    def current_span():
        cur = _CTX.get()
        return cur[1] if cur is not None else _NULL

    # -- wire propagation ------------------------------------------
    def inject(self) -> str | None:
        """Header value carrying the current span context, or None."""
        cur = _CTX.get()
        if cur is None:
            return None
        state, span = cur
        return f"{state.trace_id}:{span.span_id}:{int(state.sampled)}"

    @staticmethod
    def extract(header: str | None):
        """Parse ``trace_id:span_id:sampled`` -> tuple or None."""
        if not header:
            return None
        parts = str(header).strip().split(":")
        if len(parts) != 3 or not parts[0] or not parts[1]:
            return None
        return parts[0], parts[1], parts[2] == "1"

    # -- fan-in stitching ------------------------------------------
    def graft(self, span: Span, targets) -> int:
        """Clone ``span`` and its finished descendants into each
        target context's trace (the batcher's fused dispatch subtree
        into every coalesced follower), re-parenting the subtree root
        under the target's current span. Span ids are preserved so the
        follower's recorded link resolves to the grafted copy. Returns
        the number of traces grafted into."""
        if isinstance(span, _NullSpan):
            return 0
        src = span._state
        by_id = {s.span_id: s for s in src.spans}
        subtree = []
        for s in src.spans:
            pid = s.span_id
            while pid is not None:
                if pid == span.span_id:
                    subtree.append(s)
                    break
                parent = by_id.get(pid)
                pid = parent.parent_id if parent is not None else None
        n = 0
        for ctx in targets:
            if not ctx:
                continue
            state, tspan = ctx
            if state is src:
                continue          # the leader already owns the subtree
            for s in subtree:
                state.spans.append(s._clone_into(
                    state, tspan.span_id if s is span else s.parent_id))
            n += 1
        return n

    # -- ring ------------------------------------------------------
    def _finalize(self, state: _TraceState, root: Span):
        keep = state.sampled
        if not keep:
            slow = self.slow_ms()
            keep = slow > 0 and root.duration_ms >= slow
        if not keep:
            state.spans.clear()
            return
        spans = [s.to_dict() for s in list(state.spans)]
        try:
            cap = int(float(TRACE_MAX_SPANS.get() or 8192))
        except (TypeError, ValueError):
            cap = 8192
        with self._lock:
            if state.trace_id in self._traces:
                # a second local root of the same wire trace (e.g. two
                # scatter legs hitting one shard server): merge
                self._span_count -= len(self._traces[state.trace_id])
                spans = self._traces.pop(state.trace_id) + spans
            self._traces[state.trace_id] = spans
            self._span_count += len(spans)
            while self._span_count > cap and len(self._traces) > 1:
                _, old = self._traces.popitem(last=False)
                self._span_count -= len(old)
        path = TRACE_PATH.get()
        if path:
            try:
                with open(path, "a") as fh:
                    for d in spans:
                        fh.write(json.dumps(d, default=str) + "\n")
            except OSError:
                pass

    def traces(self, limit: int = 50) -> list[dict]:
        """Newest-first trace summaries for ``GET /rest/trace``."""
        with self._lock:
            items = list(self._traces.items())
        out = []
        for tid, spans in reversed(items[-max(0, int(limit)):]):
            roots = [s for s in spans if s.get("parent_id") is None]
            head = roots[0] if roots else spans[0]
            out.append({
                "trace_id": tid, "spans": len(spans),
                "root_kind": head["kind"], "root_name": head["name"],
                "start_ms": head["start_ms"],
                "duration_ms": head["duration_ms"],
                "error": any(s.get("error") for s in spans),
                "kinds": sorted({s["kind"] for s in spans}),
            })
        return out

    def get(self, trace_id: str) -> list[dict] | None:
        with self._lock:
            spans = self._traces.get(trace_id)
            return list(spans) if spans is not None else None

    def clear(self):
        with self._lock:
            self._traces.clear()
            self._span_count = 0


tracer = Tracer()


# -- module-level conveniences (cheap no-ops outside a trace) --------
def annotate(text: str, **attrs):
    cur = _CTX.get()
    if cur is not None:
        cur[1].annotate(text, **attrs)


def set_flag(name: str, value=True):
    cur = _CTX.get()
    if cur is not None:
        cur[0].flags[name] = value


def get_flag(name: str, default=None):
    cur = _CTX.get()
    if cur is not None:
        return cur[0].flags.get(name, default)
    return default


def current_trace_id() -> str | None:
    cur = _CTX.get()
    return cur[0].trace_id if cur is not None else None
