"""SLO burn-rate engine: multi-window multi-burn-rate alerting.

Declarative service-level objectives per route/surface (availability
and a latency objective), evaluated the way the SRE workbook's
multiwindow multi-burn-rate recipe prescribes:

- **burn rate** = (observed bad fraction) / (error budget), where the
  budget is ``1 - objective``. Burn 1.0 spends exactly the budget over
  the SLO period; burn 14.4 spends 2% of a 30-day budget in one hour.
- **fast burn** (page): burn >= 14.4 on BOTH the 5m and 1h windows —
  the short window makes the alert reset quickly once the bleeding
  stops, the long window keeps a blip from paging.
- **slow burn** (ticket): burn >= 1.0 on BOTH the 6h and 3d windows —
  a sustained trickle that will exhaust the budget, invisible to the
  fast rule.

Events are folded into two bucket rings per series (1s x 1h fine ring
for the fast windows, 60s x 3d coarse ring for the slow ones), so
``record`` is O(1) and a window sum is a bounded slot scan. The clock
is injectable and every entry point takes an explicit ``now`` — the
burn math is testable against synthetic streams with zero sleeps.

Closing the loop (``geomesa.slo.react``, default OFF): while any fast
burn fires, admission tightens — the shared retry/hedge budgets scale
down (``geomesa.retry.budget.scale``), the batcher linger ceiling
drops, and ingest shedding gets more sensitive. The pre-reaction
override state of every touched knob is saved and restored EXACTLY
when the burn clears.

Knobs: ``geomesa.slo.enabled``, ``geomesa.slo.availability.target``
(0.999), ``geomesa.slo.latency.ms`` (500) + ``geomesa.slo.latency.target``
(0.99), ``geomesa.slo.windows.fast`` ("300:3600:14.4"),
``geomesa.slo.windows.slow`` ("21600:259200:1.0"),
``geomesa.slo.min.events`` (12), ``geomesa.slo.react`` (false),
``geomesa.slo.react.factor`` (4), ``geomesa.slo.max.routes`` (64).

Surfaced at ``GET /rest/slo`` and as ``slo.burn``/``slo.alert``
gauges; alert transitions count ``slo.alerts.fired`` / ``.cleared``.
"""

from __future__ import annotations

import threading
import time

from ..metrics import metrics, sanitize_key
from ..utils.properties import SystemProperty

__all__ = ["SloEngine", "slo_engine", "SLO_ENABLED", "SLO_REACT",
           "SLO_AVAILABILITY_TARGET", "SLO_LATENCY_MS",
           "SLO_LATENCY_TARGET", "SLO_WINDOWS_FAST", "SLO_WINDOWS_SLOW",
           "SLO_MIN_EVENTS", "SLO_REACT_FACTOR", "SLO_MAX_ROUTES"]

SLO_ENABLED = SystemProperty("geomesa.slo.enabled", "true")
SLO_AVAILABILITY_TARGET = SystemProperty(
    "geomesa.slo.availability.target", "0.999")
SLO_LATENCY_MS = SystemProperty("geomesa.slo.latency.ms", "500")
SLO_LATENCY_TARGET = SystemProperty("geomesa.slo.latency.target", "0.99")
# "short:long:threshold" (seconds, seconds, burn multiple)
SLO_WINDOWS_FAST = SystemProperty("geomesa.slo.windows.fast",
                                  "300:3600:14.4")
SLO_WINDOWS_SLOW = SystemProperty("geomesa.slo.windows.slow",
                                  "21600:259200:1.0")
# a rule needs this many events in its SHORT window before it may
# fire: one failed request out of one must not page anybody
SLO_MIN_EVENTS = SystemProperty("geomesa.slo.min.events", "12")
SLO_REACT = SystemProperty("geomesa.slo.react", "false")
SLO_REACT_FACTOR = SystemProperty("geomesa.slo.react.factor", "4")
SLO_MAX_ROUTES = SystemProperty("geomesa.slo.max.routes", "64")


def _parse_windows(raw, default: tuple[float, float, float]):
    try:
        s, l, b = str(raw).split(":")
        s, l, b = float(s), float(l), float(b)
        if s <= 0 or l < s or b <= 0:
            return default
        return (s, l, b)
    except (TypeError, ValueError, AttributeError):
        return default


class _Ring:
    """Fixed ring of time buckets, each ``res_s`` wide, holding
    (total, errors, slow) event counts. Slots are lazily invalidated:
    a write into a slot whose bucket epoch moved on resets it, so no
    sweeper thread is needed and a fake clock works unmodified."""

    __slots__ = ("res", "n", "epoch", "total", "err", "slow")

    def __init__(self, res_s: int, slots: int):
        self.res = int(res_s)
        self.n = int(slots)
        self.epoch = [-1] * self.n
        self.total = [0] * self.n
        self.err = [0] * self.n
        self.slow = [0] * self.n

    def span_s(self) -> float:
        return float(self.res * self.n)

    def add(self, now: float, err: int, slow: int):
        b = int(now // self.res)
        i = b % self.n
        if self.epoch[i] != b:
            self.epoch[i] = b
            self.total[i] = 0
            self.err[i] = 0
            self.slow[i] = 0
        self.total[i] += 1
        self.err[i] += err
        self.slow[i] += slow

    def sums(self, now: float, window_s: float) -> tuple[int, int, int]:
        b_now = int(now // self.res)
        b_min = int((now - window_s) // self.res)
        tot = err = slow = 0
        for i in range(self.n):
            e = self.epoch[i]
            if b_min < e <= b_now:
                tot += self.total[i]
                err += self.err[i]
                slow += self.slow[i]
        return tot, err, slow


class _Series:
    """One tracked route/surface: its objectives, its event rings, and
    its alert state machine (fast + slow burn rules, each needing both
    of its windows over threshold to FIRE and only the short window
    under threshold to CLEAR)."""

    def __init__(self, route: str):
        self.route = route
        self.fine = _Ring(1, 3600)       # covers fast windows (<= 1h)
        self.coarse = _Ring(60, 4320)    # covers slow windows (<= 3d)
        self.fast_firing = False
        self.slow_firing = False
        self.fast_since: float | None = None
        self.slow_since: float | None = None
        self.events = 0

    def record(self, now: float, ok: bool, latency_s: float,
               lat_thresh_s: float):
        err = 0 if ok else 1
        slow = 1 if latency_s > lat_thresh_s else 0
        self.fine.add(now, err, slow)
        self.coarse.add(now, err, slow)
        self.events += 1

    def _ring_for(self, window_s: float) -> _Ring:
        return self.fine if window_s <= self.fine.span_s() else self.coarse

    def burn(self, now: float, window_s: float, kind: str,
             budget: float) -> tuple[float, int]:
        """(burn rate, events in window) for one window/objective."""
        tot, err, slow = self._ring_for(window_s).sums(now, window_s)
        if tot == 0:
            return 0.0, 0
        bad = err if kind == "availability" else slow
        return (bad / tot) / max(budget, 1e-9), tot

    def evaluate(self, now: float, fast: tuple, slow: tuple,
                 budgets: dict[str, float], min_events: int) -> dict:
        fs, fl, fb = fast
        ss, sl, sb = slow
        burns: dict[str, dict[str, float]] = {}
        fast_fire = fast_hold = False
        slow_fire = slow_hold = False
        for kind, budget in budgets.items():
            b_fs, n_fs = self.burn(now, fs, kind, budget)
            b_fl, _ = self.burn(now, fl, kind, budget)
            b_ss, n_ss = self.burn(now, ss, kind, budget)
            b_sl, _ = self.burn(now, sl, kind, budget)
            burns[kind] = {f"{int(fs)}s": round(b_fs, 4),
                           f"{int(fl)}s": round(b_fl, 4),
                           f"{int(ss)}s": round(b_ss, 4),
                           f"{int(sl)}s": round(b_sl, 4)}
            if b_fs >= fb and b_fl >= fb and n_fs >= min_events:
                fast_fire = True
            if b_fs >= fb:
                fast_hold = True   # short window still burning: no clear
            if b_ss >= sb and b_sl >= sb and n_ss >= min_events:
                slow_fire = True
            if b_ss >= sb:
                slow_hold = True
        transitions = []
        if not self.fast_firing and fast_fire:
            self.fast_firing, self.fast_since = True, now
            transitions.append(("fast-burn", "fired"))
        elif self.fast_firing and not fast_hold:
            self.fast_firing, self.fast_since = False, None
            transitions.append(("fast-burn", "cleared"))
        if not self.slow_firing and slow_fire:
            self.slow_firing, self.slow_since = True, now
            transitions.append(("slow-burn", "fired"))
        elif self.slow_firing and not slow_hold:
            self.slow_firing, self.slow_since = False, None
            transitions.append(("slow-burn", "cleared"))
        alert = ("fast-burn" if self.fast_firing
                 else "slow-burn" if self.slow_firing else "ok")
        return {"alert": alert, "fast_firing": self.fast_firing,
                "slow_firing": self.slow_firing, "burn": burns,
                "events": self.events, "_transitions": transitions}


class _Reaction:
    """The admission-tightening loop behind ``geomesa.slo.react``.

    Engage saves the process-wide override state of every knob it will
    touch (``SystemProperty.get_override`` — the override LAYER, not
    the resolved value), then tightens; restore puts each override
    back exactly, including the not-set state."""

    def __init__(self, registry=metrics):
        self._registry = registry
        self._saved: dict[str, str | None] | None = None
        self._lock = threading.Lock()

    @property
    def engaged(self) -> bool:
        return self._saved is not None

    def _knobs(self):
        # lazy imports: the serving-layer modules import obs for
        # tracing, so obs.slo must not import them at module load
        from ..ingest.pipeline import INGEST_SHED_QUEUE_DEPTH
        from ..resilience.policy import RETRY_BUDGET_SCALE
        from ..scan.batcher import BATCH_LINGER_MICROS
        return (RETRY_BUDGET_SCALE, BATCH_LINGER_MICROS,
                INGEST_SHED_QUEUE_DEPTH)

    def apply(self, firing: bool):
        react = str(SLO_REACT.get()).lower() in ("true", "1", "yes")
        with self._lock:
            if firing and react and self._saved is None:
                self._engage()
            elif self._saved is not None and (not firing or not react):
                self._restore()

    def _engage(self):
        try:
            factor = max(float(SLO_REACT_FACTOR.get() or 4.0), 1.0)
        except (TypeError, ValueError):
            factor = 4.0
        scale_p, linger_p, shed_p = self._knobs()
        self._saved = {p.name: p.get_override()
                       for p in (scale_p, linger_p, shed_p)}
        scale_p.set(f"{1.0 / factor:g}")
        linger = linger_p.as_float() or 2000.0
        linger_p.set(f"{linger / factor:g}")
        shed = shed_p.as_int() or 64
        shed_p.set(str(max(1, int(shed // factor))))
        self._registry.counter("slo.react.engaged")
        self._registry.gauge("slo.react.active", 1)

    def _restore(self):
        for prop in self._knobs():
            if prop.name in self._saved:
                prop.set(self._saved[prop.name])
        self._saved = None
        self._registry.counter("slo.react.restored")
        self._registry.gauge("slo.react.active", 0)


class SloEngine:
    """Per-route SLO tracker + burn-rate evaluator. ``record`` is the
    hot path (two ring adds under one lock); evaluation piggybacks on
    records at most every ``_EVAL_EVERY_S`` or runs explicitly via
    ``evaluate(now)`` (the fake-clock test entry point)."""

    _EVAL_EVERY_S = 0.5

    def __init__(self, clock=time.time, registry=metrics, reaction=None):
        self._clock = clock
        self._registry = registry
        self._lock = threading.RLock()
        self._series: dict[str, _Series] = {}
        self._reaction = reaction if reaction is not None \
            else _Reaction(registry)
        self._last_eval = float("-inf")

    @staticmethod
    def enabled() -> bool:
        return str(SLO_ENABLED.get()).lower() in ("true", "1", "yes")

    # -- recording ---------------------------------------------------------

    def record(self, route: str, ok: bool, latency_s: float,
               now: float | None = None, tenant: str | None = None):
        if not self.enabled():
            return
        if now is None:
            now = self._clock()
        lat_s = (SLO_LATENCY_MS.as_float() or 500.0) / 1e3
        route = sanitize_key(route)
        with self._lock:
            self._record_locked(route, now, ok, latency_s, lat_s)
            if tenant is not None:
                # per-tenant SLO series ride the same route rings under
                # a derived name; the max-routes cap (collapse to
                # "other") bounds tenant-driven cardinality
                self._record_locked(
                    f"{route}.tenant.{sanitize_key(str(tenant))}",
                    now, ok, latency_s, lat_s)
            due = now - self._last_eval >= self._EVAL_EVERY_S
        if due:
            self.evaluate(now)

    def _record_locked(self, route: str, now: float, ok: bool,
                       latency_s: float, lat_s: float):
        s = self._series.get(route)
        if s is None:
            try:
                cap = int(SLO_MAX_ROUTES.get() or 64)
            except (TypeError, ValueError):
                cap = 64
            if len(self._series) >= cap:
                route = "other"
            s = self._series.setdefault(route, _Series(route))
        s.record(now, ok, latency_s, lat_s)

    # -- evaluation --------------------------------------------------------

    def _budgets(self) -> dict[str, float]:
        avail = SLO_AVAILABILITY_TARGET.as_float() or 0.999
        lat = SLO_LATENCY_TARGET.as_float() or 0.99
        return {"availability": max(1.0 - avail, 1e-9),
                "latency": max(1.0 - lat, 1e-9)}

    def evaluate(self, now: float | None = None) -> dict:
        """Run every series' state machine at ``now`` and publish the
        gauges; returns the per-route states."""
        if now is None:
            now = self._clock()
        fast = _parse_windows(SLO_WINDOWS_FAST.get(), (300.0, 3600.0, 14.4))
        slow = _parse_windows(SLO_WINDOWS_SLOW.get(),
                              (21600.0, 259200.0, 1.0))
        min_events = SLO_MIN_EVENTS.as_int()
        if min_events is None:
            min_events = 12
        budgets = self._budgets()
        out: dict[str, dict] = {}
        any_fast = False
        with self._lock:
            self._last_eval = now
            for route, s in self._series.items():
                st = s.evaluate(now, fast, slow, budgets, min_events)
                any_fast |= st["fast_firing"]
                for kind, wins in st["burn"].items():
                    for win, val in wins.items():
                        self._registry.gauge(
                            "slo.burn", val,
                            labels={"route": route, "slo": kind,
                                    "window": win})
                self._registry.gauge(
                    "slo.alert",
                    2 if st["fast_firing"] else
                    1 if st["slow_firing"] else 0,
                    labels={"route": route})
                for rule, what in st.pop("_transitions"):
                    self._registry.counter(
                        f"slo.alerts.{what}",
                        labels={"route": route, "rule": rule})
                out[route] = st
        self._reaction.apply(any_fast)
        return out

    # -- surfaces ----------------------------------------------------------

    def status(self, now: float | None = None) -> dict:
        """The ``GET /rest/slo`` document: objectives, window config,
        reaction state, and every route's live burn/alert state."""
        fast = _parse_windows(SLO_WINDOWS_FAST.get(), (300.0, 3600.0, 14.4))
        slow = _parse_windows(SLO_WINDOWS_SLOW.get(),
                              (21600.0, 259200.0, 1.0))
        routes = self.evaluate(now) if self.enabled() else {}
        return {
            "enabled": self.enabled(),
            "objectives": {
                "availability_target":
                    SLO_AVAILABILITY_TARGET.as_float() or 0.999,
                "latency_ms": SLO_LATENCY_MS.as_float() or 500.0,
                "latency_target": SLO_LATENCY_TARGET.as_float() or 0.99,
            },
            "windows": {"fast": list(fast), "slow": list(slow)},
            "react": {
                "configured":
                    str(SLO_REACT.get()).lower() in ("true", "1", "yes"),
                "engaged": self._reaction.engaged,
            },
            "routes": routes,
        }

    def clear(self):
        """Drop all series and disengage any reaction (test/bench
        hygiene between phases)."""
        with self._lock:
            self._series.clear()
            self._last_eval = float("-inf")
        self._reaction.apply(False)


slo_engine = SloEngine()
