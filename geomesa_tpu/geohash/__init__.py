"""GeoHash: base-32 spatial hashing + spiral KNN iteration.

Analog of the reference's geohash package (geomesa-utils/.../geohash/
GeoHash.scala:25,101 — encode/decode at arbitrary bit precision;
GeohashUtils; iterators) and the KNN process machinery
(geomesa-process/.../knn/GeoHashSpiral.scala:53,80 — a priority queue of
geohash cells ordered by distance to the query point, with touching-cell
expansion; NearestNeighbors bounded PQ).

Encoding is vectorized over numpy: a geohash is the bit-interleave of
normalized lon (even bits, from the top) and lat (odd bits), rendered
base-32. Reuses the Z2 bit-spreading kernels (curves/zorder.py) — a
geohash IS a z-order prefix with lon first.
"""

from __future__ import annotations

import dataclasses
import heapq
import math

import numpy as np

from ..curves.zorder import z2_split

__all__ = ["GeoHash", "encode", "decode_bbox", "decode", "neighbors",
           "covering", "GeoHashSpiral", "BoundedNearestNeighbors",
           "precision_for_radius"]

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"
_DECODE32 = {c: i for i, c in enumerate(_BASE32)}


def encode(lon, lat, precision: int = 9):
    """Vectorized geohash of `precision` base-32 chars (5 bits each).

    GeoHash.scala builds the same lon-first interleave; here both
    coordinate arrays normalize to 30-bit ints, z-interleave via the
    shared bit-spread kernel, and the top 5*precision bits render as
    base-32 strings.
    """
    bits = 5 * precision
    lon = np.asarray(lon, dtype=np.float64)
    lat = np.asarray(lat, dtype=np.float64)
    scalar = lon.ndim == 0
    nx = np.clip(((lon + 180.0) / 360.0 * (1 << 30)).astype(np.uint64),
                 0, (1 << 30) - 1)
    ny = np.clip(((lat + 90.0) / 180.0 * (1 << 30)).astype(np.uint64),
                 0, (1 << 30) - 1)
    # lon occupies the even bit positions counting from the top
    z = (z2_split(nx) << np.uint64(1)) | z2_split(ny)  # 60 bits, lon first
    z >>= np.uint64(60 - bits)
    codes = np.zeros(z.shape + (precision,), dtype=np.uint8)
    for i in range(precision):
        shift = np.uint64(5 * (precision - 1 - i))
        codes[..., i] = ((z >> shift) & np.uint64(31)).astype(np.uint8)
    lut = np.frombuffer(_BASE32.encode(), dtype=np.uint8)
    chars = lut[codes]
    out = chars.view(f"S{precision}").reshape(z.shape).astype(str)
    return str(out[()]) if scalar else out


def _to_bits(gh: str) -> tuple[int, int]:
    """geohash string -> (value, nbits)."""
    v = 0
    for c in gh:
        v = (v << 5) | _DECODE32[c.lower()]
    return v, 5 * len(gh)


def _deinterleave(v: int, nbits: int) -> tuple[int, int, int, int]:
    """(lon_bits, lat_bits, n_lon, n_lat) from a lon-first interleave."""
    lon = lat = 0
    n_lon = n_lat = 0
    for i in range(nbits):
        bit = (v >> (nbits - 1 - i)) & 1
        if i % 2 == 0:
            lon = (lon << 1) | bit
            n_lon += 1
        else:
            lat = (lat << 1) | bit
            n_lat += 1
    return lon, lat, n_lon, n_lat


def decode_bbox(gh: str,
                bits: int | None = None) -> tuple[float, float, float,
                                                  float]:
    """(xmin, ymin, xmax, ymax) of a geohash cell. ``bits`` truncates
    to the leading bit precision (the reference's arbitrary-bit
    GeoHash cells — base-32 rendering always carries a 5-bit multiple,
    the cell itself need not)."""
    v, nbits = _to_bits(gh)
    if bits is not None and 0 < bits < nbits:
        v >>= nbits - bits
        nbits = bits
    lon, lat, n_lon, n_lat = _deinterleave(v, nbits)
    wx = 360.0 / (1 << n_lon)
    wy = 180.0 / (1 << n_lat) if n_lat else 180.0
    xmin = -180.0 + lon * wx
    ymin = -90.0 + lat * wy
    return xmin, ymin, xmin + wx, ymin + wy


def decode(gh: str) -> tuple[float, float]:
    """Cell-center (lon, lat)."""
    xmin, ymin, xmax, ymax = decode_bbox(gh)
    return (xmin + xmax) / 2, (ymin + ymax) / 2


@dataclasses.dataclass(frozen=True)
class GeoHash:
    """A geohash cell (string + derived bbox)."""
    hash: str

    @property
    def bbox(self) -> tuple[float, float, float, float]:
        return decode_bbox(self.hash)

    @property
    def center(self) -> tuple[float, float]:
        return decode(self.hash)

    @property
    def precision(self) -> int:
        return len(self.hash)


def neighbors(gh: str) -> list[str]:
    """The up-to-8 touching cells at the same precision (antimeridian
    wraps in lon; poles clip in lat)."""
    xmin, ymin, xmax, ymax = decode_bbox(gh)
    cx, cy = (xmin + xmax) / 2, (ymin + ymax) / 2
    wx, wy = xmax - xmin, ymax - ymin
    out = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            ny = cy + dy * wy
            if ny <= -90.0 or ny >= 90.0:
                continue
            nx = cx + dx * wx
            if nx < -180.0:
                nx += 360.0
            elif nx > 180.0:
                nx -= 360.0
            out.append(encode(nx, ny, len(gh)))
    # dedupe preserving order (wraps can collide at coarse precision)
    seen: set = set()
    uniq = []
    for h in out:
        if h not in seen and h != gh:
            seen.add(h)
            uniq.append(h)
    return uniq


def covering(xmin: float, ymin: float, xmax: float, ymax: float,
             precision: int, max_cells: int = 4096) -> list[str]:
    """All geohash cells at `precision` intersecting the bbox
    (GeohashUtils.getGeohashesContainedByEnvelope-style enumeration)."""
    wx = 360.0 / (1 << math.ceil(5 * precision / 2))
    wy = 180.0 / (1 << (5 * precision // 2))
    eps = 1e-12
    # sample at the global grid's cell centers so boundary cells aren't
    # skipped when the bbox edge sits near a cell edge
    x0 = math.floor((xmin + 180.0) / wx) * wx - 180.0
    y0 = math.floor((ymin + 90.0) / wy) * wy - 90.0
    xs = np.arange(x0 + wx / 2, xmax + wx / 2 + eps, wx)
    ys = np.arange(y0 + wy / 2, ymax + wy / 2 + eps, wy)
    xs = np.clip(xs, -180 + eps, 180 - eps)
    ys = np.clip(ys, -90 + eps, 90 - eps)
    if len(xs) * len(ys) > max_cells:
        raise ValueError(f"bbox needs {len(xs) * len(ys)} cells at "
                         f"precision {precision} (max {max_cells})")
    gx, gy = np.meshgrid(xs, ys)
    cells = encode(gx.ravel(), gy.ravel(), precision)
    return sorted(set(cells.tolist()))


def precision_for_radius(radius_deg: float) -> int:
    """Smallest precision whose cell width is >= radius (the spiral's
    auto-sizing, GeoHashSpiral.scala — cells comparable to the search
    radius keep the PQ small)."""
    for p in range(9, 0, -1):
        wx = 360.0 / (1 << math.ceil(5 * p / 2))
        if wx >= radius_deg:
            return p
    return 1


def _dist2_to_bbox(x: float, y: float,
                   bbox: tuple[float, float, float, float]) -> float:
    dx = max(bbox[0] - x, 0.0, x - bbox[2])
    dy = max(bbox[1] - y, 0.0, y - bbox[3])
    return dx * dx + dy * dy


class GeoHashSpiral:
    """Iterate geohash cells outward from a point in distance order
    (knn/GeoHashSpiral.scala:53,80): a PQ keyed by min-distance from the
    query point to the cell, seeded with the containing cell, expanding
    through touching neighbors. ``update_max_distance`` prunes cells
    beyond the current kth-neighbor distance (PQ cut-off)."""

    def __init__(self, x: float, y: float, precision: int):
        self.x, self.y = x, y
        self.precision = precision
        seed = encode(x, y, precision)
        self._pq: list[tuple[float, str]] = [(0.0, seed)]
        self._seen = {seed}
        self._max_d2 = math.inf

    def update_max_distance(self, d: float):
        self._max_d2 = min(self._max_d2, d * d)

    def __iter__(self):
        return self

    def __next__(self) -> str:
        while self._pq:
            d2, gh = heapq.heappop(self._pq)
            if d2 > self._max_d2:
                break
            for nb in neighbors(gh):
                if nb not in self._seen:
                    self._seen.add(nb)
                    nd2 = _dist2_to_bbox(self.x, self.y, decode_bbox(nb))
                    if nd2 <= self._max_d2:
                        heapq.heappush(self._pq, (nd2, nb))
            return gh
        raise StopIteration


class BoundedNearestNeighbors:
    """Bounded max-heap of (distance, id) pairs (knn/NearestNeighbors)."""

    def __init__(self, k: int):
        self.k = k
        self._heap: list[tuple[float, object]] = []  # (-dist, id)

    def offer(self, dist: float, item):
        if len(self._heap) < self.k:
            heapq.heappush(self._heap, (-dist, item))
        elif dist < -self._heap[0][0]:
            heapq.heapreplace(self._heap, (-dist, item))

    @property
    def max_distance(self) -> float:
        return -self._heap[0][0] if len(self._heap) == self.k else math.inf

    @property
    def full(self) -> bool:
        return len(self._heap) >= self.k

    def result(self) -> list[tuple[float, object]]:
        return sorted((-d, i) for d, i in self._heap)
