"""Bit-normalization of doubles onto integer grids.

Mirrors the semantics of the reference's ``NormalizedDimension``
(geomesa-z3/.../curve/NormalizedDimension.scala:14,74): a value in
``[min, max]`` maps to an int in ``[0, 2^precision - 1]`` via
``floor((x - min) * bins / (max - min))`` with the upper bound clamped to
``maxIndex``; denormalization returns the *center* of the bin.

Host side uses float64 numpy (normalization is ingest/plan-time work);
the device hot path only ever sees the resulting int32 grids, so no
float64 is needed on TPU.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = [
    "NormalizedDimension",
    "normalized_lon",
    "normalized_lat",
    "normalized_time",
]


@dataclasses.dataclass(frozen=True)
class NormalizedDimension:
    """Maps doubles in [min, max] to ints in [0, 2**precision - 1]."""

    min: float
    max: float
    precision: int

    def __post_init__(self) -> None:
        if not (0 < self.precision < 32):
            raise ValueError("precision (bits) must be in [1, 31]")

    @property
    def bins(self) -> int:
        return 1 << self.precision

    @property
    def max_index(self) -> int:
        return self.bins - 1

    def normalize(self, x):
        """Vectorized normalize; accepts scalars or numpy arrays.

        Values ``>= max`` clamp to ``max_index`` (the reference does the
        same; out-of-range low values are the caller's responsibility —
        see ``lenient`` handling in the SFC classes).
        """
        x = np.asarray(x, dtype=np.float64)
        normalizer = self.bins / (self.max - self.min)
        prod = np.floor((x - self.min) * normalizer)
        # NaN (null coordinates) maps to bin 0 explicitly — the old
        # NaN->int cast produced the same value via truncation but with
        # a RuntimeWarning and int-cast UB semantics
        out = np.where(np.isnan(prod), 0.0, prod).astype(np.int64)
        # float rounding can push in-bounds values just below max up to
        # `bins`; clamp rather than wrap (int32 overflow would silently
        # produce a wrong z key for points at the domain edge)
        out = np.minimum(out, self.max_index)
        return out.astype(np.int32)

    def denormalize(self, i):
        """Vectorized bin-center denormalization."""
        i = np.asarray(i, dtype=np.int64)
        denorm = (self.max - self.min) / self.bins
        i = np.minimum(i, self.max_index)
        return self.min + (i.astype(np.float64) + 0.5) * denorm

    def in_bounds(self, x) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        return (x >= self.min) & (x <= self.max)

    def clamp(self, x):
        return np.clip(np.asarray(x, dtype=np.float64), self.min, self.max)


def normalized_lon(precision: int) -> NormalizedDimension:
    return NormalizedDimension(-180.0, 180.0, precision)


def normalized_lat(precision: int) -> NormalizedDimension:
    return NormalizedDimension(-90.0, 90.0, precision)


def normalized_time(precision: int, max_offset: float) -> NormalizedDimension:
    return NormalizedDimension(0.0, max_offset, precision)
