"""XZ-ordering curves for geometries with spatial extent (XZ2 / XZ3).

Implements Böhm, Klump & Kriegel's 'XZ-Ordering: A Space-Filling Curve
for Objects with Spatial Extension', matching the reference's semantics
(geomesa-z3/.../curve/XZ2SFC.scala:24, XZ3SFC.scala:26):

- an object is indexed by its bounding box: the sequence-code *length*
  is chosen so an "enlarged" (2x) quad/oct cell covers the box
  (XZ2SFC.scala:55-80), then the cell's lower-left corner path encodes
  as an integer sequence code (Definition 2; XZ2SFC.scala:263-286).
- query ranges BFS the quad/oct tree, testing each *extended* element
  (upper bounds grown by one side length) against the query windows;
  contained elements emit their whole subtree interval (Lemma 3),
  partial elements emit a single code and recurse (XZ2SFC.scala:146-252).

Generic over dims: dims=2 => quadtree (base 4), dims=3 => octree (base 8).
``index`` is vectorized over numpy arrays of boxes (one g-step loop,
vectorized across elements); ``ranges`` is a vectorized level-wise BFS.
Sequence codes fit comfortably in int64 for the default g=12
(XZSFC.DefaultPrecision, XZSFC.scala:13).
"""

from __future__ import annotations

import numpy as np

from .timebin import TimePeriod, max_offset
from .zranges import DEFAULT_MAX_RANGES, merge_ranges

__all__ = ["XZSFC", "XZ2SFC", "XZ3SFC", "xz2sfc", "xz3sfc", "DEFAULT_G"]

DEFAULT_G = 12  # XZSFC.DefaultPrecision


class XZSFC:
    """Generic N-dimensional XZ curve over user-space bounds."""

    def __init__(self, g: int, bounds: list[tuple[float, float]]):
        if not (0 < g < 20):
            raise ValueError("g must be in (0, 20) to keep codes in int64")
        self.g = int(g)
        self.dims = len(bounds)
        self.base = 2 ** self.dims
        self.lo = np.array([b[0] for b in bounds], dtype=np.float64)
        self.hi = np.array([b[1] for b in bounds], dtype=np.float64)
        self.size = self.hi - self.lo
        # subtree_size[l] = (base^(g-l) - 1) / (base - 1): the number of
        # codes in a full subtree below a level-l cell (Lemma 3 term)
        p = np.arange(self.g + 2, dtype=np.int64)
        self._subtree = ((self.base ** np.maximum(self.g - p + 1, 0) - 1)
                         // (self.base - 1)).astype(np.int64)
        # step_size[i] = (base^(g-i) - 1)/(base-1), used in the code sum
        self._step = ((self.base ** (self.g - np.arange(self.g, dtype=np.int64)) - 1)
                      // (self.base - 1)).astype(np.int64)

    # -- normalization ----------------------------------------------------

    def _normalize(self, mins, maxs, lenient: bool):
        """User space box corners -> [0,1]^dims. mins/maxs: (dims, n)."""
        mins = np.asarray(mins, dtype=np.float64).reshape(self.dims, -1)
        maxs = np.asarray(maxs, dtype=np.float64).reshape(self.dims, -1)
        if bool(np.any(mins > maxs)):
            raise ValueError("bounds must be ordered (min <= max)")
        lo, hi = self.lo[:, None], self.hi[:, None]
        if lenient:
            mins = np.clip(mins, lo, hi)
            maxs = np.clip(maxs, lo, hi)
        elif bool(np.any((mins < lo) | (maxs > hi))):
            raise ValueError("value(s) out of bounds for xz index")
        size = self.size[:, None]
        return (mins - lo) / size, (maxs - lo) / size

    # -- indexing ---------------------------------------------------------

    def index(self, mins, maxs, lenient: bool = False) -> np.ndarray:
        """Vectorized: box corners (dims, n) or per-dim scalars -> codes (n,).

        Mirrors XZ2SFC.index (XZ2SFC.scala:55-80): pick the sequence
        length from the box extent, then encode the min corner.
        """
        nmin, nmax = self._normalize(mins, maxs, lenient)
        n = nmin.shape[1]

        max_dim = np.max(nmax - nmin, axis=0)
        with np.errstate(divide="ignore"):
            # maxDim == 0 (points) -> l1 = +inf -> clamps to g
            l1 = np.floor(np.log(max_dim) / np.log(0.5))
        l1 = np.where(np.isfinite(l1), l1, self.g).astype(np.int64)
        l1 = np.minimum(l1, self.g)

        w2 = np.power(0.5, (l1 + 1).astype(np.float64))  # width at l1+1
        fits = np.ones(n, dtype=bool)
        for d in range(self.dims):
            cell_start = np.floor(nmin[d] / w2) * w2
            fits &= nmax[d] <= cell_start + 2 * w2
        length = np.where(l1 >= self.g, self.g, np.where(fits, l1 + 1, l1))

        return self._sequence_code(nmin, length)

    def _sequence_code(self, corner: np.ndarray, length) -> np.ndarray:
        """Vectorized Definition-2 sequence code of point `corner` (dims, n)
        with per-element code `length`."""
        length = np.broadcast_to(np.asarray(length, dtype=np.int64),
                                 corner.shape[1:])
        lo = np.zeros_like(corner)
        hi = np.ones_like(corner)
        cs = np.zeros(corner.shape[1], dtype=np.int64)
        for i in range(self.g):
            active = i < length
            center = (lo + hi) * 0.5
            ge = corner >= center            # (dims, n) bools
            q = np.zeros(corner.shape[1], dtype=np.int64)
            for d in range(self.dims):
                q += ge[d].astype(np.int64) << d
            cs = np.where(active, cs + 1 + q * self._step[i], cs)
            hi = np.where(ge, hi, center)
            lo = np.where(ge, center, lo)
        return cs

    # -- query ranges -----------------------------------------------------

    def ranges(self, windows, max_ranges: int | None = None) -> np.ndarray:
        """Covering sequence-code ranges for OR'd query windows.

        windows: iterable of (mins..., maxs...) user-space tuples, e.g.
        (xmin, ymin, xmax, ymax) for dims=2 (same layout as the
        reference's ranges()). Returns int64 [n, 3]: [lo, hi, contained]
        where contained=1 means every object in the range genuinely
        intersects the window (no exact-geometry recheck needed).
        """
        if max_ranges is None:
            # practical reference usage always passes SCAN_RANGES_TARGET
            # (XZ2IndexKeySpace.scala:71); an unlimited 3-D BFS explodes
            # (boundary-surface cells grow 4x per level)
            max_ranges = DEFAULT_MAX_RANGES
        wins = []
        for w in windows:
            mins = np.array(w[:self.dims], dtype=np.float64)
            maxs = np.array(w[self.dims:], dtype=np.float64)
            nmin, nmax = self._normalize(mins[:, None], maxs[:, None], False)
            wins.append((nmin[:, 0], nmax[:, 0]))
        if not wins:
            return np.empty((0, 3), dtype=np.int64)
        wmin = np.stack([w[0] for w in wins], axis=1)  # (dims, nw)
        wmax = np.stack([w[1] for w in wins], axis=1)

        # note: sequence code 0 (length-0 code) is unreachable — at l1=0
        # the level-1 fit predicate always passes, so codes start at 1;
        # large geometries are covered via the partial single codes the
        # BFS emits along its path.
        out_lo: list[np.ndarray] = []
        out_hi: list[np.ndarray] = []
        out_cont: list[np.ndarray] = []

        # frontier: integer cell coords at the current level, (dims, n)
        frontier = np.zeros((self.dims, 1), dtype=np.int64)
        codes = np.zeros(1, dtype=np.int64)  # seq code of each frontier cell
        # descend: children of the root are level 1
        frontier, codes = self._children(frontier, codes, 0)
        level = 1
        n_emitted = 0

        while frontier.shape[1] > 0:
            w = 0.5 ** level
            cell_lo = frontier * w                        # (dims, n)
            cell_ext = (frontier + 2) * w                 # extended upper bound
            # test each cell against each window: (dims, n, nw)
            contained = ((wmin[:, None, :] <= cell_lo[:, :, None])
                         & (wmax[:, None, :] >= cell_ext[:, :, None])).all(axis=0).any(axis=1)
            overlapped = ((wmax[:, None, :] >= cell_lo[:, :, None])
                          & (wmin[:, None, :] <= cell_ext[:, :, None])).all(axis=0).any(axis=1)
            partial = overlapped & ~contained

            if contained.any():
                c = codes[contained]
                out_lo.append(c)
                out_hi.append(c + self._subtree[level])
                out_cont.append(np.ones(len(c), dtype=np.int64))
                n_emitted += len(c)

            if not partial.any():
                break

            if level >= self.g or n_emitted + int(partial.sum()) > max_ranges:
                # bottom out: emit whole subtree intervals for partials
                # (XZ2SFC.scala:221-231), flagged as not-contained
                c = codes[partial]
                out_lo.append(c)
                out_hi.append(c + self._subtree[level])
                out_cont.append(np.zeros(len(c), dtype=np.int64))
                break

            # partial cells emit their single code and recurse
            c = codes[partial]
            out_lo.append(c)
            out_hi.append(c.copy())
            out_cont.append(np.zeros(len(c), dtype=np.int64))
            n_emitted += len(c)
            frontier, codes = self._children(frontier[:, partial], c, level)
            level += 1

        if not out_lo:
            return np.empty((0, 3), dtype=np.int64)
        stacked = np.stack([np.concatenate(out_lo), np.concatenate(out_hi),
                            np.concatenate(out_cont)], axis=1)
        return merge_ranges(stacked)

    def _children(self, frontier: np.ndarray, codes: np.ndarray, level: int):
        """All 2^dims children of each frontier cell, with their codes.

        A child with per-dim high-bits q enters at level+1; its code is
        parent + 1 + q * step[level] (sequenceCode's i=level term).
        """
        n = frontier.shape[1]
        offsets = np.indices((2,) * self.dims).reshape(self.dims, -1)  # (dims, base)
        child = (frontier[:, :, None] * 2 + offsets[:, None, :]).reshape(self.dims, -1)
        q = np.zeros(self.base, dtype=np.int64)
        for d in range(self.dims):
            q += offsets[d].astype(np.int64) << d
        ccodes = (codes[:, None] + 1 + q[None, :] * self._step[level]).reshape(-1)
        return child, ccodes


class XZ2SFC(XZSFC):
    """2-D XZ curve over lon/lat (XZ2SFC.scala:24)."""

    def __init__(self, g: int = DEFAULT_G):
        super().__init__(g, [(-180.0, 180.0), (-90.0, 90.0)])

    def index_boxes(self, xmin, ymin, xmax, ymax, lenient: bool = False):
        return self.index(np.stack([np.atleast_1d(np.asarray(xmin, np.float64)),
                                    np.atleast_1d(np.asarray(ymin, np.float64))]),
                          np.stack([np.atleast_1d(np.asarray(xmax, np.float64)),
                                    np.atleast_1d(np.asarray(ymax, np.float64))]),
                          lenient)


class XZ3SFC(XZSFC):
    """3-D XZ curve over lon/lat/time-offset (XZ3SFC.scala:26)."""

    def __init__(self, g: int = DEFAULT_G,
                 period: TimePeriod | str = TimePeriod.WEEK):
        period = TimePeriod.parse(period)
        self.period = period
        super().__init__(g, [(-180.0, 180.0), (-90.0, 90.0),
                             (0.0, float(max_offset(period)))])

    def index_boxes(self, xmin, ymin, tmin, xmax, ymax, tmax,
                    lenient: bool = False):
        mk = lambda *a: np.stack([np.atleast_1d(np.asarray(v, np.float64)) for v in a])
        return self.index(mk(xmin, ymin, tmin), mk(xmax, ymax, tmax), lenient)


_XZ2_CACHE: dict[int, XZ2SFC] = {}
_XZ3_CACHE: dict[tuple[int, TimePeriod], XZ3SFC] = {}


def xz2sfc(g: int = DEFAULT_G) -> XZ2SFC:
    if g not in _XZ2_CACHE:
        _XZ2_CACHE[g] = XZ2SFC(g)
    return _XZ2_CACHE[g]


def xz3sfc(g: int = DEFAULT_G, period: TimePeriod | str = TimePeriod.WEEK) -> XZ3SFC:
    key = (g, TimePeriod.parse(period))
    if key not in _XZ3_CACHE:
        _XZ3_CACHE[key] = XZ3SFC(g, period)
    return _XZ3_CACHE[key]
