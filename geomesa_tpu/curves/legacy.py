"""Legacy Z3 curve for back-compat index decode/migration.

The reference keeps LegacyZ3SFC (curve/LegacyZ3SFC.scala:16) so stores
written by old versions can still be read and deleted: it differs from
the current Z3SFC by *semi-normalized* dimensions — ceil-based
normalization over precision 2^21-1 for lon/lat and 2^20-1 for time
(NormalizedDimension.scala:83-97 SemiNormalizedDimension: ceil((x-min)/
(max-min) * precision)) — versus the current floor-based bit
normalization. Schema-evolution parity: versioned indices are retained
as legacy classes (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import numpy as np

from . import zorder
from .timebin import TimePeriod, max_offset

__all__ = ["SemiNormalizedDimension", "LegacyZ3SFC", "legacy_z3sfc"]


class SemiNormalizedDimension:
    """ceil-based normalization (SemiNormalizedDimension analog,
    NormalizedDimension.scala:83-87): ``normalize`` is a bare
    ``ceil((x-min)/(max-min)*precision)`` with NO clamping, and
    ``denormalize`` returns ``min`` for bin 0 and cell *midpoints*
    otherwise (the "doesn't correctly bin lower bound" legacy quirk)."""

    def __init__(self, lo: float, hi: float, precision: int):
        self.lo = lo
        self.hi = hi
        self.precision = precision  # max index, NOT a bit count

    def normalize(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        i = np.ceil((x - self.lo) / (self.hi - self.lo) * self.precision)
        return i.astype(np.int64)

    def lenient(self, x) -> np.ndarray:
        """lenientIndex arithmetic (LegacyZ3SFC.scala:24-29): clamps the
        ceil at the dimension MINIMUM as a double — e.g. max(-180.0, i)
        for longitude — so far-out-of-range west/south inputs produce
        negative indices like -180 that alias through the 21-bit mask
        exactly as the old writer's did."""
        x = np.asarray(x, np.float64)
        i = np.ceil((x - self.lo) / (self.hi - self.lo) * self.precision)
        return np.maximum(i, self.lo).astype(np.int64)

    def denormalize(self, i) -> np.ndarray:
        i = np.asarray(i)
        mid = (i - 0.5) * (self.hi - self.lo) / self.precision + self.lo
        return np.where(i == 0, self.lo, mid)


class LegacyZ3SFC:
    """Old z3 index scheme: 21-bit semi-normalized lon/lat, 20-bit
    semi-normalized time (LegacyZ3SFC.scala:16-22). `index` matches the
    old lenient write path so legacy rows can be located for deletion
    or migration; `invert` decodes legacy z values."""

    def __init__(self, period: TimePeriod | str = TimePeriod.WEEK):
        self.period = TimePeriod.parse(period)
        self.lon = SemiNormalizedDimension(-180.0, 180.0, 2 ** 21 - 1)
        self.lat = SemiNormalizedDimension(-90.0, 90.0, 2 ** 21 - 1)
        self.time = SemiNormalizedDimension(
            0.0, float(max_offset(self.period)), 2 ** 20 - 1)

    def index(self, x, y, t, lenient: bool = False) -> np.ndarray:
        """x/y doubles, t = offset in the time bin.

        Default: validates bounds (out-of-range values would silently
        alias through the 21-bit mask). lenient=True skips validation
        and reproduces the old lenientIndex arithmetic exactly —
        including its aliasing — which is the point: it finds whatever
        cell the old writer actually used (LegacyZ3SFC.scala:24-29).
        """
        if lenient:
            return zorder.z3_encode(self.lon.lenient(x),
                                    self.lat.lenient(y),
                                    self.time.lenient(t))
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        t = np.asarray(t, np.float64)
        if (np.any(x < -180) or np.any(x > 180) or np.any(y < -90)
                or np.any(y > 90) or np.any(t < 0)
                or np.any(t > self.time.hi)):
            raise ValueError("value(s) out of bounds for legacy z3 "
                             "index (pass lenient=True to reproduce "
                             "the old aliasing write path)")
        return zorder.z3_encode(self.lon.normalize(x),
                                self.lat.normalize(y),
                                self.time.normalize(t))

    def invert(self, z):
        xi, yi, ti = zorder.z3_decode(z)
        return (self.lon.denormalize(xi), self.lat.denormalize(yi),
                self.time.denormalize(ti).astype(np.int64))

    # bit width per dimension for range decomposition (time uses only
    # 20 bits but the interleave reserves 21; covering ranges over the
    # 21-bit cube remain correct since legacy time cells never exceed
    # 2^20-1)
    precision = zorder.Z3_BITS

    def ranges(self, xy, t, precision: int = 64,
               max_ranges: int | None = None) -> np.ndarray:
        """Covering z ranges under the LEGACY ceil normalization, so a
        versioned (v1) index prunes with the same cells its writer
        used. Monotonicity of ceil makes [normalize(lo), normalize(hi)]
        a valid cell cover of [lo, hi]."""
        from .zranges import merge_ranges as _merge_ranges
        from .zranges import zranges as _zranges

        def norm(dim, v):
            return int(np.clip(dim.normalize(v), 0, dim.precision))

        out = []
        for (xmin, ymin, xmax, ymax) in xy:
            for (tmin, tmax) in t:
                lo = (norm(self.lon, xmin), norm(self.lat, ymin),
                      norm(self.time, tmin))
                hi = (norm(self.lon, xmax), norm(self.lat, ymax),
                      norm(self.time, tmax))
                out.append(_zranges(lo, hi, self.precision,
                                    precision=precision,
                                    max_ranges=max_ranges))
        if not out:
            return np.empty((0, 2), dtype=np.int64)
        return _merge_ranges(np.concatenate(out, axis=0))


_CACHE: dict[TimePeriod, LegacyZ3SFC] = {}


def legacy_z3sfc(period: TimePeriod | str) -> LegacyZ3SFC:
    period = TimePeriod.parse(period)
    if period not in _CACHE:
        _CACHE[period] = LegacyZ3SFC(period)
    return _CACHE[period]
