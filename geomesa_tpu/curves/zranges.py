"""Z-range decomposition: query box -> list of contiguous z-key ranges.

Equivalent in coverage to sfcurve's ``zranges`` divide-and-conquer (used
by the reference at Z3SFC.scala:54-62 / Z2SFC.scala via ``Z3.zranges``):
decompose an axis-aligned box in normalized integer space into at most
``max_ranges`` inclusive ``[zlo, zhi]`` intervals whose union covers every
z key inside the box (over-approximation is allowed and expected — an
exact filter always runs downstream, exactly like the reference's
Z3Iterator/Z3Filter re-check).

Implementation is a *vectorized level-by-level BFS* over z-prefix cells
rather than sfcurve's recursive LITMAX/BIGMIN walk: at level L each cell
is a 2^dims-ary hypercube of side 2^(maxbits-L); fully-contained cells
emit their whole z interval, partially-overlapping cells split. All cell
tests at one level run as single numpy array ops — this is the planner's
CPU hot loop #1 (SURVEY.md section 3.1) and the vectorization is what
keeps it off the profile.
"""

from __future__ import annotations

import numpy as np

__all__ = ["zranges", "merge_ranges", "DEFAULT_MAX_RANGES"]

# the reference's `geomesa.scan.ranges.target` default (QueryProperties.scala:18)
DEFAULT_MAX_RANGES = 2000


def merge_ranges(ranges: np.ndarray) -> np.ndarray:
    """Sort and coalesce overlapping/adjacent inclusive [lo, hi] ranges.

    A third column, if present, is treated as a boolean flag that is
    AND-ed across merged constituents (the XZ 'contained' flag,
    XZ2SFC.scala:236-252)."""
    if len(ranges) == 0:
        return ranges.reshape(0, ranges.shape[1] if ranges.ndim == 2 else 2)
    ranges = ranges[np.argsort(ranges[:, 0], kind="stable")]
    los, his = ranges[:, 0], ranges[:, 1]
    # a range starts a new group if its lo > running max(hi)+1 of all before
    # it; at each group's last element the running max equals the group max
    # (a larger earlier hi would have absorbed the group's start).
    running = np.maximum.accumulate(his)
    new_group = np.empty(len(ranges), dtype=bool)
    new_group[0] = True
    # subtract instead of `running + 1`: hi can be 2^63-1 (full z3
    # domain) and +1 would wrap; z keys are >= 0 so the difference fits
    new_group[1:] = los[1:] - running[:-1] > 1
    last = np.empty(len(ranges), dtype=bool)
    last[-1] = True
    last[:-1] = new_group[1:]
    out = np.stack([los[new_group], running[last]], axis=1)
    if ranges.shape[1] > 2:
        group = np.cumsum(new_group) - 1
        flags = np.ones(len(out), dtype=ranges.dtype)
        np.minimum.at(flags, group, ranges[:, 2])
        out = np.concatenate([out, flags[:, None]], axis=1)
    return out


_native_ready = None  # None = not probed; False = unavailable
# reused zranges output scratch, PER THREAD: concurrent store queries
# (e.g. job splits) must not interleave writes into one buffer
_scratch = __import__("threading").local()


def _native_zranges(lows, highs, dims, max_bits, max_level,
                    max_ranges) -> np.ndarray | None:
    """C++ fast path (native/src/zrange.cpp) — bit-identical to the
    Python BFS below; returns None when the native library is absent or
    the output overflows the preallocated buffer."""
    global _native_ready
    if _native_ready is False:
        return None
    import ctypes
    if _native_ready is None:
        from ..native import symbols
        ip = ctypes.POINTER(ctypes.c_int64)
        lib = symbols({
            "geomesa_zranges": (
                ctypes.c_int64,
                [ip, ip, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                 ctypes.c_int64, ip, ctypes.c_int64]),
        })
        _native_ready = lib if lib is not None else False
        if _native_ready is False:
            return None
    lib = _native_ready
    # the budget check allows one final partial expansion past
    # max_ranges; 4x + slack comfortably bounds the merged output
    cap = 4 * int(max_ranges) + 64
    out = getattr(_scratch, "buf", None)
    if out is None or len(out) < cap:
        # reused scratch: a per-call 128KB allocation + ctypes cast was
        # measurable on 10k-query joins
        out = _scratch.buf = np.empty((cap, 2), dtype=np.int64)
    p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    n = lib.geomesa_zranges(p(lows), p(highs), dims, max_bits,
                            max_level, int(max_ranges), p(out), cap)
    if n < 0:
        return None
    return out[:n].copy()


def _interleave(coords: np.ndarray, dims: int) -> np.ndarray:
    """Interleave per-dim int arrays (coords[d] gets bit offset d)."""
    from . import zorder
    if dims == 2:
        return zorder.z2_encode(coords[0], coords[1]).astype(np.int64)
    if dims == 3:
        return zorder.z3_encode(coords[0], coords[1], coords[2]).astype(np.int64)
    raise ValueError(f"unsupported dims: {dims}")


def zranges(lows, highs, max_bits: int, *, precision: int = 64,
            max_ranges: int | None = None) -> np.ndarray:
    """Decompose box [lows[d], highs[d]] (inclusive, normalized-int space)
    into covering z ranges.

    Args:
      lows / highs: per-dimension inclusive int bounds (len = dims).
      max_bits: bits per dimension (21 for z3, 31 for z2).
      precision: total z bits to recurse to (sfcurve arg); max recursion
        level is ``precision // dims``.
      max_ranges: soft cap on the number of returned ranges; when the BFS
        frontier would exceed it, remaining partial cells emit covering
        ranges. ``None`` -> DEFAULT_MAX_RANGES.

    Returns: int64 array [n, 2] of inclusive [zlo, zhi], sorted + merged.
    """
    lows = np.asarray(lows, dtype=np.int64)
    highs = np.asarray(highs, dtype=np.int64)
    dims = len(lows)
    if max_ranges is None:
        max_ranges = DEFAULT_MAX_RANGES
    max_level = min(max_bits, max(1, precision // dims))
    if np.any(highs < lows):
        return np.empty((0, 2), dtype=np.int64)

    native = _native_zranges(lows, highs, dims, max_bits, max_level,
                             max_ranges)
    if native is not None:
        return native

    # BFS frontier: cell origin coords in units of current cell size,
    # shape (dims, ncells). Start from the root cell.
    frontier = np.zeros((dims, 1), dtype=np.int64)
    emitted: list[np.ndarray] = []

    for level in range(0, max_level + 1):
        if frontier.shape[1] == 0:
            break
        shift = max_bits - level           # log2(cell side)
        side = np.int64(1) << shift
        cell_lo = frontier * side                  # (dims, n) inclusive
        cell_hi = cell_lo + (side - 1)
        lo_b = lows[:, None]
        hi_b = highs[:, None]
        disjoint = ((cell_hi < lo_b) | (cell_lo > hi_b)).any(axis=0)
        contained = ((cell_lo >= lo_b) & (cell_hi <= hi_b)).all(axis=0)
        partial = ~(disjoint | contained)

        def cell_ranges(mask):
            zlo = _interleave(frontier[:, mask] * side, dims)
            # python-int arithmetic: (1 << 63) - 1 still fits int64, but
            # computing it in int64 would overflow mid-expression
            span = np.int64((1 << (dims * shift)) - 1)
            return np.stack([zlo, zlo + span], axis=1)

        if contained.any():
            emitted.append(cell_ranges(contained))

        n_partial = int(partial.sum())
        if n_partial == 0:
            break
        budget_blown = (sum(len(e) for e in emitted)
                        + n_partial * (2 ** dims) > max_ranges)
        if level == max_level or budget_blown:
            emitted.append(cell_ranges(partial))
            break
        # split each partial cell into its 2^dims children
        children = frontier[:, partial] * 2            # (dims, n)
        offsets = np.indices((2,) * dims).reshape(dims, -1)  # (dims, 2^dims)
        frontier = (children[:, :, None] + offsets[:, None, :]).reshape(dims, -1)

    if not emitted:
        return np.empty((0, 2), dtype=np.int64)
    return merge_ranges(np.concatenate(emitted, axis=0))
