"""Space-filling curve front-ends: Z2SFC and Z3SFC.

API mirrors the reference's ``SpaceFillingCurve`` /
``SpaceTimeFillingCurve`` (geomesa-z3/.../curve/SpaceFillingCurve.scala:13,44
and Z2SFC.scala / Z3SFC.scala), vectorized over numpy arrays:

- ``index(x, y[, t])``    normalized-int interleave -> z key(s)
- ``invert(z)``           z key(s) -> bin-center doubles
- ``ranges(boxes, ...)``  query boxes -> covering z ranges

Out-of-bounds behavior matches the reference: strict by default
(ValueError), clamped when ``lenient=True`` (Z3SFC.scala:33-50).
"""

from __future__ import annotations

import numpy as np

from . import timebin, zorder
from .zranges import merge_ranges as _merge_ranges, zranges as _zranges
from .normalize import normalized_lat, normalized_lon, normalized_time
from .timebin import TimePeriod

__all__ = ["Z2SFC", "Z3SFC", "z2sfc", "z3sfc"]


_native_enc = None  # None = unprobed, False = unavailable


def _native_encoder():
    """ctypes handle to the fused C++ encoder (native/src/zencode.cpp),
    or None. One pass over the inputs instead of ~30 numpy temporaries —
    the index-build hot loop at 100M rows."""
    global _native_enc
    if _native_enc is False:
        return None
    if _native_enc is None:
        import ctypes
        from ..native import symbols
        dp = ctypes.POINTER(ctypes.c_double)
        ip = ctypes.POINTER(ctypes.c_int64)
        lib = symbols({
            "geomesa_z2_encode": (None, [dp, dp, ctypes.c_int64, ip]),
            "geomesa_z3_encode": (None, [dp, dp, dp, ctypes.c_int64,
                                         ctypes.c_double, ip]),
        })
        _native_enc = lib if lib is not None else False
    return _native_enc or None


def _native_index(fn_name: str, arrays, extra=()) -> np.ndarray | None:
    """Run a native encoder over EQUAL-LENGTH 1-D inputs; None when the
    native library is absent or the inputs need numpy broadcasting
    (scalars / mismatched lengths must take the numpy path — the C
    kernel would read out of bounds)."""
    if any(np.ndim(a) != 1 for a in arrays):
        return None
    lengths = {len(a) for a in arrays}
    if len(lengths) != 1:
        return None
    lib = _native_encoder()
    if lib is None:
        return None
    import ctypes
    cast = [np.ascontiguousarray(a, dtype=np.float64) for a in arrays]
    n = len(cast[0])
    out = np.empty(n, dtype=np.int64)
    ptr = ctypes.POINTER(ctypes.c_double)
    getattr(lib, fn_name)(
        *[a.ctypes.data_as(ptr) for a in cast], n, *extra,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    # zero-copy dtype parity with the numpy path (uint64)
    return out.view(np.uint64)


def _bounded(dims_and_values, lenient: bool, what: str):
    """Shared strict/lenient bounds handling: raise on out-of-bounds
    values unless lenient, in which case clamp (Z3SFC.scala:33-50)."""
    out = []
    for dim, values in dims_and_values:
        values = np.asarray(values, dtype=np.float64)
        if lenient:
            out.append(dim.clamp(values))
        else:
            if bool(np.any(~dim.in_bounds(values))):
                raise ValueError(f"value(s) out of bounds for {what}")
            out.append(values)
    return out


class Z2SFC:
    """2-D z-order curve, 31 bits per dimension (Z2SFC.scala:15)."""

    def __init__(self, precision: int = zorder.Z2_BITS):
        self.precision = precision
        self.lon = normalized_lon(precision)
        self.lat = normalized_lat(precision)

    def index(self, x, y, lenient: bool = False) -> np.ndarray:
        if lenient and self.precision == zorder.Z2_BITS:
            out = _native_index("geomesa_z2_encode", (x, y))
            if out is not None:
                return out
        x, y = _bounded([(self.lon, x), (self.lat, y)], lenient, "z2 index")
        return zorder.z2_encode(self.lon.normalize(x), self.lat.normalize(y))

    def invert(self, z):
        xi, yi = zorder.z2_decode(z)
        return self.lon.denormalize(xi), self.lat.denormalize(yi)

    def ranges(self, xy, precision: int = 64,
               max_ranges: int | None = None) -> np.ndarray:
        """xy: iterable of (xmin, ymin, xmax, ymax) boxes -> [n,2] z ranges."""
        out = []
        for (xmin, ymin, xmax, ymax) in xy:
            lo = (self.lon.normalize(xmin), self.lat.normalize(ymin))
            hi = (self.lon.normalize(xmax), self.lat.normalize(ymax))
            out.append(_zranges(lo, hi, self.precision,
                                       precision=precision,
                                       max_ranges=max_ranges))
        if not out:
            return np.empty((0, 2), dtype=np.int64)
        return _merge_ranges(np.concatenate(out, axis=0))


class Z3SFC:
    """3-D (lon, lat, binned-time-offset) z-order curve, 21 bits per
    dimension (Z3SFC.scala:22)."""

    def __init__(self, period: TimePeriod | str = TimePeriod.WEEK,
                 precision: int = zorder.Z3_BITS):
        self.period = TimePeriod.parse(period)
        self.precision = precision
        self.lon = normalized_lon(precision)
        self.lat = normalized_lat(precision)
        self.time = normalized_time(precision, float(timebin.max_offset(self.period)))

    @property
    def whole_period(self) -> tuple[int, int]:
        return (0, int(self.time.max))

    def index(self, x, y, t, lenient: bool = False) -> np.ndarray:
        """x/y doubles, t = offset within the time bin (not epoch millis)."""
        if lenient and self.precision == zorder.Z3_BITS:
            import ctypes
            out = _native_index("geomesa_z3_encode", (x, y, t),
                                extra=(ctypes.c_double(self.time.max),))
            if out is not None:
                return out
        x, y, t = _bounded([(self.lon, x), (self.lat, y), (self.time, t)],
                           lenient, "z3 index")
        return zorder.z3_encode(self.lon.normalize(x), self.lat.normalize(y),
                                self.time.normalize(t))

    def invert(self, z):
        xi, yi, ti = zorder.z3_decode(z)
        return (self.lon.denormalize(xi), self.lat.denormalize(yi),
                self.time.denormalize(ti).astype(np.int64))

    def ranges(self, xy, t, precision: int = 64,
               max_ranges: int | None = None) -> np.ndarray:
        """xy: (xmin, ymin, xmax, ymax) boxes; t: (tmin, tmax) offset pairs
        within one time bin -> [n,2] covering z ranges."""
        out = []
        for (xmin, ymin, xmax, ymax) in xy:
            for (tmin, tmax) in t:
                lo = (self.lon.normalize(xmin), self.lat.normalize(ymin),
                      self.time.normalize(tmin))
                hi = (self.lon.normalize(xmax), self.lat.normalize(ymax),
                      self.time.normalize(tmax))
                out.append(_zranges(lo, hi, self.precision,
                                           precision=precision,
                                           max_ranges=max_ranges))
        if not out:
            return np.empty((0, 2), dtype=np.int64)
        return _merge_ranges(np.concatenate(out, axis=0))


_Z3_CACHE: dict[TimePeriod, Z3SFC] = {}
_Z2 = Z2SFC()


def z3sfc(period: TimePeriod | str) -> Z3SFC:
    period = TimePeriod.parse(period)
    if period not in _Z3_CACHE:
        _Z3_CACHE[period] = Z3SFC(period)
    return _Z3_CACHE[period]


def z2sfc() -> Z2SFC:
    return _Z2
