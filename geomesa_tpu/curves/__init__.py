"""L0 math core: space-filling curves (SURVEY.md section 2.1, geomesa-z3).

Host-side numpy implementations of z-order encode/decode, bit
normalization, time binning and z-range decomposition.  The device scan
path never touches 64-bit z keys: it compares normalized int32
coordinates, matching the reference's server-side Z3Filter semantics.
"""

from .normalize import NormalizedDimension, normalized_lat, normalized_lon, normalized_time
from .timebin import BinnedTime, TimePeriod, bins_of_interval, from_binned, max_offset, to_binned
from .zorder import (Z2_BITS, Z3_BITS, z2_combine, z2_decode, z2_encode, z2_split,
                     z3_combine, z3_decode, z3_encode, z3_split)
from .zranges import DEFAULT_MAX_RANGES, merge_ranges, zranges
from .sfc import Z2SFC, Z3SFC, z2sfc, z3sfc
from .legacy import LegacyZ3SFC, legacy_z3sfc

__all__ = [
    "LegacyZ3SFC", "legacy_z3sfc",
    "NormalizedDimension", "normalized_lat", "normalized_lon", "normalized_time",
    "BinnedTime", "TimePeriod", "bins_of_interval", "from_binned", "max_offset",
    "to_binned", "Z2_BITS", "Z3_BITS", "z2_combine", "z2_decode", "z2_encode",
    "z2_split", "z3_combine", "z3_decode", "z3_encode", "z3_split",
    "DEFAULT_MAX_RANGES", "merge_ranges", "zranges",
    "Z2SFC", "Z3SFC", "z2sfc", "z3sfc",
]
