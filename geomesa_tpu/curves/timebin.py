"""Epoch binning of timestamps: (bin, offset) pairs.

Mirrors the reference's ``BinnedTime``
(geomesa-z3/.../curve/BinnedTime.scala:44-121): a timestamp is split into
a small integer *bin* (days / weeks / calendar-months / calendar-years
since the java epoch) and an *offset* into that bin (millis / seconds /
seconds / minutes respectively).  Binning the time axis is what lets a
century of data become a few thousand independent per-bin scans — on TPU
the bin axis becomes a batch/grid axis of a sharded computation.

All functions are vectorized over int64 epoch-millis numpy arrays.
Calendar-aware month/year binning uses numpy ``datetime64`` truncation,
which agrees with joda's ``monthsBetween(Epoch, d)`` /
``yearsBetween(Epoch, d)`` because the anchor is exactly
1970-01-01T00:00:00Z (the first instant of a month and a year).
"""

from __future__ import annotations

import enum

import numpy as np

__all__ = ["TimePeriod", "BinnedTime", "max_offset", "max_date_millis",
           "to_binned", "from_binned", "bin_start_millis", "bins_of_interval"]

MILLIS_PER_DAY = 86_400_000
MILLIS_PER_WEEK = 7 * MILLIS_PER_DAY
MAX_BIN = 32767  # Short.MaxValue in the reference; bins are int16-sized


class TimePeriod(str, enum.Enum):
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @classmethod
    def parse(cls, s: "str | TimePeriod") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return cls(s.lower())


class BinnedTime:
    """A (bin, offset) pair; kept as plain ints for host-side planning."""

    __slots__ = ("bin", "offset")

    def __init__(self, bin: int, offset: int):
        self.bin = int(bin)
        self.offset = int(offset)

    def __repr__(self) -> str:
        return f"BinnedTime(bin={self.bin}, offset={self.offset})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, BinnedTime)
                and self.bin == other.bin and self.offset == other.offset)

    def __hash__(self) -> int:
        return hash((self.bin, self.offset))


def max_offset(period: TimePeriod) -> int:
    """Max indexable offset within a bin (BinnedTime.scala:115-121).

    Day => millis/day; Week => seconds/week; Month => seconds in 31 days;
    Year => minutes in 52 weeks.
    """
    period = TimePeriod.parse(period)
    if period is TimePeriod.DAY:
        return MILLIS_PER_DAY
    if period is TimePeriod.WEEK:
        return MILLIS_PER_WEEK // 1000
    if period is TimePeriod.MONTH:
        return (MILLIS_PER_DAY // 1000) * 31
    return (MILLIS_PER_WEEK // 60_000) * 52


def _epoch_ms(dt64) -> np.ndarray:
    return dt64.astype("datetime64[ms]").astype(np.int64)


def max_date_millis(period: TimePeriod) -> int:
    """Exclusive max indexable date, in epoch millis (bin fits a Short)."""
    period = TimePeriod.parse(period)
    n = MAX_BIN + 1
    if period is TimePeriod.DAY:
        return n * MILLIS_PER_DAY
    if period is TimePeriod.WEEK:
        return n * MILLIS_PER_WEEK
    if period is TimePeriod.MONTH:
        return int(_epoch_ms(np.datetime64(n, "M")))
    return int(_epoch_ms(np.datetime64(n, "Y")))


_native_binned = None  # None = unprobed, False = unavailable


def _native_to_binned(millis: np.ndarray, period: TimePeriod):
    """Fused native clamp+divide for DAY/WEEK (native/src/zbuild.cpp):
    numpy int64 division scalar-loops, so the constant-divisor C++
    multiply-shift is ~10x faster on big columns. None when the
    library is absent or the period is calendar-based."""
    global _native_binned
    if _native_binned is False or period not in (TimePeriod.DAY,
                                                 TimePeriod.WEEK):
        return None
    import ctypes
    if _native_binned is None:
        from ..native import symbols
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib = symbols({
            "geomesa_binned": (ctypes.c_int64,
                               [i64p, ctypes.c_int64, ctypes.c_int32,
                                i32p, i64p]),
        })
        _native_binned = lib if lib is not None else False
        if _native_binned is False:
            return None
    millis = np.ascontiguousarray(millis, dtype=np.int64)
    n = len(millis)
    bins = np.empty(n, dtype=np.int32)
    offs = np.empty(n, dtype=np.int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    rc = _native_binned.geomesa_binned(
        millis.ctypes.data_as(i64p), n,
        0 if period is TimePeriod.DAY else 1,
        bins.ctypes.data_as(i32p), offs.ctypes.data_as(i64p))
    return None if rc != 0 else (bins, offs)


def to_binned(millis, period: TimePeriod, lenient: bool = False):
    """Vectorized epoch-millis -> (bins:int32, offsets:int64).

    Matches BinnedTime.scala to{Day,Week,Month,Year}And* semantics.
    With ``lenient`` out-of-range values clamp instead of raising.
    """
    period = TimePeriod.parse(period)
    millis = np.asarray(millis, dtype=np.int64)
    if lenient and millis.ndim == 1 and len(millis) >= 4096:
        out = _native_to_binned(millis, period)
        if out is not None:
            return out
    lo, hi = 0, max_date_millis(period)
    if lenient:
        millis = np.clip(millis, lo, hi - 1)
    elif bool(np.any((millis < lo) | (millis >= hi))):
        bad = millis[(millis < lo) | (millis >= hi)]
        raise ValueError(
            f"date exceeds indexable range [0, {hi}) for period {period.value}: "
            f"{bad[:3].tolist()}")

    if period is TimePeriod.DAY:
        bins = millis // MILLIS_PER_DAY
        offs = millis - bins * MILLIS_PER_DAY
    elif period is TimePeriod.WEEK:
        bins = millis // MILLIS_PER_WEEK
        offs = (millis - bins * MILLIS_PER_WEEK) // 1000
    else:
        unit = "M" if period is TimePeriod.MONTH else "Y"
        dt = millis.astype("datetime64[ms]")
        binned = dt.astype(f"datetime64[{unit}]")
        bins = binned.astype(np.int64)
        start = _epoch_ms(binned)
        if period is TimePeriod.MONTH:
            offs = (millis - start) // 1000
        else:
            offs = (millis - start) // 60_000
    return bins.astype(np.int32), offs.astype(np.int64)


def bin_start_millis(bins, period: TimePeriod) -> np.ndarray:
    """Vectorized bin index -> epoch millis of the bin's first instant."""
    period = TimePeriod.parse(period)
    bins = np.asarray(bins, dtype=np.int64)
    if period is TimePeriod.DAY:
        return bins * MILLIS_PER_DAY
    if period is TimePeriod.WEEK:
        return bins * MILLIS_PER_WEEK
    unit = "M" if period is TimePeriod.MONTH else "Y"
    return _epoch_ms(bins.astype(f"datetime64[{unit}]"))


def from_binned(bins, offsets, period: TimePeriod) -> np.ndarray:
    """Vectorized (bin, offset) -> epoch millis."""
    period = TimePeriod.parse(period)
    offsets = np.asarray(offsets, dtype=np.int64)
    start = bin_start_millis(bins, period)
    if period is TimePeriod.DAY:
        return start + offsets
    if period in (TimePeriod.WEEK, TimePeriod.MONTH):
        return start + offsets * 1000
    return start + offsets * 60_000


def bins_of_interval(lo_millis: int, hi_millis: int, period: TimePeriod):
    """All (bin, lo_offset, hi_offset) triples covering [lo, hi] millis,
    clamped to the indexable range.  This is the per-bin fan-out the query
    planner uses (Z3IndexKeySpace.scala:100-116): interior bins cover the
    whole period; edge bins carry partial offsets.

    Returns (bins:int32[], lo_offs:int64[], hi_offs:int64[]) with
    inclusive offset bounds.
    """
    period = TimePeriod.parse(period)
    hi_cap = max_date_millis(period) - 1
    # intervals entirely outside the indexable range match nothing; test
    # BEFORE clamping so they don't collapse onto a spurious boundary bin
    if hi_millis < lo_millis or hi_millis < 0 or lo_millis > hi_cap:
        return (np.empty(0, np.int32), np.empty(0, np.int64), np.empty(0, np.int64))
    lo_millis = int(np.clip(lo_millis, 0, hi_cap))
    hi_millis = int(np.clip(hi_millis, 0, hi_cap))
    lo_bin, lo_off = to_binned(lo_millis, period)
    hi_bin, hi_off = to_binned(hi_millis, period)
    lo_bin, hi_bin = int(lo_bin), int(hi_bin)
    bins = np.arange(lo_bin, hi_bin + 1, dtype=np.int32)
    full = max_offset(period)
    lo_offs = np.full(bins.shape, 0, dtype=np.int64)
    hi_offs = np.full(bins.shape, full, dtype=np.int64)
    lo_offs[0] = int(lo_off)
    hi_offs[-1] = int(hi_off)
    return bins, lo_offs, hi_offs
