"""Z-order (Morton) bit interleaving, vectorized over numpy uint64.

The reference delegates interleaving to the external ``sfcurve`` library
(Z3SFC.scala:22 imports ``org.locationtech.sfcurve.zorder.{Z3, ZRange}``);
this is a from-scratch magic-number implementation of the same math:

- Z2: 2 dims x 31 bits -> 62-bit key (Z2SFC.scala:15 uses precision 31)
- Z3: 3 dims x 21 bits -> 63-bit key (Z3SFC.scala:22 uses precision 21)

Host-side only: z keys are *build and plan time* artifacts (sorting, range
decomposition).  The TPU scan path compares normalized int32 coordinates
directly (exactly what the reference's Z3Filter does server-side,
index/filters/Z3Filter.scala:22-58), so 64-bit ints never reach the device.
"""

from __future__ import annotations

import numpy as np

__all__ = ["z2_split", "z2_combine", "z2_encode", "z2_decode",
           "z3_split", "z3_combine", "z3_encode", "z3_decode",
           "Z2_BITS", "Z3_BITS", "Z2_MAX", "Z3_MAX"]

Z2_BITS = 31   # bits per dimension
Z3_BITS = 21
Z2_MAX = (1 << (2 * Z2_BITS)) - 1  # max z2 key value
Z3_MAX = (1 << (3 * Z3_BITS)) - 1


def _u64(x) -> np.ndarray:
    return np.asarray(x).astype(np.uint64)


def z2_split(x) -> np.ndarray:
    """Spread the low 31 bits of each value to even bit positions."""
    x = _u64(x) & np.uint64(0x7FFFFFFF)
    x = (x ^ (x << np.uint64(16))) & np.uint64(0x0000FFFF0000FFFF)
    x = (x ^ (x << np.uint64(8))) & np.uint64(0x00FF00FF00FF00FF)
    x = (x ^ (x << np.uint64(4))) & np.uint64(0x0F0F0F0F0F0F0F0F)
    x = (x ^ (x << np.uint64(2))) & np.uint64(0x3333333333333333)
    x = (x ^ (x << np.uint64(1))) & np.uint64(0x5555555555555555)
    return x


def z2_combine(z) -> np.ndarray:
    """Inverse of z2_split: gather even bits back to the low 31 bits."""
    x = _u64(z) & np.uint64(0x5555555555555555)
    x = (x ^ (x >> np.uint64(1))) & np.uint64(0x3333333333333333)
    x = (x ^ (x >> np.uint64(2))) & np.uint64(0x0F0F0F0F0F0F0F0F)
    x = (x ^ (x >> np.uint64(4))) & np.uint64(0x00FF00FF00FF00FF)
    x = (x ^ (x >> np.uint64(8))) & np.uint64(0x0000FFFF0000FFFF)
    x = (x ^ (x >> np.uint64(16))) & np.uint64(0x00000000FFFFFFFF)
    return x.astype(np.int64)


def z2_encode(x, y) -> np.ndarray:
    """Interleave two 31-bit ints into a 62-bit z2 key (x gets bit 0)."""
    return z2_split(x) | (z2_split(y) << np.uint64(1))


def z2_decode(z):
    z = _u64(z)
    return z2_combine(z), z2_combine(z >> np.uint64(1))


def z3_split(x) -> np.ndarray:
    """Spread the low 21 bits of each value to every 3rd bit position."""
    x = _u64(x) & np.uint64(0x1FFFFF)
    x = (x | (x << np.uint64(32))) & np.uint64(0x1F00000000FFFF)
    x = (x | (x << np.uint64(16))) & np.uint64(0x1F0000FF0000FF)
    x = (x | (x << np.uint64(8))) & np.uint64(0x100F00F00F00F00F)
    x = (x | (x << np.uint64(4))) & np.uint64(0x10C30C30C30C30C3)
    x = (x | (x << np.uint64(2))) & np.uint64(0x1249249249249249)
    return x


def z3_combine(z) -> np.ndarray:
    """Inverse of z3_split."""
    x = _u64(z) & np.uint64(0x1249249249249249)
    x = (x ^ (x >> np.uint64(2))) & np.uint64(0x10C30C30C30C30C3)
    x = (x ^ (x >> np.uint64(4))) & np.uint64(0x100F00F00F00F00F)
    x = (x ^ (x >> np.uint64(8))) & np.uint64(0x1F0000FF0000FF)
    x = (x ^ (x >> np.uint64(16))) & np.uint64(0x1F00000000FFFF)
    x = (x ^ (x >> np.uint64(32))) & np.uint64(0x1FFFFF)
    return x.astype(np.int64)


def z3_encode(x, y, t) -> np.ndarray:
    """Interleave three 21-bit ints into a 63-bit z3 key (x gets bit 0)."""
    return (z3_split(x) | (z3_split(y) << np.uint64(1))
            | (z3_split(t) << np.uint64(2)))


def z3_decode(z):
    z = _u64(z)
    return (z3_combine(z), z3_combine(z >> np.uint64(1)),
            z3_combine(z >> np.uint64(2)))
