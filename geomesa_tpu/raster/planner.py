"""Raster query planner + coverage reader.

The analogs of AccumuloRasterQueryPlanner
(geomesa-accumulo-raster/.../data/AccumuloRasterQueryPlanner.scala:
pick the best stored resolution for a requested one, then turn the
query extent into covering key ranges) and GeoMesaCoverageReader
(.../raster/wcs/GeoMesaCoverageReader.scala: the WCS read(width,
height, envelope) surface that mosaics the chunks).

TPU-native shape: level selection is a resolution comparison over the
pyramid's per-level pixel pitches; the extent decomposes into geohash
cells grouped into LEXICOGRAPHIC RUNS (the key-range form the
reference hands its scanner); the mosaic itself is the store's jitted
gather kernel.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..geohash import _BASE32, covering

__all__ = ["RasterQueryPlan", "RasterQueryPlanner", "CoverageReader"]


def _geohash_succ(gh: str) -> str | None:
    """Lexicographic successor at the same precision (base-32 with
    carry); None past the last cell."""
    chars = list(gh)
    for i in range(len(chars) - 1, -1, -1):
        j = _BASE32.index(chars[i])
        if j + 1 < len(_BASE32):
            chars[i] = _BASE32[j + 1]
            return "".join(chars)
        chars[i] = _BASE32[0]
    return None


def _ranges_of(geohashes: list[str]) -> list[tuple[str, str]]:
    """Sorted geohashes -> [lo, hi] lexicographic runs (inclusive)."""
    out: list[tuple[str, str]] = []
    for gh in sorted(geohashes):
        if out and _geohash_succ(out[-1][1]) == gh:
            out[-1] = (out[-1][0], gh)
        else:
            out.append((gh, gh))
    return out


@dataclasses.dataclass
class RasterQueryPlan:
    level: int                       # chosen pyramid level
    precision: int                   # geohash precision of that level
    resolution: float                # degrees/pixel at that level
    target_resolution: float         # what the request asked for
    geohashes: list[str]             # covering cells of the extent

    @property
    def n_tiles(self) -> int:
        return len(self.geohashes)

    @property
    def ranges(self) -> list[tuple[str, str]]:
        """Covering cells as inclusive lexicographic key runs, built
        on demand (the mosaic read path never needs them)."""
        if not hasattr(self, "_ranges"):
            self._ranges = _ranges_of(self.geohashes)
        return self._ranges


class RasterQueryPlanner:
    """Chooses the overview level and decomposes the extent."""

    def __init__(self, store):
        self.store = store
        self._res_cache: dict[int, float | None] = {}

    def resolution_of(self, level: int) -> float | None:
        """Degrees/pixel of a stored level (cell width over tile
        pixels), or None when the level holds no tiles. Cached — the
        pitch is a per-level constant."""
        if level not in self._res_cache:
            res = None
            for (lv, gh), tile in self.store._tiles.items():
                if lv == level:
                    from ..geohash import decode_bbox
                    x0, _, x1, _ = decode_bbox(gh)
                    res = (x1 - x0) / tile.shape[1]
                    break
            self._res_cache[level] = res
        return self._res_cache[level]

    def select_level(self, target_resolution: float) -> int | None:
        """The reference's closest-resolution policy
        (AccumuloRasterQueryPlanner: serve the stored resolution best
        matching the request): the COARSEST level still at least as
        fine as the request (no detail lost, least data touched);
        when nothing is fine enough, the finest available."""
        best = None
        best_res = None
        finest = None
        finest_res = np.inf
        for lv in self.store.levels:
            res = self.resolution_of(lv)
            if res is None:
                continue
            if res < finest_res:
                finest, finest_res = lv, res
            if res <= target_resolution and (best_res is None
                                             or res > best_res):
                best, best_res = lv, res
        return best if best is not None else finest

    def plan(self, bbox, width: int, height: int) -> RasterQueryPlan | None:
        xmin, ymin, xmax, ymax = (float(v) for v in bbox)
        # the tighter of the two axes' pixel pitches: a tall skinny
        # output must still get vertical detail
        target = min((xmax - xmin) / max(width, 1),
                     (ymax - ymin) / max(height, 1))
        level = self.select_level(target)
        if level is None:
            return None
        from . import _level_precision
        prec = _level_precision(level)
        ghs = sorted(covering(xmin, ymin, xmax, ymax, prec))
        return RasterQueryPlan(level, prec,
                               float(self.resolution_of(level)),
                               target, ghs)


class CoverageReader:
    """WCS-shaped read surface (GeoMesaCoverageReader.read analog):
    plan -> gather the planned tiles -> device mosaic. Uses the
    store's memoized planner so per-level resolutions stay cached
    across reads."""

    def __init__(self, store):
        self.store = store

    @property
    def planner(self) -> RasterQueryPlanner:
        return self.store.planner()

    def read(self, bbox, width: int, height: int) -> np.ndarray:
        return self.store.read(bbox, width, height)
