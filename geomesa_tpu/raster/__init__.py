"""Raster/coverage store (geomesa-accumulo-raster analog).

The reference stores raster chunks in Accumulo keyed by
[resolution-lexicode][geohash] (raster/data/AccumuloRasterStore.scala:37,
RasterIndexSchema), picks the closest available resolution at query time
(AccumuloRasterQueryPlanner), filters chunks by bbox with a server-side
iterator (RasterFilteringIterator), and mosaics client-side for WCS
(GeoMesaCoverageReader).

TPU-native shape: tiles are dense float32 arrays keyed by
(resolution-level, geohash); query = geohash covering of the bbox at the
level's precision (an index lookup, not a scan); the mosaic resample is
one jitted gather kernel on device — the "client mosaic" becomes an XLA
program.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import math
import os

import numpy as np

from ..geohash import covering, decode_bbox, encode

__all__ = ["RasterStore", "RasterTile", "RasterQueryPlanner",
           "RasterQueryPlan", "CoverageReader"]


@dataclasses.dataclass
class RasterTile:
    """One stored chunk: data over the geohash cell's bbox."""
    geohash: str
    level: int          # resolution level (higher = finer)
    data: np.ndarray    # (h, w) float32, row 0 = south edge

    @property
    def bbox(self) -> tuple[float, float, float, float]:
        return decode_bbox(self.geohash)


def _level_precision(level: int) -> int:
    """Geohash precision for a resolution level: level n tiles cover
    precision-n cells (reference: geohash length keys the chunk size)."""
    return max(1, min(9, level))


@functools.partial(__import__("jax").jit, static_argnames=("out_h", "out_w"))
def _resample_kernel(tile_stack, tile_x0, tile_y0, tile_sx, tile_sy,
                     tile_valid, xs, ys, out_h: int, out_w: int):
    """Nearest-neighbor mosaic: for each output pixel, find the first
    valid tile containing it and gather the pixel. tile_stack is
    (n_tiles, th, tw); xs/ys are output pixel centers."""
    import jax.numpy as jnp
    n, th, tw = tile_stack.shape
    gx = xs[None, :]                      # (1, W)
    gy = ys[:, None]                      # (H, 1)
    # per-tile fractional indices
    fx = (gx[None] - tile_x0[:, None, None]) / tile_sx[:, None, None]
    fy = (gy[None] - tile_y0[:, None, None]) / tile_sy[:, None, None]
    ix = jnp.floor(fx).astype(jnp.int32)
    iy = jnp.floor(fy).astype(jnp.int32)
    inside = ((ix >= 0) & (ix < tw) & (iy >= 0) & (iy < th)
              & tile_valid[:, None, None])
    ixc = jnp.clip(ix, 0, tw - 1)
    iyc = jnp.clip(iy, 0, th - 1)
    vals = jnp.take_along_axis(
        tile_stack.reshape(n, -1),
        (iyc * tw + ixc).reshape(n, -1), axis=1).reshape(n, out_h, out_w)
    # first valid tile wins
    first = jnp.argmax(inside, axis=0)
    any_valid = jnp.any(inside, axis=0)
    picked = jnp.take_along_axis(vals, first[None], axis=0)[0]
    return jnp.where(any_valid, picked, jnp.nan)


class RasterStore:
    """In-memory (optionally directory-persisted) pyramid of raster
    tiles with bbox query + device mosaic."""

    def __init__(self, directory: str | None = None):
        self.directory = directory
        self._tiles: dict[tuple[int, str], np.ndarray] = {}
        self._planner = None  # memoized; invalidated by put_raster
        if directory and os.path.isdir(directory):
            self._load_catalog()

    # -- ingest ------------------------------------------------------------

    def put_raster(self, data: np.ndarray, bbox, level: int,
                   tile_size: int = 256):
        """Chop a georeferenced grid into geohash tiles at `level`.

        data is (h, w), row 0 at the south edge, spanning bbox
        (xmin, ymin, xmax, ymax).
        """
        data = np.asarray(data, dtype=np.float32)
        h, w = data.shape
        xmin, ymin, xmax, ymax = (float(v) for v in bbox)
        sx = (xmax - xmin) / w
        sy = (ymax - ymin) / h
        prec = _level_precision(level)
        for gh in covering(xmin, ymin, xmax, ymax, prec):
            gx0, gy0, gx1, gy1 = decode_bbox(gh)
            # source index range overlapping this cell
            c0 = max(0, int(math.floor((gx0 - xmin) / sx)))
            c1 = min(w, int(math.ceil((gx1 - xmin) / sx)))
            r0 = max(0, int(math.floor((gy0 - ymin) / sy)))
            r1 = min(h, int(math.ceil((gy1 - ymin) / sy)))
            if c1 <= c0 or r1 <= r0:
                continue
            # resample the overlap onto the tile grid (nearest)
            tile = np.full((tile_size, tile_size), np.nan, dtype=np.float32)
            tx = (np.arange(tile_size) + 0.5) / tile_size * (gx1 - gx0) + gx0
            ty = (np.arange(tile_size) + 0.5) / tile_size * (gy1 - gy0) + gy0
            ci = np.floor((tx - xmin) / sx).astype(int)
            ri = np.floor((ty - ymin) / sy).astype(int)
            okc = (ci >= 0) & (ci < w)
            okr = (ri >= 0) & (ri < h)
            sub = data[np.clip(ri, 0, h - 1)[:, None],
                       np.clip(ci, 0, w - 1)[None, :]]
            sub = np.where(okr[:, None] & okc[None, :], sub, np.nan)
            key = (level, gh)
            if key in self._tiles:  # merge: new data wins where non-nan
                old = self._tiles[key]
                sub = np.where(np.isnan(sub), old, sub)
            self._tiles[key] = sub
            self._persist(key, sub)
        self._planner = None  # level set / resolutions may have changed

    # -- query -------------------------------------------------------------

    @property
    def levels(self) -> list[int]:
        return sorted({lv for lv, _ in self._tiles})

    def closest_level(self, level: int) -> int | None:
        """The available level closest to the request (the reference's
        closest-resolution pick, AccumuloRasterQueryPlanner)."""
        lvls = self.levels
        if not lvls:
            return None
        return min(lvls, key=lambda lv: (abs(lv - level), -lv))

    def query_tiles(self, bbox, level: int) -> list[RasterTile]:
        lv = self.closest_level(level)
        if lv is None:
            return []
        prec = _level_precision(lv)
        out = []
        for gh in covering(*(float(v) for v in bbox), prec):
            t = self._tiles.get((lv, gh))
            if t is not None:
                out.append(RasterTile(gh, lv, t))
        return out

    def mosaic(self, bbox, width: int, height: int,
               level: int | None = None) -> np.ndarray:
        """Assemble a (height, width) grid over bbox on device; NaN where
        no coverage."""
        xmin, ymin, xmax, ymax = (float(v) for v in bbox)
        if level is None:
            # pick the level whose tile pixel pitch best matches the output
            level = 9
            for lv in self.levels:
                gh = next(g for (l2, g) in self._tiles if l2 == lv)
                x0, y0, x1, y1 = decode_bbox(gh)
                if (x1 - x0) / self._tiles[(lv, gh)].shape[1] <= \
                        (xmax - xmin) / width:
                    level = lv
                    break
        tiles = self.query_tiles(bbox, level)
        if not tiles:
            return np.full((height, width), np.nan, dtype=np.float32)
        stack = np.stack([t.data for t in tiles])
        x0 = np.array([t.bbox[0] for t in tiles], dtype=np.float32)
        y0 = np.array([t.bbox[1] for t in tiles], dtype=np.float32)
        sxv = np.array([(t.bbox[2] - t.bbox[0]) / t.data.shape[1]
                        for t in tiles], dtype=np.float32)
        syv = np.array([(t.bbox[3] - t.bbox[1]) / t.data.shape[0]
                        for t in tiles], dtype=np.float32)
        valid = np.ones(len(tiles), dtype=bool)
        xs = (np.arange(width, dtype=np.float32) + 0.5) \
            * (xmax - xmin) / width + xmin
        ys = (np.arange(height, dtype=np.float32) + 0.5) \
            * (ymax - ymin) / height + ymin
        out = _resample_kernel(stack, x0, y0, sxv, syv, valid, xs, ys,
                               height, width)
        return np.asarray(out)

    # -- persistence -------------------------------------------------------

    def _persist(self, key, tile):
        if not self.directory:
            return
        lv, gh = key
        d = os.path.join(self.directory, str(lv))
        os.makedirs(d, exist_ok=True)
        np.save(os.path.join(d, f"{gh}.npy"), tile)

    def _load_catalog(self):
        for lv_name in os.listdir(self.directory):
            d = os.path.join(self.directory, lv_name)
            if not (os.path.isdir(d) and lv_name.isdigit()):
                continue
            for f in os.listdir(d):
                if f.endswith(".npy"):
                    self._tiles[(int(lv_name), f[:-4])] = \
                        np.load(os.path.join(d, f))

    @property
    def num_tiles(self) -> int:
        return len(self._tiles)

    # -- planned coverage reads ---------------------------------------------

    def planner(self) -> "RasterQueryPlanner":
        """Memoized — the planner's per-level resolution cache must
        survive across reads (a WCS client issues many)."""
        if self._planner is None:
            self._planner = RasterQueryPlanner(self)
        return self._planner

    def read(self, bbox, width: int, height: int) -> np.ndarray:
        """WCS-shaped coverage read (GeoMesaCoverageReader.read
        analog): the query planner selects the overview level for the
        requested output resolution and decomposes the extent into
        tile key ranges; the device mosaic assembles the grid."""
        plan = self.planner().plan(bbox, width, height)
        if plan is None or plan.n_tiles == 0:
            return np.full((height, width), np.nan, dtype=np.float32)
        return self.mosaic(bbox, width, height, level=plan.level)


from .planner import (CoverageReader, RasterQueryPlan,  # noqa: E402
                      RasterQueryPlanner)
