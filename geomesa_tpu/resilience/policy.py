"""Retry policy: exponential backoff + full jitter, deadlines, budget.

The reference never writes this logic — Accumulo Thrift scanners and
HBase RPC retry, back off and fail over inside the client stacks
(SURVEY.md 2.6), so GeoMesaDataStore sees transient faults as slow
calls, not errors. Our networked tier is stdlib HTTP/TCP; this module
is the missing client stack, shared by RemoteDataStore and SocketBus:

- full-jitter exponential backoff (AWS-style: sleep ~ U(0, min(cap,
  base * 2^attempt))) so synchronized clients don't retry in lockstep;
- per-call total deadline on top of the attempt cap, so a retried call
  has bounded worst-case latency;
- a token-bucket retry budget shared across calls: each first attempt
  deposits a fraction of a token, each retry withdraws one, so a hard
  outage degrades to ~ratio extra load instead of a retry storm;
- classification by the EXCEPTION, not the call site: raisers tag
  errors with ``retryable`` (and optionally ``retry_after_s``, the
  server's explicit backpressure, e.g. a 503 Retry-After) and the
  default classifier falls back to connection/timeout types.

Every retry counts ``resilience.retries`` (and a per-site
``resilience.retries.<name>``) in the metrics registry.
"""

from __future__ import annotations

import random
import threading
import time

from ..metrics import metrics
from ..utils.properties import SystemProperty

__all__ = ["RetryPolicy", "RetryBudget", "default_retryable",
           "RETRY_ATTEMPTS", "RETRY_BASE_MS", "RETRY_CAP_MS",
           "RETRY_DEADLINE"]

# layered knobs (thread-local override -> env -> global -> default)
RETRY_ATTEMPTS = SystemProperty("geomesa.retry.attempts", "5")
RETRY_BASE_MS = SystemProperty("geomesa.retry.base.ms", "50")
RETRY_CAP_MS = SystemProperty("geomesa.retry.cap.ms", "2000")
RETRY_DEADLINE = SystemProperty("geomesa.retry.deadline", "30s")
# live multiplier on every budget's capacity (0..1]: the SLO reaction
# loop shrinks it during a fast burn so retries/hedges stop amplifying
# an outage, and restores it when the burn clears
RETRY_BUDGET_SCALE = SystemProperty("geomesa.retry.budget.scale", "1")


def default_retryable(exc: BaseException) -> bool:
    """An explicit ``retryable`` tag on the exception wins; untagged
    connection-shaped failures (reset, refused, timeout) retry."""
    tag = getattr(exc, "retryable", None)
    if tag is not None:
        return bool(tag)
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


class RetryBudget:
    """Token bucket bounding retry amplification: first attempts
    deposit ``ratio`` tokens (capped), retries withdraw one. During a
    full outage the extra retry load converges to ~ratio of the offered
    load instead of multiplying it."""

    def __init__(self, capacity: float = 10.0, ratio: float = 0.2):
        self.capacity = float(capacity)
        self.ratio = float(ratio)
        self._tokens = float(capacity)
        self._lock = threading.Lock()

    def effective_capacity(self) -> float:
        """Capacity after the live ``geomesa.retry.budget.scale``
        multiplier — re-read per call so the SLO reaction (or an
        operator) can throttle every budget in the process at once."""
        try:
            scale = float(RETRY_BUDGET_SCALE.get() or 1.0)
        except (TypeError, ValueError):
            scale = 1.0
        return self.capacity * min(max(scale, 0.0), 1.0)

    def deposit(self):
        with self._lock:
            self._tokens = min(self.effective_capacity(),
                               self._tokens + self.ratio)

    def try_withdraw(self) -> bool:
        with self._lock:
            cap = self.effective_capacity()
            if self._tokens > cap:
                # the scale was tightened while tokens were banked:
                # clamp down so the stored surplus cannot fund a storm
                self._tokens = cap
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class RetryPolicy:
    """Run a callable with bounded retries.

    ``call(fn)`` invokes ``fn()`` until it returns, raises a
    non-retryable error, or the policy gives up (attempt cap, total
    deadline, or drained budget) — then the LAST error propagates
    unchanged, so callers keep their typed exceptions."""

    def __init__(self, max_attempts: int | None = None,
                 base_s: float | None = None, cap_s: float | None = None,
                 total_deadline_s: float | None = None,
                 budget: RetryBudget | None = None,
                 sleep=time.sleep, rng: random.Random | None = None,
                 registry=metrics):
        self.max_attempts = (RETRY_ATTEMPTS.as_int()
                             if max_attempts is None else int(max_attempts))
        self.base_s = ((RETRY_BASE_MS.as_float() or 50.0) / 1e3
                       if base_s is None else float(base_s))
        self.cap_s = ((RETRY_CAP_MS.as_float() or 2000.0) / 1e3
                      if cap_s is None else float(cap_s))
        self.total_deadline_s = (RETRY_DEADLINE.as_seconds()
                                 if total_deadline_s is None
                                 else total_deadline_s)
        self.budget = budget
        self._sleep = sleep
        self._rng = rng or random
        self._registry = registry

    def backoff_s(self, attempt: int) -> float:
        """Full jitter: U(0, min(cap, base * 2^(attempt-1)))."""
        ceiling = min(self.cap_s, self.base_s * (2 ** max(attempt - 1, 0)))
        return self._rng.uniform(0.0, ceiling)

    def _call_budget(self):
        """The budget THIS call charges: with QoS on and a tenant
        bound, the tenant's own RetryBudget (so one tenant exhausting
        retries cannot drain anybody else's); otherwise the policy's
        shared budget unchanged."""
        from ..tenants import tenant_budget
        tb = tenant_budget()
        return tb if tb is not None else self.budget

    def call(self, fn, *, retryable=None, on_retry=None, name: str = ""):
        classify = retryable or default_retryable
        deadline = (None if self.total_deadline_s is None
                    else time.monotonic() + self.total_deadline_s)
        budget = self._call_budget()
        if budget is not None:
            budget.deposit()
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as e:
                attempt += 1
                if not classify(e) or attempt >= self.max_attempts:
                    raise
                # server-directed backpressure (503 Retry-After)
                # overrides the computed backoff
                delay = getattr(e, "retry_after_s", None)
                if delay is None:
                    delay = self.backoff_s(attempt)
                if deadline is not None \
                        and time.monotonic() + delay > deadline:
                    raise
                if budget is not None \
                        and not budget.try_withdraw():
                    self._registry.counter("resilience.budget.exhausted")
                    from ..tenants import active_tenant, tenant_label
                    t = active_tenant()
                    if t is not None:
                        self._registry.counter(
                            "qos.retry.exhausted",
                            labels={"tenant": tenant_label(t)})
                    raise
                self._registry.counter("resilience.retries")
                if name:
                    self._registry.counter(f"resilience.retries.{name}")
                from ..obs import annotate
                annotate("retry", name=name, attempt=attempt,
                         error=type(e).__name__,
                         delay_ms=round(delay * 1000, 3))
                if on_retry is not None:
                    on_retry(e, attempt, delay)
                self._sleep(delay)
