"""Speculative request hedging: the tail-at-scale playbook.

A p99-slow call is usually slow for reasons a SECOND, independent
attempt does not share (a GC pause, a contended socket, one slow
replica). The hedge recipe: send the request, wait roughly the
endpoint's p99 latency, and if no answer has landed, send it again —
first success wins, the loser's result is discarded. Done naively this
doubles load during an outage, so every hedge is CHARGED to the shared
``RetryBudget`` (policy.py): when the budget is drained the call
degrades to a single attempt instead of amplifying a storm.

``HedgePolicy.call`` is the shared helper: ``RemoteDataStore`` wraps
each idempotent GET attempt in it (delay = the ``BreakerBoard``'s
per-endpoint p99 estimate, floored at ``geomesa.hedge.min.delay.ms``),
and ``ClusterDataStore`` scatter legs run under it with their leg
deadline on top. Writes and non-idempotent calls NEVER hedge — a hedge
that executes twice must be invisible, and only idempotent reads are.

Rules enforced here:

- first success resolves the call; a losing attempt that completes
  later is discarded (``resilience.hedge.cancelled``) — no caller ever
  sees two deliveries;
- a failed first attempt hedges IMMEDIATELY (no point waiting out the
  delay when we already know the answer was an error);
- the hedge only launches if the budget grants a token
  (``resilience.hedge.suppressed.budget`` otherwise);
- all attempts failing raises the LAST error unchanged; a deadline
  expiring with no resolution raises ``TimeoutError``.

Knobs: ``geomesa.hedge.enabled`` (default true) and
``geomesa.hedge.min.delay.ms`` (default 10) — the floor keeps a
microsecond-fast endpoint from hedging every call on EWMA noise.

Metrics: ``resilience.hedge.attempts`` / ``.wins`` / ``.losses`` /
``.cancelled`` / ``.suppressed.budget`` (plus per-name variants of
attempts/wins for the serving tier's dashboards).
"""

from __future__ import annotations

import contextvars
import threading
import time

from ..metrics import metrics, sanitize_key
from ..utils.properties import SystemProperty

__all__ = ["HedgePolicy", "HEDGE_ENABLED", "HEDGE_MIN_DELAY_MS"]

HEDGE_ENABLED = SystemProperty("geomesa.hedge.enabled", "true")
HEDGE_MIN_DELAY_MS = SystemProperty("geomesa.hedge.min.delay.ms", "10")


class HedgePolicy:
    """Run a callable with one speculative backup attempt.

    ``budget`` is the shared RetryBudget hedges are charged to (None =
    unmetered). ``clock``/``wait`` are injectable for deterministic
    timing tests: ``wait(cond, timeout)`` parks the caller on the
    condition for up to ``timeout`` seconds (default: real
    ``cond.wait``)."""

    def __init__(self, budget=None, min_delay_s: float | None = None,
                 registry=metrics, clock=time.monotonic, wait=None):
        self.budget = budget
        self._min_delay_override = min_delay_s
        self._registry = registry
        self._clock = clock
        self._wait = wait if wait is not None \
            else (lambda cond, timeout: cond.wait(timeout))

    # -- knobs -------------------------------------------------------------

    @staticmethod
    def enabled() -> bool:
        """Process-wide kill switch, re-read per call so operators can
        flip hedging on a live tier."""
        return str(HEDGE_ENABLED.get()).lower() in ("true", "1", "yes")

    def min_delay_s(self) -> float:
        if self._min_delay_override is not None:
            return float(self._min_delay_override)
        return (HEDGE_MIN_DELAY_MS.as_float() or 10.0) / 1e3

    def delay_s(self, p99_s: float | None) -> float | None:
        """The speculative-send delay for an endpoint whose p99-ish
        latency estimate is ``p99_s``: the estimate floored at the
        min-delay knob. None (no estimate yet) means don't hedge —
        guessing a delay with no signal just doubles load."""
        if p99_s is None:
            return None
        return max(float(p99_s), self.min_delay_s())

    # -- the hedged call ---------------------------------------------------

    def call(self, fn, delay_s: float, *, deadline_s: float | None = None,
             name: str = "", on_hedge=None):
        """Invoke ``fn()`` with one backup attempt after ``delay_s`` of
        silence (or immediately if the first attempt fails). Returns
        the first success; raises the last error when every attempt
        fails, or ``TimeoutError`` when ``deadline_s`` elapses with no
        resolution. ``on_hedge()`` fires when the backup launches (the
        cluster tier counts its own leg hedges through it)."""
        cond = threading.Condition()
        # winner holds (attempt_index, value) so win/loss attribution
        # survives the race; resolved stops late losers from delivering
        state = {"winner": None, "errs": [], "running": 0,
                 "resolved": False}
        key = sanitize_key(name) if name else ""
        # with QoS on, hedges are charged to the caller's TENANT budget
        # (tenants plane): tenant A burning its retries cannot suppress
        # tenant B's hedging
        from ..tenants import active_tenant, tenant_budget, tenant_label
        budget = tenant_budget() or self.budget
        tenant = active_tenant()

        def attempt(idx: int):
            try:
                v = fn()
            except Exception as e:  # noqa: BLE001 — attempt boundary
                with cond:
                    state["errs"].append(e)
                    state["running"] -= 1
                    cond.notify_all()
                return
            with cond:
                if state["winner"] is None and not state["resolved"]:
                    state["winner"] = (idx, v)
                else:
                    # the race was already decided: this result is
                    # discarded, never delivered twice
                    self._registry.counter("resilience.hedge.cancelled")
                state["running"] -= 1
                cond.notify_all()

        def launch(idx: int):
            # each attempt runs under a copy of the caller's context so
            # trace spans parent into the live trace (and the audit
            # hook's delegation scope reaches hedged attempts too);
            # copies are independent, so concurrent attempts never
            # re-enter one Context
            state["running"] += 1
            ctx = contextvars.copy_context()
            threading.Thread(target=ctx.run, args=(attempt, idx),
                             daemon=True,
                             name=f"hedge-{name or 'call'}-{idx}").start()

        t0 = self._clock()
        hedge_at = t0 + max(float(delay_s), 0.0)
        deadline_t = None if deadline_s is None else t0 + float(deadline_s)
        hedged, can_hedge = False, True
        with cond:
            launch(0)
            while state["winner"] is None:
                now = self._clock()
                if deadline_t is not None and now >= deadline_t:
                    state["resolved"] = True
                    raise TimeoutError(
                        f"hedged call {name or fn!r} exceeded its "
                        f"{deadline_s:g}s deadline")
                if state["running"] == 0 and (hedged or not can_hedge):
                    # every attempt has failed and no backup can launch
                    state["resolved"] = True
                    raise state["errs"][-1]
                if not hedged and can_hedge \
                        and (state["running"] == 0 or now >= hedge_at):
                    if budget is not None \
                            and not budget.try_withdraw():
                        self._registry.counter(
                            "resilience.hedge.suppressed.budget")
                        if tenant is not None:
                            self._registry.counter(
                                "qos.hedge.suppressed",
                                labels={"tenant": tenant_label(tenant)})
                        can_hedge = False
                        continue
                    hedged = True
                    self._registry.counter("resilience.hedge.attempts")
                    if key:
                        self._registry.counter(
                            f"resilience.hedge.attempts.{key}")
                    from ..obs import annotate, set_flag
                    annotate("hedge.launched", name=name,
                             delay_ms=round((now - t0) * 1000, 3))
                    set_flag("hedged")
                    if on_hedge is not None:
                        on_hedge()
                    launch(1)
                    continue
                timeout = None
                if not hedged and can_hedge:
                    timeout = hedge_at - now
                if deadline_t is not None:
                    remaining = deadline_t - now
                    timeout = (remaining if timeout is None
                               else min(timeout, remaining))
                self._wait(cond, max(timeout, 0.0005)
                           if timeout is not None else None)
            idx, value = state["winner"]
            state["resolved"] = True
        if hedged:
            # the loser is still on the wire; its socket finishes (or
            # times out) in the background and its result is discarded
            # on arrival (counted ``resilience.hedge.cancelled`` by the
            # attempt closure) — the closest an HTTP client gets to
            # true cancellation
            won = idx == 1
            self._registry.counter("resilience.hedge.wins" if won
                                   else "resilience.hedge.losses")
            if key and won:
                self._registry.counter(f"resilience.hedge.wins.{key}")
        return value
