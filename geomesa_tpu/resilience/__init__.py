"""Network resilience layer (the fault tolerance the reference gets
for free from Accumulo/HBase client stacks, SURVEY.md 2.6): retry
policies with backoff/jitter/budget (policy.py), per-endpoint circuit
breakers (breaker.py), p99-delayed speculative hedging for idempotent
reads (hedge.py), and a fault-injecting TCP proxy that proves recovery
end-to-end (chaos.py). Wired through RemoteDataStore, SocketBus, the
cluster scatter legs and the web tier; emits ``resilience.*``
metrics."""

from .breaker import (BreakerBoard, CircuitBreaker, CircuitOpenError)
from .chaos import ChaosProxy
from .hedge import HedgePolicy
from .policy import (RetryBudget, RetryPolicy, default_retryable)

__all__ = ["RetryPolicy", "RetryBudget", "default_retryable",
           "CircuitBreaker", "CircuitOpenError", "BreakerBoard",
           "ChaosProxy", "HedgePolicy"]
