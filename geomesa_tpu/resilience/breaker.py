"""Per-endpoint circuit breaker: closed / open / half-open.

Against a down server, every call otherwise burns its full socket
timeout before failing — with a 60s client timeout, ten queued queries
are ten minutes of hang. The breaker watches consecutive transport
failures per endpoint; past the threshold it OPENS and calls fail in
microseconds (``CircuitOpenError``) until a reset timeout elapses, then
HALF-OPEN lets a bounded number of probe calls through — one success
re-closes, a failure re-opens. The same state machine HBase clients
get from their RPC stack's fast-fail mode (SURVEY.md 2.6).

State transitions and fast-fails count into the metrics registry
(``resilience.breaker.opened`` / ``.half_open`` / ``.closed`` /
``.fast_fail``).
"""

from __future__ import annotations

import threading
import time

from ..metrics import metrics
from ..utils.properties import SystemProperty

__all__ = ["CircuitBreaker", "CircuitOpenError", "BreakerBoard",
           "BREAKER_FAILURES", "BREAKER_RESET_MS"]

BREAKER_FAILURES = SystemProperty("geomesa.breaker.failures", "5")
BREAKER_RESET_MS = SystemProperty("geomesa.breaker.reset.ms", "5000")

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitOpenError(ConnectionError):
    """Fast-fail: the endpoint's breaker is open. NOT retryable — the
    point is to shed load off a known-dead endpoint immediately;
    ``retry_after_s`` says when the next half-open probe is due."""

    retryable = False

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit open for {name!r} (retry in {retry_after_s:.2f}s)")
        self.endpoint = name
        self.retry_after_s = max(retry_after_s, 0.0)


class CircuitBreaker:
    """One endpoint's health gate. Callers bracket each attempt:

        breaker.acquire()          # raises CircuitOpenError when open
        ...transport attempt...
        breaker.success() / breaker.failure()

    Only TRANSPORT-level failures should be recorded as failures; an
    application error in a well-formed response (404, 400) proves the
    endpoint alive and should record success."""

    def __init__(self, name: str = "", failure_threshold: int | None = None,
                 reset_timeout_s: float | None = None,
                 half_open_max: int = 1, clock=time.monotonic,
                 registry=metrics):
        self.name = name
        self.failure_threshold = (BREAKER_FAILURES.as_int()
                                  if failure_threshold is None
                                  else int(failure_threshold))
        self.reset_timeout_s = (
            (BREAKER_RESET_MS.as_float() or 5000.0) / 1e3
            if reset_timeout_s is None else float(reset_timeout_s))
        self.half_open_max = int(half_open_max)
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_inflight = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def acquire(self):
        with self._lock:
            if self._state == CLOSED:
                return
            now = self._clock()
            due = self._opened_at + self.reset_timeout_s
            if self._state == OPEN:
                if now < due:
                    self._registry.counter("resilience.breaker.fast_fail")
                    raise CircuitOpenError(self.name, due - now)
                self._transition(HALF_OPEN)
            # half-open: a bounded probe quota feels the endpoint out
            if self._probes_inflight >= self.half_open_max:
                self._registry.counter("resilience.breaker.fast_fail")
                raise CircuitOpenError(self.name, self.reset_timeout_s)
            self._probes_inflight += 1

    def success(self):
        with self._lock:
            self._consecutive_failures = 0
            if self._state != CLOSED:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._transition(CLOSED)

    def failure(self):
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self._state == CLOSED \
                    and self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition(OPEN)

    def _transition(self, state: str):
        # lock held
        if state != self._state:
            self._state = state
            if state == HALF_OPEN:
                self._probes_inflight = 0
            self._registry.counter(
                f"resilience.breaker.{'opened' if state == OPEN else state}")


class BreakerBoard:
    """Lazily-built breaker per endpoint key (e.g. the REST route
    segment), so one dead route fails fast without tripping the rest."""

    def __init__(self, **breaker_kwargs):
        self._kw = breaker_kwargs
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = self._breakers[key] = CircuitBreaker(name=key,
                                                         **self._kw)
            return b

    def states(self) -> dict[str, str]:
        with self._lock:
            return {k: b.state for k, b in self._breakers.items()}
