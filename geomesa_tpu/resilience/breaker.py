"""Per-endpoint circuit breaker: closed / open / half-open.

Against a down server, every call otherwise burns its full socket
timeout before failing — with a 60s client timeout, ten queued queries
are ten minutes of hang. The breaker watches transport failures per
endpoint; past the trip condition it OPENS and calls fail in
microseconds (``CircuitOpenError``) until a reset timeout elapses, then
HALF-OPEN lets a bounded number of probe calls through — one success
re-closes, a failure re-opens. The same state machine HBase clients
get from their RPC stack's fast-fail mode (SURVEY.md 2.6).

Two trip conditions:

- legacy (default): ``geomesa.breaker.failures`` CONSECUTIVE failures.
  Simple, but one threshold can't fit both a 10 qps and a 10k qps
  endpoint — at high qps interleaved successes keep resetting it while
  the endpoint drops half its traffic.
- sliding error-rate window (``geomesa.breaker.window`` = N recent
  calls): trip when failures / recent calls >= ``geomesa.breaker.
  error.rate`` AND at least ``geomesa.breaker.min.volume`` calls are
  in the window (a cold endpoint's first failure is not a 100% error
  rate worth tripping on). Rate-based tripping reacts in O(window)
  calls regardless of qps and doesn't flap on isolated failures.

``BreakerBoard`` additionally keeps a per-endpoint latency EWMA
(mean + deviation, so a p99-ish upper estimate falls out) fed by the
callers that time their attempts — the signal hedged requests need to
pick their speculative delay. Exposed as ``resilience.latency.*``
gauges and in the ``/rest/health`` detail.

State transitions and fast-fails count into the metrics registry
(``resilience.breaker.opened`` / ``.half_open`` / ``.closed`` /
``.fast_fail``).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..metrics import metrics, sanitize_key
from ..utils.properties import SystemProperty

__all__ = ["CircuitBreaker", "CircuitOpenError", "BreakerBoard",
           "BREAKER_FAILURES", "BREAKER_RESET_MS", "BREAKER_WINDOW",
           "BREAKER_ERROR_RATE", "BREAKER_MIN_VOLUME"]

BREAKER_FAILURES = SystemProperty("geomesa.breaker.failures", "5")
BREAKER_RESET_MS = SystemProperty("geomesa.breaker.reset.ms", "5000")
# sliding-window trip condition (opt-in): window size in calls; unset
# keeps the legacy consecutive-failures behavior
BREAKER_WINDOW = SystemProperty("geomesa.breaker.window", None)
BREAKER_ERROR_RATE = SystemProperty("geomesa.breaker.error.rate", "0.5")
BREAKER_MIN_VOLUME = SystemProperty("geomesa.breaker.min.volume", "10")

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitOpenError(ConnectionError):
    """Fast-fail: the endpoint's breaker is open. NOT retryable — the
    point is to shed load off a known-dead endpoint immediately;
    ``retry_after_s`` says when the next half-open probe is due."""

    retryable = False

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit open for {name!r} (retry in {retry_after_s:.2f}s)")
        self.endpoint = name
        self.retry_after_s = max(retry_after_s, 0.0)


class CircuitBreaker:
    """One endpoint's health gate. Callers bracket each attempt:

        breaker.acquire()          # raises CircuitOpenError when open
        ...transport attempt...
        breaker.success() / breaker.failure()

    Only TRANSPORT-level failures should be recorded as failures; an
    application error in a well-formed response (404, 400) proves the
    endpoint alive and should record success."""

    def __init__(self, name: str = "", failure_threshold: int | None = None,
                 reset_timeout_s: float | None = None,
                 half_open_max: int = 1, clock=time.monotonic,
                 registry=metrics, window: int | None = None,
                 error_rate: float | None = None,
                 min_volume: int | None = None):
        self.name = name
        self.failure_threshold = (BREAKER_FAILURES.as_int()
                                  if failure_threshold is None
                                  else int(failure_threshold))
        self.reset_timeout_s = (
            (BREAKER_RESET_MS.as_float() or 5000.0) / 1e3
            if reset_timeout_s is None else float(reset_timeout_s))
        self.half_open_max = int(half_open_max)
        # sliding error-rate window: explicit arg wins, then the knob;
        # unset (None/0) falls back to consecutive-failure counting
        self.window = (BREAKER_WINDOW.as_int() if window is None
                       else int(window)) or None
        self.error_rate = (BREAKER_ERROR_RATE.as_float() or 0.5
                           if error_rate is None else float(error_rate))
        self.min_volume = (BREAKER_MIN_VOLUME.as_int() or 10
                           if min_volume is None else int(min_volume))
        self._clock = clock
        self._registry = registry
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._outcomes: deque[bool] = deque(maxlen=self.window or 1)
        self._opened_at = 0.0
        self._probes_inflight = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def acquire(self):
        with self._lock:
            if self._state == CLOSED:
                return
            now = self._clock()
            due = self._opened_at + self.reset_timeout_s
            if self._state == OPEN:
                if now < due:
                    self._registry.counter("resilience.breaker.fast_fail")
                    raise CircuitOpenError(self.name, due - now)
                self._transition(HALF_OPEN)
            # half-open: a bounded probe quota feels the endpoint out
            if self._probes_inflight >= self.half_open_max:
                self._registry.counter("resilience.breaker.fast_fail")
                raise CircuitOpenError(self.name, self.reset_timeout_s)
            self._probes_inflight += 1

    def success(self):
        with self._lock:
            self._consecutive_failures = 0
            if self.window:
                self._outcomes.append(False)
            if self._state != CLOSED:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._transition(CLOSED)

    def failure(self):
        with self._lock:
            self._consecutive_failures += 1
            if self.window:
                self._outcomes.append(True)
            if self._state == HALF_OPEN:
                self._probes_inflight = max(self._probes_inflight - 1, 0)
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self._state == CLOSED and self._should_trip():
                self._opened_at = self._clock()
                self._transition(OPEN)

    def _should_trip(self) -> bool:
        # lock held. Window mode: failures / recent calls crosses the
        # rate threshold with enough volume to mean something; legacy
        # mode: a consecutive-failure run.
        if self.window:
            n = len(self._outcomes)
            if n < self.min_volume:
                return False
            return sum(self._outcomes) / n >= self.error_rate
        return self._consecutive_failures >= self.failure_threshold

    def _transition(self, state: str):
        # lock held
        if state != self._state:
            self._state = state
            if state == HALF_OPEN:
                self._probes_inflight = 0
            elif state == OPEN:
                # a re-closed breaker starts with a clean slate: the
                # window's stale failures must not instantly re-trip it
                self._outcomes.clear()
            self._registry.counter(
                f"resilience.breaker.{'opened' if state == OPEN else state}")


class _LatencyEwma:
    """EWMA of call latency mean + mean absolute deviation. The p99-ish
    estimate is mean + 3·deviation — crude but monotone in tail weight,
    cheap to keep per endpoint, and exactly the signal a hedged request
    needs to pick its speculative-send delay."""

    __slots__ = ("alpha", "mean_s", "dev_s", "count")

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self.mean_s = 0.0
        self.dev_s = 0.0
        self.count = 0

    def update(self, seconds: float):
        if self.count == 0:
            self.mean_s = seconds
        else:
            err = abs(seconds - self.mean_s)
            self.dev_s += self.alpha * (err - self.dev_s)
            self.mean_s += self.alpha * (seconds - self.mean_s)
        self.count += 1

    @property
    def p99_s(self) -> float:
        return self.mean_s + 3.0 * self.dev_s

    def to_json_object(self) -> dict:
        return {"mean_ms": round(self.mean_s * 1e3, 3),
                "p99_ms": round(self.p99_s * 1e3, 3),
                "count": self.count}


class BreakerBoard:
    """Lazily-built breaker per endpoint key (e.g. the REST route
    segment), so one dead route fails fast without tripping the rest.
    Also the per-endpoint latency ledger: callers feed ``observe`` with
    each successful attempt's wall time, and ``latencies`` serves the
    EWMA mean / p99-ish estimates (surfaced on ``/rest/health`` and as
    ``resilience.latency.p99.<key>`` gauges)."""

    def __init__(self, registry=metrics, **breaker_kwargs):
        self._kw = breaker_kwargs
        self._registry = registry
        self._breakers: dict[str, CircuitBreaker] = {}
        self._latency: dict[str, _LatencyEwma] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = self._breakers[key] = CircuitBreaker(
                    name=key, registry=self._registry, **self._kw)
            return b

    def states(self) -> dict[str, str]:
        with self._lock:
            return {k: b.state for k, b in self._breakers.items()}

    # -- latency ledger ----------------------------------------------------

    def observe(self, key: str, seconds: float):
        """Record one successful call's latency for ``key``. The gauge
        key is sanitized — ``key`` is often derived from request paths
        or type names, and a hostile one (newlines, spaces, unbounded
        length) must not corrupt the ``/rest/metrics`` registry dump
        or a delimited report row."""
        with self._lock:
            e = self._latency.get(key)
            if e is None:
                e = self._latency[key] = _LatencyEwma()
            e.update(seconds)
            p99_ms = e.p99_s * 1e3
        self._registry.gauge(
            f"resilience.latency.p99.{sanitize_key(key)}", p99_ms)

    def latency_p99_s(self, key: str) -> float | None:
        """Current p99-ish estimate for ``key`` (None before any
        observation) — the hedged-request delay input."""
        with self._lock:
            e = self._latency.get(key)
            return e.p99_s if e is not None and e.count else None

    def latencies(self) -> dict[str, dict]:
        with self._lock:
            return {k: e.to_json_object()
                    for k, e in self._latency.items()}
