"""Fault-injecting TCP proxy: the network you actually deploy on.

Sits between a client and an upstream (web server or socket broker)
and injects the faults the resilience layer claims to survive, so
tests and `bench.py 8_faulty_network` prove recovery END TO END over
real sockets rather than monkeypatched stubs:

    proxy = ChaosProxy(host, port, reset_rate=0.01, jitter_s=0.010,
                       seed=7).start()
    ds = RemoteDataStore(proxy.host, proxy.port)   # faults in the path

Faults (all runtime-mutable attributes):

- ``reset_rate``: probability a connection is killed with a hard RST
  (SO_LINGER 0) after a random number of forwarded bytes — covers
  connect-phase, mid-request and mid-response cuts;
- ``delay_s`` + ``jitter_s``: fixed + uniform-random added latency per
  forwarded chunk (WAN jitter);
- ``partial_write_rate``: probability a chunk is truncated mid-write
  and the connection reset (torn frame on the wire);
- ``bandwidth_bytes_s``: crude rate limit (sleep per chunk);
- ``slow_rate`` + ``slow_s``: probability a CONNECTION is a straggler —
  its first response chunk stalls ``slow_s`` before delivery. This is
  the tail-at-scale profile hedged requests exist for: most calls are
  fast, a random few hit a slow endpoint (GC pause, contended replica),
  and only a speculative second attempt rescues the p99;
- ``blackhole``: accept, read, forward NOTHING (client sees a silent
  peer and must rely on its own timeout);
- ``drop_all()``: cut every live connection at once (partition /
  upstream crash), independent of the probabilistic faults.

Deterministic under ``seed``; ``stats`` counts connections and each
injected fault kind.
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time

__all__ = ["ChaosProxy"]

_CHUNK = 65536


def _hard_reset(sock):
    """Close with RST (not FIN): the peer sees ECONNRESET."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 reset_rate: float = 0.0, delay_s: float = 0.0,
                 jitter_s: float = 0.0, partial_write_rate: float = 0.0,
                 bandwidth_bytes_s: float | None = None,
                 blackhole: bool = False, seed: int | None = None,
                 slow_rate: float = 0.0, slow_s: float = 0.0):
        self.upstream = (upstream_host, upstream_port)
        self.reset_rate = reset_rate
        self.delay_s = delay_s
        self.jitter_s = jitter_s
        self.partial_write_rate = partial_write_rate
        self.bandwidth_bytes_s = bandwidth_bytes_s
        self.blackhole = blackhole
        self.slow_rate = slow_rate
        self.slow_s = slow_s
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self.stats = {"connections": 0, "resets": 0, "partial_writes": 0,
                      "delayed_chunks": 0, "blackholed": 0, "dropped": 0,
                      "slowed": 0}
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.host, self.port = self._listener.getsockname()[:2]
        self._live: set[socket.socket] = set()
        self._live_lock = threading.Lock()
        self._running = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)

    def _rand(self) -> float:
        with self._rng_lock:
            return self._rng.random()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ChaosProxy":
        self._running = True
        self._thread.start()
        return self

    def stop(self):
        self._running = False
        try:
            self._listener.close()
        except OSError:
            pass
        self.drop_all()

    def drop_all(self):
        """Hard-reset every live connection (simulated partition)."""
        with self._live_lock:
            socks, self._live = list(self._live), set()
        for s in socks:
            self.stats["dropped"] += 1
            _hard_reset(s)

    # -- data path ---------------------------------------------------------

    def _accept_loop(self):
        while self._running:
            try:
                client, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            self.stats["connections"] += 1
            threading.Thread(target=self._serve, args=(client,),
                             daemon=True).start()

    def _serve(self, client: socket.socket):
        if self.blackhole:
            # hold the connection, consume and discard: the client
            # must save itself with its own timeout
            self.stats["blackholed"] += 1
            self._track(client)
            try:
                while client.recv(_CHUNK):
                    pass
            except OSError:
                pass
            finally:
                self._untrack(client)
            return
        try:
            up = socket.create_connection(self.upstream, timeout=10.0)
        except OSError:
            _hard_reset(client)
            return
        # per-connection reset point: a byte count the combined
        # traffic crosses (uniform in a small window so cuts land in
        # connects, requests and responses alike)
        reset_after = None
        if self.reset_rate > 0 and self._rand() < self.reset_rate:
            reset_after = int(self._rand() * 4096)
        # straggler profile: decided per CONNECTION so a hedged second
        # attempt (a fresh connection) rolls the dice again — mostly
        # landing on a fast path, which is the whole bet of hedging
        slow = 0.0
        if self.slow_rate > 0 and self._rand() < self.slow_rate:
            slow = self.slow_s
            self.stats["slowed"] += 1
        ctl = {"forwarded": 0, "reset_after": reset_after,
               "done": threading.Event()}
        self._track(client)
        self._track(up)
        t1 = threading.Thread(target=self._pump, args=(client, up, ctl),
                              daemon=True)
        t2 = threading.Thread(target=self._pump, args=(up, client, ctl),
                              kwargs={"stall_s": slow}, daemon=True)
        t1.start()
        t2.start()
        ctl["done"].wait()
        for s in (client, up):
            self._untrack(s)
            try:
                s.close()
            except OSError:
                pass

    def _pump(self, src: socket.socket, dst: socket.socket, ctl: dict,
              stall_s: float = 0.0):
        try:
            while True:
                try:
                    data = src.recv(_CHUNK)
                except OSError:
                    break
                if not data:
                    break
                if stall_s > 0:
                    # straggler: one stall before the first response
                    # chunk (total added latency = stall_s, however
                    # many chunks follow)
                    time.sleep(stall_s)
                    stall_s = 0.0
                if self.delay_s or self.jitter_s:
                    self.stats["delayed_chunks"] += 1
                    time.sleep(self.delay_s + self._rand() * self.jitter_s)
                if self.bandwidth_bytes_s:
                    time.sleep(len(data) / self.bandwidth_bytes_s)
                if self.partial_write_rate > 0 \
                        and self._rand() < self.partial_write_rate \
                        and len(data) > 1:
                    self.stats["partial_writes"] += 1
                    try:
                        dst.sendall(data[:len(data) // 2])
                    except OSError:
                        pass
                    self._reset_pair(src, dst)
                    break
                try:
                    dst.sendall(data)
                except OSError:
                    break
                ctl["forwarded"] += len(data)
                ra = ctl["reset_after"]
                if ra is not None and ctl["forwarded"] >= ra:
                    self._reset_pair(src, dst)
                    break
        finally:
            ctl["done"].set()

    def _reset_pair(self, a: socket.socket, b: socket.socket):
        self.stats["resets"] += 1
        for s in (a, b):
            self._untrack(s)
            _hard_reset(s)

    def _track(self, s: socket.socket):
        with self._live_lock:
            self._live.add(s)

    def _untrack(self, s: socket.socket):
        with self._live_lock:
            self._live.discard(s)
