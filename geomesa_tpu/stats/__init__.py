"""Statistical sketches + estimation (geomesa-utils stats package and
geomesa-index-api stats, SURVEY.md 2.1)."""

from .sketches import (CountStat, DescriptiveStats, EnumerationStat,
                       Frequency, GroupBy, Histogram, MinMax, SeqStat,
                       Stat, TopK, Z3Frequency, Z3Histogram, parse_stat)
from .estimator import DataStoreStats, StatsEstimator
from .serialize import deserialize_stat, serialize_stat

__all__ = ["CountStat", "DescriptiveStats", "EnumerationStat", "Frequency",
           "GroupBy", "Histogram", "MinMax", "SeqStat", "Stat", "TopK",
           "Z3Frequency", "Z3Histogram", "parse_stat", "DataStoreStats",
           "StatsEstimator", "serialize_stat", "deserialize_stat"]
