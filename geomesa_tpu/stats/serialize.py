"""Binary stat-sketch serialization (StatSerializer analog).

The reference moves sketches between processes in binary form — the
server-side StatsScan returns serialized partial sketches that merge
client-side, and the stats table persists them
(geomesa-utils/.../stats/StatSerializer.scala). Here every sketch
serializes to a compact self-describing payload:

    [magic u16][version u8][json header length u32][json header]
    [array payloads, 8-byte aligned]

The header is a restricted JSON tree of the sketch's state — scalars
inline, numpy arrays as {"__nd__": i} references into the payload
section, nested Stats as {"__stat__": class, "state": tree}. No pickle
anywhere: payloads are dtype/shape-tagged raw buffers, so the format is
stable across python versions and safe to read from untrusted peers
(the reason the live bus can carry these).
"""

from __future__ import annotations

import json
import struct
from typing import Any

import numpy as np

from . import sketches as _sk
from .sketches import Stat

__all__ = ["serialize_stat", "deserialize_stat"]

_MAGIC = 0x5354  # 'ST'
_VERSION = 1

# the closed set of sketch classes the wire format may instantiate
_CLASSES = {
    name: getattr(_sk, name) for name in (
        "CountStat", "MinMax", "EnumerationStat", "TopK", "Histogram",
        "Frequency", "DescriptiveStats", "GroupBy", "SeqStat",
        "Z3Histogram", "Z3Frequency")
    if hasattr(_sk, name)
}


def _encode(v, arrays: list) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        if v.dtype == object:
            # object arrays hold strings (vocab etc.): store as a list
            return {"__strs__": [None if x is None else str(x)
                                 for x in v.tolist()]}
        arrays.append(np.ascontiguousarray(v))
        return {"__nd__": len(arrays) - 1}
    if isinstance(v, Stat):
        return {"__stat__": type(v).__name__,
                "state": _encode(dict(v.__dict__), arrays)}
    if isinstance(v, dict):
        return {"__dict__": [[_encode(k, arrays), _encode(x, arrays)]
                             for k, x in v.items()]}
    if isinstance(v, (list, tuple)):
        return {"__list__": [_encode(x, arrays) for x in v],
                "tuple": isinstance(v, tuple)}
    if isinstance(v, set):
        return {"__set__": [_encode(x, arrays) for x in sorted(
            v, key=repr)]}
    # TimePeriod and other simple enums stringify; deserialization
    # re-parses through the class constructor path below
    if hasattr(v, "name") and hasattr(type(v), "__members__"):
        return {"__enum__": type(v).__name__, "value": v.name}
    raise TypeError(f"unserializable sketch field: {type(v).__name__}")


def _decode(v, arrays: list) -> Any:
    if not isinstance(v, dict):
        return v
    if "__nd__" in v:
        return arrays[v["__nd__"]]
    if "__strs__" in v:
        return np.array(v["__strs__"], dtype=object)
    if "__stat__" in v:
        cls = _CLASSES.get(v["__stat__"])
        if cls is None:
            raise ValueError(f"unknown sketch class {v['__stat__']!r}")
        out = cls.__new__(cls)
        out.__dict__.update(_decode(v["state"], arrays))
        return out
    if "__dict__" in v:
        return {_decode(k, arrays): _decode(x, arrays)
                for k, x in v["__dict__"]}
    if "__list__" in v:
        items = [_decode(x, arrays) for x in v["__list__"]]
        return tuple(items) if v.get("tuple") else items
    if "__set__" in v:
        return {_decode(x, arrays) for x in v["__set__"]}
    if "__enum__" in v:
        from ..curves.timebin import TimePeriod
        if v["__enum__"] == "TimePeriod":
            return TimePeriod.parse(v["value"])
        raise ValueError(f"unknown enum {v['__enum__']!r}")
    return v


def serialize_stat(stat: Stat) -> bytes:
    """Sketch -> stable binary payload (no pickle)."""
    arrays: list[np.ndarray] = []
    tree = _encode(stat, arrays)
    meta = {"tree": tree,
            "arrays": [{"dtype": a.dtype.str, "shape": list(a.shape)}
                       for a in arrays]}
    header = json.dumps(meta, separators=(",", ":")).encode()
    parts = [struct.pack("<HBxI", _MAGIC, _VERSION, len(header)), header]
    off = sum(len(p) for p in parts)
    for a in arrays:
        pad = (-off) % 8
        parts.append(b"\x00" * pad)
        off += pad
        buf = a.tobytes()
        parts.append(buf)
        off += len(buf)
    return b"".join(parts)


def deserialize_stat(data: bytes) -> Stat:
    """Binary payload -> sketch. EVERY malformed/crafted input raises
    ValueError — the single error the bus/lambda consumers catch (the
    untrusted-peer contract the module docstring promises)."""
    try:
        if len(data) < 8:
            raise ValueError("truncated sketch payload")
        magic, version, hlen = struct.unpack_from("<HBxI", data, 0)
        if magic != _MAGIC:
            raise ValueError("not a serialized sketch")
        if version != _VERSION:
            raise ValueError(f"unsupported sketch version {version}")
        off = 8 + hlen
        meta = json.loads(data[8:off].decode())
        arrays: list[np.ndarray] = []
        for spec in meta["arrays"]:
            off += (-off) % 8
            dt = np.dtype(spec["dtype"])
            n = int(np.prod(spec["shape"])) if spec["shape"] else 1
            nbytes = dt.itemsize * n
            arr = np.frombuffer(data[off:off + nbytes], dtype=dt) \
                .reshape(spec["shape"]).copy()
            arrays.append(arr)
            off += nbytes
        out = _decode(meta["tree"], arrays)
        if not isinstance(out, Stat):
            raise ValueError("payload did not decode to a sketch")
        return out
    except ValueError:
        raise
    except Exception as e:
        raise ValueError(f"malformed sketch payload: {e}") from e
