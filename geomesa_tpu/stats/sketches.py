"""Statistical sketch algebra (geomesa-utils/.../stats/Stat.scala:29).

Same algebra as the reference — ``observe`` / ``merge (+)`` / ``to_json``
/ ``serialize`` — but *columnar*: observe() consumes whole FeatureBatch
columns as vectorized numpy ops (the per-SimpleFeature observe loop of
the reference becomes array arithmetic; on-device versions of the hot
reductions live in scan/aggregations).

Sketches: Count, MinMax, Enumeration, TopK, Frequency (count-min),
Histogram (BinnedArray), DescriptiveStats (moments), GroupBy, SeqStat,
Z3Histogram. The DSL string constructors (``Count()``,
``MinMax(attr)``, ``Histogram(attr,20,lo,hi)``, semicolon-joined)
match the reference's StatParser grammar.
"""

from __future__ import annotations

import json
import re
from typing import Any

import numpy as np

from ..curves import TimePeriod, timebin, z3_encode, z3sfc
from ..features.batch import (DateColumn, FeatureBatch, NumericColumn,
                              PointColumn, StringColumn)

__all__ = ["Stat", "CountStat", "MinMax", "EnumerationStat", "TopK",
           "Frequency", "Histogram", "DescriptiveStats", "GroupBy",
           "SeqStat", "Z3Histogram", "Z3Frequency", "parse_stat"]


def _col_values(batch: FeatureBatch, attr: str):
    """Column -> (values array, valid mask) in sketch space."""
    col = batch.col(attr)
    if isinstance(col, NumericColumn):
        return col.values, col.valid
    if isinstance(col, DateColumn):
        return col.millis, col.valid
    if isinstance(col, StringColumn):
        vals = np.where(col.codes >= 0, col.vocab[np.maximum(col.codes, 0)], None)
        return vals, col.codes >= 0
    if isinstance(col, PointColumn):
        return (col.x, col.y), col.valid
    raise TypeError(f"unsupported stat column: {type(col).__name__}")


class Stat:
    """Base sketch."""

    def observe(self, batch: FeatureBatch) -> None:
        raise NotImplementedError

    def merge(self, other: "Stat") -> "Stat":
        """In-place combine (the reference's +=); returns self."""
        raise NotImplementedError

    def __iadd__(self, other: "Stat") -> "Stat":
        return self.merge(other)

    def __add__(self, other: "Stat") -> "Stat":
        import copy
        out = copy.deepcopy(self)
        out.merge(other)
        return out

    @property
    def is_empty(self) -> bool:
        raise NotImplementedError

    def to_json_object(self) -> Any:
        raise NotImplementedError

    def to_json(self) -> str:
        return json.dumps(self.to_json_object())

    def clear(self) -> None:
        raise NotImplementedError


class CountStat(Stat):
    def __init__(self):
        self.count = 0

    def observe(self, batch: FeatureBatch) -> None:
        self.count += batch.n

    def merge(self, other: "CountStat") -> "CountStat":
        self.count += other.count
        return self

    @property
    def is_empty(self) -> bool:
        return self.count == 0

    def to_json_object(self):
        return {"count": self.count}


class MinMax(Stat):
    """Min/max bounds + HLL-style cardinality estimate (simplified to a
    hash-set-sampling estimator; the reference uses HyperLogLog)."""

    def __init__(self, attribute: str):
        self.attribute = attribute
        self.min: Any = None
        self.max: Any = None
        self._hashes: set[int] = set()

    def observe(self, batch: FeatureBatch) -> None:
        vals, valid = _col_values(batch, self.attribute)
        if isinstance(vals, tuple):  # geometry: track envelope
            x, y = vals
            x, y = x[valid], y[valid]
            if len(x) == 0:
                return
            lo = (float(x.min()), float(y.min()))
            hi = (float(x.max()), float(y.max()))
            self.min = lo if self.min is None else (
                min(self.min[0], lo[0]), min(self.min[1], lo[1]))
            self.max = hi if self.max is None else (
                max(self.max[0], hi[0]), max(self.max[1], hi[1]))
            return
        vals = vals[valid]
        if len(vals) == 0:
            return
        if vals.dtype == object:
            vmin, vmax = min(vals), max(vals)
        else:
            vmin, vmax = vals.min(), vals.max()
            vmin = vmin.item()
            vmax = vmax.item()
        self.min = vmin if self.min is None else min(self.min, vmin)
        self.max = vmax if self.max is None else max(self.max, vmax)
        # bounded-size distinct estimate
        if len(self._hashes) < 10_000:
            self._hashes.update(hash(v) for v in
                                (vals[:: max(1, len(vals) // 1000)]).tolist())

    def merge(self, other: "MinMax") -> "MinMax":
        for v in (other.min,):
            if v is not None:
                self.min = v if self.min is None else min(self.min, v)
        for v in (other.max,):
            if v is not None:
                self.max = v if self.max is None else max(self.max, v)
        self._hashes |= other._hashes
        return self

    @property
    def cardinality(self) -> int:
        return len(self._hashes)

    @property
    def is_empty(self) -> bool:
        return self.min is None

    def to_json_object(self):
        return {"min": self.min, "max": self.max,
                "cardinality": self.cardinality}


class EnumerationStat(Stat):
    """Exact value counts (utils/stats/EnumerationStat)."""

    def __init__(self, attribute: str):
        self.attribute = attribute
        self.counts: dict[Any, int] = {}

    def observe(self, batch: FeatureBatch) -> None:
        col = batch.col(self.attribute)
        if isinstance(col, StringColumn):
            # vectorized: bincount over dictionary codes
            valid = col.codes >= 0
            bc = np.bincount(col.codes[valid], minlength=len(col.vocab))
            for code in np.flatnonzero(bc):
                v = str(col.vocab[code])
                self.counts[v] = self.counts.get(v, 0) + int(bc[code])
            return
        vals, valid = _col_values(batch, self.attribute)
        uniq, cnt = np.unique(np.asarray(vals)[valid], return_counts=True)
        for v, c in zip(uniq.tolist(), cnt.tolist()):
            self.counts[v] = self.counts.get(v, 0) + int(c)

    def merge(self, other: "EnumerationStat") -> "EnumerationStat":
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        return self

    @property
    def is_empty(self) -> bool:
        return not self.counts

    def to_json_object(self):
        return {str(k): v for k, v in sorted(
            self.counts.items(), key=lambda kv: (-kv[1], str(kv[0])))}


class TopK(Stat):
    """Top-k heavy hitters (reference wraps clearspring StreamSummary;
    here a capped exact counter with eviction — same output contract)."""

    CAPACITY = 10 * 128  # matches StreamSummary default-ish working size

    def __init__(self, attribute: str, k: int = 10):
        self.attribute = attribute
        self.k = k
        self.counts: dict[Any, int] = {}

    def observe(self, batch: FeatureBatch) -> None:
        en = EnumerationStat(self.attribute)
        en.observe(batch)
        for v, c in en.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        self._evict()

    def _evict(self):
        if len(self.counts) > self.CAPACITY:
            keep = sorted(self.counts.items(), key=lambda kv: -kv[1])
            self.counts = dict(keep[:self.CAPACITY])

    def merge(self, other: "TopK") -> "TopK":
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c
        self._evict()
        return self

    def topk(self) -> list[tuple[Any, int]]:
        return sorted(self.counts.items(), key=lambda kv: (-kv[1], str(kv[0])))[:self.k]

    @property
    def is_empty(self) -> bool:
        return not self.counts

    def to_json_object(self):
        return [{"value": v, "count": c} for v, c in self.topk()]


class Frequency(Stat):
    """Count-min sketch (utils/stats/Frequency), vectorized: values hash
    through d=4 row hashes onto w=2^precision buckets."""

    D = 4

    def __init__(self, attribute: str, precision: int = 12):
        self.attribute = attribute
        self.precision = precision
        self.width = 1 << precision
        self.table = np.zeros((self.D, self.width), dtype=np.int64)
        self.total = 0

    _SEEDS = np.array([0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F],
                      dtype=np.uint64)

    def _hash(self, vals: np.ndarray) -> np.ndarray:
        """(D, n) bucket indices via multiply-shift hashing.

        Numeric values hash from their exact 64-bit patterns (floats via
        bit view, not truncation) so observe() and count() agree for any
        value type."""
        if vals.dtype == object:
            h = np.array([self._scalar_bits(v) for v in vals], dtype=np.uint64)
        elif vals.dtype.kind == "f":
            h = vals.astype(np.float64).view(np.uint64)
        else:
            h = vals.astype(np.int64).view(np.uint64)
        out = np.empty((self.D, len(h)), dtype=np.int64)
        for d in range(self.D):
            mixed = (h * self._SEEDS[d])
            mixed ^= mixed >> np.uint64(33)
            out[d] = (mixed % np.uint64(self.width)).astype(np.int64)
        return out

    def observe(self, batch: FeatureBatch, weight: int = 1) -> None:
        vals, valid = _col_values(batch, self.attribute)
        self.observe_values(np.asarray(vals)[valid], weight)

    def observe_values(self, vals: np.ndarray, weight: int = 1) -> None:
        """Value-level update (also the hook for key-derived sketches
        like Z3Frequency). ``weight`` scales each observation — the
        write path observes strided subsamples of huge batches and
        passes the stride so differently-sampled batches stay
        comparable (same contract as Z3Histogram.observe)."""
        if len(vals) == 0:
            return
        idx = self._hash(vals)
        for d in range(self.D):
            np.add.at(self.table[d], idx[d], int(weight))
        self.total += len(vals) * int(weight)

    @staticmethod
    def _scalar_bits(v) -> int:
        if isinstance(v, (bool, np.bool_)):
            return int(v)
        if isinstance(v, (int, np.integer)):
            return int(np.int64(v).view(np.uint64))
        if isinstance(v, (float, np.floating)):
            return int(np.float64(v).view(np.uint64))
        return hash(v) & 0xFFFFFFFFFFFFFFFF

    def count(self, value) -> int:
        idx = self._hash(np.array([value], dtype=object))
        return int(min(self.table[d, idx[d, 0]] for d in range(self.D)))

    def count_value(self, value: np.int64) -> int:
        """count() for an exact-typed (non-object) scalar key."""
        idx = self._hash(np.array([value], dtype=np.int64))
        return int(min(self.table[d, idx[d, 0]] for d in range(self.D)))

    def merge(self, other: "Frequency") -> "Frequency":
        self.table += other.table
        self.total += other.total
        return self

    @property
    def is_empty(self) -> bool:
        return self.total == 0

    def to_json_object(self):
        return {"precision": self.precision, "total": self.total}


class Histogram(Stat):
    """Fixed-width binned histogram over [min, max] (utils/stats/
    Histogram + BinnedArray): values below/above clamp to the end bins."""

    def __init__(self, attribute: str, bins: int, lo, hi):
        self.attribute = attribute
        self.bins = bins
        self.lo = lo
        self.hi = hi
        self.counts = np.zeros(bins, dtype=np.int64)

    def _to_f64(self, v) -> float:
        if isinstance(v, str):
            try:
                return float(np.datetime64(v.rstrip("Z"), "ms").astype(np.int64))
            except ValueError:
                raise TypeError(f"non-numeric histogram bound: {v!r}")
        return float(v)

    def observe(self, batch: FeatureBatch) -> None:
        vals, valid = _col_values(batch, self.attribute)
        if isinstance(vals, tuple):
            raise TypeError("use Z3Histogram for geometries")
        vals = np.asarray(vals[valid], dtype=np.float64)
        if len(vals) == 0:
            return
        lo, hi = self._to_f64(self.lo), self._to_f64(self.hi)
        width = (hi - lo) / self.bins if hi > lo else 1.0
        idx = np.clip(((vals - lo) / width).astype(np.int64), 0, self.bins - 1)
        self.counts += np.bincount(idx, minlength=self.bins)

    def bin_bounds(self, i: int) -> tuple[float, float]:
        lo, hi = self._to_f64(self.lo), self._to_f64(self.hi)
        width = (hi - lo) / self.bins
        return lo + i * width, lo + (i + 1) * width

    def merge(self, other: "Histogram") -> "Histogram":
        if (other.bins != self.bins or other.lo != self.lo
                or other.hi != self.hi):
            raise ValueError("histogram shape mismatch")
        self.counts += other.counts
        return self

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    @property
    def is_empty(self) -> bool:
        return self.total == 0

    def to_json_object(self):
        return {"lower-bound": self.lo, "upper-bound": self.hi,
                "bins": self.counts.tolist()}


class DescriptiveStats(Stat):
    """Streaming moments: count/min/max/mean/variance/skew/kurtosis
    (utils/stats/DescriptiveStats), merged with the parallel-moments
    formulas."""

    def __init__(self, attribute: str):
        self.attribute = attribute
        self.n = 0
        self.min = np.inf
        self.max = -np.inf
        self.m1 = 0.0
        self.m2 = 0.0
        self.m3 = 0.0
        self.m4 = 0.0

    def observe(self, batch: FeatureBatch) -> None:
        vals, valid = _col_values(batch, self.attribute)
        v = np.asarray(vals[valid], dtype=np.float64)
        if len(v) == 0:
            return
        other = DescriptiveStats(self.attribute)
        other.n = len(v)
        other.min = float(v.min())
        other.max = float(v.max())
        other.m1 = float(v.mean())
        d = v - other.m1
        other.m2 = float((d ** 2).sum())
        other.m3 = float((d ** 3).sum())
        other.m4 = float((d ** 4).sum())
        self.merge(other)

    def merge(self, o: "DescriptiveStats") -> "DescriptiveStats":
        if o.n == 0:
            return self
        if self.n == 0:
            self.__dict__.update({k: getattr(o, k) for k in
                                  ("n", "min", "max", "m1", "m2", "m3", "m4")})
            return self
        n1, n2 = self.n, o.n
        n = n1 + n2
        delta = o.m1 - self.m1
        d_n = delta / n
        d2 = delta * d_n
        m1 = self.m1 + n2 * d_n
        m2 = self.m2 + o.m2 + d2 * n1 * n2
        m3 = (self.m3 + o.m3 + d2 * d_n * n1 * n2 * (n1 - n2)
              + 3.0 * d_n * (n1 * o.m2 - n2 * self.m2))
        m4 = (self.m4 + o.m4
              + d2 * d_n * d_n * n1 * n2 * (n1 * n1 - n1 * n2 + n2 * n2)
              + 6.0 * d_n * d_n * (n1 * n1 * o.m2 + n2 * n2 * self.m2)
              + 4.0 * d_n * (n1 * o.m3 - n2 * self.m3))
        self.n, self.m1, self.m2, self.m3, self.m4 = n, m1, m2, m3, m4
        self.min = min(self.min, o.min)
        self.max = max(self.max, o.max)
        return self

    @property
    def mean(self) -> float:
        return self.m1

    @property
    def variance(self) -> float:
        return self.m2 / (self.n - 1) if self.n > 1 else 0.0

    @property
    def stddev(self) -> float:
        return float(np.sqrt(self.variance))

    @property
    def skewness(self) -> float:
        if self.n < 2 or self.m2 == 0:
            return 0.0
        return float(np.sqrt(self.n) * self.m3 / self.m2 ** 1.5)

    @property
    def kurtosis(self) -> float:
        if self.m2 == 0:
            return 0.0
        return float(self.n * self.m4 / (self.m2 * self.m2) - 3.0)

    @property
    def is_empty(self) -> bool:
        return self.n == 0

    def to_json_object(self):
        if self.is_empty:
            return {"count": 0}
        return {"count": self.n, "minimum": self.min, "maximum": self.max,
                "mean": self.mean, "stddev": self.stddev,
                "skewness": self.skewness, "kurtosis": self.kurtosis}


class GroupBy(Stat):
    """Group a sub-stat by the values of an attribute (utils/stats/GroupBy)."""

    def __init__(self, attribute: str, sub_spec: str):
        self.attribute = attribute
        self.sub_spec = sub_spec
        self.groups: dict[Any, Stat] = {}

    def observe(self, batch: FeatureBatch) -> None:
        vals, valid = _col_values(batch, self.attribute)
        vals = np.asarray(vals)
        uniq = np.unique(vals[valid].astype(str) if vals.dtype == object
                         else vals[valid])
        for v in uniq.tolist():
            sel = np.flatnonzero(valid & (vals == v))
            sub = batch.take(sel)
            if v not in self.groups:
                self.groups[v] = parse_stat(self.sub_spec)
            self.groups[v].observe(sub)

    def merge(self, other: "GroupBy") -> "GroupBy":
        import copy
        for v, s in other.groups.items():
            if v in self.groups:
                self.groups[v].merge(s)
            else:
                # copy: adopting by reference would alias future observes
                self.groups[v] = copy.deepcopy(s)
        return self

    @property
    def is_empty(self) -> bool:
        return not self.groups

    def to_json_object(self):
        return [{str(k): v.to_json_object()} for k, v in
                sorted(self.groups.items(), key=lambda kv: str(kv[0]))]


class SeqStat(Stat):
    """Multiple stats observed together (semicolon-joined specs)."""

    def __init__(self, stats: list[Stat]):
        self.stats = stats

    def observe(self, batch: FeatureBatch) -> None:
        for s in self.stats:
            s.observe(batch)

    def merge(self, other: "SeqStat") -> "SeqStat":
        for a, b in zip(self.stats, other.stats):
            a.merge(b)
        return self

    @property
    def is_empty(self) -> bool:
        return all(s.is_empty for s in self.stats)

    def to_json_object(self):
        return [s.to_json_object() for s in self.stats]


class Z3Histogram(Stat):
    """Counts binned by (time bin, coarse z3 cell)
    (utils/stats/Z3Histogram.scala:33) — the sketch behind the
    stats-based spatio-temporal cost estimator."""

    def __init__(self, geom: str, dtg: str,
                 period: TimePeriod | str = TimePeriod.WEEK,
                 length: int = 1024):
        self.geom = geom
        self.dtg = dtg
        self.period = TimePeriod.parse(period)
        self.length = length
        self.bins: dict[int, np.ndarray] = {}
        # z bits kept: log2(length) of the leading z3 bits
        self._shift = 63 - int(np.log2(length))
        # incrementally-maintained aggregates: the cost estimator reads
        # these per query, so they must never require an O(bins) walk
        self.total = 0
        self.bin_mass: dict[int, int] = {}
        self.cell_mass = np.zeros(length, dtype=np.int64)

    def observe(self, batch: FeatureBatch, weight: int = 1) -> None:
        """``weight`` scales this batch's counts — the write path
        observes a strided subsample of huge batches and passes the
        stride so masses stay comparable across differently-sampled
        batches."""
        gcol = batch.col(self.geom)
        if not isinstance(gcol, PointColumn):
            raise TypeError("Z3Histogram requires a point geometry")
        ms = batch.col(self.dtg).millis
        valid = gcol.valid & batch.col(self.dtg).valid
        if not valid.any():
            return
        x, y, ms = gcol.x[valid], gcol.y[valid], ms[valid]
        tbins, offs = timebin.to_binned(ms, self.period, lenient=True)
        sfc = z3sfc(self.period)
        z = sfc.index(x, y, np.minimum(offs, int(sfc.time.max)), lenient=True)
        cell = (z >> np.uint64(self._shift)).astype(np.int64)
        # one fused bincount over (time bin, cell) composite keys. The
        # grid is sized by the DISTINCT bins present (np.unique remap),
        # not by the max absolute bin — keying by tbins.max() made a
        # single clamped far-future timestamp (bin 32767) allocate a
        # ~270MB transient regardless of batch size
        ubins, inv = np.unique(tbins, return_inverse=True)
        key = inv.astype(np.int64) * self.length + cell
        grid = np.bincount(
            key, minlength=len(ubins) * self.length
        ).reshape(len(ubins), self.length)
        if weight != 1:
            grid = grid * int(weight)
        for j, b in enumerate(ubins.tolist()):
            arr = self.bins.setdefault(int(b),
                                       np.zeros(self.length, dtype=np.int64))
            arr += grid[j]
            m = int(grid[j].sum())
            self.bin_mass[int(b)] = self.bin_mass.get(int(b), 0) + m
            self.total += m
        self.cell_mass += grid.sum(axis=0)

    def count(self, time_bin: int, cell: int) -> int:
        arr = self.bins.get(time_bin)
        return int(arr[cell]) if arr is not None else 0

    def merge(self, other: "Z3Histogram") -> "Z3Histogram":
        for b, arr in other.bins.items():
            if b in self.bins:
                self.bins[b] += arr
            else:
                self.bins[b] = arr.copy()
            self.bin_mass[b] = self.bin_mass.get(b, 0) + int(arr.sum())
        self.total += other.total
        self.cell_mass += other.cell_mass
        return self

    @property
    def is_empty(self) -> bool:
        return not self.bins

    def to_json_object(self):
        return {str(b): int(a.sum()) for b, a in sorted(self.bins.items())}


class Z3Frequency(Stat):
    """Count-min sketch over (time bin, coarse z3 cell) keys
    (utils/stats/Z3Frequency.scala) — approximate per-cell counts with
    bounded memory where Z3Histogram keeps exact per-bin arrays."""

    def __init__(self, geom: str, dtg: str,
                 period: TimePeriod | str = TimePeriod.WEEK,
                 precision: int = 12):
        self.geom = geom
        self.dtg = dtg
        self.period = TimePeriod.parse(period)
        self.precision = precision
        self._freq = Frequency("__z3__", precision)
        # coarse cell = leading bits of z3 (same resolution rule as
        # Z3Histogram's 1024 cells)
        self._shift = 63 - 10

    def _keys(self, batch: FeatureBatch) -> np.ndarray:
        gcol = batch.col(self.geom)
        if not isinstance(gcol, PointColumn):
            raise TypeError("Z3Frequency requires a point geometry")
        ms = batch.col(self.dtg).millis
        valid = gcol.valid & batch.col(self.dtg).valid
        x, y, ms = gcol.x[valid], gcol.y[valid], ms[valid]
        tbins, offs = timebin.to_binned(ms, self.period, lenient=True)
        sfc = z3sfc(self.period)
        z = sfc.index(x, y, np.minimum(offs, int(sfc.time.max)),
                      lenient=True)
        cell = (z >> np.uint64(self._shift)).astype(np.int64)
        # bin lives in the LOW 16 bits: the multiply-shift hash folds
        # high bits only once, so keys differing near the top would
        # collide into identical buckets
        return (cell << np.int64(16)) | (tbins.astype(np.int64) & 0xFFFF)

    def observe(self, batch: FeatureBatch) -> None:
        self._freq.observe_values(self._keys(batch))

    def count(self, time_bin: int, cell: int) -> int:
        key = np.int64((int(cell) << 16) | (int(time_bin) & 0xFFFF))
        return self._freq.count_value(key)

    def merge(self, other: "Z3Frequency") -> "Z3Frequency":
        if (other.period != self.period
                or other.precision != self.precision):
            raise ValueError(
                f"cannot merge Z3Frequency({other.period},"
                f"{other.precision}) into ({self.period},{self.precision})"
                " - different keyspaces")
        self._freq.merge(other._freq)
        return self

    @property
    def is_empty(self) -> bool:
        return self._freq.is_empty

    def to_json_object(self):
        return {"precision": self.precision, "total": self._freq.total}


# -- DSL parser ------------------------------------------------------------

_STAT_RE = re.compile(r"^\s*(\w+)\((.*)\)\s*$")


def _split_args(s: str) -> list[str]:
    out, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return [a.strip().strip("'\"") for a in out]


def parse_stat(spec: str) -> Stat:
    """Parse a reference-style stat spec string, e.g.
    ``"MinMax(foo);Histogram(bar,20,0,100)"`` (StatParser analog)."""
    parts = [p for p in spec.split(";") if p.strip()]
    if len(parts) > 1:
        return SeqStat([parse_stat(p) for p in parts])
    m = _STAT_RE.match(parts[0])
    if not m:
        raise ValueError(f"cannot parse stat spec: {spec!r}")
    name, args = m.group(1), _split_args(m.group(2))
    if name == "Count":
        return CountStat()
    if name == "MinMax":
        return MinMax(args[0])
    if name == "Enumeration":
        return EnumerationStat(args[0])
    if name == "TopK":
        return TopK(args[0], int(args[1]) if len(args) > 1 else 10)
    if name == "Frequency":
        precision = int(args[-1]) if len(args) > 1 else 12
        return Frequency(args[0], precision)
    if name == "Histogram":
        lo, hi = args[2], args[3]
        for conv in (int, float):
            try:
                lo, hi = conv(args[2]), conv(args[3])
                break
            except ValueError:
                continue
        return Histogram(args[0], int(args[1]), lo, hi)
    if name == "DescriptiveStats":
        return DescriptiveStats(args[0])
    if name == "GroupBy":
        return GroupBy(args[0], ",".join(args[1:]))
    if name == "Z3Histogram":
        period = args[2] if len(args) > 2 else "week"
        length = int(args[3]) if len(args) > 3 else 1024
        return Z3Histogram(args[0], args[1], period, length)
    if name == "Z3Frequency":
        period = args[2] if len(args) > 2 else "week"
        precision = int(args[3]) if len(args) > 3 else 12
        return Z3Frequency(args[0], args[1], period, precision)
    raise ValueError(f"unknown stat: {name}")
