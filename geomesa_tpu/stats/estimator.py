"""Stats-based selectivity estimation for the cost decider.

Analog of StatsBasedEstimator (index/stats/StatsBasedEstimator.scala:27):
estimate the number of features matching a filter from maintained
sketches — Count for totals, Z3Histogram for spatio-temporal
selectivity, Histogram/Enumeration for attribute selectivity.
"""

from __future__ import annotations

import numpy as np

from ..curves import timebin, z3sfc
from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import extract_geometries, extract_intervals
from .sketches import (CountStat, Frequency, Histogram, SeqStat, Stat,
                       Z3Histogram)

__all__ = ["StatsEstimator", "DataStoreStats"]


class StatsEstimator:
    """Wraps maintained sketches; answers estimate_count(filter)."""

    def __init__(self, sft: SimpleFeatureType):
        self.sft = sft
        self.count = CountStat()
        self.z3: Z3Histogram | None = None
        if sft.is_points and sft.dtg_field is not None:
            self.z3 = Z3Histogram(sft.geom_field, sft.dtg_field,
                                  sft.z3_interval)
        self.attr_hist: dict[str, Histogram] = {}
        # per-INDEXED-attribute count-min sketches, auto-maintained on
        # write: equality selectivity feeds attr-vs-z strategy costs
        # (StatsBasedEstimator.scala:27 composes per-attribute
        # estimates the same way)
        self.attr_freq: dict[str, Frequency] = {}
        # box-tuple -> coarse-cell indices (see _cells_for_boxes)
        self._cells_cache: dict[tuple, np.ndarray] = {}
        # lazily-built per-cell spatial bounds (see _cell_bounds)
        self._cell_bounds_arr: tuple | None = None

    # write-side stats sample cap: the z3 histogram only ever feeds
    # RATIO estimates (mass / total_mass), so a strided subsample keeps
    # selectivity unbiased while the write path stays O(sample) — a
    # 100M-row ingest must not pay a full z3 re-encode for stats
    # (the reference's stats are likewise approximate sketches)
    _Z3_SAMPLE = 1_000_000

    def observe(self, batch) -> None:
        self.count.observe(batch)
        # ONE strided sub-batch shared by every sketch; weight = stride
        # keeps masses comparable across differently-sampled batches (a
        # small unsampled batch must not outweigh a large strided one)
        step = 1
        sub = batch
        if batch.n > self._Z3_SAMPLE:
            step = batch.n // self._Z3_SAMPLE + 1
            sub = batch.take(np.arange(0, batch.n, step, dtype=np.int64))
        for a in self.sft.attributes:
            if not a.indexed or a.name not in batch.columns:
                continue
            fr = self.attr_freq.setdefault(a.name, Frequency(a.name))
            fr.observe(sub, weight=step)
        if self.z3 is not None:
            self.z3.observe(sub, weight=step)

    def estimate_count(self, f: ast.Filter) -> int | None:
        """Estimated matching features, or None if not estimable."""
        total = self.count.count
        if total == 0:
            return 0
        if isinstance(f, ast.Include):
            return total
        if isinstance(f, ast.Exclude):
            return 0
        rest, attr_sel = self._split_attr_equality(f)
        if rest is None:
            # every conjunct was a sketch-backed attribute equality:
            # estimable without any spatio-temporal bound
            return int(round(attr_sel * total))
        sel = self._spatio_temporal_selectivity(rest)
        if sel is None:
            return None
        if attr_sel is not None:
            sel *= attr_sel
        return int(round(sel * total))

    def _split_attr_equality(self, f: ast.Filter):
        """Factor sketch-backed ``attr = value`` conjuncts out of a
        top-level AND: returns ``(rest, attr_selectivity)`` where rest
        is the filter minus those conjuncts (None when nothing is
        left) and attr_selectivity their combined count-min selectivity
        (None when no conjunct had a sketch — behavior then matches
        the pre-composition estimator exactly). Independence is
        assumed across conjuncts, as the reference's estimator does."""
        conjuncts = (list(f.children) if isinstance(f, ast.And) else [f])
        sel = None
        rest = []
        for c in conjuncts:
            est = None
            if isinstance(c, ast.Compare) and c.op == ast.CompareOp.EQ:
                est = self.attr_equality_estimate(c.prop, c.value)
            if est is None:
                rest.append(c)
                continue
            frac = min(1.0, est / max(self.count.count, 1))
            sel = frac if sel is None else sel * frac
        if sel is None:
            return f, None
        if not rest:
            return None, sel
        return (ast.And(rest) if len(rest) > 1 else rest[0]), sel

    def _spatio_temporal_selectivity(self, f: ast.Filter) -> float | None:
        geom = self.sft.geom_field
        dtg = self.sft.dtg_field
        if geom is None:
            return None
        geoms = extract_geometries(f, geom)
        if geoms.disjoint:
            return 0.0
        has_temporal = False
        if dtg is not None:
            iv = extract_intervals(f, dtg)
            if iv.disjoint:
                return 0.0
            has_temporal = bool(iv) and any(
                b.lower.is_bounded or b.upper.is_bounded for b in iv)
        if geoms.is_empty and not has_temporal:
            # no spatio-temporal constraint: not estimable here (attr/id
            # strategies must fall back to their heuristic costs)
            return None
        if self.z3 is None or self.z3.is_empty:
            # envelope-area fallback
            if not geoms:
                return None
            area = sum((g.envelope.xmax - g.envelope.xmin)
                       * (g.envelope.ymax - g.envelope.ymin) for g in geoms)
            return min(1.0, area / (360.0 * 180.0))
        # z3-histogram estimate: fraction of mass in covered (bin, cell)s.
        # All aggregates (total / per-bin / per-cell masses) are
        # maintained incrementally on observe — per-query cost must stay
        # O(selected bins), never O(all bins x cells): a 10k-polygon join
        # issues 10k count queries through this estimate
        intervals = (extract_intervals(f, dtg) if dtg is not None
                     else None)
        boxes = [g.envelope for g in geoms] or None
        hist = self.z3
        total_mass = hist.total
        if total_mass == 0:
            return 0.0
        period = hist.period
        all_bins = True
        sel_bins: set[int] = set()
        if intervals and not intervals.disjoint and len(intervals):
            all_bins = False
            for b in intervals:
                if not (b.lower.is_bounded and b.upper.is_bounded):
                    all_bins = True
                    break
                bins, _, _ = timebin.bins_of_interval(
                    int(b.lower.value), int(b.upper.value), period)
                sel_bins.update(bins.tolist())
        cells = (None if boxes is None
                 else self._cells_for_boxes(hist, boxes))
        if all_bins:
            mass = (total_mass if cells is None
                    else int(hist.cell_mass[cells].sum()))
        elif cells is None:
            mass = sum(hist.bin_mass.get(b, 0) for b in sel_bins)
        else:
            mass = sum(int(arr[cells].sum()) for b in sel_bins
                       if (arr := hist.bins.get(b)) is not None)
        return mass / total_mass

    def attr_equality_estimate(self, attr: str, value) -> int | None:
        """Estimated rows matching ``attr = value`` from the maintained
        count-min sketch, scaled for write-side subsampling; None when
        no sketch exists (unindexed attribute / nothing observed)."""
        fr = self.attr_freq.get(attr)
        if fr is None or fr.total == 0:
            return None
        scale = max(self.count.count, 1) / fr.total
        return int(round(fr.count(value) * scale))

    def temporal_fraction(self, intervals) -> float | None:
        """Fraction of observed mass inside the date intervals (time-bin
        resolution, from the z3 histogram) — the cost-model view of the
        attribute index's secondary (value, date) narrowing. ``intervals``
        is a FilterValues of date Bounds; None when not estimable."""
        if (self.z3 is None or self.z3.is_empty
                or not intervals or intervals.disjoint):
            return None
        hist = self.z3
        total = hist.total
        if total == 0:
            return None
        from ..filters.helper import to_millis
        sel_bins: set[int] = set()
        for b in intervals:
            if not (b.lower.is_bounded and b.upper.is_bounded):
                return None
            try:
                lo, hi = to_millis(b.lower.value), to_millis(b.upper.value)
            except Exception:
                return None
            # bins_of_interval handles out-of-range intervals itself
            # (wholly pre-epoch -> no bins); pre-clamping here would
            # collapse them onto a spurious bin 0
            bins, _, _ = timebin.bins_of_interval(lo, hi, hist.period)
            sel_bins.update(bins.tolist())
        mass = sum(hist.bin_mass.get(b, 0) for b in sel_bins)
        return mass / total

    def _cell_bounds(self, hist: Z3Histogram) -> tuple:
        """Spatial bounds (x0, x1, y0, y1 arrays) of every coarse z cell,
        decoded once from each cell's z-prefix range: a prefix fixes the
        leading bits of each interleaved dimension, so the prefix-lo
        decode gives the cell's min bin and the prefix-hi decode its max
        bin per dimension (expanded by half a bin: denormalize returns
        bin centers)."""
        if self._cell_bounds_arr is None:
            sfc = z3sfc(hist.period)
            c = np.arange(hist.length, dtype=np.uint64)
            shift = np.uint64(hist._shift)
            z_lo = c << shift
            z_hi = ((c + np.uint64(1)) << shift) - np.uint64(1)
            xl, yl, _ = sfc.invert(z_lo)
            xh, yh, _ = sfc.invert(z_hi)
            hx = (sfc.lon.max - sfc.lon.min) / sfc.lon.bins / 2
            hy = (sfc.lat.max - sfc.lat.min) / sfc.lat.bins / 2
            self._cell_bounds_arr = (xl - hx, xh + hx, yl - hy, yh + hy)
        return self._cell_bounds_arr

    def _cells_for_boxes(self, hist: Z3Histogram, boxes) -> np.ndarray:
        """Indices of coarse z cells whose spatial extent intersects the
        boxes — a vectorized overlap test against precomputed per-cell
        bounds (replaces a per-query z-range decomposition: 10k-query
        joins pay this on every count)."""
        key = tuple(b.as_tuple() for b in boxes)
        cached = self._cells_cache.get(key)
        if cached is not None:
            return cached
        x0, x1, y0, y1 = self._cell_bounds(hist)
        mask = np.zeros(hist.length, dtype=bool)
        for b in boxes:
            xmin, ymin, xmax, ymax = b.as_tuple()
            mask |= (x1 >= xmin) & (x0 <= xmax) & (y1 >= ymin) & (y0 <= ymax)
        out = np.flatnonzero(mask)
        if len(self._cells_cache) >= 64:
            self._cells_cache.pop(next(iter(self._cells_cache)))
        self._cells_cache[key] = out
        return out


class DataStoreStats:
    """Per-type stats registry for a datastore (GeoMesaStats analog,
    index/stats/GeoMesaStats.scala:29): auto-maintained on write, used
    for cost estimation and exposed for stats queries."""

    def __init__(self):
        self._by_type: dict[str, StatsEstimator] = {}

    def ensure(self, sft: SimpleFeatureType) -> StatsEstimator:
        if sft.type_name not in self._by_type:
            self._by_type[sft.type_name] = StatsEstimator(sft)
        return self._by_type[sft.type_name]

    def get(self, type_name: str) -> StatsEstimator | None:
        return self._by_type.get(type_name)

    def observe(self, sft: SimpleFeatureType, batch) -> None:
        self.ensure(sft).observe(batch)

    def clear(self, type_name: str) -> None:
        self._by_type.pop(type_name, None)
