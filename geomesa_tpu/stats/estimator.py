"""Stats-based selectivity estimation for the cost decider.

Analog of StatsBasedEstimator (index/stats/StatsBasedEstimator.scala:27):
estimate the number of features matching a filter from maintained
sketches — Count for totals, Z3Histogram for spatio-temporal
selectivity, Histogram/Enumeration for attribute selectivity.
"""

from __future__ import annotations

import numpy as np

from ..curves import timebin, z3sfc
from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import extract_geometries, extract_intervals
from .sketches import CountStat, Histogram, SeqStat, Stat, Z3Histogram

__all__ = ["StatsEstimator", "DataStoreStats"]


class StatsEstimator:
    """Wraps maintained sketches; answers estimate_count(filter)."""

    def __init__(self, sft: SimpleFeatureType):
        self.sft = sft
        self.count = CountStat()
        self.z3: Z3Histogram | None = None
        if sft.is_points and sft.dtg_field is not None:
            self.z3 = Z3Histogram(sft.geom_field, sft.dtg_field,
                                  sft.z3_interval)
        self.attr_hist: dict[str, Histogram] = {}
        # box-tuple -> coarse-cell indices (see _cells_for_boxes)
        self._cells_cache: dict[tuple, np.ndarray] = {}

    # write-side stats sample cap: the z3 histogram only ever feeds
    # RATIO estimates (mass / total_mass), so a strided subsample keeps
    # selectivity unbiased while the write path stays O(sample) — a
    # 100M-row ingest must not pay a full z3 re-encode for stats
    # (the reference's stats are likewise approximate sketches)
    _Z3_SAMPLE = 1_000_000

    def observe(self, batch) -> None:
        self.count.observe(batch)
        if self.z3 is not None:
            if batch.n > self._Z3_SAMPLE:
                # weight = stride, so masses from batches sampled at
                # different rates stay comparable (a small unsampled
                # batch must not outweigh a large strided one)
                step = batch.n // self._Z3_SAMPLE + 1
                self.z3.observe(batch.take(
                    np.arange(0, batch.n, step, dtype=np.int64)),
                    weight=step)
            else:
                self.z3.observe(batch)

    def estimate_count(self, f: ast.Filter) -> int | None:
        """Estimated matching features, or None if not estimable."""
        total = self.count.count
        if total == 0:
            return 0
        if isinstance(f, ast.Include):
            return total
        if isinstance(f, ast.Exclude):
            return 0
        sel = self._spatio_temporal_selectivity(f)
        if sel is None:
            return None
        return int(round(sel * total))

    def _spatio_temporal_selectivity(self, f: ast.Filter) -> float | None:
        geom = self.sft.geom_field
        dtg = self.sft.dtg_field
        if geom is None:
            return None
        geoms = extract_geometries(f, geom)
        if geoms.disjoint:
            return 0.0
        has_temporal = False
        if dtg is not None:
            iv = extract_intervals(f, dtg)
            if iv.disjoint:
                return 0.0
            has_temporal = bool(iv) and any(
                b.lower.is_bounded or b.upper.is_bounded for b in iv)
        if geoms.is_empty and not has_temporal:
            # no spatio-temporal constraint: not estimable here (attr/id
            # strategies must fall back to their heuristic costs)
            return None
        if self.z3 is None or self.z3.is_empty:
            # envelope-area fallback
            if not geoms:
                return None
            area = sum((g.envelope.xmax - g.envelope.xmin)
                       * (g.envelope.ymax - g.envelope.ymin) for g in geoms)
            return min(1.0, area / (360.0 * 180.0))
        # z3-histogram estimate: fraction of mass in covered (bin, cell)s
        intervals = (extract_intervals(f, dtg) if dtg is not None
                     else None)
        boxes = [g.envelope for g in geoms] or None
        hist = self.z3
        total_mass = sum(int(a.sum()) for a in hist.bins.values())
        if total_mass == 0:
            return 0.0
        period = hist.period
        if intervals and not intervals.disjoint and len(intervals):
            sel_bins = set()
            for b in intervals:
                if not (b.lower.is_bounded and b.upper.is_bounded):
                    sel_bins = set(hist.bins)
                    break
                bins, _, _ = timebin.bins_of_interval(
                    int(b.lower.value), int(b.upper.value), period)
                sel_bins.update(bins.tolist())
        else:
            sel_bins = set(hist.bins)
        mass = 0
        sfc = z3sfc(period)
        cells = (None if boxes is None
                 else self._cells_for_boxes(sfc, hist, boxes))
        for b in sel_bins:
            arr = hist.bins.get(b)
            if arr is None:
                continue
            mass += int(arr.sum() if cells is None else arr[cells].sum())
        return mass / total_mass

    def temporal_fraction(self, intervals) -> float | None:
        """Fraction of observed mass inside the date intervals (time-bin
        resolution, from the z3 histogram) — the cost-model view of the
        attribute index's secondary (value, date) narrowing. ``intervals``
        is a FilterValues of date Bounds; None when not estimable."""
        if (self.z3 is None or self.z3.is_empty
                or not intervals or intervals.disjoint):
            return None
        hist = self.z3
        total = sum(int(a.sum()) for a in hist.bins.values())
        if total == 0:
            return None
        from ..filters.helper import to_millis
        sel_bins: set[int] = set()
        for b in intervals:
            if not (b.lower.is_bounded and b.upper.is_bounded):
                return None
            try:
                lo, hi = to_millis(b.lower.value), to_millis(b.upper.value)
            except Exception:
                return None
            # bins_of_interval handles out-of-range intervals itself
            # (wholly pre-epoch -> no bins); pre-clamping here would
            # collapse them onto a spurious bin 0
            bins, _, _ = timebin.bins_of_interval(lo, hi, hist.period)
            sel_bins.update(bins.tolist())
        mass = sum(int(hist.bins[b].sum())
                   for b in sel_bins if b in hist.bins)
        return mass / total

    def _cells_for_boxes(self, sfc, hist: Z3Histogram, boxes) -> np.ndarray:
        """Indices of coarse z cells whose z-range intersects the boxes'
        z-ranges over the whole period (cells are leading z bits).
        Cached by box tuple: a repeated query's cost estimate must not
        re-run the range decomposition every time."""
        key = tuple(b.as_tuple() for b in boxes)
        cached = self._cells_cache.get(key)
        if cached is not None:
            return cached
        shift = hist._shift
        ranges = sfc.ranges([b.as_tuple() for b in boxes],
                            [(0, int(sfc.time.max))], max_ranges=256)
        lo_cells = (ranges[:, 0].astype(np.uint64) >> np.uint64(shift)).astype(np.int64)
        hi_cells = (ranges[:, 1].astype(np.uint64) >> np.uint64(shift)).astype(np.int64)
        mask = np.zeros(hist.length, dtype=bool)
        for lo, hi in zip(lo_cells.tolist(), hi_cells.tolist()):
            mask[lo:hi + 1] = True
        out = np.flatnonzero(mask)
        if len(self._cells_cache) >= 64:
            self._cells_cache.pop(next(iter(self._cells_cache)))
        self._cells_cache[key] = out
        return out


class DataStoreStats:
    """Per-type stats registry for a datastore (GeoMesaStats analog,
    index/stats/GeoMesaStats.scala:29): auto-maintained on write, used
    for cost estimation and exposed for stats queries."""

    def __init__(self):
        self._by_type: dict[str, StatsEstimator] = {}

    def ensure(self, sft: SimpleFeatureType) -> StatsEstimator:
        if sft.type_name not in self._by_type:
            self._by_type[sft.type_name] = StatsEstimator(sft)
        return self._by_type[sft.type_name]

    def get(self, type_name: str) -> StatsEstimator | None:
        return self._by_type.get(type_name)

    def observe(self, sft: SimpleFeatureType, batch) -> None:
        self.ensure(sft).observe(batch)

    def clear(self, type_name: str) -> None:
        self._by_type.pop(type_name, None)
