"""SFB (simple-feature-binary) codec: versioned row serialization with a
per-row offset table for lazy attribute access.

This is the analog of the reference's Kryo feature serializer
(geomesa-features/.../kryo/KryoFeatureSerializer.scala:19) and its lazy
buffer feature (kryo/KryoBufferSimpleFeature.scala — attribute offsets
array + ``setBuffer``): a serialized row can serve a single attribute
read without decoding the rest. Batch encode/decode is the host-side
hot path and runs in C++ (native/src/feature_codec.cpp) when the
toolchain is available, with a numpy/python fallback.

Row layout (little-endian, version 1) — see feature_codec.cpp header.

Wire encodings per SFT type:
  Integer        i32            Float          f32
  Long           i64            Double         f64
  Boolean        u8             Date           i64 epoch-millis
  Point          f64 x, f64 y   String/UUID    utf-8 bytes
  Bytes          raw bytes      other geometry WKB
  List/Map       recursive (count + elements), single-feature API only
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct
from typing import Any

import numpy as np

from ..geometry import Geometry, Point
from ..geometry.wkb import from_wkb, to_wkb
from .batch import (BoolColumn, DateColumn, FeatureBatch, GeometryColumn,
                    NumericColumn, PointColumn, StringColumn)
from .sft import SimpleFeatureType

__all__ = ["FeatureCodec", "EncodedBatch", "LazyFeature"]

_FIXED_WIDTH = {"Integer": 4, "Long": 8, "Float": 4, "Double": 8,
                "Boolean": 1, "Date": 8, "Point": 16}
_FIXED_DTYPE = {"Integer": "<i4", "Long": "<i8", "Float": "<f4",
                "Double": "<f8", "Boolean": "u1", "Date": "<i8"}


@dataclasses.dataclass
class EncodedBatch:
    """A batch of SFB rows: one contiguous blob + row offsets + ids."""
    blob: bytes
    row_offsets: np.ndarray   # int64[n+1]
    ids: np.ndarray           # object[n]

    @property
    def n(self) -> int:
        return len(self.row_offsets) - 1

    def row(self, i: int) -> bytes:
        return self.blob[self.row_offsets[i]:self.row_offsets[i + 1]]


def _cell_inputs(codec: "FeatureCodec", batch: FeatureBatch):
    """Normalize columns into (kind, width, fixed_bytes, var_bytes,
    var_offsets, valid) per attribute."""
    out = []
    for a in codec.sft.attributes:
        col = batch.columns[a.name]
        t = a.type.name
        valid = np.ascontiguousarray(col.valid, dtype=np.uint8)
        if t == "Point":
            assert isinstance(col, PointColumn)
            xy = np.empty((col.n, 2), dtype="<f8")
            xy[:, 0] = col.x
            xy[:, 1] = col.y
            out.append((0, 16, np.ascontiguousarray(xy).view(np.uint8),
                        None, None, valid))
        elif t in _FIXED_DTYPE:
            if isinstance(col, DateColumn):
                vals = col.millis
            else:
                vals = col.values  # type: ignore[union-attr]
            arr = np.ascontiguousarray(vals.astype(_FIXED_DTYPE[t]))
            out.append((0, _FIXED_WIDTH[t], arr.view(np.uint8).reshape(col.n, -1),
                        None, None, valid))
        elif t in ("String", "UUID"):
            assert isinstance(col, StringColumn)
            vocab_bytes = [s.encode("utf-8") for s in col.vocab]
            lens = np.array([len(b) for b in vocab_bytes], dtype=np.int64)
            row_lens = np.where(col.codes >= 0, lens[np.maximum(col.codes, 0)], 0)
            offsets = np.zeros(col.n + 1, dtype=np.int64)
            np.cumsum(row_lens, out=offsets[1:])
            buf = bytearray(int(offsets[-1]))
            for i, c in enumerate(col.codes):
                if c >= 0:
                    buf[offsets[i]:offsets[i + 1]] = vocab_bytes[c]
            out.append((1, 0, None, np.frombuffer(bytes(buf), dtype=np.uint8),
                        offsets, valid))
        else:  # geometry (non-point) / Bytes
            if isinstance(col, GeometryColumn):
                cells = [to_wkb(g) if g is not None else b"" for g in col.geoms]
            else:
                cells = [bytes(v) if v is not None else b""
                         for v in (col.value(i) for i in range(col.n))]
            lens = np.array([len(b) for b in cells], dtype=np.int64)
            offsets = np.zeros(len(cells) + 1, dtype=np.int64)
            np.cumsum(lens, out=offsets[1:])
            out.append((1, 0, None,
                        np.frombuffer(b"".join(cells), dtype=np.uint8)
                        if offsets[-1] else np.empty(0, dtype=np.uint8),
                        offsets, valid))
    return out


class FeatureCodec:
    """Batch-oriented SFB serializer for one SimpleFeatureType."""

    def __init__(self, sft: SimpleFeatureType, use_native: bool = True):
        self.sft = sft
        self.n_attrs = len(sft.attributes)
        self._bitmap_len = (self.n_attrs + 7) // 8
        self._header = 1 + self._bitmap_len + 4 * self.n_attrs
        self._lib = None
        if use_native:
            from .. import native
            self._lib = native.load()

    # -- batch encode -----------------------------------------------------

    def encode_batch(self, batch: FeatureBatch) -> EncodedBatch:
        cells = _cell_inputs(self, batch)
        n = batch.n
        if self._lib is not None and n > 0:
            enc = self._encode_native(cells, n)
        else:
            enc = self._encode_python(cells, n)
        blob, row_offsets = enc
        return EncodedBatch(blob, row_offsets, np.asarray(batch.ids, dtype=object))

    def _encode_native(self, cells, n):
        lib = self._lib
        na = self.n_attrs
        kinds = np.array([c[0] for c in cells], dtype=np.uint8)
        widths = np.array([c[1] for c in cells], dtype=np.int32)
        PP = ctypes.POINTER(ctypes.c_uint8) * na
        LP = ctypes.POINTER(ctypes.c_int64) * na

        def u8p(a):
            return (a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
                    if a is not None else None)

        fixed = PP(*[u8p(c[2]) for c in cells])
        var = PP(*[u8p(c[3]) for c in cells])
        voff = LP(*[(c[4].ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
                     if c[4] is not None else None) for c in cells])
        valids = PP(*[u8p(c[5]) for c in cells])

        lib.sfb_encoded_size.restype = ctypes.c_int64
        size = lib.sfb_encoded_size(
            ctypes.c_int32(n), ctypes.c_int32(na),
            kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), voff, valids)
        out = np.zeros(int(size), dtype=np.uint8)
        row_offsets = np.zeros(n + 1, dtype=np.int64)
        lib.sfb_encode_batch.restype = ctypes.c_int64
        written = lib.sfb_encode_batch(
            ctypes.c_int32(n), ctypes.c_int32(na),
            kinds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            widths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            fixed, var, voff, valids,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(size),
            row_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if written != size:
            raise RuntimeError(f"native encode wrote {written}, expected {size}")
        return out.tobytes(), row_offsets

    def _encode_python(self, cells, n):
        rows = []
        pos = 0
        row_offsets = np.zeros(n + 1, dtype=np.int64)
        for r in range(n):
            bm = bytearray(self._bitmap_len)
            offs = np.zeros(self.n_attrs, dtype="<u4")
            payload = bytearray()
            for a, (kind, width, fixed, var, voff, valid) in enumerate(cells):
                offs[a] = len(payload)
                if not valid[r]:
                    continue
                bm[a >> 3] |= 1 << (a & 7)
                if kind == 0:
                    payload += fixed[r * width:(r + 1) * width].tobytes() \
                        if fixed.ndim == 1 else fixed[r].tobytes()
                else:
                    payload += var[voff[r]:voff[r + 1]].tobytes()
            row = b"\x01" + bytes(bm) + offs.tobytes() + bytes(payload)
            rows.append(row)
            pos += len(row)
            row_offsets[r + 1] = pos
        return b"".join(rows), row_offsets

    # -- batch decode -----------------------------------------------------

    def decode_batch(self, enc: EncodedBatch) -> FeatureBatch:
        cols: dict[str, Any] = {}
        for a in self.sft.attributes:
            cols[a.name] = self.decode_attribute(enc, a.name)
        return FeatureBatch(self.sft, enc.ids, cols)

    def decode_attribute(self, enc: EncodedBatch, name: str):
        """Lazily extract ONE attribute column from the blob."""
        attr = self.sft.index_of(name)
        spec = self.sft.attributes[attr]
        t = spec.type.name
        n = enc.n
        blob = np.frombuffer(enc.blob, dtype=np.uint8)
        if t in _FIXED_WIDTH:
            width = _FIXED_WIDTH[t]
            vals = np.zeros(n * width, dtype=np.uint8)
            valid = np.zeros(n, dtype=np.uint8)
            if self._lib is not None and n > 0:
                self._lib.sfb_decode_fixed.restype = ctypes.c_int64
                rc = self._lib.sfb_decode_fixed(
                    blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    enc.row_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    ctypes.c_int32(n), ctypes.c_int32(self.n_attrs),
                    ctypes.c_int32(attr), ctypes.c_int32(width),
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
                if rc != n:
                    raise ValueError("corrupt SFB blob (width mismatch)")
            else:
                self._decode_fixed_py(blob, enc.row_offsets, attr, width,
                                      vals, valid)
            vmask = valid.astype(bool)
            if t == "Point":
                xy = vals.view("<f8").reshape(n, 2)
                x = np.where(vmask, xy[:, 0], np.nan)
                y = np.where(vmask, xy[:, 1], np.nan)
                return PointColumn(name, x, y, vmask)
            arr = vals.view(_FIXED_DTYPE[t]).copy()
            if t == "Date":
                return DateColumn(name, arr.astype(np.int64), vmask)
            if t == "Boolean":
                return BoolColumn(name, arr.astype(bool), vmask)
            if t in ("Double", "Float"):
                return NumericColumn(name, arr.astype(np.float64), vmask)
            return NumericColumn(name, arr.astype(np.int64), vmask)
        # var-width
        cells, vmask = self._decode_var(blob, enc.row_offsets, attr)
        if t in ("String", "UUID"):
            vals = [c.tobytes().decode("utf-8") if v else None
                    for c, v in zip(cells, vmask)]
            return StringColumn.from_strings(name, vals)
        if t == "Bytes":
            lst = [c.tobytes() if v else None for c, v in zip(cells, vmask)]
            return _BytesColumn(name, lst)
        geoms = [from_wkb(c.tobytes()) if v else None
                 for c, v in zip(cells, vmask)]
        return GeometryColumn.from_geoms(name, geoms)

    def _decode_fixed_py(self, blob, row_offsets, attr, width, vals, valid):
        n = len(row_offsets) - 1
        for r in range(n):
            base = int(row_offsets[r])
            s, e, ok, pstart = self._cell_span(blob, base,
                                               int(row_offsets[r + 1]), attr)
            valid[r] = 1 if ok else 0
            if ok:
                if e - s != width:
                    raise ValueError("corrupt SFB blob (width mismatch)")
                vals[r * width:(r + 1) * width] = blob[pstart + s:pstart + e]

    def _cell_span(self, blob, base, end, attr):
        bm = blob[base + 1:base + 1 + self._bitmap_len]
        ok = bool((bm[attr >> 3] >> (attr & 7)) & 1)
        offs = blob[base + 1 + self._bitmap_len:base + self._header].view("<u4")
        s = int(offs[attr])
        e = int(offs[attr + 1]) if attr + 1 < self.n_attrs \
            else end - base - self._header
        return s, e, ok, base + self._header

    def _decode_var(self, blob, row_offsets, attr):
        n = len(row_offsets) - 1
        if self._lib is not None and n > 0:
            lens = np.zeros(n, dtype=np.int64)
            valid = np.zeros(n, dtype=np.uint8)
            self._lib.sfb_decode_varlen_sizes.restype = ctypes.c_int64
            total = self._lib.sfb_decode_varlen_sizes(
                blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                row_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ctypes.c_int32(n), ctypes.c_int32(self.n_attrs),
                ctypes.c_int32(attr),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            offsets = np.zeros(n + 1, dtype=np.int64)
            np.cumsum(lens, out=offsets[1:])
            out = np.zeros(int(total), dtype=np.uint8)
            self._lib.sfb_decode_varlen.restype = ctypes.c_int64
            self._lib.sfb_decode_varlen(
                blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                row_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ctypes.c_int32(n), ctypes.c_int32(self.n_attrs),
                ctypes.c_int32(attr),
                offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            cells = [out[offsets[r]:offsets[r + 1]] for r in range(n)]
            return cells, valid.astype(bool)
        cells, valid = [], np.zeros(n, dtype=bool)
        for r in range(n):
            base = int(row_offsets[r])
            s, e, ok, pstart = self._cell_span(blob, base,
                                               int(row_offsets[r + 1]), attr)
            valid[r] = ok
            cells.append(blob[pstart + s:pstart + e] if ok
                         else np.empty(0, dtype=np.uint8))
        return cells, valid

    # -- single features ----------------------------------------------------

    def serialize(self, values: dict[str, Any]) -> bytes:
        """Serialize one feature (dict of attribute values) to SFB bytes."""
        bm = bytearray(self._bitmap_len)
        offs = np.zeros(self.n_attrs, dtype="<u4")
        payload = bytearray()
        for a, spec in enumerate(self.sft.attributes):
            offs[a] = len(payload)
            v = values.get(spec.name)
            if v is None:
                continue
            bm[a >> 3] |= 1 << (a & 7)
            payload += _encode_value(spec.type, v)
        return b"\x01" + bytes(bm) + offs.tobytes() + bytes(payload)

    def deserialize(self, buf: bytes) -> "LazyFeature":
        return LazyFeature(self, buf)


@dataclasses.dataclass
class _BytesColumn:
    """Object column of raw bytes values (Bytes attribute type)."""
    name: str
    data: list

    @property
    def n(self) -> int:
        return len(self.data)

    @property
    def valid(self) -> np.ndarray:
        return np.array([v is not None for v in self.data])

    def take(self, idx):
        idx = np.asarray(idx)
        if idx.dtype == bool:
            idx = np.flatnonzero(idx)
        return _BytesColumn(self.name, [self.data[i] for i in idx])

    def value(self, i: int):
        return self.data[i]


def _encode_value(atype, v) -> bytes:
    t = atype.name
    if t == "Integer":
        return struct.pack("<i", int(v))
    if t in ("Long", "Date"):
        return struct.pack("<q", int(v))
    if t == "Float":
        return struct.pack("<f", float(v))
    if t == "Double":
        return struct.pack("<d", float(v))
    if t == "Boolean":
        return struct.pack("B", 1 if v else 0)
    if t in ("String", "UUID"):
        return str(v).encode("utf-8")
    if t == "Bytes":
        return bytes(v)
    if t == "Point":
        if isinstance(v, Point):
            return struct.pack("<dd", v.x, v.y)
        return struct.pack("<dd", float(v[0]), float(v[1]))
    if t == "List":
        elems = [_encode_value(_elem_type(atype.value_type), e) for e in v]
        return struct.pack("<I", len(elems)) + b"".join(
            struct.pack("<I", len(e)) + e for e in elems)
    if t == "Map":
        items = list(v.items())
        out = [struct.pack("<I", len(items))]
        for k, val in items:
            ke = _encode_value(_elem_type(atype.key_type), k)
            ve = _encode_value(_elem_type(atype.value_type), val)
            out.append(struct.pack("<I", len(ke)) + ke)
            out.append(struct.pack("<I", len(ve)) + ve)
        return b"".join(out)
    if isinstance(v, Geometry):
        return to_wkb(v)
    raise TypeError(f"cannot encode {t}")


def _decode_value(atype, buf: bytes):
    t = atype.name
    if t == "Integer":
        return struct.unpack("<i", buf)[0]
    if t in ("Long", "Date"):
        return struct.unpack("<q", buf)[0]
    if t == "Float":
        return struct.unpack("<f", buf)[0]
    if t == "Double":
        return struct.unpack("<d", buf)[0]
    if t == "Boolean":
        return bool(buf[0])
    if t in ("String", "UUID"):
        return buf.decode("utf-8")
    if t == "Bytes":
        return bytes(buf)
    if t == "Point":
        return Point(*struct.unpack("<dd", buf))
    if t == "List":
        n = struct.unpack_from("<I", buf, 0)[0]
        pos, out = 4, []
        et = _elem_type(atype.value_type)
        for _ in range(n):
            ln = struct.unpack_from("<I", buf, pos)[0]
            out.append(_decode_value(et, buf[pos + 4:pos + 4 + ln]))
            pos += 4 + ln
        return out
    if t == "Map":
        n = struct.unpack_from("<I", buf, 0)[0]
        pos, out = 4, {}
        kt, vt = _elem_type(atype.key_type), _elem_type(atype.value_type)
        for _ in range(n):
            ln = struct.unpack_from("<I", buf, pos)[0]
            k = _decode_value(kt, buf[pos + 4:pos + 4 + ln])
            pos += 4 + ln
            ln = struct.unpack_from("<I", buf, pos)[0]
            out[k] = _decode_value(vt, buf[pos + 4:pos + 4 + ln])
            pos += 4 + ln
        return out
    return from_wkb(bytes(buf))


class _ET:
    def __init__(self, name):
        self.name = name


def _elem_type(name: str):
    return _ET(name)


class LazyFeature:
    """Offset-table view over one SFB row: attribute reads decode only
    the requested cell (KryoBufferSimpleFeature.scala semantics)."""

    def __init__(self, codec: FeatureCodec, buf: bytes):
        if not buf or buf[0] != 1:
            raise ValueError("bad SFB version")
        self._codec = codec
        self._buf = buf

    def get(self, i: int):
        codec = self._codec
        bm = self._buf[1:1 + codec._bitmap_len]
        if not (bm[i >> 3] >> (i & 7)) & 1:
            return None
        offs = np.frombuffer(self._buf, dtype="<u4", count=codec.n_attrs,
                             offset=1 + codec._bitmap_len)
        start = codec._header + int(offs[i])
        end = (codec._header + int(offs[i + 1]) if i + 1 < codec.n_attrs
               else len(self._buf))
        return _decode_value(codec.sft.attributes[i].type,
                             self._buf[start:end])

    def get_by_name(self, name: str):
        return self.get(self._codec.sft.index_of(name))

    def as_dict(self) -> dict[str, Any]:
        return {a.name: self.get(i)
                for i, a in enumerate(self._codec.sft.attributes)}
