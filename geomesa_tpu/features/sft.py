"""SimpleFeatureType schema model + spec-string parser.

Keeps the reference's spec grammar (geomesa-utils/.../geotools/
SimpleFeatureTypes.scala:24 and SimpleFeatureSpecParser):

    "name:String:index=true,dtg:Date,*geom:Point:srid=4326;geomesa.z3.interval='week'"

- comma-separated attributes: ``[*]name:Type[:opt=val]*`` where ``*``
  marks the default geometry
- after ``;``: schema-level user-data options (``key='val'`` or
  ``key=val``)
- types: String, Integer/Int, Double, Float, Long, Boolean, Date,
  Timestamp, UUID, Bytes, List[T], Map[K,V], Point, LineString,
  Polygon, MultiPoint, MultiLineString, MultiPolygon,
  GeometryCollection, Geometry

Typed accessors for the geomesa.* user-data keys mirror
RichSimpleFeatureType (Conversions.scala:239 getXZPrecision etc.).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

from ..curves.timebin import TimePeriod
from ..curves.xz import DEFAULT_G

__all__ = ["AttributeType", "AttributeSpec", "SimpleFeatureType",
           "parse_spec", "encode_spec", "Configs"]


class Configs:
    """Schema-level user-data keys (SimpleFeatureTypes.scala:28-49)."""
    TABLE_SHARING = "geomesa.table.sharing"
    DEFAULT_DATE = "geomesa.index.dtg"
    IGNORE_INDEX_DTG = "geomesa.ignore.dtg"
    VIS_LEVEL = "geomesa.visibility.level"
    Z3_INTERVAL = "geomesa.z3.interval"
    XZ_PRECISION = "geomesa.xz.precision"
    MIXED_GEOMETRIES = "geomesa.mixed.geometries"
    ENABLED_INDICES = "geomesa.indices.enabled"
    Z_SPLITS = "geomesa.z.splits"
    ATTR_SPLITS = "geomesa.attr.splits"
    LOGICAL_TIME = "geomesa.logical.time"
    KEYWORDS = "geomesa.keywords"
    INDEX_VERSION = "geomesa.index.version"


# current z-index layout version (see SimpleFeatureType.index_version)
CURRENT_INDEX_VERSION = 2
# versions a store can read or migrate to (1 = legacy curve)
KNOWN_INDEX_VERSIONS = frozenset({1, CURRENT_INDEX_VERSION})


def check_index_version(to_version) -> int:
    """Shared reindex-target validation (every store's reindex calls
    this, so version rules cannot drift between backends)."""
    if to_version is None:
        return CURRENT_INDEX_VERSION
    v = int(to_version)
    if v not in KNOWN_INDEX_VERSIONS:
        raise ValueError(f"unknown index version {to_version}; "
                         f"known: {sorted(KNOWN_INDEX_VERSIONS)}")
    return v

GEOMETRY_TYPES = {
    "Point", "LineString", "Polygon", "MultiPoint", "MultiLineString",
    "MultiPolygon", "GeometryCollection", "Geometry",
}

_TYPE_ALIASES = {
    "Int": "Integer", "int": "Integer", "Integer": "Integer",
    "String": "String", "str": "String",
    "Double": "Double", "double": "Double",
    "Float": "Float", "float": "Float",
    "Long": "Long", "long": "Long",
    "Boolean": "Boolean", "boolean": "Boolean",
    "Date": "Date", "Timestamp": "Date",
    "UUID": "UUID", "Uuid": "UUID",
    "Bytes": "Bytes",
}


@dataclasses.dataclass(frozen=True)
class AttributeType:
    """A resolved attribute type, possibly parameterized (List/Map)."""
    name: str                       # canonical binding name
    key_type: str | None = None     # for Map
    value_type: str | None = None   # for List/Map

    @property
    def is_geometry(self) -> bool:
        return self.name in GEOMETRY_TYPES

    def __str__(self) -> str:
        if self.name == "List":
            return f"List[{self.value_type}]"
        if self.name == "Map":
            return f"Map[{self.key_type},{self.value_type}]"
        return self.name


@dataclasses.dataclass
class AttributeSpec:
    name: str
    type: AttributeType
    options: dict[str, str] = dataclasses.field(default_factory=dict)
    default_geom: bool = False

    @property
    def is_geometry(self) -> bool:
        return self.type.is_geometry

    @property
    def indexed(self) -> bool:
        v = self.options.get("index", "false").lower()
        return v in ("true", "full", "join")

    @property
    def cardinality(self) -> str:
        return self.options.get("cardinality", "unknown").lower()

    def to_spec(self) -> str:
        star = "*" if self.default_geom else ""
        opts = "".join(f":{k}={v}" for k, v in sorted(self.options.items()))
        return f"{star}{self.name}:{self.type}{opts}"


class SimpleFeatureType:
    """Schema: ordered attributes + user-data, with geomesa accessors."""

    def __init__(self, type_name: str, attributes: list[AttributeSpec],
                 user_data: dict[str, Any] | None = None):
        self.type_name = type_name
        self.attributes = list(attributes)
        self.user_data: dict[str, Any] = dict(user_data or {})
        self._by_name = {a.name: i for i, a in enumerate(self.attributes)}
        if len(self._by_name) != len(self.attributes):
            raise ValueError("duplicate attribute names")

    # -- lookup -----------------------------------------------------------

    def index_of(self, name: str) -> int:
        if name not in self._by_name:
            raise KeyError(f"no attribute '{name}' in {self.type_name}")
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def attr(self, name: str) -> AttributeSpec:
        return self.attributes[self.index_of(name)]

    @property
    def geom_field(self) -> str | None:
        """Default geometry attribute (the '*'-marked one, else first geom)."""
        for a in self.attributes:
            if a.default_geom:
                return a.name
        for a in self.attributes:
            if a.is_geometry:
                return a.name
        return None

    @property
    def dtg_field(self) -> str | None:
        """Default date attribute: geomesa.index.dtg user-data, else the
        first Date attribute (RichSimpleFeatureType semantics)."""
        if self.user_data.get(Configs.IGNORE_INDEX_DTG) in (True, "true"):
            return None
        explicit = self.user_data.get(Configs.DEFAULT_DATE)
        if explicit and explicit in self:
            return explicit
        for a in self.attributes:
            if a.type.name == "Date":
                return a.name
        return None

    @property
    def is_points(self) -> bool:
        g = self.geom_field
        return g is not None and self.attr(g).type.name == "Point"

    # -- geomesa config accessors ----------------------------------------

    @property
    def z3_interval(self) -> TimePeriod:
        return TimePeriod.parse(self.user_data.get(Configs.Z3_INTERVAL, "week"))

    @property
    def visibility_level(self) -> str:
        """'feature' (default) or 'attribute': attribute-level stores
        one visibility label PER ATTRIBUTE per feature (comma-joined on
        write), and queries null out unauthorized attributes instead of
        dropping whole rows (KryoVisibilityRowEncoder semantics,
        accumulo/iterators/KryoVisibilityRowEncoder.scala:26)."""
        return str(self.user_data.get(Configs.VIS_LEVEL, "feature"))

    @property
    def index_version(self) -> int:
        """Z-index layout version (GeoMesaFeatureIndex keys table names
        by version, GeoMesaFeatureIndex.scala:33-35): v1 = legacy
        semi-normalized z3 curve (curves/legacy.py), v2 = current
        floor-normalized curves. Stores persist it in metadata so a
        reopened table keeps reading with its writer's layout until a
        reindex migrates it."""
        return int(self.user_data.get(Configs.INDEX_VERSION,
                                      CURRENT_INDEX_VERSION))

    @property
    def xz_precision(self) -> int:
        return int(self.user_data.get(Configs.XZ_PRECISION, DEFAULT_G))

    @property
    def enabled_indices(self) -> list[str]:
        v = self.user_data.get(Configs.ENABLED_INDICES)
        if not v:
            return []
        return [s.strip() for s in str(v).split(",") if s.strip()]

    @property
    def z_shards(self) -> int:
        """Leading shard count (geomesa.z.splits, default 4 in the
        reference's GeoMesaSchemaValidator)."""
        return int(self.user_data.get(Configs.Z_SPLITS, 4))

    @property
    def attr_shards(self) -> int:
        return int(self.user_data.get(Configs.ATTR_SPLITS, 4))

    # -- encoding ---------------------------------------------------------

    def to_spec(self) -> str:
        return encode_spec(self)

    def __repr__(self) -> str:
        return f"SimpleFeatureType({self.type_name!r}, {self.to_spec()!r})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, SimpleFeatureType)
                and self.type_name == other.type_name
                and self.to_spec() == other.to_spec())


_ATTR_RE = re.compile(
    r"^(?P<star>\*?)(?P<name>[a-zA-Z_][\w.-]*):(?P<type>[A-Za-z]+(?:\[[^\]]+\])?)"
    r"(?P<opts>(?::[^:,;]+=[^:,;]*)*)$")


def _parse_type(s: str) -> AttributeType:
    m = re.match(r"^List\[\s*(\w+)\s*\]$", s)
    if m:
        return AttributeType("List", value_type=_TYPE_ALIASES.get(m.group(1), m.group(1)))
    m = re.match(r"^Map\[\s*(\w+)\s*,\s*(\w+)\s*\]$", s)
    if m:
        return AttributeType("Map", key_type=_TYPE_ALIASES.get(m.group(1), m.group(1)),
                             value_type=_TYPE_ALIASES.get(m.group(2), m.group(2)))
    if s in GEOMETRY_TYPES:
        return AttributeType(s)
    if s in _TYPE_ALIASES:
        return AttributeType(_TYPE_ALIASES[s])
    raise ValueError(f"unknown attribute type: {s!r}")


def _split_top(s: str, sep: str) -> list[str]:
    """Split on sep outside of [] brackets and quotes."""
    out, depth, quote, cur = [], 0, None, []
    for ch in s:
        if quote:
            if ch == quote:
                quote = None
            cur.append(ch)
        elif ch in "'\"":
            quote = ch
            cur.append(ch)
        elif ch == "[":
            depth += 1
            cur.append(ch)
        elif ch == "]":
            depth -= 1
            cur.append(ch)
        elif ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


def parse_spec(type_name: str, spec: str) -> SimpleFeatureType:
    """Parse a spec string into a SimpleFeatureType."""
    spec = spec.strip()
    if ";" in spec:
        attr_part, opt_part = spec.split(";", 1)
    else:
        attr_part, opt_part = spec, ""

    attributes = []
    for raw in _split_top(attr_part, ","):
        raw = raw.strip()
        if not raw:
            continue
        m = _ATTR_RE.match(raw)
        if not m:
            raise ValueError(f"invalid attribute spec: {raw!r}")
        atype = _parse_type(m.group("type"))
        opts: dict[str, str] = {}
        opt_str = m.group("opts")
        if opt_str:
            for kv in opt_str.strip(":").split(":"):
                k, _, v = kv.partition("=")
                opts[k.strip()] = v.strip()
        default_geom = m.group("star") == "*"
        if default_geom and not atype.is_geometry:
            raise ValueError(f"'*' default marker on non-geometry: {raw!r}")
        attributes.append(AttributeSpec(m.group("name"), atype, opts, default_geom))

    user_data: dict[str, Any] = {}
    if opt_part.strip():
        for kv in _split_top(opt_part, ","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            v = v.strip()
            if len(v) >= 2 and v[0] == v[-1] and v[0] in "'\"":
                v = v[1:-1]
            user_data[k.strip()] = v

    return SimpleFeatureType(type_name, attributes, user_data)


def encode_spec(sft: SimpleFeatureType) -> str:
    attrs = ",".join(a.to_spec() for a in sft.attributes)
    if sft.user_data:
        opts = ",".join(f"{k}='{v}'" for k, v in sorted(sft.user_data.items()))
        return f"{attrs};{opts}"
    return attrs
