"""L1/L2 feature model: schemas + columnar batches (SURVEY.md 2.1,
geomesa-utils SimpleFeatureTypes + geomesa-features serializers)."""

from .sft import AttributeSpec, AttributeType, Configs, SimpleFeatureType, parse_spec
from .batch import (BoolColumn, Column, DateColumn, FeatureBatch,
                    GeometryColumn, NumericColumn, PointColumn, StringColumn)

__all__ = ["AttributeSpec", "AttributeType", "Configs", "SimpleFeatureType",
           "parse_spec", "BoolColumn", "Column", "DateColumn", "FeatureBatch",
           "GeometryColumn", "NumericColumn", "PointColumn", "StringColumn"]
