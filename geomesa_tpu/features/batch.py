"""Columnar feature model: struct-of-arrays batches.

Replaces the reference's row-oriented SimpleFeature + Kryo lazy
serialization (geomesa-features/.../kryo/KryoBufferSimpleFeature.scala):
on TPU the natural layout is struct-of-arrays — one numpy/jax array per
attribute with validity masks, dictionary-encoded strings, epoch-millis
dates and split-out point coordinates. The "lazy attribute access" trick
(read only the attributes a filter needs) becomes simply: kernels touch
only the columns they reference.

Host-side numpy here; the in-memory store builds device views (normalized
int32 grids, two-float coords) at index-build time.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

import numpy as np

try:  # ingest hands string columns over as Arrow arrays when it can
    import pyarrow as pa
except Exception:  # pragma: no cover — arrow-less fallback stays live
    pa = None

from ..geometry import Geometry, Point, parse_wkt
from .sft import SimpleFeatureType

__all__ = ["FeatureBatch", "Column", "NumericColumn", "BoolColumn",
           "DateColumn", "StringColumn", "PointColumn", "GeometryColumn"]


class Column:
    """Base column; length n with a validity mask."""

    name: str
    n: int
    valid: np.ndarray  # bool[n]

    def take(self, idx: np.ndarray) -> "Column":
        raise NotImplementedError

    def value(self, i: int):
        raise NotImplementedError


@dataclasses.dataclass
class NumericColumn(Column):
    name: str
    values: np.ndarray          # f64 or i64
    valid: np.ndarray

    @property
    def n(self) -> int:
        return len(self.values)

    def take(self, idx) -> "NumericColumn":
        return NumericColumn(self.name, self.values[idx], self.valid[idx])

    def value(self, i: int):
        if not self.valid[i]:
            return None
        v = self.values[i]
        return float(v) if self.values.dtype.kind == "f" else int(v)


@dataclasses.dataclass
class BoolColumn(Column):
    name: str
    values: np.ndarray          # bool
    valid: np.ndarray

    @property
    def n(self) -> int:
        return len(self.values)

    def take(self, idx) -> "BoolColumn":
        return BoolColumn(self.name, self.values[idx], self.valid[idx])

    def value(self, i: int):
        return bool(self.values[i]) if self.valid[i] else None


@dataclasses.dataclass
class DateColumn(Column):
    """Dates as epoch millis int64 (reference stores java Dates)."""
    name: str
    millis: np.ndarray
    valid: np.ndarray

    @property
    def n(self) -> int:
        return len(self.millis)

    def take(self, idx) -> "DateColumn":
        return DateColumn(self.name, self.millis[idx], self.valid[idx])

    def value(self, i: int):
        return int(self.millis[i]) if self.valid[i] else None


@dataclasses.dataclass
class StringColumn(Column):
    """Dictionary-encoded strings: codes int32 into vocab; -1 = null.

    The dictionary is the device-side representation too — string
    predicates compile to integer compares against looked-up codes
    (the ArrowFilterOptimizer trick, arrow/filter/ArrowFilterOptimizer.scala:36).
    """
    name: str
    codes: np.ndarray           # int32, -1 for null
    vocab: np.ndarray           # object array of unique strings, sorted

    @property
    def n(self) -> int:
        return len(self.codes)

    @property
    def valid(self) -> np.ndarray:  # type: ignore[override]
        return self.codes >= 0

    def take(self, idx) -> "StringColumn":
        return StringColumn(self.name, self.codes[idx], self.vocab)

    def value(self, i: int):
        c = self.codes[i]
        return None if c < 0 else str(self.vocab[c])

    def code_of(self, s: str) -> int:
        """Vocab code for s, or -1 if absent (planner-side lookup)."""
        i = np.searchsorted(self.vocab, s)
        if i < len(self.vocab) and self.vocab[i] == s:
            return int(i)
        return -1

    @classmethod
    def from_strings(cls, name: str, values: Iterable) -> "StringColumn":
        arr = np.asarray(list(values), dtype=object)
        mask = np.array([v is not None for v in arr], dtype=bool)
        filled = np.where(mask, arr, "")
        vocab, codes = np.unique(filled.astype(str), return_inverse=True)
        codes = codes.astype(np.int32)
        codes[~mask] = -1
        return cls(name, codes, vocab.astype(object))

    @classmethod
    def from_arrow(cls, name: str, arr) -> "StringColumn":
        """Dictionary-encode in C, then remap codes to the sorted-vocab
        order ``code_of``'s searchsorted contract requires. Sorting the
        (small) vocab beats argsorting every row."""
        if arr.null_count:
            return cls.from_strings(
                name, np.asarray(arr.to_numpy(zero_copy_only=False),
                                 dtype=object))
        d = arr.dictionary_encode()
        codes = np.asarray(d.indices.to_numpy(zero_copy_only=False),
                           dtype=np.int32)
        vocab = np.asarray(d.dictionary.to_numpy(zero_copy_only=False),
                           dtype=object)
        order = np.argsort(vocab)
        inv = np.empty(len(order), dtype=np.int32)
        inv[order] = np.arange(len(order), dtype=np.int32)
        return cls(name, inv[codes], vocab[order])


@dataclasses.dataclass
class PointColumn(Column):
    """Point geometry: x/y float64 pairs (the hot layout)."""
    name: str
    x: np.ndarray
    y: np.ndarray
    valid: np.ndarray

    @property
    def n(self) -> int:
        return len(self.x)

    def take(self, idx) -> "PointColumn":
        return PointColumn(self.name, self.x[idx], self.y[idx], self.valid[idx])

    def value(self, i: int):
        return Point(self.x[i], self.y[i]) if self.valid[i] else None


@dataclasses.dataclass
class GeometryColumn(Column):
    """Arbitrary geometries, host-side objects + cached bboxes.

    Packed device buffers (vertex arrays + offsets) are built lazily by
    the scan layer for the geometries a kernel actually needs.
    """
    name: str
    geoms: list  # list[Geometry | None]
    bounds: np.ndarray  # (n, 4) xmin ymin xmax ymax; nan for null

    @property
    def n(self) -> int:
        return len(self.geoms)

    @property
    def valid(self) -> np.ndarray:  # type: ignore[override]
        return ~np.isnan(self.bounds[:, 0])

    def take(self, idx) -> "GeometryColumn":
        idx = np.asarray(idx)
        if idx.dtype == bool:
            idx = np.flatnonzero(idx)
        return GeometryColumn(self.name, [self.geoms[i] for i in idx],
                              self.bounds[idx])

    def value(self, i: int):
        return self.geoms[i]

    @classmethod
    def from_geoms(cls, name: str, geoms: Iterable) -> "GeometryColumn":
        gl = [g if g is None or isinstance(g, Geometry) else parse_wkt(str(g))
              for g in geoms]
        bounds = np.full((len(gl), 4), np.nan)
        for i, g in enumerate(gl):
            if g is not None and not g.is_empty:
                e = g.envelope
                bounds[i] = (e.xmin, e.ymin, e.xmax, e.ymax)
        return cls(name, gl, bounds)


def _column_for(spec_type: str, name: str, data) -> Column:
    n = len(data)
    if spec_type == "Point":
        if isinstance(data, tuple):
            x, y = data
            x = np.asarray(x, dtype=np.float64)
            y = np.asarray(y, dtype=np.float64)
            valid = ~(np.isnan(x) | np.isnan(y))
            return PointColumn(name, x, y, valid)
        xs = np.full(n, np.nan)
        ys = np.full(n, np.nan)
        for i, g in enumerate(data):
            if g is None:
                continue
            if isinstance(g, Point):
                xs[i], ys[i] = g.x, g.y
            else:
                p = parse_wkt(str(g))
                xs[i], ys[i] = p.x, p.y  # type: ignore[union-attr]
        return PointColumn(name, xs, ys, ~np.isnan(xs))
    if spec_type in ("LineString", "Polygon", "MultiPoint", "MultiLineString",
                     "MultiPolygon", "GeometryCollection", "Geometry"):
        return GeometryColumn.from_geoms(name, data)
    if spec_type == "String" or spec_type == "UUID":
        if pa is not None and isinstance(data, (pa.Array, pa.ChunkedArray)):
            if isinstance(data, pa.ChunkedArray):
                data = data.combine_chunks()
            return StringColumn.from_arrow(name, data)
        return StringColumn.from_strings(name, data)
    if spec_type == "Date":
        arr = np.asarray(data)
        if arr.dtype.kind == "M":
            millis = arr.astype("datetime64[ms]").astype(np.int64)
            valid = ~np.isnat(arr)
        elif arr.dtype == object:
            valid = np.array([v is not None for v in arr], dtype=bool)
            millis = np.array(
                [int(np.datetime64(v, "ms").astype(np.int64)) if v is not None
                 else 0 for v in arr], dtype=np.int64)
        else:
            millis = arr.astype(np.int64)
            valid = np.ones(n, dtype=bool)
        return DateColumn(name, millis, valid)
    if spec_type == "Boolean":
        arr = np.asarray(data)
        if arr.dtype == object:
            valid = np.array([v is not None for v in arr], dtype=bool)
            vals = np.array([bool(v) for v in np.where(valid, arr, False)], dtype=bool)
        else:
            vals = arr.astype(bool)
            valid = np.ones(n, dtype=bool)
        return BoolColumn(name, vals, valid)
    # numeric
    dtype = np.float64 if spec_type in ("Double", "Float") else np.int64
    arr = np.asarray(data)
    if arr.dtype == object:
        valid = np.array([v is not None for v in arr], dtype=bool)
        vals = np.array([v if v is not None else 0 for v in arr], dtype=dtype)
    else:
        vals = arr.astype(dtype)
        valid = (~np.isnan(arr) if arr.dtype.kind == "f"
                 else np.ones(n, dtype=bool))
    return NumericColumn(name, vals, valid)


class FeatureBatch:
    """A batch of features: ids + one column per schema attribute."""

    def __init__(self, sft: SimpleFeatureType, ids: np.ndarray,
                 columns: dict[str, Column]):
        self.sft = sft
        self.ids = np.asarray(ids, dtype=object)
        self.columns = columns
        ns = {c.n for c in columns.values()} | {len(self.ids)}
        if len(ns) > 1:
            raise ValueError(f"column length mismatch: {ns}")

    @property
    def n(self) -> int:
        return len(self.ids)

    def __len__(self) -> int:
        return self.n

    def col(self, name: str) -> Column:
        return self.columns[name]

    @classmethod
    def from_dict(cls, sft: SimpleFeatureType, ids,
                  data: dict[str, Any]) -> "FeatureBatch":
        """Build from {attribute: array-like}; Point columns accept a
        (x_array, y_array) tuple or an iterable of Point/WKT."""
        columns = {}
        for a in sft.attributes:
            if a.name not in data:
                raise KeyError(f"missing column: {a.name}")
            columns[a.name] = _column_for(a.type.name, a.name, data[a.name])
        return cls(sft, np.asarray(ids, dtype=object), columns)

    def take(self, idx) -> "FeatureBatch":
        idx = np.asarray(idx)
        return FeatureBatch(self.sft, self.ids[idx],
                            {k: c.take(idx) for k, c in self.columns.items()})

    def feature(self, i: int) -> dict[str, Any]:
        """Row view (for result iteration / debugging)."""
        out = {"id": self.ids[i]}
        for name, c in self.columns.items():
            out[name] = c.value(i)
        return out

    def concat(self, other: "FeatureBatch") -> "FeatureBatch":
        return FeatureBatch.concat_all([self, other])

    @classmethod
    def concat_all(cls, batches: list["FeatureBatch"]) -> "FeatureBatch":
        """Single-pass multi-way concatenation: each column is copied
        once and string vocabs merge with one np.unique, so folding a
        burst of k small writes is O(total), not O(k * total)."""
        if not batches:
            raise ValueError("nothing to concatenate")
        if len(batches) == 1:
            return batches[0]
        first = batches[0]
        for b in batches[1:]:
            if b.sft != first.sft:
                raise ValueError("schema mismatch")
        cols: dict[str, Column] = {}
        for name, c in first.columns.items():
            parts = [b.columns[name] for b in batches]
            if isinstance(c, StringColumn):
                # one vocab merge: re-unique all vocabs, remap each code
                # array through its inverse segment, keep -1 nulls
                sizes = [len(p.vocab) for p in parts]
                vocab, inverse = np.unique(
                    np.concatenate([p.vocab for p in parts]).astype(str),
                    return_inverse=True)
                offs = np.cumsum([0] + sizes)
                codes = [
                    np.where(p.codes >= 0,
                             inverse[offs[i]:offs[i + 1]][
                                 np.maximum(p.codes, 0)], -1)
                    for i, p in enumerate(parts)]
                cols[name] = StringColumn(
                    name, np.concatenate(codes).astype(np.int32),
                    vocab.astype(object))
            elif isinstance(c, GeometryColumn):
                geoms: list = []
                for p in parts:
                    geoms.extend(p.geoms)  # type: ignore[union-attr]
                cols[name] = GeometryColumn(
                    name, geoms, np.vstack([p.bounds for p in parts]))
            elif isinstance(c, PointColumn):
                cols[name] = PointColumn(
                    name, np.concatenate([p.x for p in parts]),
                    np.concatenate([p.y for p in parts]),
                    np.concatenate([p.valid for p in parts]))
            elif isinstance(c, DateColumn):
                cols[name] = DateColumn(
                    name, np.concatenate([p.millis for p in parts]),
                    np.concatenate([p.valid for p in parts]))
            else:
                cols[name] = type(c)(
                    name,
                    np.concatenate([p.values for p in parts]),  # type: ignore[attr-defined]
                    np.concatenate([p.valid for p in parts]))
        return FeatureBatch(first.sft,
                            np.concatenate([b.ids for b in batches]), cols)

    # -- arrow interchange ------------------------------------------------

    def to_arrow(self):
        """Convert to a pyarrow RecordBatch (the host interchange format,
        mirroring geomesa-arrow's SimpleFeatureVector encoding)."""
        import pyarrow as pa
        from ..geometry.wkt import to_wkt
        arrays = [pa.array(self.ids.astype(str))]
        names = ["__fid__"]
        for a in self.sft.attributes:
            c = self.columns[a.name]
            names.append(a.name)
            if isinstance(c, PointColumn):
                arrays.append(pa.StructArray.from_arrays(
                    [pa.array(c.x), pa.array(c.y)], ["x", "y"]))
            elif isinstance(c, GeometryColumn):
                vals = [to_wkt(g) if g is not None else None for g in c.geoms]
                arrays.append(pa.array(vals, type=pa.string()))
            elif isinstance(c, StringColumn):
                null = c.codes < 0
                arrays.append(pa.DictionaryArray.from_arrays(
                    np.where(null, 0, c.codes).astype(np.int32),
                    pa.array(c.vocab.astype(str)), mask=null))
            elif isinstance(c, DateColumn):
                arrays.append(pa.array(
                    np.where(c.valid, c.millis, 0), type=pa.timestamp("ms"),
                    mask=~c.valid))
            else:
                arrays.append(pa.array(c.values, mask=~c.valid))
        return pa.RecordBatch.from_arrays(arrays, names)

    @classmethod
    def from_arrow(cls, sft: SimpleFeatureType, rb) -> "FeatureBatch":
        ids = np.asarray(rb.column("__fid__").to_pylist(), dtype=object)
        data: dict[str, Any] = {}
        for a in sft.attributes:
            col = rb.column(a.name)
            if a.type.name == "Point":
                flat = col.flatten()
                data[a.name] = (np.asarray(flat[0]), np.asarray(flat[1]))
            elif a.type.name == "Date":
                arr = col.to_pandas()
                data[a.name] = arr.values
            else:
                data[a.name] = col.to_pylist()
        return cls.from_dict(sft, ids, data)
