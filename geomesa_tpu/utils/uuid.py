"""Feature-id generators with z3 locality (utils/uuid/ package:
Z3FeatureIdGenerator.scala:26, Version4UuidGenerator,
IngestTimeFeatureIdGenerator).

A feature id is a UUID whose most-significant 8 bytes embed
[4-bit shard][time bin][leading z3 bits] — ids written together in
space/time sort near each other (write locality on the id/record
index) while the random least-significant half keeps them unique
(Z3FeatureIdGenerator.scala:84-120: shard nibble, z3 bytes shifted a
nibble, version bits at byte 6, 62 random bits). Vectorized: one call
generates ids for a whole batch.
"""

from __future__ import annotations

import numpy as np

from ..curves import TimePeriod, to_binned, z3sfc

__all__ = ["z3_uuids", "ingest_time_uuids", "z3_shard_of"]


def _set_version_variant(msb: np.ndarray, lsb: np.ndarray):
    """RFC-4122 version-4 + IETF variant bits."""
    msb &= ~np.uint64(0xF000)
    msb |= np.uint64(0x4000)
    lsb &= ~(np.uint64(0xC) << np.uint64(60))
    lsb |= np.uint64(0x8) << np.uint64(60)
    return msb, lsb


def _format(msb: np.ndarray, lsb: np.ndarray) -> np.ndarray:
    out = np.empty(len(msb), dtype=object)
    for i in range(len(msb)):
        h = f"{int(msb[i]):016x}{int(lsb[i]):016x}"
        out[i] = f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"
    return out


def z3_shard_of(bins: np.ndarray, z: np.ndarray, n_shards: int = 16):
    """Shard nibble from a hash of the (bin, z) key — spreads
    concurrent writers over pre-split shards while keeping each id's
    z3 locality below the shard prefix."""
    h = (bins.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
         ^ z.astype(np.uint64) * np.uint64(0xC2B2AE3D27D4EB4F))
    h ^= h >> np.uint64(33)
    return (h % np.uint64(n_shards)).astype(np.uint64)


def z3_uuids(x: np.ndarray, y: np.ndarray, millis: np.ndarray,
             period: TimePeriod | str = TimePeriod.WEEK,
             rng: np.random.Generator | None = None) -> np.ndarray:
    """Locality-preserving ids for point features.

    msb layout (64 bits): [shard:4][bin:16][z3 high bits:40][version:4]
    — the same shard-nibble + shifted-z3 shape as the reference, built
    with uint64 ops instead of byte juggling. lsb: 62 random bits +
    variant.
    """
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    millis = np.asarray(millis, np.int64)
    if np.any(~np.isfinite(x)) or np.any(~np.isfinite(y)):
        raise ValueError("cannot meaningfully index a NULL/NaN geometry")
    period = TimePeriod.parse(period)
    bins, offs = to_binned(millis, period, lenient=True)
    sfc = z3sfc(period)
    z = sfc.index(x, y, np.clip(offs, 0, int(sfc.time.max)),
                  lenient=True).astype(np.uint64)
    shard = z3_shard_of(bins, z)

    msb = (shard << np.uint64(60))
    msb |= (bins.astype(np.uint64) & np.uint64(0xFFFF)) << np.uint64(44)
    # top 40 bits of the 63-bit z value, placed above the version nibble
    msb |= (z >> np.uint64(23)) << np.uint64(4)

    rng = rng or np.random.default_rng()
    lsb = rng.integers(0, 2 ** 63, len(x), dtype=np.uint64) * np.uint64(2)
    msb, lsb = _set_version_variant(msb, lsb)
    return _format(msb, lsb)


def ingest_time_uuids(n: int, millis: int | None = None,
                      rng: np.random.Generator | None = None) -> np.ndarray:
    """Time-sorted ids (IngestTimeFeatureIdGenerator.scala:44): msb =
    ingest epoch millis, lsb random — ids sort by ingest time."""
    import time as _time
    ms = int(millis if millis is not None else _time.time() * 1000)
    msb = np.full(n, np.uint64(ms) << np.uint64(16), dtype=np.uint64)
    rng = rng or np.random.default_rng()
    msb |= rng.integers(0, 2 ** 12, n, dtype=np.uint64)
    lsb = rng.integers(0, 2 ** 63, n, dtype=np.uint64) * np.uint64(2)
    msb, lsb = _set_version_variant(msb, lsb)
    return _format(msb, lsb)
