"""Query timeout management.

Analog of ThreadManagement (geomesa-index-api/.../utils/
ThreadManagement.scala — a reaper sweeping open readers and killing
those past their timeout). JAX scans aren't interruptible mid-kernel,
so enforcement is at the plan/batch boundaries: a ManagedQuery is
checked between pipeline stages via ``check()`` and the reaper marks
overdue queries terminated so their next check raises."""

from __future__ import annotations

import threading
import time

__all__ = ["ManagedQuery", "ThreadManagement", "QueryTimeout"]


class QueryTimeout(RuntimeError):
    pass


class ManagedQuery:
    def __init__(self, type_name: str, filter_str: str, timeout_s: float):
        self.type_name = type_name
        self.filter_str = filter_str
        self.timeout_s = timeout_s
        self.start = time.monotonic()
        self._terminated = threading.Event()

    @property
    def deadline(self) -> float:
        return self.start + self.timeout_s

    @property
    def overdue(self) -> bool:
        return time.monotonic() > self.deadline

    def terminate(self):
        self._terminated.set()

    def check(self):
        """Raise if the reaper (or the deadline) killed this query.
        Call between pipeline stages."""
        if self._terminated.is_set() or self.overdue:
            self._terminated.set()
            raise QueryTimeout(
                f"query on {self.type_name!r} exceeded "
                f"{self.timeout_s}s: {self.filter_str!r}")


class ThreadManagement:
    """Registry + background reaper (5s sweep in the reference; the
    interval is configurable here and the sweep also runs inline on
    register to keep tests deterministic)."""

    def __init__(self, sweep_interval_s: float = 5.0):
        self.sweep_interval_s = sweep_interval_s
        self._open: set[ManagedQuery] = set()
        self._lock = threading.Lock()
        self._reaper: threading.Thread | None = None
        self._stop = threading.Event()

    def register(self, q: ManagedQuery) -> ManagedQuery:
        with self._lock:
            self._open.add(q)
            if self._reaper is None:
                self._reaper = threading.Thread(target=self._run, daemon=True)
                self._reaper.start()
        return q

    def complete(self, q: ManagedQuery):
        with self._lock:
            self._open.discard(q)

    def sweep(self) -> int:
        """Terminate overdue queries; returns how many were killed."""
        killed = 0
        with self._lock:
            for q in list(self._open):
                if q.overdue:
                    q.terminate()
                    self._open.discard(q)
                    killed += 1
        return killed

    def _run(self):
        while not self._stop.wait(self.sweep_interval_s):
            self.sweep()

    def shutdown(self):
        self._stop.set()
