"""System properties: layered config flags.

Analog of GeoMesaSystemProperties.SystemProperty (geomesa-utils/.../conf/
GeoMesaSystemProperties.scala:17-60): a named flag resolved, in order,
from (1) a thread-local override, (2) the process environment
(dots become underscores, uppercased), (3) a global override map,
(4) the declared default. Typed accessors mirror the reference
(`.toInt/.toBoolean/.toDuration` -> as_int/as_bool/as_seconds)."""

from __future__ import annotations

import os
import re
import threading

__all__ = ["SystemProperty"]

_overrides: dict[str, str] = {}
_tls = threading.local()


class SystemProperty:
    def __init__(self, name: str, default: str | None = None):
        self.name = name
        self.default = default

    # -- resolution --------------------------------------------------------

    def get(self) -> str | None:
        tl = getattr(_tls, "values", {})
        if self.name in tl:
            return tl[self.name]
        env = self.name.replace(".", "_").upper()
        if env in os.environ:
            return os.environ[env]
        if self.name in _overrides:
            return _overrides[self.name]
        return self.default

    def set(self, value: str | None):
        """Process-wide override (None clears)."""
        if value is None:
            _overrides.pop(self.name, None)
        else:
            _overrides[self.name] = str(value)

    def get_override(self) -> str | None:
        """The process-wide override layer ONLY (None = unset),
        ignoring thread-local/env/default resolution — lets a caller
        (the SLO reaction loop) save the exact override state it found
        and later restore it with ``set``, without baking a resolved
        env/default value into the override map."""
        return _overrides.get(self.name)

    def thread_local_set(self, value: str | None):
        tl = getattr(_tls, "values", None)
        if tl is None:
            tl = _tls.values = {}
        if value is None:
            tl.pop(self.name, None)
        else:
            tl[self.name] = str(value)

    # -- typed accessors ---------------------------------------------------

    def as_int(self) -> int | None:
        v = self.get()
        return None if v is None else int(v)

    def as_float(self) -> float | None:
        v = self.get()
        return None if v is None else float(v)

    def as_bool(self) -> bool | None:
        v = self.get()
        return None if v is None else v.strip().lower() in ("true", "1", "yes")

    def as_seconds(self) -> float | None:
        """Duration strings: '10s', '5 minutes', '100ms', bare seconds."""
        v = self.get()
        if v is None:
            return None
        m = re.match(r"^\s*([\d.]+)\s*([a-zA-Z]*)\s*$", v)
        if not m:
            raise ValueError(f"bad duration {v!r}")
        n = float(m.group(1))
        unit = m.group(2).lower()
        mult = {"": 1.0, "s": 1.0, "sec": 1.0, "second": 1.0, "seconds": 1.0,
                "ms": 1e-3, "millis": 1e-3, "milliseconds": 1e-3,
                "m": 60.0, "min": 60.0, "minute": 60.0, "minutes": 60.0,
                "h": 3600.0, "hour": 3600.0, "hours": 3600.0}.get(unit)
        if mult is None:
            raise ValueError(f"bad duration unit {unit!r}")
        return n * mult


# the reference's headline tuning flags (QueryProperties.scala:14-18)
SCAN_RANGES_TARGET = SystemProperty("geomesa.scan.ranges.target", "2000")
# coarser target for the host index tiers, which re-check every
# candidate exactly: deep decompositions are a per-query cost that a
# selective query stream never earns back
HOST_RANGES_TARGET = SystemProperty("geomesa.scan.ranges.host", "256")
QUERY_TIMEOUT = SystemProperty("geomesa.query.timeout", None)
FORCE_COUNT = SystemProperty("geomesa.force.count", "false")
