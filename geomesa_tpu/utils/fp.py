"""Floating-point error-band helpers shared by the f32 device kernels.

Device kernels compute in f32 and stay exact in f64 terms by pairing a
conservative error band with a host recheck of in-band rows (the
two-tier contract used by analytics/join, parallel/ring, scan/gscan).
"""

from __future__ import annotations

import numpy as np

__all__ = ["f32_band"]


def f32_band(r: float, coord_span: float) -> tuple[float, float]:
    """Conservative f32 error band for d2 = dx^2 + dy^2 around r^2.

    Returns (r2_hi, r2_lo): pairs with f32 d2 <= r2_lo are definitely
    within r in f64 terms; pairs with f32 d2 > r2_hi are definitely
    outside; the rest need a host f64 recheck. `coord_span` bounds the
    coordinate magnitudes (360 for degrees).
    """
    r2 = r * r
    eps = float(np.finfo(np.float32).eps)
    # The band only has to be valid for pairs whose f32 d2 lands NEAR
    # r^2 — and for those, |dx| and |dy| are bounded by ~r, not by the
    # coordinate span. Per-coordinate f64->f32 rounding plus the f32
    # subtraction give |dx_f32 - dx| <= E with E ~ eps*span/2 (two
    # half-ulp roundings of span/2-sized values + one ulp on the
    # difference); we take E = eps*span for slack. Then
    #   |d2_f32 - d2| <= 2(|dx|+|dy|)E + 2E^2 + 4 eps B^2
    # with |dx|,|dy| <= B = sqrt(r2 + err). Solve by one fixed-point
    # iteration from B = r (handles r ~ 0, where B ~ sqrt(2)*E).
    #
    # The old bound used max(span^2, r^2), which for r << span made the
    # band wider than r^2 itself (r2_lo = 0): every true hit became a
    # "maybe" and the entire join count fell to the host recheck path.
    E = eps * coord_span
    err = 4.0 * r * E + 2.0 * E * E + 4.0 * eps * r2
    B = float(np.sqrt(r2 + err))
    err = 4.0 * B * E + 2.0 * E * E + 4.0 * eps * B * B
    return r2 + err, max(r2 - err, 0.0)
