"""Floating-point error-band helpers shared by the f32 device kernels.

Device kernels compute in f32 and stay exact in f64 terms by pairing a
conservative error band with a host recheck of in-band rows (the
two-tier contract used by analytics/join, parallel/ring, scan/gscan).
"""

from __future__ import annotations

import numpy as np

__all__ = ["f32_band"]


def f32_band(r: float, coord_span: float) -> tuple[float, float]:
    """Conservative f32 error band for d2 = dx^2 + dy^2 around r^2.

    Returns (r2_hi, r2_lo): pairs with f32 d2 <= r2_lo are definitely
    within r in f64 terms; pairs with f32 d2 > r2_hi are definitely
    outside; the rest need a host f64 recheck. `coord_span` bounds the
    coordinate magnitudes (360 for degrees).
    """
    r2 = r * r
    # relative error of the f32 computation ~ 4 ulp on terms of size span^2
    err = 8.0 * float(np.finfo(np.float32).eps) * max(coord_span * coord_span, r2)
    return r2 + err, max(r2 - err, 0.0)
