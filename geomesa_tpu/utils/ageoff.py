"""Age-off: retention by feature age.

Analog of the Accumulo age-off iterators (accumulo/iterators/
AgeOffIterator / DtgAgeOffIterator — drop rows older than an expiry at
scan/compaction time). The TPU stores are explicit-state, so age-off is
a maintenance op over any store exposing query/delete: compute the
expired id set by dtg (or ingest-time user data) and delete it. The
live/lambda stores additionally expire inline (store/live.py)."""

from __future__ import annotations

import time

from ..index.api import Query

__all__ = ["age_off", "expired_ids"]


def expired_ids(store, type_name: str, expiry_ms: int,
                now_ms: int | None = None,
                dtg_field: str | None = None) -> list[str]:
    sft = store.get_schema(type_name)
    dtg = dtg_field or sft.dtg_field
    if dtg is None:
        raise ValueError(f"type {type_name!r} has no date attribute")
    cutoff = (int(time.time() * 1000) if now_ms is None else now_ms) \
        - expiry_ms
    res = store.query(Query(type_name, f"{dtg} < {cutoff}"))
    if res.batch is None:
        return []
    return [str(i) for i in res.batch.ids]


def age_off(store, type_name: str, expiry_ms: int,
            now_ms: int | None = None,
            dtg_field: str | None = None) -> int:
    """Delete features older than expiry; returns how many."""
    ids = expired_ids(store, type_name, expiry_ms, now_ms, dtg_field)
    if ids:
        store.delete(type_name, ids)
    return len(ids)
