"""Distributed-style locking for schema mutations.

Analog of DistributedLocking / ZookeeperLocking (geomesa-index-api/
.../utils/DistributedLocking.scala:14, geomesa-zk-utils) — the
reference guards schema create/delete with ZK locks; here the two
deployment shapes are in-process (LocalLock) and cross-process via
O_EXCL lock files with stale-lock breaking (FileLock)."""

from __future__ import annotations

import contextlib
import os
import threading
import time

__all__ = ["LocalLock", "FileLock", "with_lock"]


class LocalLock:
    """Named in-process locks (LocalLocking analog)."""

    _locks: dict[str, threading.RLock] = {}
    _guard = threading.Lock()

    def __init__(self, key: str):
        with LocalLock._guard:
            self._lock = LocalLock._locks.setdefault(key, threading.RLock())

    def acquire(self, timeout_s: float = 60.0) -> bool:
        return self._lock.acquire(timeout=timeout_s)

    def release(self):
        self._lock.release()


class FileLock:
    """Cross-process lock file created with O_EXCL; the holder writes
    its pid + timestamp, and locks older than `stale_s` are broken
    (a crash analog of ZK ephemeral-node expiry)."""

    def __init__(self, path: str, stale_s: float = 300.0):
        self.path = path
        self.stale_s = stale_s
        self._held = False

    def acquire(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, f"{os.getpid()} {time.time()}".encode())
                os.close(fd)
                self._held = True
                return True
            except FileExistsError:
                self._break_if_stale()
                if time.monotonic() >= deadline:
                    return False
                time.sleep(0.02)

    def _break_if_stale(self):
        try:
            age = time.time() - os.path.getmtime(self.path)
            if age > self.stale_s:
                os.remove(self.path)
        except OSError:
            pass

    def release(self):
        if self._held:
            self._held = False
            with contextlib.suppress(OSError):
                os.remove(self.path)


@contextlib.contextmanager
def with_lock(lock, timeout_s: float = 60.0):
    if not lock.acquire(timeout_s):
        raise TimeoutError(f"could not acquire lock within {timeout_s}s")
    try:
        yield
    finally:
        lock.release()
