"""Distributed-style locking for schema mutations.

Analog of DistributedLocking / ZookeeperLocking (geomesa-index-api/
.../utils/DistributedLocking.scala:14, geomesa-zk-utils) — the
reference guards schema create/delete with ZK locks; here the two
deployment shapes are in-process (LocalLock) and cross-process via
kernel-arbitrated flock(2) lock files (FileLock) — like ZK ephemeral
nodes, the kernel releases the lock when the holder dies, so no
stale-lock heuristics (and none of their TOCTOU races) are needed."""

from __future__ import annotations

import contextlib
import fcntl
import os
import threading
import time

__all__ = ["LocalLock", "FileLock", "with_lock"]


class LocalLock:
    """Named in-process locks (LocalLocking analog)."""

    _locks: dict[str, threading.RLock] = {}
    _guard = threading.Lock()

    def __init__(self, key: str):
        with LocalLock._guard:
            self._lock = LocalLock._locks.setdefault(key, threading.RLock())

    def acquire(self, timeout_s: float = 60.0) -> bool:
        return self._lock.acquire(timeout=timeout_s)

    def release(self):
        self._lock.release()


class FileLock:
    """Cross-process lock via flock(2) on a lock file. The kernel owns
    the lock state: a crashed holder's lock is released automatically
    (the ZK ephemeral-node analog), so there is no staleness window and
    no lock-breaking race. The file itself is never deleted.

    `stale_s` is accepted for API compatibility but unused — crash
    recovery is immediate under flock.
    """

    def __init__(self, path: str, stale_s: float = 300.0):
        self.path = path
        self.stale_s = stale_s
        self._fd: int | None = None

    def acquire(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        fd = os.open(self.path, os.O_CREAT | os.O_WRONLY, 0o644)
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                os.truncate(fd, 0)
                os.write(fd, f"{os.getpid()} {time.time()}".encode())
                self._fd = fd
                return True
            except OSError:
                if time.monotonic() >= deadline:
                    os.close(fd)
                    return False
                time.sleep(0.02)

    def release(self):
        if self._fd is not None:
            fd, self._fd = self._fd, None
            with contextlib.suppress(OSError):
                fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)


@contextlib.contextmanager
def with_lock(lock, timeout_s: float = 60.0):
    if not lock.acquire(timeout_s):
        raise TimeoutError(f"could not acquire lock within {timeout_s}s")
    try:
        yield
    finally:
        lock.release()
