"""Persistent XLA compilation cache.

The reference ships its server-side code as a pre-built jar to the
tablet servers (geomesa-accumulo-distributed-runtime), so scan
machinery never compiles at query time. The TPU analog: persist XLA
executables across processes so only the FIRST process ever pays the
20-40s trace+compile of the scan/join kernels — every later run (and
every benchmark round) loads them from disk.

Enabled the first time any kernel module imports; configuration:

- ``GEOMESA_TPU_COMPILE_CACHE`` — cache directory (default:
  ``<repo>/.jax_cache``)
- ``GEOMESA_TPU_NO_COMPILE_CACHE=1`` — disable entirely
"""

from __future__ import annotations

import os
import pathlib

_done = False


def ensure_compile_cache() -> None:
    """Idempotent: point JAX at the persistent compilation cache."""
    global _done
    if _done or os.environ.get("GEOMESA_TPU_NO_COMPILE_CACHE"):
        _done = True
        return
    _done = True
    try:
        import jax

        d = os.environ.get("GEOMESA_TPU_COMPILE_CACHE")
        candidates = ([d] if d else
                      [str(pathlib.Path(__file__).resolve().parents[2]
                           / ".jax_cache"),
                       # read-only installs (site-packages): user cache
                       os.path.join(os.path.expanduser("~"), ".cache",
                                    "geomesa_tpu", "jax")])
        d = None
        for cand in candidates:
            try:
                pathlib.Path(cand).mkdir(parents=True, exist_ok=True)
                probe = pathlib.Path(cand) / ".wtest"
                probe.touch()
                probe.unlink()
                d = cand
                break
            except OSError:
                continue
        if d is None:
            return
        jax.config.update("jax_compilation_cache_dir", d)
        # cache everything that took meaningful compile time; the
        # default threshold skips exactly the 1-2s kernels that add up
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        try:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # knob absent on older jax
    except Exception:
        pass  # cache is an optimization, never a failure mode
