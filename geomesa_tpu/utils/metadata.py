"""Metadata catalog: durable key-value schema/state registry.

Analog of GeoMesaMetadata (geomesa-index-api/.../metadata/
GeoMesaMetadata.scala:17 — typed key-value rows per feature type:
schema spec, index config, stats, table names) with the reference's
backends collapsed to two: in-memory (InMemoryMetadata of the test
datastore) and a JSON file directory (ZookeeperMetadata /
AccumuloBackedMetadata analog for a single-controller deployment).
Both cache reads (CachedLazyMetadata semantics) and support scan-by-
prefix.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Iterator

__all__ = ["MetadataCatalog", "InMemoryMetadata", "FileMetadata"]

SCHEMA_KEY = "schema"        # GeoMesaMetadata.ATTRIBUTES_KEY analog
STATS_KEY_PREFIX = "stats"
VERSION_KEY = "version"


class MetadataCatalog:
    """Interface: per-type key-value metadata."""

    def read(self, type_name: str, key: str) -> str | None:
        raise NotImplementedError

    def insert(self, type_name: str, key: str, value: str):
        raise NotImplementedError

    def insert_many(self, type_name: str, kvs: dict):
        for k, v in kvs.items():
            self.insert(type_name, k, v)

    def remove(self, type_name: str, key: str):
        raise NotImplementedError

    def delete(self, type_name: str):
        """Drop all keys for a type."""
        raise NotImplementedError

    def get_type_names(self) -> list[str]:
        raise NotImplementedError

    def scan(self, type_name: str, prefix: str) -> Iterator[tuple[str, str]]:
        raise NotImplementedError

    def read_required(self, type_name: str, key: str) -> str:
        v = self.read(type_name, key)
        if v is None:
            raise KeyError(f"no metadata {key!r} for type {type_name!r}")
        return v


class InMemoryMetadata(MetadataCatalog):
    def __init__(self):
        self._data: dict[str, dict[str, str]] = {}
        self._lock = threading.Lock()

    def read(self, type_name, key):
        return self._data.get(type_name, {}).get(key)

    def insert(self, type_name, key, value):
        with self._lock:
            self._data.setdefault(type_name, {})[key] = str(value)

    def remove(self, type_name, key):
        with self._lock:
            self._data.get(type_name, {}).pop(key, None)

    def delete(self, type_name):
        with self._lock:
            self._data.pop(type_name, None)

    def get_type_names(self):
        return sorted(self._data)

    def scan(self, type_name, prefix):
        for k, v in sorted(self._data.get(type_name, {}).items()):
            if k.startswith(prefix):
                yield k, v


class FileMetadata(MetadataCatalog):
    """One JSON file per type under a root dir; writes are atomic
    (tmp + rename) and re-read when the mtime changes."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._cache: dict[str, tuple[tuple[int, int, int], dict]] = {}
        self._lock = threading.Lock()

    def _path(self, type_name: str) -> str:
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in type_name)
        return os.path.join(self.root, f"{safe}.json")

    def _load(self, type_name: str) -> dict:
        path = self._path(type_name)
        try:
            st = os.stat(path)
        except OSError:
            return {}
        # ns mtime + size + inode: a same-tick cross-process replace
        # (os.replace swaps in a new inode) still invalidates the cache
        stamp = (st.st_mtime_ns, st.st_size, st.st_ino)
        cached = self._cache.get(type_name)
        if cached and cached[0] == stamp:
            return cached[1]
        with open(path) as fh:
            data = json.load(fh)
        self._cache[type_name] = (stamp, data)
        return data

    def _store(self, type_name: str, data: dict):
        path = self._path(type_name)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        self._cache.pop(type_name, None)

    def read(self, type_name, key):
        return self._load(type_name).get(key)

    def insert(self, type_name, key, value):
        with self._lock:
            data = dict(self._load(type_name))
            data[key] = str(value)
            self._store(type_name, data)

    def insert_many(self, type_name, kvs):
        with self._lock:
            data = dict(self._load(type_name))
            data.update({k: str(v) for k, v in kvs.items()})
            self._store(type_name, data)

    def remove(self, type_name, key):
        with self._lock:
            data = dict(self._load(type_name))
            if key in data:
                del data[key]
                self._store(type_name, data)

    def delete(self, type_name):
        with self._lock:
            path = self._path(type_name)
            if os.path.exists(path):
                os.remove(path)
            self._cache.pop(type_name, None)

    def get_type_names(self):
        return sorted(f[:-5] for f in os.listdir(self.root)
                      if f.endswith(".json"))

    def scan(self, type_name, prefix):
        for k, v in sorted(self._load(type_name).items()):
            if k.startswith(prefix):
                yield k, v
