"""Cross-cutting runtime utilities (metadata catalog, locking, query
timeout management, age-off, config properties)."""

from .metadata import FileMetadata, InMemoryMetadata, MetadataCatalog
from .locking import FileLock, LocalLock, with_lock
from .threads import ManagedQuery, ThreadManagement
from .properties import SystemProperty

__all__ = ["MetadataCatalog", "InMemoryMetadata", "FileMetadata",
           "LocalLock", "FileLock", "with_lock", "ThreadManagement",
           "ManagedQuery", "SystemProperty"]
