"""Version-skew detection.

Analog of the reference's distributed version check
(ProjectVersionIterator + GeoMesaDataStore.checkProjectVersion,
index/geotools/GeoMesaDataStore.scala:304-318): stores stamp the
framework version into their durable metadata at schema-create time;
on open, the recorded version is compared against the running package
and a mismatch warns (minor skew) or raises (major skew) — the single-
controller equivalent of client/server jar skew."""

from __future__ import annotations

import warnings

from .. import __version__
from .metadata import MetadataCatalog, VERSION_KEY

__all__ = ["stamp_version", "check_version", "VersionMismatch"]


class VersionMismatch(RuntimeError):
    pass


def _parse(v: str) -> tuple[int, int]:
    parts = (v.split(".") + ["0", "0"])[:2]
    return int(parts[0]), int(parts[1])


def stamp_version(catalog: MetadataCatalog, type_name: str):
    catalog.insert(type_name, VERSION_KEY, __version__)


def check_version(catalog: MetadataCatalog, type_name: str,
                  strict: bool = False) -> str | None:
    """Compare recorded vs running version. Returns the recorded
    version (None if never stamped). Major skew raises; minor skew
    warns (or raises when strict)."""
    recorded = catalog.read(type_name, VERSION_KEY)
    if recorded is None:
        return None
    check_version_string(recorded, type_name, strict)
    return recorded


def check_version_string(recorded: str, type_name: str,
                         strict: bool = False):
    if recorded == __version__:
        return
    rmaj, rmin = _parse(recorded)
    cmaj, cmin = _parse(__version__)
    msg = (f"type {type_name!r} written by geomesa_tpu {recorded}, "
           f"running {__version__}")
    if rmaj != cmaj or strict:
        raise VersionMismatch(msg)
    if rmin != cmin:
        warnings.warn(msg, stacklevel=2)
