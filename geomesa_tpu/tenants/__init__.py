"""The tenant isolation plane: identity, policy, and per-tenant budgets.

Millions of users means noisy neighbors. Every scaling primitive the
serving tier owns — retry/hedge budgets, load shedding, fused batch
admission, the cache byte budget, the ingest row bucket — is
process-global by default, so one abusive caller degrades everyone.
This module is the shared spine that makes them tenant-aware:

- **identity** — ``geomesa.web.auth.tokens`` maps bearer tokens to
  tenant names (``tok1:alice,tok2:bob``); the legacy single
  ``geomesa.web.auth.token`` (and anonymous callers) resolve to the
  ``default`` tenant. The web tier resolves the token once per request
  and runs the handler under ``tenant_scope``; a contextvar carries the
  name through batcher admission, retries, hedged attempts
  (``contextvars.copy_context`` in resilience/hedge.py), ingest staging
  and cache lookups without any surface plumbing arguments.
- **policy** — ``TenantPolicy`` reads per-tenant knobs LIVE
  (``geomesa.qos.tenant.<name>.weight`` etc., falling back to the
  process-wide ``geomesa.qos.*`` defaults), so operators can retune a
  running tier per tenant.
- **state** — ``TenantRegistry`` owns each tenant's ``RetryBudget``,
  web in-flight counter and ingest row bucket, and publishes the
  ``/rest/qos`` status document.
- **fair share** — ``weighted_drain`` is the deficit-weighted
  round-robin the batcher uses to fill fused dispatch chunks from
  per-tenant FIFO queues: a 2:1 weight ratio yields a 2:1 dispatch
  share under contention, an idle tenant's deficit resets instead of
  accumulating, and order WITHIN a tenant stays FIFO.

Kill switch: ``geomesa.qos.enabled`` (default false). Off,
``active_tenant()`` is None everywhere, so every touch point takes its
pre-QoS path bit-identically — admission order, shed decisions, cache
keys and budgets are unchanged.

Metric labels always pass tenant names through ``tenant_label``
(``sanitize_key``), and the registry's ``geomesa.metrics.max.series``
guard bounds per-tenant series cardinality (overflow collapses to
``other``).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from dataclasses import dataclass

from ..metrics import metrics, sanitize_key
from ..utils.properties import SystemProperty

__all__ = ["QOS_ENABLED", "WEB_AUTH_TOKENS", "DEFAULT_TENANT",
           "TenantPolicy", "TenantRegistry", "tenant_registry",
           "qos_enabled", "tenant_scope", "active_tenant",
           "tenant_budget", "tenant_label", "weighted_drain"]

# master kill switch: off (the default) is bit-identical to the
# pre-QoS serving tier on every touched surface
QOS_ENABLED = SystemProperty("geomesa.qos.enabled", "false")
# "token:tenant,token2:tenant2" — the multi-tenant face of the single
# geomesa.web.auth.token (which keeps gating mutations and maps to the
# "default" tenant)
WEB_AUTH_TOKENS = SystemProperty("geomesa.web.auth.tokens", None)

# process-wide per-tenant defaults; geomesa.qos.tenant.<name>.<suffix>
# overrides any of them for one tenant
QOS_WEIGHT = SystemProperty("geomesa.qos.weight", "1")
QOS_RETRY_BUDGET = SystemProperty("geomesa.qos.retry.budget", "10")
QOS_MAX_INFLIGHT = SystemProperty("geomesa.qos.max.inflight", None)
QOS_MAX_INFLIGHT_ROWS = SystemProperty("geomesa.qos.max.inflight.rows",
                                       None)
QOS_CACHE_MAX_BYTES = SystemProperty("geomesa.qos.cache.max.bytes", None)
QOS_VISIBILITY = SystemProperty("geomesa.qos.visibility", None)

DEFAULT_TENANT = "default"

_tenant: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_qos_tenant", default=None)


def qos_enabled() -> bool:
    """Re-read per call: the kill switch works on a live tier."""
    return str(QOS_ENABLED.get()).lower() in ("true", "1", "yes")


@contextlib.contextmanager
def tenant_scope(name: str | None):
    """Bind the calling context's tenant identity (web auth sets it;
    copied contexts — hedge attempts, scatter legs — inherit it)."""
    token = _tenant.set(name)
    try:
        yield
    finally:
        _tenant.reset(token)


def active_tenant() -> str | None:
    """The context's tenant, or None when QoS is disabled — the single
    gate every touch point checks, so the off path never branches."""
    if not qos_enabled():
        return None
    return _tenant.get()


def tenant_label(name: str) -> str:
    """Metric-safe tenant label: hostile/odd names collapse through
    ``sanitize_key`` so a tenant id can never mint unbounded or
    exposition-breaking label values."""
    return sanitize_key(str(name)) or "other"


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's QoS envelope, resolved from live knobs."""
    name: str
    weight: float = 1.0
    retry_budget: float = 10.0
    max_inflight: int | None = None
    max_inflight_rows: int | None = None
    cache_max_bytes: int | None = None
    visibility: str = ""


class _TenantState:
    __slots__ = ("budget", "inflight", "rows", "sheds", "row_refusals")

    def __init__(self, budget):
        self.budget = budget
        self.inflight = 0
        self.rows = 0
        self.sheds = 0
        self.row_refusals = 0


class TenantRegistry:
    """Token resolution, live policy reads, and per-tenant runtime
    state (retry budget, web in-flight count, ingest row bucket)."""

    def __init__(self, registry=metrics):
        self._registry = registry
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._state: dict[str, _TenantState] = {}

    # -- identity ----------------------------------------------------------

    def resolve_token(self, token: str | None) -> str:
        """Bearer token -> tenant name. Unknown/absent tokens (and the
        legacy single ``geomesa.web.auth.token``) are the ``default``
        tenant, so pre-QoS deployments keep one well-defined bucket."""
        raw = WEB_AUTH_TOKENS.get()
        if token and raw:
            for part in str(raw).split(","):
                tok, _, name = part.strip().partition(":")
                if tok and name and tok == token:
                    return name
        return DEFAULT_TENANT

    # -- policy ------------------------------------------------------------

    def policy(self, tenant: str) -> TenantPolicy:
        """Read the tenant's knobs LIVE (per-tenant override wins over
        the process-wide ``geomesa.qos.*`` default)."""
        def raw(suffix: str, default_prop: SystemProperty):
            v = SystemProperty(
                f"geomesa.qos.tenant.{tenant}.{suffix}", None).get()
            return v if v is not None else default_prop.get()

        def as_f(suffix, default_prop, fallback):
            v = raw(suffix, default_prop)
            try:
                return fallback if v is None else float(v)
            except (TypeError, ValueError):
                return fallback

        def as_i(suffix, default_prop):
            v = raw(suffix, default_prop)
            try:
                return None if v is None else int(v)
            except (TypeError, ValueError):
                return None

        return TenantPolicy(
            name=tenant,
            weight=max(as_f("weight", QOS_WEIGHT, 1.0), 1e-3),
            retry_budget=max(as_f("retry.budget", QOS_RETRY_BUDGET,
                                  10.0), 0.0),
            max_inflight=as_i("max.inflight", QOS_MAX_INFLIGHT),
            max_inflight_rows=as_i("max.inflight.rows",
                                   QOS_MAX_INFLIGHT_ROWS),
            cache_max_bytes=as_i("cache.max.bytes", QOS_CACHE_MAX_BYTES),
            visibility=str(raw("visibility", QOS_VISIBILITY) or ""))

    # -- state -------------------------------------------------------------

    def state(self, tenant: str) -> _TenantState:
        with self._lock:
            st = self._state.get(tenant)
            if st is None:
                from ..resilience.policy import RetryBudget
                st = _TenantState(
                    RetryBudget(capacity=self.policy(tenant).retry_budget))
                self._state[tenant] = st
                self._registry.gauge("qos.tenants", len(self._state))
            return st

    def retry_budget(self, tenant: str):
        return self.state(tenant).budget

    # -- web in-flight caps ------------------------------------------------

    def try_acquire_inflight(self, tenant: str) -> bool:
        """One web request slot for ``tenant``; False = shed (503) —
        only THIS tenant is over its cap, others keep proceeding."""
        cap = self.policy(tenant).max_inflight
        label = tenant_label(tenant)
        with self._lock:
            st = self.state(tenant)
            if cap is not None and st.inflight >= cap:
                st.sheds += 1
                self._registry.counter("qos.web.sheds",
                                       labels={"tenant": label})
                return False
            st.inflight += 1
            self._registry.gauge("qos.web.inflight", st.inflight,
                                 labels={"tenant": label})
        return True

    def release_inflight(self, tenant: str):
        with self._lock:
            st = self.state(tenant)
            st.inflight = max(0, st.inflight - 1)
            self._registry.gauge("qos.web.inflight", st.inflight,
                                 labels={"tenant": tenant_label(tenant)})

    # -- ingest row buckets ------------------------------------------------

    def acquire_rows(self, tenant: str, rows: int, block: bool = True,
                     timeout: float | None = None) -> bool:
        """Admit ``rows`` against the tenant's in-flight bucket
        (``IngestGovernor.acquire`` semantics: an oversize batch is
        admitted alone once the bucket drains). No cap configured ->
        rows are tracked for status but never refused."""
        cap = self.policy(tenant).max_inflight_rows
        label = tenant_label(tenant)
        with self._cv:
            st = self.state(tenant)
            if cap is not None:
                while st.rows > 0 and st.rows + rows > cap:
                    if not block:
                        st.row_refusals += 1
                        self._registry.counter(
                            "qos.ingest.refused", labels={"tenant": label})
                        return False
                    if not self._cv.wait(timeout=timeout):
                        st.row_refusals += 1
                        self._registry.counter(
                            "qos.ingest.refused", labels={"tenant": label})
                        return False
            st.rows += rows
            self._registry.gauge("qos.ingest.rows", st.rows,
                                 labels={"tenant": label})
        return True

    def release_rows(self, tenant: str, rows: int):
        with self._cv:
            st = self.state(tenant)
            st.rows = max(0, st.rows - rows)
            self._registry.gauge("qos.ingest.rows", st.rows,
                                 labels={"tenant": tenant_label(tenant)})
            self._cv.notify_all()

    # -- surfaces ----------------------------------------------------------

    def status(self) -> dict:
        """The ``/rest/qos`` document: every tenant seen so far with
        its live policy and runtime budget state."""
        with self._lock:
            tenants = {}
            for name, st in self._state.items():
                pol = self.policy(name)
                tenants[name] = {
                    "weight": pol.weight,
                    "inflight": st.inflight,
                    "max_inflight": pol.max_inflight,
                    "inflight_rows": st.rows,
                    "max_inflight_rows": pol.max_inflight_rows,
                    "retry_budget_tokens": round(st.budget.tokens, 3),
                    "retry_budget_capacity":
                        round(st.budget.effective_capacity(), 3),
                    "cache_max_bytes": pol.cache_max_bytes,
                    "visibility": pol.visibility,
                    "sheds": st.sheds,
                    "row_refusals": st.row_refusals,
                }
        return {"enabled": qos_enabled(), "tenants": tenants}

    def reset(self):
        """Drop all tenant state (test/bench hygiene)."""
        with self._lock:
            self._state.clear()


def tenant_budget():
    """The active tenant's RetryBudget, or None when QoS is off / no
    tenant is bound — retry/hedge policies substitute it for their
    shared budget so one tenant draining retries cannot suppress
    another's hedging."""
    t = active_tenant()
    if t is None:
        return None
    return tenant_registry.retry_budget(t)


def weighted_drain(queues: dict, deficits: dict, cap: int,
                   weight_of=None) -> list:
    """One deficit-weighted round-robin fill: pop up to ``cap`` items
    across per-tenant FIFO ``queues`` (mutated in place). Each round
    credits every backlogged tenant ``weight`` deficit and spends whole
    units, so sustained 2:1 weights dispatch 2:1 shares. ``deficits``
    persists across calls (unspent credit carries to the next chunk);
    a tenant whose queue is empty has its deficit dropped — idle
    tenants never bank unbounded credit."""
    out: list = []
    for t in list(deficits):
        if not queues.get(t):
            deficits.pop(t)
    active = sorted(t for t, q in queues.items() if q)
    if not active:
        return out
    weights = {t: max(float(weight_of(t)) if weight_of else 1.0, 1e-3)
               for t in active}
    while len(out) < cap and any(queues[t] for t in active):
        for t in active:
            q = queues[t]
            if not q:
                deficits.pop(t, None)
                continue
            deficits[t] = deficits.get(t, 0.0) + weights[t]
            while deficits[t] >= 1.0 and q and len(out) < cap:
                out.append(q.pop(0))
                deficits[t] -= 1.0
            if not q:
                deficits.pop(t, None)
            if len(out) >= cap:
                break
    return out


tenant_registry = TenantRegistry()
