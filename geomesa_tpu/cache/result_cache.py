"""The materialized result cache: version-stamped entries, LRU byte
budget, single-flight coalescing.

An entry is ``(type_name, plan key) -> (version, payload)`` where
``version`` is the store's pushdown version for the type — the WAL LSN
on durable stores, a store-local mutation counter otherwise. The
version is read BEFORE compute: a write landing mid-compute stamps the
result with the older version, leaving it unreachable at the new one (a
wasted recompute, never a stale serve).

Single-flight: concurrent misses on one ``(type, key, version)`` elect
a leader that computes once; followers park on an event and decode the
leader's payload — they never touch the store's op lock, so a
thundering herd of identical cold tiles costs exactly one device
dispatch and zero lock convoys.

Payloads are stored in an immutable-safe form (the caller's ``encode``)
and every hit decodes a private copy, so a consumer mutating its result
(the cluster's in-place ``Stat.merge``, a caller scribbling on a grid)
can never corrupt the cached original.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..metrics import metrics
from ..utils.properties import SystemProperty

__all__ = ["ResultCache", "CACHE_ENABLED", "CACHE_MAX_BYTES"]

# kill switch for the materialized pushdown cache (off: every request
# recomputes, the pre-cache behavior)
CACHE_ENABLED = SystemProperty("geomesa.cache.enabled", "true")
# LRU byte budget across one store's cached payloads
CACHE_MAX_BYTES = SystemProperty("geomesa.cache.max.bytes",
                                 str(256 * 1024 * 1024))

# a wedged leader must not park followers forever; past this they
# recompute for themselves
_FLIGHT_WAIT_S = 600.0


def _nbytes(stored) -> int:
    if stored is None:
        return 0
    if isinstance(stored, (bytes, bytearray)):
        return len(stored)
    nb = getattr(stored, "nbytes", None)
    if nb is not None:
        return int(nb)
    import sys
    return sys.getsizeof(stored)


class _Entry:
    __slots__ = ("version", "stored", "nbytes", "hits", "compute",
                 "encode", "tenant")

    def __init__(self, version, stored, nbytes, compute, encode,
                 tenant=None):
        self.version = version
        self.stored = stored
        self.nbytes = nbytes
        self.hits = 0
        self.compute = compute
        self.encode = encode
        # installing tenant (None with QoS off): per-tenant byte
        # budgets evict the owning tenant's LRU entries only
        self.tenant = tenant


class _Flight:
    __slots__ = ("event", "stored", "error")

    def __init__(self):
        self.event = threading.Event()
        self.stored = None
        self.error = None


class ResultCache:
    """Per-store cache; ``version_fn(type_name)`` is the store's
    pushdown-version accessor (the LSN face of invalidation)."""

    def __init__(self, version_fn, registry=metrics):
        self._version_fn = version_fn
        self._registry = registry
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, _Entry] = OrderedDict()
        self._inflight: dict[tuple, _Flight] = {}
        self._bytes = 0
        self._tenant_bytes: dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.singleflight_waits = 0
        self.refreshes = 0
        self.invalidations = 0

    @staticmethod
    def enabled() -> bool:
        return bool(CACHE_ENABLED.as_bool())

    @staticmethod
    def max_bytes() -> int:
        return int(CACHE_MAX_BYTES.as_int() or 0)

    # -- the serving path --------------------------------------------------

    def get_or_compute(self, type_name: str, key: str, compute,
                       encode=None, decode=None):
        """Serve ``(type_name, key)`` at the type's current version:
        a memoized payload when the version is unchanged, one
        single-flighted ``compute()`` otherwise."""
        if not self.enabled():
            return compute()
        # tenant scoping (tenants plane): the plan key is suffixed with
        # the tenant's VISIBILITY scope, so tenants with different
        # visibilities never share an entry while same-visibility
        # tenants still deduplicate. QoS off -> tenant is None and the
        # key is byte-identical to the pre-QoS cache.
        from ..tenants import active_tenant, tenant_registry
        tenant = active_tenant()
        if tenant is not None:
            key = f"{key}|qosvis={tenant_registry.policy(tenant).visibility}"
        version = self._version_fn(type_name)
        k = (type_name, key)
        fk = (type_name, key, version)
        with self._lock:
            e = self._entries.get(k)
            if e is not None and e.version == version:
                e.hits += 1
                self.hits += 1
                self._entries.move_to_end(k)
                stored = e.stored
                leader = None
            else:
                fl = self._inflight.get(fk)
                if fl is None:
                    fl = self._inflight[fk] = _Flight()
                    leader = True
                else:
                    leader = False
                    self.singleflight_waits += 1
        from ..obs import annotate, set_flag
        if leader is None:
            self._registry.counter("cache.hits")
            annotate("cache.hit", type=type_name)
            set_flag("cache_hit")
            return decode(stored) if decode is not None else stored
        if leader is False:
            # follower: park on the leader's flight, decode a private
            # copy of its payload — no store lock, no device dispatch
            self._registry.counter("cache.singleflight.waits")
            annotate("cache.singleflight.follower", type=type_name)
            fl.event.wait(_FLIGHT_WAIT_S)
            if fl.error is not None or not fl.event.is_set() \
                    or fl.stored is None:
                return compute()
            return decode(fl.stored) if decode is not None else fl.stored
        # leader: compute (the store's own synchronization applies),
        # publish to followers, install the entry
        self._registry.counter("cache.misses")
        annotate("cache.miss", type=type_name)
        with self._lock:
            self.misses += 1
        try:
            value = compute()
        except BaseException as ex:
            fl.error = ex
            fl.event.set()
            with self._lock:
                self._inflight.pop(fk, None)
            raise
        stored = None
        try:
            stored = encode(value) if encode is not None else value
        except Exception:
            # unencodable payload: serve it, just don't memoize
            self._registry.counter("cache.encode_errors")
        if stored is not None:
            self._install(k, version, stored, compute, encode,
                          tenant=tenant)
        fl.stored = stored
        fl.event.set()
        with self._lock:
            self._inflight.pop(fk, None)
        return value

    def _install(self, k, version, stored, compute, encode, tenant=None):
        nbytes = _nbytes(stored)
        budget = self.max_bytes()
        tenant_budget = None
        if tenant is not None:
            from ..tenants import tenant_registry
            tenant_budget = tenant_registry.policy(tenant).cache_max_bytes
        with self._lock:
            old = self._entries.pop(k, None)
            if old is not None:
                self._drop_bytes_locked(old)
            if (budget and nbytes > budget) or \
                    (tenant_budget and nbytes > tenant_budget):
                # a single payload larger than the whole budget would
                # evict everything and still not fit
                self._gauges_locked()
                return
            e = _Entry(version, stored, nbytes, compute, encode,
                       tenant=tenant)
            if old is not None:
                e.hits = old.hits  # heat survives version bumps
            self._entries[k] = e
            self._add_bytes_locked(e)
            while budget and self._bytes > budget and self._entries:
                _, ev = self._entries.popitem(last=False)
                self._drop_bytes_locked(ev)
                self.evictions += 1
                self._registry.counter("cache.evictions")
            # per-tenant byte budget: evict THIS tenant's LRU entries
            # until it fits — other tenants' entries are untouchable
            while tenant_budget and \
                    self._tenant_bytes.get(tenant, 0) > tenant_budget:
                victim = next((vk for vk, ve in self._entries.items()
                               if ve.tenant == tenant and vk != k), None)
                if victim is None:
                    break
                self._drop_bytes_locked(self._entries.pop(victim))
                self.evictions += 1
                self._registry.counter("cache.evictions")
                from ..tenants import tenant_label
                self._registry.counter(
                    "qos.cache.evictions",
                    labels={"tenant": tenant_label(tenant)})
            self._gauges_locked()

    def _add_bytes_locked(self, e: _Entry):
        self._bytes += e.nbytes
        if e.tenant is not None:
            self._tenant_bytes[e.tenant] = \
                self._tenant_bytes.get(e.tenant, 0) + e.nbytes

    def _drop_bytes_locked(self, e: _Entry):
        self._bytes -= e.nbytes
        if e.tenant is not None:
            left = self._tenant_bytes.get(e.tenant, 0) - e.nbytes
            if left > 0:
                self._tenant_bytes[e.tenant] = left
            else:
                self._tenant_bytes.pop(e.tenant, None)

    def _gauges_locked(self):
        self._registry.gauge("cache.bytes", self._bytes)
        self._registry.gauge("cache.entries", len(self._entries))

    # -- maintenance -------------------------------------------------------

    def invalidate(self, type_name: str | None = None) -> int:
        """Drop entries (one type or all); returns the dropped count.
        Version bumps already make stale entries unreachable — this is
        the explicit memory-reclaim / operator face."""
        with self._lock:
            if type_name is None:
                n = len(self._entries)
                self._entries.clear()
                self._bytes = 0
                self._tenant_bytes.clear()
            else:
                keys = [k for k in self._entries if k[0] == type_name]
                n = len(keys)
                for k in keys:
                    self._drop_bytes_locked(self._entries.pop(k))
            self.invalidations += n
            self._gauges_locked()
        if n:
            self._registry.counter("cache.invalidated_entries", n)
        return n

    def refresh_hot(self, top_k: int = 8) -> int:
        """Re-materialize the hottest stale entries at their type's
        current version (the background refresher's unit). Returns the
        number refreshed."""
        if not self.enabled():
            return 0
        with self._lock:
            hottest = sorted(self._entries.items(),
                             key=lambda kv: kv[1].hits,
                             reverse=True)[:max(int(top_k), 0)]
        n = 0
        for (tn, key), e in hottest:
            version = self._version_fn(tn)
            if e.version == version:
                continue
            try:
                value = e.compute()
                stored = (e.encode(value) if e.encode is not None
                          else value)
            except KeyError:
                # schema dropped under us: reclaim its entries
                self.invalidate(tn)
                continue
            except Exception:
                self._registry.counter("cache.refresh.errors")
                continue
            self._install((tn, key), version, stored, e.compute, e.encode,
                          tenant=e.tenant)
            with self._lock:
                self.refreshes += 1
            self._registry.counter("cache.refreshes")
            n += 1
        return n

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            per_type: dict[str, int] = {}
            for (tn, _), _e in self._entries.items():
                per_type[tn] = per_type.get(tn, 0) + 1
            tenant_bytes = dict(self._tenant_bytes)
            return {"enabled": self.enabled(),
                    "entries": len(self._entries),
                    "bytes": self._bytes,
                    "tenant_bytes": tenant_bytes,
                    "max_bytes": self.max_bytes(),
                    "hits": self.hits,
                    "misses": self.misses,
                    "evictions": self.evictions,
                    "singleflight_waits": self.singleflight_waits,
                    "refreshes": self.refreshes,
                    "invalidations": self.invalidations,
                    "types": per_type}
