"""Canonical plan keys for the materialized pushdown cache.

The cache's identity contract is ``(type_name, plan key, version)``:
two requests share an entry iff their canonical keys match. The key
comes from the re-parseable ECQL stringification in ``filters/ast.py``
(every node's ``__str__`` re-parses to an equal tree, so whitespace,
case, and numeric-literal variants of one filter collapse to one
string) plus the pushdown kind and its parameters.

Each ``*_key`` helper returns ``(filter_ast, key)`` — the caller passes
the normalized AST down to the compute path so the cached compute and
a later recompute evaluate the identical plan (byte-exactness gate).
"""

from __future__ import annotations

from ..filters import ast
from ..filters.ecql import parse_ecql

__all__ = ["canonical_filter", "density_key", "stats_key", "bin_key",
           "arrow_key"]


def canonical_filter(ecql) -> tuple[ast.Filter, str]:
    """Normalize an ECQL filter (string, AST, or None) to
    ``(AST, canonical string)``. ``None`` means match-all (the
    stats/bin surfaces' convention) and canonicalizes to INCLUDE."""
    if ecql is None:
        flt = parse_ecql("INCLUDE")
    elif isinstance(ecql, ast.Filter):
        flt = ecql
    else:
        flt = parse_ecql(str(ecql))
    return flt, str(flt)


def density_key(ecql, bbox, width: int, height: int,
                weight_attr: str | None = None) -> tuple[ast.Filter, str]:
    """Density-surface plan key: filter + bbox + grid shape + weight."""
    flt, fstr = canonical_filter(ecql)
    bb = ",".join(repr(float(v)) for v in bbox)
    return flt, (f"density|{int(width)}x{int(height)}|{bb}"
                 f"|w={weight_attr}|{fstr}")


def stats_key(ecql, stat_spec: str) -> tuple[ast.Filter, str]:
    """Stat-sketch plan key: filter + the stat spec string."""
    flt, fstr = canonical_filter(ecql)
    return flt, f"stats|{str(stat_spec).strip()}|{fstr}"


def bin_key(ecql, track: str | None = None, label: str | None = None,
            sort: bool = False) -> tuple[ast.Filter, str]:
    """BIN-record plan key: filter + track/label columns + sort flag."""
    flt, fstr = canonical_filter(ecql)
    return flt, f"bin|t={track}|l={label}|s={bool(sort)}|{fstr}"


def arrow_key(ecql, sort_by: str | None = None) -> tuple[ast.Filter, str]:
    """Arrow-IPC plan key: filter + sort column."""
    flt, fstr = canonical_filter(ecql)
    return flt, f"arrow|sort={sort_by}|{fstr}"
