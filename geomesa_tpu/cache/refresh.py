"""Background hot-tile refresher (the integrity Scrubber pattern).

After writes advance a type's version, the hottest cached tiles are
stale; steady-state viewers would each pay one cold recompute. The
refresher re-materializes the top-K hottest stale entries on a cadence
(``geomesa.cache.refresh.interval.s``; 0 disables the loop) so the
serving path stays all-hits under sustained writes. ``run_once()`` is
the synchronous unit (tests and operators call it directly).
"""

from __future__ import annotations

import threading
import time

from ..metrics import metrics
from ..utils.properties import SystemProperty

__all__ = ["CacheRefresher", "CACHE_REFRESH_INTERVAL_S",
           "CACHE_REFRESH_TOP_K"]

# refresh cadence (seconds) for the background loop; 0 = off
CACHE_REFRESH_INTERVAL_S = SystemProperty("geomesa.cache.refresh.interval.s",
                                          "0")
# how many of the hottest entries one pass re-materializes
CACHE_REFRESH_TOP_K = SystemProperty("geomesa.cache.refresh.top.k", "8")


class CacheRefresher:
    """Periodic re-materializer for a store's ``result_cache``.

    ``CacheRefresher(store).start()`` refreshes on the knob cadence;
    ``run_once()`` is one synchronous pass."""

    def __init__(self, store=None, cache=None, interval_s: float | None = None,
                 top_k: int | None = None, registry=metrics):
        if cache is None:
            cache = getattr(store, "result_cache", None)
        if cache is None:
            raise ValueError("cache refresher needs a store exposing "
                             "result_cache (or an explicit cache)")
        self.cache = cache
        self.interval_s = float(
            interval_s if interval_s is not None
            else (CACHE_REFRESH_INTERVAL_S.as_float() or 0.0))
        self.top_k = int(top_k if top_k is not None
                         else (CACHE_REFRESH_TOP_K.as_int() or 8))
        self.registry = registry
        self.runs = 0
        self.last_refreshed = 0
        self.last_seconds = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CacheRefresher":
        if self.interval_s <= 0:
            return self  # loop disabled; run_once() stays available
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cache-refresher")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                # a refresh pass must never take the process down
                self.registry.counter("cache.refresh.crashes")

    # -- one pass ----------------------------------------------------------

    def run_once(self) -> dict:
        t0 = time.perf_counter()
        n = self.cache.refresh_hot(self.top_k)
        self.runs += 1
        self.last_refreshed = n
        self.last_seconds = round(time.perf_counter() - t0, 4)
        return {"refreshed": n, "runs": self.runs, "top_k": self.top_k,
                "seconds": self.last_seconds}

    def status(self) -> dict:
        return {"running": bool(self._thread is not None
                                and self._thread.is_alive()),
                "interval_s": self.interval_s,
                "top_k": self.top_k,
                "runs": self.runs,
                "last_refreshed": self.last_refreshed,
                "last_seconds": self.last_seconds}
