"""LSN-keyed materialized pushdown-result cache (cache/ subsystem).

The WAL gives every store an exact version counter
(``Journal.wal.last_lsn``, ``Replica.applied_lsn``, the cluster LSN
vector), so pushdown results — density grids, stats sketches, bin
buffers, arrow IPC payloads — can be memoized *provably* fresh: an
entry is keyed ``(type_name, canonical plan key)`` and stamped with the
type's version at compute time. A write advancing the version makes
stale entries unreachable by key; an unchanged version returns the
memoized payload without touching the device.
"""

from .keys import (arrow_key, bin_key, canonical_filter, density_key,
                   stats_key)
from .refresh import (CACHE_REFRESH_INTERVAL_S, CACHE_REFRESH_TOP_K,
                      CacheRefresher)
from .result_cache import CACHE_ENABLED, CACHE_MAX_BYTES, ResultCache

__all__ = [
    "ResultCache", "CacheRefresher",
    "canonical_filter", "density_key", "stats_key", "bin_key", "arrow_key",
    "CACHE_ENABLED", "CACHE_MAX_BYTES",
    "CACHE_REFRESH_INTERVAL_S", "CACHE_REFRESH_TOP_K",
]
