"""Arrow columnar interchange (geomesa-arrow analog, SURVEY.md 2.3).

Arrow is the host interchange format between the TPU store and external
consumers: query results stream out as dictionary-encoded IPC batches
(SimpleFeatureVector.scala:35 semantics), shard-level partial results
merge with dictionary deltas (io/DeltaWriter.scala:47,203), and Arrow
files are directly queryable (ArrowDataStore).
"""

from .io import (DEFAULT_BATCH_SIZE, FeatureArrowFileReader,
                 FeatureArrowFileWriter, merge_sorted_ipc, read_ipc_batches,
                 sort_batches, write_ipc)
from .scan import ArrowScan, merge_deltas
from .data import ArrowDataStore
from .feature import ArrowFeature
from .vector import (ArrowAttributeReader, ArrowAttributeWriter,
                     ArrowDictionary, SimpleFeatureVector)

__all__ = ["DEFAULT_BATCH_SIZE", "FeatureArrowFileWriter",
           "FeatureArrowFileReader", "write_ipc", "read_ipc_batches",
           "sort_batches", "merge_sorted_ipc", "ArrowScan", "merge_deltas",
           "ArrowDataStore", "ArrowFeature", "SimpleFeatureVector",
           "ArrowDictionary", "ArrowAttributeReader",
           "ArrowAttributeWriter"]
