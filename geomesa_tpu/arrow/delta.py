"""Streaming result plane: incremental Arrow delta batches.

The reference never ships a query result as one monolithic buffer: the
batch scanner pulls fixed-size record batches off the tablet servers
(AccumuloQueryPlan.scala:123-137) and ``DeltaWriter`` encodes each one
against *growing* dictionaries, shipping only the per-batch dictionary
delta (DeltaWriter.scala:47,203). This module is that shape over
pyarrow's IPC **stream** format:

- ``DeltaWriter`` — feed it FeatureBatches, it re-chunks to a fixed
  row count and writes IPC stream messages where string dictionaries
  grow append-only, so pyarrow emits per-batch dictionary *deltas*
  (``emit_dictionary_deltas``) instead of re-shipping the vocabulary
  with every batch.
- ``stream_ipc`` / ``stream_bin`` — generators that encode a
  materialized result one fixed-size slice at a time: the schema (and
  first batch) leave the process before the last slice is encoded, so
  time-to-first-batch is independent of total hits.
- ``iter_ipc`` — the consuming half: decode an IPC stream (or file)
  payload, bytes or file-like, one record batch at a time in bounded
  memory.
- ``merge_sorted_streams`` — k-way merge of pre-sorted batch streams
  on a sort attribute that never materializes more than one in-flight
  batch per source (the streaming replacement for the eager
  ``merge_deltas`` concat-everything path).

Knobs: ``geomesa.stream.batch.rows`` (rows per wire batch, default
8192 — pow2 so downstream padded shape classes land exactly, unlike
the reference's 8096 vector capacity) and
``geomesa.stream.max.inflight.batches`` (producer->consumer queue
depth for streamed scatter legs, cluster/coordinator.py).
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType
from ..utils.properties import SystemProperty
from .io import _empty_col, _schema_meta
from .vector import ArrowDictionary

__all__ = ["DeltaWriter", "STREAM_BATCH_ROWS", "STREAM_MAX_INFLIGHT",
           "ARROW_STREAM_MIME", "stream_ipc", "stream_bin", "iter_ipc",
           "slice_batches", "merge_sorted_streams", "reassemble_ipc",
           "empty_batch"]

# rows per streamed record batch (the fixed vector capacity of the
# wire); 8192 not the reference's 8096 so pow2 shape classes fit
STREAM_BATCH_ROWS = SystemProperty("geomesa.stream.batch.rows", "8192")
# bounded producer->consumer depth for streamed scatter legs: a slow
# consumer backpressures the legs instead of buffering them
STREAM_MAX_INFLIGHT = SystemProperty("geomesa.stream.max.inflight.batches",
                                     "4")

ARROW_STREAM_MIME = "application/vnd.apache.arrow.stream"


def _rows(batch_rows: int | None) -> int:
    if batch_rows is not None:
        return max(int(batch_rows), 1)
    return max(STREAM_BATCH_ROWS.as_int() or 8192, 1)


def empty_batch(sft: SimpleFeatureType) -> FeatureBatch:
    return FeatureBatch.from_dict(
        sft, np.empty(0, dtype=object),
        {a.name: _empty_col(a) for a in sft.attributes})


class DeltaWriter:
    """Incremental Arrow IPC *stream* encoder with per-batch dictionary
    deltas (DeltaWriter.scala:47,203 analog).

    String columns encode against per-attribute ``ArrowDictionary``
    instances that only ever append: every emitted record batch's
    dictionary is a prefix extension of the previous one, so the IPC
    writer ships just the delta values. ``write`` re-chunks input to
    ``batch_rows``; ``flush`` force-emits a partial batch (a stream
    boundary); ``close`` flushes and writes the end-of-stream marker.
    """

    def __init__(self, sink, sft: SimpleFeatureType,
                 batch_rows: int | None = None):
        import pyarrow as pa
        self.sft = sft
        self.batch_rows = _rows(batch_rows)
        self._dicts = {a.name: ArrowDictionary()
                       for a in sft.attributes if a.type.name == "String"}
        probe = empty_batch(sft)
        schema = probe.to_arrow().schema.with_metadata(_schema_meta(sft))
        self._schema = pa.schema(
            [schema.field(i) for i in range(len(schema.names))],
            metadata=schema.metadata)
        self._writer = pa.ipc.new_stream(
            sink, self._schema,
            options=pa.ipc.IpcWriteOptions(emit_dictionary_deltas=True))
        self._pending: FeatureBatch | None = None
        self.batches_written = 0

    def write(self, batch: FeatureBatch | None):
        if batch is None or not batch.n:
            return
        self._pending = (batch if self._pending is None
                         else self._pending.concat(batch))
        while self._pending.n >= self.batch_rows:
            head = self._pending.take(np.arange(self.batch_rows))
            self._pending = self._pending.take(
                np.arange(self.batch_rows, self._pending.n))
            self._emit(head)

    def flush(self):
        """Emit any buffered partial batch now (stream boundary)."""
        if self._pending is not None and self._pending.n:
            head, self._pending = self._pending, None
            self._emit(head)

    def _emit(self, batch: FeatureBatch):
        import pyarrow as pa
        rb = batch.to_arrow()
        if self._dicts:
            arrays = list(rb.columns)
            names = rb.schema.names
            for name, d in self._dicts.items():
                col = batch.columns[name]
                # grow the global dictionary append-only and remap the
                # batch-local codes through it: the IPC writer sees a
                # prefix-extended dictionary and emits only the delta
                vocab = [str(v) for v in col.vocab]
                remap = (np.asarray(d.add_all(vocab), dtype=np.int32)
                         if vocab else np.empty(0, dtype=np.int32))
                null = col.codes < 0
                gcodes = np.zeros(len(col.codes), dtype=np.int32)
                if len(remap):
                    gcodes = remap[np.maximum(col.codes, 0)]
                arrays[names.index(name)] = pa.DictionaryArray.from_arrays(
                    pa.array(gcodes, type=pa.int32(), mask=null),
                    pa.array(d.delta_since(0), type=pa.string()))
            rb = pa.RecordBatch.from_arrays(arrays, names)
        # unify non-dictionary column types with the declared schema
        table = pa.Table.from_batches([rb], schema=None).cast(pa.schema(
            [self._schema.field(i) for i in range(len(self._schema.names))]))
        for rb2 in table.to_batches():
            self._writer.write_batch(rb2)
            self.batches_written += 1

    def close(self):
        self.flush()
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ChunkSink:
    """File-like that buffers writes until drained — lets a generator
    interleave DeltaWriter output with yields."""

    closed = False

    def __init__(self):
        self._parts: list[bytes] = []

    def write(self, data) -> int:
        self._parts.append(bytes(data))
        return len(data)

    def flush(self):
        pass

    def close(self):
        self.closed = True

    def drain(self) -> bytes:
        out = b"".join(self._parts)
        self._parts.clear()
        return out


def slice_batches(batch: FeatureBatch | None,
                  batch_rows: int | None = None) -> Iterator[FeatureBatch]:
    """Slice one materialized batch into fixed-size row windows."""
    rows = _rows(batch_rows)
    n = batch.n if batch is not None else 0
    for start in range(0, n, rows):
        yield batch.take(np.arange(start, min(start + rows, n)))


def stream_ipc(sft: SimpleFeatureType, batch: FeatureBatch | None,
               batch_rows: int | None = None) -> Iterator[bytes]:
    """Encode one result as an IPC stream, yielded chunk-by-chunk: the
    schema preamble first, then one chunk per fixed-size record batch
    (dictionary deltas ride inside). Peak memory is one slice."""
    sink = _ChunkSink()
    w = DeltaWriter(sink, sft, batch_rows)
    head = sink.drain()  # schema message: first bytes on the wire
    if head:
        yield head
    for piece in slice_batches(batch, w.batch_rows):
        w.write(piece)
        w.flush()
        chunk = sink.drain()
        if chunk:
            yield chunk
    w.close()
    tail = sink.drain()  # end-of-stream marker
    if tail:
        yield tail


def stream_bin(sft: SimpleFeatureType, batch: FeatureBatch | None,
               ids=None, track: str | None = None,
               label: str | None = None,
               batch_rows: int | None = None) -> Iterator[bytes]:
    """Encode one result as BIN records (scan/aggregations.py wire
    format), one fixed-size slice of records per chunk."""
    from ..scan.aggregations import encode_bin_batch
    if batch is None or not batch.n:
        return
    all_ids = np.asarray(ids if ids is not None else batch.ids)
    rows = _rows(batch_rows)
    for start in range(0, batch.n, rows):
        idx = np.arange(start, min(start + rows, batch.n))
        yield encode_bin_batch(sft, all_ids[idx], batch.take(idx),
                               track=track, label=label)


def _sft_from_schema(schema, sft: SimpleFeatureType | None):
    if sft is not None:
        return sft
    meta = schema.metadata or {}
    spec = meta.get(b"geomesa.sft.spec")
    if spec is None:
        raise ValueError("no SFT metadata in arrow stream; pass sft=")
    from ..features.sft import parse_spec
    name = meta.get(b"geomesa.sft.name", b"features").decode()
    return parse_spec(name, spec.decode())


def iter_ipc(source, sft: SimpleFeatureType | None = None):
    """Incrementally decode an Arrow IPC payload into FeatureBatches.

    ``source`` is bytes (stream OR file format — shard payloads are
    files, the wire is a stream) or a file-like with ``read`` (an HTTP
    response body, decoded batch-at-a-time in bounded memory). Returns
    ``(sft, iterator)``; the iterator skips empty batches.
    """
    import pyarrow as pa
    if isinstance(source, (bytes, bytearray, memoryview)):
        data = bytes(source)
        if data[:6] == b"ARROW1":
            rd = pa.ipc.open_file(pa.BufferReader(data))
            out_sft = _sft_from_schema(rd.schema, sft)

            def gen_file():
                for i in range(rd.num_record_batches):
                    rb = rd.get_batch(i)
                    if rb.num_rows:
                        yield FeatureBatch.from_arrow(out_sft, rb)
            return out_sft, gen_file()
        source = pa.BufferReader(data)
    rd = pa.ipc.open_stream(source)
    out_sft = _sft_from_schema(rd.schema, sft)

    def gen_stream():
        for rb in rd:
            if rb.num_rows:
                yield FeatureBatch.from_arrow(out_sft, rb)
    return out_sft, gen_stream()


def reassemble_ipc(sft: SimpleFeatureType,
                   batches: Iterable[FeatureBatch]) -> bytes:
    """Rebuild the materialized IPC *file* payload from streamed
    batches — byte-identical to ``write_ipc`` of the same rows at the
    same version (the bench 14 reconstruction gate)."""
    from .io import write_ipc
    parts = [b for b in batches if b is not None and b.n]
    if not parts:
        return write_ipc(sft, empty_batch(sft))
    merged = parts[0] if len(parts) == 1 else FeatureBatch.concat_all(parts)
    return write_ipc(sft, merged)


# -- streaming k-way sort-merge ---------------------------------------------


def _merge_keys(batch: FeatureBatch, sort_by: str) -> np.ndarray:
    """Cross-source-comparable sort keys for one batch: millis for
    dates, values for numerics, decoded strings for dictionary columns
    (codes are only ordered within one vocab)."""
    col = batch.columns[sort_by]
    millis = getattr(col, "millis", None)
    if millis is not None:
        return np.asarray(millis)
    codes = getattr(col, "codes", None)
    if codes is not None:
        vocab = col.vocab.astype(str)
        vals = (vocab[np.maximum(codes, 0)] if len(vocab)
                else np.full(len(codes), "", dtype=str))
        # nulls sort last (store/common.sort_order convention)
        return np.where(codes >= 0, vals, "\U0010ffff")
    vals = np.asarray(col.values)
    if vals.dtype.kind == "f":
        # Null Double/Float is stored as NaN. sort_order argsorts
        # ascending (NaN last) and reverses for descending, so NaN
        # behaves like +inf in both directions — but a raw NaN key
        # poisons the merge bound (every comparison is False and no
        # cursor can advance). Substitute +inf to keep bounds total.
        vals = np.where(np.isnan(vals), np.inf, vals)
    return vals


def _stable_order(keys: np.ndarray, reverse: bool) -> np.ndarray:
    if not reverse:
        return np.argsort(keys, kind="stable")
    # stable descending: stable-sort the reversed array, map back
    rev = np.argsort(keys[::-1], kind="stable")
    return (len(keys) - 1 - rev)[::-1]


class _Cursor:
    """One merge source: the current batch, its keys, and a read
    position. At most one batch is resident per source."""

    __slots__ = ("it", "batch", "keys", "pos")

    def __init__(self, it):
        self.it = it
        self.batch: FeatureBatch | None = None
        self.keys: np.ndarray | None = None
        self.pos = 0

    def pull(self, sort_by: str | None) -> bool:
        for batch in self.it:
            if batch is None or not batch.n:
                continue
            self.batch = batch
            self.keys = (_merge_keys(batch, sort_by)
                         if sort_by is not None else None)
            self.pos = 0
            return True
        self.batch = None
        return False


def merge_sorted_streams(sources, sort_by: str | None,
                         reverse: bool = False,
                         batch_rows: int | None = None
                         ) -> Iterator[FeatureBatch]:
    """K-way merge of pre-sorted FeatureBatch streams on ``sort_by``
    without materializing any source (the streaming replacement for
    the eager concat-then-sort ``merge_deltas`` reduce).

    Each round emits every row whose key is provably final: rows up to
    the minimum (maximum, for ``reverse``) of the sources' current
    last keys — any future row from any source sorts at or after that
    bound, because each source stream is itself sorted. ``sort_by``
    None concatenates the streams in source order (no merge keys)."""
    rows = _rows(batch_rows)
    cursors = [c for c in (_Cursor(iter(s)) for s in sources)
               if c.pull(sort_by)]
    pending: FeatureBatch | None = None

    def chunks(batch, final=False):
        nonlocal pending
        if batch is not None and batch.n:
            pending = batch if pending is None else pending.concat(batch)
        while pending is not None and pending.n >= rows:
            head = pending.take(np.arange(rows))
            pending = pending.take(np.arange(rows, pending.n))
            yield head
        if final and pending is not None and pending.n:
            head, pending = pending, None
            yield head

    if sort_by is None:
        for c in cursors:
            more = True
            while more:
                tail = (c.batch if c.pos == 0
                        else c.batch.take(np.arange(c.pos, c.batch.n)))
                yield from chunks(tail)
                more = c.pull(None)
        yield from chunks(None, final=True)
        return

    while cursors:
        if len(cursors) == 1:
            # single live source: pass its batches straight through
            c = cursors[0]
            more = True
            while more:
                tail = (c.batch if c.pos == 0
                        else c.batch.take(np.arange(c.pos, c.batch.n)))
                yield from chunks(tail)
                more = c.pull(sort_by)
            break
        bound = (min if not reverse else max)(
            c.keys[-1] for c in cursors)
        parts: list[FeatureBatch] = []
        keys: list[np.ndarray] = []
        for c in list(cursors):
            k = c.keys[c.pos:]
            take_n = int(np.count_nonzero(
                k <= bound if not reverse else k >= bound))
            if take_n:
                idx = np.arange(c.pos, c.pos + take_n)
                parts.append(c.batch.take(idx))
                keys.append(k[:take_n])
                c.pos += take_n
            if c.pos >= c.batch.n and not c.pull(sort_by):
                cursors.remove(c)
        if not parts:
            continue
        window = (parts[0] if len(parts) == 1
                  else FeatureBatch.concat_all(parts))
        order = _stable_order(np.concatenate(keys), reverse)
        yield from chunks(window.take(order))
    yield from chunks(None, final=True)
