"""ArrowFeature: zero-copy feature facade over a pyarrow RecordBatch row
(arrow/vector/ArrowSimpleFeature analog) — attribute reads index the
Arrow vectors directly without materializing python rows.
"""

from __future__ import annotations

from ..features.sft import SimpleFeatureType
from ..geometry import Point

__all__ = ["ArrowFeature"]


class ArrowFeature:
    def __init__(self, sft: SimpleFeatureType, rb, row: int):
        self._sft = sft
        self._rb = rb
        self._row = row

    @property
    def id(self) -> str:
        return self._rb.column("__fid__")[self._row].as_py()

    def get(self, name: str):
        a = self._sft.attr(name)
        col = self._rb.column(name)
        v = col[self._row]
        if not v.is_valid:
            return None
        if a.type.name == "Point":
            d = v.as_py()
            return Point(d["x"], d["y"])
        if a.type.is_geometry:
            from ..geometry.wkt import parse_wkt
            return parse_wkt(v.as_py())
        if a.type.name == "Date":
            import numpy as np
            return int(np.datetime64(v.as_py(), "ms").astype(np.int64))
        return v.as_py()

    def as_dict(self) -> dict:
        out = {"id": self.id}
        for a in self._sft.attributes:
            out[a.name] = self.get(a.name)
        return out
