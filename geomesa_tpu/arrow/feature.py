"""ArrowFeature: zero-copy feature facade over a pyarrow RecordBatch row
(arrow/vector/ArrowSimpleFeature analog) — attribute reads index the
Arrow vectors directly without materializing python rows.
"""

from __future__ import annotations

from ..features.sft import SimpleFeatureType
from ..geometry import Point

__all__ = ["ArrowFeature"]


class ArrowFeature:
    def __init__(self, sft: SimpleFeatureType, rb, row: int):
        self._sft = sft
        self._rb = rb
        self._row = row

    @property
    def id(self) -> str:
        return self._rb.column("__fid__")[self._row].as_py()

    def get(self, name: str):
        # ONE decode implementation for every layout: the typed reader
        # (arrow/vector.py); a second copy here would drift
        from .vector import ArrowAttributeReader
        return ArrowAttributeReader(
            name, self._rb.column(name),
            attr=self._sft.attr(name)).apply(self._row)

    def as_dict(self) -> dict:
        out = {"id": self.id}
        for a in self._sft.attributes:
            out[a.name] = self.get(a.name)
        return out
