"""ArrowScan: Arrow-encoded query results + distributed dictionary-delta
merge.

The reference runs ArrowScan inside the database servers — each
tablet/region emits dictionary-encoded record batches whose dictionaries
are *local deltas*, merged client-side (index-api ArrowScan:34 +
arrow/io/DeltaWriter.scala:47,203). Here each mesh shard produces an
IPC payload with shard-local dictionaries; ``merge_deltas`` unifies the
dictionaries and re-encodes codes — pure host-side numpy (planner-time
cost, not scan-time).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from ..features.batch import FeatureBatch, StringColumn
from ..features.sft import SimpleFeatureType
from .io import read_ipc_batches, sort_batches, write_ipc

__all__ = ["ArrowScan", "merge_deltas"]


class ArrowScan:
    """Produce Arrow IPC bytes from a query over a datastore.

    Usage mirrors the ARROW_ENCODE query-hint path
    (AccumuloIndexAdapter.scanConfig arrow branch):

        payload = ArrowScan(store).execute(type_name, ecql,
                                           sort_by="dtg")
    """

    def __init__(self, store):
        self.store = store

    def execute(self, type_name: str, ecql: str = "INCLUDE",
                sort_by: str | None = None, reverse: bool = False,
                batch_size: int | None = None) -> bytes:
        from ..index.api import Query
        res = self.store.query(Query(type_name, ecql))
        sft = self.store.get_schema(type_name)
        batch = res.batch
        if batch is None:
            batch = FeatureBatch.from_dict(
                sft, np.empty(0, dtype=object),
                {a.name: ((np.empty(0), np.empty(0))
                          if a.type.name == "Point" else [])
                 for a in sft.attributes})
        if sort_by:
            batch = sort_batches(batch, sort_by, reverse)
        kw = {} if batch_size is None else {"batch_size": batch_size}
        return write_ipc(sft, batch, **kw)


def merge_deltas(payloads: Sequence[bytes],
                 sft: SimpleFeatureType | None = None,
                 sort_by: str | None = None) -> bytes:
    """Merge shard-local IPC payloads into one payload with unified
    dictionaries (DeltaWriter.reduce analog).

    Each payload's string columns carry their own vocab; FeatureBatch
    decoding re-dictionary-encodes on concat, so the merged file has one
    global dictionary per column.
    """
    merged = None
    out_sft = sft
    for p in payloads:
        s, b = read_ipc_batches(p, sft)
        out_sft = out_sft or s
        if b is None:
            continue
        merged = b if merged is None else merged.concat(b)
    if out_sft is None:
        raise ValueError("no payloads")
    if merged is None:
        return write_ipc(out_sft, FeatureBatch.from_dict(
            out_sft, np.empty(0, dtype=object),
            {a.name: ((np.empty(0), np.empty(0))
                      if a.type.name == "Point" else [])
             for a in out_sft.attributes}))
    if sort_by:
        merged = sort_batches(merged, sort_by)
    return write_ipc(out_sft, merged)
