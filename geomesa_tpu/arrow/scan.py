"""ArrowScan: Arrow-encoded query results + distributed dictionary-delta
merge.

The reference runs ArrowScan inside the database servers — each
tablet/region emits dictionary-encoded record batches whose dictionaries
are *local deltas*, merged client-side (index-api ArrowScan:34 +
arrow/io/DeltaWriter.scala:47,203). Here each mesh shard produces an
IPC payload with shard-local dictionaries; ``merge_deltas`` unifies the
dictionaries and re-encodes codes — pure host-side numpy (planner-time
cost, not scan-time).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from ..features.batch import FeatureBatch, StringColumn
from ..features.sft import SimpleFeatureType
from .io import read_ipc_batches, sort_batches, write_ipc

__all__ = ["ArrowScan", "merge_deltas"]


class ArrowScan:
    """Produce Arrow IPC bytes from a query over a datastore.

    Usage mirrors the ARROW_ENCODE query-hint path
    (AccumuloIndexAdapter.scanConfig arrow branch):

        payload = ArrowScan(store).execute(type_name, ecql,
                                           sort_by="dtg")
    """

    def __init__(self, store):
        self.store = store

    def execute(self, type_name: str, ecql: str = "INCLUDE",
                sort_by: str | None = None, reverse: bool = False,
                batch_size: int | None = None) -> bytes:
        from ..index.api import Query
        res = self.store.query(Query(type_name, ecql))
        sft = self.store.get_schema(type_name)
        batch = res.batch
        if batch is None:
            batch = FeatureBatch.from_dict(
                sft, np.empty(0, dtype=object),
                {a.name: ((np.empty(0), np.empty(0))
                          if a.type.name == "Point" else [])
                 for a in sft.attributes})
        if sort_by:
            batch = sort_batches(batch, sort_by, reverse)
        kw = {} if batch_size is None else {"batch_size": batch_size}
        return write_ipc(sft, batch, **kw)


def merge_deltas(payloads: Sequence[bytes],
                 sft: SimpleFeatureType | None = None,
                 sort_by: str | None = None,
                 presorted: bool = False) -> bytes:
    """Merge shard-local IPC payloads into one payload with unified
    dictionaries (DeltaWriter.reduce analog) — as a *stream*: payloads
    decode batch-at-a-time and feed an incremental writer, never
    concatenating the full result set (the old eager reduce held every
    shard's rows at once).

    ``presorted`` declares each payload already sorted on ``sort_by``
    (the mesh shards emit sorted payloads): the reduce is then a k-way
    streaming merge holding one in-flight batch per payload. Without
    it, each payload is sorted individually as it is first pulled —
    still never the union.
    """
    from .delta import (empty_batch, iter_ipc, merge_sorted_streams,
                        slice_batches)
    sources = []
    out_sft = sft
    for p in payloads:
        s, it = iter_ipc(p, sft)
        out_sft = out_sft or s
        sources.append(it)
    if out_sft is None:
        raise ValueError("no payloads")
    if sort_by and not presorted:
        def _sorted(it):
            parts = [b for b in it if b.n]
            if parts:
                whole = (parts[0] if len(parts) == 1
                         else FeatureBatch.concat_all(parts))
                yield from slice_batches(sort_batches(whole, sort_by))
        sources = [_sorted(it) for it in sources]
    merged = merge_sorted_streams(sources, sort_by or None)
    import io as _io
    sink = _io.BytesIO()
    from .io import FeatureArrowFileWriter
    wrote = False
    with FeatureArrowFileWriter(sink, out_sft) as w:
        for b in merged:
            w.write(b)
            wrote = True
    if not wrote:
        return write_ipc(out_sft, empty_batch(out_sft))
    return sink.getvalue()
