"""Arrow IPC file IO for feature batches.

Analog of the reference's SimpleFeatureArrowFileWriter/Reader and
SimpleFeatureArrowIO sort/merge (geomesa-arrow/.../io/): feature batches
stream to the Arrow IPC file format in fixed-capacity vectors
(SimpleFeatureVector.scala:98 defaults to 8,096 features per batch),
and sorted batch streams merge k-way on a sort attribute.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType

DEFAULT_BATCH_SIZE = 8096  # SimpleFeatureVector.scala:98

__all__ = ["DEFAULT_BATCH_SIZE", "FeatureArrowFileWriter",
           "FeatureArrowFileReader", "write_ipc", "read_ipc_batches",
           "sort_batches", "merge_sorted_ipc"]


def _schema_meta(sft: SimpleFeatureType) -> dict:
    return {b"geomesa.sft.name": sft.type_name.encode(),
            b"geomesa.sft.spec": sft.to_spec().encode()}


class FeatureArrowFileWriter:
    """Stream FeatureBatches to an Arrow IPC file, re-chunked to a fixed
    vector capacity; SFT name/spec ride in the schema metadata so the
    file is self-describing.

    The IPC *file* format allows exactly one dictionary per field
    (no deltas/replacement), but incremental feeds — the streaming
    scatter merges — hand this writer chunks whose vocabularies differ.
    String columns therefore encode against a per-attribute global
    ``ArrowDictionary`` that only appends; encoded batches buffer until
    ``close``, when every batch is emitted against the one final
    dictionary (valid for all of them, since each batch's codes index a
    prefix). A single-vocabulary feed produces byte-identical output to
    the old direct-write path.

    Memory: when the SFT has String attributes, the whole encoded
    result is held until ``close`` (the file format's one-dictionary
    rule forces it) — so file-format sinks fed by a streaming reduce
    are *not* constant-memory; only the IPC stream format
    (``arrow/delta.DeltaWriter``) is. Schemas with no String columns
    need no dictionary and write through batch by batch."""

    def __init__(self, sink, sft: SimpleFeatureType,
                 batch_size: int = DEFAULT_BATCH_SIZE):
        import pyarrow as pa
        self.sft = sft
        self.batch_size = batch_size
        self._pending: FeatureBatch | None = None
        probe = FeatureBatch.from_dict(
            sft, np.empty(0, dtype=object),
            {a.name: _empty_col(a) for a in sft.attributes})
        schema = probe.to_arrow().schema.with_metadata(_schema_meta(sft))
        self._writer = pa.ipc.new_file(sink, schema)
        self._schema = schema
        from .vector import ArrowDictionary
        self._dicts = {a.name: ArrowDictionary()
                       for a in sft.attributes if a.type.name == "String"}
        # (record batch, {string col -> (global codes, null mask)})
        self._buffered: list = []

    def write(self, batch: FeatureBatch):
        self._pending = (batch if self._pending is None
                         else self._pending.concat(batch))
        while self._pending.n >= self.batch_size:
            head = self._pending.take(np.arange(self.batch_size))
            self._pending = self._pending.take(
                np.arange(self.batch_size, self._pending.n))
            self._flush(head)

    def _flush(self, batch: FeatureBatch):
        import pyarrow as pa
        rb = batch.to_arrow()
        # unify non-dictionary column types with the declared schema
        table = pa.Table.from_batches([rb]).cast(pa.schema(
            [self._schema.field(i) for i in range(len(self._schema.names))]))
        if not self._dicts:
            # no string columns → no dictionary to finalize: write
            # through directly instead of buffering until close
            for rb2 in table.to_batches():
                self._writer.write_batch(rb2)
            return
        recodes = {}
        for name, d in self._dicts.items():
            col = batch.columns[name]
            vocab = [str(v) for v in col.vocab]
            remap = (np.asarray(d.add_all(vocab), dtype=np.int32)
                     if vocab else np.empty(0, dtype=np.int32))
            null = col.codes < 0
            gcodes = np.zeros(len(col.codes), dtype=np.int32)
            if len(remap):
                gcodes = remap[np.maximum(col.codes, 0)]
            recodes[name] = (gcodes, null)
        for rb2 in table.to_batches():
            self._buffered.append((rb2, recodes))

    def close(self):
        import pyarrow as pa
        if self._pending is not None and self._pending.n:
            self._flush(self._pending)
            self._pending = None
        finals = {name: pa.array(d.delta_since(0), type=pa.string())
                  for name, d in self._dicts.items()}
        names = [self._schema.field(i).name
                 for i in range(len(self._schema.names))]
        for rb, recodes in self._buffered:
            if recodes:
                arrays = list(rb.columns)
                for name, (gcodes, null) in recodes.items():
                    arrays[names.index(name)] = \
                        pa.DictionaryArray.from_arrays(
                            pa.array(gcodes, type=pa.int32(), mask=null),
                            finals[name])
                rb = pa.RecordBatch.from_arrays(arrays, names)
            self._writer.write_batch(rb)
        self._buffered.clear()
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _empty_col(a):
    if a.type.name == "Point":
        return (np.empty(0), np.empty(0))
    return []


class FeatureArrowFileReader:
    """Read an IPC feature file; recovers the SFT from metadata."""

    def __init__(self, source, sft: SimpleFeatureType | None = None):
        import pyarrow as pa
        self._reader = pa.ipc.open_file(source)
        meta = self._reader.schema.metadata or {}
        if sft is None:
            from ..features.sft import parse_spec
            name = meta.get(b"geomesa.sft.name", b"features").decode()
            spec = meta.get(b"geomesa.sft.spec")
            if spec is None:
                raise ValueError("no SFT metadata in arrow file; pass sft=")
            sft = parse_spec(name, spec.decode())
        self.sft = sft

    @property
    def num_batches(self) -> int:
        return self._reader.num_record_batches

    def batches(self) -> Iterator[FeatureBatch]:
        for i in range(self._reader.num_record_batches):
            yield FeatureBatch.from_arrow(self.sft,
                                          self._reader.get_batch(i))

    def read_all(self) -> FeatureBatch:
        out = None
        for b in self.batches():
            out = b if out is None else out.concat(b)
        if out is None:
            raise ValueError("empty arrow file")
        return out


def write_ipc(sft: SimpleFeatureType, batch: FeatureBatch,
              batch_size: int = DEFAULT_BATCH_SIZE) -> bytes:
    """Encode one batch as Arrow IPC file bytes."""
    import io as _io
    sink = _io.BytesIO()
    with FeatureArrowFileWriter(sink, sft, batch_size) as w:
        if batch.n:
            w.write(batch)
    return sink.getvalue()


def read_ipc_batches(data: bytes,
                     sft: SimpleFeatureType | None = None):
    """Decode IPC file bytes -> (sft, FeatureBatch or None)."""
    import io as _io
    r = FeatureArrowFileReader(_io.BytesIO(data), sft)
    out = None
    for b in r.batches():
        out = b if out is None else out.concat(b)
    return r.sft, out


def sort_batches(batch: FeatureBatch, sort_by: str,
                 reverse: bool = False) -> FeatureBatch:
    """Sort a batch by an attribute (SimpleFeatureArrowIO sort)."""
    col = batch.columns[sort_by]
    if hasattr(col, "millis"):
        keys = col.millis
    elif hasattr(col, "codes"):
        keys = col.codes
    else:
        keys = col.values  # type: ignore[union-attr]
    order = np.argsort(keys, kind="stable")
    if reverse:
        order = order[::-1]
    return batch.take(order)


def merge_sorted_ipc(payloads: Iterable[bytes], sort_by: str,
                     reverse: bool = False,
                     sft: SimpleFeatureType | None = None) -> bytes:
    """K-way merge of sorted shard payloads into one sorted IPC file
    (the reduce step of ArrowScan / SimpleFeatureArrowIO.sort).

    Payloads must each be pre-sorted on ``sort_by``; the merge streams
    batch-at-a-time (arrow/delta.merge_sorted_streams) rather than
    concatenating the union. ``reverse`` requires descending payloads.
    """
    from .delta import iter_ipc, merge_sorted_streams
    import io as _io
    sources = []
    out_sft = sft
    for p in payloads:
        s, it = iter_ipc(p, sft)
        out_sft = out_sft or s
        sources.append(it)
    if out_sft is None:
        raise ValueError("no payloads to merge")
    sink = _io.BytesIO()
    wrote = False
    with FeatureArrowFileWriter(sink, out_sft) as w:
        for b in merge_sorted_streams(sources, sort_by, reverse=reverse):
            w.write(b)
            wrote = True
    if not wrote:
        return write_ipc(out_sft,
                         FeatureBatch.from_dict(
                             out_sft, np.empty(0, dtype=object),
                             {a.name: _empty_col(a)
                              for a in out_sft.attributes}))
    return sink.getvalue()
