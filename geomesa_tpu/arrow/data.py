"""ArrowDataStore: queryable Arrow IPC files (arrow/data/ArrowDataStore.scala
analog). An Arrow file (written by FeatureArrowFileWriter or any producer
following the SFT-metadata convention) loads into the in-memory TPU store
and serves the full query surface; writes append via re-encode.
"""

from __future__ import annotations

import os

import numpy as np

from ..features.batch import FeatureBatch
from ..index.api import Query
from .io import FeatureArrowFileReader, FeatureArrowFileWriter

__all__ = ["ArrowDataStore"]


class ArrowDataStore:
    def __init__(self, path: str):
        self.path = path
        self._mem = None
        self._sft = None

    # -- schema ------------------------------------------------------------

    def get_schema(self):
        self._ensure()
        return self._sft

    def create_schema(self, sft):
        """Initialize an empty arrow file for the type."""
        with open(self.path, "wb") as fh:
            FeatureArrowFileWriter(fh, sft).close()
        self._mem, self._sft = None, None

    # -- io ---------------------------------------------------------------

    def _ensure(self):
        if self._mem is not None:
            return
        from ..store.memory import InMemoryDataStore
        with open(self.path, "rb") as fh:
            r = FeatureArrowFileReader(fh)
            self._sft = r.sft
            mem = InMemoryDataStore()
            mem.create_schema(r.sft)
            for b in r.batches():
                mem.write(r.sft.type_name, b)
        self._mem = mem

    def write(self, batch: FeatureBatch):
        """Append features (rewrites the file — arrow files are immutable
        once sealed, matching the reference's append-by-rewrite)."""
        self._ensure()
        self._mem.write(self._sft.type_name, batch)
        res = self._mem.query(Query(self._sft.type_name, "INCLUDE"))
        with open(self.path, "wb") as fh:
            w = FeatureArrowFileWriter(fh, self._sft)
            if res.batch is not None and res.batch.n:
                w.write(res.batch)
            w.close()

    # -- queries -----------------------------------------------------------

    def query(self, ecql: str = "INCLUDE", **kw):
        self._ensure()
        return self._mem.query(Query(self._sft.type_name, ecql), **kw)

    def count(self) -> int:
        self._ensure()
        return self._mem.count(self._sft.type_name)

    @property
    def exists(self) -> bool:
        return os.path.exists(self.path)
