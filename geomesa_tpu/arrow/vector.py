"""Typed per-attribute Arrow vectors (SimpleFeatureVector analog).

The reference's core Arrow abstraction is a fixed-capacity vector of
features with one typed reader/writer per attribute
(geomesa-arrow/.../vector/SimpleFeatureVector.scala:35-93,
ArrowAttributeReader/Writer, ArrowDictionary.scala:133): points store
as fixed-size-list doubles with configurable precision, strings
dictionary-encode against explicit dictionaries that can grow in
deltas, and features read zero-copy through a facade over the vectors.

This is the same surface over pyarrow: ``SimpleFeatureVector`` owns a
set of ``ArrowAttributeWriter``s (or wraps a RecordBatch with
``ArrowAttributeReader``s), ``ArrowDictionary`` carries the explicit
value <-> code mapping with delta growth, and ``ArrowFeature``
(arrow/feature.py) stays the zero-copy row facade.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..features.sft import SimpleFeatureType
from ..geometry import Geometry, Point

__all__ = ["ArrowDictionary", "ArrowAttributeWriter",
           "ArrowAttributeReader", "SimpleFeatureVector",
           "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 8096  # SimpleFeatureVector.scala:98


class ArrowDictionary:
    """Explicit dictionary: value <-> code with delta growth
    (ArrowDictionary.scala:133 — dictionaries are immutable snapshots
    on the wire; deltas append new values without re-coding old ones).
    """

    def __init__(self, values=()):
        self._values: list = []
        self._codes: dict = {}
        self.add_all(values)

    def __len__(self) -> int:
        return len(self._values)

    @property
    def values(self) -> list:
        return list(self._values)

    def code(self, value) -> int:
        """Code for value, growing the dictionary when unseen."""
        c = self._codes.get(value)
        if c is None:
            c = len(self._values)
            self._values.append(value)
            self._codes[value] = c
        return c

    def lookup(self, value) -> int:
        """Code for value or -1 (no growth) — the read-side probe."""
        return self._codes.get(value, -1)

    def value(self, code: int):
        return self._values[code]

    def add_all(self, values) -> list:
        return [self.code(v) for v in values]

    def delta_since(self, n: int) -> list:
        """Values appended after the first ``n`` (the wire delta)."""
        return self._values[n:]


# -- typed writers ---------------------------------------------------------

class ArrowAttributeWriter:
    """One attribute's typed write surface into a fixed-capacity
    vector; ``apply(i, value)`` then ``to_arrow()``."""

    def __init__(self, name: str, capacity: int):
        self.name = name
        self.capacity = capacity

    def apply(self, i: int, value) -> None:
        raise NotImplementedError

    def to_arrow(self, n: int):
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class _NumericWriter(ArrowAttributeWriter):
    np_dtype: Any = np.float64

    def __init__(self, name: str, capacity: int):
        super().__init__(name, capacity)
        self._vals = np.zeros(capacity, dtype=self.np_dtype)
        self._valid = np.zeros(capacity, dtype=bool)

    def apply(self, i: int, value) -> None:
        if value is None:
            self._valid[i] = False
        else:
            self._vals[i] = value
            self._valid[i] = True

    def to_arrow(self, n: int):
        import pyarrow as pa
        # COPY: pyarrow zero-copies numeric numpy buffers, and the
        # writer's buffers keep mutating after unload()
        return pa.array(self._vals[:n].copy(), mask=~self._valid[:n])

    def reset(self) -> None:
        self._valid[:] = False


class IntWriter(_NumericWriter):
    np_dtype = np.int32


class LongWriter(_NumericWriter):
    np_dtype = np.int64


class FloatWriter(_NumericWriter):
    np_dtype = np.float32


class DoubleWriter(_NumericWriter):
    np_dtype = np.float64


class BooleanWriter(_NumericWriter):
    np_dtype = np.bool_


class DateWriter(_NumericWriter):
    """Epoch millis as timestamp[ms]."""
    np_dtype = np.int64

    def to_arrow(self, n: int):
        import pyarrow as pa
        return pa.array(self._vals[:n].copy(), mask=~self._valid[:n],
                        type=pa.timestamp("ms"))


class StringWriter(ArrowAttributeWriter):
    """Dictionary-encoded strings against an EXPLICIT (shareable,
    delta-growable) ArrowDictionary."""

    def __init__(self, name: str, capacity: int,
                 dictionary: ArrowDictionary | None = None):
        super().__init__(name, capacity)
        self.dictionary = dictionary if dictionary is not None \
            else ArrowDictionary()
        self._codes = np.full(capacity, -1, dtype=np.int32)

    def apply(self, i: int, value) -> None:
        self._codes[i] = -1 if value is None \
            else self.dictionary.code(str(value))

    def to_arrow(self, n: int):
        import pyarrow as pa
        codes = self._codes[:n].copy()  # buffers mutate after unload
        return pa.DictionaryArray.from_arrays(
            pa.array(codes, mask=codes < 0, type=pa.int32()),
            pa.array(self.dictionary.values, type=pa.string()))

    def reset(self) -> None:
        self._codes[:] = -1


class PointWriter(ArrowAttributeWriter):
    """Points as a fixed-size-list of 2 floats; ``precision`` selects
    f32 or f64 storage (the reference's precision-configurable point
    vectors)."""

    def __init__(self, name: str, capacity: int, precision: str = "f64"):
        super().__init__(name, capacity)
        if precision not in ("f32", "f64"):
            raise ValueError("precision must be 'f32' or 'f64'")
        self.precision = precision
        dt = np.float32 if precision == "f32" else np.float64
        self._xy = np.full((capacity, 2), np.nan, dtype=dt)

    def apply(self, i: int, value) -> None:
        if value is None:
            self._xy[i] = np.nan
        elif isinstance(value, Point):
            self._xy[i, 0] = value.x
            self._xy[i, 1] = value.y
        else:
            self._xy[i, 0], self._xy[i, 1] = value

    def to_arrow(self, n: int):
        import pyarrow as pa
        dt = pa.float32() if self.precision == "f32" else pa.float64()
        flat = pa.array(self._xy[:n].copy().ravel(), type=dt)
        return pa.FixedSizeListArray.from_arrays(flat, 2)

    def reset(self) -> None:
        self._xy[:] = np.nan


class GeometryWriter(ArrowAttributeWriter):
    """Arbitrary geometries as WKB binary."""

    def __init__(self, name: str, capacity: int):
        super().__init__(name, capacity)
        self._wkb: list = [None] * capacity

    def apply(self, i: int, value) -> None:
        from ..geometry.wkb import to_wkb
        self._wkb[i] = None if value is None else (
            to_wkb(value) if isinstance(value, Geometry) else bytes(value))

    def to_arrow(self, n: int):
        import pyarrow as pa
        return pa.array(list(self._wkb[:n]), type=pa.binary())

    def reset(self) -> None:
        self._wkb = [None] * self.capacity


_WRITERS = {
    "Integer": IntWriter,
    "Long": LongWriter,
    "Float": FloatWriter,
    "Double": DoubleWriter,
    "Boolean": BooleanWriter,
    "Date": DateWriter,
    "String": StringWriter,
    "Point": PointWriter,
}


def writer_for(attr, capacity: int, precision: str = "f64",
               dictionaries: dict | None = None) -> ArrowAttributeWriter:
    t = attr.type.name
    if t == "String":
        d = (dictionaries or {}).get(attr.name)
        return StringWriter(attr.name, capacity, d)
    if t == "Point":
        return PointWriter(attr.name, capacity, precision)
    cls = _WRITERS.get(t)
    if cls is not None:
        return cls(attr.name, capacity)
    if getattr(attr.type, "is_geometry", False):
        return GeometryWriter(attr.name, capacity)
    raise ValueError(f"no Arrow vector writer for attribute type "
                     f"{t!r} ({attr.name!r})")


# -- typed readers ---------------------------------------------------------

class ArrowAttributeReader:
    """One attribute's typed read surface over an arrow array — THE
    decode logic for every supported layout (ArrowFeature delegates
    here; there must never be a second copy to drift).

    Layouts: fixed-size-list [x, y] and struct {"x", "y"} points, WKB
    binary and WKT string geometries, timestamp[ms] dates, dictionary
    strings, plain scalars. ``attr`` (an SFT attribute) disambiguates
    WKT geometry strings from plain strings."""

    def __init__(self, name: str, arr, attr=None):
        self.name = name
        self.arr = arr
        self.attr = attr

    def apply(self, i: int):
        import pyarrow as pa
        v = self.arr[i]
        if not v.is_valid:
            return None
        t = self.arr.type
        if pa.types.is_fixed_size_list(t):
            xy = v.as_py()
            return None if xy is None or any(
                x is None or x != x for x in xy) else Point(*xy)
        if pa.types.is_struct(t):
            d = v.as_py()
            if d is None or d.get("x") is None:
                return None
            x, y = d["x"], d["y"]
            return None if x != x or y != y else Point(x, y)
        if pa.types.is_binary(t):
            from ..geometry.wkb import from_wkb
            return from_wkb(v.as_py())
        if pa.types.is_timestamp(t):
            return int(v.value)
        if (self.attr is not None
                and getattr(self.attr.type, "is_geometry", False)
                and (pa.types.is_string(t)
                     or (pa.types.is_dictionary(t)
                         and pa.types.is_string(t.value_type)))):
            from ..geometry.wkt import parse_wkt
            return parse_wkt(v.as_py())
        return v.as_py()

    def __len__(self):
        return len(self.arr)


class SimpleFeatureVector:
    """Fixed-capacity vector of features with typed per-attribute
    readers/writers (SimpleFeatureVector.scala:35-93).

    Write side::

        v = SimpleFeatureVector.create(sft, capacity=1024)
        v.set(0, "fid0", {"name": "x", "geom": Point(1, 2)})
        rb = v.unload()         # pyarrow RecordBatch (n = writes)

    Read side::

        v = SimpleFeatureVector.wrap(sft, rb)
        v.reader("name").apply(0)
        v.feature(0)            # zero-copy row facade
    """

    def __init__(self, sft: SimpleFeatureType, capacity: int,
                 writers=None, batch=None):
        self.sft = sft
        self.capacity = capacity
        self._writers = writers
        self._ids = ([None] * capacity) if writers is not None else None
        self._n = 0
        self._batch = batch
        self._readers: dict[str, ArrowAttributeReader] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, sft: SimpleFeatureType,
               capacity: int = DEFAULT_CAPACITY, precision: str = "f64",
               dictionaries: dict | None = None) -> "SimpleFeatureVector":
        writers = {a.name: writer_for(a, capacity, precision,
                                      dictionaries)
                   for a in sft.attributes}
        return cls(sft, capacity, writers=writers)

    @classmethod
    def wrap(cls, sft: SimpleFeatureType, batch) -> "SimpleFeatureVector":
        return cls(sft, batch.num_rows, batch=batch)

    # -- write side --------------------------------------------------------

    def writer(self, name: str) -> ArrowAttributeWriter:
        return self._writers[name]

    def set(self, i: int, fid: str, values: dict) -> None:
        if i >= self.capacity:
            raise IndexError("vector capacity exceeded")
        self._ids[i] = str(fid)
        for name, w in self._writers.items():
            w.apply(i, values.get(name))
        self._n = max(self._n, i + 1)

    def unload(self):
        """The written rows as a pyarrow RecordBatch (__fid__ first,
        like the file format)."""
        import pyarrow as pa
        n = self._n
        arrays = [pa.array(self._ids[:n], type=pa.string())]
        names = ["__fid__"]
        for name, w in self._writers.items():
            arrays.append(w.to_arrow(n))
            names.append(name)
        return pa.RecordBatch.from_arrays(arrays, names=names)

    def reset(self) -> None:
        """Clear for refill: sparse refills must never re-emit the
        previous batch's rows."""
        self._n = 0
        if self._ids is not None:
            self._ids = [None] * self.capacity
        for w in (self._writers or {}).values():
            w.reset()

    # -- read side ---------------------------------------------------------

    @property
    def n(self) -> int:
        return self._batch.num_rows if self._batch is not None else self._n

    def reader(self, name: str) -> ArrowAttributeReader:
        if name not in self._readers:
            if self._batch is None:
                raise ValueError("write-mode vector has no readers; "
                                 "unload() and wrap() first")
            self._readers[name] = ArrowAttributeReader(
                name, self._batch.column(name),
                attr=self.sft.attr(name))
        return self._readers[name]

    def ids(self) -> np.ndarray:
        return np.asarray(self._batch.column("__fid__").to_pylist(),
                          dtype=object)

    def feature(self, i: int):
        from .feature import ArrowFeature
        return ArrowFeature(self.sft, self._batch, i)
