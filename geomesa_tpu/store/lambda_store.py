"""Lambda-architecture store: transient live tier + long-term persistence.

The analog of geomesa-lambda (lambda/data/LambdaDataStore.scala:38):
writes land in the transient (live) tier; a background-style persistence
step moves features older than an age threshold into the persistent
store (DataStorePersistence analog); queries union both tiers with the
transient winning on id collisions (LambdaQueryRunner). The
LAMBDA_QUERY_PERSISTENT / LAMBDA_QUERY_TRANSIENT hints restrict to one
tier (QueryHints.scala:60-61).
"""

from __future__ import annotations

import time

import numpy as np

from ..features.sft import SimpleFeatureType, parse_spec
from ..index.api import Query
from .api import DataStore
from .live import LiveDataStore, MessageBus
from .memory import InMemoryDataStore, QueryResult

__all__ = ["LambdaDataStore", "LAMBDA_QUERY_PERSISTENT",
           "LAMBDA_QUERY_TRANSIENT"]

LAMBDA_QUERY_PERSISTENT = "LAMBDA_QUERY_PERSISTENT"
LAMBDA_QUERY_TRANSIENT = "LAMBDA_QUERY_TRANSIENT"


class LambdaDataStore(DataStore):
    def __init__(self, persistent=None, bus: MessageBus | None = None,
                 persist_after_millis: int = 3_600_000,
                 durable_dir: str | None = None,
                 wal_fsync: str | None = None):
        # durability guards the volatile half: crash-recovered transient
        # rows reopen stamped "now", so the normal persist() cadence
        # re-ages them toward the persistent tier
        self.transient = LiveDataStore(bus, durable_dir=durable_dir,
                                       wal_fsync=wal_fsync)
        self.persistent = persistent or InMemoryDataStore()
        self.persist_after = persist_after_millis
        # create_schema registers types in BOTH tiers; recovery only
        # repopulated the transient one — mirror the schemas across
        for tn in self.transient.get_type_names():
            if tn not in self.persistent.get_type_names():
                self.persistent.create_schema(self.transient.get_schema(tn))

    @property
    def journal(self):
        """The transient tier's WAL journal, or None when not durable."""
        return self.transient.journal

    def checkpoint(self, keep: int = 2) -> dict:
        return self.transient.checkpoint(keep=keep)

    def close(self):
        self.transient.close()
        close = getattr(self.persistent, "close", None)
        if close is not None:
            close()

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        self.transient.create_schema(sft)
        if sft.type_name not in self.persistent.get_type_names():
            self.persistent.create_schema(sft)

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        try:
            return self.transient.get_schema(type_name)
        except KeyError:
            # types living only in a user-supplied persistent tier are
            # still part of this store's surface
            return self.persistent.get_schema(type_name)

    def get_type_names(self) -> list[str]:
        return sorted(set(self.transient.get_type_names())
                      | set(self.persistent.get_type_names()))

    def remove_schema(self, type_name: str):
        if self._transient_has(type_name):
            self.transient.remove_schema(type_name)
        if type_name in self.persistent.get_type_names():
            self.persistent.remove_schema(type_name)

    def _transient_has(self, type_name: str) -> bool:
        return type_name in self.transient.get_type_names()

    def write(self, type_name: str, batch, timestamp_ms=None,
              visibilities=None):
        if not self._transient_has(type_name):
            if type_name in self.persistent.get_type_names():
                # persistent-only type: register it in the transient
                # tier so the write lands in the cache (not a silent
                # publish to a topic nobody consumes)
                self.transient.create_schema(
                    self.persistent.get_schema(type_name))
            else:
                raise KeyError(f"no such schema: {type_name}")
        self.transient.write(type_name, batch, timestamp_ms,
                             visibilities=visibilities)

    def delete(self, type_name: str, ids):
        self.transient.delete(type_name, ids)
        self.persistent.delete(type_name, ids)

    def persist(self, type_name: str, now_ms: int | None = None) -> int:
        """Move features older than the age threshold into the
        persistent tier (DataStorePersistence run). Returns moved count."""
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        ids, batch = self.transient.features_older_than(
            type_name, now - self.persist_after)
        if batch is None or batch.n == 0:
            return 0
        # visibility labels travel with the features to the durable
        # tier (looked up by id BEFORE the transient delete)
        st = self.transient._mem._state(type_name)
        vis = None
        if st.has_vis and st.batch is not None:
            pos = {str(i): k for k, i
                   in enumerate(st.batch.ids.astype(str))}
            vis = [st.vis[pos[str(i)]] if str(i) in pos else None
                   for i in ids]
        # upsert into the persistent store
        self.persistent.delete(type_name, ids)
        self.persistent.write(type_name, batch, visibilities=vis)
        self.transient.delete(type_name, ids)
        return batch.n

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None) -> QueryResult:
        if isinstance(q, str):
            q = Query(type_name, q)
        if q.hints.get(LAMBDA_QUERY_TRANSIENT):
            return self.transient.query(q, explain_out=explain_out)
        if q.hints.get(LAMBDA_QUERY_PERSISTENT) \
                or not self._transient_has(q.type_name):
            # persistent-only types answer from that tier alone
            return self.persistent.query(q, explain_out=explain_out)
        # run the tiers unsorted/unlimited; sort + limit re-apply on the
        # union (per-tier limits would be wrong)
        import dataclasses as _dc
        tier_q = _dc.replace(q, max_features=None, sort_by=None)
        rt = self.transient.query(tier_q, explain_out=explain_out)
        rp = self.persistent.query(tier_q, explain_out=explain_out)
        # a persistent row is stale if ANY transient version of the id
        # exists (not just one matching this filter): transient holds
        # the current version, which may no longer match
        t_state = self.transient._mem._state(q.type_name)
        all_t_ids = (t_state.batch.ids.astype(str)
                     if t_state.batch is not None else np.empty(0, "U1"))
        keep = ~np.isin(rp.ids.astype(str), all_t_ids)
        ids = np.concatenate([rt.ids, rp.ids[keep]])
        batch = rt.batch
        if rp.batch is not None and keep.any():
            sub = rp.batch.take(np.flatnonzero(keep))
            batch = sub if batch is None else batch.concat(sub)
        rt.explain(f"Lambda union: {rt.n} transient + "
                   f"{int(keep.sum())} persistent")
        if batch is not None and q.sort_by is not None:
            from .common import sort_order
            order = sort_order(batch, q.sort_by, q.sort_desc)
            ids = ids[order]
            batch = batch.take(order)
        if q.max_features is not None:
            ids = ids[:q.max_features]
            if batch is not None:
                batch = batch.take(np.arange(min(q.max_features, batch.n)))
        return QueryResult(ids, batch, rt.explain, rt.plan)

    def query_batched(self, queries: list[Query],
                      explain_out=None) -> list[QueryResult]:
        """Coalesced execution across the lambda tiers. Queries that a
        single tier can answer (persistent-only types, tier hints) fuse
        within that tier's batched scan; queries needing the
        transient+persistent union run the scalar merge path — its
        dedup depends on BOTH tiers' results, so fusing it would not
        change the number of dispatches it needs."""
        queries = list(queries)
        if len(queries) <= 1:
            return [self.query(q, explain_out=explain_out)
                    for q in queries]
        results: list[QueryResult | None] = [None] * len(queries)
        persistent_idx: list[int] = []
        transient_idx: list[int] = []
        union_idx: list[int] = []
        for i, q in enumerate(queries):
            if q.hints.get(LAMBDA_QUERY_TRANSIENT):
                transient_idx.append(i)
            elif q.hints.get(LAMBDA_QUERY_PERSISTENT) \
                    or not self._transient_has(q.type_name):
                persistent_idx.append(i)
            else:
                union_idx.append(i)

        def run_tier(tier, members):
            if not members:
                return
            if len(members) >= 2 and hasattr(tier, "query_batched"):
                sub = tier.query_batched([queries[i] for i in members],
                                         explain_out=explain_out)
                for i, r in zip(members, sub):
                    results[i] = r
            else:
                for i in members:
                    results[i] = tier.query(queries[i],
                                            explain_out=explain_out)

        run_tier(self.persistent, persistent_idx)
        run_tier(self.transient, transient_idx)
        for i in union_idx:
            results[i] = self.query(queries[i], explain_out=explain_out)
        return results  # type: ignore[return-value]

    def count(self, type_name: str) -> int:
        q = Query(type_name)
        return self.query(q).n

    def bin_query(self, type_name: str, ecql="INCLUDE",
                  track: str | None = None, label: str | None = None,
                  sort: bool = False) -> bytes:
        """BIN aggregation over the merged tier view (transient rows
        win over persistent, same as ``query``)."""
        from ..scan.aggregations import encode_bin_batch
        res = self.query(Query(type_name, ecql))
        if res.batch is None or res.batch.n == 0:
            return b""
        return encode_bin_batch(self.get_schema(type_name), res.ids,
                                res.batch, track=track, label=label,
                                sort=sort)

    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None) -> bytes:
        from ..arrow.scan import ArrowScan
        return ArrowScan(self).execute(type_name, ecql, sort_by=sort_by)
