"""TCP message bus: genuine network transport for the live tier.

The reference's live tier is network pub/sub — feature mutations flow
through Kafka brokers and consumer offsets checkpoint server-side
(/root/reference/geomesa-kafka/geomesa-kafka-datastore/src/main/scala/
org/locationtech/geomesa/kafka/data/KafkaDataStore.scala:44,
geomesa-lambda/.../stream/ZookeeperOffsetManager.scala:27). FileBus
reproduces the log design over a shared filesystem; this module adds
the missing piece — a wire transport, so producers and consumers on
DIFFERENT HOSTS interoperate:

- ``SocketBroker``: the Kafka-cluster analog. Per-topic ordered
  in-memory logs, consumer-group offsets (the Zookeeper role), served
  over a length-prefixed TCP protocol. With ``root=`` it persists
  messages in the FileBus segment layout (same directory structure and
  payload bytes, via filebus's shared atomic-write helpers), so a
  broker restart replays the durable log and a FileBus pointed at the
  same root can read it — FileBus stays the durable tier, the broker
  is the network tier.
- ``SocketBus``: producer/consumer client with the same
  subscribe/publish/poll surface as FileBus (LiveDataStore plugs in
  unchanged). A single multi-topic fetch supports LONG-POLL
  (``poll(wait_s=...)``): the broker parks it until a publish arrives
  on ANY subscribed topic, so consumers get wakeup-on-publish instead
  of busy polling — the notification gap of the file transport. Long
  polls ride a dedicated connection, so a same-client publish (the
  wakeup source) is never serialized behind a parked fetch.

Payloads reuse the FileBus GeoMessage encoding (JSON header + Arrow
IPC stream), a self-describing wire format: consumers need no
out-of-band schema exchange.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Callable

from ..metrics import metrics
from ..resilience import RetryPolicy
from .filebus import (_SEQ_DIGITS, _decode, _encode, segment_name,
                      write_bytes_atomic, write_json_atomic)
from .live import GeoMessage

__all__ = ["SocketBroker", "SocketBus", "ProtocolError"]

# frame hardening: declared lengths past these caps are garbage or
# hostile input (port scan, HTTP probe) — reject BEFORE allocating,
# not after an unbounded _recv_exact
_MAX_HEADER_BYTES = 1 << 20    # 1 MiB of JSON header
_MAX_PAYLOAD_BYTES = 1 << 28   # 256 MiB frame payload

# how many publish idempotency keys the broker remembers per topic
# (the dedup window for client retries)
_PUB_KEY_WINDOW = 8192


class ProtocolError(ConnectionError):
    """Wire-protocol violation (oversized or truncated frame): the
    stream position is unrecoverable, the connection must be dropped
    and re-established."""


def _send_frame(sock, header: dict, payload: bytes = b""):
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">II", len(h), len(payload)) + h + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock):
    hlen, plen = struct.unpack(">II", _recv_exact(sock, 8))
    if hlen > _MAX_HEADER_BYTES or plen > _MAX_PAYLOAD_BYTES:
        raise ProtocolError(
            f"frame lengths {hlen}/{plen} exceed caps "
            f"{_MAX_HEADER_BYTES}/{_MAX_PAYLOAD_BYTES}")
    header = json.loads(_recv_exact(sock, hlen).decode())
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


class SocketBroker:
    """Append-only per-topic logs + consumer-group offsets behind a
    TCP server. One instance per deployment (the broker role); clients
    connect with SocketBus."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 root: str | None = None):
        self._logs: dict[str, list[bytes]] = {}
        self._group_offsets: dict[str, dict[str, int]] = {}
        # publish idempotency keys -> assigned seq, per topic (bounded
        # window): a client retrying a publish whose ACK was lost gets
        # the original seq back instead of a duplicate log entry
        self._pub_keys: dict[str, OrderedDict[str, int]] = {}
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.root = root
        if root:
            self._load_root()

        broker = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                broker._track(self.request)
                try:
                    while True:
                        try:
                            header, payload = _recv_frame(self.request)
                        except (json.JSONDecodeError, UnicodeDecodeError,
                                ProtocolError):
                            # not our protocol (port scan, garbage,
                            # absurd declared lengths): drop the
                            # connection quietly, allocate nothing
                            return
                        try:
                            broker._handle(self.request, header, payload)
                        except (KeyError, TypeError, ValueError) as e:
                            _send_frame(self.request,
                                        {"error": f"bad request: {e}"})
                except (ConnectionError, OSError, struct.error):
                    pass  # client went away
                finally:
                    broker._untrack(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            # a restarted broker must rebind its old port immediately
            # (crash recovery), not wait out TIME_WAIT
            allow_reuse_address = True

        self._srv = _Server((host, port), _Handler)
        self._srv.daemon_threads = True
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self) -> "SocketBroker":
        self._thread.start()
        return self

    def stop(self):
        """Stop serving AND sever live client connections — a stopped
        broker must look like a dead broker (clients see a closed
        peer and run their reconnect path), not a half-alive one
        whose surviving handler threads keep answering."""
        self._srv.shutdown()
        self._srv.server_close()
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _track(self, sock):
        with self._conns_lock:
            self._conns.add(sock)

    def _untrack(self, sock):
        with self._conns_lock:
            self._conns.discard(sock)

    # -- request dispatch --------------------------------------------------

    def _handle(self, sock, header: dict, payload: bytes):
        op = header.get("op")
        if op == "publish":
            topic = header["topic"]
            key = header.get("key")
            with self._cond:
                if key is not None:
                    keys = self._pub_keys.setdefault(topic, OrderedDict())
                    dup = keys.get(key)
                    if dup is not None:
                        # retried publish: already appended (and
                        # persisted) under this key — ack, don't dup
                        metrics.counter("resilience.socketbus.pub_dedup")
                        _send_frame(sock, {"seq": dup, "dup": True})
                        return
                log = self._logs.setdefault(topic, [])
                log.append(payload)
                seq = len(log)
                if key is not None:
                    keys[key] = seq
                    while len(keys) > _PUB_KEY_WINDOW:
                        keys.popitem(last=False)
                self._cond.notify_all()
            if self.root:
                self._persist(topic, seq, payload)
            _send_frame(sock, {"seq": seq})
        elif op == "fetch":
            # one fetch covers every topic the consumer follows; the
            # park wakes on a publish to ANY of them
            offsets = {t: int(v) for t, v in header["topics"].items()}
            maxm = header.get("max")
            wait_s = float(header.get("wait_s", 0) or 0)
            deadline = time.monotonic() + wait_s
            with self._cond:
                while True:
                    ready = {t: self._logs.get(t, [])[off:]
                             for t, off in offsets.items()}
                    ready = {t: m for t, m in ready.items() if m}
                    if ready or wait_s <= 0:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            meta: dict = {}
            chunks: list[bytes] = []
            budget = None if maxm is None else int(maxm)
            for t in sorted(ready):
                msgs = ready[t]
                if budget is not None:
                    msgs = msgs[:budget]
                meta[t] = {"count": len(msgs)}
                chunks.extend(struct.pack(">I", len(m)) + m for m in msgs)
                if budget is not None:
                    budget -= len(msgs)
                    if budget <= 0:
                        break
            _send_frame(sock, {"topics": meta}, b"".join(chunks))
        elif op == "commit":
            group = header["group"]
            with self._lock:
                g = self._group_offsets.setdefault(group, {})
                g.update({k: int(v)
                          for k, v in header["offsets"].items()})
            if self.root:
                self._persist_offsets(group)
            _send_frame(sock, {"ok": True})
        elif op == "offsets":
            with self._lock:
                offs = dict(self._group_offsets.get(header["group"], {}))
            _send_frame(sock, {"offsets": offs})
        else:
            _send_frame(sock, {"error": f"unknown op {op!r}"})

    # -- durable tier (FileBus segment layout, shared helpers) -------------

    def _persist(self, topic: str, seq: int, raw: bytes):
        d = os.path.join(self.root, "topics", topic)
        os.makedirs(d, exist_ok=True)
        write_bytes_atomic(os.path.join(d, segment_name(seq)), raw)

    def _persist_offsets(self, group: str):
        d = os.path.join(self.root, "offsets")
        os.makedirs(d, exist_ok=True)
        with self._lock:
            offs = dict(self._group_offsets.get(group, {}))
        write_json_atomic(os.path.join(d, f"{group}.json"), offs)

    def _load_root(self):
        """Replay the durable log on startup (broker restart = the
        reference's log-backed recovery). Gaps (e.g. a FileBus claim
        skipped as stale) load as empty messages that consumers skip."""
        tdir = os.path.join(self.root, "topics")
        if os.path.isdir(tdir):
            for topic in os.listdir(tdir):
                d = os.path.join(tdir, topic)
                seqs = sorted(int(f[:_SEQ_DIGITS]) for f in os.listdir(d)
                              if f.endswith(".msg"))
                log: list[bytes] = []
                for seq in seqs:
                    while len(log) < seq - 1:
                        log.append(b"")
                    with open(os.path.join(d, segment_name(seq)),
                              "rb") as f:
                        log.append(f.read())
                self._logs[topic] = log
        odir = os.path.join(self.root, "offsets")
        if os.path.isdir(odir):
            for fn in os.listdir(odir):
                if not fn.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(odir, fn)) as f:
                        self._group_offsets[fn[:-5]] = {
                            k: int(v) for k, v in json.load(f).items()}
                except (json.JSONDecodeError, ValueError):
                    continue


class _Channel:
    """One broker connection + its lock (commands and long-polls ride
    separate channels so a parked fetch never blocks a publish).

    ``rpc`` reconnects transparently with backoff under ``policy``: a
    reset connection (or a down broker, within the retry deadline) is
    absorbed here, so callers only see failures that outlived the
    policy. Safe because every broker op is idempotent at the protocol
    level — fetch/offsets are reads against client-held offsets,
    commit sets absolute offsets, and publish carries a dedup key."""

    def __init__(self, host, port, timeout_s, policy=None):
        self.host, self.port, self.timeout_s = host, port, timeout_s
        self.lock = threading.Lock()
        self.sock = None
        self.policy = policy if policy is not None else RetryPolicy()
        self._ever_connected = False

    def _attempt(self, header, payload, timeout_s):
        with self.lock:
            if self.sock is None:
                self.sock = socket.create_connection(
                    (self.host, self.port), timeout=self.timeout_s)
                if self._ever_connected:
                    metrics.counter("resilience.socketbus.reconnects")
                self._ever_connected = True
            self.sock.settimeout(timeout_s or self.timeout_s)
            try:
                _send_frame(self.sock, header, payload)
                return _recv_frame(self.sock)
            except (ConnectionError, OSError):
                try:
                    self.sock.close()
                finally:
                    self.sock = None
                raise

    def rpc(self, header: dict, payload: bytes = b"",
            timeout_s: float | None = None):
        return self.policy.call(
            lambda: self._attempt(header, payload, timeout_s),
            name="socketbus")

    def close(self):
        with self.lock:
            if self.sock is not None:
                try:
                    self.sock.close()
                finally:
                    self.sock = None


class SocketBus:
    """Network MessageBus client: FileBus's subscribe/publish/poll
    surface over a broker connection, with server-side consumer-group
    offsets and long-poll wakeups."""

    def __init__(self, host: str, port: int, group: str = "default",
                 timeout_s: float = 30.0,
                 retry_policy: RetryPolicy | None = None):
        self.host = host
        self.port = port
        self.group = group
        self.timeout_s = timeout_s
        self._subs: dict[str, list[Callable[[GeoMessage], None]]] = {}
        self._cmd = _Channel(host, port, timeout_s, policy=retry_policy)
        self._fetch = _Channel(host, port, timeout_s, policy=retry_policy)
        header, _ = self._cmd.rpc({"op": "offsets", "group": group})
        self._offsets: dict[str, int] = {
            k: int(v) for k, v in header.get("offsets", {}).items()}

    def close(self):
        self._cmd.close()
        self._fetch.close()

    # -- offsets -----------------------------------------------------------

    def offset(self, topic: str) -> int:
        return self._offsets.get(topic, 0)

    def set_offset(self, topic: str, offset: int):
        """Manual seek (offset = last consumed sequence number),
        committed to the broker."""
        self._offsets[topic] = int(offset)
        self._commit()

    def _commit(self):
        self._cmd.rpc({"op": "commit", "group": self.group,
                       "offsets": self._offsets})

    # -- producer / consumer -----------------------------------------------

    def publish(self, topic: str, msg: GeoMessage) -> int:
        # the client-assigned idempotency key makes retried publishes
        # (ACK lost to a reset) exactly-once: the broker dedups on it
        header, _ = self._cmd.rpc(
            {"op": "publish", "topic": topic, "key": uuid.uuid4().hex},
            _encode(msg))
        return int(header["seq"])

    def subscribe(self, topic: str, fn: Callable[[GeoMessage], None]):
        self._subs.setdefault(topic, []).append(fn)

    def poll(self, max_messages: int | None = None,
             wait_s: float = 0.0) -> int:
        """Drain new messages on subscribed topics, in sequence order;
        commits offsets to the broker. ``wait_s`` long-polls: when no
        subscribed topic has news, the broker parks the fetch until a
        publish arrives on any of them (wakeup-on-publish). Returns
        messages delivered."""
        topics = {t: self._offsets.get(t, 0) for t in list(self._subs)}
        if not topics:
            return 0
        # the fetch channel reconnects under its retry policy: a
        # broker restart mid-long-poll re-issues this fetch against
        # the new broker, which resumes at our (server-committed)
        # offsets — exactly-once from the last commit
        header, body = self._fetch.rpc(
            {"op": "fetch", "topics": topics, "max": max_messages,
             "wait_s": wait_s},
            timeout_s=self.timeout_s + wait_s)
        delivered = 0
        advanced = False
        error: Exception | None = None
        pos = 0
        try:
            for t, info in header.get("topics", {}).items():
                off = self._offsets.get(t, 0)
                count = int(info.get("count", 0))
                for _ in range(count):
                    if pos + 4 > len(body):
                        self._fetch.close()  # stream position is junk
                        raise ProtocolError(
                            f"truncated fetch body at {pos}/{len(body)}")
                    (mlen,) = struct.unpack(">I", body[pos:pos + 4])
                    if pos + 4 + mlen > len(body):
                        self._fetch.close()
                        raise ProtocolError(
                            f"truncated fetch message ({mlen} declared, "
                            f"{len(body) - pos - 4} available)")
                    raw = body[pos + 4:pos + 4 + mlen]
                    pos += 4 + mlen
                    if raw:
                        msg = _decode(raw)
                        # read the live subscriber list — consumer-side
                        # schema auto-create may append handlers mid-poll
                        for fn in self._subs.get(t, []):
                            fn(msg)
                        delivered += 1
                    # a message advances our offset only once every
                    # handler ran; a raising subscriber leaves it due
                    # for redelivery (at-least-once for that message)
                    off += 1
                    self._offsets[t] = off
                    advanced = True
        except Exception as e:
            error = e
        if advanced:
            # progress made before a failure still commits: a raising
            # subscriber (or torn body) must not force redelivery of
            # the messages that were already fully delivered
            self._commit()
        if error is not None:
            raise error
        return delivered

    def wait_for(self, predicate, timeout_s: float = 10.0,
                 interval_s: float = 0.25) -> bool:
        """Long-poll until predicate() is true or the timeout lapses
        (interval_s bounds each broker park, not a sleep)."""
        deadline = time.monotonic() + timeout_s
        while True:
            self.poll(wait_s=min(interval_s,
                                 max(deadline - time.monotonic(), 0)))
            if predicate():
                return True
            if time.monotonic() >= deadline:
                return False
