"""Filesystem datastore: Parquet persistence with partition pruning.

The analog of geomesa-fs (FileSystemDataStore.scala:29 +
ParquetFileSystemStorage.scala:63): features persist as Parquet files
under partition directories; a JSON metadata catalog records the schema
and partition-scheme config; query planning prunes partitions from the
filter, loads the surviving files into the in-memory device store, and
runs the normal TPU execution path (a per-pruned-set device cache makes
repeated queries device-resident — the 'storage tier feeds the compute
tier' shape of SURVEY.md section 7 step 8).

Layout:
    root/<type_name>/metadata.json
    root/<type_name>/data/<partition...>/<uuid>.parquet
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Any

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType, parse_spec
from ..filters import ast
from ..index.api import Query
from .api import DataStore
from .memory import InMemoryDataStore, QueryResult
from .partitions import (DateTimeScheme, PartitionScheme, Z2Scheme,
                         scheme_from_config)

__all__ = ["FileSystemDataStore"]

# reserved parquet column carrying per-feature visibility labels
_VIS_COL = "__vis__"


def _safe_partition(name) -> str:
    """Sanitize a scheme-produced partition name into a relative path:
    attribute-derived names must not traverse outside the data dir."""
    from urllib.parse import quote
    segs = []
    for seg in str(name).split("/"):
        q = quote(seg, safe="")
        if q in ("", ".", ".."):
            q = "%" + q
        segs.append(q)
    return "/".join(segs)


def _pushdown_expr(f: ast.Filter, sft: SimpleFeatureType):
    """Filter AST -> a CONSERVATIVE pyarrow dataset expression (matches
    a superset of the filter), or None when nothing is pushable.

    The analog of geomesa-fs's FilterConverter (fs/parquet
    FilterConverter: CQL -> parquet predicate pushdown): row groups
    whose column statistics cannot match are never read, and
    non-matching rows are dropped at scan time. Exactness is unaffected
    — the in-memory engine re-evaluates the full filter over whatever
    loads. AND may drop unpushable conjuncts; OR is pushed only when
    every branch is pushable.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    geom = sft.geom_field
    point_geom = geom is not None and sft.is_points

    def lit(prop, v):
        type_name = next((at.type.name for at in sft.attributes
                          if at.name == prop), None)
        if type_name == "Date":
            from ..filters.helper import to_millis
            return pa.scalar(np.datetime64(to_millis(v), "ms"))
        return v

    def conv(f):
        if isinstance(f, ast.And):
            parts = [p for p in (conv(c) for c in f.children)
                     if p is not None]
            if not parts:
                return None
            e = parts[0]
            for p in parts[1:]:
                e = e & p
            return e
        if isinstance(f, ast.Or):
            parts = [conv(c) for c in f.children]
            if not parts or any(p is None for p in parts):
                return None
            e = parts[0]
            for p in parts[1:]:
                e = e | p
            return e
        if isinstance(f, ast.BBox) and point_geom and f.prop == geom:
            gx, gy = pc.field(geom, "x"), pc.field(geom, "y")
            return ((gx >= f.xmin) & (gx <= f.xmax)
                    & (gy >= f.ymin) & (gy <= f.ymax))
        if isinstance(f, (ast.Intersects, ast.Within, ast.DWithin)) \
                and point_geom and f.prop == geom:
            from ..filters.helper import dwithin_degrees
            env = f.geom.envelope
            pad = (dwithin_degrees(f.geom, f.distance, f.units)
                   if isinstance(f, ast.DWithin) else 0.0)
            gx, gy = pc.field(geom, "x"), pc.field(geom, "y")
            return ((gx >= env.xmin - pad) & (gx <= env.xmax + pad)
                    & (gy >= env.ymin - pad) & (gy <= env.ymax + pad))
        if isinstance(f, ast.Compare):
            fld = pc.field(f.prop)
            v = lit(f.prop, f.value)
            return {
                ast.CompareOp.EQ: lambda: fld == v,
                ast.CompareOp.NE: lambda: fld != v,
                ast.CompareOp.LT: lambda: fld < v,
                ast.CompareOp.GT: lambda: fld > v,
                ast.CompareOp.LE: lambda: fld <= v,
                ast.CompareOp.GE: lambda: fld >= v,
            }[f.op]()
        if isinstance(f, ast.Between):
            fld = pc.field(f.prop)
            return (fld >= lit(f.prop, f.lo)) & (fld <= lit(f.prop, f.hi))
        if isinstance(f, ast.InList):
            return pc.field(f.prop).isin(
                [lit(f.prop, v) for v in f.values])
        if isinstance(f, ast.During):
            fld = pc.field(f.prop)
            return ((fld > pa.scalar(np.datetime64(f.start, "ms")))
                    & (fld < pa.scalar(np.datetime64(f.end, "ms"))))
        if isinstance(f, ast.Before):
            return pc.field(f.prop) < pa.scalar(np.datetime64(f.time, "ms"))
        if isinstance(f, ast.After):
            return pc.field(f.prop) > pa.scalar(np.datetime64(f.time, "ms"))
        if isinstance(f, ast.IsNull):
            return pc.field(f.prop).is_null()
        return None  # LIKE, NOT, fids, exotic spatial: not pushed

    try:
        return conv(f)
    except Exception:
        return None  # a column the files lack, bad literal, ...


class _FsTypeState:
    def __init__(self, sft: SimpleFeatureType, scheme: PartitionScheme,
                 root: str):
        self.sft = sft
        self.scheme = scheme
        self.root = root
        # cache: frozenset(partition files) -> loaded memory store
        self.cache: dict[frozenset, InMemoryDataStore] = {}
        # load-key digest -> memory store awaiting sidecar persistence
        self.pending_sidecar: dict[str, InMemoryDataStore] = {}

    @property
    def data_dir(self) -> str:
        return os.path.join(self.root, "data")

    @property
    def index_dir(self) -> str:
        return os.path.join(self.root, "index")


class FileSystemDataStore(DataStore):
    """Parquet-backed datastore with the same query surface as the
    in-memory store."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._types: dict[str, _FsTypeState] = {}
        for name in os.listdir(root):
            meta = os.path.join(root, name, "metadata.json")
            if os.path.isfile(meta):
                self._load_type(name)

    # -- metadata catalog --------------------------------------------------

    def _load_type(self, name: str):
        with open(os.path.join(self.root, name, "metadata.json")) as fh:
            meta = json.load(fh)
        # version-skew check (GeoMesaDataStore.checkProjectVersion analog)
        recorded = meta.get("version")
        if recorded is not None:
            from ..utils.version import check_version_string
            check_version_string(recorded, name)
        sft = parse_spec(name, meta["spec"])
        scheme = scheme_from_config(meta["partition_scheme"])
        self._types[name] = _FsTypeState(
            sft, scheme, os.path.join(self.root, name))

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None,
                      scheme: PartitionScheme | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        if sft.type_name in self._types:
            raise ValueError(f"schema {sft.type_name!r} already exists")
        if scheme is None:
            # reference default: daily datetime + z2 when both axes exist
            if sft.dtg_field is not None:
                scheme = DateTimeScheme("daily")
            elif sft.geom_field is not None:
                scheme = Z2Scheme(4)
            else:
                raise ValueError("schema needs a dtg or geometry for "
                                 "partitioning; pass an explicit scheme")
        tdir = os.path.join(self.root, sft.type_name)
        os.makedirs(os.path.join(tdir, "data"), exist_ok=True)
        from .. import __version__
        with open(os.path.join(tdir, "metadata.json"), "w") as fh:
            json.dump({"spec": sft.to_spec(),
                       "partition_scheme": scheme.to_config(),
                       "version": __version__}, fh, indent=2)
        self._types[sft.type_name] = _FsTypeState(sft, scheme, tdir)

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._state(type_name).sft

    def get_type_names(self) -> list[str]:
        return sorted(self._types)

    def remove_schema(self, type_name: str):
        """Drop the type and its on-disk data/index directories. The
        directory removal runs FIRST and raises on failure — the
        catalog entry must not disappear while data survives on disk
        (a reopen would silently resurrect the schema)."""
        import shutil
        st = self._state(type_name)
        shutil.rmtree(st.root)
        self._types.pop(type_name, None)

    def _state(self, type_name: str) -> _FsTypeState:
        if type_name not in self._types:
            raise KeyError(f"no such schema: {type_name}")
        return self._types[type_name]

    # -- writes ------------------------------------------------------------

    def write(self, type_name: str, batch: FeatureBatch,
              visibilities=None):
        import pyarrow as pa
        import pyarrow.parquet as pq
        st = self._state(type_name)
        vis = None
        if visibilities is not None:
            vis = np.asarray(visibilities, dtype=object)
            if len(vis) != batch.n:
                raise ValueError("visibilities length mismatch")
            from ..security import validate_labels
            validate_labels(st.sft,
                            set(v for v in vis.tolist() if v))
        names = st.scheme.partition_for_rows(st.sft, batch)
        for part in np.unique(names):
            sel = np.flatnonzero(names == part)
            sub = batch.take(sel)
            pdir = os.path.join(st.data_dir, _safe_partition(part))
            os.makedirs(pdir, exist_ok=True)
            path = os.path.join(pdir, f"{uuid.uuid4().hex[:12]}.parquet")
            table = pa.Table.from_batches([sub.to_arrow()])
            if vis is not None:
                # labels persist in a reserved column next to the data
                # (the Accumulo column-visibility model made durable)
                table = table.append_column(
                    _VIS_COL, pa.array([None if v is None else str(v)
                                        for v in vis[sel]], pa.string()))
            pq.write_table(table, path)
        st.cache.clear()
        st.pending_sidecar.clear()
        # per-row RAW partition names (callers quote via partitions()
        # semantics when keying on-disk names) — the sharded tier reuses
        # this instead of recomputing the assignment
        return names

    def delete(self, type_name: str, ids):
        """Remove features by id: rewrite every parquet file that holds
        any of them (delete + compaction in one step — the reference's
        fs storage likewise rewrites data files on modify)."""
        import pyarrow as pa
        import pyarrow.compute as pc
        import pyarrow.parquet as pq
        st = self._state(type_name)
        value_set = pa.array([str(i) for i in ids], pa.string())
        for f in self._files_for(st, None):
            table = pq.read_table(f)
            hit = pc.is_in(pc.cast(table.column("__fid__"), pa.string()),
                           value_set=value_set)
            n_hit = pc.sum(hit).as_py() or 0
            if not n_hit:
                continue
            kept = table.filter(pc.invert(hit))
            if kept.num_rows:
                pq.write_table(kept, f)
            else:
                os.remove(f)
        st.cache.clear()
        st.pending_sidecar.clear()

    # -- partitions --------------------------------------------------------

    def partitions(self, type_name: str) -> list[str]:
        st = self._state(type_name)
        out = []
        for dirpath, _dirs, files in os.walk(st.data_dir):
            if any(f.endswith(".parquet") for f in files):
                out.append(os.path.relpath(dirpath, st.data_dir)
                           .replace(os.sep, "/"))
        return sorted(out)

    def _files_for(self, st: _FsTypeState,
                   parts: list[str] | None) -> list[str]:
        if parts is None:
            files = []
            for dirpath, _d, fnames in os.walk(st.data_dir):
                files.extend(os.path.join(dirpath, f) for f in fnames
                             if f.endswith(".parquet"))
            return sorted(files)
        files = []
        for p in parts:
            pdir = os.path.join(st.data_dir, _safe_partition(p))
            if os.path.isdir(pdir):
                files.extend(os.path.join(pdir, f)
                             for f in sorted(os.listdir(pdir))
                             if f.endswith(".parquet"))
        return files

    # -- index sidecars ----------------------------------------------------
    #
    # Built z-key sort orders persist next to the Parquet data
    # (root/<type>/index/<digest>/), keyed by a digest of the loaded
    # file set (+ sizes/mtimes) and the pushdown key, so a reopened
    # store memory-maps the permutation instead of re-sorting 100M keys
    # — the durable-index-table analog of the reference's fs metadata
    # (fs/FileMetadata; geomesa-fs keeps its indexes IN the data files'
    # key order, here the sort order itself is the index).

    _SIDECAR_CAP = 4  # LRU cap on persisted index snapshots per type

    @staticmethod
    def _sidecar_digest(st: _FsTypeState, files, expr, props) -> str:
        import hashlib
        h = hashlib.sha256()
        for f in sorted(files):
            s = os.stat(f)
            h.update(f"{os.path.relpath(f, st.root)}|{s.st_size}|"
                     f"{s.st_mtime_ns}\n".encode())
        h.update(repr(None if expr is None else str(expr)).encode())
        h.update(repr(None if props is None else tuple(props)).encode())
        return h.hexdigest()[:24]

    def _install_sidecar(self, st: _FsTypeState, digest: str,
                         mem: InMemoryDataStore, type_name: str) -> bool:
        d = os.path.join(st.index_dir, digest)
        man = os.path.join(d, "manifest.json")
        if not os.path.isfile(man):
            return False
        try:
            with open(man) as fh:
                names = json.load(fh)["arrays"]
            state = {n: np.load(os.path.join(d, n + ".npy"),
                                mmap_mode="r") for n in names}
        except Exception:
            return False  # torn/corrupt sidecar: rebuild from scratch
        mem.warm_index(type_name, state)
        os.utime(d)  # recency for the LRU prune
        return True

    def _flush_sidecars(self, st: _FsTypeState, type_name: str):
        """Persist sort orders for loads whose index has since been
        built (lazily, by a query); prune old snapshots."""
        import shutil
        done = []
        for digest, mem in st.pending_sidecar.items():
            state = mem.index_state(type_name)
            if not state:
                continue
            d = os.path.join(st.index_dir, digest)
            tmp = d + f".tmp{os.getpid()}"
            try:
                os.makedirs(tmp, exist_ok=True)
                for name, arr in state.items():
                    np.save(os.path.join(tmp, name + ".npy"),
                            np.asarray(arr))
                with open(os.path.join(tmp, "manifest.json"), "w") as fh:
                    json.dump({"arrays": sorted(state)}, fh)
                if os.path.isdir(d):
                    shutil.rmtree(tmp)
                else:
                    os.rename(tmp, d)
            except OSError:
                shutil.rmtree(tmp, ignore_errors=True)
            done.append(digest)
        for digest in done:
            st.pending_sidecar.pop(digest, None)
        # LRU prune
        if os.path.isdir(st.index_dir):
            snaps = [os.path.join(st.index_dir, n)
                     for n in os.listdir(st.index_dir)
                     if ".tmp" not in n]
            snaps.sort(key=lambda p: os.path.getmtime(p))
            for p in snaps[:-self._SIDECAR_CAP]:
                shutil.rmtree(p, ignore_errors=True)

    def read_partition(self, type_name: str, partition: str):
        """Raw rows of one partition: (FeatureBatch | None, vis | None).
        ``partition`` is a name as returned by ``partitions()`` (the
        on-disk quoted form) — it is NOT re-quoted here. The loader the
        sharded mesh tier maps over partitions (partition -> device
        placement; FsQueryPlanning reads the same files per-partition
        in the reference)."""
        import pyarrow.dataset as pds
        st = self._state(type_name)
        pdir = os.path.join(st.data_dir,
                            partition.replace("/", os.sep))
        if not os.path.isdir(pdir) \
                or os.path.commonpath(
                    [os.path.abspath(pdir),
                     os.path.abspath(st.data_dir)]) \
                != os.path.abspath(st.data_dir):
            return None, None
        files = [os.path.join(pdir, f) for f in sorted(os.listdir(pdir))
                 if f.endswith(".parquet")]
        if not files:
            return None, None
        dataset = pds.dataset(files)
        table = dataset.to_table()
        has_vis = _VIS_COL in dataset.schema.names
        batches, vises = [], []
        for rb in table.to_batches():
            if not rb.num_rows:
                continue
            if has_vis:
                i = rb.schema.get_field_index(_VIS_COL)
                vises.append(np.asarray(rb.column(i).to_pylist(),
                                        dtype=object))
                rb = rb.drop_columns([_VIS_COL])
            batches.append(FeatureBatch.from_arrow(st.sft, rb))
        if not batches:
            return None, None
        batch = FeatureBatch.concat_all(batches)
        return batch, (np.concatenate(vises) if has_vis else None)

    def _load(self, st: _FsTypeState, files: list[str],
              expr=None, props: list[str] | None = None
              ) -> InMemoryDataStore:
        key = (frozenset(files), None if expr is None else str(expr),
               None if props is None else tuple(props))
        if key in st.cache:
            st.cache[key] = st.cache.pop(key)  # LRU recency refresh
            return st.cache[key]
        import pyarrow.dataset as pds
        sft = st.sft
        columns = None
        if props is not None:
            keep = set(props)
            sft = SimpleFeatureType(
                sft.type_name, [a for a in sft.attributes
                                if a.name in keep], sft.user_data)
            columns = ["__fid__"] + [a.name for a in sft.attributes]
        ds = InMemoryDataStore()
        ds.create_schema(sft)
        if files:
            dataset = pds.dataset(files)
            has_vis = _VIS_COL in dataset.schema.names
            if has_vis and columns is not None:
                # labels must survive projection or vis filtering
                # silently disappears on projected queries
                columns = columns + [_VIS_COL]
            # attribute-level labels are positional over the FULL
            # schema; a projected load must remap each label to the
            # kept attributes or the parts guard the wrong columns
            remap = None
            if (has_vis and props is not None
                    and st.sft.visibility_level == "attribute"):
                kept = {a.name for a in sft.attributes}
                keep_j = [j for j, a in enumerate(st.sft.attributes)
                          if a.name in kept]
                n_full = len(st.sft.attributes)

                def remap(v, _k=keep_j, _n=n_full):
                    if not v:
                        return v
                    parts = (str(v).split(",") + [""] * _n)[:_n]
                    return ",".join(parts[j] for j in _k)
            # row-group statistics pruning + row-level predicate and
            # column projection happen inside the parquet scan
            table = dataset.to_table(filter=expr, columns=columns)
            for rb in table.to_batches():
                if not rb.num_rows:
                    continue
                vis = None
                if has_vis:
                    i = rb.schema.get_field_index(_VIS_COL)
                    vis = np.asarray(rb.column(i).to_pylist(),
                                     dtype=object)
                    if remap is not None:
                        vis = np.array([remap(v) for v in vis],
                                       dtype=object)
                    rb = rb.drop_columns([_VIS_COL])
                ds.write(sft.type_name,
                         FeatureBatch.from_arrow(sft, rb),
                         visibilities=vis)
        # adopt a persisted index snapshot for this exact load, or mark
        # the store for persistence once a query builds its index
        if files:
            digest = self._sidecar_digest(st, files, expr, props)
            if not self._install_sidecar(st, digest, ds, sft.type_name):
                st.pending_sidecar[digest] = ds
        # bounded LRU: pushdown makes keys (files, filter, columns), so
        # a rotation of several recurring queries must stay resident
        if len(st.cache) >= 8:
            evicted = st.cache.pop(next(iter(st.cache)))
            # an evicted store must not stay pinned awaiting a sidecar
            # flush that can never come (its index will never be built)
            st.pending_sidecar = {d: m for d, m in
                                  st.pending_sidecar.items()
                                  if m is not evicted}
        st.cache[key] = ds
        return ds

    # -- queries -----------------------------------------------------------

    def load_resident(self, type_name: str) -> None:
        """Load the full table into the device-resident engine once.
        Subsequent queries are served from it (no per-query parquet
        scans), its z-key index persists as a sidecar, and a reopened
        store adopts the memory-mapped sort order instead of re-sorting
        — the intended workflow at 100M-row scale, matching the
        reference's always-resident index tables."""
        st = self._state(type_name)
        self._load(st, self._files_for(st, None))

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None) -> QueryResult:
        if isinstance(q, str):
            q = Query(type_name, q)
        st = self._state(q.type_name)
        # a resident full-table store answers directly: device columns
        # and sort orders are already built (or memory-mapped), so skip
        # partition pruning and parquet pushdown entirely. A persisted
        # FULL-TABLE sidecar on disk also routes here: a reopened store
        # adopts the memory-mapped sort order rather than re-sorting —
        # the fs durable-metadata reopen path (FileMetadata analog,
        # fs-storage-common FileBasedMetadata)
        files_all = self._files_for(st, None)
        full_key = (frozenset(files_all), None, None)
        resident = full_key in st.cache
        if files_all and not resident and os.path.isdir(st.index_dir):
            # probe only when sidecars exist at all; a pure pushdown
            # workload never pays the stat+digest pass
            digest = self._sidecar_digest(st, files_all, None, None)
            resident = os.path.isfile(os.path.join(
                st.index_dir, digest, "manifest.json"))
        if files_all and resident:
            mem = self._load(st, files_all)
            res = mem.query(q, explain_out=explain_out)
            self._flush_sidecars(st, q.type_name)
            res.explain("Served from resident full-table store")
            return res
        parts = st.scheme.covering_partitions(st.sft, q.filter)
        if parts == []:
            from ..index.api import Explainer, FilterStrategy
            ex = Explainer(explain_out)
            ex("All partitions pruned")
            return QueryResult(np.empty(0, dtype=object), None, ex,
                               FilterStrategy("empty", None, None))
        files = self._files_for(st, parts)
        expr = _pushdown_expr(q.filter, st.sft)
        props = None
        if q.properties is not None:
            need = ast.props_of(q.filter) | set(q.properties)
            if st.sft.geom_field:
                need.add(st.sft.geom_field)
            if st.sft.dtg_field:
                need.add(st.sft.dtg_field)
            if q.sort_by:
                need.add(q.sort_by)
            from ..index.api import QueryHints
            sample_by = q.hints.get(QueryHints.SAMPLE_BY)
            if sample_by:
                need.add(sample_by)
            props = [a.name for a in st.sft.attributes if a.name in need]
        mem = self._load(st, files, expr, props)
        res = mem.query(q, explain_out=explain_out)
        self._flush_sidecars(st, q.type_name)
        res.explain(f"Partitions scanned: "
                    f"{'all' if parts is None else len(parts)}; "
                    f"files: {len(files)}; parquet pushdown: "
                    f"{'yes' if expr is not None else 'no'}"
                    + (f"; columns: {len(props)}" if props else ""))
        return res

    def count(self, type_name: str) -> int:
        st = self._state(type_name)
        mem = self._load(st, self._files_for(st, None))
        return mem.count(type_name)

    def bin_query(self, type_name: str, ecql="INCLUDE",
                  track: str | None = None, label: str | None = None,
                  sort: bool = False) -> bytes:
        """BIN aggregation over the loaded partitions (the in-memory
        scan core computes it; partition pruning still applies through
        its query path)."""
        st = self._state(type_name)
        mem = self._load(st, self._files_for(st, None))
        return mem.bin_query(type_name, ecql, track=track, label=label,
                             sort=sort)

    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None) -> bytes:
        st = self._state(type_name)
        mem = self._load(st, self._files_for(st, None))
        return mem.arrow_ipc(type_name, ecql, sort_by=sort_by)

    def reindex(self, type_name: str, to_version: int | None = None):
        """Migrate the type's z-index layout: record the new version in
        the durable metadata, drop the old version's sidecars (their
        sort orders are meaningless under the new curve — load_state
        also rejects them by version), and rebuild loaded stores."""
        import shutil
        from ..features.sft import Configs, check_index_version
        to_version = check_index_version(to_version)
        st = self._state(type_name)
        if st.sft.index_version == to_version:
            return
        st.sft.user_data[Configs.INDEX_VERSION] = to_version
        meta_path = os.path.join(st.root, "metadata.json")
        with open(meta_path) as fh:
            meta = json.load(fh)
        meta["spec"] = st.sft.to_spec()
        tmp = meta_path + f".tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(meta, fh, indent=2)
        os.replace(tmp, meta_path)
        shutil.rmtree(st.index_dir, ignore_errors=True)
        # loaded stores may share the sft object (full loads) or hold a
        # projected copy; set the version on each and mark dirty so the
        # next read rebuilds under the new curve
        for mem in st.cache.values():
            try:
                ms = mem._state(type_name)
            except KeyError:
                continue
            ms.sft.user_data[Configs.INDEX_VERSION] = int(to_version)
            ms.dirty = True
        st.pending_sidecar.clear()

    def compact(self, type_name: str):
        """Merge each partition's files into one (fs/tools/compact analog)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        st = self._state(type_name)
        for part in self.partitions(type_name):
            pdir = os.path.join(st.data_dir, part)
            files = [os.path.join(pdir, f) for f in sorted(os.listdir(pdir))
                     if f.endswith(".parquet")]
            if len(files) <= 1:
                continue
            tables = [pq.read_table(f) for f in files]
            # files may disagree on the optional __vis__ column
            merged = pa.concat_tables(tables, promote_options="default")
            out = os.path.join(pdir, f"{uuid.uuid4().hex[:12]}.parquet")
            pq.write_table(merged, out)
            for f in files:
                os.remove(f)
        st.cache.clear()
        st.pending_sidecar.clear()
