"""File-backed message bus: cross-process transport for the live tier.

The reference's live tier is real network pub/sub — feature mutations
flow through Kafka topics as GeoMessages and consumer offsets checkpoint
in Zookeeper (/root/reference/geomesa-kafka/geomesa-kafka-datastore/src/
main/scala/org/locationtech/geomesa/kafka/data/KafkaDataStore.scala:44,
/root/reference/geomesa-lambda/geomesa-lambda-datastore/src/main/scala/
org/locationtech/geomesa/lambda/stream/ZookeeperOffsetManager.scala:27).

Kafka's essence is a durable, ordered, append-only log per topic with
independent consumer offsets; this module is that design on a shared
filesystem, so two PROCESSES see each other's mutations:

- topic = directory; message = one segment file named by sequence
  number, claimed atomically with O_CREAT|O_EXCL (multi-producer safe)
  and written tmp-then-rename (readers never see partial messages);
- payload = JSON header (kind/ids/timestamp/schema spec) + an Arrow IPC
  stream for create batches — a self-describing wire format, so
  consumers need no out-of-band schema exchange;
- consumers poll for sequence numbers past their offset; offsets
  checkpoint to ``offsets/<group>.json`` after every poll, so a
  restarted consumer resumes where it left off (the checkpointed
  stream-recovery shape of the Lambda tier).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import encode_spec, parse_spec
from .live import GeoMessage

__all__ = ["FileBus"]

_SEQ_DIGITS = 12


def segment_name(seq: int) -> str:
    """Canonical log-segment file name — shared with SocketBroker's
    durable tier so the two transports read each other's logs."""
    return f"{seq:0{_SEQ_DIGITS}d}.msg"


def fsync_dir(path: str):
    """fsync a DIRECTORY so the rename/unlink entries inside it are
    durable — os.replace alone only orders the data, not the dirent; a
    crash can still lose the new name. Best-effort: some filesystems
    refuse directory fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_bytes_atomic(path: str, raw: bytes):
    """Durable atomic write: tmp + fsync + rename + directory fsync
    (readers never see a partial file, and the rename itself survives a
    crash — the WAL checkpoint manifest relies on this). Tmp names are
    pid+thread-unique (the broker persists from handler threads).

    Both the data write and the fsync route through the fault shim
    keyed by the LOGICAL destination path, so chaos tests can tear or
    bit-flip a checkpoint file without knowing the tmp name."""
    import threading

    from ..integrity import faultfs
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        faultfs.write(f, raw, path)
        f.flush()
        faultfs.fsync(f.fileno(), path)
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def write_json_atomic(path: str, obj):
    import threading

    from ..integrity import faultfs
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "wb") as f:
        faultfs.write(f, json.dumps(obj).encode(), path)
        f.flush()
        faultfs.fsync(f.fileno(), path)
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def _encode(msg: GeoMessage) -> bytes:
    header: dict = {"kind": msg.kind, "type_name": msg.type_name,
                    "ids": list(msg.ids), "timestamp_ms": msg.timestamp_ms}
    if msg.visibilities is not None:
        header["vis"] = list(msg.visibilities)
    payload = b""
    if msg.batch is not None:
        import pyarrow as pa
        header["spec"] = encode_spec(msg.batch.sft)
        rb = msg.batch.to_arrow()
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, rb.schema) as w:
            w.write_batch(rb)
        payload = sink.getvalue().to_pybytes()
    h = json.dumps(header).encode()
    return len(h).to_bytes(4, "big") + h + payload


def _decode(raw: bytes) -> GeoMessage:
    hlen = int.from_bytes(raw[:4], "big")
    header = json.loads(raw[4:4 + hlen].decode())
    batch = None
    payload = raw[4 + hlen:]
    if payload:
        import pyarrow as pa
        sft = parse_spec(header["type_name"], header["spec"])
        with pa.ipc.open_stream(pa.BufferReader(payload)) as r:
            rb = r.read_next_batch()
        batch = FeatureBatch.from_arrow(sft, rb)
    vis = header.get("vis")
    return GeoMessage(header["kind"], header["type_name"], batch,
                      tuple(header.get("ids") or ()),
                      header.get("timestamp_ms", 0),
                      None if vis is None else tuple(vis))


class FileBus:
    """Durable multi-process topic log. Same subscribe surface as the
    in-process MessageBus, but delivery is poll-driven: ``publish``
    appends to the shared log; ``poll()`` drains messages past this
    consumer group's offsets into the subscribers."""

    # an empty claimed-but-never-written message file older than this is
    # an aborted publish: consumers skip it instead of wedging the topic
    STALE_CLAIM_S = 5.0

    def __init__(self, root: str, group: str = "default"):
        self.root = root
        self.group = group
        self._subs: dict[str, list[Callable[[GeoMessage], None]]] = {}
        self._offsets: dict[str, int] = {}
        self._next_seq: dict[str, int] = {}  # producer-side cache
        os.makedirs(os.path.join(root, "offsets"), exist_ok=True)
        self._load_offsets()

    # -- offsets (ZookeeperOffsetManager analog) ---------------------------

    def _offsets_path(self) -> str:
        return os.path.join(self.root, "offsets", f"{self.group}.json")

    def _load_offsets(self):
        try:
            with open(self._offsets_path()) as f:
                self._offsets = {k: int(v) for k, v in json.load(f).items()}
        except (FileNotFoundError, json.JSONDecodeError):
            self._offsets = {}

    def _save_offsets(self):
        write_json_atomic(self._offsets_path(), self._offsets)

    def offset(self, topic: str) -> int:
        return self._offsets.get(topic, 0)

    def set_offset(self, topic: str, offset: int):
        """Manual seek (offset = last consumed sequence number)."""
        self._offsets[topic] = int(offset)
        self._save_offsets()

    # -- producer ----------------------------------------------------------

    def _topic_dir(self, topic: str) -> str:
        d = os.path.join(self.root, "topics", topic)
        os.makedirs(d, exist_ok=True)
        return d

    def _last_seq(self, topic: str) -> int:
        d = self._topic_dir(topic)
        seqs = [int(f[:_SEQ_DIGITS]) for f in os.listdir(d)
                if f.endswith(".msg")]
        return max(seqs, default=0)

    def publish(self, topic: str, msg: GeoMessage):
        d = self._topic_dir(topic)
        raw = _encode(msg)
        # the payload is fully written (and fsynced) BEFORE any sequence
        # number is claimed, so the empty-claim window is just a rename
        # — a producer can no longer stall mid-write holding a claim
        tmp = os.path.join(d, f".payload.{os.getpid()}.tmp")
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        # cached next sequence avoids an O(topic-size) listdir per
        # publish; contention falls through to the O_EXCL retry loop
        seq = self._next_seq.get(topic)
        if seq is None:
            seq = self._last_seq(topic) + 1
        while True:
            name = f"{seq:0{_SEQ_DIGITS}d}.msg"
            try:
                # claim the sequence number atomically across processes
                fd = os.open(os.path.join(d, name),
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                seq += 1
                continue
            try:
                os.replace(tmp, os.path.join(d, name))
            finally:
                os.close(fd)
            self._next_seq[topic] = seq + 1
            return seq

    # -- consumer ----------------------------------------------------------

    def subscribe(self, topic: str, fn: Callable[[GeoMessage], None]):
        self._subs.setdefault(topic, []).append(fn)

    def poll(self, max_messages: int | None = None) -> int:
        """Drain new messages on all subscribed topics to their
        subscribers, in sequence order; checkpoints offsets. Returns the
        number of messages delivered."""
        delivered = 0
        advanced = False
        # snapshot: a subscriber may register new topics mid-delivery
        # (consumer-side schema auto-create)
        for topic, fns in list(self._subs.items()):
            d = self._topic_dir(topic)
            start = self._offsets.get(topic, 0)
            seqs = sorted(int(f[:_SEQ_DIGITS]) for f in os.listdir(d)
                          if f.endswith(".msg")
                          and int(f[:_SEQ_DIGITS]) > start)
            for seq in seqs:
                path = os.path.join(d, f"{seq:0{_SEQ_DIGITS}d}.msg")
                try:
                    with open(path, "rb") as f:
                        raw = f.read()
                    if raw:
                        msg = _decode(raw)
                    else:
                        msg = None
                except FileNotFoundError:
                    break  # racing a writer: retry next poll
                except (json.JSONDecodeError, ValueError, KeyError):
                    msg = None  # corrupt payload: treat like a claim
                if msg is None:
                    if (time.time() - os.path.getmtime(path)
                            > self.STALE_CLAIM_S):
                        # aborted publish or corrupt persisted message:
                        # messages appear atomically via rename, so it
                        # cannot self-heal — skip past it rather than
                        # wedging every later message on the topic
                        self._offsets[topic] = seq
                        advanced = True
                        continue
                    # fresh: may still be mid-swap; retry next poll
                    break
                for fn in fns:
                    fn(msg)
                self._offsets[topic] = seq
                advanced = True
                delivered += 1
                if max_messages is not None and delivered >= max_messages:
                    break
            if max_messages is not None and delivered >= max_messages:
                break
        if advanced:
            self._save_offsets()
        return delivered

    def wait_for(self, predicate, timeout_s: float = 10.0,
                 interval_s: float = 0.05) -> bool:
        """Poll until predicate() is true or the timeout lapses."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.poll()
            if predicate():
                return True
            time.sleep(interval_s)
        return False
