"""Shared store-layer helpers (used by memory/mesh/lambda stores)."""

from __future__ import annotations

import numpy as np

__all__ = ["sort_order"]


def sort_order(batch, sort_by: str, sort_desc: bool = False,
               idx: np.ndarray | None = None,
               hidden: np.ndarray | None = None) -> np.ndarray:
    """Stable argsort of a batch's rows (or the row subset ``idx``) by an
    attribute column — the SortingSimpleFeatureIterator analog
    (reference utils/iterators/SortingSimpleFeatureIterator:22). Returns
    positions into ``idx`` (or into the batch when ``idx`` is None).

    ``hidden`` (aligned with idx) marks rows whose sort value the
    caller is not authorized to see: they sort as NULL (last), so the
    returned order cannot leak hidden values."""
    col = batch.col(sort_by)
    keys = getattr(col, "values", None)
    if keys is None:
        keys = getattr(col, "millis", None)
    if keys is None:
        codes = getattr(col, "codes", None)
        if codes is not None:
            # dictionary-encoded strings: the vocab is sorted, so code
            # order IS lexicographic order; nulls (-1) sort last
            keys = np.where(codes < 0, np.iinfo(codes.dtype).max, codes)
    if keys is None:
        raise ValueError(f"cannot sort by {sort_by}")
    if idx is not None:
        keys = keys[idx]
    if hidden is not None and hidden.any():
        keys = np.where(hidden, np.inf if keys.dtype.kind == "f"
                        else np.iinfo(keys.dtype).max, keys)
        # ints saturate rather than NaN; ties among hidden rows keep
        # the stable (scan) order, revealing nothing
    order = np.argsort(keys, kind="stable")
    if sort_desc:
        order = order[::-1]
    return order
