"""The DataStore SPI: the pluggable-backend contract.

The reference's public surface is the GeoTools DataStore SPI — every
backend (Accumulo, HBase, Cassandra, fs, memory, Kafka, Lambda)
implements the same schema/write/query interface, and backends plug
into the planner core through IndexAdapter's small abstract member set
(/root/reference/geomesa-index-api/src/main/scala/org/locationtech/
geomesa/index/index/IndexAdapter.scala:24-102, GeoMesaDataStore.scala:38).

Here the contract is this ABC: a backend supplies schema management,
batch writes, and ``query`` (a ``Query`` in, a ``QueryResult`` of ids +
columns out). The planner/kernel core is shared — memory, filesystem,
live, lambda and mesh-distributed stores are all implementations, and
``tests/test_datastore_contract.py`` runs the same black-box battery
over every one of them (the TestGeoMesaDataStore pattern of the
reference's index-api test suite).
"""

from __future__ import annotations

import abc
from typing import Any, Iterator

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType
from ..index.api import Query

__all__ = ["DataStore"]


class DataStore(abc.ABC):
    """Pluggable datastore contract (GeoTools DataStore SPI analog)."""

    # -- schema management ---------------------------------------------------

    @abc.abstractmethod
    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        """Register a feature type (sft object, or name + spec string)."""

    @abc.abstractmethod
    def get_schema(self, type_name: str) -> SimpleFeatureType:
        """The schema for a type; KeyError if absent."""

    @abc.abstractmethod
    def get_type_names(self) -> list[str]:
        """All registered type names."""

    def remove_schema(self, type_name: str):
        """Drop a feature type and its data. Part of the SPI (the CLI
        and web server call it polymorphically); backends without a
        removal story must say so explicitly rather than AttributeError."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support schema removal")

    # -- writes ----------------------------------------------------------------

    @abc.abstractmethod
    def write(self, type_name: str, batch: FeatureBatch, **kwargs):
        """Append a feature batch."""

    def write_dict(self, type_name: str, ids, data: dict[str, Any],
                   **kwargs):
        """Convenience: build a batch from {attribute: array} and write."""
        self.write(type_name,
                   FeatureBatch.from_dict(self.get_schema(type_name),
                                          ids, data), **kwargs)

    def write_many(self, type_name: str,
                   pairs: list[tuple[FeatureBatch, Any]]):
        """Group-commit: coalesce staged (batch, visibilities) pairs
        into ONE backend write. The fused batch pays a single journal
        append / fsync decision and a single state append on durable
        stores, and is sliced once across partition groups on the
        cluster store — per-caller writes would pay all of that per
        batch. Returns the backend write's return value (e.g. an LSN
        vector)."""
        batches = [b for b, _ in pairs]
        if not batches:
            return None
        if len(batches) == 1:
            return self.write(type_name, batches[0],
                              visibilities=pairs[0][1])
        fused = FeatureBatch.concat_all(batches)
        if all(v is None for _, v in pairs):
            vis = None
        else:
            import numpy as np
            parts = [np.full(b.n, None, dtype=object) if v is None
                     else np.asarray(v, dtype=object) for b, v in pairs]
            vis = np.concatenate(parts) if parts else None
        return self.write(type_name, fused, visibilities=vis)

    # -- queries -----------------------------------------------------------

    @abc.abstractmethod
    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None):
        """Run a query; returns a QueryResult (ids, batch, explain,
        plan). A string argument is ECQL and requires type_name."""

    @abc.abstractmethod
    def count(self, type_name: str) -> int:
        """Total stored features of a type."""

    def query_count(self, q: Query | str,
                    type_name: str | None = None) -> int:
        """Matching-feature count. Default materializes the result;
        backends override with count-only fast paths (the EXACT_COUNT
        / geomesa.force.count shape of the reference)."""
        return self.query(q, type_name).n

    def query_stream(self, q: Query | str, type_name: str | None = None,
                     batch_rows: int | None = None
                     ) -> Iterator[FeatureBatch]:
        """Stream matching features as fixed-size FeatureBatch slices
        (``geomesa.stream.batch.rows`` each). Default runs the
        vectorized scan and slices the materialized result — the
        uniform surface the streaming wire/CLI/cluster paths consume;
        wire-native backends (RemoteDataStore, ClusterDataStore)
        override with true incremental streams."""
        from ..arrow.delta import slice_batches
        res = self.query(q, type_name)
        return slice_batches(res.batch, batch_rows)

    # -- shared conveniences -------------------------------------------------

    def features(self, type_name: str,
                 ecql: str = "INCLUDE") -> Iterator[dict]:
        """Iterate matching features as dicts (reader-style access)."""
        res = self.query(ecql, type_name)
        return res.features()
