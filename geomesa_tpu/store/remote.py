"""Networked datastore client: the DataStore SPI over HTTP.

The reference's remote backends are client stacks speaking a wire
protocol to data-holding servers (Accumulo Thrift scanners/batch
writers, HBase protobuf RPC — SURVEY.md 2.6); queries execute where
the data lives and results stream back. The TPU analog: a
``GeoMesaWebServer`` (web/server.py) fronts any local store — the
fs-backed mesh store for a durable, device-served deployment — and
``RemoteDataStore`` is the client plumbing: schema management, Arrow
batch writes (visibility labels ride a reserved ``__vis__`` column,
the parquet tier's convention), server-side query/count/stats/density
execution, Arrow results decoded back into columnar batches.

    server = GeoMesaWebServer(FsBackedDistributedDataStore(root)).start()
    ds = RemoteDataStore("127.0.0.1", server.port)
    ds.create_schema("pts", "*geom:Point:srid=4326")
    ds.write_dict("pts", ids, {"geom": (x, y)})
    ds.query("BBOX(geom, 0, 0, 10, 10)", "pts").ids
"""

from __future__ import annotations

import http.client
import io
import json
import time
from typing import Any
from urllib.parse import quote, urlencode

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType, parse_spec
from ..resilience import (BreakerBoard, HedgePolicy, RetryBudget,
                          RetryPolicy)
from ..resilience.breaker import CLOSED
from ..index.api import FilterStrategy, Query, QueryHints
from .api import DataStore

__all__ = ["RemoteDataStore"]


class RemoteError(RuntimeError):
    """Server-reported failure. ``status`` is the HTTP code;
    ``retryable`` tells RetryPolicy whether another attempt is safe
    (5xx on idempotent calls, 503 sheds always — the server guarantees
    a shed request was never executed)."""

    def __init__(self, msg: str, status: int = 0,
                 retryable: bool = False,
                 retry_after_s: float | None = None):
        super().__init__(msg)
        self.status = status
        self.retryable = retryable
        if retry_after_s is not None:
            self.retry_after_s = retry_after_s


def _breaker_counts(exc: BaseException) -> bool:
    """Transport faults and 5xx responses trip the breaker; a
    well-formed 4xx proves the endpoint alive."""
    if isinstance(exc, RemoteError):
        return exc.status >= 500
    return isinstance(exc, (ConnectionError, TimeoutError, OSError,
                            http.client.HTTPException))


class RemoteDataStore(DataStore):
    """DataStore client over the GeoMesaWebServer wire surface.

    Transient network faults are absorbed client-side (the role the
    reference delegates to Accumulo/HBase client stacks): idempotent
    calls — every GET, plus connect-phase failures and 503 sheds on
    writes — retry with full-jitter backoff under a shared retry
    budget, and a per-endpoint circuit breaker fast-fails once an
    endpoint looks dead instead of burning ``timeout_s`` per call.

    Idempotent GETs additionally HEDGE (resilience/hedge.py): once an
    endpoint's latency EWMA has a p99-ish estimate, each attempt waits
    that long for an answer, then launches one speculative second
    attempt — first success wins, the loser is discarded. Hedges are
    charged to the same retry budget, never fire on writes, and are
    suppressed while the endpoint's breaker isn't CLOSED (a sick
    endpoint needs shed load, not doubled load). ``hedge=False``
    disables; a ``HedgePolicy`` instance overrides the default."""

    def __init__(self, host: str, port: int, timeout_s: float = 60.0,
                 auth_token: str | None = None,
                 retry_policy: RetryPolicy | None = None,
                 breakers: BreakerBoard | None = None,
                 hedge: HedgePolicy | bool | None = None,
                 audit=None):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.auth_token = auth_token  # bearer token for gated endpoints
        self.audit = audit  # AuditLogger or None (global fallback)
        self._schemas: dict[str, SimpleFeatureType] = {}
        self._retry = retry_policy if retry_policy is not None \
            else RetryPolicy(budget=RetryBudget())
        self._breakers = breakers if breakers is not None else BreakerBoard()
        if hedge is False:
            self._hedge = None
        elif hedge is None or hedge is True:
            self._hedge = HedgePolicy(budget=self._retry.budget)
        else:
            self._hedge = hedge

    # -- transport ---------------------------------------------------------

    def _request(self, method: str, path: str, params: dict | None = None,
                 body: bytes | None = None, idempotent: bool | None = None):
        if idempotent is None:
            idempotent = method == "GET"
        # breaker per route segment ("/rest/query/t" -> "query"): one
        # dead endpoint fails fast without gating the others
        segs = path.strip("/").split("/")
        endpoint = segs[1] if len(segs) > 1 else (segs[0] or "root")
        breaker = self._breakers.get(endpoint)

        def attempt():
            breaker.acquire()  # CircuitOpenError fast-fail when open
            t0 = time.perf_counter()
            try:
                out = self._do_request(method, path, params, body,
                                       idempotent)
            except Exception as e:
                if _breaker_counts(e):
                    breaker.failure()
                else:
                    breaker.success()
                raise
            breaker.success()
            # only successful attempts feed the latency EWMA: timeouts
            # and resets would teach the p99 the timeout value, and the
            # hedging delay it informs applies to healthy calls
            self._breakers.observe(endpoint, time.perf_counter() - t0)
            return out

        from ..obs import tracer
        with tracer.span("remote", f"{method} {path}") as sp:
            try:
                return self._retry.call(
                    self._maybe_hedged(attempt, breaker, endpoint,
                                       idempotent),
                    name=f"remote.{endpoint}")
            except Exception as e:
                sp.annotate("remote.failed", error=type(e).__name__)
                raise

    def _maybe_hedged(self, attempt, breaker, endpoint: str,
                      idempotent: bool, streaming: bool = False):
        """Wrap one retry attempt in a speculative hedge when every
        eligibility gate passes; otherwise return it untouched. Gates,
        re-checked per call so a flipped knob or a tripped breaker
        takes effect immediately: hedging configured and enabled,
        the call is idempotent (a hedge executes twice; only reads
        survive that invisibly), the call is NOT streaming (a hedged
        chunked response would double-deliver rows to the consumer and
        double-charge the budget for a transfer whose duration scales
        with result size, not endpoint health), the breaker is CLOSED,
        and the endpoint has a latency estimate for the delay."""
        if streaming or self._hedge is None or not idempotent \
                or not HedgePolicy.enabled() or breaker.state != CLOSED:
            return attempt
        delay = self._hedge.delay_s(self._breakers.latency_p99_s(endpoint))
        if delay is None:
            return attempt
        return lambda: self._hedge.call(attempt, delay,
                                        name=f"remote.{endpoint}")

    def _do_request(self, method, path, params, body, idempotent):
        qs = ("?" + urlencode(params)) if params else ""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        headers = {}
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        from ..obs import TRACE_HEADER, tracer
        wire = tracer.inject()
        if wire is not None:
            # the server continues this trace: its web/store spans land
            # under our current span's trace id
            headers[TRACE_HEADER] = wire
        try:
            try:
                conn.connect()
            except OSError as e:
                # connect phase: nothing reached the server, always
                # safe to retry — even for writes
                e.retryable = True
                raise
            try:
                conn.request(method, path + qs, body=body, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
            except (ConnectionError, TimeoutError, OSError,
                    http.client.HTTPException) as e:
                # the request may have executed server-side; only
                # idempotent calls can safely go again
                e.retryable = idempotent
                raise
            if resp.status == 404:
                # the server maps KeyError -> 404; surface the SPI's
                # unknown-type signal so the client stays a drop-in
                try:
                    msg = json.loads(data.decode()).get("error", path)
                except Exception:
                    msg = path
                raise KeyError(msg)
            if resp.status >= 400:
                try:
                    msg = json.loads(data.decode()).get("error", "")
                except Exception:
                    msg = data[:200].decode(errors="replace")
                if resp.status in (503, 429):
                    # load shed (503) or ingest admission refusal (429):
                    # the server refused BEFORE executing, so a retry is
                    # duplicate-safe for any method; honor its explicit
                    # backpressure hint — this is how a remote writer
                    # experiences the ingest governor's blocking put
                    ra = resp.getheader("Retry-After")
                    raise RemoteError(
                        f"{resp.status} {path}: {msg}",
                        status=resp.status, retryable=True,
                        retry_after_s=float(ra) if ra else None)
                raise RemoteError(f"{resp.status} {path}: {msg}",
                                  status=resp.status,
                                  retryable=idempotent
                                  and resp.status >= 500)
            return resp.getheader("Content-Type", ""), data
        finally:
            conn.close()

    def _json(self, method: str, path: str, params=None, body=None):
        _, data = self._request(method, path, params, body)
        return json.loads(data.decode())

    # -- schema management -------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        self._json("POST", f"/rest/schemas/{quote(sft.type_name)}",
                   body=sft.to_spec().encode())
        self._schemas[sft.type_name] = sft

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        if type_name not in self._schemas:
            meta = self._json("GET", f"/rest/schemas/{quote(type_name)}")
            self._schemas[type_name] = parse_spec(type_name,
                                                  meta["spec"])
        return self._schemas[type_name]

    def get_type_names(self) -> list[str]:
        return list(self._json("GET", "/rest/schemas"))

    def remove_schema(self, type_name: str):
        self._json("DELETE", f"/rest/schemas/{quote(type_name)}")
        self._schemas.pop(type_name, None)

    # -- writes ------------------------------------------------------------

    def write(self, type_name: str, batch: FeatureBatch,
              visibilities=None, **kwargs):
        import pyarrow as pa
        table = pa.Table.from_batches([batch.to_arrow()])
        if visibilities is not None:
            vis = np.asarray(visibilities, dtype=object)
            if len(vis) != batch.n:
                raise ValueError("visibilities length mismatch")
            table = table.append_column(
                "__vis__", pa.array([None if v is None else str(v)
                                     for v in vis], pa.string()))
        sink = io.BytesIO()
        with pa.ipc.new_file(sink, table.schema) as w:
            w.write_table(table)
        out = self._json("POST", f"/rest/write/{quote(type_name)}",
                         body=sink.getvalue())
        # durable-LSN stamp when the server journals: the replication
        # router waits on it for its replica ack
        return out.get("lsn")

    def delete(self, type_name: str, ids):
        out = self._json("POST", f"/rest/delete/{quote(type_name)}",
                         body=json.dumps([str(i) for i in ids]).encode())
        return out.get("lsn")

    # -- queries -----------------------------------------------------------

    @staticmethod
    def _as_query(q: Query | str, type_name: str | None) -> Query:
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        return q

    @staticmethod
    def _query_params(q: Query, fmt: str) -> dict:
        params: dict[str, Any] = {"cql": str(q.filter), "format": fmt}
        if q.max_features is not None:
            params["maxFeatures"] = q.max_features
        if q.properties is not None:
            params["properties"] = ",".join(q.properties)
        if q.sort_by is not None:
            params["sortBy"] = q.sort_by
            params["sortOrder"] = "desc" if q.sort_desc else "asc"
        if q.auths is not None:
            params["auths"] = ",".join(q.auths)
        if QueryHints.SAMPLING in q.hints:
            params["sampling"] = q.hints[QueryHints.SAMPLING]
        if QueryHints.SAMPLE_BY in q.hints:
            params["sampleBy"] = q.hints[QueryHints.SAMPLE_BY]
        if QueryHints.QUERY_INDEX in q.hints:
            params["index"] = q.hints[QueryHints.QUERY_INDEX]
        return params

    def _result_sft(self, q: Query) -> SimpleFeatureType:
        sft = self.get_schema(q.type_name)
        if q.properties is not None:
            keep = set(q.properties)
            sft = SimpleFeatureType(
                sft.type_name,
                [a for a in sft.attributes if a.name in keep],
                sft.user_data)
        return sft

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None):
        q = self._as_query(q, type_name)
        params = self._query_params(q, "arrow")
        t0 = time.perf_counter()
        _, data = self._request("GET", f"/rest/query/{quote(q.type_name)}",
                                params)
        t_fetch_ms = (time.perf_counter() - t0) * 1000
        sft = self._result_sft(q)
        import pyarrow as pa
        with pa.ipc.open_file(io.BytesIO(data)) as rd:
            table = rd.read_all()
        batches = [FeatureBatch.from_arrow(sft, rb)
                   for rb in table.to_batches() if rb.num_rows]
        batch = (FeatureBatch.concat_all(batches) if batches
                 else FeatureBatch.from_dict(
                     sft, np.empty(0, dtype=object),
                     {a.name: ((np.empty(0), np.empty(0))
                               if a.type.name == "Point" else [])
                      for a in sft.attributes}))
        from .memory import QueryResult
        from ..index.api import Explainer
        from ..audit import audit_query
        audit_query(self.audit, "remote", q.type_name, str(q.filter),
                    q.hints, 0.0, t_fetch_ms, batch.n, index="remote")
        return QueryResult(batch.ids, batch, Explainer(),
                           FilterStrategy("remote", q.filter, None),
                           n=batch.n)

    # -- streaming reads ---------------------------------------------------

    def _open_stream(self, path: str, params: dict):
        """Open a chunked streaming GET: retries/breakers cover only
        the pre-stream phase (connect + status line); once headers are
        back the connection is handed to the consuming generator. Never
        hedged — see ``_maybe_hedged``."""
        segs = path.strip("/").split("/")
        endpoint = segs[1] if len(segs) > 1 else "root"
        breaker = self._breakers.get(endpoint)
        qs = ("?" + urlencode(params)) if params else ""
        headers = {}
        if self.auth_token:
            headers["Authorization"] = f"Bearer {self.auth_token}"
        from ..obs import TRACE_HEADER, tracer
        wire = tracer.inject()
        if wire is not None:
            headers[TRACE_HEADER] = wire

        def attempt():
            breaker.acquire()
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout_s)
            try:
                try:
                    conn.connect()
                except OSError as e:
                    e.retryable = True
                    raise
                try:
                    conn.request("GET", path + qs, headers=headers)
                    resp = conn.getresponse()
                except (ConnectionError, TimeoutError, OSError,
                        http.client.HTTPException) as e:
                    e.retryable = True  # no stream bytes delivered yet
                    raise
                if resp.status == 404:
                    try:
                        msg = json.loads(resp.read().decode()) \
                            .get("error", path)
                    except Exception:
                        msg = path
                    raise KeyError(msg)
                if resp.status >= 400:
                    data = resp.read()
                    try:
                        msg = json.loads(data.decode()).get("error", "")
                    except Exception:
                        msg = data[:200].decode(errors="replace")
                    raise RemoteError(f"{resp.status} {path}: {msg}",
                                      status=resp.status,
                                      retryable=resp.status in (503,)
                                      or resp.status >= 500)
            except Exception as e:
                conn.close()
                if _breaker_counts(e):
                    breaker.failure()
                else:
                    breaker.success()
                raise
            breaker.success()
            return conn, resp

        return self._retry.call(
            self._maybe_hedged(attempt, breaker, endpoint, True,
                               streaming=True),
            name=f"remote.{endpoint}.stream")

    def query_stream(self, q: Query | str, type_name: str | None = None,
                     batch_rows: int | None = None):
        """Stream matching features as FeatureBatches decoded
        incrementally off a chunked ``format=arrow-stream`` response:
        client-side memory is bounded by one wire batch regardless of
        hit count, and the first batch arrives while the server is
        still encoding the rest. A mid-stream transport fault or
        truncated response raises a typed ``RemoteError`` — never a
        silently-short result (the chunked framing carries an explicit
        end-of-stream marker)."""
        q = self._as_query(q, type_name)
        params = self._query_params(q, "arrow-stream")
        if batch_rows is not None:
            params["batchRows"] = int(batch_rows)
        # resolve the SFT (its own HTTP round-trip) before opening the
        # stream: a failure here must not leak a live connection
        sft = self._result_sft(q)
        conn, resp = self._open_stream(
            f"/rest/query/{quote(q.type_name)}", params)

        def gen():
            import pyarrow as pa
            try:
                try:
                    rd = pa.ipc.open_stream(resp)
                    for rb in rd:
                        if rb.num_rows:
                            yield FeatureBatch.from_arrow(sft, rb)
                    resp.read()  # the chunked terminator must be intact
                except (ConnectionError, TimeoutError, OSError,
                        http.client.HTTPException, pa.ArrowInvalid) as e:
                    raise RemoteError(
                        f"stream interrupted mid-transfer: {e!r}",
                        retryable=False) from e
            finally:
                conn.close()
        return gen()

    def bin_stream(self, q: Query | str, type_name: str | None = None,
                   track: str | None = None, label: str | None = None):
        """Stream the compact BIN wire encoding (16/24-byte records)
        for a query: yields raw record chunks off a chunked
        ``format=bin`` response. Same typed-error contract as
        ``query_stream``."""
        q = self._as_query(q, type_name)
        params = self._query_params(q, "bin")
        if track:
            params["track"] = track
        if label:
            params["label"] = label
        conn, resp = self._open_stream(
            f"/rest/query/{quote(q.type_name)}", params)

        def gen():
            try:
                try:
                    while True:
                        chunk = resp.read(65536)
                        if not chunk:
                            break
                        yield chunk
                except (ConnectionError, TimeoutError, OSError,
                        http.client.HTTPException) as e:
                    raise RemoteError(
                        f"stream interrupted mid-transfer: {e!r}",
                        retryable=False) from e
            finally:
                conn.close()
        return gen()

    def bin_query(self, type_name: str, ecql="INCLUDE",
                  track: str | None = None, label: str | None = None,
                  sort: bool = False) -> bytes:
        """Server-side BIN aggregation (GET /rest/bin) — the contract
        surface every local backend exposes, materialized."""
        params: dict[str, Any] = {"cql": str(ecql or "INCLUDE")}
        if track:
            params["track"] = track
        if label:
            params["label"] = label
        if sort:
            params["sort"] = "true"
        _, data = self._request("GET", f"/rest/bin/{quote(type_name)}",
                                params)
        return data

    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None) -> bytes:
        """Materialized Arrow IPC file bytes, encoded server-side."""
        params: dict[str, Any] = {"cql": str(ecql or "INCLUDE"),
                                  "format": "arrow"}
        if sort_by:
            params["sortBy"] = sort_by
        _, data = self._request("GET", f"/rest/query/{quote(type_name)}",
                                params)
        return data

    def count(self, type_name: str) -> int:
        return int(self._json("GET", f"/rest/count/{quote(type_name)}")
                   ["count"])

    def estimate_count(self, type_name: str, f=None) -> int | None:
        """Server-side sketch cardinality estimate (GET /rest/estimate)
        — the remote leg of the cluster-merged planner estimate. None
        when the server cannot estimate OR cannot be reached: the
        planner treats both as cold stats, never an error."""
        try:
            est = self._json(
                "GET", f"/rest/estimate/{quote(type_name)}",
                params={"cql": str(f) if f is not None else "INCLUDE"}
            )["estimate"]
        except Exception:  # noqa: BLE001 — estimates are advisory
            return None
        return None if est is None else int(est)

    # -- distributed SQL legs ----------------------------------------------
    # POST bodies, but read-only: idempotent=True keeps them eligible
    # for the client's retry/hedge machinery

    def sql_partial(self, stmt: str) -> dict:
        """One shard group's partial-aggregate leg, evaluated server-
        side next to the data (sql/distributed.py wire format)."""
        _, data = self._request("POST", "/rest/sql",
                                params={"mode": "partial"},
                                body=stmt.encode(), idempotent=True)
        return json.loads(data.decode())

    def sql_join_partial(self, spec: dict) -> dict:
        """One shard group's broadcast-join leg: the spec carries the
        statement plus the encoded small side."""
        _, data = self._request("POST", "/rest/sql/join-partial",
                                body=json.dumps(spec).encode(),
                                idempotent=True)
        return json.loads(data.decode())

    def query_count(self, q: Query | str,
                    type_name: str | None = None) -> int:
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        mapped = {QueryHints.SAMPLING, QueryHints.SAMPLE_BY,
                  QueryHints.QUERY_INDEX}
        if set(q.hints) - mapped:
            # a hint the count endpoint cannot express: evaluate via
            # the full query surface so semantics stay exact
            return self.query(q).n
        # hinted/sampled/limited counts evaluate SERVER-side through
        # the same Query parse as /rest/query — the response is one
        # number, never O(n) rows shipped just to be len()'d here
        params: dict[str, Any] = {"cql": str(q.filter)}
        if q.max_features is not None:
            params["maxFeatures"] = q.max_features
        if q.auths is not None:
            params["auths"] = ",".join(q.auths)
        if QueryHints.SAMPLING in q.hints:
            params["sampling"] = q.hints[QueryHints.SAMPLING]
        if QueryHints.SAMPLE_BY in q.hints:
            params["sampleBy"] = q.hints[QueryHints.SAMPLE_BY]
        if QueryHints.QUERY_INDEX in q.hints:
            params["index"] = q.hints[QueryHints.QUERY_INDEX]
        t0 = time.perf_counter()
        n = int(self._json(
            "GET", f"/rest/count/{quote(q.type_name)}", params)["count"])
        from ..audit import audit_query
        audit_query(self.audit, "remote", q.type_name, str(q.filter),
                    q.hints, 0.0, (time.perf_counter() - t0) * 1000, n,
                    index="remote")
        return n

    # -- observability surfaces --------------------------------------------

    def traces(self, limit: int = 50) -> list[dict]:
        """Trace summaries from the server's ring (GET /rest/trace)."""
        return self._json("GET", "/rest/trace", {"limit": limit})

    def trace(self, trace_id: str) -> list[dict]:
        """Full span list for one trace (KeyError if unknown)."""
        return self._json("GET", f"/rest/trace/{quote(trace_id)}")

    def runtime_snapshot(self) -> dict:
        """Runtime telemetry: compile churn, device memory, transfer
        bytes (GET /rest/runtime)."""
        return self._json("GET", "/rest/runtime")

    def slo_status(self) -> dict:
        """SLO burn-rate/alert state (GET /rest/slo)."""
        return self._json("GET", "/rest/slo")

    def qos_status(self) -> dict:
        """Per-tenant QoS state: in-flight caps, row buckets, retry
        budgets (GET /rest/qos)."""
        return self._json("GET", "/rest/qos")

    def profile_collapsed(self) -> str:
        """Collapsed-stack profile text (GET /rest/profile)."""
        _, data = self._request("GET", "/rest/profile")
        return data.decode("utf-8", "replace")

    def audit_events(self, type_name: str | None = None,
                     since_ms: int | None = None) -> list[dict]:
        """Server-side audit events (GET /rest/audit)."""
        params: dict[str, Any] = {}
        if type_name is not None:
            params["type"] = type_name
        if since_ms is not None:
            params["since"] = since_ms
        return self._json("GET", "/rest/audit", params or None)

    # -- server-side analytics ---------------------------------------------

    def stats_query(self, type_name: str, stat_spec: str, ecql=None):
        params = {"stat": stat_spec}
        if ecql:
            params["cql"] = str(ecql)
        return self._json("GET", f"/rest/stats/{quote(type_name)}", params)

    def density(self, type_name: str, ecql, bbox, width: int,
                height: int):
        out = self._json("GET", f"/rest/density/{quote(type_name)}",
                         {"cql": str(ecql or "INCLUDE"),
                          "bbox": ",".join(str(v) for v in bbox),
                          "width": width, "height": height})
        return np.asarray(out["grid"], dtype=np.float32)

    # -- health / replication ------------------------------------------------

    def probe_health(self, timeout_s: float = 1.0) -> bool:
        """One direct liveness probe: no retries, no breaker, short
        timeout — the replication router's failure detector must see
        the primary's real state NOW, not a retry-masked one."""
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout_s)
        try:
            conn.request("GET", "/rest/health")
            resp = conn.getresponse()
            resp.read()
            return resp.status == 200
        except (ConnectionError, TimeoutError, OSError,
                http.client.HTTPException):
            return False
        finally:
            conn.close()

    def replication_status(self) -> dict:
        """GET /rest/replication (server must front a replicated or
        shipping store)."""
        return self._json("GET", "/rest/replication")

    def promote(self) -> dict:
        """POST /rest/replication/promote (bearer-gated like the other
        mutating admin routes)."""
        return self._json("POST", "/rest/replication/promote")

    def cluster_status(self) -> dict:
        """GET /rest/cluster (server must front a ClusterDataStore)."""
        return self._json("GET", "/rest/cluster")

    def promote_group(self, group: str | None = None) -> dict:
        """POST /rest/cluster/promote?group=NAME (bearer-gated):
        force intra-group failover on a cluster coordinator server."""
        params = {"group": group} if group else None
        return self._json("POST", "/rest/cluster/promote", params)

    def topology(self, include_counts: bool = True) -> dict:
        """GET /rest/topology: the cluster's epoch-stamped segment
        map (server must front a ClusterDataStore)."""
        params = None if include_counts else {"counts": "false"}
        return self._json("GET", "/rest/topology", params)

    def reshard_status(self) -> dict:
        """GET /rest/reshard: migrations in flight, epoch history,
        cooldown."""
        return self._json("GET", "/rest/reshard")

    def reshard(self, verb: str, **params) -> dict:
        """POST /rest/reshard/{split|migrate|resume|abort|auto}
        (bearer-gated). Keyword args become query params (e.g.
        ``reshard("split", src="shard2")``)."""
        clean = {k: v for k, v in params.items() if v is not None}
        return self._json("POST", f"/rest/reshard/{quote(verb)}",
                          clean or None)

    def reindex(self, type_name: str,
                to_version: int | None = None) -> dict:
        """POST /rest/reindex/{type}?version= (bearer-gated): the
        BLOCKING reindex oracle — the server holds its store op lock
        for the whole rebuild. Use ``evolve("reindex", ...)`` for the
        online shadow-build migration."""
        params = ({"version": int(to_version)}
                  if to_version is not None else None)
        return self._json("POST", f"/rest/reindex/{quote(type_name)}",
                          params)

    def evolve_status(self) -> dict:
        """GET /rest/evolve: active evolution (phase, cursor, barrier)
        plus completed history."""
        return self._json("GET", "/rest/evolve")

    def evolve(self, verb: str, **params) -> dict:
        """POST /rest/evolve/{reindex|update|resume|abort}
        (bearer-gated). Keyword args become query params; an ``update``
        change list ships in a JSON body (e.g. ``evolve("update",
        type="t", changes=[{"op": "add", ...}])``)."""
        clean = {k: v for k, v in params.items() if v is not None}
        body = None
        changes = clean.pop("changes", None)
        if changes is not None:
            body = json.dumps({"type": clean.pop("type", None),
                               "changes": changes}).encode()
        return self._json("POST", f"/rest/evolve/{quote(verb)}",
                          clean or None, body=body)

    def cache_status(self) -> dict:
        """GET /rest/cache: the server store's materialized-cache
        status (entries, bytes, hit/miss counters, refresher state)."""
        return self._json("GET", "/rest/cache")

    def invalidate_cache(self, type_name: str | None = None) -> int:
        """POST /rest/cache/invalidate[?type=NAME] (bearer-gated);
        returns the number of entries dropped server-side."""
        params = {"type": type_name} if type_name else None
        out = self._json("POST", "/rest/cache/invalidate", params)
        return int(out.get("invalidated", 0))

    def cq_status(self) -> dict:
        """GET /rest/cq: registered continuous queries plus per-type
        device filter-set stats."""
        return self._json("GET", "/rest/cq")

    def cq_register(self, name: str, type_name: str,
                    ecql: str = "INCLUDE") -> dict:
        """POST /rest/cq/register (bearer-gated); the ECQL travels in a
        JSON body, not the query string."""
        body = json.dumps({"name": name, "type": type_name,
                           "ecql": ecql}).encode()
        return self._json("POST", "/rest/cq/register", body=body)

    def cq_unregister(self, name: str) -> dict:
        """POST /rest/cq/unregister?name= (bearer-gated)."""
        return self._json("POST", "/rest/cq/unregister", {"name": name})

    def views_status(self) -> dict:
        """GET /rest/views: registered materialized views with fold
        counters and LSN staleness."""
        return self._json("GET", "/rest/views")

    def views_get(self, name: str) -> dict:
        """GET /rest/views/{name}: the view's rows at its fold LSN."""
        return self._json("GET", f"/rest/views/{quote(name)}")

    def views_register(self, name: str, sql: str) -> dict:
        """POST /rest/views/register (bearer-gated); the standing
        SELECT travels in a JSON body, not the query string."""
        body = json.dumps({"name": name, "sql": sql}).encode()
        return self._json("POST", "/rest/views/register", body=body)

    def views_unregister(self, name: str) -> dict:
        """POST /rest/views/unregister?name= (bearer-gated)."""
        return self._json("POST", "/rest/views/unregister",
                          {"name": name})

    def views_refresh(self, name: str) -> dict:
        """POST /rest/views/refresh?name= (bearer-gated): full
        re-execution — the O(table) baseline the folds replace."""
        return self._json("POST", "/rest/views/refresh", {"name": name})
