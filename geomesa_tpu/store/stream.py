"""Generic streaming sources: raw feeds -> converter -> queryable cache.

The analog of geomesa-stream (/root/reference/geomesa-stream/
geomesa-stream-generic/src/main/scala/org/locationtech/geomesa/stream/
generic/GenericSimpleFeatureStreamSourceFactory.scala:26 +
geomesa-stream-datastore/.../StreamDataStore.scala:49): the reference
wires an Apache Camel route (file, netty, ...) through a converter into
an in-memory queryable cache with expiry and listeners. Here the route
is a ``StreamSource`` SPI — anything that yields raw records when
polled — and the cache is the live tier:

    source.poll() -> converter.process(...) -> LiveDataStore cache
                                                (ttl expiry, listeners,
                                                 full query surface)

Built-in sources: ``FileTailSource`` (a growing file, the camel `file:`
route analog) and ``IterableSource`` (any generator/queue). New
transports implement ``poll``.
"""

from __future__ import annotations

import abc
import os
from typing import Any, Iterable

from ..features.sft import SimpleFeatureType, parse_spec
from ..index.api import Query
from .api import DataStore
from .live import LiveDataStore, MessageBus

__all__ = ["StreamSource", "FileTailSource", "IterableSource",
           "StreamDataStore"]


class StreamSource(abc.ABC):
    """SPI: a transport that yields raw records (lines/objects)."""

    @abc.abstractmethod
    def poll(self) -> list[Any]:
        """Records that arrived since the last poll (may be empty)."""


class FileTailSource(StreamSource):
    """Tails a text file: each poll returns complete new lines."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._inode: int | None = None

    def poll(self) -> list[str]:
        try:
            stat = os.stat(self.path)  # one syscall: no exists/size race
        except FileNotFoundError:
            return []  # not created yet / mid-rotation: retry next poll
        if (stat.st_size < self._offset
                or (self._inode is not None
                    and stat.st_ino != self._inode)):
            # truncated in place, or replaced by a new file (rename
            # rotation): restart from the top instead of tailing a
            # stale offset into unrelated bytes
            self._offset = 0
        self._inode = stat.st_ino
        # binary mode: the offset is in BYTES, so multi-byte characters
        # never desynchronize the tail position
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
        except FileNotFoundError:
            return []  # removed between stat and open (rotation)
        # other OSErrors (EACCES, EISDIR, ...) are real
        # misconfigurations and must surface, not silently no-op
        if not chunk:
            return []
        # hold back a trailing partial line until its newline arrives
        complete = chunk.rfind(b"\n")
        if complete < 0:
            return []
        self._offset += complete + 1
        return [ln.decode("utf-8", "replace")
                for ln in chunk[:complete].split(b"\n") if ln]


class IterableSource(StreamSource):
    """Adapts a python iterable/generator; each poll drains up to
    ``batch`` pending records."""

    def __init__(self, it: Iterable, batch: int = 1024):
        self._it = iter(it)
        self.batch = batch

    def poll(self) -> list[Any]:
        out = []
        for _ in range(self.batch):
            try:
                out.append(next(self._it))
            except StopIteration:
                break
        return out


class StreamDataStore(DataStore):
    """A queryable cache fed by a StreamSource through a converter.

    ``tick()`` advances the pipeline one poll; everything else is the
    standard DataStore surface over the live cache (ttl expiry and
    listeners included, StreamDataStore.scala:49's cache semantics).
    """

    def __init__(self, sft: SimpleFeatureType | str,
                 converter_config: dict, source: StreamSource,
                 spec: str | None = None,
                 ttl_millis: int | None = None,
                 bus: MessageBus | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        from ..convert import converter_for
        self.sft = sft
        self.source = source
        self.converter = converter_for(sft, converter_config)
        self._live = LiveDataStore(bus=bus, ttl_millis=ttl_millis)
        self._live.create_schema(sft)

    # -- pipeline ----------------------------------------------------------

    def tick(self) -> int:
        """Poll the source, convert, apply to the cache; returns the
        number of features ingested this tick."""
        records = self.source.poll()
        if not records:
            self._live.expire(self.sft.type_name)
            return 0
        # converters consume text streams: string records join as
        # lines; structured records (dicts/lists from a queue source)
        # serialize to JSON lines for the json converter. Records that
        # serialize to nothing sane become bad-record lines the
        # converter counts as failures, not a dead pipeline.
        import json as _json

        def as_line(r) -> str:
            if isinstance(r, str):
                # records read from file handles keep their newline;
                # strip so the join never produces blank "bad records"
                return r.rstrip("\r\n")
            try:
                return _json.dumps(r)
            except (TypeError, ValueError):
                return str(r)

        payload: Any = "\n".join(as_line(r) for r in records) + "\n"
        batch, ctx = self.converter.process(payload)
        if batch.n:
            self._live.write(self.sft.type_name, batch)
        self._live.expire(self.sft.type_name)
        return batch.n

    def add_listener(self, fn):
        self._live.add_listener(self.sft.type_name, fn)

    def remove_listener(self, fn):
        self._live.remove_listener(self.sft.type_name, fn)

    # -- DataStore surface -------------------------------------------------

    def create_schema(self, sft, spec=None):
        raise NotImplementedError("a stream store is bound to one type")

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._live.get_schema(type_name)

    def get_type_names(self) -> list[str]:
        return self._live.get_type_names()

    def write(self, type_name: str, batch, **kwargs):
        self._live.write(type_name, batch, **kwargs)

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None):
        return self._live.query(q, type_name, explain_out=explain_out)

    def count(self, type_name: str) -> int:
        return self._live.count(type_name)
