"""Durable sharded tier: fs-store partitions served by the device mesh.

The reference's scale story is a durable, partitioned table (tablets on
Accumulo/HBase, parquet partitions on the fs store) scanned by many
servers. The TPU analog pairs the two tiers this repo already has:

- **durability** — parquet partitions + metadata catalog + index
  sidecars from the fs store (store/fs.py; reference
  geomesa-fs/.../FileSystemDataStore.scala:29, partition pruning
  FsQueryPlanning.scala);
- **serving** — the mesh-distributed store (store/mesh_store.py),
  whose device tier shards hot columns over a `jax.sharding.Mesh`.

A `FsBackedDistributedDataStore(root, mesh)` opens the catalog, loads
every partition in deterministic partition order onto the mesh
(recovery = construct again on the same root), and writes through:
every write lands in parquet first, then the serving tier. The z-key
sort orders persist as sidecars under `<type>/index_mesh/` so a reopen
adopts them instead of re-sorting 100M rows.

**Placement note (deviation from the reference, deliberate).** Tablet
servers own whole tablets; here rows shard EVENLY over the device mesh
regardless of partition boundaries. Equal shards are what make XLA's
SPMD collectives (psum over ICI) efficient — honoring partition
boundaries per device would trade balanced compute for a locality the
shard-local kernels never exploit. The partition -> shard relationship
stays available as metadata (`partition_shards`): partitions load in
sorted order, so each maps to a contiguous row range and therefore to a
computable device range (BaseFeatureIndex.getSplits:63-72 is the
reference's equivalent bookkeeping).
"""

from __future__ import annotations

import os

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType, parse_spec
from .fs import FileSystemDataStore, _safe_partition
from .mesh_store import DistributedDataStore

__all__ = ["FsBackedDistributedDataStore"]


class FsBackedDistributedDataStore(DistributedDataStore):
    """Mesh-served datastore whose source of truth is an fs-store root.

    Construction replays the catalog (write -> reopen -> query yields
    identical ids); writes go to parquet first, then the mesh tier.
    """

    def __init__(self, root: str, mesh=None, audit=None):
        super().__init__(mesh=mesh, audit=audit)
        self.root = root
        self.fs = FileSystemDataStore(root)
        # type -> [(partition, lo, hi)] row ranges in load order
        self._partition_rows: dict[str, list[tuple[str, int, int]]] = {}
        for t in self.fs.get_type_names():
            self._adopt(t)

    # -- recovery ----------------------------------------------------------

    def _adopt(self, type_name: str):
        """Replay one type from the durable tier onto the mesh:
        partitions load in sorted order (deterministic row placement),
        then the persisted sort orders install so the reopen skips the
        O(n log n) index build."""
        sft = self.fs.get_schema(type_name)
        super().create_schema(sft)
        ranges: list[tuple[str, int, int]] = []
        row = 0
        batches, vises = [], []
        for part in self.fs.partitions(type_name):
            batch, vis = self.fs.read_partition(type_name, part)
            if batch is None or batch.n == 0:
                continue
            ranges.append((part, row, row + batch.n))
            row += batch.n
            batches.append(batch)
            vises.append(vis)  # None when the partition has no labels
        if batches:
            any_vis = any(v is not None for v in vises)
            vis_all = None
            if any_vis:
                vis_all = np.concatenate(
                    [v if v is not None
                     else np.full(b.n, None, dtype=object)
                     for b, v in zip(batches, vises)])
            super().write(type_name, FeatureBatch.concat_all(batches),
                          visibilities=vis_all)
        self._partition_rows[type_name] = ranges
        self._install_index_sidecar(type_name)

    # -- durable write-through ---------------------------------------------

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None, scheme=None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        self.fs.create_schema(sft, scheme=scheme)
        super().create_schema(sft)
        self._partition_rows[sft.type_name] = []

    def write(self, type_name: str, batch: FeatureBatch,
              visibilities=None):
        raw = self.fs.write(type_name, batch, visibilities=visibilities)
        st = self._state(type_name)
        # on-disk (quoted) names: partition metadata keys must match
        # partitions() so live and reopened stores agree
        names = np.asarray([_safe_partition(p) for p in raw], dtype=str)
        # serve rows grouped by sorted partition — the CANONICAL layout
        # a reopen reproduces (partition dirs load in sorted order), so
        # persisted sort orders stay valid across restarts for the
        # bulk-load-then-reopen flow
        order = np.argsort(names, kind="stable")
        batch = batch.take(order)
        vis = (None if visibilities is None
               else np.asarray(visibilities, dtype=object)[order])
        names = names[order]
        lo = st.n  # includes pending appends
        for part in np.unique(names):
            sel = np.flatnonzero(names == part)
            self._partition_rows.setdefault(type_name, []).append(
                (str(part), lo, lo + len(sel)))
            lo += len(sel)
        super().write(type_name, batch, visibilities=vis)

    def delete(self, type_name: str, ids):
        self.fs.delete(type_name, ids)
        super().delete(type_name, ids)
        # row ranges shift after a delete; recompute lazily on demand
        self._partition_rows[type_name] = []

    def remove_schema(self, type_name: str):
        self.fs.remove_schema(type_name)
        super().remove_schema(type_name)
        self._partition_rows.pop(type_name, None)

    # -- partition / shard metadata ----------------------------------------

    def partitions(self, type_name: str) -> list[str]:
        return self.fs.partitions(type_name)

    def partition_shards(self, type_name: str) -> dict[str, list[int]]:
        """partition -> mesh device indices holding (part of) its rows.
        Rows shard evenly over the mesh in load order, so a partition's
        contiguous row range maps to a device range (see module note).
        Recomputed from the scheme when the tracked ranges went stale
        (deletes shift row positions)."""
        st = self._state(type_name)
        ranges = self._partition_rows.get(type_name)
        # ranges are stale whenever they don't cover every serving row —
        # not just when empty: a write after a delete appends ranges for
        # the NEW rows only, leaving the surviving rows untracked
        covered = sum(hi - lo for _, lo, hi in ranges or [])
        if st.n and covered != st.n:
            ranges = self._recompute_partition_rows(type_name)
        k = self.mesh.devices.size
        n = max(st.n, 1)
        per = (n + k - 1) // k
        out: dict[str, list[int]] = {}
        for part, lo, hi in ranges or []:
            if hi <= lo:
                continue
            d0, d1 = lo // per, (hi - 1) // per
            devs = list(range(int(d0), int(d1) + 1))
            out.setdefault(part, [])
            out[part] = sorted(set(out[part]) | set(devs))
        return out

    def _recompute_partition_rows(self, type_name: str):
        """Re-derive partition row ranges from the scheme over the
        CURRENT serving rows (runs of equal names in row order)."""
        st = self._state(type_name)
        if st.batch is None or st.n == 0:
            return []
        raw = self.fs._state(type_name).scheme.partition_for_rows(
            self.fs.get_schema(type_name), st.batch)
        names = np.asarray([_safe_partition(p) for p in raw], dtype=str)
        edges = np.flatnonzero(
            np.concatenate([[True], names[1:] != names[:-1]]))
        bounds = np.append(edges, len(names))
        ranges = [(str(names[int(lo)]), int(lo), int(hi))
                  for lo, hi in zip(bounds[:-1], bounds[1:])]
        self._partition_rows[type_name] = ranges
        return ranges

    # -- index sidecars ----------------------------------------------------

    def _index_dir(self, type_name: str) -> str:
        return os.path.join(self.root, type_name, "index_mesh")

    def _ids_digest(self, type_name: str) -> str:
        """Layout fingerprint over the FULL id column: sort orders are
        permutations over ROW POSITIONS, so adopting them onto a
        differently-ordered table would silently drop rows — the
        digest must match before a sidecar installs. A strided sample
        is NOT enough: two layouts agreeing on count and every sampled
        position but differing between samples would adopt each
        other's sidecars and serve wrong rows. Hashing is chunked so
        a 100M-id column never builds one giant joined string."""
        import hashlib
        st = self._state(type_name)
        ids = (st.batch.ids if st.batch is not None
               else np.empty(0, dtype=object))
        h = hashlib.sha256(str(len(ids)).encode())
        for lo in range(0, len(ids), 1_000_000):
            part = ids[lo:lo + 1_000_000]
            h.update("\0".join(map(str, part)).encode())
            h.update(b"\0")
        return h.hexdigest()

    def persist_index(self, type_name: str) -> bool:
        """Write the serving tier's built z-key sort orders next to the
        data (the fs store's sidecar pattern, kept per-type here). A
        reopen adopts them via warm_index when the reopened layout
        matches (ids digest)."""
        state = self.index_state(type_name)
        if not state:
            return False
        state = dict(state)
        state["ids_digest"] = np.array([self._ids_digest(type_name)])
        d = self._index_dir(type_name)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, "orders.tmp.npz")  # np.savez needs .npz
        np.savez(tmp, **state)
        os.replace(tmp, os.path.join(d, "orders.npz"))
        return True

    def _install_index_sidecar(self, type_name: str):
        path = os.path.join(self._index_dir(type_name), "orders.npz")
        if not os.path.isfile(path):
            return
        try:
            data = np.load(path, mmap_mode="r")
            state = {k: data[k] for k in data.files}
            digest = str(np.asarray(state.pop("ids_digest", [""]))[0])
            if digest != self._ids_digest(type_name):
                return  # different row layout: lazy rebuild instead
            self.warm_index(type_name, state)
        except Exception:
            pass  # stale/corrupt sidecar: lazy rebuild is the fallback
