"""Continuous queries: standing ECQL filters pushed as deltas.

The streaming inverse of a scan: instead of a client polling
``query()`` for new matches, a ``ContinuousQueryPublisher`` attaches to
a live store's mutation feed (LiveDataStore / StreamDataStore
listeners), evaluates each registered filter against every create
batch with the exact vectorized evaluator (filters/evaluate.py), and
publishes ONLY the matching rows to a per-query topic
(``cq.<name>``). Subscribers receive incremental feature deltas in the
bus wire format (JSON header + Arrow IPC — filebus._encode), or
BIN-encoded chunks via ``on_bin`` — never a full rescan.

Resumability is the broker's offset contract (socketbus.py): over a
``SocketBroker`` the ``cq.*`` topics get server-committed
consumer-group offsets, so a subscriber that dies and reattaches — or
a broker that restarts with ``root=`` persistence — resumes gapless
and duplicate-free from the last committed offset (the
ZookeeperOffsetManager analog). The in-process ``MessageBus`` works
too for single-process pipelines (push delivery, no offsets).

Matching is DEVICE-RESIDENT by default: every registered filter is
compiled into a per-type ``StandingFilterSet`` (scan/standing.py), so
one ingest batch against 100k standing geofences is a single fused
``rows x filters`` kernel launch plus per-filter host patches — not
100k interpreted ``evaluate`` passes. The ``geomesa.cq.device`` kill
switch falls back to the original host loop, which is also the forced
path for stores whose schemas the publisher cannot read (the two paths
publish bit-identical deltas; tests assert it).

Knobs:

- ``geomesa.cq.publish.batch.rows`` caps rows per published delta
  message — a bulk write matching 1M rows streams to subscribers as
  fixed-size messages, not one giant frame.
- ``geomesa.cq.device`` (default true) — fuse standing-filter matching
  into one device dispatch per ingest batch; ``false`` restores the
  per-filter host loop.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from ..filters import evaluate, parse_ecql
from ..metrics import metrics
from ..utils.properties import SystemProperty
from .live import GeoMessage

__all__ = ["ContinuousQuery", "ContinuousQueryPublisher",
           "ContinuousQuerySubscriber", "CQ_PUBLISH_BATCH_ROWS",
           "CQ_DEVICE"]

# rows per published continuous-query delta message: bounds subscriber
# (and broker frame) memory when a bulk write matches many rows
CQ_PUBLISH_BATCH_ROWS = SystemProperty("geomesa.cq.publish.batch.rows",
                                       "8192")

# device-resident standing-filter matching (scan/standing.py); false
# falls back to the per-filter host evaluate loop
CQ_DEVICE = SystemProperty("geomesa.cq.device", "true")


def cq_topic(name: str) -> str:
    return f"cq.{name}"


class ContinuousQuery:
    """One registered standing query: the parsed filter plus counters."""

    __slots__ = ("name", "type_name", "ecql", "filter", "topic",
                 "matched", "published")

    def __init__(self, name: str, type_name: str, ecql: str):
        self.name = name
        self.type_name = type_name
        self.ecql = ecql
        self.filter = parse_ecql(ecql)
        self.topic = cq_topic(name)
        self.matched = 0     # rows that passed the filter
        self.published = 0   # delta messages published


class ContinuousQueryPublisher:
    """Evaluates standing queries against a live store's mutation feed
    and publishes matching deltas to per-query bus topics.

    ``store`` is a LiveDataStore or StreamDataStore (anything with an
    ``add_listener`` feeding GeoMessages); ``bus`` is where ``cq.*``
    deltas go — a SocketBus for cross-process subscribers with
    resumable offsets, or the store's own in-process bus by default.
    """

    def __init__(self, store, bus=None, registry=metrics):
        self.store = store
        self.bus = bus if bus is not None else self._store_bus(store)
        if self.bus is None:
            raise ValueError("no bus: pass bus= or use a store with one")
        self._registry = registry
        self._queries: dict[str, ContinuousQuery] = {}
        self._attached: set[str] = set()
        self._lock = threading.Lock()
        # one StandingFilterSet per type; types whose schema the
        # publisher cannot read stay host-only FOREVER (a set created
        # late would miss earlier registrations)
        self._sets: dict = {}
        self._host_only: set[str] = set()

    @staticmethod
    def _store_bus(store):
        bus = getattr(store, "bus", None)
        if bus is None:
            live = getattr(store, "_live", None)
            bus = getattr(live, "bus", None)
        return bus

    def register(self, name: str, type_name: str,
                 ecql: str = "INCLUDE") -> ContinuousQuery:
        """Add a standing query; raises on a duplicate name or an
        unparseable filter (fail at registration, not per-message)."""
        cq = ContinuousQuery(name, type_name, ecql)
        with self._lock:
            if name in self._queries:
                raise ValueError(f"continuous query {name!r} exists")
            fset = self._set_for(type_name)
            if fset is not None:
                fset.register(name, cq.filter)
            self._queries[name] = cq
            attach = type_name not in self._attached
            if attach:
                self._attached.add(type_name)
            n = len(self._queries)
        if attach:
            self._attach(type_name)
        self._registry.gauge("cq.registered", n)
        return cq

    def unregister(self, name: str):
        """Drop a standing query; detaches the store listener when the
        last query for its type goes (a publisher must not keep
        evaluating types nobody watches)."""
        with self._lock:
            cq = self._queries.pop(name, None)
            detach = None
            if cq is not None:
                fset = self._sets.get(cq.type_name)
                if fset is not None:
                    fset.unregister(name)
                if cq.type_name in self._attached and not any(
                        q.type_name == cq.type_name
                        for q in self._queries.values()):
                    self._attached.discard(cq.type_name)
                    detach = cq.type_name
            n = len(self._queries)
        if detach is not None:
            self._detach(detach)
        self._registry.gauge("cq.registered", n)

    def close(self):
        """Detach every store listener and drop all queries; the
        publisher stops evaluating entirely."""
        with self._lock:
            attached = list(self._attached)
            self._attached.clear()
            self._queries.clear()
            self._sets.clear()
            self._host_only.clear()
        for type_name in attached:
            self._detach(type_name)
        self._registry.gauge("cq.registered", 0)

    def queries(self) -> list[ContinuousQuery]:
        with self._lock:
            return list(self._queries.values())

    def device_stats(self) -> list[dict]:
        """Per-type StandingFilterSet stats (empty when every type is
        on the host path)."""
        with self._lock:
            return [s.stats() for s in self._sets.values()]

    def _set_for(self, type_name: str):
        """The type's StandingFilterSet, created on first registration;
        None (host-only, sticky) when the schema is unreadable —
        e.g. a bus-fed store that has not seen the type yet."""
        if type_name in self._host_only:
            return None
        fset = self._sets.get(type_name)
        if fset is None:
            from ..scan.standing import StandingFilterSet
            try:
                sft = self.store.get_schema(type_name)
                fset = StandingFilterSet(sft, registry=self._registry)
            except Exception:
                self._host_only.add(type_name)
                return None
            self._sets[type_name] = fset
        return fset

    @staticmethod
    def _takes_type(fn) -> bool:
        # LiveDataStore.add_listener(type_name, fn);
        # StreamDataStore.add_listener(fn) — bound to its one type
        import inspect
        return len(inspect.signature(fn).parameters) >= 2

    def _attach(self, type_name: str):
        add = self.store.add_listener
        if self._takes_type(add):
            add(type_name, self._on_message)
        else:
            add(self._on_message)

    def _detach(self, type_name: str):
        remove = getattr(self.store, "remove_listener", None)
        if remove is None:
            return
        if self._takes_type(remove):
            remove(type_name, self._on_message)
        else:
            remove(self._on_message)

    # -- the push path -------------------------------------------------------

    def _on_message(self, msg: GeoMessage):
        with self._lock:
            cqs = [cq for cq in self._queries.values()
                   if cq.type_name == msg.type_name]
            fset = self._sets.get(msg.type_name)
        if not cqs:
            return
        if msg.kind == "create" and msg.batch is not None and msg.batch.n:
            rows = max(CQ_PUBLISH_BATCH_ROWS.as_int() or 8192, 1)
            # one fused rows x filters device dispatch for the whole
            # standing population; any failure falls back to the host
            # loop for this message (both paths emit identical hits)
            device_hits = None
            if fset is not None and len(fset) and CQ_DEVICE.as_bool():
                try:
                    device_hits = fset.dispatch(msg.batch)
                except Exception:
                    self._registry.counter("cq.device.errors")
                    device_hits = None
            for cq in cqs:
                if device_hits is not None:
                    hits = device_hits.get(
                        cq.name, np.empty(0, dtype=np.int64))
                else:
                    mask = evaluate(cq.filter, msg.batch)
                    hits = np.flatnonzero(mask)
                if not len(hits):
                    continue
                cq.matched += len(hits)
                self._registry.counter("cq.rows.matched", len(hits))
                sub = (msg.batch if len(hits) == msg.batch.n
                       else msg.batch.take(hits))
                vis = None
                if msg.visibilities is not None:
                    vis = tuple(np.asarray(msg.visibilities,
                                           dtype=object)[hits])
                for start in range(0, sub.n, rows):
                    piece = (sub if sub.n <= rows else sub.take(
                        np.arange(start, min(start + rows, sub.n))))
                    pvis = (None if vis is None
                            else vis[start:start + rows])
                    self.bus.publish(cq.topic, GeoMessage(
                        "create", msg.type_name, piece,
                        timestamp_ms=msg.timestamp_ms,
                        visibilities=pvis))
                    cq.published += 1
                    self._registry.counter("cq.messages.published")
        elif msg.kind in ("delete", "clear"):
            # retractions forward verbatim: the filter cannot run on
            # ids alone, and deleting absent ids downstream is a no-op
            for cq in cqs:
                self.bus.publish(cq.topic, msg)
                cq.published += 1
                self._registry.counter("cq.messages.published")


class ContinuousQuerySubscriber:
    """The consuming half of one continuous query.

    Connects its own consumer group (``cq.<name>.<group>``) so each
    subscriber's offsets commit independently; ``poll`` drains new
    deltas (long-polling the broker with ``wait_s``), handlers run
    before the offset advances, and the SocketBus channel reconnects
    through broker restarts — with a persistent broker (``root=``)
    resume is gapless and duplicate-free from the last commit.
    """

    def __init__(self, name: str, host: str | None = None,
                 port: int | None = None, group: str = "default",
                 bus=None, timeout_s: float = 30.0):
        self.name = name
        self.topic = cq_topic(name)
        if bus is None:
            if host is None or port is None:
                raise ValueError("pass host/port or bus=")
            from .socketbus import SocketBus
            bus = SocketBus(host, port, group=f"cq.{name}.{group}",
                            timeout_s=timeout_s)
            self._owns_bus = True
        else:
            self._owns_bus = False
        self.bus = bus
        self._handlers: list[Callable[[GeoMessage], None]] = []
        bus.subscribe(self.topic, self._deliver)

    def _deliver(self, msg: GeoMessage):
        for fn in self._handlers:
            fn(msg)

    def on_message(self, fn: Callable[[GeoMessage], None]):
        """Raw delivery: fn(GeoMessage) for every delta (create /
        delete / clear)."""
        self._handlers.append(fn)
        return fn

    def on_batch(self, fn):
        """fn(FeatureBatch) for each create delta's matching rows."""
        def wrap(msg: GeoMessage):
            if msg.kind == "create" and msg.batch is not None:
                fn(msg.batch)
        self._handlers.append(wrap)
        return fn

    def on_bin(self, fn, track: str | None = None,
               label: str | None = None):
        """fn(bytes) — each create delta BIN-encoded over the wire
        format of scan/aggregations.py (bin-over-the-wire push)."""
        from ..scan.aggregations import encode_bin_batch
        def wrap(msg: GeoMessage):
            if msg.kind == "create" and msg.batch is not None \
                    and msg.batch.n:
                fn(encode_bin_batch(msg.batch.sft, msg.batch.ids,
                                    msg.batch, track=track, label=label))
        self._handlers.append(wrap)
        return fn

    def poll(self, wait_s: float = 0.0,
             max_messages: int | None = None) -> int:
        """Drain new deltas; no-op for a push bus (in-process
        MessageBus delivers synchronously on publish)."""
        poll = getattr(self.bus, "poll", None)
        if poll is None:
            return 0
        return poll(max_messages=max_messages, wait_s=wait_s)

    def offset(self) -> int:
        """Last consumed sequence on this query's topic (committed
        server-side for SocketBus groups)."""
        off = getattr(self.bus, "offset", None)
        return off(self.topic) if callable(off) else 0

    def close(self):
        if self._owns_bus:
            close = getattr(self.bus, "close", None)
            if callable(close):
                close()
