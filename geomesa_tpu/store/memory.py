"""In-memory TPU datastore: the end-to-end execution engine.

The reference's in-memory store (geomesa-memory/.../GeoCQEngine.scala:33)
indexes features in CQEngine collections and evaluates queries on the
CPU; here feature batches live as columnar device arrays and queries run
as fused XLA scans:

    write(batch) -> host columns + device scan arrays
    query(q)     -> plan (splitter + cost decider)
                 -> device kernel mask (spatio-temporal, exact via
                    two-float + boundary f64 patch)
                 -> residual filter on surviving candidates (host f64
                    reference evaluator; device compilation later)
                 -> QueryResult (ids / batches / aggregates)

This single-device path is the building block the mesh-sharded store
(parallel/) distributes.
"""

from __future__ import annotations

import dataclasses
import functools
import threading
import weakref
from typing import Any, Iterator

import numpy as np

from ..features.batch import FeatureBatch, PointColumn
from ..features.sft import SimpleFeatureType, parse_spec
from ..filters import ast
from ..filters.ecql import parse_ecql
from ..filters.evaluate import evaluate
from ..filters.helper import extract_geometries, extract_intervals
from ..geometry import Envelope
from ..index.api import Explainer, FilterStrategy, Query, QueryHints
from ..index.planner import decide_strategy
from .api import DataStore
from ..scan import gscan, zscan
from ..stats import DataStoreStats, parse_stat
from ..utils.properties import SystemProperty
from ..utils.threads import ThreadManagement

# process-wide query reaper (ThreadManagement.scala's 5s sweep)
_REAPER = ThreadManagement()

# dense-scan kernel selection: "xla" (default) or "pallas" — the
# hand-tiled kernel (scan/pallas_scan.py) is numerically identical and
# parity-tested; the flag mirrors the reference's pluggable iterator
# stack selection (AccumuloIndexAdapter.scanConfig choosing iterators)
SCAN_KERNEL = SystemProperty("geomesa.scan.kernel", "xla")

# index-pruned candidate sets at or below this size evaluate exactly on
# host in f64 (one vectorized pass over the gathered rows) instead of a
# device round trip — per-query latency is then index-search +
# candidate-sized work, not dispatch-floor bound. Larger candidate sets
# ride the device kernels where HBM bandwidth wins.
HOST_SCAN_ROWS = SystemProperty("geomesa.scan.host.rows", "2000000")

# the extent pruned path re-checks candidates with per-geometry exact
# predicates (Python-loop scale, not the vectorized point math), so its
# crossover back to the dense device tristate sits much lower
EXTENT_HOST_SCAN_ROWS = SystemProperty("geomesa.scan.extent.host.rows",
                                       "50000")

# point-in-polygon residuals below this row count stay on the host
# (vectorized crossing-number, ~tens of M rows/s): a device dispatch
# pays a round trip that only amortizes over large candidate sets
_DEVICE_PIP_ROWS = 2_000_000

# pre-compile the dwithin/KNN join-kernel shape family at bulk-ingest
# time (analytics/join.prewarm_join_kernels): the compile (or its
# persistent-cache load) runs inside the untimed load phase, so the
# first join/KNN query pays milliseconds, not a multi-second XLA
# compile — the join-path analog of the eager z-index build below
JOIN_PREWARM = SystemProperty("geomesa.join.prewarm", "true")

__all__ = ["InMemoryDataStore", "QueryResult"]


class _PlanArtifacts:
    """Filter-derived plan state reused across identical queries
    (cached next to the FilterStrategy in _TypeState.plan_cache):
    query geometries/boxes/intervals and the device scan-query struct.
    All fields derive from the immutable filter AST only, never from
    the data, so they survive until the plan cache is invalidated."""

    __slots__ = ("geoms", "boxes", "intervals", "needs_exact",
                 "spatial_f", "sq", "filled")

    def __init__(self):
        self.filled = False
        self.sq = None


class _LazyBatch:
    """Deferred result materialization: the source batch snapshot (the
    columnar arrays are immutable — writes build new objects) plus the
    matched rows. The column copies happen only if a caller actually
    reads ``result.batch`` — id-only consumers (counts, exactness
    checks, bench loops) never pay them. The reference's feature
    readers are lazy in the same way (KryoBufferSimpleFeature)."""

    def __init__(self, source: FeatureBatch, idx: np.ndarray,
                 properties, row_order: bool = True):
        self.source = source
        self.idx = idx
        self.properties = properties
        # False when the caller reordered idx (sort_by): the endpoint
        # identity check below would misread a permutation as identity
        self.row_order = row_order
        self._mat: FeatureBatch | None = None

    def detach(self):
        """Break the pin on the source snapshot (the store calls this
        when data mutates): small results materialize — the copy is
        trivial, and an unread small result must not keep a superseded
        multi-GB snapshot alive. Large results stay lazy (pre-existing
        policy: their consumers read the columns soon, and the copy is
        the expensive part)."""
        if self._mat is None and len(self.idx) <= 10_000:
            self.materialize()

    def materialize(self) -> FeatureBatch:
        if self._mat is not None:
            return self._mat
        if (self.row_order and self.properties is None
                and len(self.idx) == self.source.n
                and self.idx[0] == 0 and self.idx[-1] == self.source.n - 1):
            # full-table result in ASCENDING row order (the scan
            # strategies all return sorted indices), so endpoint +
            # length checks imply identity: the immutable source
            # snapshot IS the result — an INCLUDE scan over 100M rows
            # must not copy every column
            self._mat = self.source
            return self._mat
        batch = self.source.take(self.idx)
        if self.properties is not None:
            cols = {p: batch.columns[p] for p in self.properties}
            batch = FeatureBatch(
                _project_sft(self.source.sft, self.properties),
                batch.ids, cols)
        self._mat = batch
        self.source = None  # release the snapshot pin
        return batch


class QueryResult:
    """Result of a feature query.

    ``batch`` materializes lazily when the store handed over a
    _LazyBatch; id-only consumers never pay the column copies. ``None``
    means the store/type held no data at all — a zero-hit query still
    yields an (empty) batch.
    """

    def __init__(self, ids, batch, explain: Explainer,
                 plan: FilterStrategy, n: int | None = None):
        # ids may be a thunk: the object-array id gather at 10M+ rows
        # costs more than many whole queries, and join/count consumers
        # never read it
        self._ids = ids
        self._n = n if n is not None else len(ids)
        self._batch = batch          # FeatureBatch | None | _LazyBatch
        self.explain = explain
        self.plan = plan

    @property
    def ids(self) -> np.ndarray:
        if callable(self._ids):
            self._ids = self._ids()
        return self._ids

    @property
    def batch(self) -> FeatureBatch | None:
        if isinstance(self._batch, _LazyBatch):
            self._batch = self._batch.materialize()
        return self._batch

    @batch.setter
    def batch(self, value):
        self._batch = value

    @property
    def n(self) -> int:
        return self._n

    def features(self) -> Iterator[dict[str, Any]]:
        if self.batch is None:
            return iter(())
        return (self.batch.feature(i) for i in range(self.batch.n))

    def __repr__(self) -> str:
        return (f"QueryResult(n={self.n}, "
                f"plan={self.plan.index if self.plan else None})")


def _attr_vis_masks(vis_rows, n_attr: int, auths) -> np.ndarray:
    """(len(rows), n_attr) bool authorization matrix for
    attribute-level visibility labels (comma-joined per attribute;
    empty part = world-readable). Distinct label combos are parsed
    once."""
    from ..security import parse_visibility
    out = np.ones((len(vis_rows), n_attr), dtype=bool)
    cache: dict[str, np.ndarray] = {}
    auth_set = set(auths)
    for i, v in enumerate(vis_rows):
        if not v:
            continue
        row = cache.get(v)
        if row is None:
            parts = (str(v).split(",") + [""] * n_attr)[:n_attr]
            row = np.array(
                [not p or parse_visibility(p).evaluate(auth_set)
                 for p in parts], dtype=bool)
            cache[v] = row
        out[i] = row
    return out


def _null_cells(col, bad: np.ndarray):
    """Copy of a column with `bad` rows nulled (unauthorized
    attribute values at query time)."""
    import dataclasses as _dc

    from ..features.batch import GeometryColumn, StringColumn
    if isinstance(col, StringColumn):
        codes = col.codes.copy()
        codes[bad] = -1
        return StringColumn(col.name, codes, col.vocab)
    if isinstance(col, GeometryColumn):
        geoms = [None if b else g for g, b in zip(col.geoms, bad)]
        bounds = col.bounds.copy()
        bounds[bad] = np.nan
        return GeometryColumn(col.name, geoms, bounds)
    return _dc.replace(col, valid=np.asarray(col.valid) & ~bad)


class _TypeState:
    """Per-feature-type storage: host batch + lazily-built device index.

    Writes are LSM-style: appends land in a pending buffer (O(delta));
    the first read flushes the buffer — one concat, and already-built
    sort orders are MERGED with the delta (ZKeyIndex.extend sorted-run
    merge, device-side scan-array concat) instead of rebuilt from
    scratch. The reference gets the same shape from BatchWriter
    mutations merging into tablets at minor compaction
    (accumulo/util/GeoMesaBatchWriterConfig.scala)."""

    def __init__(self, sft: SimpleFeatureType):
        self.sft = sft
        # guards the lazy read-side mutations (pending flush, index
        # build, deferred device upload): process helpers reach these
        # through the state object directly, without the store-level
        # _op_lock, so two concurrent fused dispatches must not race a
        # rebuild. Store ops already hold _op_lock when they get here —
        # the order is always store lock -> state lock, never reversed.
        self._state_lock = threading.RLock()
        self._batch: FeatureBatch | None = None
        self._pending: list[tuple[FeatureBatch, np.ndarray]] = []
        self._pending_n = 0
        self._scan_data: zscan.DeviceScanData | None = None
        self._scan_thunk = None  # deferred device build (see scan_data)
        self.extent_data = None  # gscan.ExtentScanData for non-points
        self.zindex = None       # index.zkeys.ZKeyIndex for points
        self._host_xhi: np.ndarray | None = None
        self._host_yhi: np.ndarray | None = None
        # lazily-built sorted attribute indexes (AttributeIndex analog)
        self.attr_idx: dict[str, Any] = {}
        # lazy device uploads of attribute columns for residual kernels
        self.devcols = None  # scan.residual.DeviceColumns
        # lazily-built tiled columns for the Pallas kernel (flag-gated)
        self.pallas_data = None
        self.dirty = False
        # per-feature visibility expressions (None = world-readable);
        # has_vis avoids an O(n) object-array scan on every query.
        # Attribute-level schemas store comma-joined per-attribute
        # labels in the same array (split lazily at query time).
        self.vis: np.ndarray = np.empty(0, dtype=object)
        self.has_vis = False
        # persisted sort orders to install into the next-built zindex
        # (fs-store index sidecars); consumed by ensure_index
        self.zindex_warm: dict | None = None
        # (filter, hints) -> (filter_ref, FilterStrategy, _PlanArtifacts):
        # repeated queries skip the splitter/cost decision and the
        # filter-side geometry/interval extraction (the reference keeps
        # the same artifacts on its QueryPlan). Cleared on any data
        # mutation — costs and n_features feed the decision.
        self.plan_cache: dict = {}
        # outstanding lazy results: on data mutation, small ones are
        # detached (materialized) so they stop pinning the superseded
        # column snapshot
        self.live_lazy: "weakref.WeakSet" = weakref.WeakSet()

    @property
    def scan_data(self):
        """The device point-scan arrays, uploaded ON FIRST DEVICE USE:
        ensure_index defers the host->device column transfer (the
        dominant cold-start cost at 100M rows) so selective queries
        answered by the host z-index fast path never pay it. Reading
        this property materializes the upload."""
        if self._scan_data is None and self._scan_thunk is not None:
            with self._state_lock:
                if self._scan_thunk is not None:
                    self._scan_data = self._scan_thunk()
                    self._scan_thunk = None
        return self._scan_data

    @scan_data.setter
    def scan_data(self, value):
        self._scan_data = value
        self._scan_thunk = None

    def _deferred_scan_build(self):
        """Thunk over the CURRENT batch: reads state at materialize
        time, so successive deferred extends just re-defer."""
        def build():
            geom = self.sft.geom_field
            dtg = self.sft.dtg_field
            col = self._batch.col(geom)
            millis = (self._batch.col(dtg).millis if dtg is not None
                      else np.zeros(self._batch.n, dtype=np.int64))
            return zscan.build_scan_data(col.x, col.y, millis)
        return build

    @property
    def host_xhi(self) -> np.ndarray | None:
        self._ensure_host_split()
        return self._host_xhi

    @property
    def host_yhi(self) -> np.ndarray | None:
        self._ensure_host_split()
        return self._host_yhi

    def _ensure_host_split(self):
        """Two-float hi parts of the coordinates, built on first use by
        the boundary-patch/device tiers (deferred like scan_data)."""
        if (self._host_xhi is None and self._batch is not None
                and self.sft.geom_field is not None):
            col = self._batch.col(self.sft.geom_field)
            if isinstance(col, PointColumn):
                self._host_xhi, _ = zscan.split_two_float(col.x)
                self._host_yhi, _ = zscan.split_two_float(col.y)

    @property
    def n(self) -> int:
        return (0 if self._batch is None else self._batch.n) \
            + self._pending_n

    @property
    def batch(self) -> FeatureBatch | None:
        self.flush()
        return self._batch

    def validate(self, batch: FeatureBatch, visibilities=None):
        """Pre-flight append checks WITHOUT mutating — also the durable
        write path's guard: a record must be known applyable before it
        is journaled, or replay would re-fail on it. Returns the
        normalized (vis array, distinct labels)."""
        if visibilities is None:
            # fast path: no O(n) object scan for the common open write
            vis = np.full(batch.n, None, dtype=object)
            distinct = set()
        else:
            vis = np.asarray(visibilities, dtype=object)
            distinct = set(v for v in vis.tolist() if v)
        if len(vis) != batch.n:
            raise ValueError("visibilities length mismatch")
        from ..security import validate_labels
        validate_labels(self.sft, distinct)  # raises on malformed
        return vis, distinct

    def append(self, batch: FeatureBatch, visibilities=None):
        # validate everything BEFORE mutating: a failed write must not
        # leave batch/vis misaligned
        vis, distinct = self.validate(batch, visibilities)
        if distinct:
            self.has_vis = True
        self._pending.append((batch, vis))
        self._pending_n += batch.n
        self.plan_cache.clear()
        self._detach_live()

    def _detach_live(self):
        """Materialize outstanding small lazy results so they release
        the about-to-be-superseded column snapshot."""
        for lb in list(self.live_lazy):
            lb.detach()
        self.live_lazy.clear()

    def has_point_scan(self) -> bool:
        """Whether a device point-scan structure is built or deferred
        (subclasses redefine what that structure is — e.g. mesh-sharded
        segments). Checking must NOT force the deferred upload."""
        return (self._scan_data is not None
                or self._scan_thunk is not None)

    def has_extent_scan(self) -> bool:
        return self.extent_data is not None

    def flush(self):
        """Materialize pending appends: one concat for the burst, then
        incremental index maintenance when the index is already built."""
        with self._state_lock:
            self._flush_locked()

    def _flush_locked(self):
        if not self._pending:
            return
        delta = FeatureBatch.concat_all([b for b, _ in self._pending])
        base = self._batch
        can_merge = (base is not None and not self.dirty
                     and self.has_point_scan()
                     and self.zindex is not None)
        # build everything BEFORE mutating state: a MemoryError on the
        # big concat must leave the store consistent (batch/vis/pending
        # aligned), matching append()'s fail-atomic contract
        new_batch = delta if base is None else base.concat(delta)
        new_vis = np.concatenate([self.vis]
                                 + [v for _, v in self._pending])
        self._batch = new_batch
        self.vis = new_vis
        self._pending = []
        self._pending_n = 0
        # merged indexes go stale per-column; rebuild those lazily
        self.attr_idx.clear()
        self.devcols = None
        self.pallas_data = None
        # pessimistically dirty: if index maintenance below fails midway,
        # the next read must rebuild rather than scan a short index
        self.dirty = True
        if not can_merge:
            return
        geom = self.sft.geom_field
        col = delta.col(geom) if geom else None
        if not isinstance(col, PointColumn):
            return
        dtg = self.sft.dtg_field
        dmillis = (delta.col(dtg).millis if dtg is not None
                   else np.zeros(delta.n, dtype=np.int64))
        # device first: when it declines (segment-cap compaction), the
        # O(n) zindex sorted-run merge must not have been paid for
        # nothing; dirty stays True throughout, so a failure at any
        # point still rebuilds on the next read
        if not self._extend_device_index(col, dmillis):
            return  # stays dirty: next read rebuilds (compaction)
        self.zindex = self.zindex.extend(
            col.x, col.y, dmillis if dtg is not None else None)
        self.dirty = False

    def _extend_device_index(self, col: PointColumn,
                             dmillis: np.ndarray) -> bool:
        """Append the delta to the device scan structures; False leaves
        the state dirty so the next read rebuilds from scratch."""
        dxhi, dxlo = zscan.split_two_float(col.x)
        dyhi, dylo = zscan.split_two_float(col.y)
        if self._scan_data is None and self._scan_thunk is not None:
            # device build still deferred: extend the host split (when
            # materialized) and re-defer over the merged batch
            if self._host_xhi is not None:
                self._host_xhi = np.concatenate([self._host_xhi, dxhi])
                self._host_yhi = np.concatenate([self._host_yhi, dyhi])
            self._scan_thunk = self._deferred_scan_build()
            return True
        scan_data = zscan.extend_scan_data(
            self.scan_data, col.x, col.y, dmillis,
            xy_split=(dxhi, dxlo, dyhi, dylo))
        if scan_data is None:
            # capacity exhausted: rebuild once with power-of-two
            # headroom, then future bursts append in place again
            dtg = self.sft.dtg_field
            gcol = self._batch.col(self.sft.geom_field)
            fmillis = (self._batch.col(dtg).millis if dtg is not None
                       else np.zeros(self._batch.n, dtype=np.int64))
            scan_data = zscan.build_scan_data(
                gcol.x, gcol.y, fmillis,
                cap=zscan.next_pow2(self._batch.n + 1))
        # all structures built: publish atomically
        self.scan_data = scan_data
        if self._host_xhi is not None:
            self._host_xhi = np.concatenate([self._host_xhi, dxhi])
            self._host_yhi = np.concatenate([self._host_yhi, dyhi])
        return True

    def delete(self, ids: set[str]):
        # dirty first: the flush skips merge work the delete is about to
        # invalidate anyway
        self.dirty = True
        self.plan_cache.clear()
        self._detach_live()
        self.flush()
        if self._batch is None:
            return
        keep = ~np.isin(self._batch.ids.astype(str), list(ids))
        self._batch = self._batch.take(np.flatnonzero(keep))
        self.vis = self.vis[keep]
        self.attr_idx.clear()
        self.devcols = None
        self.pallas_data = None
        self.dirty = True

    def ensure_index(self):
        """(Re)build device arrays if writes happened."""
        with self._state_lock:
            self._ensure_index_locked()

    def _ensure_index_locked(self):
        self.flush()  # may maintain the index incrementally
        if not self.dirty and (self.has_point_scan()
                               or self.has_extent_scan()):
            return
        if self.batch is None or self.batch.n == 0:
            self._clear_device_index()
            self.dirty = False
            return
        geom = self.sft.geom_field
        dtg = self.sft.dtg_field
        col = self.batch.col(geom) if geom else None
        if not isinstance(col, PointColumn):
            # extent geometries: device bbox tristate scan (XZ analog)
            # plus a host XZ-key index for range pruning
            self._clear_device_index()
            if col is not None:
                millis = (self.batch.col(dtg).millis
                          if dtg is not None else None)
                self._build_extent_index(col.bounds, millis)
                from ..index.xzkeys import XZKeyIndex
                self.zindex = XZKeyIndex(col.bounds, millis,
                                         self.sft.z3_interval)
            self.dirty = False
            return
        x = col.x
        y = col.y
        if dtg is not None:
            millis = self.batch.col(dtg).millis
        else:
            millis = np.zeros(len(x), dtype=np.int64)
        self._build_point_index(x, y, millis)
        # host sorted z-key index for range pruning (lazy per curve);
        # Z3IndexKeySpace.getRanges analog feeding the gathered scan
        from ..index.zkeys import ZKeyIndex
        self.zindex = ZKeyIndex(x, y,
                                millis if dtg is not None else None,
                                self.sft.z3_interval,
                                version=self.sft.index_version)
        if self.zindex_warm is not None:
            self.zindex.load_state(self.zindex_warm)  # no-op when stale
            self.zindex_warm = None
        self.dirty = False

    def _clear_device_index(self):
        self.scan_data = None
        self.extent_data = None

    def _build_point_index(self, x, y, millis):
        # DEFER both the host two-float split (only the boundary-patch
        # pass reads the hi parts) and the device upload: a selective
        # first query resolves on the host z-index and pays neither
        self._host_xhi = None
        self._host_yhi = None
        self._scan_data = None
        self._scan_thunk = self._deferred_scan_build()

    def _build_extent_index(self, bounds, millis):
        self.extent_data = gscan.build_extent_data(bounds, millis)

    def attr_index(self, name: str):
        """Sorted attribute index for one column, built on first use
        (AttributeIndex analog; see index/attr.py). Keys are (value,
        date) composites when the schema has a default date, so
        equality scans narrow by the filter's date bounds."""
        self.flush()  # cached indexes must cover pending rows
        if name not in self.attr_idx:
            from ..index.attr import AttributeKeyIndex
            dtg = self.sft.dtg_field
            date_millis = (self.batch.col(dtg).millis
                           if dtg is not None and dtg != name else None)
            try:
                self.attr_idx[name] = AttributeKeyIndex(
                    self.batch.col(name), date_millis=date_millis)
            except TypeError:
                self.attr_idx[name] = None  # unindexable column type
        return self.attr_idx[name]

    def device_cols(self):
        self.flush()  # cached uploads must cover pending rows
        if self.devcols is None:
            from ..scan.residual import DeviceColumns
            self.devcols = DeviceColumns(self.batch)
        return self.devcols

    def pallas(self):
        """Tiled device columns for the Pallas dense-scan kernel, built
        on first use under the geomesa.scan.kernel=pallas flag.

        Unlike scan_data, pallas tiles rebuild fully after a write burst
        (no capacity-padded extend yet) — the flag targets read-heavy
        scans; write-heavy workloads should stay on the XLA path."""
        self.flush()
        if self.pallas_data is None:
            from ..scan.pallas_scan import build_pallas_data
            geom = self.sft.geom_field
            dtg = self.sft.dtg_field
            col = self._batch.col(geom)
            millis = (self._batch.col(dtg).millis if dtg is not None
                      else np.zeros(self._batch.n, dtype=np.int64))
            self.pallas_data = build_pallas_data(col.x, col.y, millis)
        return self.pallas_data


def _synchronized(fn):
    """Serialize a store operation on the per-store reentrant lock.
    Reads mutate state too (pending-append flush, lazy index builds,
    plan caches), so ANY two concurrent operations on one store may
    race — a replica apply loop interleaving with scatter-gather query
    legs would desync batch/vis and silently drop rows. Per-store
    serialization keeps cross-store parallelism (each shard group owns
    its lock) while making a single store safe to serve from many
    threads."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._op_lock:
            return fn(self, *args, **kwargs)
    return wrapper


def _grid_copy(grid: np.ndarray) -> np.ndarray:
    """Cache encode/decode for density grids: every hit hands out a
    private copy, so a caller scribbling on its grid (or a cluster leg
    accumulating in place) cannot corrupt the memoized original."""
    return np.asarray(grid).copy()


class InMemoryDataStore(DataStore):
    """A GeoTools-DataStore-shaped API over device-resident batches."""

    def __init__(self, audit=None, durable_dir: str | None = None,
                 wal_fsync: str | None = None):
        self._op_lock = threading.RLock()
        self._types: dict[str, _TypeState] = {}
        self.stats = DataStoreStats()
        self.audit = audit  # AuditLogger or None
        # LSN-keyed materialized pushdown cache (cache/ subsystem):
        # every mutation stamps the type's version — the WAL LSN when
        # durable, a store-local counter otherwise — so density/stats/
        # bin/arrow results memoize until the type actually changes.
        # Created before the journal: recovery replays mutations
        # through write()/delete(), which stamp versions.
        from ..cache import ResultCache
        self._pushdown_clock = 0
        self._pushdown_versions: dict[str, int] = {}
        self.result_cache = ResultCache(self.pushdown_version)
        # evolve/ subsystem: per-type dual-feed taps installed while a
        # shadow schema build is in flight (empty = zero-cost path),
        # and the lazily built Evolver behind them
        self._evolve_feeds: dict = {}
        self._evolver = None
        # opt-in durability: journal mutations to a WAL under
        # durable_dir (validate -> journal -> apply) and replay the
        # last checkpoint + log tail on open (wal/ subsystem)
        self.journal = None
        if durable_dir:
            from ..wal.durable import Journal
            self.journal = Journal(durable_dir, fsync=wal_fsync)
            self.journal.recover(self)

    # -- schema management (MetadataBackedDataStore surface) --------------

    @_synchronized
    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        if sft.type_name in self._types:
            raise ValueError(f"schema {sft.type_name!r} already exists")
        if self.journal is not None:
            self.journal.log_create_schema(sft)
        self._types[sft.type_name] = self._new_state(sft)
        # an estimator exists from schema creation: a type with zero
        # observed rows estimates 0 (a cluster group that owns no rows
        # of a type must not null the coordinator's merged estimate);
        # only an explicit stats.clear() makes a type non-estimable
        self.stats.ensure(sft)
        self._bump_pushdown_version(sft.type_name)

    def _new_state(self, sft: SimpleFeatureType) -> _TypeState:
        return _TypeState(sft)

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._state(type_name).sft

    def get_type_names(self) -> list[str]:
        return sorted(self._types)

    @_synchronized
    def remove_schema(self, type_name: str):
        if self.journal is not None and type_name in self._types:
            self.journal.log_drop_schema(type_name)
        st = self._types.pop(type_name, None)
        if st is not None:
            # outstanding small lazy results must not pin the dropped
            # column snapshot
            st._detach_live()
        self._bump_pushdown_version(type_name)
        self.result_cache.invalidate(type_name)

    def _state(self, type_name: str) -> _TypeState:
        if type_name not in self._types:
            raise KeyError(f"no such schema: {type_name}")
        if self._evolve_feeds:
            # a mid-flip evolution fences every op on its type typed
            # (SchemaEvolutionError) until resume()/abort() restores a
            # consistent state — exact-or-typed, never silently stale
            feed = self._evolve_feeds.get(type_name)
            if feed is not None:
                feed.guard()
        return self._types[type_name]

    @property
    def evolver(self):
        """The online schema-evolution plane for this store (evolve/
        subsystem), built on first touch."""
        if self._evolver is None:
            with self._op_lock:
                if self._evolver is None:
                    from ..evolve import Evolver
                    self._evolver = Evolver(self)
        return self._evolver

    # -- pushdown versions (cache/ subsystem) ------------------------------

    def _bump_pushdown_version(self, type_name: str):
        """Stamp the type's version after a mutation: the WAL LSN when
        the journal advanced, a store-local counter otherwise (replay
        suppresses journaling, so the counter also covers recovery)."""
        prev = self._pushdown_versions.get(type_name, 0)
        v = self.journal.wal.last_lsn if self.journal is not None else 0
        if v <= prev:
            self._pushdown_clock += 1
            v = max(prev + 1, self._pushdown_clock)
        self._pushdown_versions[type_name] = v

    def pushdown_version(self, type_name: str) -> int:
        """Cache/ETag version for the type: any change to its rows or
        schema advances it; unchanged version == identical pushdown
        results. Per-type, so writes to one type never invalidate
        another's cached tiles."""
        return self._pushdown_versions.get(type_name, 0)

    def cache_status(self) -> dict:
        out = self.result_cache.status()
        out["versions"] = dict(self._pushdown_versions)
        return out

    def invalidate_cache(self, type_name: str | None = None) -> int:
        return self.result_cache.invalidate(type_name)

    # -- writes ------------------------------------------------------------

    # bulk writes at or above this build the z-key orders eagerly: the
    # reference indexes at INGEST (every BatchWriter mutation carries
    # its z-keys, write path 3.2), so a bulk load should hand the first
    # query a ready index instead of a multi-second build
    _EAGER_INDEX_ROWS = 5_000_000

    @_synchronized
    def write(self, type_name: str, batch: FeatureBatch, visibilities=None):
        st = self._state(type_name)
        if batch.sft != st.sft:
            raise ValueError("batch schema does not match store schema")
        feed = self._evolve_feeds.get(type_name) \
            if self._evolve_feeds else None
        if feed is not None:
            # refuse before journaling: a write that conflicts with an
            # in-flight evolution (non-null values for a mid-drop
            # attribute) must never be acked
            feed.check_write(batch)
        if self.journal is not None:
            # write-ahead: validate (so the journaled record is known
            # applyable), journal, then apply
            st.validate(batch, visibilities)
            self.journal.log_write(type_name, batch, visibilities)
        was_empty = st.n == 0
        st.append(batch, visibilities)
        self._bump_pushdown_version(type_name)
        # auto-maintained stats, the write-side StatsCombiner analog
        # (accumulo/data/stats/StatsCombiner.scala)
        self.stats.observe(st.sft, batch)
        if feed is not None:
            # dual-feed: non-durable stores queue the acked mutation
            # for the shadow build (durable stores tail the WAL)
            feed.on_write(batch, visibilities)
        # initial bulk load only: chunked ingests must not re-merge the
        # whole accumulated table per batch (later chunks stay lazy and
        # fold into ONE incremental merge at the next read)
        if was_empty and batch.n >= self._EAGER_INDEX_ROWS:
            try:
                st.ensure_index()
                if st.zindex is not None and hasattr(st.zindex, "warm"):
                    st.zindex.warm()
                self._prewarm_join(st)
            except MemoryError:
                raise
            except Exception:
                import logging
                logging.getLogger("geomesa_tpu").warning(
                    "ingest-time index build failed; falling back to "
                    "lazy build on first read", exc_info=True)

    @staticmethod
    def _prewarm_join(st):
        """Compile-cache the dwithin/KNN kernel family for this type's
        capacity class during ingest (``geomesa.join.prewarm``), so the
        first join/KNN query is a cache hit — the join analog of the
        eager z-index build above."""
        if str(JOIN_PREWARM.get()).lower() not in ("true", "1", "yes"):
            return
        from ..features.batch import PointColumn
        col = st.batch.col(st.sft.geom_field) if st.batch is not None \
            else None
        if not isinstance(col, PointColumn):
            return
        sd = getattr(st, "scan_data", None)
        device_xy = (sd.xhi, sd.yhi) if sd is not None else None
        from ..analytics.join import prewarm_join_kernels
        prewarm_join_kernels(col.x, col.y, device_xy=device_xy)

    @_synchronized
    def delete(self, type_name: str, ids):
        st = self._state(type_name)
        ids = set(map(str, ids))
        if self.journal is not None:
            self.journal.log_delete(type_name, sorted(ids))
        st.delete(ids)
        self._bump_pushdown_version(type_name)
        feed = self._evolve_feeds.get(type_name) \
            if self._evolve_feeds else None
        if feed is not None:
            feed.on_delete(ids)

    # -- durability (wal/ subsystem, opt-in via durable_dir) ---------------

    @_synchronized
    def checkpoint(self, keep: int = 2) -> dict:
        """Snapshot current state and compact the journal; requires the
        ``durable_dir`` knob. ``keep=2`` retains the prior checkpoint
        (and the log back to it) so recovery can fall back id-exactly
        if the newest snapshot is later found corrupt."""
        if self.journal is None:
            raise ValueError("store is not durable (no durable_dir)")
        return self.journal.checkpoint(self, keep=keep)

    def close(self):
        if self.journal is not None:
            self.journal.close()

    @_synchronized
    def warm_index(self, type_name: str, state: dict):
        """Install persisted z-key sort orders (possibly memory-mapped)
        to be adopted by the next index build — the fs store's sidecar
        reopen path. Stale states (row count mismatch) are ignored."""
        self._state(type_name).zindex_warm = state

    @_synchronized
    def index_state(self, type_name: str) -> dict | None:
        """Built z-key sort orders for persistence, or None when no
        index has been built yet."""
        st = self._state(type_name)
        if st.zindex is None or not hasattr(st.zindex, "state_dict"):
            return None
        out = st.zindex.state_dict()
        return out or None

    @_synchronized
    def count(self, type_name: str) -> int:
        return self._state(type_name).n

    @_synchronized
    def reindex(self, type_name: str, to_version: int | None = None):
        """Migrate the type's z-index layout to ``to_version`` (the
        WriteIndexJob / AttributeIndexJob reindex analog,
        jobs/accumulo/AttributeIndexJob; GeoMesaFeatureIndex.scala:33-35
        versioned tables): rebuild the sort orders under the new
        curve and swap them in atomically — the old index serves every
        query until the swap."""
        from ..features.sft import Configs, check_index_version
        to_version = check_index_version(to_version)
        st = self._state(type_name)
        if st.sft.index_version == to_version:
            return
        st.sft.user_data[Configs.INDEX_VERSION] = to_version
        if st.batch is None or st.n == 0:
            return
        st.dirty = True
        st.plan_cache.clear()
        st.ensure_index()  # rebuild + atomic swap

    @_synchronized
    def analyze(self, type_name: str):
        """Recompute stats from scratch (stats are additive on write and
        go stale after deletes — the reference's `stats analyze` run)."""
        st = self._state(type_name)
        self.stats.clear(type_name)
        st.plan_cache.clear()  # cached strategies used the stale stats
        if st.batch is not None and st.n:
            self.stats.observe(st.sft, st.batch)
        return self.stats.get(type_name)

    # -- materialized pushdowns (cache/ subsystem) -------------------------
    #
    # The public pushdowns are caching wrappers: canonical plan key +
    # per-type version lookup and single-flight coalescing run OUTSIDE
    # _op_lock, so repeated identical tiles cost a dict lookup and a
    # thundering herd of cold ones costs one device dispatch with zero
    # lock convoys. The _*_uncached bodies hold the synchronized
    # compute; store subclasses override those, keeping the cache on
    # every flavor.

    def density(self, type_name: str, ecql, bbox, width: int, height: int,
                weight_attr: str | None = None) -> np.ndarray:
        """Density surface (DensityScan pushdown analog): heatmap grid of
        matching features over bbox at width x height pixels."""
        from ..cache import density_key
        flt, key = density_key(ecql, bbox, width, height, weight_attr)
        return self.result_cache.get_or_compute(
            type_name, key,
            lambda: self._density_uncached(type_name, flt, bbox, width,
                                           height, weight_attr),
            encode=_grid_copy, decode=_grid_copy)

    def bin_query(self, type_name: str, ecql, track: str | None = None,
                  label: str | None = None, sort: bool = False) -> bytes:
        """BIN-format results (BinAggregatingScan analog): compact
        16/24-byte records for matching features."""
        from ..cache import bin_key
        flt, key = bin_key(ecql, track, label, sort)
        return self.result_cache.get_or_compute(
            type_name, key,
            lambda: self._bin_query_uncached(type_name, flt, track=track,
                                             label=label, sort=sort))

    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None) -> bytes:
        """Arrow IPC stream of matching features, readable by
        FeatureArrowFileReader (the ARROW_ENCODE hint surface)."""
        from ..cache import arrow_key
        flt, key = arrow_key(ecql, sort_by)
        return self.result_cache.get_or_compute(
            type_name, key,
            lambda: self._arrow_ipc_uncached(type_name, flt,
                                             sort_by=sort_by))

    def stats_query(self, type_name: str, stat_spec: str,
                    ecql: str | ast.Filter = None):
        """Run a stat sketch over query results (StatsScan analog):
        returns the observed Stat. Cached in serialized-sketch form
        (stats/serialize.py) so every caller gets a private copy —
        the cluster's in-place merge cannot corrupt the original."""
        from ..cache import stats_key
        from ..stats.serialize import deserialize_stat, serialize_stat
        flt, key = stats_key(ecql, stat_spec)
        return self.result_cache.get_or_compute(
            type_name, key,
            lambda: self._stats_query_uncached(type_name, stat_spec, flt),
            encode=serialize_stat, decode=deserialize_stat)

    @_synchronized
    def _density_uncached(self, type_name: str, ecql, bbox, width: int,
                          height: int,
                          weight_attr: str | None = None) -> np.ndarray:
        from ..scan.aggregations import density_grid
        st = self._state(type_name)
        if st.batch is None or st.n == 0:
            return np.zeros((height, width), dtype=np.float32)
        res = self.query(Query(type_name, ecql))
        if res.batch is None or res.batch.n == 0:
            return np.zeros((height, width), dtype=np.float32)
        x, y, gvalid = _geom_centroids(res.batch, st.sft.geom_field)
        mask = gvalid.copy()
        w = None
        if weight_attr is not None:
            wcol = res.batch.col(weight_attr)
            w = np.where(wcol.valid, wcol.values, 0.0).astype(np.float32)
            mask &= wcol.valid
        # NaN coords on invalid rows would clip into pixel (0,0): zero them
        x = np.where(gvalid, x, bbox[0])
        y = np.where(gvalid, y, bbox[1])
        return density_grid(x, y, mask, bbox, width, height, w)

    @_synchronized
    def _bin_query_uncached(self, type_name: str, ecql,
                            track: str | None = None,
                            label: str | None = None,
                            sort: bool = False) -> bytes:
        from ..scan.aggregations import encode_bin_batch
        st = self._state(type_name)
        res = self.query(Query(type_name, ecql))
        if res.batch is None or res.batch.n == 0:
            return b""
        return encode_bin_batch(st.sft, res.ids, res.batch,
                                track=track, label=label, sort=sort)

    @_synchronized
    def arrow_query(self, type_name: str, ecql):
        """Arrow-encoded results (ArrowScan analog): a pyarrow
        RecordBatch of matching features."""
        res = self.query(Query(type_name, ecql))
        if res.batch is None:
            return None
        return res.batch.to_arrow()

    @_synchronized
    def _arrow_ipc_uncached(self, type_name: str, ecql="INCLUDE",
                            sort_by: str | None = None) -> bytes:
        # the distributed store overrides this with the shard-local
        # dictionary-delta merge
        from ..arrow.scan import ArrowScan
        return ArrowScan(self).execute(type_name, ecql, sort_by=sort_by)

    @_synchronized
    def _stats_query_uncached(self, type_name: str, stat_spec: str,
                              ecql: str | ast.Filter = None):
        # StatsScan analog (index/iterators/StatsScan.scala)
        st = self._state(type_name)
        stat = parse_stat(stat_spec)
        if st.batch is None or st.n == 0:
            return stat
        if ecql is None or isinstance(ecql, ast.Include):
            stat.observe(st.batch)
            return stat
        res = self.query(Query(type_name, ecql))
        if res.batch is not None and res.batch.n:
            stat.observe(res.batch)
        return stat

    # -- queries -----------------------------------------------------------

    def _indices(self, sft: SimpleFeatureType) -> list[str]:
        out = []
        if sft.geom_field is not None:
            if sft.is_points:
                if sft.dtg_field is not None:
                    out.append("z3")
                out.append("z2")
            else:
                if sft.dtg_field is not None:
                    out.append("xz3")
                out.append("xz2")
        out.append("id")
        for a in sft.attributes:
            if a.indexed:
                out.append(f"attr:{a.name}")
        return out

    def _plan_for(self, q: Query, st: _TypeState,
                  explain: Explainer) -> tuple[FilterStrategy,
                                               _PlanArtifacts]:
        """Plan-cache lookup (keyed on the filter object +
        strategy-relevant hints): the ECQL parse cache returns one
        shared AST per query string, so repeated queries hit here and
        skip the splitter / cost estimation / geometry extraction. The
        `is` check makes id() reuse after GC harmless."""
        pkey = (id(q.filter), q.hints.get(QueryHints.QUERY_INDEX))
        hit = st.plan_cache.get(pkey)
        if hit is not None and hit[0] is q.filter:
            strategy, art = hit[1], hit[2]
            explain(lambda: f"Plan cache hit: {strategy.index}")
        else:
            strategy = decide_strategy(st.sft, q,
                                       self._indices(st.sft), st.n,
                                       stats=self.stats.get(q.type_name),
                                       explain=explain)
            art = _PlanArtifacts()
            if len(st.plan_cache) >= 256:
                st.plan_cache.pop(next(iter(st.plan_cache)))
            st.plan_cache[pkey] = (q.filter, strategy, art)
        return strategy, art

    def _matching_rows(self, q: Query, st: _TypeState,
                       explain: Explainer):
        """The shared row-selection pipeline: plan (under the timeout
        reaper), scan, visibility, sampling. Returns (idx, strategy,
        t_plan, t_scan0, attr_mask) — attr_mask is the per-row
        attribute authorization matrix for attribute-level visibility
        schemas (None otherwise); query() materializes from it,
        query_count() just counts — one pipeline, no drift."""
        # query timeout enforcement at stage boundaries
        # (ThreadManagement analog; geomesa.query.timeout property)
        from ..utils.properties import QUERY_TIMEOUT
        managed = None
        timeout_s = q.hints.get("TIMEOUT") or QUERY_TIMEOUT.as_seconds()
        if timeout_s:
            from ..utils.threads import ManagedQuery
            managed = _REAPER.register(
                ManagedQuery(q.type_name, str(q.filter), float(timeout_s)))

        import time as _time
        try:
            t_plan0 = _time.perf_counter()
            strategy, art = self._plan_for(q, st, explain)
            t_plan = _time.perf_counter() - t_plan0
            if managed is not None:
                managed.check()
            t_scan0 = _time.perf_counter()
            idx = self._execute(st, q, strategy, explain, art)
            if managed is not None:
                managed.check()
        finally:
            if managed is not None:
                _REAPER.complete(managed)

        idx, attr_mask = self._post_scan(q, st, idx, explain)
        return idx, strategy, t_plan, t_scan0, attr_mask

    def _post_scan(self, q: Query, st: _TypeState, idx: np.ndarray,
                   explain: Explainer):
        """Post-scan row stages shared by the scalar and batched
        pipelines: visibility filtering (row- or attribute-level) and
        statistical sampling. Returns (idx, attr_mask)."""
        attr_mask = None
        if q.auths is not None or st.has_vis:
            from ..security import evaluate_visibilities
            auths = q.auths or []
            if st.sft.visibility_level == "attribute" and st.has_vis:
                # a row survives when ANY of its attributes is
                # authorized; the mask rides along (aligned with idx)
                # so materialization nulls cells without re-parsing
                m = _attr_vis_masks(st.vis[idx],
                                    len(st.sft.attributes), auths)
                keep = m.any(axis=1)
                idx = idx[keep]
                attr_mask = m[keep]
                # leak guard: the scan matched on RAW values, but the
                # caller must not learn hidden cells through the
                # predicate (reference semantics put the visibility
                # filter BELOW the query filter). Re-evaluate on the
                # NULLED view; hidden cells compare as NULL (UNKNOWN
                # -> excluded). Deviation: IS NULL on a hidden cell
                # under-matches here (the raw scan already dropped it).
                if not attr_mask.all() \
                        and not isinstance(q.filter, ast.Include):
                    refd = ast.props_of(q.filter)
                    by_name = {a.name: j for j, a
                               in enumerate(st.sft.attributes)}
                    hidden_refd = [a for a in refd if a in by_name
                                   and not attr_mask[:, by_name[a]].all()]
                    if hidden_refd:
                        sub = st.batch.take(idx)
                        cols = dict(sub.columns)
                        for a in hidden_refd:
                            cols[a] = _null_cells(
                                sub.col(a), ~attr_mask[:, by_name[a]])
                        nulled = FeatureBatch(sub.sft, sub.ids, cols)
                        ok = np.asarray(evaluate(q.filter, nulled),
                                        dtype=bool)
                        idx = idx[ok]
                        attr_mask = attr_mask[ok]
                explain(f"Attribute-level visibility filter applied "
                        f"({len(auths)} auths)")
            else:
                # evaluate only the rows that survived the scan
                vis_ok = evaluate_visibilities(st.vis[idx], auths)
                idx = idx[vis_ok]
                explain(f"Visibility filter applied ({len(auths)} auths)")

        rate = q.hints.get(QueryHints.SAMPLING)
        if rate is not None and len(idx):
            from ..scan.aggregations import sample_mask
            by_attr = q.hints.get(QueryHints.SAMPLE_BY)
            by = None
            if by_attr is not None:
                col = st.batch.col(by_attr)
                # nulls sort as empty string (argsort needs a total order)
                by = np.array([col.value(int(i)) or "" for i in idx],
                              dtype=object).astype(str)
            smask = sample_mask(len(idx), float(rate), by)
            idx = idx[smask]
            if attr_mask is not None:
                attr_mask = attr_mask[smask]
            explain(f"Sampling applied: rate={rate}")
        return idx, attr_mask

    @_synchronized
    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None) -> QueryResult:
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        st = self._state(q.type_name)
        explain = Explainer(explain_out)
        explain.push(lambda: f"Planning '{q.type_name}' "
                             f"filter={q.filter}")
        if st.batch is None or st.n == 0:
            explain("Store is empty").pop()
            return QueryResult(np.empty(0, dtype=object), None, explain,
                               FilterStrategy("empty", None, None))
        from ..obs import tracer
        with tracer.span("store-scan", q.type_name) as sp:
            idx, strategy, t_plan, t_scan0, attr_mask = \
                self._matching_rows(q, st, explain)
            sp.set_attr(index=strategy.index, rows=int(st.n),
                        hits=int(len(idx)))
            return self._finish_query(q, st, idx, attr_mask, strategy,
                                      explain, t_plan, t_scan0)

    def _finish_query(self, q: Query, st: _TypeState, idx: np.ndarray,
                      attr_mask, strategy: FilterStrategy,
                      explain: Explainer, t_plan: float,
                      t_scan0: float, batched: bool = False) -> QueryResult:
        """Result-assembly stages shared by the scalar and batched
        pipelines: sort, max_features, projection validation, lazy
        batch + attribute-cell redaction, id gather, audit."""
        import time as _time
        if q.sort_by is not None:
            from .common import sort_order
            hidden = None
            if attr_mask is not None:
                # hidden sort values must not leak through the row
                # ordering: they sort as NULL
                aj = {a.name: j
                      for j, a in enumerate(st.sft.attributes)}.get(q.sort_by)
                if aj is not None:
                    hidden = ~attr_mask[:, aj]
            order = sort_order(st.batch, q.sort_by, q.sort_desc, idx,
                               hidden=hidden)
            idx = idx[order]
            if attr_mask is not None:
                attr_mask = attr_mask[order]
        if q.max_features is not None:
            idx = idx[:q.max_features]
            if attr_mask is not None:
                attr_mask = attr_mask[:q.max_features]

        if q.properties is not None:
            # validate projection names NOW: errors belong to query(),
            # not to whenever (or whether) .batch is first read
            missing = [p for p in q.properties
                       if p not in st.batch.columns]
            if missing:
                raise KeyError(f"unknown propert"
                               f"{'ies' if len(missing) > 1 else 'y'}: "
                               f"{', '.join(missing)}")
        batch: Any = _LazyBatch(st.batch, idx, q.properties,
                                row_order=q.sort_by is None)
        st.live_lazy.add(batch)
        if attr_mask is not None:
            # null unauthorized attribute values in the result rows
            # (KryoVisibilityRowEncoder: the row is assembled from the
            # cells the scanner's auths can see)
            m = attr_mask
            if not m.all():
                mb = batch.materialize() if isinstance(batch, _LazyBatch) \
                    else batch
                by_name = {a.name: j
                           for j, a in enumerate(st.sft.attributes)}
                cols = {}
                for a in mb.sft.attributes:
                    col = mb.col(a.name)
                    bad = ~m[:, by_name[a.name]]
                    cols[a.name] = (_null_cells(col, bad) if bad.any()
                                    else col)
                batch = FeatureBatch(mb.sft, mb.ids, cols)
        if isinstance(batch, FeatureBatch):
            # attr-visibility path materialized already; reuse its ids
            ids = batch.ids
        elif len(idx) <= 100_000:
            # eager id gather (the result's identity), lazy columns:
            # id-only consumers — count checks, bench loops, join sides
            # — never pay the per-column copies, and .batch still
            # materializes on first read (the reference's readers are
            # lazy over their scan buffers the same way,
            # KryoBufferSimpleFeature). The result pins the immutable
            # column snapshot until dropped.
            ids = st.batch.ids[idx]
        else:
            # deferred gather against the immutable batch snapshot:
            # large results are often consumed via batch columns (or
            # only counted) and never read ids at all
            src = st.batch
            ids = (lambda: src.ids[idx])
        explain(f"Hits: {len(idx)}").pop()
        scan_s = _time.perf_counter() - t_scan0
        from ..metrics import metrics as _metrics
        _metrics.observe("store.scan", scan_s,
                         labels={"type": q.type_name,
                                 "index": strategy.index or "none"})
        from ..obs.slo import slo_engine
        slo_engine.record("store.scan", ok=True, latency_s=scan_s)
        from ..audit import audit_query
        audit_query(self.audit, "memory", q.type_name, str(q.filter),
                    q.hints, t_plan * 1000, scan_s * 1000, len(idx),
                    index=strategy.index, rows_scanned=int(st.n),
                    batched=batched)
        return QueryResult(ids, batch, explain, strategy, n=len(idx))

    @_synchronized
    def query_count(self, q: Query | str,
                    type_name: str | None = None) -> int:
        """Count without materializing ids or columns: the shared
        row-selection pipeline (plan, scan, visibility, sampling, all
        under the timeout reaper), then just the length. Skips the
        object-array id gather and per-column result copies."""
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        st = self._state(q.type_name)
        if st.batch is None or st.n == 0:
            return 0
        import time as _time
        explain = Explainer()
        explain.push(lambda: f"Counting '{q.type_name}' "
                             f"filter={q.filter}")
        from ..obs import tracer
        with tracer.span("store-scan", q.type_name) as sp:
            idx, strategy, t_plan, t_scan0, _m = \
                self._matching_rows(q, st, explain)
            n = len(idx)
            if q.max_features is not None:
                n = min(n, q.max_features)
            sp.set_attr(index=strategy.index, rows=int(st.n), hits=n)
            from ..audit import audit_query
            audit_query(self.audit, "memory", q.type_name,
                        str(q.filter), q.hints, t_plan * 1000,
                        (_time.perf_counter() - t_scan0) * 1000, n,
                        index=strategy.index, rows_scanned=int(st.n))
        return n

    @_synchronized
    def query_batched(self, queries: list[Query],
                      explain_out=None) -> list[QueryResult]:
        """Micro-batched execution: evaluate several queries with ONE
        fused device scan (the vmapped kernel in scan/zscan.py) and
        demultiplex per-query results.

        Queries whose plan cannot fuse — non-point schemas, id/attr
        strategies, secondary residual filters, exact-geometry
        predicates — fall back to the scalar pipeline individually, so
        the result list is always exactly what per-query ``query()``
        calls would return, id for id. Single-element batches pass
        through to ``query()`` untouched."""
        queries = list(queries)
        if len(queries) <= 1:
            return [self.query(q, explain_out=explain_out)
                    for q in queries]
        results: list[QueryResult | None] = [None] * len(queries)
        groups: dict[str, list[int]] = {}
        for i, q in enumerate(queries):
            groups.setdefault(q.type_name, []).append(i)
        import time as _time
        for tn, members in groups.items():
            st = self._types.get(tn)
            fused: list[int] = []
            plans: dict[int, tuple[FilterStrategy, _PlanArtifacts]] = {}
            fallback: list[int] = []
            for i in members:
                q = queries[i]
                if st is None or st.batch is None or st.n == 0:
                    fallback.append(i)
                    continue
                explain = Explainer(explain_out)
                strategy, art = self._plan_for(q, st, explain)
                ok = (strategy.index in ("z3", "z2")
                      and strategy.secondary is None)
                if ok:
                    st.ensure_index()
                    ok = st.has_point_scan()
                if ok:
                    _g, _b, _i, needs_exact, _s = \
                        self._fill_artifacts(st, strategy, art)
                    ok = not needs_exact
                if ok:
                    fused.append(i)
                    plans[i] = (strategy, art)
                else:
                    fallback.append(i)
            if len(fused) < 2:
                fallback = sorted(fallback + fused)
                fused = []
            for i in fallback:
                results[i] = self.query(queries[i],
                                        explain_out=explain_out)
            if not fused:
                continue
            t_scan0 = _time.perf_counter()
            from ..obs import tracer
            with tracer.span("store-scan", tn) as sp:
                sp.set_attr(fused=len(fused), rows=int(st.n))
                rows_per_q = self._batched_scan_rows(
                    st, [(queries[i],) + plans[i] for i in fused])
                for i, rows in zip(fused, rows_per_q):
                    q = queries[i]
                    explain = Explainer(explain_out)
                    explain.push(lambda q=q: f"Batched '{q.type_name}' "
                                             f"filter={q.filter}")
                    idx, attr_mask = self._post_scan(q, st, rows,
                                                     explain)
                    results[i] = self._finish_query(
                        q, st, idx, attr_mask, plans[i][0], explain,
                        0.0, t_scan0, batched=True)
        return results  # type: ignore[return-value]

    def _batched_scan_rows(self, st: _TypeState, items) -> list[np.ndarray]:
        """One fused vmapped launch over the stacked queries, then a
        per-query exact boundary patch (candidates are compacted on
        device inside the same launch, so there is NO per-query O(n)
        host work). ``items`` is a list of (query, strategy, artifacts)
        whose plans were checked fusible by query_batched."""
        sqs = []
        for _q, strategy, art in items:
            if art.sq is None:
                _g, boxes, intervals, _ne, _s = \
                    self._fill_artifacts(st, strategy, art)
                art.sq = zscan.make_query(boxes, intervals)
            sqs.append(art.sq)
        bq = zscan.stack_queries(sqs)
        hits, cands = zscan.batch_hit_rows(st.scan_data, bq)
        batch = st.batch
        col = batch.col(st.sft.geom_field)
        dtg = st.sft.dtg_field
        millis = (batch.col(dtg).millis if dtg is not None
                  else np.zeros(st.n, dtype=np.int64))
        return [zscan.patch_hit_rows(rows, sq, col.x, col.y, millis, cand)
                for rows, cand, sq in zip(hits, cands, sqs)]

    def _execute(self, st: _TypeState, q: Query, strategy: FilterStrategy,
                 explain: Explainer,
                 art: "_PlanArtifacts | None" = None) -> np.ndarray:
        """Run the chosen strategy; returns sorted matching row indices.

        Index-space (not mask-space) so an index-pruned scan never pays
        O(n) host work — cost is proportional to the candidate set."""
        sft = st.sft
        n = st.n
        batch = st.batch
        if strategy.index == "empty":
            return np.empty(0, dtype=np.int64)

        if strategy.index in ("z3", "z2", "xz3", "xz2"):
            st.ensure_index()

        if strategy.index in ("z3", "z2") and st.has_point_scan():
            idx = self._device_scan(st, q, strategy, explain, art)
        elif strategy.index in ("xz3", "xz2") and st.has_extent_scan():
            idx = self._device_extent_scan(st, q, strategy, explain)
        elif strategy.index == "id" and strategy.primary is not None:
            idx = np.flatnonzero(
                np.isin(batch.ids.astype(str),
                        np.asarray(strategy.primary.ids, dtype=str)))
        elif (strategy.index.startswith("attr:")
              and strategy.primary is not None):
            idx = self._attr_scan(st, strategy, explain)
        else:
            # fullscan / attr-fallback / extent-geometry path: device
            # kernel when the primary is attribute-only (the pushed-down
            # "iterator" of the reference), else host evaluation
            if strategy.primary is None:
                idx = np.arange(n, dtype=np.int64)
            else:
                from ..scan import residual
                if residual.is_compilable(strategy.primary, batch):
                    explain(f"Device residual scan for {strategy.index}")
                    mask = residual.device_mask(strategy.primary, batch,
                                                st.device_cols())
                    idx = np.flatnonzero(np.asarray(mask))
                else:
                    explain(f"Executing host scan for {strategy.index}")
                    idx = np.flatnonzero(evaluate(strategy.primary, batch))

        if strategy.secondary is not None:
            if len(idx):
                idx = self._apply_residual(st, strategy.secondary, idx,
                                           explain)
            explain(f"Residual filter applied: {strategy.secondary}")
        return idx

    def _apply_residual(self, st: _TypeState, residual_f: ast.Filter,
                        idx: np.ndarray, explain: Explainer) -> np.ndarray:
        """Secondary-filter application: a dense device pass when the
        candidate set is a large fraction of the table (gathering would
        cost more than re-touching the column), host evaluation on the
        gathered candidates otherwise."""
        from ..scan import residual
        batch = st.batch
        if (len(idx) * 4 > st.n
                and residual.is_compilable(residual_f, batch)):
            explain("Device residual scan (dense)")
            mask = np.asarray(residual.device_mask(residual_f, batch,
                                                   st.device_cols()))
            return idx[mask[idx]]
        return idx[evaluate(residual_f, batch.take(idx))]

    def _attr_scan(self, st: _TypeState, strategy: FilterStrategy,
                   explain: Explainer) -> np.ndarray:
        """Attribute-index scan: binary-searched candidate rows from the
        sorted column, then the exact primary on just those rows (bounds
        over-approximate e.g. non-prefix LIKE). The candidate gather is
        the positional join back to the record columns — the reference's
        attribute-index -> record-table join
        (accumulo/index/AttributeIndex.scala:386-395)."""
        from ..filters.helper import extract_attribute_bounds
        from ..index.zkeys import SCAN_BLOCK_THRESHOLD
        attr = strategy.index.split(":", 1)[1]
        aidx = st.attr_index(attr)
        rows = None
        intervals = []
        if aidx is not None:
            bounds = extract_attribute_bounds(strategy.primary, attr)
            # secondary date tiering: the residual's date bounds narrow
            # equality slices inside the (value, date) composite order
            dtg = st.sft.dtg_field
            if (dtg is not None and strategy.secondary is not None
                    and aidx.sorted_millis is not None):
                intervals = _intervals_ms(strategy.secondary, dtg,
                                          lo_unbounded=-(2 ** 62))
            max_rows = int(float(SCAN_BLOCK_THRESHOLD.get()) * st.n)
            rows = aidx.candidates(bounds, max_rows=max_rows,
                                   intervals_ms=intervals)
            # the secondary tier only engages on equality slices
            narrowed = bool(intervals) and any(
                aidx._is_point_bound(b) for b in bounds)
        if rows is None:
            from ..scan import residual
            if residual.is_compilable(strategy.primary, st.batch):
                explain(f"Attribute bounds too wide; dense device scan "
                        f"for {strategy.index}")
                mask = residual.device_mask(strategy.primary, st.batch,
                                            st.device_cols())
                return np.flatnonzero(np.asarray(mask))
            explain(f"Attribute bounds not range-scannable; "
                    f"host scan for {strategy.index}")
            return np.flatnonzero(evaluate(strategy.primary, st.batch))
        explain(f"Attribute index scan: {len(rows)} candidate row(s) "
                f"of {st.n}" + (" (date-narrowed)" if narrowed else ""))
        if not len(rows):
            return rows
        keep = evaluate(strategy.primary, st.batch.take(rows))
        return rows[keep]

    def _fill_artifacts(self, st: _TypeState, strategy: FilterStrategy,
                        art: "_PlanArtifacts | None"):
        """Derive (and cache on the plan artifacts) the scan-shaped
        view of a strategy's primary filter: query geometries, their
        envelopes, time intervals, and whether an exact geometry
        residual is needed."""
        sft = st.sft
        primary = (strategy.primary if strategy.primary is not None
                   else ast.Include())
        if art is not None and art.filled:
            return (art.geoms, art.boxes, art.intervals,
                    art.needs_exact, art.spatial_f)
        geom = sft.geom_field
        dtg = sft.dtg_field
        geoms = extract_geometries(primary, geom)
        boxes = [g.envelope.as_tuple() for g in geoms] or \
            [(-180.0, -90.0, 180.0, 90.0)]
        intervals = (_intervals_ms(primary, dtg)
                     if dtg is not None and strategy.index == "z3"
                     else [])
        needs_exact = _needs_exact(geoms, primary)
        spatial_f = (_spatial_only(primary, geom) if needs_exact
                     else None)
        if art is not None:
            art.geoms, art.boxes = geoms, boxes
            art.intervals = intervals
            art.needs_exact, art.spatial_f = needs_exact, spatial_f
            art.filled = True
        return geoms, boxes, intervals, needs_exact, spatial_f

    def _device_scan(self, st: _TypeState, q: Query,
                     strategy: FilterStrategy, explain: Explainer,
                     art: "_PlanArtifacts | None" = None) -> np.ndarray:
        """The hot path: z-range index pruning -> fused device kernel
        (gathered candidates or dense) + exact boundary patch +
        non-envelope geometry residual. Returns sorted row indices."""
        sft = st.sft
        batch = st.batch
        geom = sft.geom_field
        primary = strategy.primary if strategy.primary is not None else ast.Include()
        geoms, boxes, intervals, needs_exact, spatial_f = \
            self._fill_artifacts(st, strategy, art)

        # z-range pruning (Z3IndexKeySpace.getRanges analog): the host
        # fast path resolves selective queries EXACTLY inside the index
        # (sequential passes over sorted-order coordinate copies); wider
        # candidate sets fall to the gathered device scan, and beyond
        # the block threshold to the dense full-batch kernel. One
        # decomposition serves all tiers (zkeys.search_rows).
        from ..index.zkeys import SCAN_BLOCK_THRESHOLD, search_rows
        block_cap = int(float(SCAN_BLOCK_THRESHOLD.get()) * st.n)
        host_cap = min(block_cap, int(HOST_SCAN_ROWS.get()))
        kind, res_rows = search_rows(st.zindex, strategy.index, boxes,
                                     intervals, host_cap, block_cap)
        idx_exact = res_rows if kind == "exact" else None
        rows = res_rows if kind == "candidates" else None

        if idx_exact is not None:
            # selective query resolved exactly inside the index: no
            # two-float machinery, no boundary patch, no device round
            # trip — the reference's tablet-local iterator work as one
            # sequential pass (zkeys.ZKeyIndex.query_rows)
            explain(f"Index-pruned host scan: {len(idx_exact)} hit(s) "
                    f"of {st.n}, {len(boxes)} box(es), "
                    f"{len(intervals)} interval(s)")
            idx = idx_exact
        else:
            # the two-float device query struct is only needed by the
            # kernel tiers; the exact host tier above never builds it
            sq = art.sq if art is not None and art.sq is not None \
                else zscan.make_query(boxes, intervals)
            if art is not None:
                art.sq = sq
            if rows is not None:
                idx = self._scan_gathered(st, sq, rows, explain,
                                          len(boxes), len(intervals))
            else:
                idx = self._scan_dense(st, sq, explain,
                                       len(boxes), len(intervals))

        # non-envelope query geometries need the exact predicate too
        if needs_exact:
            if len(idx):
                if spatial_f is not None:
                    col = batch.col(geom)
                    keep = self._pip_residual(spatial_f, col, idx, explain)
                    if keep is None and isinstance(col, PointColumn) \
                            and isinstance(spatial_f, (ast.Intersects,
                                                       ast.Within)) \
                            and hasattr(spatial_f.geom, "contains_points"):
                        # host crossing-number on just the candidate
                        # coords — a full batch.take gathers every
                        # column for rows whose geometry alone decides
                        keep = spatial_f.geom.contains_points(
                            col.x[idx], col.y[idx]) & col.valid[idx]
                    if keep is None:
                        keep = evaluate(spatial_f, batch.take(idx))
                    idx = idx[keep]
            explain("Exact geometry predicate applied")
        return idx

    def _patch_mask(self, st: _TypeState, mask, xhi, yhi, sel,
                    sq: zscan.ScanQuery, explain: Explainer):
        """Exact f64 recheck of rows whose hi-cell touches a query
        bound; sel=None means full-table arrays, else a row subset
        (rows outside a pruned candidate set are provably outside
        the query in exact f64, so patching the subset is exact)."""
        cand = zscan.boundary_candidates(xhi, yhi, sq)
        if not len(cand):
            return mask
        batch = st.batch
        dtg = st.sft.dtg_field
        col = batch.col(st.sft.geom_field)
        x, y = col.x, col.y
        millis = (batch.col(dtg).millis if dtg is not None
                  else np.zeros(st.n, dtype=np.int64))
        if sel is not None:
            x, y, millis = x[sel], y[sel], millis[sel]
        explain(f"Boundary recheck: {len(cand)} candidate(s)")
        return zscan.exact_patch(mask, cand, x, y, millis, sq)

    def _scan_gathered(self, st: _TypeState, sq: zscan.ScanQuery,
                       rows: np.ndarray, explain: Explainer,
                       nb: int, ni: int) -> np.ndarray:
        """Index-pruned candidate tier: fused kernel over just the
        gathered rows + boundary patch on the subset."""
        explain(f"Index-pruned device scan: {len(rows)} candidate "
                f"row(s) of {st.n}, {nb} box(es), {ni} interval(s)")
        sub = zscan.scan_mask_at(st.scan_data, sq, rows)
        sub = self._patch_mask(st, sub, st.host_xhi[rows],
                               st.host_yhi[rows], rows, sq, explain)
        return np.sort(rows[sub])

    def _scan_dense(self, st: _TypeState, sq: zscan.ScanQuery,
                    explain: Explainer, nb: int, ni: int) -> np.ndarray:
        """Dense full-batch tier: the flag-selected XLA or Pallas
        kernel + full-table boundary patch."""
        if SCAN_KERNEL.get() == "pallas":
            from ..scan.pallas_scan import pallas_scan_mask
            explain(f"Pallas device scan: {nb} box(es), "
                    f"{ni} interval(s), n={st.n}")
            mask = pallas_scan_mask(st.pallas(), sq)
        else:
            explain(f"Device scan: {nb} box(es), "
                    f"{ni} interval(s), n={st.n}")
            mask = np.asarray(zscan.scan_mask(st.scan_data, sq))[:st.n]
        mask = self._patch_mask(st, mask, st.host_xhi, st.host_yhi,
                                None, sq, explain)
        return np.flatnonzero(mask)

    def _device_extent_scan(self, st: _TypeState, q: Query,
                            strategy: FilterStrategy,
                            explain: Explainer) -> np.ndarray:
        """XZ-index analog for extent geometries: device bbox tristate
        (definite in / definite out / boundary band), exact host
        predicate only on the band — the per-candidate JTS evaluation
        of the reference's XZ scans (curve/XZ2SFC.scala:146-252 ranges
        + server-side exact filter)."""
        sft = st.sft
        batch = st.batch
        geom = sft.geom_field
        dtg = sft.dtg_field
        primary = (strategy.primary if strategy.primary is not None
                   else ast.Include())

        geoms = extract_geometries(primary, geom)
        boxes = [g.envelope.as_tuple() for g in geoms] or \
            [(-180.0, -90.0, 180.0, 90.0)]
        intervals = (_intervals_ms(primary, dtg)
                     if dtg is not None and strategy.index == "xz3" else [])

        # XZ-key pruning (XZ2/XZ3IndexKeySpace analog): selective
        # queries evaluate only the candidate extents, exactly, on host
        from ..index.zkeys import SCAN_BLOCK_THRESHOLD, prune_candidates
        max_rows = min(int(float(SCAN_BLOCK_THRESHOLD.get()) * st.n),
                       int(EXTENT_HOST_SCAN_ROWS.get()))
        rows = prune_candidates(st.zindex, strategy.index, boxes,
                                intervals, max_rows)
        if rows is not None:
            explain(f"XZ-pruned host scan: {len(rows)} candidate "
                    f"row(s) of {st.n}")
            if not len(rows):
                return rows
            keep = evaluate(primary, batch.take(rows))
            return np.sort(rows[keep])

        eq = gscan.extent_query(boxes, intervals)
        state = self._extent_states(st, eq)
        explain(f"Device extent scan: {len(boxes)} box(es), "
                f"{len(intervals)} interval(s), n={st.n}")

        mask = state == 2  # definite IN
        needs_exact = _needs_exact(geoms, primary)
        spatial_f = _spatial_only(primary, geom)
        if needs_exact:
            # envelope containment only proves envelope intersection;
            # the true predicate needs every surviving candidate checked
            check = np.flatnonzero(state >= 1)
        else:
            check = np.flatnonzero(state == 1)  # MAYBE band only
        if spatial_f is not None and len(check):
            keep = evaluate(spatial_f, batch.take(check))
            if needs_exact:
                mask = np.zeros(st.n, dtype=bool)
            mask = mask.copy()
            mask[check[keep]] = True
            explain(f"Exact predicate on {len(check)} candidate(s)")
        elif spatial_f is None:
            # no spatial constraint (pure time query on xz3): every
            # non-OUT row matches
            mask = state >= 1
        return np.flatnonzero(mask)

    def _extent_states(self, st: _TypeState,
                       eq: "gscan.ExtentQuery") -> np.ndarray:
        return gscan.extent_tristate(st.extent_data, eq)

    def _pip_residual(self, spatial_f, col, candidates: np.ndarray,
                      explain: Explainer):
        """Device point-in-polygon for the exact residual when the data
        are points and the query is a single polygon intersects/within
        (the ST_Contains hot loop; SURVEY §7 hard part (b)). Returns a
        bool[len(candidates)] keep mask, or None if not applicable."""
        from ..geometry.base import MultiPolygon, Polygon
        if not isinstance(col, PointColumn):
            return None
        if not isinstance(spatial_f, (ast.Intersects, ast.Within)):
            return None
        g = spatial_f.geom
        if not isinstance(g, (Polygon, MultiPolygon)):
            return None
        if len(candidates) < _DEVICE_PIP_ROWS:
            # a device dispatch costs a round trip (~100ms through a
            # tunnel); the vectorized host crossing-number test clears
            # small candidate sets orders of magnitude sooner — the
            # selective ST_Contains hot loop must stay host-side
            return None
        px = col.x[candidates]
        py = col.y[candidates]
        inside, band_idx = gscan.points_in_polygon_device(
            px, py, gscan.pack_polygon(g))
        if len(band_idx):
            # exact open/closed boundary semantics via the reference
            # evaluator on just the band rows
            sub = self._batch_rows_for(col, px[band_idx], py[band_idx])
            inside[band_idx] = evaluate(spatial_f, sub)
        explain(f"Device point-in-polygon residual "
                f"({len(candidates)} candidates, {len(band_idx)} band)")
        return inside

    @staticmethod
    def _batch_rows_for(col: PointColumn, x: np.ndarray, y: np.ndarray):
        """A minimal single-column FeatureBatch view for band rechecks."""
        sft = parse_spec("band", f"*{col.name}:Point:srid=4326")
        ids = np.array([str(i) for i in range(len(x))], dtype=object)
        return FeatureBatch(sft, ids,
                            {col.name: PointColumn(
                                col.name, x, y,
                                np.ones(len(x), dtype=bool))})


def _geom_centroids(batch: FeatureBatch, geom_field: str):
    """(x, y, valid) for any geometry column: point coords, or envelope
    centroids for extent geometries."""
    col = batch.col(geom_field)
    if isinstance(col, PointColumn):
        return col.x, col.y, col.valid
    bounds = col.bounds
    x = (bounds[:, 0] + bounds[:, 2]) / 2
    y = (bounds[:, 1] + bounds[:, 3]) / 2
    return x, y, col.valid


def _intervals_ms(primary: ast.Filter, dtg: str,
                  lo_unbounded: int = 0) -> list[tuple[int, int]]:
    """Extract inclusive [lo, hi] epoch-millis intervals for the device
    kernels, applying the reference's exclusive-bound adjustment
    (FilterHelper.scala:267-307 rounding semantics). ``lo_unbounded``
    is the open-lower sentinel: 0 for the z3 kernels (the index domain
    floor), a large negative for raw-millis consumers (pre-epoch dates
    are representable there)."""
    from ..filters.helper import to_millis as _to_millis
    out = []
    for b in extract_intervals(primary, dtg):
        lo = _to_millis(b.lower.value) if b.lower.is_bounded \
            else lo_unbounded
        hi = _to_millis(b.upper.value) if b.upper.is_bounded else 2**62
        if b.lower.is_bounded and not b.lower.inclusive:
            lo += 1
        if b.upper.is_bounded and not b.upper.inclusive:
            hi -= 1
        out.append((lo, hi))
    return out


def _needs_exact(geoms, primary: ast.Filter) -> bool:
    """True when the bbox prefilter is insufficient and the exact
    geometry predicate must run on surviving candidates."""
    return any(not _is_envelope(g) for g in geoms) or any(
        isinstance(c, (ast.DWithin, ast.SpatialPredicate))
        for c in ast.walk(primary))


def _is_envelope(g) -> bool:
    from ..filters.helper import _is_box
    from ..geometry import Polygon
    return isinstance(g, Polygon) and not g.holes and _is_box(g)


def _spatial_only(f: ast.Filter, geom: str) -> ast.Filter | None:
    from ..index.splitter import spatial_part
    spatial, _ = spatial_part(f, geom)
    return spatial


def _project_sft(sft: SimpleFeatureType, props: list[str]) -> SimpleFeatureType:
    return SimpleFeatureType(
        sft.type_name, [a for a in sft.attributes if a.name in props],
        sft.user_data)
