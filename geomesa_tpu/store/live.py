"""Live streaming tier: the Kafka datastore analog.

The reference's KafkaDataStore (kafka/data/KafkaDataStore.scala:44)
streams feature mutations as GeoMessages (Create/Delete/Clear,
kafka/utils/GeoMessage.scala:14) through topics; consumers materialize
an in-memory queryable cache with live listeners. Here:

- ``MessageBus`` is the in-process topic fabric (multiple stores attach
  to the same bus: producers publish, consumer stores apply);
- ``LiveDataStore`` maintains an append-buffer + tombstone view over the
  in-memory device store, re-indexing in batches (the cache the
  KafkaCacheLoader builds, kafka/data/KafkaDataStore.scala:68-84);
- listeners receive feature events (KafkaFeatureEvent analog);
- optional age-off expiry drops features older than a ttl at
  maintenance time (AgeOffIterator analog).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import numpy as np

from ..features.batch import FeatureBatch
from ..features.sft import SimpleFeatureType, parse_spec
from ..index.api import Query
from .api import DataStore
from .memory import InMemoryDataStore, QueryResult

__all__ = ["GeoMessage", "MessageBus", "LiveDataStore"]


@dataclasses.dataclass
class GeoMessage:
    """A feature mutation on the bus (GeoMessage.scala:14)."""
    kind: str                       # "create" | "delete" | "clear"
    type_name: str
    batch: FeatureBatch | None = None   # for create
    ids: tuple = ()                 # for delete
    timestamp_ms: int = 0
    visibilities: tuple | None = None   # per-feature labels (create)


class MessageBus:
    """In-process pub/sub topics: the Kafka stand-in. Subscribers are
    called synchronously on publish (tests and single-process pipelines;
    a networked bus slots in behind the same interface)."""

    def __init__(self):
        self._subs: dict[str, list[Callable[[GeoMessage], None]]] = {}

    def subscribe(self, topic: str, fn: Callable[[GeoMessage], None]):
        self._subs.setdefault(topic, []).append(fn)

    def publish(self, topic: str, msg: GeoMessage):
        for fn in self._subs.get(topic, []):
            fn(msg)


class LiveDataStore(DataStore):
    """Streaming store over a MessageBus: publish mutations, query the
    live cache."""

    def __init__(self, bus: MessageBus | None = None,
                 ttl_millis: int | None = None,
                 durable_dir: str | None = None,
                 wal_fsync: str | None = None):
        self.bus = bus or MessageBus()
        self.ttl_millis = ttl_millis
        # the cache journals every applied mutation (bus-delivered ones
        # included) and replays checkpoint + log on open
        self._mem = InMemoryDataStore(durable_dir=durable_dir,
                                      wal_fsync=wal_fsync)
        self._listeners: dict[str, list[Callable[[GeoMessage], None]]] = {}
        self._arrival_ms: dict[str, np.ndarray] = {}
        self._subscribed: set[str] = set()
        # recovered types need the live-tier bookkeeping the replay
        # bypassed: re-subscribe, and stamp rows with the reopen time
        # (real arrival times aren't journaled — "now" gives them a
        # full ttl lease instead of instant age-off)
        now = int(time.time() * 1000)
        for tn in self._mem.get_type_names():
            self._arrival_ms[tn] = np.full(self._mem.count(tn), now,
                                           dtype=np.int64)
            self._subscribed.add(tn)
            self.bus.subscribe(tn, self._on_message)

    @property
    def journal(self):
        """The cache's WAL journal, or None when not durable."""
        return self._mem.journal

    def checkpoint(self, keep: int = 2) -> dict:
        return self._mem.checkpoint(keep=keep)

    def close(self):
        self._mem.close()

    # -- schema ------------------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec or "")
        self._mem.create_schema(sft)
        self._arrival_ms[sft.type_name] = np.empty(0, dtype=np.int64)
        if sft.type_name not in self._subscribed:
            self._subscribed.add(sft.type_name)
            self.bus.subscribe(sft.type_name, self._on_message)

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._mem.get_schema(type_name)

    def get_type_names(self) -> list[str]:
        return self._mem.get_type_names()

    def remove_schema(self, type_name: str):
        self._mem.remove_schema(type_name)
        self._arrival_ms.pop(type_name, None)
        self._listeners.pop(type_name, None)

    # -- producer side -----------------------------------------------------

    def write(self, type_name: str, batch: FeatureBatch,
              timestamp_ms: int | None = None, visibilities=None):
        ts = timestamp_ms if timestamp_ms is not None else int(time.time() * 1000)
        vis = (None if visibilities is None
               else tuple(None if v is None else str(v)
                          for v in visibilities))
        self.bus.publish(type_name, GeoMessage("create", type_name, batch,
                                               timestamp_ms=ts,
                                               visibilities=vis))

    def delete(self, type_name: str, ids):
        self.bus.publish(type_name, GeoMessage(
            "delete", type_name, ids=tuple(map(str, ids)),
            timestamp_ms=int(time.time() * 1000)))

    def clear(self, type_name: str):
        self.bus.publish(type_name, GeoMessage(
            "clear", type_name, timestamp_ms=int(time.time() * 1000)))

    # -- consumer side -----------------------------------------------------

    def poll(self) -> int:
        """Drain a poll-driven bus (FileBus) into this store's cache;
        no-op for the synchronous in-process bus. Returns messages
        applied."""
        poll = getattr(self.bus, "poll", None)
        return poll() if poll is not None else 0

    def _on_message(self, msg: GeoMessage):
        t = msg.type_name
        if t not in self._mem.get_type_names():
            if msg.batch is None:
                # delete/clear for a type this cache never saw: a no-op
                # (nothing to remove), not an error that wedges polling
                return
            # consumer side of a cross-process bus: the schema travels
            # with the message (self-describing wire format). The topic
            # is already subscribed — this message arrived through it —
            # so mark it before create_schema to avoid double delivery.
            self._subscribed.add(t)
            self.create_schema(msg.batch.sft)
        if msg.kind == "create":
            # upsert semantics: replace existing ids (the cache keeps the
            # latest version of each feature, as the reference's does)
            existing = self._mem._state(t)
            incoming = set(msg.batch.ids.astype(str))
            if existing.batch is not None and existing.n:
                dup = np.isin(existing.batch.ids.astype(str), list(incoming))
                if dup.any():
                    self._mem.delete(t, existing.batch.ids[dup])
                    self._arrival_ms[t] = self._arrival_ms[t][~dup]
            self._mem.write(t, msg.batch,
                            visibilities=msg.visibilities)
            self._arrival_ms[t] = np.concatenate([
                self._arrival_ms[t],
                np.full(msg.batch.n, msg.timestamp_ms, dtype=np.int64)])
        elif msg.kind == "delete":
            st = self._mem._state(t)
            if st.batch is not None and st.n:
                keep = ~np.isin(st.batch.ids.astype(str), list(msg.ids))
                self._arrival_ms[t] = self._arrival_ms[t][keep]
            self._mem.delete(t, msg.ids)
        elif msg.kind == "clear":
            sft = self._mem.get_schema(t)
            self._mem.remove_schema(t)
            self._mem.create_schema(sft)
            self._arrival_ms[t] = np.empty(0, dtype=np.int64)
        for fn in self._listeners.get(t, []):
            fn(msg)

    def add_listener(self, type_name: str, fn: Callable[[GeoMessage], None]):
        self._listeners.setdefault(type_name, []).append(fn)

    def remove_listener(self, type_name: str,
                        fn: Callable[[GeoMessage], None]):
        fns = self._listeners.get(type_name, [])
        if fn in fns:
            fns.remove(fn)

    # -- maintenance -------------------------------------------------------

    def expire(self, type_name: str, now_ms: int | None = None) -> int:
        """Drop features older than the ttl; returns the dropped count."""
        if self.ttl_millis is None:
            return 0
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        st = self._mem._state(type_name)
        if st.batch is None or st.n == 0:
            return 0
        old = self._arrival_ms[type_name] < now - self.ttl_millis
        if not old.any():
            return 0
        ids = st.batch.ids[old]
        self._arrival_ms[type_name] = self._arrival_ms[type_name][~old]
        self._mem.delete(type_name, ids)
        return int(old.sum())

    def features_older_than(self, type_name: str, cutoff_ms: int):
        """(ids, batch) of features that arrived before the cutoff — the
        Lambda tier's persistence feed."""
        st = self._mem._state(type_name)
        if st.batch is None or st.n == 0:
            return np.empty(0, object), None
        old = self._arrival_ms[type_name] < cutoff_ms
        idx = np.flatnonzero(old)
        if not len(idx):
            return np.empty(0, object), None
        return st.batch.ids[idx], st.batch.take(idx)

    # -- queries -----------------------------------------------------------

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None) -> QueryResult:
        return self._mem.query(q, type_name, explain_out=explain_out)

    def query_batched(self, queries: list[Query],
                      explain_out=None) -> list[QueryResult]:
        """Coalesced multi-query execution over the live view (one
        fused device scan; see InMemoryDataStore.query_batched)."""
        return self._mem.query_batched(queries, explain_out=explain_out)

    def count(self, type_name: str) -> int:
        return self._mem.count(type_name)

    def bin_query(self, type_name: str, ecql="INCLUDE",
                  track: str | None = None, label: str | None = None,
                  sort: bool = False) -> bytes:
        """BIN aggregation over the live view (delegates to the
        in-memory scan core, version-keyed caching included)."""
        return self._mem.bin_query(type_name, ecql, track=track,
                                   label=label, sort=sort)

    def arrow_ipc(self, type_name: str, ecql="INCLUDE",
                  sort_by: str | None = None) -> bytes:
        return self._mem.arrow_ipc(type_name, ecql, sort_by=sort_by)
