"""Mesh-distributed datastore: the multi-chip execution tier.

One engine, two execution tiers: this store IS the single-device
engine (it subclasses InMemoryDataStore, inheriting the planner,
attribute strategies, visibility filtering, deletes, residual
compilation, LSM writes and the host z-key fast path), with the
*device* tier swapped out — hot columns live as mesh-sharded segments
and wide scans fan out shard-locally with ICI reduces. That mirrors
the reference, where a single ``GeoMesaDataStore`` runs the full query
surface over every distributed backend
(/root/reference/geomesa-index-api/src/main/scala/org/locationtech/
geomesa/index/geotools/GeoMesaDataStore.scala:38, with backends
plugging in through IndexAdapter.scala:24-102 — here the "adapter" is
the small set of scan-tier hooks this subclass overrides).

Execution tiers per query (same policy as the single-device store):

- selective queries resolve EXACTLY inside the host z-key index
  (index-space candidates, never an O(n) mask);
- mid-size candidate sets evaluate exactly on host over just the
  gathered candidate rows;
- wide scans run the fused kernel shard-locally on every device
  (shard_map) with the exact f64 boundary patch on the gathered
  verdict; counts/density/histograms reduce over ICI with psum and
  never materialize row sets at all.

Writes are LSM-style at BOTH levels: host appends buffer and merge
into the sorted z-key index incrementally, and the device tier appends
delta-sized sharded SEGMENTS (re-shard cost proportional to the burst,
the minor-compaction shape); segments compact into one when they pile
up. The reference gets the same write path from BatchWriter mutations
merging into tablets at minor compaction.
"""

from __future__ import annotations

import numpy as np

from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import extract_geometries
from ..index.api import Explainer, Query, QueryHints
from ..parallel import (DistributedScanData, data_mesh, distributed_count,
                        distributed_density, distributed_histogram,
                        distributed_knn, distributed_tristate,
                        exact_hit_rows, shard_extent_data,
                        shard_points_split, shard_scan_data)
from ..scan import zscan
from .memory import (HOST_SCAN_ROWS, InMemoryDataStore, _TypeState,
                     _geom_centroids, _intervals_ms, _needs_exact)

__all__ = ["DistributedDataStore"]

# segment count that triggers compaction (one full re-shard): bounds
# per-query scan dispatches while keeping write bursts O(delta)
MAX_SEGMENTS = 8


class _MeshTypeState(_TypeState):
    """Per-type state whose device tier is a list of mesh-sharded
    segments (LSM runs): writes append delta-sized segments, reads scan
    every segment, compaction re-shards into one."""

    def __init__(self, sft: SimpleFeatureType, mesh):
        super().__init__(sft)
        self.mesh = mesh
        self.segments: list[DistributedScanData] = []
        self.ext_segments: list = []   # DistributedExtentData runs
        self._knn_splits: list = []    # per-segment two-float shards

    # -- device-tier hooks -------------------------------------------------

    def has_point_scan(self) -> bool:
        return bool(self.segments)

    def has_extent_scan(self) -> bool:
        return bool(self.ext_segments)

    def _clear_device_index(self):
        self.segments = []
        self.ext_segments = []
        self._knn_splits = []

    def _build_point_index(self, x, y, millis):
        self.segments = [shard_scan_data(x, y, millis, self.mesh)]
        self.ext_segments = []
        self._knn_splits = [None]

    def _build_extent_index(self, bounds, millis):
        self.ext_segments = [shard_extent_data(bounds, millis, self.mesh)]
        self.segments = []
        self._knn_splits = []

    def _extend_device_index(self, col, dmillis) -> bool:
        """Write burst -> one new delta-sized sharded segment (cost
        proportional to the delta); False once MAX_SEGMENTS runs have
        piled up, leaving the state dirty so the next read compacts
        (full re-shard)."""
        if len(self.segments) >= MAX_SEGMENTS:
            return False
        self.segments.append(
            shard_scan_data(col.x, col.y, dmillis, self.mesh))
        self._knn_splits.append(None)
        return True

    def segment_offsets(self) -> list[int]:
        offs = [0]
        for seg in self.segments:
            offs.append(offs[-1] + seg.n)
        return offs


class DistributedDataStore(InMemoryDataStore):
    """Full-featured datastore sharded over a device mesh — the scale
    tier for 100M+-row tables (BASELINE.md north-star shape), with the
    complete single-device query surface."""

    def __init__(self, mesh=None, audit=None):
        super().__init__(audit=audit)
        self.mesh = mesh if mesh is not None else data_mesh()

    def _new_state(self, sft: SimpleFeatureType) -> _MeshTypeState:
        return _MeshTypeState(sft, self.mesh)

    # -- scan tiers over the sharded segments ------------------------------

    def _scan_gathered(self, st: _MeshTypeState, sq: zscan.ScanQuery,
                       rows: np.ndarray, explain: Explainer,
                       nb: int, ni: int) -> np.ndarray:
        """Candidate sets between the host cap and the block threshold
        evaluate exactly on host in f64 over just the gathered rows —
        index-space work, never an O(n) mask. (A cross-shard device
        gather would pay an all-gather of the candidate set for no
        arithmetic advantage at this tier.)"""
        explain(f"Index-pruned host candidate scan: {len(rows)} "
                f"candidate row(s) of {st.n}, {nb} box(es), "
                f"{ni} interval(s)")
        from ..index.zkeys import ZKeyIndex
        col = st.batch.col(st.sft.geom_field)
        intervals = [] if sq.time_any else \
            [tuple(iv) for iv in sq.host_intervals]
        ms = (st.batch.col(st.sft.dtg_field).millis
              if intervals else None)
        boxes = [tuple(b) for b in sq.host_boxes]
        keep = ZKeyIndex._eval_sorted(col.x, col.y, ms, rows, boxes,
                                      intervals)
        return np.sort(rows[keep])

    def _scan_dense(self, st: _MeshTypeState, sq: zscan.ScanQuery,
                    explain: Explainer, nb: int, ni: int) -> np.ndarray:
        """Dense tier: the fused kernel shard-locally on every device,
        per segment, compacted ON DEVICE to hit row ids (count-then-
        allocate; O(hits) host work, never a full-length mask) with the
        exact f64 boundary patch applied in row-set space."""
        explain(f"Distributed scan over {self.mesh.devices.size} "
                f"device(s), {len(st.segments)} segment(s), n={st.n}, "
                f"{nb} box(es), {ni} interval(s)")
        offs = st.segment_offsets()[:-1]
        parts = [exact_hit_rows(seg, sq) + off
                 for seg, off in zip(st.segments, offs)]
        return (np.concatenate(parts) if parts
                else np.empty(0, dtype=np.int64))

    def _batched_scan_rows(self, st: _MeshTypeState,
                           items) -> list[np.ndarray]:
        """Micro-batched dense tier over the sharded segments: ONE
        shard-mapped launch per segment evaluates every query in the
        batch (parallel/mesh.batch_exact_hit_rows), replacing the
        per-query dispatch of the scalar path."""
        from ..parallel.mesh import batch_exact_hit_rows
        sqs = []
        for _q, strategy, art in items:
            if art.sq is None:
                _g, boxes, intervals, _ne, _s = \
                    self._fill_artifacts(st, strategy, art)
                art.sq = zscan.make_query(boxes, intervals)
            sqs.append(art.sq)
        bq = zscan.stack_queries(sqs)
        offs = st.segment_offsets()[:-1]
        per_query: list[list[np.ndarray]] = [[] for _ in sqs]
        for seg, off in zip(st.segments, offs):
            for j, rows in enumerate(batch_exact_hit_rows(seg, bq)):
                per_query[j].append(rows + off)
        return [np.concatenate(parts) if parts
                else np.empty(0, dtype=np.int64)
                for parts in per_query]

    def _extent_states(self, st: _MeshTypeState, eq) -> np.ndarray:
        return np.concatenate([distributed_tristate(seg, eq)
                               for seg in st.ext_segments])

    # -- aggregate pushdown (psum over ICI) --------------------------------

    def _psum_plan(self, st: _MeshTypeState, q: Query):
        """(strategy, boxes, intervals) when the plan's result is fully
        decided by the shard-local kernel — pure z envelope scan, no
        residual, no visibility, no sampling/limit stages — else None
        (caller takes the shared row pipeline)."""
        from ..index.planner import decide_strategy
        strategy = decide_strategy(st.sft, q, self._indices(st.sft), st.n,
                                   stats=self.stats.get(q.type_name),
                                   explain=Explainer())
        primary = (strategy.primary if strategy.primary is not None
                   else ast.Include())
        geoms = extract_geometries(primary, st.sft.geom_field)
        if (strategy.index not in ("z2", "z3")
                or strategy.secondary is not None
                or _needs_exact(geoms, primary)
                or st.has_vis or q.auths is not None
                or q.hints.get(QueryHints.SAMPLING) is not None
                or q.max_features is not None):
            return None
        boxes = [g.envelope.as_tuple() for g in geoms] or \
            [(-180.0, -90.0, 180.0, 90.0)]
        intervals = (_intervals_ms(primary, st.sft.dtg_field)
                     if st.sft.dtg_field is not None
                     and strategy.index == "z3" else [])
        return strategy, boxes, intervals

    def query_count(self, q: Query | str,
                    type_name: str | None = None) -> int:
        """Counts never materialize row sets on the dense tier: the
        selective path counts inside the host z-key index; wide
        psum-eligible scans reduce over ICI (server-side aggregate ->
        client reduce, SURVEY §2.5#5) with the exact host boundary
        adjustment. Every other plan shape takes the shared pipeline."""
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        st = self._state(q.type_name)
        if st.n == 0:
            return 0
        st.ensure_index()
        plan = self._psum_plan(st, q) if st.segments else None
        if plan is None:
            return super().query_count(q)
        strategy, boxes, intervals = plan
        import time as _time
        t0 = _time.perf_counter()
        from ..index.zkeys import SCAN_BLOCK_THRESHOLD, search_rows
        host_cap = min(int(float(SCAN_BLOCK_THRESHOLD.get()) * st.n),
                       int(HOST_SCAN_ROWS.get()))
        kind, rows = search_rows(st.zindex, strategy.index, boxes,
                                 intervals, host_cap, host_cap)
        if kind == "exact":
            n = len(rows)
        else:
            sq = zscan.make_query(boxes, intervals)
            n = sum(distributed_count(seg, sq) for seg in st.segments)
        from ..audit import audit_query
        audit_query(self.audit, "mesh", q.type_name, str(q.filter),
                    q.hints, 0.0, (_time.perf_counter() - t0) * 1000, n,
                    index=strategy.index, rows_scanned=int(st.n))
        return n

    def _density_uncached(self, type_name: str, ecql, bbox, width: int,
                          height: int,
                          weight_attr: str | None = None) -> np.ndarray:
        """Heatmap grid: shard-local scatter-add psum-merged over ICI
        (DensityScan -> client-reduce shape) for psum-eligible plans;
        the shared host-binned path otherwise. (The public ``density``
        wrapper in the base class adds the materialized-result cache.)"""
        st = self._state(type_name)
        if st.n == 0 or weight_attr is not None:
            return super()._density_uncached(type_name, ecql, bbox, width,
                                             height, weight_attr)
        st.ensure_index()
        q = Query(type_name, ecql)
        plan = self._psum_plan(st, q) if st.segments else None
        if plan is None:
            return super()._density_uncached(type_name, ecql, bbox, width,
                                             height, weight_attr)
        _, boxes, intervals = plan
        sq = zscan.make_query(boxes, intervals)
        grid = np.zeros((height, width), dtype=np.float32)
        for seg in st.segments:
            grid += distributed_density(seg, sq, bbox, width, height)
        return grid

    def histogram(self, type_name: str, attribute: str, nbins: int,
                  lo: float, hi: float) -> np.ndarray:
        """Distributed attribute histogram: shard-local bincount merged
        over ICI with psum (StatsCombiner merge analog)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        st = self._state(type_name)
        if st.n == 0:
            return np.zeros(nbins, dtype=np.int64)
        vals = st.batch.col(attribute)
        v = np.asarray(getattr(vals, "values", getattr(vals, "millis", None)),
                       np.float64)
        k = self.mesh.devices.size
        n_padded = ((st.n + k - 1) // k) * k
        vp = np.full(n_padded, np.nan, np.float32)
        vp[: st.n] = v
        m = np.zeros(n_padded, dtype=bool)
        m[: st.n] = np.asarray(vals.valid)
        sh = NamedSharding(self.mesh, P("data"))
        return distributed_histogram(jax.device_put(jnp.asarray(vp), sh),
                                     jax.device_put(jnp.asarray(m), sh),
                                     self.mesh, nbins, lo, hi)

    def _arrow_ipc_uncached(self, type_name: str, ecql="INCLUDE",
                            sort_by: str | None = None) -> bytes:
        """Distributed Arrow output (DeltaWriter.scala:47,203 shape):
        the row-selection pipeline runs once, matched rows split along
        the mesh's shard boundaries, every shard encodes ITS rows as an
        IPC payload with shard-local dictionaries, and the payloads
        merge into one stream with global dictionaries
        (arrow/scan.merge_deltas). On hardware the per-shard encode is
        host work against that device's row range — the client-side
        reduce of the reference's server-side ArrowScan."""
        from ..arrow.io import sort_batches, write_ipc
        from ..arrow.scan import merge_deltas
        from ..features.batch import FeatureBatch
        from ..index.api import Query as _Q
        from .memory import _null_cells
        st = self._state(type_name)
        sft = st.sft
        if st.batch is None or st.n == 0:
            return merge_deltas([], sft=sft, sort_by=sort_by)
        q = ecql if isinstance(ecql, _Q) else _Q(type_name, ecql)
        idx, _strategy, _tp, _ts, attr_mask = self._matching_rows(
            q, st, Explainer())
        if not len(idx):
            return merge_deltas([], sft=sft, sort_by=sort_by)
        # matched ORIGINAL row ids split at the mesh's shard
        # boundaries (rows shard evenly in row order): each shard
        # encodes its own rows with shard-local dictionaries
        k = self.mesh.devices.size
        per = (st.n + k - 1) // k
        shard_of = np.minimum(idx // max(per, 1), k - 1)
        payloads = []
        for s in np.unique(shard_of):
            sel = shard_of == s
            sub = st.batch.take(idx[sel])
            if attr_mask is not None and not attr_mask[sel].all():
                # same cell-level redaction as query(): unauthorized
                # attribute values must not leak through the Arrow
                # surface (KryoVisibilityRowEncoder semantics)
                m = attr_mask[sel]
                cols = {}
                for j, a in enumerate(sft.attributes):
                    col = sub.col(a.name)
                    bad = ~m[:, j]
                    cols[a.name] = (_null_cells(col, bad) if bad.any()
                                    else col)
                sub = FeatureBatch(sft, sub.ids, cols)
            if sort_by:
                # shard-local sort so the client reduce is a streaming
                # k-way merge instead of a concat-then-sort (the
                # reference's tablets return sorted batches too)
                sub = sort_batches(sub, sort_by)
            payloads.append(write_ipc(sft, sub))
        return merge_deltas(payloads, sft=sft, sort_by=sort_by,
                            presorted=True)

    def knn(self, type_name: str, qx: float, qy: float, k: int) -> np.ndarray:
        """k nearest feature ids: shard-local top-k prune per segment
        (candidates travel with their two-float coords), exact f64
        re-rank across segment candidates on host."""
        st = self._state(type_name)
        if st.n == 0:
            return np.empty(0, dtype=object)
        if st.sft.geom_field is None:
            raise ValueError("knn requires a geometry field")
        st.ensure_index()
        if not st.segments:
            # extent types: exact centroid ranking on host
            x, y, valid = _geom_centroids(st.batch, st.sft.geom_field)
            d2 = np.where(valid, (x - qx) ** 2 + (y - qy) ** 2, np.inf)
            return st.batch.ids[np.argsort(d2, kind="stable")[:k]]
        col = st.batch.col(st.sft.geom_field)
        offs = st.segment_offsets()
        cands = []
        for i in range(len(st.segments)):
            split = st._knn_splits[i]
            if split is None:
                lo, hi = offs[i], offs[i + 1]
                split = shard_points_split(col.x[lo:hi], col.y[lo:hi],
                                           self.mesh)
                st._knn_splits[i] = split
            sp, valid, n = split
            idx = distributed_knn(None, None, valid, self.mesh, n,
                                  qx, qy, k, split=sp)
            cands.append(np.asarray(idx, dtype=np.int64) + offs[i])
        cand = np.concatenate(cands)
        d2 = (col.x[cand] - qx) ** 2 + (col.y[cand] - qy) ** 2
        order = np.argsort(d2, kind="stable")
        return st.batch.ids[cand[order][:k]]
