"""Mesh-distributed datastore: the multi-chip execution tier.

Where InMemoryDataStore runs fused scans on one device, this store
shards the hot columns of each point type over a ``jax.sharding.Mesh``
and executes the same query plans with shard-local kernels + ICI
reduces — the architectural analog of the reference's horizontal
scaling across tablet/region servers (SURVEY.md §2.5 #2/#5: shard
parallelism + server-side pushdown with client reduce):

- query ids/features: distributed scan mask (shard_map) gathered with
  the exact f64 boundary patch, residual filters evaluated on host
  candidates only;
- count: psum on ICI, host boundary adjustment (never gathers a mask);
- density: shard-local scatter-add grids psum-merged over ICI;
- histogram stats: shard-local bincount + psum;
- KNN: shard-local top-k prune + host exact re-rank.

The host batch stays resident as the source of truth for residual
predicates and attribute materialization (the "record table" role);
device shards hold the scan-hot columns (the "index tables").
"""

from __future__ import annotations

import numpy as np

from ..features.batch import FeatureBatch, PointColumn
from ..features.sft import SimpleFeatureType, parse_spec
from ..filters import ast
from ..filters.evaluate import evaluate
from ..filters.helper import extract_geometries
from ..index.api import Explainer, FilterStrategy, Query, QueryHints
from .api import DataStore
from ..index.planner import decide_strategy
from ..parallel import (DistributedScanData, data_mesh, distributed_count,
                        distributed_density, distributed_histogram,
                        distributed_knn, exact_host_mask,
                        shard_points_split, shard_scan_data)
from ..scan import zscan
from .memory import (QueryResult, _intervals_ms, _is_envelope, _needs_exact,
                     _spatial_only)

__all__ = ["DistributedDataStore"]


class _MeshTypeState:
    def __init__(self, sft: SimpleFeatureType):
        self.sft = sft
        self.batch: FeatureBatch | None = None
        self.data: DistributedScanData | None = None
        self.split = None    # two-float sharded coords for KNN
        self.valid = None
        self.zindex = None   # host sorted z-key index (range pruning)
        self.dirty = False

    @property
    def n(self) -> int:
        return 0 if self.batch is None else self.batch.n


class DistributedDataStore(DataStore):
    """Point-type datastore sharded over a device mesh.

    Extent (non-point) types belong on the single-device store for now;
    this tier is the 100M+-row scan engine (BASELINE.md target shape).
    """

    def __init__(self, mesh=None):
        self.mesh = mesh if mesh is not None else data_mesh()
        self._types: dict[str, _MeshTypeState] = {}

    # -- schema / writes --------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType | str,
                      spec: str | None = None):
        if isinstance(sft, str):
            sft = parse_spec(sft, spec)
        if sft.geom_field is None or not sft.is_points:
            raise ValueError("DistributedDataStore requires a point "
                             "geometry type")
        self._types[sft.type_name] = _MeshTypeState(sft)

    def get_schema(self, type_name: str) -> SimpleFeatureType:
        return self._state(type_name).sft

    def get_type_names(self) -> list[str]:
        return sorted(self._types)

    def _state(self, type_name: str) -> _MeshTypeState:
        try:
            return self._types[type_name]
        except KeyError:
            raise KeyError(f"unknown feature type '{type_name}'") from None

    def write(self, type_name: str, batch: FeatureBatch):
        st = self._state(type_name)
        st.batch = batch if st.batch is None else st.batch.concat(batch)
        st.dirty = True

    def count(self, type_name: str) -> int:
        return self._state(type_name).n

    # -- sharding ---------------------------------------------------------

    def _ensure_sharded(self, st: _MeshTypeState):
        """(Re)shard the hot columns after writes — the re-balance that
        tablet splits do continuously happens here at scan boundaries."""
        if not st.dirty and st.data is not None:
            return
        if st.batch is None or st.batch.n == 0:
            st.data = None
            st.split = None
            st.valid = None
            st.zindex = None
            st.dirty = False
            return
        col = st.batch.col(st.sft.geom_field)
        dtg = st.sft.dtg_field
        millis = (st.batch.col(dtg).millis if dtg is not None
                  else np.zeros(st.batch.n, dtype=np.int64))
        st.data = shard_scan_data(col.x, col.y, millis, self.mesh)
        st.split, st.valid, _ = shard_points_split(col.x, col.y, self.mesh)
        # the same host z-key index the single-device engine prunes
        # with: selective queries skip the mesh scan entirely
        from ..index.zkeys import ZKeyIndex
        st.zindex = ZKeyIndex(col.x, col.y,
                              millis if dtg is not None else None,
                              st.sft.z3_interval)
        st.dirty = False

    # -- queries ----------------------------------------------------------

    def _scan_query(self, st: _MeshTypeState,
                    strategy: FilterStrategy) -> zscan.ScanQuery:
        primary = (strategy.primary if strategy.primary is not None
                   else ast.Include())
        geom = st.sft.geom_field
        dtg = st.sft.dtg_field
        geoms = extract_geometries(primary, geom)
        boxes = [g.envelope.as_tuple() for g in geoms] or \
            [(-180.0, -90.0, 180.0, 90.0)]
        intervals = (_intervals_ms(primary, dtg)
                     if dtg is not None and strategy.index == "z3" else [])
        return zscan.make_query(boxes, intervals)

    def _plan(self, q: Query, st: _MeshTypeState, explain: Explainer):
        indices = ["z3", "z2"] if st.sft.dtg_field is not None else ["z2"]
        indices.append("id")
        return decide_strategy(st.sft, q, indices, st.n, explain=explain)

    def query(self, q: Query | str, type_name: str | None = None,
              explain_out=None) -> QueryResult:
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        st = self._state(q.type_name)
        explain = Explainer(explain_out)
        explain.push(f"Distributed planning '{q.type_name}' "
                     f"filter={q.filter} mesh={self.mesh.devices.size}dev")
        if st.n == 0:
            explain("Store is empty").pop()
            return QueryResult(np.empty(0, dtype=object), None, explain,
                               FilterStrategy("empty", None, None))
        self._ensure_sharded(st)
        strategy = self._plan(q, st, explain)

        if strategy.index == "empty":
            mask = np.zeros(st.n, dtype=bool)
        elif strategy.index == "id" and strategy.primary is not None:
            mask = np.isin(st.batch.ids.astype(str),
                           np.asarray(strategy.primary.ids, dtype=str))
        else:
            sq = self._scan_query(st, strategy)
            mask = self._pruned_or_distributed(st, strategy, sq, explain)
            primary = strategy.primary or ast.Include()
            geoms = extract_geometries(primary, st.sft.geom_field)
            if _needs_exact(geoms, primary):
                cand = np.flatnonzero(mask)
                spatial_f = _spatial_only(primary, st.sft.geom_field)
                if spatial_f is not None and len(cand):
                    keep = evaluate(spatial_f, st.batch.take(cand))
                    mask = np.zeros(st.n, dtype=bool)
                    mask[cand[keep]] = True
                    explain(f"Exact predicate on {len(cand)} candidate(s)")

        if strategy.secondary is not None:
            cand = np.flatnonzero(mask)
            if len(cand):
                keep = evaluate(strategy.secondary, st.batch.take(cand))
                mask = np.zeros(st.n, dtype=bool)
                mask[cand[keep]] = True
            explain(f"Residual filter applied: {strategy.secondary}")

        idx = np.flatnonzero(mask)
        rate = q.hints.get(QueryHints.SAMPLING)
        if rate is not None and len(idx):
            from ..scan.aggregations import sample_mask
            by_attr = q.hints.get(QueryHints.SAMPLE_BY)
            by = None
            if by_attr is not None:
                col = st.batch.col(by_attr)
                by = np.array([col.value(int(i)) or "" for i in idx],
                              dtype=object).astype(str)
            idx = idx[sample_mask(len(idx), float(rate), by)]
            explain(f"Sampling applied: rate={rate}")
        if q.sort_by is not None:
            from .common import sort_order
            idx = idx[sort_order(st.batch, q.sort_by, q.sort_desc, idx)]
            explain(f"Sorted by {q.sort_by}"
                    f"{' desc' if q.sort_desc else ''}")
        if q.max_features is not None:
            idx = idx[: q.max_features]
        ids = st.batch.ids[idx]
        batch = st.batch.take(idx)
        explain(f"Hits: {len(ids)}").pop()
        return QueryResult(ids, batch, explain, strategy)

    def _pruned_or_distributed(self, st: _MeshTypeState,
                               strategy: FilterStrategy,
                               sq: zscan.ScanQuery,
                               explain: Explainer) -> np.ndarray:
        """z-index pruning + host fast path for selective queries (the
        single-device engine's crossover); wide scans fan out over the
        mesh. Returns a bool[n] mask."""
        from ..index.zkeys import SCAN_BLOCK_THRESHOLD, search_rows
        from .memory import HOST_SCAN_ROWS
        boxes = [tuple(b) for b in sq.host_boxes]
        intervals = [tuple(iv) for iv in sq.host_intervals]
        # the mesh has no gathered-candidate device path, so pruning is
        # only worthwhile up to the host fast-path size
        max_rows = min(int(float(SCAN_BLOCK_THRESHOLD.get()) * st.n),
                       int(HOST_SCAN_ROWS.get()))
        kind, idx = search_rows(st.zindex, strategy.index, boxes,
                                intervals, max_rows, max_rows)
        if kind == "exact":
            explain(f"Index-pruned host scan: {len(idx)} hit(s) "
                    f"of {st.n}")
            mask = np.zeros(st.n, dtype=bool)
            mask[idx] = True
            return mask
        explain(f"Distributed scan over {self.mesh.devices.size} "
                f"device(s)")
        return exact_host_mask(st.data, sq)

    def query_count(self, q: Query | str, type_name: str | None = None) -> int:
        """Count without gathering a mask: psum over ICI + host boundary
        adjustment (exact). Falls back to query() when the plan needs
        residual/exact predicates."""
        if isinstance(q, str):
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        st = self._state(q.type_name)
        if st.n == 0:
            return 0
        self._ensure_sharded(st)
        explain = Explainer()
        strategy = self._plan(q, st, explain)
        primary = strategy.primary or ast.Include()
        geoms = extract_geometries(primary, st.sft.geom_field)
        if (strategy.index not in ("z2", "z3")
                or strategy.secondary is not None
                or _needs_exact(geoms, primary)
                or q.hints.get(QueryHints.SAMPLING) is not None
                or q.max_features is not None
                or q.auths is not None):
            # row-limiting/sampling/visibility stages need the full
            # query pipeline for counts to match query().n
            return int(self.query(q).n)
        return distributed_count(st.data, self._scan_query(st, strategy))

    def density(self, type_name: str, ecql, bbox, width: int, height: int):
        """Heatmap grid via shard-local scatter-add + psum."""
        st = self._state(type_name)
        if st.n == 0:
            return np.zeros((height, width), dtype=np.float32)
        self._ensure_sharded(st)
        q = Query(type_name, ecql)
        explain = Explainer()
        strategy = self._plan(q, st, explain)
        if strategy.index in ("z2", "z3") and strategy.secondary is None:
            sq = self._scan_query(st, strategy)
            return distributed_density(st.data, sq, bbox, width, height)
        # residual-bearing plans: exact mask, host binning
        res = self.query(q)
        from ..scan.aggregations import density_grid
        col = res.batch.col(st.sft.geom_field)
        return density_grid(col.x, col.y, np.ones(len(col.x), bool),
                            bbox, width, height)

    def histogram(self, type_name: str, attribute: str, nbins: int,
                  lo: float, hi: float) -> np.ndarray:
        """Distributed attribute histogram (psum-merged)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        st = self._state(type_name)
        if st.n == 0:
            return np.zeros(nbins, dtype=np.int64)
        self._ensure_sharded(st)
        vals = st.batch.col(attribute)
        v = np.asarray(getattr(vals, "values", getattr(vals, "millis", None)),
                       np.float64)
        k = self.mesh.devices.size
        n_padded = ((st.n + k - 1) // k) * k
        vp = np.full(n_padded, np.nan, np.float32)
        vp[: st.n] = v
        m = np.zeros(n_padded, dtype=bool)
        m[: st.n] = np.asarray(vals.valid)
        sh = NamedSharding(self.mesh, P("data"))
        return distributed_histogram(jax.device_put(jnp.asarray(vp), sh),
                                     jax.device_put(jnp.asarray(m), sh),
                                     self.mesh, nbins, lo, hi)

    def knn(self, type_name: str, qx: float, qy: float, k: int) -> np.ndarray:
        """k nearest feature ids via the distributed prune + exact
        host re-rank."""
        st = self._state(type_name)
        if st.n == 0:
            return np.empty(0, dtype=object)
        self._ensure_sharded(st)
        idx = distributed_knn(None, None, st.valid, self.mesh, st.n,
                              qx, qy, k, split=st.split)
        return st.batch.ids[idx]
