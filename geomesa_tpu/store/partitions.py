"""Partition schemes for the filesystem store.

Mirrors the reference's fs partition schemes
(fs/storage/common/PartitionScheme.scala:99): DateTimeScheme (daily /
hourly / monthly / julian directory trees), Z2Scheme (z-curve cell
dirs), and CompositeScheme (scheme products). A scheme maps each
feature to a partition name at write time and a filter to the covering
partition-name set at plan time (partition pruning IS the fs store's
query planning, fs/FsQueryPlanning.scala).
"""

from __future__ import annotations

import itertools

import numpy as np

from ..curves import z2_decode, z2_encode
from ..features.batch import FeatureBatch, PointColumn
from ..features.sft import SimpleFeatureType
from ..filters import ast
from ..filters.helper import extract_geometries, extract_intervals

__all__ = ["PartitionScheme", "DateTimeScheme", "Z2Scheme",
           "CompositeScheme", "scheme_from_config", "AttributeScheme"]

MS_HOUR = 3_600_000
MS_DAY = 86_400_000


class PartitionScheme:
    """Maps rows -> partition names and filters -> covering names."""

    name: str

    def partition_for_rows(self, sft: SimpleFeatureType,
                           batch: FeatureBatch) -> np.ndarray:
        raise NotImplementedError

    def covering_partitions(self, sft: SimpleFeatureType,
                            f: ast.Filter) -> list[str] | None:
        """Partition names possibly matching the filter, or None when
        the scheme cannot prune (= all partitions)."""
        raise NotImplementedError

    def to_config(self) -> dict:
        raise NotImplementedError


class DateTimeScheme(PartitionScheme):
    """Time-directory partitions (PartitionScheme.scala:190).

    Formats: 'daily' -> yyyy/MM/dd, 'hourly' -> yyyy/MM/dd/HH,
    'monthly' -> yyyy/MM, 'julian-daily' -> yyyy/DDD.
    """

    FORMATS = ("daily", "hourly", "monthly", "julian-daily")

    def __init__(self, fmt: str = "daily", dtg: str | None = None):
        if fmt not in self.FORMATS:
            raise ValueError(f"unknown datetime format {fmt!r}")
        self.fmt = fmt
        self.dtg = dtg
        self.name = f"datetime:{fmt}"

    def _names_for_millis(self, ms: np.ndarray) -> np.ndarray:
        dt = np.asarray(ms, np.int64).astype("datetime64[ms]")
        years = dt.astype("datetime64[Y]")
        y = (years.astype(np.int64) + 1970).astype("U4")
        months = dt.astype("datetime64[M]")
        m = np.char.zfill(((months.astype(np.int64) % 12) + 1).astype("U2"), 2)
        if self.fmt == "monthly":
            return np.char.add(np.char.add(y, "/"), m)
        days = dt.astype("datetime64[D]")
        if self.fmt == "julian-daily":
            doy = ((days - years.astype("datetime64[D]"))
                   .astype(np.int64) + 1).astype("U3")
            return np.char.add(np.char.add(y, "/"), np.char.zfill(doy, 3))
        dom = np.char.zfill(
            ((days - months.astype("datetime64[D]")).astype(np.int64) + 1
             ).astype("U2"), 2)
        ymd = np.char.add(np.char.add(np.char.add(np.char.add(y, "/"), m), "/"), dom)
        if self.fmt == "daily":
            return ymd
        hh = np.char.zfill(((np.asarray(ms, np.int64) // MS_HOUR) % 24
                            ).astype("U2"), 2)
        return np.char.add(np.char.add(ymd, "/"), hh)

    def partition_for_rows(self, sft, batch):
        dtg = self.dtg or sft.dtg_field
        ms = batch.col(dtg).millis
        return self._names_for_millis(ms)

    def covering_partitions(self, sft, f):
        dtg = self.dtg or sft.dtg_field
        if dtg is None:
            return None
        iv = extract_intervals(f, dtg)
        if iv.disjoint:
            return []
        if not iv or any(not (b.lower.is_bounded and b.upper.is_bounded)
                         for b in iv):
            return None
        step = {"hourly": MS_HOUR}.get(self.fmt, MS_DAY)
        names: set[str] = set()
        for b in iv:
            lo = int(b.lower.value) if not isinstance(b.lower.value, str) \
                else int(np.datetime64(str(b.lower.value).rstrip("Z"), "ms").astype(np.int64))
            hi = int(b.upper.value) if not isinstance(b.upper.value, str) \
                else int(np.datetime64(str(b.upper.value).rstrip("Z"), "ms").astype(np.int64))
            if hi < lo:
                continue
            if (hi - lo) // step > 100_000:
                return None  # too wide to enumerate; fall back to all
            ts = np.arange((lo // step) * step, hi + 1, step, dtype=np.int64)
            names.update(self._names_for_millis(ts).tolist())
        return sorted(names)

    def to_config(self):
        return {"scheme": "datetime", "format": self.fmt, "dtg": self.dtg}


class Z2Scheme(PartitionScheme):
    """Z2-cell partitions (PartitionScheme.scala:262): the leading
    2*bits bits of the z2 key, as zero-padded decimal dir names."""

    def __init__(self, bits: int = 4, geom: str | None = None):
        self.bits = bits
        self.geom = geom
        self.name = f"z2:{bits}"
        self._digits = len(str((1 << (2 * bits)) - 1))

    def _cell_of(self, x, y) -> np.ndarray:
        z = z2_encode(self._norm(x, 180.0), self._norm(y, 90.0))
        return (z >> np.uint64(62 - 2 * self.bits)).astype(np.int64)

    def _norm(self, v, half: float) -> np.ndarray:
        v = np.clip(np.asarray(v, np.float64), -half, half)
        n = np.floor((v + half) / (2 * half) * (1 << 31)).astype(np.int64)
        return np.minimum(n, (1 << 31) - 1).astype(np.int64)

    def partition_for_rows(self, sft, batch):
        geom = self.geom or sft.geom_field
        col = batch.col(geom)
        if isinstance(col, PointColumn):
            x, y = col.x, col.y
        else:
            x = (col.bounds[:, 0] + col.bounds[:, 2]) / 2
            y = (col.bounds[:, 1] + col.bounds[:, 3]) / 2
        cells = self._cell_of(x, y)
        return np.char.zfill(cells.astype(f"U{self._digits}"), self._digits)

    def covering_partitions(self, sft, f):
        geom = self.geom or sft.geom_field
        if geom is None:
            return None
        geoms = extract_geometries(f, geom)
        if geoms.disjoint:
            return []
        if not geoms:
            return None
        cells: set[int] = set()
        side = 1 << self.bits
        for g in geoms:
            env = g.envelope
            x0 = int(np.clip((env.xmin + 180) / 360 * side, 0, side - 1))
            x1 = int(np.clip((env.xmax + 180) / 360 * side, 0, side - 1))
            y0 = int(np.clip((env.ymin + 90) / 180 * side, 0, side - 1))
            y1 = int(np.clip((env.ymax + 90) / 180 * side, 0, side - 1))
            for cx in range(x0, x1 + 1):
                for cy in range(y0, y1 + 1):
                    z = int(z2_encode(np.int64(cx) << np.int64(31 - self.bits),
                                      np.int64(cy) << np.int64(31 - self.bits)))
                    cells.add(z >> (62 - 2 * self.bits))
        return [str(c).zfill(self._digits) for c in sorted(cells)]

    def to_config(self):
        return {"scheme": "z2", "bits": self.bits, "geom": self.geom}


class AttributeScheme(PartitionScheme):
    """Partition by an attribute's value (the reference supports
    attribute partitioning in later versions; useful for e.g. per-day
    source splits)."""

    def __init__(self, attribute: str):
        self.attribute = attribute
        self.name = f"attr:{attribute}"

    def partition_for_rows(self, sft, batch):
        col = batch.col(self.attribute)
        return np.array([str(col.value(i)) for i in range(batch.n)])

    def covering_partitions(self, sft, f):
        from ..filters.helper import extract_attribute_bounds
        bounds = extract_attribute_bounds(f, self.attribute)
        if bounds.disjoint:
            return []
        if not bounds:
            return None
        names = []
        for b in bounds:
            if b.is_equality:
                names.append(str(b.lower.value))
            else:
                return None
        return sorted(set(names))

    def to_config(self):
        return {"scheme": "attribute", "attribute": self.attribute}


class CompositeScheme(PartitionScheme):
    """Product of schemes: names join with '/' (PartitionScheme.scala
    CompositeScheme)."""

    def __init__(self, schemes: list[PartitionScheme]):
        self.schemes = schemes
        self.name = "composite:" + "+".join(s.name for s in schemes)

    def partition_for_rows(self, sft, batch):
        parts = [s.partition_for_rows(sft, batch) for s in self.schemes]
        out = parts[0]
        for p in parts[1:]:
            out = np.char.add(np.char.add(out.astype(str), "/"), p.astype(str))
        return out

    def covering_partitions(self, sft, f):
        per = [s.covering_partitions(sft, f) for s in self.schemes]
        if any(p == [] for p in per):
            return []
        if any(p is None for p in per):
            # cannot enumerate the product when one side is unpruned
            return None
        return ["/".join(combo) for combo in itertools.product(*per)]

    def to_config(self):
        return {"scheme": "composite",
                "schemes": [s.to_config() for s in self.schemes]}


def scheme_from_config(cfg: dict) -> PartitionScheme:
    kind = cfg["scheme"]
    if kind == "datetime":
        return DateTimeScheme(cfg.get("format", "daily"), cfg.get("dtg"))
    if kind == "z2":
        return Z2Scheme(cfg.get("bits", 4), cfg.get("geom"))
    if kind == "attribute":
        return AttributeScheme(cfg["attribute"])
    if kind == "composite":
        return CompositeScheme([scheme_from_config(c) for c in cfg["schemes"]])
    raise ValueError(f"unknown partition scheme: {kind}")
