"""L5 datastores (SURVEY.md 2.2): the in-memory TPU store is the
flagship execution engine; fs (Parquet + partition pruning), live
(streaming bus) and lambda (two-tier) layer on top of it."""

from .api import DataStore
from .memory import InMemoryDataStore, QueryResult
from .fs import FileSystemDataStore
from .live import GeoMessage, LiveDataStore, MessageBus
from .filebus import FileBus
from .socketbus import SocketBroker, SocketBus
from .lambda_store import LambdaDataStore
from .mesh_store import DistributedDataStore
from .fs_mesh import FsBackedDistributedDataStore
from .remote import RemoteDataStore
from .stream import (FileTailSource, IterableSource, StreamDataStore,
                     StreamSource)
from .partitions import (AttributeScheme, CompositeScheme, DateTimeScheme,
                         PartitionScheme, Z2Scheme, scheme_from_config)

__all__ = ["DataStore", "InMemoryDataStore", "QueryResult",
           "FileSystemDataStore",
           "DistributedDataStore", "FsBackedDistributedDataStore",
           "RemoteDataStore",
           "GeoMessage", "LiveDataStore", "MessageBus", "LambdaDataStore",
           "FileBus", "SocketBroker", "SocketBus",
           "StreamSource", "StreamDataStore", "FileTailSource",
           "IterableSource",
           "AttributeScheme", "CompositeScheme", "DateTimeScheme",
           "PartitionScheme", "Z2Scheme", "scheme_from_config"]
