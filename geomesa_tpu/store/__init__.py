"""L5 datastores. The in-memory TPU store is the flagship execution
engine (geomesa-memory/CQEngine analog, but device-resident); fs/live
tiers layer on top of it."""

from .memory import InMemoryDataStore, QueryResult

__all__ = ["InMemoryDataStore", "QueryResult"]
