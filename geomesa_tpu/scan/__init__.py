"""L6 device scan kernels (SURVEY.md 2.2 iterators/coprocessors).

The reference pushes filtering and aggregation into the database's scan
machinery (Accumulo iterators, HBase coprocessors); here the equivalents
are fused, jitted JAX kernels over columnar device arrays.  A "scan" is
one XLA program: predicate masks + optional aggregation, executed on the
shard holding the data, with ICI collectives as the reduce.
"""

from .pallas_scan import (PallasScanData, build_pallas_data,
                          pallas_scan_count, pallas_scan_mask)
from .zscan import (DeviceScanData, ScanQuery, boundary_candidates,
                    build_scan_data, exact_patch, make_query, scan_mask,
                    split_two_float)

__all__ = ["DeviceScanData", "ScanQuery", "boundary_candidates",
           "build_scan_data", "exact_patch", "make_query", "scan_mask",
           "split_two_float", "PallasScanData", "build_pallas_data",
           "pallas_scan_count", "pallas_scan_mask"]
