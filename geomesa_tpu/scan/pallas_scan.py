"""Pallas TPU kernel for the fused spatio-temporal scan.

The XLA path (zscan._scan_mask) is already HBM-bound; this Pallas
version exists for the count-only hot query (`pallas_scan_count`),
which accumulates the hit count across row blocks in a (1,1) output
without ever writing the n-row mask back to HBM — the "server-side
aggregate" shape (BaseAggregatingIterator,
accumulo/iterators/: aggregate on the tablet, ship only the partial)
taken all the way down to the kernel level.

Layout: columns are padded and reshaped to (rows, 128) f32/i32 tiles;
the grid walks row blocks of BLOCK_R x 128 (double-buffered HBM->VMEM
streaming is implicit in the BlockSpec pipeline). Query boxes/times are
small VMEM-resident tables; invalid padding slots carry impossible
bounds so the kernel needs no validity masks.

Numerics are identical to zscan: two-float lexicographic compares for
space, (day, ms) int32 pairs for time — so `pallas_scan_mask` is
bit-identical to the XLA kernel and shares its host boundary patch.

On CPU (tests) the kernel runs in interpret mode.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()
from jax.experimental import pallas as pl

from .zscan import MILLIS_PER_DAY, ScanQuery, split_two_float

try:  # TPU-only module; absent on CPU-only installs of pallas
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["PallasScanData", "build_pallas_data", "pallas_scan_mask",
           "pallas_scan_count", "pallas_query_tables", "BLOCK_R"]

LANES = 128
BLOCK_R = 2048  # rows per grid step


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@dataclasses.dataclass
class PallasScanData:
    """(rows, 128)-tiled device columns; pad points carry coords/times
    that no query can match."""
    xhi: jax.Array
    xlo: jax.Array
    yhi: jax.Array
    ylo: jax.Array
    tday: jax.Array
    tms: jax.Array
    n: int
    rows: int


def build_pallas_data(x: np.ndarray, y: np.ndarray,
                      millis: np.ndarray) -> PallasScanData:
    n = len(x)
    rows = -(-n // LANES)
    rows = -(-rows // BLOCK_R) * BLOCK_R
    n_padded = rows * LANES

    def tile(a, fill, dtype):
        out = np.full(n_padded, fill, dtype)
        out[:n] = a
        return jnp.asarray(out.reshape(rows, LANES))

    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    millis = np.asarray(millis, np.int64)
    xhi, xlo = split_two_float(x)
    yhi, ylo = split_two_float(y)
    tday = (millis // MILLIS_PER_DAY).astype(np.int32)
    tms = (millis - tday.astype(np.int64) * MILLIS_PER_DAY).astype(np.int32)
    return PallasScanData(
        tile(xhi, 1e9, np.float32), tile(xlo, 0, np.float32),
        tile(yhi, 1e9, np.float32), tile(ylo, 0, np.float32),
        tile(tday, -1, np.int32), tile(tms, 0, np.int32), n, rows)


def pallas_query_tables(q: ScanQuery) -> tuple[jax.Array, jax.Array]:
    """ScanQuery -> (boxes (K,8) f32, times (B,4) i32) with invalid
    slots folded into impossible bounds (no validity masks needed)."""
    boxes = np.array(q.boxes_np, np.float32, copy=True)
    valid = q.box_valid_np
    boxes[~valid, 0] = np.inf    # xmin_hi = +inf -> never >= it
    boxes[~valid, 2] = -np.inf
    times = np.array(q.times_np, np.int32, copy=True)
    tvalid = q.time_valid_np
    times[~tvalid, 0] = np.iinfo(np.int32).max  # day_lo -> never after
    times[~tvalid, 2] = np.iinfo(np.int32).min
    return jnp.asarray(boxes), jnp.asarray(times)


def _ge2(hi, lo, bhi, blo):
    return (hi > bhi) | ((hi == bhi) & (lo >= blo))


def _le2(hi, lo, bhi, blo):
    return (hi < bhi) | ((hi == bhi) & (lo <= blo))


def _block_mask(xhi, xlo, yhi, ylo, tday, tms, boxes_ref, times_ref,
                k: int, b: int, time_any: bool):
    m = jnp.zeros(xhi.shape, jnp.bool_)
    for i in range(k):  # static unroll: K is the padded pow2 box count
        m |= (_ge2(xhi, xlo, boxes_ref[i, 0], boxes_ref[i, 1])
              & _le2(xhi, xlo, boxes_ref[i, 2], boxes_ref[i, 3])
              & _ge2(yhi, ylo, boxes_ref[i, 4], boxes_ref[i, 5])
              & _le2(yhi, ylo, boxes_ref[i, 6], boxes_ref[i, 7]))
    if not time_any:
        t = jnp.zeros(xhi.shape, jnp.bool_)
        for j in range(b):
            after = ((tday > times_ref[j, 0])
                     | ((tday == times_ref[j, 0]) & (tms >= times_ref[j, 1])))
            before = ((tday < times_ref[j, 2])
                      | ((tday == times_ref[j, 2]) & (tms <= times_ref[j, 3])))
            t |= after & before
        m &= t
    return m


@functools.partial(jax.jit, static_argnames=("k", "b", "time_any", "rows"))
def _mask_call(xhi, xlo, yhi, ylo, tday, tms, boxes, times,
               k: int, b: int, time_any: bool, rows: int):
    def kernel(boxes_ref, times_ref, xh, xl, yh, yl, td, tm, out_ref):
        out_ref[:] = _block_mask(xh[:], xl[:], yh[:], yl[:], td[:], tm[:],
                                 boxes_ref, times_ref, k, b,
                                 time_any).astype(jnp.int8)

    grid = (rows // BLOCK_R,)
    col = pl.BlockSpec((BLOCK_R, LANES), lambda i: (i, 0),
                       memory_space=_VMEM)
    small = pl.BlockSpec(memory_space=_VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
        grid=grid,
        in_specs=[small, small] + [col] * 6,
        out_specs=col,
        interpret=_interpret(),
    )(boxes, times, xhi, xlo, yhi, ylo, tday, tms)


@functools.partial(jax.jit, static_argnames=("k", "b", "time_any", "rows"))
def _count_call(xhi, xlo, yhi, ylo, tday, tms, boxes, times,
                k: int, b: int, time_any: bool, rows: int):
    def kernel(boxes_ref, times_ref, xh, xl, yh, yl, td, tm, out_ref):
        m = _block_mask(xh[:], xl[:], yh[:], yl[:], td[:], tm[:],
                        boxes_ref, times_ref, k, b, time_any)
        partial = jnp.sum(m, dtype=jnp.int32)

        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[0, 0] = 0

        out_ref[0, 0] += partial

    grid = (rows // BLOCK_R,)
    col = pl.BlockSpec((BLOCK_R, LANES), lambda i: (i, 0),
                       memory_space=_VMEM)
    small = pl.BlockSpec(memory_space=_VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        grid=grid,
        in_specs=[small, small] + [col] * 6,
        # every grid step maps to the same output block -> sequential
        # accumulation across steps; SMEM because the store is scalar
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=(pltpu.SMEM if pltpu else None)),
        interpret=_interpret(),
    )(boxes, times, xhi, xlo, yhi, ylo, tday, tms)


def pallas_scan_mask(data: PallasScanData, q: ScanQuery) -> np.ndarray:
    """bool[n] mask, bit-identical to zscan.scan_mask (apply the same
    host boundary patch for exact f64 results)."""
    boxes, times = pallas_query_tables(q)
    out = _mask_call(data.xhi, data.xlo, data.yhi, data.ylo,
                     data.tday, data.tms, boxes, times,
                     int(boxes.shape[0]), int(times.shape[0]),
                     q.time_any, data.rows)
    return np.asarray(out).reshape(-1)[: data.n].astype(bool)


def pallas_scan_count(data: PallasScanData, q: ScanQuery) -> int:
    """Fused scan + count: the mask never touches HBM; one int32 comes
    back. Pad rows can't match (out-of-domain coords), so no
    correction is needed beyond the standard host boundary adjustment
    callers apply for exact f64 counts."""
    boxes, times = pallas_query_tables(q)
    out = _count_call(data.xhi, data.xlo, data.yhi, data.ylo,
                      data.tday, data.tms, boxes, times,
                      int(boxes.shape[0]), int(times.shape[0]),
                      q.time_any, data.rows)
    return int(np.asarray(out)[0, 0])
