"""Device residual-predicate compiler: attribute filters as XLA ops.

The reference evaluates residual CQL row-by-row inside server-side
iterators (/root/reference/geomesa-accumulo/geomesa-accumulo-datastore/
src/main/scala/org/locationtech/geomesa/accumulo/iterators/
KryoLazyFilterTransformIterator.scala:37); for Arrow scans it first
rewrites string predicates against dictionary codes so the hot loop
compares ints (/root/reference/geomesa-arrow/geomesa-arrow-gt/src/main/
scala/org/locationtech/geomesa/arrow/filter/ArrowFilterOptimizer.scala:36).

TPU analog: attribute predicates compile to vector compares over
device-resident columns. TPUs run with 32-bit lanes (no x64), so every
64-bit column gets an exact 32-bit decomposition:

- float64   -> (f32 hi, f32 residual lo); compares are lexicographic on
               (hi, lo) with a host patch of the (rare) rows whose key
               collides with the threshold's key — the same two-float
               exactness scheme as the coordinate scan (scan/zscan.py)
- int64     -> (signed high word v >> 32, unsigned low word
               v & 0xFFFFFFFF); lexicographic compare is exact over the
               full int64 range, no patch needed
- date      -> (day, millis-of-day) pair, as in the z3 time axis
- string    -> integer compares against code-space thresholds from the
               sorted vocab; IN/LIKE run over the vocab on host and map
               through one device gather
- AND/OR/NOT -> logical ops on device masks

Spatial and id predicates are NOT handled here — they are the primary
scan's job (zscan/gscan). ``is_compilable`` reports whether a filter
tree is fully in this subset; callers fall back to the host reference
evaluator (filters/evaluate.py) otherwise, so this layer can never
change semantics — parity is enforced by differential tests.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..features.batch import (BoolColumn, DateColumn, FeatureBatch,
                              NumericColumn, StringColumn)
from ..filters import ast
from ..filters.helper import like_vocab_mask, to_millis
from .zscan import MILLIS_PER_DAY

__all__ = ["is_compilable", "device_mask", "DeviceColumns"]

def _split_f64(v: np.ndarray | float):
    v = np.asarray(v, dtype=np.float64)
    hi = v.astype(np.float32)
    lo = (v - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


def _split_i64(v: np.ndarray | int):
    """Exact full-range int64 split: (signed high word, unsigned low
    word) — lexicographic compare on the pair equals int64 compare."""
    v = np.asarray(v, dtype=np.int64)
    return (v >> 32).astype(np.int32), (v & 0xFFFFFFFF).astype(np.uint32)


def _split_ms(v: np.ndarray | int):
    v = np.asarray(v, dtype=np.int64)
    day = v // MILLIS_PER_DAY
    return (day.astype(np.int32),
            (v - day * MILLIS_PER_DAY).astype(np.int32))


class DeviceColumns:
    """Lazy per-column device uploads for one feature batch.

    Columns move to HBM once, on first use by a device residual, and are
    reused across queries until the batch changes (the owner clears the
    cache on write/delete).
    """

    def __init__(self, batch: FeatureBatch):
        self._batch = batch
        self._cache: dict[str, dict] = {}

    def get(self, name: str) -> dict | None:
        if name in self._cache:
            return self._cache[name]
        col = self._batch.col(name)
        if isinstance(col, NumericColumn):
            if col.values.dtype.kind == "f":
                hi, lo = _split_f64(col.values)
                dev = {"kind": "f64", "hi": jnp.asarray(hi),
                       "lo": jnp.asarray(lo),
                       "valid": jnp.asarray(col.valid),
                       "host": col.values}
            else:
                hi, lo = _split_i64(col.values)
                dev = {"kind": "i64", "hi": jnp.asarray(hi),
                       "lo": jnp.asarray(lo),
                       "valid": jnp.asarray(col.valid)}
        elif isinstance(col, DateColumn):
            day, ms = _split_ms(col.millis)
            dev = {"kind": "date", "hi": jnp.asarray(day),
                   "lo": jnp.asarray(ms), "valid": jnp.asarray(col.valid)}
        elif isinstance(col, BoolColumn):
            dev = {"kind": "bool", "values": jnp.asarray(col.values),
                   "valid": jnp.asarray(col.valid)}
        elif isinstance(col, StringColumn):
            dev = {"kind": "str", "codes": jnp.asarray(col.codes)}
        else:
            return None
        self._cache[name] = dev
        return dev


_COMPILABLE_COLS = (NumericColumn, DateColumn, BoolColumn, StringColumn)


def is_compilable(f: ast.Filter, batch: FeatureBatch) -> bool:
    """True if the whole filter tree evaluates on device."""
    if isinstance(f, (ast.Include, ast.Exclude)):
        return True
    if isinstance(f, (ast.And, ast.Or)):
        return all(is_compilable(c, batch) for c in f.children)
    if isinstance(f, ast.Not):
        return is_compilable(f.child, batch)
    if isinstance(f, (ast.Compare, ast.Between, ast.InList, ast.IsNull,
                      ast.During, ast.Before, ast.After, ast.TEquals)):
        col = batch.columns.get(f.prop)
        return isinstance(col, _COMPILABLE_COLS)
    if isinstance(f, ast.Like):
        return isinstance(batch.columns.get(f.prop), StringColumn)
    return False


def device_mask(f: ast.Filter, batch: FeatureBatch,
                cols: DeviceColumns) -> jnp.ndarray:
    """Evaluate a compilable filter tree; returns a device bool[n] mask.

    Eager jnp ops: every node is a memory-bound vector pass, so there is
    nothing for a jit to fuse that XLA's eager dispatch doesn't already
    pipeline, and skipping jit avoids per-query retraces.
    """
    n = batch.n
    if isinstance(f, ast.Include):
        return jnp.ones(n, dtype=bool)
    if isinstance(f, ast.Exclude):
        return jnp.zeros(n, dtype=bool)
    if isinstance(f, ast.And):
        out = device_mask(f.children[0], batch, cols)
        for c in f.children[1:]:
            out = out & device_mask(c, batch, cols)
        return out
    if isinstance(f, ast.Or):
        out = device_mask(f.children[0], batch, cols)
        for c in f.children[1:]:
            out = out | device_mask(c, batch, cols)
        return out
    if isinstance(f, ast.Not):
        return ~device_mask(f.child, batch, cols)
    if isinstance(f, ast.IsNull):
        col = batch.col(f.prop)
        if isinstance(col, StringColumn):
            return cols.get(f.prop)["codes"] < 0
        return ~cols.get(f.prop)["valid"]
    if isinstance(f, ast.Compare):
        return _compare(f.op, f.prop, f.value, batch, cols)
    if isinstance(f, ast.Between):
        return (_compare(ast.CompareOp.GE, f.prop, f.lo, batch, cols)
                & _compare(ast.CompareOp.LE, f.prop, f.hi, batch, cols))
    if isinstance(f, ast.InList):
        return _in_list(f, batch, cols)
    if isinstance(f, ast.Like):
        return _like(f, batch, cols)
    if isinstance(f, ast.During):
        return (_compare(ast.CompareOp.GT, f.prop, f.start, batch, cols)
                & _compare(ast.CompareOp.LT, f.prop, f.end, batch, cols))
    if isinstance(f, ast.Before):
        return _compare(ast.CompareOp.LT, f.prop, f.time, batch, cols)
    if isinstance(f, ast.After):
        return _compare(ast.CompareOp.GT, f.prop, f.time, batch, cols)
    if isinstance(f, ast.TEquals):
        return _compare(ast.CompareOp.EQ, f.prop, f.time, batch, cols)
    raise TypeError(f"not device-compilable: {type(f).__name__}")


def _int_cmp_const(op: str, v):
    """Rewrite a compare against a possibly-fractional literal into an
    exact integer compare: returns (op', int_value, const) where const
    (True/False) short-circuits the whole predicate. `x < 30.5` becomes
    `x <= 30`; `x = 30.5` is constant False — matching the host
    evaluator's numpy promotion semantics exactly."""
    if not isinstance(v, float) or v.is_integer():
        op2, iv = op, int(v)
    else:
        import math
        if op == ast.CompareOp.EQ:
            return None, None, False
        if op == ast.CompareOp.NE:
            return None, None, True
        if op in (ast.CompareOp.LT, ast.CompareOp.LE):
            op2, iv = ast.CompareOp.LE, math.floor(v)
        else:
            op2, iv = ast.CompareOp.GE, math.floor(v) + 1
    # literals beyond int64 are constants against any int64 column
    if iv > 2**63 - 1 or iv < -(2**63):
        above = iv > 0
        if op2 == ast.CompareOp.EQ:
            return None, None, False
        if op2 == ast.CompareOp.NE:
            return None, None, True
        if op2 in (ast.CompareOp.LT, ast.CompareOp.LE):
            return None, None, above
        return None, None, not above
    return op2, iv, None


def _pair_cmp(hi, lo, vh, vl, op: str, valid):
    """Lexicographic compare of a (hi, lo) pair column against a split
    threshold. Exact for the integer splits; for f64 the EQ band is
    patched by the caller."""
    lt = (hi < vh) | ((hi == vh) & (lo < vl))
    gt = (hi > vh) | ((hi == vh) & (lo > vl))
    if op == ast.CompareOp.LT:
        return lt & valid
    if op == ast.CompareOp.GT:
        return gt & valid
    if op == ast.CompareOp.LE:
        return ~gt & valid
    if op == ast.CompareOp.GE:
        return ~lt & valid
    if op == ast.CompareOp.EQ:
        return ~lt & ~gt & valid
    if op == ast.CompareOp.NE:
        return (lt | gt) & valid
    raise ValueError(op)


def _compare(op: str, prop: str, value, batch: FeatureBatch,
             cols: DeviceColumns) -> jnp.ndarray:
    col = batch.col(prop)
    dev = cols.get(prop)
    kind = dev["kind"]
    if kind == "str":
        return _compare_str(op, str(value), col, dev)
    if kind == "bool":
        # promote to int like numpy: True==1, False==0, fractional
        # literals compare in float space
        v = int(value) if isinstance(value, bool) else value
        vals = dev["values"].astype(jnp.int32)
        res = {
            ast.CompareOp.EQ: lambda: vals == v,
            ast.CompareOp.NE: lambda: vals != v,
            ast.CompareOp.LT: lambda: vals < v,
            ast.CompareOp.GT: lambda: vals > v,
            ast.CompareOp.LE: lambda: vals <= v,
            ast.CompareOp.GE: lambda: vals >= v,
        }[op]()
        return res & dev["valid"]
    if kind in ("date", "i64"):
        if kind == "date" and isinstance(value, str):
            op2, iv, const = op, to_millis(value), None
        else:
            op2, iv, const = _int_cmp_const(op, value)
        if const is not None:
            return dev["valid"] if const else jnp.zeros_like(dev["valid"])
        vh, vl = _split_ms(iv) if kind == "date" else _split_i64(iv)
        return _pair_cmp(dev["hi"], dev["lo"], int(vh), int(vl), op2,
                         dev["valid"])
    # f64: two-float lexicographic compare + host patch of the band
    # where the split key collides with the threshold key (the same
    # boundary-exactness scheme as zscan.exact_patch)
    v = float(value)
    vh, vl = _split_f64(v)
    res = _pair_cmp(dev["hi"], dev["lo"], vh, vl, op, dev["valid"])
    band = (dev["hi"] == vh) & (dev["lo"] == vl) & dev["valid"]
    bidx = np.flatnonzero(np.asarray(band))
    if len(bidx):
        host = dev["host"][bidx]
        ok = {
            ast.CompareOp.EQ: host == v, ast.CompareOp.NE: host != v,
            ast.CompareOp.LT: host < v, ast.CompareOp.GT: host > v,
            ast.CompareOp.LE: host <= v, ast.CompareOp.GE: host >= v,
        }[op]
        res = res.at[jnp.asarray(bidx)].set(jnp.asarray(ok))
    return res


def _compare_str(op: str, v: str, col: StringColumn,
                 dev: dict) -> jnp.ndarray:
    """String compare as integer compares in code space: codes index a
    sorted vocab, so lexicographic thresholds are vocab positions."""
    codes = dev["codes"]
    vocab = col.vocab.astype(str)
    valid = codes >= 0
    if op in (ast.CompareOp.EQ, ast.CompareOp.NE):
        c = col.code_of(v)
        if op == ast.CompareOp.EQ:
            # c == -1 (absent) would compare equal to nulls; mask them
            return (codes == c) & valid
        return (codes != c) & valid
    if op == ast.CompareOp.LT:
        t = int(np.searchsorted(vocab, v, side="left"))
        return (codes < t) & valid
    if op == ast.CompareOp.LE:
        t = int(np.searchsorted(vocab, v, side="right"))
        return (codes < t) & valid
    if op == ast.CompareOp.GT:
        t = int(np.searchsorted(vocab, v, side="right"))
        return codes >= t  # codes >= t implies valid (t >= 0)
    if op == ast.CompareOp.GE:
        t = int(np.searchsorted(vocab, v, side="left"))
        return codes >= t
    raise ValueError(op)


def _vocab_gather(vocab_ok: np.ndarray, codes: jnp.ndarray) -> jnp.ndarray:
    """Map a host vocab-level bool mask through device codes: one gather.
    Nulls (code -1) hit the appended always-False sentinel slot."""
    table = jnp.asarray(np.append(vocab_ok, False))
    idx = jnp.where(codes < 0, len(vocab_ok), codes)
    return jnp.take(table, idx, mode="clip")


def _in_list(f: ast.InList, batch: FeatureBatch,
             cols: DeviceColumns) -> jnp.ndarray:
    col = batch.col(f.prop)
    dev = cols.get(f.prop)
    if isinstance(col, StringColumn):
        vocab_ok = np.isin(col.vocab.astype(str),
                           np.asarray([str(v) for v in f.values], dtype=str))
        return _vocab_gather(vocab_ok, dev["codes"])
    # IN lists are small: OR of equality compares (each exact)
    out = jnp.zeros(batch.n, dtype=bool)
    for v in f.values:
        out = out | _compare(ast.CompareOp.EQ, f.prop, v, batch, cols)
    return out


def _like(f: ast.Like, batch: FeatureBatch,
          cols: DeviceColumns) -> jnp.ndarray:
    col = batch.col(f.prop)
    # LIKE runs over the (small) vocab on host; device sees one gather
    vocab_ok = like_vocab_mask(f.pattern, f.case_sensitive, col.vocab)
    return _vocab_gather(vocab_ok, cols.get(f.prop)["codes"])
