"""Process-wide batcher registry: one fused dispatch stream per store.

The batcher only pays off when callers actually share it. Before this
module, the web tier built a private ``QueryBatcher`` per
``GeoServerApp`` and embedded callers built their own, so two tiers
querying the same store dispatched separately — half the coalescing,
and two jit/plan shape caches warming independently. The registry is
the process-wide rendezvous: every caller that asks for a batcher for
the "same store" gets the SAME instance, so web-tier and embedded
queries coalesce into one fused dispatch and share one warmed plan
cache.

"Same store" is decided by a durable identity, not object identity:

- a store with a durable journal -> ``("durable", journal.root)``, so
  the batcher SURVIVES a store reopen (close + reopen of the same
  directory rebinds the existing batcher to the new store object; the
  plan cache stays valid because its keys carry index_version and the
  padded data cap);
- a ``RemoteDataStore`` -> ``("remote", host, port)``, so every client
  of one server endpoint coalesces;
- anything else -> ``("object", id(store))`` — a pure in-memory store
  has no identity beyond the object, and two of them must never share
  a batcher.

Knob: ``geomesa.batcher.registry.enabled`` (default true) —
``shared_batcher`` returns a private, unregistered batcher when off,
restoring the old per-caller behavior.

Metrics: ``batcher.registry.size`` gauge plus the per-type
``batcher.queue_depth.<type>`` gauges the underlying batchers emit;
``queue_depths()`` aggregates every registered batcher's pending
queues for the ``/rest/health`` detail.
"""

from __future__ import annotations

import threading

from ..metrics import metrics
from ..utils.properties import SystemProperty
from .batcher import QueryBatcher

__all__ = ["BatcherRegistry", "batcher_registry", "shared_batcher",
           "store_identity", "BATCHER_REGISTRY_ENABLED"]

BATCHER_REGISTRY_ENABLED = SystemProperty(
    "geomesa.batcher.registry.enabled", "true")


def store_identity(store) -> tuple:
    """The durable identity deciding which callers share a batcher."""
    journal = getattr(store, "journal", None)
    root = getattr(journal, "root", None)
    if root:
        return ("durable", str(root))
    host = getattr(store, "host", None)
    port = getattr(store, "port", None)
    if host is not None and port is not None:
        return ("remote", str(host), int(port))
    return ("object", id(store))


class BatcherRegistry:
    """Identity-keyed ``QueryBatcher`` singletons.

    ``get(store)`` returns the one batcher for the store's identity,
    creating it on first use and REBINDING it to the new store object
    when the same durable identity is reopened — in-flight leaders
    drain against the old object; new admissions dispatch against the
    new one. Thread-safe; strong references (a handful of stores per
    process, each batcher is a few dicts)."""

    def __init__(self, registry=metrics):
        self._registry = registry
        self._lock = threading.Lock()
        self._batchers: dict[tuple, QueryBatcher] = {}

    def get(self, store, **batcher_kwargs) -> QueryBatcher:
        key = store_identity(store)
        with self._lock:
            b = self._batchers.get(key)
            if b is None:
                b = self._batchers[key] = QueryBatcher(
                    store, registry=self._registry, **batcher_kwargs)
            elif b.store is not store:
                # same durable identity, reopened store object: keep
                # the warmed plan cache and cost EWMAs, serve from the
                # live store
                b.store = store
            self._registry.gauge("batcher.registry.size",
                                 len(self._batchers))
            return b

    def queue_depths(self) -> dict[str, int]:
        """Pending-queue depth per type across every registered
        batcher (summed when two stores share a type name)."""
        with self._lock:
            batchers = list(self._batchers.values())
        depths: dict[str, int] = {}
        for b in batchers:
            for k, v in b.queue_depths().items():
                depths[k] = depths.get(k, 0) + v
        return depths

    def stats(self) -> dict:
        with self._lock:
            batchers = list(self._batchers.items())
        return {"size": len(batchers),
                "stores": [list(map(str, k)) for k, _ in batchers]}

    def clear(self):
        """Drop every registered batcher (tests; also the only way to
        release a store an embedded caller is done with)."""
        with self._lock:
            self._batchers.clear()


batcher_registry = BatcherRegistry()


def shared_batcher(store, **batcher_kwargs) -> QueryBatcher:
    """The process-wide batcher for ``store`` — or a private one when
    ``geomesa.batcher.registry.enabled`` is off."""
    enabled = str(BATCHER_REGISTRY_ENABLED.get()).lower() in (
        "true", "1", "yes")
    if not enabled:
        return QueryBatcher(store, **batcher_kwargs)
    return batcher_registry.get(store, **batcher_kwargs)
