"""Fused spatio-temporal scan kernel: the TPU analog of the reference's
server-side iterator stack (Z3Iterator + KryoLazyFilterTransformIterator,
accumulo/iterators/Z3Iterator.scala:47-60 + index/filters/Z3Filter.scala).

Instead of per-row z-key decode + int compares on tablet servers, the
whole batch is filtered in one XLA program:

- coordinates live on device as *round-down two-float* pairs
  (hi = float32 rounded toward -inf, lo = float32(x - hi) in [0, ulp)),
  so bbox comparisons against query bounds split the same way are exact
  in float64 terms up to a ~1e-12 deg residual; points sharing a hi cell
  with a query bound are flagged for host float64 recheck, making the
  final mask EXACTLY the double-precision result;
- times live as (days-since-epoch int32, millis-in-day int32) pairs —
  exact epoch millis without 64-bit device ints;
- query boxes and time intervals are padded to fixed shapes (next power
  of two) so jit traces are reused across queries.

No f64, no i64, no data-dependent shapes inside jit.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()

__all__ = ["BatchedScanQuery", "DeviceScanData", "ScanQuery",
           "batch_hit_rows", "build_scan_data", "extend_scan_data",
           "make_query", "next_pow2", "patch_hit_rows", "scan_mask",
           "scan_mask_at", "scan_mask_batch", "scan_mask_batch_at",
           "split_two_float", "stack_points", "stack_queries",
           "MILLIS_PER_DAY"]

MILLIS_PER_DAY = 86_400_000


def split_two_float(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """f64 -> (hi, lo) f32 pair with hi = round-toward-neg-inf(x) and
    lo = f32(x - hi) >= 0. Lexicographic (hi, lo) compare then mirrors
    the f64 order to within f32-rounding of the residual."""
    x = np.asarray(x, dtype=np.float64)
    hi = x.astype(np.float32)
    over = hi.astype(np.float64) > x
    hi = np.where(over, np.nextafter(hi, np.float32(-np.inf)), hi)
    lo = (x - hi.astype(np.float64)).astype(np.float32)
    return hi, lo


@dataclasses.dataclass
class DeviceScanData:
    """Device-resident columns for the spatio-temporal scan.

    Arrays may be longer than ``n`` (capacity padding): the write path
    allocates power-of-two capacity and appends in place with
    dynamic_update_slice, so incremental writes keep STATIC shapes —
    no per-flush XLA recompiles of the scan or the append. Kernels mask
    rows >= n."""
    xhi: jax.Array
    xlo: jax.Array
    yhi: jax.Array
    ylo: jax.Array
    tday: jax.Array
    tms: jax.Array
    n: int

    @property
    def cap(self) -> int:
        return int(self.xhi.shape[0])

    @property
    def nbytes(self) -> int:
        return self.cap * (4 * 4 + 2 * 4)


def _split_time(millis) -> tuple[np.ndarray, np.ndarray]:
    millis = np.asarray(millis, dtype=np.int64)
    tday = (millis // MILLIS_PER_DAY).astype(np.int32)
    tms = (millis - tday.astype(np.int64) * MILLIS_PER_DAY).astype(np.int32)
    return tday, tms


def build_scan_data(x: np.ndarray, y: np.ndarray, millis: np.ndarray,
                    device=None, cap: int | None = None,
                    xy_split=None) -> DeviceScanData:
    """Host f64 coords + epoch millis -> device arrays, zero-padded to
    ``cap`` rows when given (capacity headroom for in-place appends).
    ``xy_split`` passes precomputed (xhi, xlo, yhi, ylo) pairs so a
    caller that also needs host copies splits once (and never fetches
    them back off the device — a 2x column transfer at 100M rows)."""
    if xy_split is not None:
        xhi, xlo, yhi, ylo = xy_split
    else:
        xhi, xlo = split_two_float(x)
        yhi, ylo = split_two_float(y)
    tday, tms = _split_time(millis)
    n = len(xhi)
    if cap is not None and cap > n:
        def padded(a):
            return np.pad(a, (0, cap - n))
        xhi, xlo, yhi, ylo, tday, tms = (
            padded(a) for a in (xhi, xlo, yhi, ylo, tday, tms))
    put = functools.partial(jax.device_put, device=device)
    return DeviceScanData(put(xhi), put(xlo), put(yhi), put(ylo),
                          put(tday), put(tms), n)


@jax.jit
def _update1(a, u, i):
    return jax.lax.dynamic_update_slice(a, u, (i,))


def extend_scan_data(data: DeviceScanData, x, y, millis,
                     xy_split=None) -> DeviceScanData | None:
    """Append rows in place within existing capacity, or None when the
    capacity is exhausted (caller rebuilds with fresh headroom). The
    delta is padded to a power of two so the device program is reused
    across write bursts of any size. ``xy_split`` passes precomputed
    (xhi, xlo, yhi, ylo) two-float pairs to avoid re-splitting."""
    d = len(x)
    if d == 0:
        return data
    k = next_pow2(d)
    if data.n + k > data.cap:
        return None
    if xy_split is None:
        xhi, xlo = split_two_float(np.asarray(x, dtype=np.float64))
        yhi, ylo = split_two_float(np.asarray(y, dtype=np.float64))
    else:
        xhi, xlo, yhi, ylo = xy_split
    tday, tms = _split_time(millis)

    def padded(a):
        return jnp.asarray(np.pad(a, (0, k - d)))
    i = data.n  # python int traces as a dynamic scalar: no retrace
    return DeviceScanData(
        _update1(data.xhi, padded(xhi), i), _update1(data.xlo, padded(xlo), i),
        _update1(data.yhi, padded(yhi), i), _update1(data.ylo, padded(ylo), i),
        _update1(data.tday, padded(tday), i), _update1(data.tms, padded(tms), i),
        data.n + d)


class ScanQuery:
    """Padded query: K spatial boxes + B time intervals.

    boxes: (K, 8) f32 [xmin_hi, xmin_lo, xmax_hi, xmax_lo,
                       ymin_hi, ymin_lo, ymax_hi, ymax_lo]
    box_valid: (K,) bool
    times: (B, 4) i32 [day_lo, ms_lo, day_hi, ms_hi], inclusive bounds
    time_valid: (B,) bool; time_any: no time constraint at all

    The device arrays upload LAZILY on first access: selective queries
    resolved entirely on host (the index fast path) never touch the
    device, so building a ScanQuery must not cost device_put round
    trips. ``host_*`` fields are the exact f64/i64 originals for
    boundary rechecks and host evaluation.
    """

    def __init__(self, boxes: np.ndarray, box_valid: np.ndarray,
                 times: np.ndarray, time_valid: np.ndarray,
                 time_any: bool, n_boxes: int, host_boxes: np.ndarray,
                 host_box_his: np.ndarray, host_intervals: np.ndarray):
        self._np = (np.asarray(boxes), np.asarray(box_valid),
                    np.asarray(times), np.asarray(time_valid))
        self._dev = None
        self.time_any = time_any
        self.n_boxes = n_boxes
        self.host_boxes = host_boxes
        self.host_box_his = host_box_his
        self.host_intervals = host_intervals

    def _device(self):
        if self._dev is None:
            self._dev = tuple(jnp.asarray(a) for a in self._np)
        return self._dev

    @property
    def boxes(self) -> jax.Array:
        return self._device()[0]

    @property
    def box_valid(self) -> jax.Array:
        return self._device()[1]

    @property
    def times(self) -> jax.Array:
        return self._device()[2]

    @property
    def time_valid(self) -> jax.Array:
        return self._device()[3]

    @property
    def boxes_np(self) -> np.ndarray:
        """Padded boxes as host numpy (no device round trip)."""
        return self._np[0]

    @property
    def box_valid_np(self) -> np.ndarray:
        return self._np[1]

    @property
    def times_np(self) -> np.ndarray:
        return self._np[2]

    @property
    def time_valid_np(self) -> np.ndarray:
        return self._np[3]


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def make_query(boxes_f64, intervals_ms) -> ScanQuery:
    """Build a padded ScanQuery.

    boxes_f64: list of (xmin, ymin, xmax, ymax) float64 tuples.
    intervals_ms: list of (lo_millis, hi_millis) INCLUSIVE int bounds,
      or None/[] for no time constraint.
    """
    boxes_f64 = list(boxes_f64)
    k = max(next_pow2(max(len(boxes_f64), 1)), 1)
    boxes = np.zeros((k, 8), dtype=np.float32)
    valid = np.zeros(k, dtype=bool)
    host_boxes = np.zeros((len(boxes_f64), 4), dtype=np.float64)
    host_his = np.zeros((len(boxes_f64), 4), dtype=np.float32)
    for i, (xmin, ymin, xmax, ymax) in enumerate(boxes_f64):
        xmin_hi, xmin_lo = split_two_float(np.float64(xmin))
        xmax_hi, xmax_lo = split_two_float(np.float64(xmax))
        ymin_hi, ymin_lo = split_two_float(np.float64(ymin))
        ymax_hi, ymax_lo = split_two_float(np.float64(ymax))
        boxes[i] = (xmin_hi, xmin_lo, xmax_hi, xmax_lo,
                    ymin_hi, ymin_lo, ymax_hi, ymax_lo)
        host_boxes[i] = (xmin, ymin, xmax, ymax)
        host_his[i] = (xmin_hi, xmax_hi, ymin_hi, ymax_hi)
        valid[i] = True

    intervals_ms = list(intervals_ms or [])
    time_any = not intervals_ms
    b = max(next_pow2(max(len(intervals_ms), 1)), 1)
    times = np.zeros((b, 4), dtype=np.int32)
    tvalid = np.zeros(b, dtype=bool)
    for i, (lo, hi) in enumerate(intervals_ms):
        lo, hi = int(lo), int(hi)
        times[i] = (lo // MILLIS_PER_DAY, lo % MILLIS_PER_DAY,
                    hi // MILLIS_PER_DAY, hi % MILLIS_PER_DAY)
        tvalid[i] = True

    host_iv = np.asarray(intervals_ms, dtype=np.int64).reshape(-1, 2)
    return ScanQuery(boxes, valid, times, tvalid, time_any,
                     len(boxes_f64), host_boxes, host_his, host_iv)


# -- the kernel ------------------------------------------------------------

def _ge_two_float(hi, lo, b_hi, b_lo):
    """(hi, lo) >= (b_hi, b_lo) lexicographically."""
    return (hi > b_hi) | ((hi == b_hi) & (lo >= b_lo))


def _le_two_float(hi, lo, b_hi, b_lo):
    return (hi < b_hi) | ((hi == b_hi) & (lo <= b_lo))


def _mask_body(xhi, xlo, yhi, ylo, tday, tms,
               boxes, box_valid, times, time_valid, time_any: bool,
               n_valid=None):
    # spatial: any valid box contains the point — (n, K) broadcast
    bx = boxes[None, :, :]                      # (1, K, 8)
    sx = (_ge_two_float(xhi[:, None], xlo[:, None], bx[..., 0], bx[..., 1])
          & _le_two_float(xhi[:, None], xlo[:, None], bx[..., 2], bx[..., 3])
          & _ge_two_float(yhi[:, None], ylo[:, None], bx[..., 4], bx[..., 5])
          & _le_two_float(yhi[:, None], ylo[:, None], bx[..., 6], bx[..., 7]))
    spatial = jnp.any(sx & box_valid[None, :], axis=1)
    # capacity-padded rows (>= n_valid) are never matches
    if n_valid is not None:
        spatial = spatial & (jnp.arange(xhi.shape[0]) < n_valid)
    if time_any:
        return spatial
    tx = times[None, :, :]                      # (1, B, 4)
    after_lo = ((tday[:, None] > tx[..., 0])
                | ((tday[:, None] == tx[..., 0]) & (tms[:, None] >= tx[..., 1])))
    before_hi = ((tday[:, None] < tx[..., 2])
                 | ((tday[:, None] == tx[..., 2]) & (tms[:, None] <= tx[..., 3])))
    temporal = jnp.any(after_lo & before_hi & time_valid[None, :], axis=1)
    return spatial & temporal


_scan_mask = functools.partial(jax.jit, static_argnames=("time_any",))(
    _mask_body)


@functools.partial(jax.jit, static_argnames=("time_any",))
def _gather_scan_mask(xhi, xlo, yhi, ylo, tday, tms, idx,
                      boxes, box_valid, times, time_valid, time_any: bool):
    """Scan only the gathered candidate rows (index-pruned path)."""
    def g(a):
        return jnp.take(a, idx, mode="clip")
    return _mask_body(g(xhi), g(xlo), g(yhi), g(ylo), g(tday), g(tms),
                      boxes, box_valid, times, time_valid, time_any)


def scan_mask_at(data: DeviceScanData, q: ScanQuery,
                 rows: np.ndarray) -> np.ndarray:
    """Run the fused scan over just ``rows`` (original-order indices from
    the z-key index); returns a host bool[len(rows)] mask.

    The row list is padded to the next power of two so jit traces are
    reused across queries (pad rows gather row 0 and are sliced off).
    """
    m = len(rows)
    if m == 0:
        return np.zeros(0, dtype=bool)
    k = next_pow2(m)
    # pad in the rows' own dtype (row counts are capped at int32 range
    # by ZKeyIndex._perm_dtype; device gathers are 32-bit)
    idx = np.zeros(k, dtype=rows.dtype)
    idx[:m] = rows
    out = _gather_scan_mask(data.xhi, data.xlo, data.yhi, data.ylo,
                            data.tday, data.tms, jnp.asarray(idx),
                            q.boxes, q.box_valid, q.times, q.time_valid,
                            q.time_any)
    return np.asarray(out)[:m]


def scan_mask(data: DeviceScanData, q: ScanQuery) -> jax.Array:
    """Run the fused scan; returns a device bool[cap] mask whose
    capacity-padding tail (rows >= data.n) is always False."""
    n_valid = None if data.cap == data.n else data.n
    return _scan_mask(data.xhi, data.xlo, data.yhi, data.ylo,
                      data.tday, data.tms,
                      q.boxes, q.box_valid, q.times, q.time_valid,
                      q.time_any, n_valid)


def boundary_candidates(data_xhi: np.ndarray, data_yhi: np.ndarray,
                        q: ScanQuery) -> np.ndarray:
    """Host-side: indices of points whose hi-cell equals any query bound's
    hi-cell — the only points where the two-float compare can differ from
    exact f64. Typically a vanishing fraction of n (~n * 2^-23)."""
    mask = np.zeros(len(data_xhi), dtype=bool)
    for i in range(q.n_boxes):
        his = q.host_box_his[i]
        mask |= (data_xhi == his[0]) | (data_xhi == his[1])
        mask |= (data_yhi == his[2]) | (data_yhi == his[3])
    return np.flatnonzero(mask)


def _exact_hits(cand_idx: np.ndarray, x: np.ndarray, y: np.ndarray,
                millis: np.ndarray, q: ScanQuery) -> np.ndarray:
    """Exact f64/i64 verdict for each candidate row index."""
    cx, cy = x[cand_idx], y[cand_idx]
    ok = np.zeros(len(cand_idx), dtype=bool)
    for i in range(q.n_boxes):
        xmin, ymin, xmax, ymax = q.host_boxes[i]
        ok |= (cx >= xmin) & (cx <= xmax) & (cy >= ymin) & (cy <= ymax)
    if not q.time_any:
        cm = millis[cand_idx]
        t_ok = np.zeros(len(cand_idx), dtype=bool)
        for lo, hi in q.host_intervals:
            t_ok |= (cm >= lo) & (cm <= hi)
        ok &= t_ok
    return ok


def exact_patch(mask: np.ndarray, cand_idx: np.ndarray,
                x: np.ndarray, y: np.ndarray, millis: np.ndarray,
                q: ScanQuery) -> np.ndarray:
    """Fully re-evaluate boundary candidates in exact f64/i64 semantics
    and patch their mask bits, making the overall result exact."""
    if len(cand_idx) == 0:
        return mask
    ok = _exact_hits(cand_idx, x, y, millis, q)
    mask = mask.copy()
    mask[cand_idx] = ok
    return mask


# -- micro-batched multi-query scan ---------------------------------------
#
# N concurrent queries become ONE device launch: each query's padded
# boxes/intervals are stacked along a leading pow2 batch dim and the
# scalar-query kernel is vmapped over it. Per-query `time_any` is a
# static argument and may differ within a batch, so time-unconstrained
# queries get a CATCH-ALL interval (all representable days) and the
# batched kernel always runs the temporal compare.

_CATCH_ALL_INTERVAL = (-(2 ** 30), 0, 2 ** 30, MILLIS_PER_DAY)


class BatchedScanQuery:
    """Qp stacked queries padded to common box/interval counts.

    boxes: (Qp, K, 8) f32; box_valid: (Qp, K) bool
    times: (Qp, B, 4) i32; time_valid: (Qp, B) bool

    ``queries`` keeps the original ScanQuery objects (exact f64 bounds
    for per-query boundary patches); Qp - n_queries tail rows are pure
    padding with box_valid all False (they match nothing).
    """

    def __init__(self, boxes: np.ndarray, box_valid: np.ndarray,
                 times: np.ndarray, time_valid: np.ndarray,
                 queries: list[ScanQuery]):
        self._np = (np.asarray(boxes), np.asarray(box_valid),
                    np.asarray(times), np.asarray(time_valid))
        self._dev = None
        self.queries = queries

    @property
    def n_queries(self) -> int:
        return len(self.queries)

    @property
    def padded_queries(self) -> int:
        return int(self._np[0].shape[0])

    @property
    def shape_key(self) -> tuple[int, int, int]:
        """(Qp, K, B) — the jit shape class of this batch."""
        return (int(self._np[0].shape[0]), int(self._np[0].shape[1]),
                int(self._np[2].shape[1]))

    def _device(self):
        if self._dev is None:
            self._dev = tuple(jnp.asarray(a) for a in self._np)
        return self._dev

    @property
    def boxes(self) -> jax.Array:
        return self._device()[0]

    @property
    def box_valid(self) -> jax.Array:
        return self._device()[1]

    @property
    def times(self) -> jax.Array:
        return self._device()[2]

    @property
    def time_valid(self) -> jax.Array:
        return self._device()[3]


def stack_queries(queries: list[ScanQuery],
                  min_batch: int = 1) -> BatchedScanQuery:
    """Stack padded ScanQueries into one BatchedScanQuery.

    Box/interval dims are padded to the max across the batch (already
    pow2 per query, so the max is pow2 too); the batch dim is padded to
    a power of two (at least ``min_batch``) so jit traces are reused
    across occupancy levels."""
    if not queries:
        raise ValueError("stack_queries needs at least one query")
    k = max(q.boxes_np.shape[0] for q in queries)
    b = max(q.times_np.shape[0] for q in queries)
    qp = max(next_pow2(len(queries)), min_batch)
    boxes = np.zeros((qp, k, 8), dtype=np.float32)
    box_valid = np.zeros((qp, k), dtype=bool)
    times = np.zeros((qp, b, 4), dtype=np.int32)
    time_valid = np.zeros((qp, b), dtype=bool)
    for i, q in enumerate(queries):
        bk = q.boxes_np.shape[0]
        boxes[i, :bk] = q.boxes_np
        box_valid[i, :bk] = q.box_valid_np
        if q.time_any:
            times[i, 0] = _CATCH_ALL_INTERVAL
            time_valid[i, 0] = True
        else:
            tb = q.times_np.shape[0]
            times[i, :tb] = q.times_np
            time_valid[i, :tb] = q.time_valid_np
    return BatchedScanQuery(boxes, box_valid, times, time_valid,
                            list(queries))


def stack_points(qx, qy, min_batch: int = 1
                 ) -> tuple[np.ndarray, np.ndarray, int]:
    """Stack query POINTS into one pow2-padded f32 batch — the
    point-query analog of ``stack_queries`` (multi-query KNN, batched
    proximity). Returns ``(qx_pad, qy_pad, nq)`` where the batch dim is
    the next power of two >= max(nq, min_batch); padding rows repeat the
    first query so they are valid coordinates (callers slice results
    back to ``nq`` — a repeated query costs nothing extra in a fused
    kernel, while garbage coordinates could produce NaN/inf work)."""
    qx = np.atleast_1d(np.asarray(qx, np.float64))
    qy = np.atleast_1d(np.asarray(qy, np.float64))
    if qx.shape != qy.shape or qx.ndim != 1:
        raise ValueError("stack_points needs matching 1-d coordinates")
    nq = len(qx)
    if nq == 0:
        raise ValueError("stack_points needs at least one query point")
    qp = max(next_pow2(nq), max(min_batch, 1))
    qxp = np.full(qp, qx[0], dtype=np.float32)
    qyp = np.full(qp, qy[0], dtype=np.float32)
    qxp[:nq] = qx.astype(np.float32)
    qyp[:nq] = qy.astype(np.float32)
    return qxp, qyp, nq


def _cand_body(xhi, yhi, boxes, box_valid, n_valid=None):
    """Boundary-candidate mask: rows whose hi-cell equals any valid
    box bound's hi-cell (the only rows where the two-float compare can
    disagree with exact f64). Device analog of boundary_candidates."""
    bx = boxes[None, :, :]
    c = ((xhi[:, None] == bx[..., 0]) | (xhi[:, None] == bx[..., 2])
         | (yhi[:, None] == bx[..., 4]) | (yhi[:, None] == bx[..., 6]))
    cand = jnp.any(c & box_valid[None, :], axis=1)
    if n_valid is not None:
        cand = cand & (jnp.arange(xhi.shape[0]) < n_valid)
    return cand


@jax.jit
def _batch_mask(xhi, xlo, yhi, ylo, tday, tms,
                boxes, box_valid, times, time_valid, n_valid):
    def one(bx, bv, tx, tv):
        return _mask_body(xhi, xlo, yhi, ylo, tday, tms,
                          bx, bv, tx, tv, time_any=False, n_valid=n_valid)
    return jax.vmap(one)(boxes, box_valid, times, time_valid)


@jax.jit
def _batch_mask_cand(xhi, xlo, yhi, ylo, tday, tms,
                     boxes, box_valid, times, time_valid, n_valid):
    def one(bx, bv, tx, tv):
        return (_mask_body(xhi, xlo, yhi, ylo, tday, tms,
                           bx, bv, tx, tv, time_any=False, n_valid=n_valid),
                _cand_body(xhi, yhi, bx, bv, n_valid))
    return jax.vmap(one)(boxes, box_valid, times, time_valid)


@jax.jit
def _batch_gather_mask(xhi, xlo, yhi, ylo, tday, tms, idx,
                       boxes, box_valid, times, time_valid):
    def g(a):
        return jnp.take(a, idx, mode="clip")

    def one(bx, bv, tx, tv):
        return _mask_body(g(xhi), g(xlo), g(yhi), g(ylo), g(tday), g(tms),
                          bx, bv, tx, tv, time_any=False, n_valid=None)
    return jax.vmap(one)(boxes, box_valid, times, time_valid)


@jax.jit
def _batch_count(mask):
    return jnp.sum(mask, axis=1, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("size",))
def _batch_nonzero(mask, size: int):
    def one(row):
        return jnp.nonzero(row, size=size, fill_value=row.shape[0])[0]
    return jax.vmap(one)(mask)


def scan_mask_batch(data: DeviceScanData,
                    bq: BatchedScanQuery) -> jax.Array:
    """One fused launch over all queries: device bool[Qp, cap] mask.
    ``n_valid`` is traced (not static) so appends within a capacity
    class never recompile."""
    return _batch_mask(data.xhi, data.xlo, data.yhi, data.ylo,
                       data.tday, data.tms,
                       bq.boxes, bq.box_valid, bq.times, bq.time_valid,
                       jnp.int32(data.n))


def scan_mask_batch_at(data: DeviceScanData, bq: BatchedScanQuery,
                       rows: np.ndarray) -> np.ndarray:
    """Fused batch scan over one SHARED candidate row set (the union of
    the batch's index candidates); host bool[Qp, len(rows)]."""
    m = len(rows)
    if m == 0:
        return np.zeros((bq.padded_queries, 0), dtype=bool)
    k = next_pow2(m)
    idx = np.zeros(k, dtype=rows.dtype)
    idx[:m] = rows
    out = _batch_gather_mask(
        data.xhi, data.xlo, data.yhi, data.ylo, data.tday, data.tms,
        jnp.asarray(idx), bq.boxes, bq.box_valid, bq.times, bq.time_valid)
    return np.asarray(out)[:, :m]


def batch_hit_rows(data: DeviceScanData, bq: BatchedScanQuery
                   ) -> tuple[list[np.ndarray], list[np.ndarray]]:
    """Fused scan + on-device compaction: per-query (sorted hit rows,
    boundary-candidate rows).

    Transfers O(Qp * max_hits) instead of O(Qp * cap) — counts are
    fetched first (a 2*Qp-int sync), then hits/candidates are compacted
    to the next pow2 of the largest per-query count so the compaction
    kernel's trace is reused across batches in the same hit-size class.
    Boundary candidates are found ON DEVICE inside the same launch, so
    the per-query O(n) host candidate scan of the scalar path is
    amortized away entirely."""
    mask, cand = _batch_mask_cand(
        data.xhi, data.xlo, data.yhi, data.ylo, data.tday, data.tms,
        bq.boxes, bq.box_valid, bq.times, bq.time_valid, jnp.int32(data.n))
    counts = np.asarray(_batch_count(mask))
    ccounts = np.asarray(_batch_count(cand))
    size = next_pow2(max(int(counts.max()), 1))
    csize = next_pow2(max(int(ccounts.max()), 1))
    idx = np.asarray(_batch_nonzero(mask, size))
    cidx = np.asarray(_batch_nonzero(cand, csize))
    hits = [idx[i, :counts[i]] for i in range(bq.n_queries)]
    cands = [cidx[i, :ccounts[i]] for i in range(bq.n_queries)]
    return hits, cands


def patch_hit_rows(rows: np.ndarray, q: ScanQuery,
                   x: np.ndarray, y: np.ndarray, millis: np.ndarray,
                   cand: np.ndarray) -> np.ndarray:
    """Boundary patch in row-index space: re-evaluate the (vanishing)
    set of hi-cell boundary candidates ``cand`` in exact f64/i64 and
    add/remove them from ``rows``, making the hit set exactly the f64
    result."""
    if len(cand) == 0:
        return rows
    ok = _exact_hits(cand, x, y, millis, q)
    add = cand[ok]
    drop = cand[~ok]
    if len(drop):
        rows = np.setdiff1d(rows, drop, assume_unique=False)
    if len(add):
        rows = np.union1d(rows, add)
    return rows
