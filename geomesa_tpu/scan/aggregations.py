"""Scan-time aggregation kernels: density, BIN encoding, sampling.

Device/vectorized analogs of the reference's aggregating iterators
(index/iterators/: DensityScan.scala:30, BinAggregatingScan.scala:22,
SamplingIterator.scala:22). Each consumes a scan mask + columns and
produces the compact aggregate the reference would stream back from
tablet servers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()

__all__ = ["density_grid", "encode_bin_records", "encode_bin_batch",
           "decode_bin_records", "merge_sorted_bin_chunks",
           "sample_mask"]


@functools.partial(jax.jit, static_argnames=("width", "height"))
def _density_kernel(x, y, w, mask, xmin, ymin, sx, sy, width: int, height: int):
    col = jnp.clip(((x - xmin) * sx).astype(jnp.int32), 0, width - 1)
    row = jnp.clip(((y - ymin) * sy).astype(jnp.int32), 0, height - 1)
    flat = row * width + col
    grid = jnp.zeros((height * width,), dtype=jnp.float32)
    return grid.at[flat].add(jnp.where(mask, w, 0.0)).reshape(height, width)


def density_grid(x: np.ndarray, y: np.ndarray, mask: np.ndarray,
                 bbox: tuple[float, float, float, float],
                 width: int, height: int,
                 weights: np.ndarray | None = None) -> np.ndarray:
    """Weighted 2-D histogram over the pixel grid (DensityScan analog:
    GridSnap pixel binning + weight accumulation)."""
    xmin, ymin, xmax, ymax = (float(v) for v in bbox)
    sx = width / (xmax - xmin) if xmax > xmin else 0.0
    sy = height / (ymax - ymin) if ymax > ymin else 0.0
    w = (np.ones(len(x), dtype=np.float32) if weights is None
         else np.asarray(weights, dtype=np.float32))
    out = _density_kernel(
        jnp.asarray(np.asarray(x, np.float32)),
        jnp.asarray(np.asarray(y, np.float32)),
        jnp.asarray(w), jnp.asarray(np.asarray(mask, bool)),
        np.float32(xmin), np.float32(ymin),
        np.float32(sx), np.float32(sy), width, height)
    return np.asarray(out)


def _id_hashes(ids: np.ndarray) -> np.ndarray:
    """Track-id hash codes, matching java String.hashCode semantics
    (BinaryOutputEncoder uses id.hashCode, utils/bin/BinaryOutputEncoder.scala:58)."""
    out = np.zeros(len(ids), dtype=np.int64)
    for i, s in enumerate(ids):
        h = 0
        for ch in str(s):
            h = (31 * h + ord(ch)) & 0xFFFFFFFF
        out[i] = h if h < 0x80000000 else h - 0x100000000
    return out.astype(np.int32)


def encode_bin_records(ids: np.ndarray, x: np.ndarray, y: np.ndarray,
                       millis: np.ndarray,
                       labels: np.ndarray | None = None,
                       track_values: np.ndarray | None = None,
                       sort: bool = False) -> bytes:
    """Encode the 16-byte (or 24-byte labeled) BIN format:
    [track_hash:i32][seconds:i32][lat:f32][lon:f32]([label:8bytes]) —
    little-endian, matching BinaryOutputEncoder's record layout.

    track_values overrides the per-record track id source (the
    BIN_TRACK hint attribute); default is the feature id.
    """
    n = len(ids)
    track = _id_hashes(track_values if track_values is not None else ids)
    secs = (np.asarray(millis, np.int64) // 1000).astype(np.int32)
    if sort:
        order = np.argsort(secs, kind="stable")
        track, secs = track[order], secs[order]
        x, y = np.asarray(x)[order], np.asarray(y)[order]
        if labels is not None:
            labels = np.asarray(labels)[order]
    if labels is None:
        rec = np.empty(n, dtype=[("track", "<i4"), ("secs", "<i4"),
                                 ("lat", "<f4"), ("lon", "<f4")])
    else:
        rec = np.empty(n, dtype=[("track", "<i4"), ("secs", "<i4"),
                                 ("lat", "<f4"), ("lon", "<f4"),
                                 ("label", "S8")])
        rec["label"] = np.asarray([str(l)[:8].encode() for l in labels])
    rec["track"] = track
    rec["secs"] = secs
    rec["lat"] = np.asarray(y, np.float32)
    rec["lon"] = np.asarray(x, np.float32)
    return rec.tobytes()


def encode_bin_batch(sft, ids: np.ndarray, batch,
                     track: str | None = None,
                     label: str | None = None,
                     sort: bool = False) -> bytes:
    """BIN-encode one FeatureBatch: the shared column-extraction front
    half of every backend's ``bin_query`` (centroids, dtg millis,
    track/label attribute values) over ``encode_bin_records``."""
    if batch is None or not batch.n:
        return b""
    col = batch.col(sft.geom_field)
    x = getattr(col, "x", None)
    if x is not None:
        x, y = col.x, col.y
    else:
        bounds = col.bounds
        x = (bounds[:, 0] + bounds[:, 2]) / 2
        y = (bounds[:, 1] + bounds[:, 3]) / 2
    dtg = sft.dtg_field
    millis = (batch.col(dtg).millis if dtg
              else np.zeros(batch.n, dtype=np.int64))
    track_vals = None
    if track is not None and track != "id":
        tc = batch.col(track)
        track_vals = np.array([tc.value(i) for i in range(batch.n)],
                              dtype=object)
    labels = None
    if label is not None:
        lc = batch.col(label)
        labels = np.array([lc.value(i) for i in range(batch.n)],
                          dtype=object)
    return encode_bin_records(np.asarray(ids), x, y, millis,
                              labels=labels, track_values=track_vals,
                              sort=sort)


def merge_sorted_bin_chunks(chunks: list[bytes],
                            labeled: bool = False) -> bytes:
    """Merge per-shard time-sorted BIN chunks into one sorted stream —
    the BinSorter client reduce (index/utils/bin/BinSorter.scala:16
    merge-sorts the per-tablet chunks by the seconds field). Columnar
    twist: a single stable argsort over the concatenated seconds column
    replaces the heap of chunk cursors (k-way merge degenerates to a
    sort because chunks arrive fully materialized here)."""
    if not chunks:
        return b""
    recs = [decode_bin_records(c, labeled) for c in chunks]
    allr = np.concatenate(recs)
    return allr[np.argsort(allr["secs"], kind="stable")].tobytes()


def decode_bin_records(data: bytes, labeled: bool = False) -> np.ndarray:
    if labeled:
        dt = [("track", "<i4"), ("secs", "<i4"), ("lat", "<f4"),
              ("lon", "<f4"), ("label", "S8")]
    else:
        dt = [("track", "<i4"), ("secs", "<i4"), ("lat", "<f4"),
              ("lon", "<f4")]
    return np.frombuffer(data, dtype=dt)


def sample_mask(n: int, rate: float, by: np.ndarray | None = None,
                seed: int = 0) -> np.ndarray:
    """1-in-k sampling mask (SamplingIterator): keeps every k-th feature
    overall, or every k-th per `by` group (the SAMPLE_BY attribute)."""
    if rate >= 1.0:
        return np.ones(n, dtype=bool)
    k = max(1, int(round(1.0 / max(rate, 1e-9))))
    if by is None:
        return (np.arange(n) % k) == 0
    # per-group modulo: order within group via stable argsort
    by = np.asarray(by)
    order = np.argsort(by, kind="stable")
    rank = np.empty(n, dtype=np.int64)
    grp = by[order]
    new_grp = np.empty(n, dtype=bool)
    new_grp[0] = True
    new_grp[1:] = grp[1:] != grp[:-1]
    # position within each group
    idx = np.arange(n)
    start = np.maximum.accumulate(np.where(new_grp, idx, 0))
    rank[order] = idx - start
    return (rank % k) == 0
