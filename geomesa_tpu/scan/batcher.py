"""Query micro-batching: coalesce concurrent ``query()`` calls into one
fused device scan.

The reference amortizes per-request overhead by running filters inside
the scan machinery itself (Accumulo iterators / HBase coprocessors
serving many concurrent scans per tablet server). The TPU rebuild's
analog bottleneck is DISPATCH COUNT: a 10M-point fused scan costs
~0.33 ms on device, so at production concurrency the store spends its
time launching kernels, not filtering points. This module turns N
in-flight queries into ONE vmapped launch (scan/zscan.py
``stack_queries`` + ``batch_hit_rows``) and demultiplexes per-caller
results.

Admission control is leader/follower with per-schema queues: the first
caller for a schema becomes the leader, lingers up to
``linger_us`` microseconds (or until ``max_batch`` callers are queued),
then drains the queue and dispatches ``store.query_batched``.
Followers block until the leader hands them their ``QueryResult``.
Queues are keyed by type name, so queries never coalesce across
schemas.

Lingering is load-gated: an idle singleton dispatches immediately (a
lone query must not pay the linger window as latency), and the wait
only applies when another dispatch is already in flight or followers
are already queued — exactly the situations where arrivals inside the
window can coalesce.

Knobs (system properties / environment):

- ``geomesa.batch.max.size``  (``GEOMESA_BATCH_MAX_SIZE``)   — max
  queries per fused dispatch, default 32; <= 1 disables batching.
- ``geomesa.batch.linger.micros`` (``GEOMESA_BATCH_LINGER_MICROS``) —
  how long a leader waits for followers, default 2000 µs.
- ``geomesa.batch.linger.adaptive`` (``GEOMESA_BATCH_LINGER_ADAPTIVE``)
  — derive the wait from an EWMA of per-schema inter-arrival time,
  clamped to ``[0, linger_us]`` (the static knob stays the ceiling);
  default true. Idle schemas (arrivals slower than the ceiling) pay
  ~zero linger; saturated ones wait just long enough for the queue to
  fill.
- ``geomesa.knn.batch`` (``GEOMESA_KNN_BATCH``) — coalesce concurrent
  ``knn()`` calls into one fused multi-query top-k dispatch
  (analytics/join.knn_batched), the way bbox queries already coalesce;
  default true. Disabled, each KNN request dispatches on its own.

Metrics (global registry): ``batcher.queries``, ``batcher.batches``,
``batcher.coalesced``, ``batcher.occupancy``, ``batcher.coalesce_ratio``,
``batcher.linger`` (timer), ``batcher.linger_effective_us``,
``batcher.plan_cache.hit`` / ``.miss``, ``batcher.plan_cache.hit_rate``.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..metrics import metrics
from ..utils.properties import SystemProperty
from .zscan import next_pow2

__all__ = ["QueryBatcher", "BATCH_MAX_SIZE", "BATCH_LINGER_MICROS",
           "BATCH_LINGER_ADAPTIVE", "KNN_BATCH"]

BATCH_MAX_SIZE = SystemProperty("geomesa.batch.max.size", "32")
BATCH_LINGER_MICROS = SystemProperty("geomesa.batch.linger.micros", "2000")
BATCH_LINGER_ADAPTIVE = SystemProperty("geomesa.batch.linger.adaptive",
                                       "true")
KNN_BATCH = SystemProperty("geomesa.knn.batch", "true")

# EWMA smoothing for the per-schema inter-arrival estimate: the most
# recent ~5 arrivals dominate, so the estimate tracks load shifts
# quickly without whiplashing on one outlier gap
_EWMA_ALPHA = 0.2


class _Pending:
    __slots__ = ("q", "ev", "result", "error")

    def __init__(self, q):
        self.q = q
        self.ev = threading.Event()
        self.result = None
        self.error = None

    def resolve(self, result=None, error=None):
        self.result, self.error = result, error
        self.ev.set()

    def get(self):
        self.ev.wait()
        if self.error is not None:
            raise self.error
        return self.result


class _TypeQueue:
    __slots__ = ("items", "has_leader", "last_arrival", "ewma_gap_s")

    def __init__(self):
        self.items: list[_Pending] = []
        self.has_leader = False
        self.last_arrival: float | None = None  # monotonic, admission
        self.ewma_gap_s: float | None = None    # None until 2 arrivals

    def observe_arrival(self, now: float):
        """Fold one admission into the inter-arrival EWMA."""
        if self.last_arrival is not None:
            gap = now - self.last_arrival
            self.ewma_gap_s = (gap if self.ewma_gap_s is None
                               else _EWMA_ALPHA * gap
                               + (1.0 - _EWMA_ALPHA) * self.ewma_gap_s)
        self.last_arrival = now


class QueryBatcher:
    """Admission-queue executor over a DataStore's ``query_batched``.

    Thread-safe; one instance fronts one store. Callers on the same
    schema arriving within a linger window share a single fused device
    scan; results are exactly what per-query ``store.query()`` would
    return (the store falls back per query for non-fusible plans).
    """

    def __init__(self, store, max_batch: int | None = None,
                 linger_us: float | None = None, adaptive: bool | None = None,
                 registry=metrics):
        self.store = store
        self.max_batch = int(max_batch if max_batch is not None
                             else BATCH_MAX_SIZE.get())
        self.linger_us = float(linger_us if linger_us is not None
                               else BATCH_LINGER_MICROS.get())
        self.adaptive = (adaptive if adaptive is not None
                         else str(BATCH_LINGER_ADAPTIVE.get()).lower()
                         in ("true", "1", "yes"))
        self.registry = registry
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: dict[str, _TypeQueue] = {}
        # jit/plan shape-class cache: keyed (type_name, index_version,
        # padded data cap, padded batch size). A miss predicts an XLA
        # retrace of the fused kernel for that shape class; hits mean
        # the trace is reused. Tracking it here (not in jax) gives the
        # serving layer observable recompile behavior.
        self._plan_keys: set[tuple] = set()
        self._in_flight = 0
        self.total_queries = 0
        self.coalesced_queries = 0
        self.batches = 0
        self.cache_hits = 0
        self.cache_misses = 0

    # -- public surface ----------------------------------------------------

    def query(self, q, type_name: str | None = None):
        """Submit one query; blocks until its result is ready. Mirrors
        ``store.query(q, type_name)`` ergonomics (ECQL string + type
        name, or a Query object)."""
        if isinstance(q, str):
            from ..index.api import Query
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        if self.max_batch <= 1:
            self._note(1)
            return self.store.query(q)
        p = _Pending(q)
        with self._cond:
            tq = self._queues.setdefault(q.type_name, _TypeQueue())
            tq.observe_arrival(time.monotonic())
            tq.items.append(p)
            if not tq.has_leader:
                tq.has_leader = True
                leader = True
            else:
                leader = False
                if len(tq.items) >= self.max_batch:
                    self._cond.notify_all()
        if not leader:
            return p.get()
        self._lead(q.type_name, tq)
        return p.get()

    def knn(self, type_name: str, qx: float, qy: float, k: int):
        """Submit one KNN query; blocks until (ids, distances) is
        ready. Concurrent callers on the same (type, k) coalesce into
        ONE fused multi-query top-k dispatch — the KNN analog of
        ``query()``'s admission queue (``geomesa.knn.batch``)."""
        from ..analytics.processes import knn_process
        enabled = str(KNN_BATCH.get()).lower() in ("true", "1", "yes")
        if not enabled or self.max_batch <= 1:
            self._note(1)
            return knn_process(self.store, type_name, float(qx),
                               float(qy), k)
        p = _Pending((float(qx), float(qy)))
        key = f"{type_name}\x00knn\x00{int(k)}"
        with self._cond:
            tq = self._queues.setdefault(key, _TypeQueue())
            tq.observe_arrival(time.monotonic())
            tq.items.append(p)
            if not tq.has_leader:
                tq.has_leader = True
                leader = True
            else:
                leader = False
                if len(tq.items) >= self.max_batch:
                    self._cond.notify_all()
        if not leader:
            return p.get()
        self._lead(key, tq,
                   dispatch=lambda _key, chunk:
                   self._dispatch_knn(type_name, int(k), chunk))
        return p.get()

    def stats(self) -> dict:
        """Batching counters (also mirrored into the metrics registry)."""
        total = self.total_queries
        probes = self.cache_hits + self.cache_misses
        return {
            "total_queries": total,
            "batches": self.batches,
            "coalesced_queries": self.coalesced_queries,
            "coalesce_ratio": (self.coalesced_queries / total
                               if total else 0.0),
            "plan_cache_hits": self.cache_hits,
            "plan_cache_misses": self.cache_misses,
            "plan_cache_hit_rate": (self.cache_hits / probes
                                    if probes else 0.0),
        }

    # -- leader path -------------------------------------------------------

    def _lead(self, type_name: str, tq: _TypeQueue, dispatch=None):
        """Linger for followers (only under load), then drain the queue
        in max_batch chunks and dispatch each as one fused scan.
        ``dispatch`` overrides the bbox-query dispatcher (the KNN path
        shares the admission/linger machinery, not the plan cache)."""
        t0 = time.perf_counter()
        chunks: list[list[_Pending]] = []
        with self._cond:
            # linger pays only when arrivals inside the window can
            # actually coalesce: another dispatch in flight, or
            # followers already queued behind this leader. An idle
            # singleton dispatches immediately — a lone query must not
            # see the linger window as added latency.
            linger_s = self._effective_linger_s(tq)
            self.registry.gauge("batcher.linger_effective_us",
                                linger_s * 1e6)
            if linger_s > 0 and (self._in_flight > 0
                                 or len(tq.items) > 1):
                deadline = time.monotonic() + linger_s
                while len(tq.items) < self.max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            while tq.items:
                chunks.append(tq.items[:self.max_batch])
                del tq.items[:self.max_batch]
            tq.has_leader = False
            self._in_flight += 1
        self._observe_linger(time.perf_counter() - t0)
        dispatch = dispatch or self._dispatch
        try:
            for chunk in chunks:
                dispatch(type_name, chunk)
        finally:
            with self._cond:
                self._in_flight -= 1

    def _effective_linger_s(self, tq: _TypeQueue) -> float:
        """The leader's wait budget for this dispatch, in seconds.

        Static mode (``adaptive=False``) always returns the ceiling.
        Adaptive mode sizes the wait from the schema's inter-arrival
        EWMA: no samples yet -> the ceiling (a cold queue behaves like
        the static knob); arrivals slower than the ceiling -> 0 (no
        follower can land inside the window, so lingering is pure added
        latency); otherwise enough gaps to fill the remaining batch
        slots, clamped to the ceiling."""
        ceiling = self.linger_us / 1e6
        if not self.adaptive or ceiling <= 0:
            return max(ceiling, 0.0)
        gap = tq.ewma_gap_s
        if gap is None:
            return ceiling
        if gap >= ceiling:
            return 0.0
        remaining_slots = max(self.max_batch - len(tq.items), 0)
        return min(ceiling, gap * remaining_slots)

    def _observe_linger(self, seconds: float):
        ctx = self.registry.time("batcher.linger")
        ctx.__enter__()
        ctx.t0 -= seconds  # backdate so the timer records the real wait
        ctx.__exit__(None, None, None)

    def _dispatch(self, type_name: str, chunk: list[_Pending]):
        occupancy = len(chunk)
        self._note(occupancy)
        try:
            if occupancy == 1:
                results = [self.store.query(chunk[0].q)]
            else:
                self._probe_plan_cache(type_name, occupancy)
                results = self.store.query_batched(
                    [p.q for p in chunk])
            for p, r in zip(chunk, results):
                p.resolve(result=r)
        except Exception:
            # semantics fallback: a batch-level failure must not take
            # down every caller — replay each query individually so
            # errors land on exactly the caller that owns them
            for p in chunk:
                try:
                    p.resolve(result=self.store.query(p.q))
                except Exception as e:  # noqa: BLE001
                    p.resolve(error=e)

    def _dispatch_knn(self, type_name: str, k: int,
                      chunk: list[_Pending]):
        """One fused multi-query top-k for a drained KNN chunk: stack
        the query points and let the batched process answer all of them
        in one device dispatch; demultiplex (ids, distances) per
        caller. Failures replay per caller, same contract as
        ``_dispatch``."""
        from ..analytics.processes import knn_batch_process, knn_process
        occupancy = len(chunk)
        self._note(occupancy)
        try:
            if occupancy == 1:
                qx, qy = chunk[0].q
                chunk[0].resolve(result=knn_process(
                    self.store, type_name, qx, qy, k))
                return
            qx = np.array([p.q[0] for p in chunk])
            qy = np.array([p.q[1] for p in chunk])
            results = knn_batch_process(self.store, type_name, qx, qy, k)
            for p, r in zip(chunk, results):
                p.resolve(result=r)
        except Exception:
            for p in chunk:
                try:
                    p.resolve(result=knn_process(
                        self.store, type_name, p.q[0], p.q[1], k))
                except Exception as e:  # noqa: BLE001
                    p.resolve(error=e)

    # -- accounting --------------------------------------------------------

    def _note(self, occupancy: int):
        with self._lock:
            self.total_queries += occupancy
            self.batches += 1
            if occupancy > 1:
                self.coalesced_queries += occupancy
            total, co = self.total_queries, self.coalesced_queries
        reg = self.registry
        reg.counter("batcher.queries", occupancy)
        reg.counter("batcher.batches")
        if occupancy > 1:
            reg.counter("batcher.coalesced", occupancy)
        reg.gauge("batcher.occupancy", occupancy)
        reg.gauge("batcher.coalesce_ratio", co / total if total else 0.0)

    def _probe_plan_cache(self, type_name: str, occupancy: int):
        key = self._shape_key(type_name, occupancy)
        with self._lock:
            hit = key in self._plan_keys
            if hit:
                self.cache_hits += 1
            else:
                self._plan_keys.add(key)
                self.cache_misses += 1
            hits, misses = self.cache_hits, self.cache_misses
        reg = self.registry
        reg.counter("batcher.plan_cache.hit" if hit
                    else "batcher.plan_cache.miss")
        reg.gauge("batcher.plan_cache.hit_rate",
                  hits / (hits + misses) if hits + misses else 0.0)

    def _shape_key(self, type_name: str, occupancy: int) -> tuple:
        """(type_name, index_version, padded data cap, padded batch
        size) — the shape class that decides whether the fused kernel's
        jit trace is reused. An index version bump or a capacity-class
        change invalidates every cached trace for the type."""
        try:
            version = self.store.get_schema(type_name).index_version
        except Exception:  # noqa: BLE001
            version = -1
        try:
            cap = next_pow2(max(int(self.store.count(type_name)), 1))
        except Exception:  # noqa: BLE001
            cap = 0
        return (type_name, version, cap, next_pow2(occupancy))
