"""Query micro-batching: coalesce concurrent ``query()`` calls into one
fused device scan.

The reference amortizes per-request overhead by running filters inside
the scan machinery itself (Accumulo iterators / HBase coprocessors
serving many concurrent scans per tablet server). The TPU rebuild's
analog bottleneck is DISPATCH COUNT: a 10M-point fused scan costs
~0.33 ms on device, so at production concurrency the store spends its
time launching kernels, not filtering points. This module turns N
in-flight queries into ONE vmapped launch (scan/zscan.py
``stack_queries`` + ``batch_hit_rows``) and demultiplexes per-caller
results.

Admission control is leader/follower with per-schema queues: the first
caller for a schema becomes the leader, lingers up to
``linger_us`` microseconds (or until ``max_batch`` callers are queued),
then drains the queue and dispatches ``store.query_batched``.
Followers block until the leader hands them their ``QueryResult``.
Queues are keyed by type name, so queries never coalesce across
schemas.

Lingering is load-gated: an idle singleton dispatches immediately (a
lone query must not pay the linger window as latency), and the wait
only applies when another dispatch is already in flight or followers
are already queued — exactly the situations where arrivals inside the
window can coalesce.

Knobs (system properties / environment):

- ``geomesa.batch.max.size``  (``GEOMESA_BATCH_MAX_SIZE``)   — max
  queries per fused dispatch, default 32; <= 1 disables batching.
- ``geomesa.batch.linger.micros`` (``GEOMESA_BATCH_LINGER_MICROS``) —
  how long a leader waits for followers, default 2000 µs.
- ``geomesa.batch.linger.adaptive`` (``GEOMESA_BATCH_LINGER_ADAPTIVE``)
  — derive the wait from an EWMA of per-schema inter-arrival time,
  clamped to ``[0, linger_us]`` (the static knob stays the ceiling);
  default true. Idle schemas (arrivals slower than the ceiling) pay
  ~zero linger; saturated ones wait just long enough for the queue to
  fill.
- ``geomesa.knn.batch`` (``GEOMESA_KNN_BATCH``) — coalesce concurrent
  ``knn()`` calls into one fused multi-query top-k dispatch
  (analytics/join.knn_batched), the way bbox queries already coalesce;
  default true. Disabled, each KNN request dispatches on its own.
- ``geomesa.batch.latency.budget.ms``
  (``GEOMESA_BATCH_LATENCY_BUDGET_MS``) — latency-derived batch caps:
  derive the effective ``max_batch`` from the observed per-shape-class
  dispatch-latency EWMA so one fused batch costs at most this budget
  (the p99 a serving tier is willing to spend on coalescing), with the
  static ``geomesa.batch.max.size`` staying the ceiling exactly like
  adaptive linger. Unset (default) keeps the static cap.

Metrics (global registry): ``batcher.queries``, ``batcher.batches``,
``batcher.coalesced``, ``batcher.occupancy``, ``batcher.coalesce_ratio``,
``batcher.linger`` (timer), ``batcher.linger_effective_us.<type>``,
``batcher.max_batch_effective.<type>``, ``batcher.queue_depth.<type>``,
``batcher.plan_cache.hit`` / ``.miss``, ``batcher.plan_cache.hit_rate``
(type-keyed gauges sanitize the type name — metrics/registry
``sanitize_key``).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..metrics import metrics, sanitize_key
from ..utils.properties import SystemProperty
from .zscan import next_pow2

__all__ = ["QueryBatcher", "BATCH_MAX_SIZE", "BATCH_LINGER_MICROS",
           "BATCH_LINGER_ADAPTIVE", "KNN_BATCH",
           "BATCH_LATENCY_BUDGET_MS"]

BATCH_MAX_SIZE = SystemProperty("geomesa.batch.max.size", "32")
BATCH_LINGER_MICROS = SystemProperty("geomesa.batch.linger.micros", "2000")
BATCH_LINGER_ADAPTIVE = SystemProperty("geomesa.batch.linger.adaptive",
                                       "true")
KNN_BATCH = SystemProperty("geomesa.knn.batch", "true")
BATCH_LATENCY_BUDGET_MS = SystemProperty("geomesa.batch.latency.budget.ms",
                                         None)

# EWMA smoothing for the per-schema inter-arrival estimate: the most
# recent ~5 arrivals dominate, so the estimate tracks load shifts
# quickly without whiplashing on one outlier gap
_EWMA_ALPHA = 0.2


class _Pending:
    __slots__ = ("q", "ev", "result", "error", "span_ctx", "tenant")

    def __init__(self, q):
        self.q = q
        self.ev = threading.Event()
        self.result = None
        self.error = None
        # caller's (trace state, span) — the leader links its fused
        # dispatch span to every waiter and grafts the dispatch
        # subtree back into their traces (obs/trace.py)
        self.span_ctx = None
        # tenant identity captured at admission (None with QoS off):
        # the leader drains per-tenant FIFO queues by deficit-weighted
        # round-robin instead of one global FIFO (tenants/__init__.py)
        from ..tenants import active_tenant
        self.tenant = active_tenant()

    def resolve(self, result=None, error=None):
        self.result, self.error = result, error
        self.ev.set()

    def get(self):
        self.ev.wait()
        if self.error is not None:
            raise self.error
        return self.result


class _TypeQueue:
    __slots__ = ("items", "has_leader", "last_arrival", "ewma_gap_s")

    def __init__(self):
        self.items: list[_Pending] = []
        self.has_leader = False
        self.last_arrival: float | None = None  # monotonic, admission
        self.ewma_gap_s: float | None = None    # None until 2 arrivals

    def observe_arrival(self, now: float):
        """Fold one admission into the inter-arrival EWMA."""
        if self.last_arrival is not None:
            gap = now - self.last_arrival
            self.ewma_gap_s = (gap if self.ewma_gap_s is None
                               else _EWMA_ALPHA * gap
                               + (1.0 - _EWMA_ALPHA) * self.ewma_gap_s)
        self.last_arrival = now


class QueryBatcher:
    """Admission-queue executor over a DataStore's ``query_batched``.

    Thread-safe; one instance fronts one store. Callers on the same
    schema arriving within a linger window share a single fused device
    scan; results are exactly what per-query ``store.query()`` would
    return (the store falls back per query for non-fusible plans).
    """

    def __init__(self, store, max_batch: int | None = None,
                 linger_us: float | None = None, adaptive: bool | None = None,
                 latency_budget_ms: float | None = None,
                 registry=metrics):
        self.store = store
        self.max_batch = int(max_batch if max_batch is not None
                             else BATCH_MAX_SIZE.get())
        self._linger_override = (None if linger_us is None
                                 else float(linger_us))
        self.adaptive = (adaptive if adaptive is not None
                         else str(BATCH_LINGER_ADAPTIVE.get()).lower()
                         in ("true", "1", "yes"))
        self._latency_budget_override = latency_budget_ms
        self.registry = registry
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: dict[str, _TypeQueue] = {}
        # jit/plan shape-class cache: keyed (type_name, index_version,
        # padded data cap, padded batch size). A miss predicts an XLA
        # retrace of the fused kernel for that shape class; hits mean
        # the trace is reused. Tracking it here (not in jax) gives the
        # serving layer observable recompile behavior.
        self._plan_keys: set[tuple] = set()
        # latency-derived batch caps: per shape-class EWMA of the
        # per-query cost of one fused dispatch (elapsed / occupancy)
        # and the last observed shape class per type, so the effective
        # cap can be read without touching the store
        self._cost_ewma: dict[tuple, float] = {}
        self._last_shape: dict[str, tuple] = {}
        # per-(queue key, tenant) DWRR deficit counters: unspent
        # fair-share credit carries across dispatches (tenants plane)
        self._deficits: dict[str, dict[str, float]] = {}
        self._in_flight = 0
        self.total_queries = 0
        self.coalesced_queries = 0
        self.batches = 0
        self.cache_hits = 0
        self.cache_misses = 0

    @property
    def linger_us(self) -> float:
        """The linger ceiling in force: an explicit constructor value
        wins; otherwise the knob is re-read LIVE per dispatch, so the
        SLO reaction loop (and operators) can lower the ceiling on a
        running tier without rebuilding batchers."""
        if self._linger_override is not None:
            return self._linger_override
        try:
            return float(BATCH_LINGER_MICROS.get())
        except (TypeError, ValueError):
            return 2000.0

    @linger_us.setter
    def linger_us(self, value: float):
        self._linger_override = float(value)

    # -- public surface ----------------------------------------------------

    def query(self, q, type_name: str | None = None):
        """Submit one query; blocks until its result is ready. Mirrors
        ``store.query(q, type_name)`` ergonomics (ECQL string + type
        name, or a Query object)."""
        if isinstance(q, str):
            from ..index.api import Query
            if type_name is None:
                raise ValueError("type_name required with a filter string")
            q = Query(type_name, q)
        from ..obs import tracer
        if self.max_batch <= 1:
            self._note(1)
            with tracer.span("batcher-wait", q.type_name, root=True):
                return self.store.query(q)
        p = _Pending(q)
        with tracer.span("batcher-wait", q.type_name, root=True) as wsp:
            p.span_ctx = tracer.current()
            with self._cond:
                tq = self._queues.setdefault(q.type_name, _TypeQueue())
                tq.observe_arrival(time.monotonic())
                tq.items.append(p)
                depth = len(tq.items)
                if not tq.has_leader:
                    tq.has_leader = True
                    leader = True
                else:
                    leader = False
                    if depth >= self.effective_max_batch(q.type_name):
                        self._cond.notify_all()
            self.registry.gauge(
                f"batcher.queue_depth.{sanitize_key(q.type_name)}", depth)
            wsp.set_attr(leader=leader, depth=depth)
            if not leader:
                return p.get()
            self._lead(q.type_name, tq)
            return p.get()

    def knn(self, type_name: str, qx: float, qy: float, k: int):
        """Submit one KNN query; blocks until (ids, distances) is
        ready. Concurrent callers on the same (type, k) coalesce into
        ONE fused multi-query top-k dispatch — the KNN analog of
        ``query()``'s admission queue (``geomesa.knn.batch``)."""
        from ..analytics.processes import knn_process
        enabled = str(KNN_BATCH.get()).lower() in ("true", "1", "yes")
        if not enabled or self.max_batch <= 1:
            self._note(1)
            return knn_process(self.store, type_name, float(qx),
                               float(qy), k)
        from ..obs import tracer
        p = _Pending((float(qx), float(qy)))
        key = f"{type_name}\x00knn\x00{int(k)}"
        with tracer.span("batcher-wait", f"knn:{type_name}",
                         root=True):
            p.span_ctx = tracer.current()
            with self._cond:
                tq = self._queues.setdefault(key, _TypeQueue())
                tq.observe_arrival(time.monotonic())
                tq.items.append(p)
                depth = len(tq.items)
                if not tq.has_leader:
                    tq.has_leader = True
                    leader = True
                else:
                    leader = False
                    if depth >= self.max_batch:
                        self._cond.notify_all()
            self.registry.gauge(
                f"batcher.queue_depth.{sanitize_key(key)}", depth)
            if not leader:
                return p.get()
            self._lead(key, tq,
                       dispatch=lambda _key, chunk:
                       self._dispatch_knn(type_name, int(k), chunk))
            return p.get()

    def stats(self) -> dict:
        """Batching counters (also mirrored into the metrics registry)."""
        total = self.total_queries
        probes = self.cache_hits + self.cache_misses
        return {
            "total_queries": total,
            "batches": self.batches,
            "coalesced_queries": self.coalesced_queries,
            "coalesce_ratio": (self.coalesced_queries / total
                               if total else 0.0),
            "plan_cache_hits": self.cache_hits,
            "plan_cache_misses": self.cache_misses,
            "plan_cache_hit_rate": (self.cache_hits / probes
                                    if probes else 0.0),
        }

    # -- leader path -------------------------------------------------------

    def _lead(self, type_name: str, tq: _TypeQueue, dispatch=None):
        """Linger for followers (only under load), then drain the queue
        in max_batch chunks and dispatch each as one fused scan.
        ``dispatch`` overrides the bbox-query dispatcher (the KNN path
        shares the admission/linger machinery, not the plan cache)."""
        t0 = time.perf_counter()
        chunks: list[list[_Pending]] = []
        with self._cond:
            # linger pays only when arrivals inside the window can
            # actually coalesce: another dispatch in flight, or
            # followers already queued behind this leader. An idle
            # singleton dispatches immediately — a lone query must not
            # see the linger window as added latency.
            cap = self.effective_max_batch(type_name)
            linger_s = self._effective_linger_s(tq)
            self.registry.gauge(
                "batcher.linger_effective_us."
                f"{sanitize_key(type_name)}", linger_s * 1e6)
            if linger_s > 0 and (self._in_flight > 0
                                 or len(tq.items) > 1):
                deadline = time.monotonic() + linger_s
                while len(tq.items) < cap:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            chunks = self._drain_chunks(type_name, tq, cap)
            tq.has_leader = False
            self._in_flight += 1
        self.registry.gauge(
            f"batcher.queue_depth.{sanitize_key(type_name)}", 0)
        self._observe_linger(time.perf_counter() - t0)
        dispatch = dispatch or self._dispatch
        try:
            for chunk in chunks:
                dispatch(type_name, chunk)
        finally:
            with self._cond:
                self._in_flight -= 1

    def _drain_chunks(self, key: str, tq: _TypeQueue,
                      cap: int) -> list[list[_Pending]]:
        """Drain the admission queue into cap-sized dispatch chunks.

        With QoS off every pending item carries ``tenant=None`` and the
        drain is the original global FIFO, bit-identically. With tenant
        identities present, items regroup into per-tenant FIFO queues
        filled by deficit-weighted round-robin (``weighted_drain``), so
        coalescing still fuses but a flooding tenant cannot occupy
        every batch slot. Called under ``self._cond``."""
        chunks: list[list[_Pending]] = []
        if not tq.items:
            return chunks
        tenants = {p.tenant for p in tq.items}
        if tenants == {None}:
            while tq.items:
                chunks.append(tq.items[:cap])
                del tq.items[:cap]
            return chunks
        from ..tenants import (DEFAULT_TENANT, tenant_label,
                               tenant_registry, weighted_drain)
        groups: dict[str, list[_Pending]] = {}
        for p in tq.items:
            groups.setdefault(p.tenant or DEFAULT_TENANT, []).append(p)
        tq.items.clear()
        deficits = self._deficits.setdefault(key, {})
        weight_of = lambda t: tenant_registry.policy(t).weight  # noqa: E731
        while any(groups.values()):
            chunk = weighted_drain(groups, deficits, cap, weight_of)
            if not chunk:
                break
            for t in {p.tenant or DEFAULT_TENANT for p in chunk}:
                self.registry.counter(
                    "qos.admission.dispatched",
                    sum(1 for p in chunk
                        if (p.tenant or DEFAULT_TENANT) == t),
                    labels={"tenant": tenant_label(t)})
            chunks.append(chunk)
        return chunks

    def _effective_linger_s(self, tq: _TypeQueue) -> float:
        """The leader's wait budget for this dispatch, in seconds.

        Static mode (``adaptive=False``) always returns the ceiling.
        Adaptive mode sizes the wait from the schema's inter-arrival
        EWMA: no samples yet -> the ceiling (a cold queue behaves like
        the static knob); arrivals slower than the ceiling -> 0 (no
        follower can land inside the window, so lingering is pure added
        latency); otherwise enough gaps to fill the remaining batch
        slots, clamped to the ceiling."""
        ceiling = self.linger_us / 1e6
        if not self.adaptive or ceiling <= 0:
            return max(ceiling, 0.0)
        gap = tq.ewma_gap_s
        if gap is None:
            return ceiling
        if gap >= ceiling:
            return 0.0
        remaining_slots = max(self.max_batch - len(tq.items), 0)
        return min(ceiling, gap * remaining_slots)

    def _observe_linger(self, seconds: float):
        ctx = self.registry.time("batcher.linger")
        ctx.__enter__()
        ctx.t0 -= seconds  # backdate so the timer records the real wait
        ctx.__exit__(None, None, None)

    def _dispatch(self, type_name: str, chunk: list[_Pending]):
        from ..obs import tracer
        occupancy = len(chunk)
        self._note(occupancy)
        shape = self._shape_key(type_name, occupancy)
        dsp = self._open_dispatch_span(tracer, type_name, chunk)
        err = None
        results: list = []
        with dsp:
            dsp.set_attr(occupancy=occupancy)
            try:
                if occupancy == 1:
                    results = [self.store.query(chunk[0].q)]
                else:
                    self._probe_plan_cache(shape)
                    from ..obs.prof import watchdog
                    from ..obs.runtime import runtime
                    t0 = time.perf_counter()
                    with watchdog.watch(
                            f"dispatch.{sanitize_key(type_name)}",
                            span=dsp):
                        results = self.store.query_batched(
                            [p.q for p in chunk])
                    dt = time.perf_counter() - t0
                    # only FUSED dispatches feed the cost EWMA: the cap
                    # decision is about how many queries one fused
                    # launch can carry inside the budget, and the
                    # scalar fast path has a different cost profile
                    # entirely
                    self._observe_cost(type_name, shape, dt / occupancy)
                    runtime.note_dispatch("batcher", shape, dt)
            except Exception as e:  # noqa: BLE001
                dsp.annotate("dispatch.failed", error=str(e))
                err = e
        # graft BEFORE resolving: the dispatch subtree lands in every
        # follower's trace while their roots are still open
        tracer.graft(dsp, [p.span_ctx for p in chunk])
        if err is None:
            for p, r in zip(chunk, results):
                p.resolve(result=r)
            return
        # semantics fallback: a batch-level failure must not take
        # down every caller — replay each query individually so
        # errors land on exactly the caller that owns them
        for p in chunk:
            try:
                p.resolve(result=self.store.query(p.q))
            except Exception as e:  # noqa: BLE001
                p.resolve(error=e)

    def _open_dispatch_span(self, tracer, name: str,
                            chunk: list[_Pending]):
        """A fused dispatch serves N waiting callers: the span links
        to each waiter and each waiter's span links back, so the
        N-queries -> 1-dispatch fan-in is navigable from both ends."""
        dsp = tracer.span("dispatch", name)
        if dsp.span_id is not None:
            for p in chunk:
                if p.span_ctx:
                    state, wsp = p.span_ctx
                    dsp.link(state.trace_id, wsp.span_id)
                    wsp.link(dsp.trace_id, dsp.span_id)
        return dsp

    def _dispatch_knn(self, type_name: str, k: int,
                      chunk: list[_Pending]):
        """One fused multi-query top-k for a drained KNN chunk: stack
        the query points and let the batched process answer all of them
        in one device dispatch; demultiplex (ids, distances) per
        caller. Failures replay per caller, same contract as
        ``_dispatch``."""
        from ..analytics.processes import knn_batch_process, knn_process
        from ..obs import tracer
        occupancy = len(chunk)
        self._note(occupancy)
        dsp = self._open_dispatch_span(tracer, f"knn:{type_name}", chunk)
        err = None
        results: list = []
        with dsp:
            dsp.set_attr(occupancy=occupancy, k=int(k))
            try:
                if occupancy == 1:
                    qx, qy = chunk[0].q
                    results = [knn_process(self.store, type_name,
                                           qx, qy, k)]
                else:
                    from ..obs.prof import watchdog
                    from ..obs.runtime import runtime
                    qx = np.array([p.q[0] for p in chunk])
                    qy = np.array([p.q[1] for p in chunk])
                    t0 = time.perf_counter()
                    with watchdog.watch(
                            f"dispatch.knn.{sanitize_key(type_name)}",
                            span=dsp):
                        results = knn_batch_process(self.store, type_name,
                                                    qx, qy, k)
                    runtime.note_dispatch(
                        "knn", (type_name, int(k), next_pow2(occupancy)),
                        time.perf_counter() - t0,
                        h2d_bytes=int(qx.nbytes + qy.nbytes))
            except Exception as e:  # noqa: BLE001
                dsp.annotate("dispatch.failed", error=str(e))
                err = e
        tracer.graft(dsp, [p.span_ctx for p in chunk])
        if err is None:
            for p, r in zip(chunk, results):
                p.resolve(result=r)
            return
        for p in chunk:
            try:
                p.resolve(result=knn_process(
                    self.store, type_name, p.q[0], p.q[1], k))
            except Exception as e:  # noqa: BLE001
                p.resolve(error=e)

    # -- accounting --------------------------------------------------------

    def _note(self, occupancy: int):
        with self._lock:
            self.total_queries += occupancy
            self.batches += 1
            if occupancy > 1:
                self.coalesced_queries += occupancy
            total, co = self.total_queries, self.coalesced_queries
        reg = self.registry
        reg.counter("batcher.queries", occupancy)
        reg.counter("batcher.batches")
        if occupancy > 1:
            reg.counter("batcher.coalesced", occupancy)
        reg.gauge("batcher.occupancy", occupancy)
        reg.gauge("batcher.coalesce_ratio", co / total if total else 0.0)

    def _probe_plan_cache(self, key: tuple):
        with self._lock:
            hit = key in self._plan_keys
            if hit:
                self.cache_hits += 1
            else:
                self._plan_keys.add(key)
                self.cache_misses += 1
            hits, misses = self.cache_hits, self.cache_misses
        reg = self.registry
        reg.counter("batcher.plan_cache.hit" if hit
                    else "batcher.plan_cache.miss")
        reg.gauge("batcher.plan_cache.hit_rate",
                  hits / (hits + misses) if hits + misses else 0.0)
        from ..obs.runtime import runtime
        runtime.note_plan_probe("batcher", key, hit)

    # -- latency-derived batch caps ----------------------------------------

    def _latency_budget_s(self) -> float | None:
        """Per-dispatch wall budget driving the effective batch cap;
        None (the default) disables the derivation entirely."""
        if self._latency_budget_override is not None:
            return float(self._latency_budget_override) / 1e3
        ms = BATCH_LATENCY_BUDGET_MS.as_float()
        return None if ms is None else ms / 1e3

    def _observe_cost(self, type_name: str, shape: tuple,
                      per_query_s: float):
        """Fold one dispatch's per-query cost into the shape-class EWMA.
        Keyed by (type, index_version, data cap) — the part of the
        shape class that predicts kernel cost independent of how many
        queries happened to coalesce this time."""
        cls = shape[:3]
        with self._lock:
            prev = self._cost_ewma.get(cls)
            self._cost_ewma[cls] = (
                per_query_s if prev is None
                else _EWMA_ALPHA * per_query_s
                + (1.0 - _EWMA_ALPHA) * prev)
            self._last_shape[type_name] = cls

    def effective_max_batch(self, type_name: str) -> int:
        """The batch cap actually in force for ``type_name``: the
        static knob, shrunk so one fused dispatch fits the latency
        budget given the shape class's observed per-query cost. Pure
        dict reads (never touches the store) so it is safe under the
        admission lock; no budget or no cost samples yet -> the static
        ceiling, mirroring adaptive linger's cold-start behavior."""
        budget_s = self._latency_budget_s()
        if budget_s is None or budget_s <= 0:
            return self.max_batch
        cls = self._last_shape.get(type_name)
        cost = self._cost_ewma.get(cls) if cls is not None else None
        if not cost or cost <= 0:
            return self.max_batch
        eff = min(self.max_batch, max(1, int(budget_s / cost)))
        self.registry.gauge(
            f"batcher.max_batch_effective.{sanitize_key(type_name)}", eff)
        return eff

    def queue_depths(self) -> dict[str, int]:
        """Per-type pending-queue depth snapshot (the ``/rest/health``
        batcher detail)."""
        with self._lock:
            return {k: len(tq.items) for k, tq in self._queues.items()
                    if tq.items}

    def _shape_key(self, type_name: str, occupancy: int) -> tuple:
        """(type_name, index_version, padded data cap, padded batch
        size) — the shape class that decides whether the fused kernel's
        jit trace is reused. An index version bump or a capacity-class
        change invalidates every cached trace for the type."""
        try:
            version = self.store.get_schema(type_name).index_version
        except Exception:  # noqa: BLE001
            version = -1
        try:
            cap = next_pow2(max(int(self.store.count(type_name)), 1))
        except Exception:  # noqa: BLE001
            cap = 0
        return (type_name, version, cap, next_pow2(occupancy))
