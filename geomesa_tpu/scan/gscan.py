"""Device extent-geometry scan: the XZ-index scan analog.

The reference stores non-point geometries in XZ2/XZ3 indexes
(geomesa-z3 curve/XZ2SFC.scala:24, XZ3SFC.scala:26) whose ranges give
*candidate* features, then evaluates the exact JTS predicate per
candidate on the tablet server. Here the whole geometry column's
bounding boxes live on device and one fused kernel classifies every
feature into a tristate:

- OUT  — bbox definitely disjoint from every query envelope
- IN   — bbox definitely inside a query envelope (a geometry is always
         somewhere inside its own bbox, so it definitely intersects)
- MAYBE— overlapping the envelope boundary; only these few go to the
         exact host f64 predicate (the per-candidate JTS analog)

f32 rounding is handled conservatively: data bboxes are rounded
*outward* at build time, and each query envelope is evaluated at both
an outward-rounded (for OUT) and inward-rounded (for IN) f32 version,
so the tristate is correct in exact-f64 terms by construction.

Also here: a device point-in-polygon (crossing-number) kernel over
padded edge buffers with an epsilon uncertainty band — points inside
the band are re-checked on host, making point-vs-polygon predicates
exact while the dense inner loop stays on the VPU. This is the hot
loop of ST_Contains / ST_Intersects residuals and of the
points-vs-polygons join (BASELINE config #5).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()

from .zscan import MILLIS_PER_DAY, next_pow2

__all__ = ["ExtentScanData", "build_extent_data", "extent_query",
           "extent_tristate", "PackedPolygon", "pack_polygon",
           "points_in_polygon_device", "points_in_polygon", "EDGE_EPS"]

# uncertainty half-band (degrees) for the f32 point-in-polygon kernel;
# ~11m at the equator — generous vs f32 ulp (~1.5e-5 deg at lon 180)
EDGE_EPS = 1e-4

_OUT, _MAYBE, _IN = np.int8(0), np.int8(1), np.int8(2)


def _round_out(lo: np.ndarray, hi: np.ndarray):
    """Round [lo, hi] f64 bounds outward to f32."""
    lo32 = lo.astype(np.float32)
    hi32 = hi.astype(np.float32)
    lo32 = np.where(lo32.astype(np.float64) > lo,
                    np.nextafter(lo32, np.float32(-np.inf)), lo32)
    hi32 = np.where(hi32.astype(np.float64) < hi,
                    np.nextafter(hi32, np.float32(np.inf)), hi32)
    return lo32, hi32


def _round_in(lo: np.ndarray, hi: np.ndarray):
    """Round [lo, hi] f64 bounds inward to f32 (may become empty)."""
    lo32 = lo.astype(np.float32)
    hi32 = hi.astype(np.float32)
    lo32 = np.where(lo32.astype(np.float64) < lo,
                    np.nextafter(lo32, np.float32(np.inf)), lo32)
    hi32 = np.where(hi32.astype(np.float64) > hi,
                    np.nextafter(hi32, np.float32(-np.inf)), hi32)
    return lo32, hi32


@dataclasses.dataclass
class ExtentScanData:
    """Device-resident per-feature bboxes (outward-rounded f32) and
    optional (day, ms) time columns for the XZ3 analog."""
    bxmin: jax.Array
    bymin: jax.Array
    bxmax: jax.Array
    bymax: jax.Array
    tday: jax.Array | None
    tms: jax.Array | None
    valid: jax.Array      # false for null/empty geometries
    n: int


def build_extent_data(bounds: np.ndarray, millis: np.ndarray | None = None,
                      device=None) -> ExtentScanData:
    """bounds: (n, 4) f64 [xmin ymin xmax ymax], NaN rows for nulls."""
    bounds = np.asarray(bounds, np.float64)
    valid = ~np.isnan(bounds[:, 0])
    safe = np.where(valid[:, None], bounds, 0.0)
    xmin, xmax = _round_out(safe[:, 0], safe[:, 2])
    ymin, ymax = _round_out(safe[:, 1], safe[:, 3])
    put = functools.partial(jax.device_put, device=device)
    tday = tms = None
    if millis is not None:
        millis = np.asarray(millis, np.int64)
        d = (millis // MILLIS_PER_DAY).astype(np.int32)
        tday = put(d)
        tms = put((millis - d.astype(np.int64) * MILLIS_PER_DAY)
                  .astype(np.int32))
    return ExtentScanData(put(xmin), put(ymin), put(xmax), put(ymax),
                          tday, tms, put(valid), len(bounds))


@dataclasses.dataclass
class ExtentQuery:
    """Padded query envelopes at outer/inner f32 rounding + optional
    inclusive time intervals as (day, ms) int32 bounds."""
    outer: jax.Array       # (K, 4) xmin ymin xmax ymax, outward
    inner: jax.Array       # (K, 4) inward (possibly empty boxes)
    box_valid: jax.Array   # (K,)
    times: jax.Array       # (B, 4) day_lo ms_lo day_hi ms_hi
    time_valid: jax.Array
    time_any: bool


def extent_query(boxes_f64, intervals_ms=None) -> ExtentQuery:
    boxes_f64 = list(boxes_f64)
    k = next_pow2(max(len(boxes_f64), 1))
    outer = np.zeros((k, 4), np.float32)
    inner = np.zeros((k, 4), np.float32)
    valid = np.zeros(k, dtype=bool)
    for i, (xmin, ymin, xmax, ymax) in enumerate(boxes_f64):
        xlo, xhi = _round_out(np.float64(xmin), np.float64(xmax))
        ylo, yhi = _round_out(np.float64(ymin), np.float64(ymax))
        outer[i] = (xlo, ylo, xhi, yhi)
        xlo, xhi = _round_in(np.float64(xmin), np.float64(xmax))
        ylo, yhi = _round_in(np.float64(ymin), np.float64(ymax))
        inner[i] = (xlo, ylo, xhi, yhi)
        valid[i] = True

    intervals_ms = list(intervals_ms or [])
    time_any = not intervals_ms
    b = next_pow2(max(len(intervals_ms), 1))
    times = np.zeros((b, 4), np.int32)
    tvalid = np.zeros(b, dtype=bool)
    for i, (lo, hi) in enumerate(intervals_ms):
        lo, hi = int(lo), int(hi)
        times[i] = (lo // MILLIS_PER_DAY, lo % MILLIS_PER_DAY,
                    hi // MILLIS_PER_DAY, hi % MILLIS_PER_DAY)
        tvalid[i] = True
    return ExtentQuery(jnp.asarray(outer), jnp.asarray(inner),
                       jnp.asarray(valid), jnp.asarray(times),
                       jnp.asarray(tvalid), time_any)


def _tristate_body(bxmin, bymin, bxmax, bymax, valid, tday, tms,
                   outer, inner, box_valid, times, time_valid,
                   time_any: bool, has_time: bool):
    ob = outer[None, :, :]
    # overlap with outward-rounded envelope: false => definitely disjoint
    overlap = ((bxmax[:, None] >= ob[..., 0]) & (bxmin[:, None] <= ob[..., 2])
               & (bymax[:, None] >= ob[..., 1]) & (bymin[:, None] <= ob[..., 3]))
    overlap &= box_valid[None, :]
    ib = inner[None, :, :]
    # containment in inward-rounded envelope: true => definitely inside
    inside = ((bxmin[:, None] >= ib[..., 0]) & (bxmax[:, None] <= ib[..., 2])
              & (bymin[:, None] >= ib[..., 1]) & (bymax[:, None] <= ib[..., 3]))
    inside &= box_valid[None, :]
    any_overlap = jnp.any(overlap, axis=1)
    any_inside = jnp.any(inside, axis=1)
    state = jnp.where(any_inside, _IN,
                      jnp.where(any_overlap, _MAYBE, _OUT))
    state = jnp.where(valid, state, _OUT)
    if time_any or not has_time:
        return state
    tx = times[None, :, :]
    after = ((tday[:, None] > tx[..., 0])
             | ((tday[:, None] == tx[..., 0]) & (tms[:, None] >= tx[..., 1])))
    before = ((tday[:, None] < tx[..., 2])
              | ((tday[:, None] == tx[..., 2]) & (tms[:, None] <= tx[..., 3])))
    t_ok = jnp.any(after & before & time_valid[None, :], axis=1)
    return jnp.where(t_ok, state, _OUT)


_tristate_kernel = functools.partial(
    jax.jit, static_argnames=("time_any", "has_time"))(_tristate_body)


def extent_tristate(data: ExtentScanData, q: ExtentQuery) -> np.ndarray:
    """Returns int8[n]: 0=OUT, 1=MAYBE (host exact check), 2=IN.

    Time intervals are exact (int compares), so they never force MAYBE.
    """
    has_time = data.tday is not None
    tday = data.tday if has_time else jnp.zeros((data.n,), jnp.int32)
    tms = data.tms if has_time else jnp.zeros((data.n,), jnp.int32)
    out = _tristate_kernel(data.bxmin, data.bymin, data.bxmax, data.bymax,
                           data.valid, tday, tms,
                           q.outer, q.inner, q.box_valid,
                           q.times, q.time_valid, q.time_any, has_time)
    return np.asarray(out)


# -- point-in-polygon device kernel ---------------------------------------

@dataclasses.dataclass
class PackedPolygon:
    """One polygon's rings as a padded edge list on device.

    edges: (E, 4) f32 [x0 y0 x1 y1]; edge_valid: (E,) bool. Holes are
    included — crossing-number parity handles them uniformly. `host`
    keeps the original geometry for the exact band recheck.
    """
    edges: jax.Array
    edge_valid: jax.Array
    host: object


def pack_polygon(poly) -> PackedPolygon:
    """Pack a Polygon/MultiPolygon's rings into an edge buffer."""
    rings: list[np.ndarray] = []
    polys = getattr(poly, "parts", [poly])
    for p in polys:
        rings.append(np.asarray(p.shell, np.float64))
        for h in getattr(p, "holes", []):
            rings.append(np.asarray(h, np.float64))
    segs = []
    for ring in rings:
        a = ring[:-1] if np.allclose(ring[0], ring[-1]) else ring
        b = np.roll(a, -1, axis=0)
        segs.append(np.concatenate([a, b], axis=1))
    e = np.concatenate(segs, axis=0) if segs else np.zeros((0, 4))
    ne = next_pow2(max(len(e), 1))
    edges = np.zeros((ne, 4), np.float32)
    edges[: len(e)] = e.astype(np.float32)
    valid = np.zeros(ne, dtype=bool)
    valid[: len(e)] = True
    return PackedPolygon(jnp.asarray(edges), jnp.asarray(valid), poly)


@jax.jit
def _pip_kernel(px, py, edges, edge_valid):
    """Crossing-number parity + uncertainty band.

    Returns (inside, band): inside via +x ray cast; band flags points
    within EDGE_EPS of any edge (f32 result untrustworthy there).
    """
    x0 = edges[None, :, 0]
    y0 = edges[None, :, 1]
    x1 = edges[None, :, 2]
    y1 = edges[None, :, 3]
    pxc = px[:, None]
    pyc = py[:, None]
    cond = (y0 > pyc) != (y1 > pyc)
    dy = jnp.where(y1 == y0, jnp.float32(1e-30), y1 - y0)
    xint = x0 + (pyc - y0) * (x1 - x0) / dy
    cross = cond & (pxc < xint) & edge_valid[None, :]
    inside = (jnp.sum(cross, axis=1) % 2) == 1

    # distance-to-segment (squared, planar degrees) for the band test
    ex = x1 - x0
    ey = y1 - y0
    len2 = ex * ex + ey * ey
    t = jnp.clip(((pxc - x0) * ex + (pyc - y0) * ey)
                 / jnp.where(len2 == 0, jnp.float32(1.0), len2), 0.0, 1.0)
    dx = pxc - (x0 + t * ex)
    dyv = pyc - (y0 + t * ey)
    d2 = dx * dx + dyv * dyv
    d2 = jnp.where(edge_valid[None, :], d2, jnp.float32(np.inf))
    band = jnp.min(d2, axis=1) < jnp.float32(EDGE_EPS * EDGE_EPS)
    return inside, band


def points_in_polygon_device(px: np.ndarray, py: np.ndarray,
                             packed: PackedPolygon
                             ) -> tuple[np.ndarray, np.ndarray]:
    """Device crossing-number test; returns (inside, band_idx).

    px/py: host f64 coords. `inside` is trustworthy except at the rows
    in `band_idx` (within EDGE_EPS of an edge) — the caller re-evaluates
    those with its exact host predicate (so open/closed boundary
    semantics are decided by the caller, not this kernel).
    """
    n = len(px)
    # pad points to the next power of two so candidate-count jitter
    # doesn't retrace/recompile the kernel (same reason edges/query
    # boxes are padded); the fill point is far outside any geometry so
    # it lands inside=False, band=False and is sliced away below
    np_pad = next_pow2(max(n, 1))
    px32 = np.full(np_pad, 1e9, np.float32)
    py32 = np.full(np_pad, 1e9, np.float32)
    px32[:n] = np.asarray(px, np.float64).astype(np.float32)
    py32[:n] = np.asarray(py, np.float64).astype(np.float32)
    inside, band = _pip_kernel(jnp.asarray(px32), jnp.asarray(py32),
                               packed.edges, packed.edge_valid)
    # np.array (not asarray): device buffers are read-only views and the
    # caller patches band rows in place
    return np.array(inside[:n]), np.flatnonzero(np.asarray(band[:n]))


def points_in_polygon(px: np.ndarray, py: np.ndarray, poly) -> np.ndarray:
    """Exact closed-boundary point-in-polygon via the device kernel +
    host band recheck (contains_points semantics)."""
    from ..analytics.st_functions import contains_points
    packed = pack_polygon(poly)
    inside, band_idx = points_in_polygon_device(px, py, packed)
    if len(band_idx):
        inside[band_idx] = contains_points(poly, np.asarray(px)[band_idx],
                                           np.asarray(py)[band_idx])
    return inside
