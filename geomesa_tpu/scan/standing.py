"""Standing-filter set: 100k registered geofences as ONE fused kernel.

The inverse of scan/zscan.py's batched ad-hoc scan: there the data is
device-resident and queries arrive; here the FILTERS are
device-resident (compiled to stacked bound arrays by
filters/compile.py) and data arrives as ingest batches. Every create
batch is evaluated against the whole filter population in a single
``rows x filters`` launch — a vmap of the rectangle predicate over the
filter axis — followed by the zscan count-then-compact transfer, so the
host sees per-filter hit lists sized by actual matches, not
rows*filters.

Incrementality is the whole point of a STANDING set:

- per-filter columns are capacity-padded to a power of two; ``register``
  appends in place with ``dynamic_update_slice`` (amortized-doubling
  rebuild only when the cap or per-filter box width grows) and
  ``unregister`` tombstones the slot via the alive mask — neither
  changes any device shape, so filter churn within the cap NEVER
  recompiles (asserted via the plan-cache counters, the
  scan/batcher.py observability pattern);
- ingest rows are padded to the next power of two, so the jit shape
  class is (filter cap, box width, attr count, padded rows) — a handful
  of traces over a workload's whole life.

Exactness mirrors zscan's conservative-mask + exact-patch split: the
kernel compares two-float pairs against slightly WIDENED bounds (a
guaranteed superset of the f64 predicate), and each filter's surviving
candidates take a host patch — the cheap vectorized f64 recheck for
compiled-exact filters, the full ``filters.evaluate`` oracle for
residual ones (LIKE, polygons, OR trees). Either way the final hit set
is id-exact against the oracle.

Metrics (``cq.device.*``): dispatch timer, padded cap / live / residual
fraction gauges, candidate+hit row counters, plan-cache hit/miss.
"""

from __future__ import annotations

import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..filters.compile import (CompiledFilter, compile_filter, exact_hits,
                               numeric_attrs)
from ..metrics import metrics
from ..utils.properties import SystemProperty
from .zscan import (MILLIS_PER_DAY, _ge_two_float, _le_two_float,
                    next_pow2, split_two_float)

__all__ = ["StandingFilterSet", "STANDING_MIN_CAP", "CQ_DEVICE_MAX_CELLS"]

# starting filter capacity (pow2); the set doubles from here on demand
STANDING_MIN_CAP = 64

# mask-cell budget per kernel launch: dispatch chunks ingest rows so
# the fused mask never exceeds cap*chunk = this many bools (128M cells
# ~= 128MB). 100k filters x a 1M-row bulk write would otherwise
# materialize a 131GB mask; chunking bounds it and every full chunk
# shares ONE jit shape class (the last chunk pads up to the same size)
CQ_DEVICE_MAX_CELLS = SystemProperty("geomesa.cq.device.max.cells",
                                     str(1 << 27))

# values are clamped into +/-_F32_SAFE before the two-float split so
# overflow-to-inf can never poison the lo residual with NaN; the clamp
# is monotone, so superset-ness survives (host recheck restores f64)
_F32_SAFE = 1.0e38

# widened-bound slack: relative 1e-11 dominates the ~2^-47 relative
# error of a two-float pair by three orders of magnitude, guaranteeing
# the device compare never drops a true f64 match; the slack's false
# positives die in the host recheck
_WIDEN_REL = 1e-11

# catch-all day range: filters with no time constraint carry an
# interval spanning all representable days (zscan._CATCH_ALL_INTERVAL),
# so the kernel needs no per-filter static time_any argument
_TIME_ALL = (-(2 ** 30), 0, 2 ** 30, MILLIS_PER_DAY)


def _clamp(a: np.ndarray) -> np.ndarray:
    a = np.asarray(a, dtype=np.float64)
    return np.clip(a, -_F32_SAFE, _F32_SAFE)


def _widen_lo(v: float) -> float:
    return v - (abs(v) + 1.0) * _WIDEN_REL


def _widen_hi(v: float) -> float:
    return v + (abs(v) + 1.0) * _WIDEN_REL


def _split_bound(v: float) -> tuple[np.float32, np.float32]:
    hi, lo = split_two_float(np.float64(v))
    return np.float32(hi), np.float32(lo)


def _split_time(millis: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    millis = np.asarray(millis, dtype=np.int64)
    tday = (millis // MILLIS_PER_DAY).astype(np.int32)
    tms = (millis - tday.astype(np.int64) * MILLIS_PER_DAY).astype(np.int32)
    return tday, tms


# -- in-place device row updates (one trace per array rank/dtype) ----------

@jax.jit
def _upd1(a, u, i):
    return jax.lax.dynamic_update_slice(a, u, (i,))


@jax.jit
def _upd2(a, u, i):
    return jax.lax.dynamic_update_slice(a, u[None], (i, 0))


@jax.jit
def _upd3(a, u, i):
    return jax.lax.dynamic_update_slice(a, u[None], (i, 0, 0))


# -- the fused rows x filters kernel ---------------------------------------

@jax.jit
def _standing_mask(xhi, xlo, yhi, ylo, tday, tms, avhi, avlo,
                   boxes, box_valid, box_any, times,
                   attrs, attr_any, alive, n_valid):
    """bool[F_cap, rows_padded]: which rows each filter matches
    (conservatively — widened bounds, see module docstring).

    Row arrays: (Np,) two-float coords + day/ms times; (Np, A) two-float
    attribute values. Filter arrays: (F, K, 8) boxes, (F, K) box_valid,
    (F,) box_any (no spatial constraint: pass), (F, 4) inclusive time
    envelopes, (F, A, 4) attribute bound pairs, (F, A) attr_any, (F,)
    alive. ``n_valid`` masks the row padding (traced, not static)."""

    def one(bx, bv, bany, tx, ab, aany):
        sx = (_ge_two_float(xhi[:, None], xlo[:, None],
                            bx[None, :, 0], bx[None, :, 1])
              & _le_two_float(xhi[:, None], xlo[:, None],
                              bx[None, :, 2], bx[None, :, 3])
              & _ge_two_float(yhi[:, None], ylo[:, None],
                              bx[None, :, 4], bx[None, :, 5])
              & _le_two_float(yhi[:, None], ylo[:, None],
                              bx[None, :, 6], bx[None, :, 7]))
        spatial = bany | jnp.any(sx & bv[None, :], axis=1)
        after = (tday > tx[0]) | ((tday == tx[0]) & (tms >= tx[1]))
        before = (tday < tx[2]) | ((tday == tx[2]) & (tms <= tx[3]))
        a_ge = _ge_two_float(avhi, avlo, ab[None, :, 0], ab[None, :, 1])
        a_le = _le_two_float(avhi, avlo, ab[None, :, 2], ab[None, :, 3])
        attr_ok = jnp.all(aany[None, :] | (a_ge & a_le), axis=1)
        return spatial & after & before & attr_ok

    m = jax.vmap(one)(boxes, box_valid, box_any, times, attrs, attr_any)
    row_ok = jnp.arange(xhi.shape[0], dtype=jnp.int32) < n_valid
    return m & alive[:, None] & row_ok[None, :]


@jax.jit
def _mask_total(mask):
    return jnp.sum(mask, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("size",))
def _flat_nonzero(mask, size: int):
    """Candidate (filter, row) cells as FLAT ascending indices into the
    raveled mask — one nonzero over the whole mask beats a per-filter
    vmapped nonzero by an order of magnitude, and ascending flat order
    IS (filter-major, row-ascending) grouping for free."""
    return jnp.nonzero(mask.ravel(), size=size, fill_value=mask.size)[0]


@functools.lru_cache(maxsize=1)
def _host_compact() -> bool:
    """On the CPU backend the mask already lives in host memory, and
    ``np.flatnonzero`` is ~200x faster than XLA:CPU's sized nonzero;
    on an accelerator the device compaction keeps the transfer at
    actual-candidate size instead of shipping the raw mask."""
    return jax.default_backend() == "cpu"


class StandingFilterSet:
    """The registered filter population for one feature type, compiled
    to capacity-padded device columns, plus the dispatch that matches
    an ingest batch against all of it in one launch."""

    def __init__(self, sft, registry=metrics, min_cap: int = STANDING_MIN_CAP):
        self.sft = sft
        self.geom_attr = sft.geom_field if sft.is_points else None
        self.dtg_attr = sft.dtg_field
        self.attr_names = numeric_attrs(sft)
        self._registry = registry
        self._lock = threading.RLock()
        self._cap = max(next_pow2(max(min_cap, 1)), 1)
        self._k = 1                       # boxes per filter (pow2)
        self._slots: dict[str, int] = {}  # name -> slot
        self._free: list[int] = []        # tombstoned slots, reusable
        self._high = 0                    # high-water slot count
        self._filters: list = []          # slot -> (name, ast, compiled)
        self._alloc_host()
        self._dev = None                  # lazy device mirrors
        # jit shape-class observability (scan/batcher.py pattern): a
        # probed key already seen means the dispatch reuses a trace
        self._plan_keys: set[tuple] = set()
        self.cache_hits = 0
        self.cache_misses = 0

    # -- storage ----------------------------------------------------------

    def _alloc_host(self):
        f, k, a = self._cap, self._k, len(self.attr_names)
        self._boxes = np.zeros((f, k, 8), dtype=np.float32)
        self._box_valid = np.zeros((f, k), dtype=bool)
        self._box_any = np.zeros(f, dtype=bool)
        self._times = np.zeros((f, 4), dtype=np.int32)
        self._attrs = np.zeros((f, a, 4), dtype=np.float32)
        self._attr_any = np.zeros((f, a), dtype=bool)
        self._alive = np.zeros(f, dtype=bool)

    def _device(self):
        if self._dev is None:
            self._dev = [jnp.asarray(a) for a in (
                self._boxes, self._box_valid, self._box_any, self._times,
                self._attrs, self._attr_any, self._alive)]
        return self._dev

    def _encode(self, cf: CompiledFilter):
        """CompiledFilter -> one row of each per-filter array (widened
        two-float bounds, catch-all defaults)."""
        k, a = self._k, len(self.attr_names)
        boxes = np.zeros((k, 8), dtype=np.float32)
        box_valid = np.zeros(k, dtype=bool)
        box_any = cf.spatial_any and not cf.never
        for i, (xmin, ymin, xmax, ymax) in enumerate(cf.boxes):
            xminh, xminl = _split_bound(_widen_lo(_clamp(xmin)))
            xmaxh, xmaxl = _split_bound(_widen_hi(_clamp(xmax)))
            yminh, yminl = _split_bound(_widen_lo(_clamp(ymin)))
            ymaxh, ymaxl = _split_bound(_widen_hi(_clamp(ymax)))
            boxes[i] = (xminh, xminl, xmaxh, xmaxl,
                        yminh, yminl, ymaxh, ymaxl)
            box_valid[i] = True
        times = np.asarray(_TIME_ALL, dtype=np.int32)
        if cf.interval is not None:
            lo, hi = cf.interval
            lod, lom = (_TIME_ALL[0], _TIME_ALL[1]) if lo is None \
                else (lo // MILLIS_PER_DAY, lo % MILLIS_PER_DAY)
            hid, him = (_TIME_ALL[2], _TIME_ALL[3]) if hi is None \
                else (hi // MILLIS_PER_DAY, hi % MILLIS_PER_DAY)
            times = np.asarray((lod, lom, hid, him), dtype=np.int32)
        attrs = np.zeros((a, 4), dtype=np.float32)
        attr_any = np.ones(a, dtype=bool)
        for j, name in enumerate(self.attr_names):
            ab = cf.attr_bounds.get(name)
            if ab is None:
                continue
            attr_any[j] = False
            lo = -_F32_SAFE if ab.lo is None \
                else _widen_lo(float(_clamp(ab.lo)))
            hi = _F32_SAFE if ab.hi is None \
                else _widen_hi(float(_clamp(ab.hi)))
            loh, lol = _split_bound(lo)
            hih, hil = _split_bound(hi)
            attrs[j] = (loh, lol, hih, hil)
        if cf.never:
            # dead on arrival: no box, no box_any -> spatial never passes
            box_valid[:] = False
            box_any = False
        return boxes, box_valid, box_any, times, attrs, attr_any

    def _write_slot(self, slot: int, row, alive: bool):
        boxes, box_valid, box_any, times, attrs, attr_any = row
        self._boxes[slot] = boxes
        self._box_valid[slot] = box_valid
        self._box_any[slot] = box_any
        self._times[slot] = times
        self._attrs[slot] = attrs
        self._attr_any[slot] = attr_any
        self._alive[slot] = alive
        if self._dev is not None:
            d = self._dev
            i = slot  # python int traces as a dynamic scalar: no retrace
            d[0] = _upd3(d[0], jnp.asarray(boxes), i)
            d[1] = _upd2(d[1], jnp.asarray(box_valid), i)
            d[2] = _upd1(d[2], jnp.asarray([box_any]), i)
            d[3] = _upd2(d[3], jnp.asarray(times), i)
            d[4] = _upd3(d[4], jnp.asarray(attrs), i)
            d[5] = _upd2(d[5], jnp.asarray(attr_any), i)
            d[6] = _upd1(d[6], jnp.asarray([alive]), i)

    def _grow(self, cap: int | None = None, k: int | None = None):
        """Amortized-doubling rebuild: re-encode every live filter into
        fresh host arrays (device mirrors re-upload lazily)."""
        self._cap = max(self._cap, next_pow2(max(cap or 0, 1)))
        self._k = max(self._k, next_pow2(max(k or 0, 1)))
        live = [(name, f, cf) for (name, f, cf) in self._filters
                if name is not None]
        self._alloc_host()
        self._dev = None
        self._slots = {}
        self._free = []
        self._filters = []
        self._high = 0
        for name, f, cf in live:
            self._append(name, f, cf)

    def _append(self, name: str, f, cf: CompiledFilter):
        if self._free:
            slot = self._free.pop()
            self._filters[slot] = (name, f, cf)
        else:
            slot = self._high
            if slot >= self._cap:
                self._grow(cap=self._cap * 2)
                self._append(name, f, cf)
                return
            self._high += 1
            self._filters.append((name, f, cf))
        self._slots[name] = slot
        self._write_slot(slot, self._encode(cf), alive=not cf.never)

    # -- registration ------------------------------------------------------

    def register(self, name: str, f, compiled: CompiledFilter | None = None):
        """Compile + append one standing filter. Within the padded cap
        this is a pure in-place row write — no shape changes."""
        with self._lock:
            if name in self._slots:
                raise ValueError(f"standing filter {name!r} exists")
            cf = compiled if compiled is not None \
                else compile_filter(f, self.sft)
            if cf.n_boxes > self._k:
                self._grow(k=cf.n_boxes)
            self._append(name, f, cf)
            self._gauges()
            return cf

    def unregister(self, name: str) -> bool:
        """Tombstone a filter: alive goes False in place, the slot is
        reused by the next register. Never reshapes, never recompiles."""
        with self._lock:
            slot = self._slots.pop(name, None)
            if slot is None:
                return False
            self._filters[slot] = (None, None, None)
            self._free.append(slot)
            self._alive[slot] = False
            if self._dev is not None:
                self._dev[6] = _upd1(self._dev[6],
                                     jnp.asarray([False]), slot)
            self._gauges()
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._slots

    # -- dispatch ----------------------------------------------------------

    def _rows_host(self, batch):
        """Ingest batch -> host row arrays (two-float coords/attrs,
        split times), unchunked and unpadded. Null coords/attrs are NaN
        and simply never match constrained filters (they are never true
        matches for those), while unconstrained dimensions pass via
        box_any/attr_any/catch-all."""
        n = batch.n
        if self.geom_attr is not None:
            col = batch.col(self.geom_attr)
            xhi, xlo = split_two_float(_clamp(col.x))
            yhi, ylo = split_two_float(_clamp(col.y))
        else:
            xhi = xlo = yhi = ylo = np.zeros(n, dtype=np.float32)
        if self.dtg_attr is not None:
            col = batch.col(self.dtg_attr)
            tday, tms = _split_time(np.where(col.valid, col.millis, 0))
        else:
            tday = tms = np.zeros(n, dtype=np.int32)
        a = len(self.attr_names)
        avhi = np.zeros((n, a), dtype=np.float32)
        avlo = np.zeros((n, a), dtype=np.float32)
        for j, name in enumerate(self.attr_names):
            col = batch.col(name)
            vals = _clamp(np.where(col.valid,
                                   col.values.astype(np.float64), np.nan))
            vhi, vlo = split_two_float(vals)
            avhi[:, j] = vhi
            avlo[:, j] = vlo
        return (np.asarray(xhi, dtype=np.float32),
                np.asarray(xlo, dtype=np.float32),
                np.asarray(yhi, dtype=np.float32),
                np.asarray(ylo, dtype=np.float32),
                tday.astype(np.int32), tms.astype(np.int32),
                avhi, avlo)

    @staticmethod
    def _chunk_device(rows, start: int, stop: int, chunk: int):
        """Slice [start:stop) out of the host row arrays and pad to the
        fixed chunk size (every chunk shares one device shape)."""
        out = []
        for a in rows:
            buf = np.zeros((chunk,) + a.shape[1:], dtype=a.dtype)
            buf[:stop - start] = a[start:stop]
            out.append(jnp.asarray(buf))
        return out

    def _chunk_rows(self, n: int) -> int:
        """Rows per kernel launch: the largest pow2 keeping the fused
        mask under the cell budget, clamped to the padded batch size."""
        cells = max(CQ_DEVICE_MAX_CELLS.as_int() or (1 << 27), 1)
        q = max(cells // max(self._cap, 1), 1)
        chunk = 1 << (q.bit_length() - 1)
        return min(chunk, next_pow2(max(n, 1)))

    def _probe_plan_cache(self, key: tuple):
        hit = key in self._plan_keys
        if hit:
            self.cache_hits += 1
        else:
            self._plan_keys.add(key)
            self.cache_misses += 1
        reg = self._registry
        reg.counter("cq.device.plan_cache.hit" if hit
                    else "cq.device.plan_cache.miss")
        from ..obs.runtime import runtime
        runtime.note_plan_probe("standing", key, hit)

    def dispatch(self, batch) -> dict[str, np.ndarray]:
        """Match one ingest batch against every registered filter:
        {filter name: sorted hit row indices}, id-exact vs the
        ``filters.evaluate`` oracle. Rows stream through the kernel in
        fixed-size chunks (CQ_DEVICE_MAX_CELLS bounds cap*chunk), so a
        1M-row bulk write at 100k filters runs in constant device
        memory."""
        with self._lock:
            if not self._slots:
                return {}
            entries = [(name, slot, self._filters[slot][1],
                        self._filters[slot][2])
                       for name, slot in self._slots.items()]
            reg = self._registry
            n = batch.n
            from ..obs.prof import watchdog
            from ..obs.runtime import runtime
            t_disp = time.perf_counter()
            h2d = d2h = 0
            with reg.time("cq.device.dispatch"), \
                    watchdog.watch("dispatch.cq"):
                rows = self._rows_host(batch)
                chunk = self._chunk_rows(n)
                key = (self._cap, self._k, len(self.attr_names), chunk)
                self._probe_plan_cache(key)
                fids_parts: list[np.ndarray] = []
                rows_parts: list[np.ndarray] = []
                for start in range(0, n, chunk):
                    stop = min(start + chunk, n)
                    dev = self._chunk_device(rows, start, stop, chunk)
                    h2d += sum(int(getattr(b, "nbytes", 0)) for b in dev)
                    mask = _standing_mask(*dev, *self._device(),
                                          jnp.int32(stop - start))
                    if _host_compact():
                        host_mask = np.asarray(mask)
                        d2h += int(host_mask.nbytes)
                        flat = np.flatnonzero(host_mask)
                        if not len(flat):
                            continue
                    else:
                        total = int(_mask_total(mask))
                        if not total:
                            continue
                        size = next_pow2(total)
                        host_flat = np.asarray(_flat_nonzero(mask, size))
                        d2h += int(host_flat.nbytes)
                        flat = host_flat[:total].astype(np.int64)
                    fids_parts.append(flat // chunk)
                    rows_parts.append(flat % chunk + start)
                if fids_parts:
                    fids = np.concatenate(fids_parts)
                    rws = np.concatenate(rows_parts)
                    # stable by filter id: per-filter rows stay
                    # ascending because chunks were visited in order
                    order = np.argsort(fids, kind="stable")
                    fids = fids[order]
                    rws = rws[order]
                    lo = np.searchsorted(fids, np.arange(self._cap))
                    hi = np.searchsorted(fids, np.arange(self._cap),
                                         side="right")
                else:
                    rws = np.empty(0, dtype=np.int64)
                    lo = hi = np.zeros(self._cap + 1, dtype=np.int64)
            runtime.note_dispatch("standing", key,
                                  time.perf_counter() - t_disp,
                                  h2d_bytes=h2d, d2h_bytes=d2h)
            out: dict[str, np.ndarray] = {}
            cand_rows = 0
            for name, slot, f, cf in entries:
                cand = rws[lo[slot]:hi[slot]]
                cand_rows += len(cand)
                out[name] = exact_hits(cf, f, batch, cand)
            n_res = sum(1 for _, _, _, cf in entries if cf.residual)
            reg.counter("cq.device.rows", n)
            reg.counter("cq.device.candidates", cand_rows)
            reg.counter("cq.device.hits",
                        int(sum(len(h) for h in out.values())))
            self._gauges(residual=n_res / max(len(entries), 1))
            return out

    # -- observability -----------------------------------------------------

    def _gauges(self, residual: float | None = None):
        reg = self._registry
        reg.gauge("cq.device.padded_cap", self._cap)
        reg.gauge("cq.device.live", len(self._slots))
        if residual is not None:
            reg.gauge("cq.device.residual.fraction", round(residual, 4))
        probes = self.cache_hits + self.cache_misses
        if probes:
            reg.gauge("cq.device.plan_cache.hit_rate",
                      round(self.cache_hits / probes, 4))

    def stats(self) -> dict:
        with self._lock:
            n_res = sum(1 for e in self._filters
                        if e[0] is not None and e[2].residual)
            live = len(self._slots)
            return {
                "type_name": self.sft.type_name,
                "live": live,
                "padded_cap": self._cap,
                "boxes_per_filter": self._k,
                "tracked_attrs": list(self.attr_names),
                "residual": n_res,
                "residual_fraction": round(n_res / max(live, 1), 4),
                "plan_cache_hits": self.cache_hits,
                "plan_cache_misses": self.cache_misses,
            }
