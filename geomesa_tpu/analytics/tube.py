"""TubeSelect: find features within a space-time "tube" around a track
(geomesa-process tube/TubeSelectProcess.scala:37).

The reference buffers + time-bins the input track (TubeBuilder:36, with
line-gap interpolation) and issues one spatio-temporal query per bin.
Here the tube becomes a *paired* device kernel: K (box, time-interval)
pairs evaluated in one program — a point matches if it falls in box_i
AND interval_i for some i (contrast with the cross-product semantics of
the plain scan kernel).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()

from ..scan.zscan import MILLIS_PER_DAY, next_pow2, split_two_float

__all__ = ["TubeBuilder", "tube_select_mask"]


class TubeBuilder:
    """Discretize a track into (bbox, time-interval) tube segments.

    bin_gap interpolation: consecutive track points further apart than
    max_bins get intermediate segments (LineGapFill analog).
    """

    def __init__(self, buffer_deg: float, bin_millis: int,
                 max_bins: int = 256):
        self.buffer = float(buffer_deg)
        self.bin_millis = int(bin_millis)
        self.max_bins = max_bins

    def build(self, xs, ys, millis) -> tuple[np.ndarray, np.ndarray]:
        """Track points -> (boxes (k,4) f64, intervals (k,2) i64).

        Each time bin covered by the track gets a box around the track's
        interpolated position(s) in that bin.
        """
        xs = np.asarray(xs, np.float64)
        ys = np.asarray(ys, np.float64)
        ms = np.asarray(millis, np.int64)
        order = np.argsort(ms, kind="stable")
        xs, ys, ms = xs[order], ys[order], ms[order]
        bins: dict[int, list[tuple[float, float]]] = {}

        def add(b, x, y):
            bins.setdefault(int(b), []).append((float(x), float(y)))

        for i in range(len(xs)):
            add(ms[i] // self.bin_millis, xs[i], ys[i])
            if i + 1 < len(xs):
                b0 = ms[i] // self.bin_millis
                b1 = ms[i + 1] // self.bin_millis
                gap = int(b1 - b0)
                if 1 < gap <= self.max_bins:
                    # linear interpolation across the gap (LineGapFill)
                    for s in range(1, gap):
                        t = s / gap
                        add(b0 + s, xs[i] + t * (xs[i + 1] - xs[i]),
                            ys[i] + t * (ys[i + 1] - ys[i]))

        boxes = []
        intervals = []
        for b in sorted(bins):
            pts = np.array(bins[b])
            boxes.append((pts[:, 0].min() - self.buffer,
                          pts[:, 1].min() - self.buffer,
                          pts[:, 0].max() + self.buffer,
                          pts[:, 1].max() + self.buffer))
            intervals.append((b * self.bin_millis,
                              (b + 1) * self.bin_millis - 1))
        return np.array(boxes, np.float64), np.array(intervals, np.int64)


@jax.jit
def _tube_kernel(xhi, xlo, yhi, ylo, tday, tms, boxes, times, valid):
    """Paired (box_i AND interval_i) membership, OR over i."""
    bx = boxes[None, :, :]
    sx = (((xhi[:, None] > bx[..., 0]) | ((xhi[:, None] == bx[..., 0])
                                          & (xlo[:, None] >= bx[..., 1])))
          & ((xhi[:, None] < bx[..., 2]) | ((xhi[:, None] == bx[..., 2])
                                            & (xlo[:, None] <= bx[..., 3])))
          & ((yhi[:, None] > bx[..., 4]) | ((yhi[:, None] == bx[..., 4])
                                            & (ylo[:, None] >= bx[..., 5])))
          & ((yhi[:, None] < bx[..., 6]) | ((yhi[:, None] == bx[..., 6])
                                            & (ylo[:, None] <= bx[..., 7]))))
    tx = times[None, :, :]
    tt = (((tday[:, None] > tx[..., 0]) | ((tday[:, None] == tx[..., 0])
                                           & (tms[:, None] >= tx[..., 1])))
          & ((tday[:, None] < tx[..., 2]) | ((tday[:, None] == tx[..., 2])
                                             & (tms[:, None] <= tx[..., 3]))))
    return jnp.any(sx & tt & valid[None, :], axis=1)


def tube_select_mask(data, boxes: np.ndarray,
                     intervals: np.ndarray) -> np.ndarray:
    """Evaluate tube membership against DeviceScanData; returns host
    bool mask. Boxes/intervals padded to a power of two for jit reuse."""
    k = len(boxes)
    if k == 0:
        return np.zeros(data.n, dtype=bool)
    p = next_pow2(k)
    bx = np.zeros((p, 8), np.float32)
    tm = np.zeros((p, 4), np.int32)
    valid = np.zeros(p, bool)
    for i, (xmin, ymin, xmax, ymax) in enumerate(boxes):
        xmin_hi, xmin_lo = split_two_float(np.float64(xmin))
        xmax_hi, xmax_lo = split_two_float(np.float64(xmax))
        ymin_hi, ymin_lo = split_two_float(np.float64(ymin))
        ymax_hi, ymax_lo = split_two_float(np.float64(ymax))
        bx[i] = (xmin_hi, xmin_lo, xmax_hi, xmax_lo,
                 ymin_hi, ymin_lo, ymax_hi, ymax_lo)
        lo, hi = int(intervals[i][0]), int(intervals[i][1])
        tm[i] = (lo // MILLIS_PER_DAY, lo % MILLIS_PER_DAY,
                 hi // MILLIS_PER_DAY, hi % MILLIS_PER_DAY)
        valid[i] = True
    mask = _tube_kernel(data.xhi, data.xlo, data.yhi, data.ylo,
                        data.tday, data.tms,
                        jnp.asarray(bx), jnp.asarray(tm), jnp.asarray(valid))
    # slice off capacity padding (rows >= n are not real features)
    return np.asarray(mask)[:data.n]
