"""ST_* geometry function library.

The vectorized analog of geomesa-spark-sql's UDF set
(org/apache/spark/sql/SQLSpatialFunctions.scala:31-41 and the
accessor/constructor/cast/output/processing modules): each function
operates on scalars or numpy arrays of geometries/coordinates.

Scalar-geometry functions delegate to the geometry engine; the hot
point-column forms (st_contains over a PointColumn, st_distance
point-to-points) are vectorized numpy/JAX.
"""

from __future__ import annotations

import math

import numpy as np

from ..geometry import (Envelope, Geometry, LineString, MultiPoint, Point,
                        Polygon, parse_wkt, to_wkt)
from ..geometry.base import _point_segments_dist2

__all__ = [
    "st_contains", "st_covers", "st_crosses", "st_disjoint", "st_equals",
    "st_intersects", "st_overlaps", "st_touches", "st_within", "st_dwithin",
    "st_distance", "st_distance_sphere", "st_area", "st_length",
    "st_centroid", "st_envelope", "st_buffer_envelope", "st_convex_hull",
    "convex_hull_points",
    "st_closest_point", "st_translate", "st_point", "st_make_bbox",
    "st_geom_from_wkt", "st_as_text", "st_x", "st_y",
    "contains_points", "distance_points",
]

EARTH_RADIUS_M = 6_371_008.8


# -- constructors / accessors ---------------------------------------------

def st_point(x: float, y: float) -> Point:
    return Point(x, y)


def st_make_bbox(xmin, ymin, xmax, ymax) -> Polygon:
    return Envelope(xmin, ymin, xmax, ymax).to_polygon()


def st_geom_from_wkt(wkt: str) -> Geometry:
    return parse_wkt(wkt)


def st_as_text(g: Geometry) -> str:
    return to_wkt(g)


def st_x(g: Point) -> float:
    return g.x


def st_y(g: Point) -> float:
    return g.y


def st_envelope(g: Geometry) -> Polygon:
    return g.envelope.to_polygon()


# -- predicates ------------------------------------------------------------

def st_contains(a: Geometry, b: Geometry) -> bool:
    return a.contains(b)


def st_covers(a: Geometry, b: Geometry) -> bool:
    return a.contains(b)  # boundary-inclusive contains == covers here


def st_within(a: Geometry, b: Geometry) -> bool:
    return b.contains(a)


def st_intersects(a: Geometry, b: Geometry) -> bool:
    return a.intersects(b)


def st_disjoint(a: Geometry, b: Geometry) -> bool:
    return not a.intersects(b)


def st_equals(a: Geometry, b: Geometry) -> bool:
    return a.envelope == b.envelope and a.contains(b) and b.contains(a)


def st_crosses(a: Geometry, b: Geometry) -> bool:
    return (a.intersects(b) and not a.contains(b) and not b.contains(a))


def st_overlaps(a: Geometry, b: Geometry) -> bool:
    return (a.geom_type == b.geom_type and a.intersects(b)
            and not a.contains(b) and not b.contains(a))


def st_touches(a: Geometry, b: Geometry) -> bool:
    if not a.intersects(b):
        return False
    ca, cb = a.centroid, b.centroid
    return not (a.contains(cb) or b.contains(ca))


def st_dwithin(a: Geometry, b: Geometry, distance_deg: float) -> bool:
    return a.dwithin(b, distance_deg)


# -- measures --------------------------------------------------------------

def st_distance(a: Geometry, b: Geometry) -> float:
    return a.distance(b)


def st_distance_sphere(a: Point, b: Point) -> float:
    """Great-circle distance in meters (ST_DistanceSpheroid analog,
    haversine on the mean sphere)."""
    return float(haversine_m(a.x, a.y, b.x, b.y))


def haversine_m(x1, y1, x2, y2):
    """Vectorized haversine, meters."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, np.float64))
                              for v in (x1, y1, x2, y2))
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = (np.sin(dlat / 2) ** 2
         + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2)
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


def st_area(g: Geometry) -> float:
    return g.area


def st_length(g: Geometry) -> float:
    return g.length


def st_centroid(g: Geometry) -> Point:
    return g.centroid


def st_buffer_envelope(g: Geometry, d: float) -> Polygon:
    """Envelope-expansion buffer (planning-grade; exact round buffers are
    not needed by any reference hot path)."""
    return g.envelope.buffer(d).to_polygon()


def st_convex_hull(g: Geometry) -> Geometry:
    """Monotone-chain convex hull of all vertices."""
    return convex_hull_points(np.vstack(g.coords_list()))


def convex_hull_points(pts: np.ndarray) -> Geometry:
    """Monotone-chain convex hull of an (n, 2) coordinate array — the
    raw form the SQL ConvexHull aggregate pools group members into."""
    pts = np.unique(np.asarray(pts, np.float64), axis=0)
    if len(pts) == 1:
        return Point(*pts[0])
    if len(pts) == 2:
        return LineString(pts)
    pts = pts[np.lexsort((pts[:, 1], pts[:, 0]))]

    def cross2(o, a, b):
        return (a[0] - o[0]) * (b[1] - o[1]) - (a[1] - o[1]) * (b[0] - o[0])

    def half(points):
        out: list[np.ndarray] = []
        for p in points:
            while len(out) >= 2 and cross2(out[-2], out[-1], p) <= 0:
                out.pop()
            out.append(p)
        return out

    lower = half(pts)
    upper = half(pts[::-1])
    hull = np.array(lower[:-1] + upper[:-1])
    if len(hull) < 3:
        return LineString(hull)
    return Polygon(hull)


def st_closest_point(a: Geometry, b: Point) -> Point:
    """Closest point on a to point b (per ring/part — no phantom
    segments bridging separate components)."""
    if isinstance(a, Point):
        return a
    best = None
    best_d2 = np.inf
    for coords in a.coords_list():
        if len(coords) < 2:
            if len(coords) == 1:
                d2 = (b.x - coords[0, 0]) ** 2 + (b.y - coords[0, 1]) ** 2
                if d2 < best_d2:
                    best_d2, best = d2, Point(*coords[0])
            continue
        x0, y0 = coords[:-1, 0], coords[:-1, 1]
        dx, dy = np.diff(coords[:, 0]), np.diff(coords[:, 1])
        len2 = dx * dx + dy * dy
        with np.errstate(divide="ignore", invalid="ignore"):
            t = ((b.x - x0) * dx + (b.y - y0) * dy) / len2
        t = np.where(len2 == 0, 0.0, np.clip(t, 0, 1))
        cx, cy = x0 + t * dx, y0 + t * dy
        d2 = (b.x - cx) ** 2 + (b.y - cy) ** 2
        i = int(np.argmin(d2))
        if d2[i] < best_d2:
            best_d2, best = float(d2[i]), Point(cx[i], cy[i])
    return best


def st_translate(g: Geometry, dx: float, dy: float) -> Geometry:
    import copy
    out = copy.deepcopy(g)

    def shift(geom):
        if isinstance(geom, Point):
            geom.x += dx
            geom.y += dy
        elif isinstance(geom, LineString):
            geom.coords = geom.coords + np.array([dx, dy])
        elif isinstance(geom, Polygon):
            geom.shell = geom.shell + np.array([dx, dy])
            geom.holes = [h + np.array([dx, dy]) for h in geom.holes]
        else:
            for p in geom.parts:
                shift(p)
    shift(out)
    return out


# -- vectorized column forms ----------------------------------------------

def contains_points(g: Geometry, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Vectorized geometry-contains-points (the ST_Contains hot form)."""
    if hasattr(g, "contains_points"):
        return g.contains_points(x, y)
    env = g.envelope
    out = (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)
    for i in np.flatnonzero(out):
        out[i] = g.contains(Point(x[i], y[i]))
    return out


def distance_points(g: Geometry, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Vectorized distance from geometry to each point (degrees).
    Each ring/part measures separately — vstacking them would create
    phantom bridging segments."""
    if isinstance(g, Point):
        return np.hypot(x - g.x, y - g.y)
    d2 = np.full(np.shape(np.asarray(x, np.float64)), np.inf)
    for coords in g.coords_list():
        if len(coords) == 0:
            continue
        if len(coords) == 1:
            d2 = np.minimum(d2, (x - coords[0, 0]) ** 2 + (y - coords[0, 1]) ** 2)
        else:
            d2 = np.minimum(d2, _point_segments_dist2(x, y, coords))
    d = np.sqrt(d2)
    if hasattr(g, "contains_points"):
        d = np.where(g.contains_points(x, y), 0.0, d)
    return d
