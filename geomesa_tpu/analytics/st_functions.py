"""ST_* geometry function library.

The vectorized analog of geomesa-spark-sql's UDF set
(org/apache/spark/sql/SQLSpatialFunctions.scala:31-41 and the
accessor/constructor/cast/output/processing modules): each function
operates on scalars or numpy arrays of geometries/coordinates.

Scalar-geometry functions delegate to the geometry engine; the hot
point-column forms (st_contains over a PointColumn, st_distance
point-to-points) are vectorized numpy/JAX.
"""

from __future__ import annotations

import math

import numpy as np

from ..geometry import (Envelope, Geometry, LineString, MultiLineString,
                        MultiPoint, MultiPolygon, Point, Polygon, parse_wkt,
                        to_wkt)
from ..geometry.base import _Multi, _point_segments_dist2

__all__ = [
    "st_contains", "st_covers", "st_crosses", "st_disjoint", "st_equals",
    "st_intersects", "st_overlaps", "st_touches", "st_within", "st_dwithin",
    "st_distance", "st_distance_sphere", "st_area", "st_length",
    "st_centroid", "st_envelope", "st_buffer_envelope", "st_convex_hull",
    "convex_hull_points",
    "st_closest_point", "st_translate", "st_point", "st_make_bbox",
    "st_geom_from_wkt", "st_as_text", "st_x", "st_y",
    "st_point_n", "st_exterior_ring", "st_num_points", "st_make_polygon",
    "st_relate", "st_relate_bool", "st_buffer", "st_buffer_point",
    "st_distance_spheroid", "st_length_spheroid",
    "st_antimeridian_safe_geom", "st_idl_safe_geom",
    "st_cast_to_point", "st_cast_to_linestring",
    "st_cast_to_polygon", "st_cast_to_geometry", "st_as_binary",
    "st_geom_from_wkb", "st_as_geojson", "SQL_SCALARS",
    "st_geohash", "st_geom_from_geohash",
    "contains_points", "distance_points",
]

EARTH_RADIUS_M = 6_371_008.8


# -- constructors / accessors ---------------------------------------------

def st_point(x: float, y: float) -> Point:
    return Point(x, y)


def st_make_bbox(xmin, ymin, xmax, ymax) -> Polygon:
    return Envelope(xmin, ymin, xmax, ymax).to_polygon()


def st_geom_from_wkt(wkt: str) -> Geometry:
    return parse_wkt(wkt)


def st_point_n(g: Geometry, n) -> Point | None:
    """N-th vertex of a LineString, 1-based (st_pointN); negative n
    counts from the end. None for other types or out of range — the
    reference returns null rather than raising."""
    if not isinstance(g, LineString):
        return None
    n = int(n)
    size = len(g.coords)
    if n < 0:
        n = size + n + 1
    if n < 1 or n > size:
        return None
    x, y = g.coords[n - 1]
    return Point(float(x), float(y))


def st_exterior_ring(g: Geometry) -> LineString | None:
    """Polygon shell as a (closed) LineString (st_exteriorRing); None
    for non-polygons."""
    if not isinstance(g, Polygon):
        return None
    return LineString(g.shell)


def st_num_points(g: Geometry) -> int:
    """Total vertex count over every ring/part (st_numPoints)."""
    if isinstance(g, Point):
        return 1
    return int(sum(len(c) for c in g.coords_list()))


def st_make_polygon(shell: LineString) -> Polygon | None:
    """Polygon from a LineString shell (st_makePolygon); None for
    other types or degenerate (< 3 point) rings — the reference
    returns null rather than raising."""
    if not isinstance(shell, LineString) or len(shell.coords) < 3:
        return None
    return Polygon(shell.coords)


def st_as_text(g: Geometry) -> str:
    return to_wkt(g)


def st_x(g: Point) -> float:
    return g.x


def st_y(g: Point) -> float:
    return g.y


def st_envelope(g: Geometry) -> Polygon:
    return g.envelope.to_polygon()


# -- predicates ------------------------------------------------------------

def st_contains(a: Geometry, b: Geometry) -> bool:
    return a.contains(b)


def st_covers(a: Geometry, b: Geometry) -> bool:
    return a.contains(b)  # boundary-inclusive contains == covers here


def st_within(a: Geometry, b: Geometry) -> bool:
    return b.contains(a)


def st_intersects(a: Geometry, b: Geometry) -> bool:
    return a.intersects(b)


def st_disjoint(a: Geometry, b: Geometry) -> bool:
    return not a.intersects(b)


def st_equals(a: Geometry, b: Geometry) -> bool:
    return a.envelope == b.envelope and a.contains(b) and b.contains(a)


def st_crosses(a: Geometry, b: Geometry) -> bool:
    from ..geometry.relate import crosses
    return crosses(a, b)


def st_overlaps(a: Geometry, b: Geometry) -> bool:
    from ..geometry.relate import overlaps
    return overlaps(a, b)


def st_touches(a: Geometry, b: Geometry) -> bool:
    from ..geometry.relate import touches
    return touches(a, b)


def st_relate(a: Geometry, b: Geometry) -> str:
    """The DE-9IM matrix string (SQLSpatialFunctions ST_Relate)."""
    from ..geometry.relate import relate
    return relate(a, b)


def st_relate_bool(a: Geometry, b: Geometry, pattern: str) -> bool:
    """DE-9IM pattern match (ST_RelateBool)."""
    from ..geometry.relate import relate, relate_matches
    return relate_matches(relate(a, b), pattern)


def st_dwithin(a: Geometry, b: Geometry, distance_deg: float) -> bool:
    return a.dwithin(b, distance_deg)


# -- measures --------------------------------------------------------------

def st_distance(a: Geometry, b: Geometry) -> float:
    return a.distance(b)


def st_distance_sphere(a: Point, b: Point) -> float:
    """Great-circle distance in meters (ST_DistanceSpheroid analog,
    haversine on the mean sphere)."""
    return float(haversine_m(a.x, a.y, b.x, b.y))


def haversine_m(x1, y1, x2, y2):
    """Vectorized haversine, meters."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(v, np.float64))
                              for v in (x1, y1, x2, y2))
    dlat = lat2 - lat1
    dlon = lon2 - lon1
    a = (np.sin(dlat / 2) ** 2
         + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2)
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0, 1)))


def st_area(g: Geometry) -> float:
    return g.area


def st_length(g: Geometry) -> float:
    return g.length


def st_centroid(g: Geometry) -> Point:
    return g.centroid


def st_buffer_envelope(g: Geometry, d: float) -> Polygon:
    """Envelope-expansion buffer (planning-grade; exact round buffers are
    not needed by any reference hot path)."""
    return g.envelope.buffer(d).to_polygon()


_buffer_envelope_warned = False


def st_buffer(g: Geometry, d: float, segments: int = 64) -> Polygon:
    """Planar buffer in degrees (JTS ST_Buffer semantics). Points get a
    true round buffer (n-gon circle in coordinate space); other
    geometries use the envelope expansion — a documented
    over-approximation (planning-grade; exact offset curves for
    lines/polygons are not on any reference hot path)."""
    if isinstance(g, Point):
        ang = np.linspace(0.0, 2.0 * math.pi, segments, endpoint=False)
        ring = np.column_stack([g.x + d * np.cos(ang),
                                g.y + d * np.sin(ang)])
        return Polygon(ring)
    global _buffer_envelope_warned
    if not _buffer_envelope_warned:
        _buffer_envelope_warned = True
        import warnings
        warnings.warn(
            "st_buffer of a non-point geometry returns an envelope"
            " expansion (bbox over-approximation), not an exact offset"
            " curve", stacklevel=2)
    return st_buffer_envelope(g, d)


def st_buffer_point(p: Point, meters: float, segments: int = 64) -> Polygon:
    """True round buffer of a point by a distance in METERS: a ring of
    geodesic destination points on the mean sphere (the reference's
    ST_BufferPoint uses GeoHashUtils' geodesic point buffer;
    SQLGeometryProcessingFunctions.scala). Accurate to the spherical
    approximation; exact circle in the metric, polygonal in degrees."""
    lat1 = math.radians(p.y)
    lon1 = math.radians(p.x)
    ang = meters / EARTH_RADIUS_M
    bearings = np.linspace(0.0, 2.0 * math.pi, segments, endpoint=False)
    lat2 = np.arcsin(np.sin(lat1) * np.cos(ang)
                     + np.cos(lat1) * np.sin(ang) * np.cos(bearings))
    lon2 = lon1 + np.arctan2(
        np.sin(bearings) * np.sin(ang) * np.cos(lat1),
        np.cos(ang) - np.sin(lat1) * np.sin(lat2))
    ring = np.column_stack([np.degrees(lon2), np.degrees(lat2)])
    return Polygon(ring)


# WGS84 spheroid
_WGS84_A = 6_378_137.0
_WGS84_F = 1.0 / 298.257223563
_WGS84_B = _WGS84_A * (1.0 - _WGS84_F)


def st_distance_spheroid(a: Point, b: Point) -> float:
    """Vincenty inverse distance on the WGS84 ellipsoid in meters
    (SQLGeometryProcessingFunctions ST_DistanceSpheroid). Falls back to
    haversine for near-antipodal pairs where the iteration diverges."""
    if a.x == b.x and a.y == b.y:
        return 0.0
    L = math.radians(b.x - a.x)
    u1 = math.atan((1 - _WGS84_F) * math.tan(math.radians(a.y)))
    u2 = math.atan((1 - _WGS84_F) * math.tan(math.radians(b.y)))
    su1, cu1 = math.sin(u1), math.cos(u1)
    su2, cu2 = math.sin(u2), math.cos(u2)
    lam = L
    for _ in range(200):
        sl, cl = math.sin(lam), math.cos(lam)
        s_sig = math.sqrt((cu2 * sl) ** 2
                          + (cu1 * su2 - su1 * cu2 * cl) ** 2)
        if s_sig == 0:
            return 0.0
        c_sig = su1 * su2 + cu1 * cu2 * cl
        sig = math.atan2(s_sig, c_sig)
        sin_alpha = cu1 * cu2 * sl / s_sig
        cos2_alpha = 1.0 - sin_alpha * sin_alpha
        cos_2sigm = (c_sig - 2 * su1 * su2 / cos2_alpha
                     if cos2_alpha != 0 else 0.0)
        C = _WGS84_F / 16 * cos2_alpha * (4 + _WGS84_F
                                          * (4 - 3 * cos2_alpha))
        lam_prev = lam
        lam = L + (1 - C) * _WGS84_F * sin_alpha * (
            sig + C * s_sig * (cos_2sigm
                               + C * c_sig * (-1 + 2 * cos_2sigm ** 2)))
        if abs(lam - lam_prev) < 1e-12:
            break
    else:
        return float(haversine_m(a.x, a.y, b.x, b.y))
    u_sq = cos2_alpha * (_WGS84_A ** 2 - _WGS84_B ** 2) / _WGS84_B ** 2
    A = 1 + u_sq / 16384 * (4096 + u_sq * (-768 + u_sq
                                           * (320 - 175 * u_sq)))
    B = u_sq / 1024 * (256 + u_sq * (-128 + u_sq * (74 - 47 * u_sq)))
    d_sig = B * s_sig * (cos_2sigm + B / 4 * (
        c_sig * (-1 + 2 * cos_2sigm ** 2)
        - B / 6 * cos_2sigm * (-3 + 4 * s_sig ** 2)
        * (-3 + 4 * cos_2sigm ** 2)))
    return float(_WGS84_B * A * (sig - d_sig))


# -- casts / outputs (SQLGeometricCastFunctions / OutputFunctions) ---------

def st_cast_to_point(g: Geometry) -> Point:
    if isinstance(g, Point):
        return g
    if isinstance(g, MultiPoint) and len(g.parts) == 1:
        return g.parts[0]
    raise TypeError(f"cannot cast {g.geom_type} to Point")


def st_cast_to_linestring(g: Geometry) -> LineString:
    if isinstance(g, LineString):
        return g
    from ..geometry import MultiLineString
    if isinstance(g, MultiLineString) and len(g.parts) == 1:
        return g.parts[0]
    raise TypeError(f"cannot cast {g.geom_type} to LineString")


def st_cast_to_polygon(g: Geometry) -> Polygon:
    if isinstance(g, Polygon):
        return g
    from ..geometry import MultiPolygon
    if isinstance(g, MultiPolygon) and len(g.parts) == 1:
        return g.parts[0]
    raise TypeError(f"cannot cast {g.geom_type} to Polygon")


def st_cast_to_geometry(g: Geometry) -> Geometry:
    return g


def st_as_binary(g: Geometry) -> bytes:
    from ..geometry.wkb import to_wkb
    return to_wkb(g)


def st_geom_from_wkb(data: bytes) -> Geometry:
    from ..geometry.wkb import from_wkb
    return from_wkb(data)


def st_as_geojson(g: Geometry) -> str:
    import json
    from ..geometry.geojson import to_geojson
    return json.dumps(to_geojson(g))


def st_length_spheroid(g: Geometry) -> float:
    """Geodesic length of a line geometry on the WGS84 spheroid in
    meters: Vincenty distance summed over consecutive vertices of every
    part (the reference's ST_LengthSpheroid)."""
    if isinstance(g, (Point, MultiPoint)):
        return 0.0
    total = 0.0
    for coords in g.coords_list():
        for i in range(len(coords) - 1):
            total += st_distance_spheroid(Point(*coords[i]),
                                          Point(*coords[i + 1]))
    return float(total)


def _clip_halfplane(ring: np.ndarray, east: bool) -> np.ndarray | None:
    """Sutherland-Hodgman clip of a closed ring against the vertical
    line x=180, keeping x>=180 (east=True) or x<=180 (east=False)."""
    pts = ring[:-1] if len(ring) > 1 and np.array_equal(ring[0], ring[-1]) \
        else ring

    def inside(p):
        return p[0] >= 180.0 if east else p[0] <= 180.0

    out: list = []
    for i in range(len(pts)):
        a, b = pts[i - 1], pts[i]
        ain, bin_ = inside(a), inside(b)
        if bin_:
            if not ain:
                out.append(_cross_at_180(a, b))
            out.append(b)
        elif ain:
            out.append(_cross_at_180(a, b))
    if len(out) < 3:
        return None
    return np.asarray(out, np.float64)


def _cross_at_180(a, b):
    t = (180.0 - a[0]) / (b[0] - a[0])
    return (180.0, a[1] + t * (b[1] - a[1]))


def _split_line_at_180(coords: np.ndarray) -> list[np.ndarray]:
    """Cut a linestring's coordinates wherever a segment crosses x=180,
    duplicating the crossing point into both pieces."""
    pieces: list[list] = [[coords[0]]]
    for i in range(1, len(coords)):
        a, b = coords[i - 1], coords[i]
        if (a[0] - 180.0) * (b[0] - 180.0) < 0:
            x = _cross_at_180(a, b)
            pieces[-1].append(x)
            pieces.append([x])
        pieces[-1].append(b)
    return [np.asarray(p, np.float64) for p in pieces if len(p) >= 2]


def st_antimeridian_safe_geom(g: Geometry) -> Geometry:
    """Split a geometry that extends past the antimeridian into parts
    that each live inside [-180, 180] (the reference's
    st_antimeridianSafeGeom). Input uses the continuous-longitude
    convention (a bbox spanning the dateline runs e.g. 170..190); the
    overflow east of x=180 is clipped off and translated by -360, so
    area/length are preserved and point-in-polygon tests work in the
    standard domain."""
    env = g.envelope
    if env.is_empty or env.xmax <= 180.0:
        return g
    if isinstance(g, Point):
        return Point(g.x - 360.0, g.y) if g.x > 180.0 else g
    if isinstance(g, _Multi):
        parts: list[Geometry] = []
        for p in g.parts:
            safe = st_antimeridian_safe_geom(p)
            parts.extend(safe.parts if isinstance(safe, _Multi) else [safe])
        return parts[0] if len(parts) == 1 else type(g)(parts)
    if isinstance(g, LineString):
        pieces = _split_line_at_180(g.coords)
        lines = [LineString(p - [360.0, 0.0] if p[:, 0].max() > 180.0 else p)
                 for p in pieces]
        return lines[0] if len(lines) == 1 else MultiLineString(lines)
    if isinstance(g, Polygon):
        polys: list[Polygon] = []
        for east in (False, True):
            shell = _clip_halfplane(g.shell, east)
            if shell is None:
                continue
            holes = [h for h in (_clip_halfplane(h, east) for h in g.holes)
                     if h is not None]
            if east:
                shell = shell - [360.0, 0.0]
                holes = [h - [360.0, 0.0] for h in holes]
            polys.append(Polygon(shell, holes))
        if not polys:
            return g
        return polys[0] if len(polys) == 1 else MultiPolygon(polys)
    return g


def st_idl_safe_geom(g: Geometry) -> Geometry:
    """The reference's st_idlSafeGeom — an exact alias of
    st_antimeridianSafeGeom (GeometryProcessingFunctions.scala registers
    both names over one implementation). Kept as a named function so
    the alias contract is testable: the two must stay bit-identical."""
    return st_antimeridian_safe_geom(g)


def st_geohash(g: Geometry, prec: int = 25) -> str:
    """Base-32 geohash of the geometry at ``prec`` BITS of precision
    (the reference's st_geoHash; GeoHash.scala:25 takes bit precision).
    Non-point geometries hash their centroid. The rendered string
    carries ceil(prec/5) characters — the 5-bit base-32 granularity."""
    from ..geohash import encode
    c = g if isinstance(g, Point) else g.centroid
    chars = max(1, -(-int(prec) // 5))
    return encode(float(c.x), float(c.y), chars)


def st_geom_from_geohash(gh: str, prec: int | None = None) -> Polygon:
    """The geohash cell's bbox polygon (the reference's
    st_geomFromGeoHash); ``prec`` (BITS) truncates to a coarser cell."""
    from ..geohash import decode_bbox
    xmin, ymin, xmax, ymax = decode_bbox(
        str(gh), None if prec is None else int(prec))
    return Envelope(xmin, ymin, xmax, ymax).to_polygon()


# SQL scalar registry: SELECT-list ST_* calls resolve here (uppercased
# SQL name -> python fn taking (geometry_value, *literal_args)); the
# SQLSpatialAccessorFunctions / CastFunctions / OutputFunctions /
# GeometryProcessingFunctions surface of the reference
SQL_SCALARS = {
    "ST_X": lambda g: float(g.x),
    "ST_Y": lambda g: float(g.y),
    "ST_AREA": lambda g: g.area,
    "ST_LENGTH": lambda g: g.length,
    "ST_CENTROID": lambda g: g.centroid,
    "ST_ENVELOPE": lambda g: st_envelope(g),
    "ST_GEOMETRYTYPE": lambda g: g.geom_type,
    "ST_ASTEXT": lambda g: st_as_text(g),
    "ST_ASBINARY": st_as_binary,
    "ST_ASGEOJSON": st_as_geojson,
    "ST_CASTTOPOINT": st_cast_to_point,
    "ST_CASTTOLINESTRING": st_cast_to_linestring,
    "ST_CASTTOPOLYGON": st_cast_to_polygon,
    "ST_CASTTOGEOMETRY": st_cast_to_geometry,
    "ST_BUFFER": lambda g, d: st_buffer(g, float(d)),
    "ST_BUFFERPOINT": lambda g, m: st_buffer_point(g, float(m)),
    "ST_CONVEXHULL": lambda g: st_convex_hull(g),
    "ST_TRANSLATE": lambda g, dx, dy: st_translate(g, float(dx),
                                                   float(dy)),
    "ST_DISTANCE": lambda g, other: st_distance(g, other),
    "ST_DISTANCESPHERE": lambda g, o: st_distance_sphere(g, o),
    "ST_DISTANCESPHEROID": lambda g, o: st_distance_spheroid(g, o),
    "ST_CLOSESTPOINT": lambda g, o: st_closest_point(g, o),
    "ST_RELATE": lambda g, o: st_relate(g, o),
    "ST_RELATEBOOL": lambda g, o, p: st_relate_bool(g, o, str(p)),
    "ST_LENGTHSPHEROID": st_length_spheroid,
    "ST_ANTIMERIDIANSAFEGEOM": st_antimeridian_safe_geom,
    "ST_IDLSAFEGEOM": st_idl_safe_geom,
    "ST_GEOHASH": lambda g, prec=25: st_geohash(g, int(prec)),
    "ST_GEOMFROMGEOHASH": lambda gh, prec=None: st_geom_from_geohash(
        gh, None if prec is None else int(prec)),
    "ST_POINTN": lambda g, n: st_point_n(g, int(n)),
    "ST_EXTERIORRING": st_exterior_ring,
    "ST_NUMPOINTS": st_num_points,
    # all-literal constructors: the parser passes '__const__' as the
    # column and the engine broadcasts the constructed value per row
    "ST_MAKEBBOX": lambda *args: st_make_bbox(*(float(a) for a in args)),
    "ST_MAKEPOLYGON": st_make_polygon,
}


def st_convex_hull(g: Geometry) -> Geometry:
    """Monotone-chain convex hull of all vertices."""
    return convex_hull_points(np.vstack(g.coords_list()))


def convex_hull_points(pts: np.ndarray) -> Geometry:
    """Monotone-chain convex hull of an (n, 2) coordinate array — the
    raw form the SQL ConvexHull aggregate pools group members into."""
    pts = np.unique(np.asarray(pts, np.float64), axis=0)
    if len(pts) == 1:
        return Point(*pts[0])
    if len(pts) == 2:
        return LineString(pts)
    pts = pts[np.lexsort((pts[:, 1], pts[:, 0]))]

    def cross2(o, a, b):
        return (a[0] - o[0]) * (b[1] - o[1]) - (a[1] - o[1]) * (b[0] - o[0])

    def half(points):
        out: list[np.ndarray] = []
        for p in points:
            while len(out) >= 2 and cross2(out[-2], out[-1], p) <= 0:
                out.pop()
            out.append(p)
        return out

    lower = half(pts)
    upper = half(pts[::-1])
    hull = np.array(lower[:-1] + upper[:-1])
    if len(hull) < 3:
        return LineString(hull)
    return Polygon(hull)


def st_closest_point(a: Geometry, b: Point) -> Point:
    """Closest point on a to point b (per ring/part — no phantom
    segments bridging separate components)."""
    if isinstance(a, Point):
        return a
    best = None
    best_d2 = np.inf
    for coords in a.coords_list():
        if len(coords) < 2:
            if len(coords) == 1:
                d2 = (b.x - coords[0, 0]) ** 2 + (b.y - coords[0, 1]) ** 2
                if d2 < best_d2:
                    best_d2, best = d2, Point(*coords[0])
            continue
        x0, y0 = coords[:-1, 0], coords[:-1, 1]
        dx, dy = np.diff(coords[:, 0]), np.diff(coords[:, 1])
        len2 = dx * dx + dy * dy
        with np.errstate(divide="ignore", invalid="ignore"):
            t = ((b.x - x0) * dx + (b.y - y0) * dy) / len2
        t = np.where(len2 == 0, 0.0, np.clip(t, 0, 1))
        cx, cy = x0 + t * dx, y0 + t * dy
        d2 = (b.x - cx) ** 2 + (b.y - cy) ** 2
        i = int(np.argmin(d2))
        if d2[i] < best_d2:
            best_d2, best = float(d2[i]), Point(cx[i], cy[i])
    return best


def st_translate(g: Geometry, dx: float, dy: float) -> Geometry:
    import copy
    out = copy.deepcopy(g)

    def shift(geom):
        if isinstance(geom, Point):
            geom.x += dx
            geom.y += dy
        elif isinstance(geom, LineString):
            geom.coords = geom.coords + np.array([dx, dy])
        elif isinstance(geom, Polygon):
            geom.shell = geom.shell + np.array([dx, dy])
            geom.holes = [h + np.array([dx, dy]) for h in geom.holes]
        else:
            for p in geom.parts:
                shift(p)
    shift(out)
    return out


# -- vectorized column forms ----------------------------------------------

def contains_points(g: Geometry, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Vectorized geometry-contains-points (the ST_Contains hot form)."""
    if hasattr(g, "contains_points"):
        return g.contains_points(x, y)
    env = g.envelope
    out = (x >= env.xmin) & (x <= env.xmax) & (y >= env.ymin) & (y <= env.ymax)
    for i in np.flatnonzero(out):
        out[i] = g.contains(Point(x[i], y[i]))
    return out


def distance_points(g: Geometry, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Vectorized distance from geometry to each point (degrees).
    Each ring/part measures separately — vstacking them would create
    phantom bridging segments."""
    if isinstance(g, Point):
        return np.hypot(x - g.x, y - g.y)
    d2 = np.full(np.shape(np.asarray(x, np.float64)), np.inf)
    for coords in g.coords_list():
        if len(coords) == 0:
            continue
        if len(coords) == 1:
            d2 = np.minimum(d2, (x - coords[0, 0]) ** 2 + (y - coords[0, 1]) ** 2)
        else:
            d2 = np.minimum(d2, _point_segments_dist2(x, y, coords))
    d = np.sqrt(d2)
    if hasattr(g, "contains_points"):
        d = np.where(g.contains_points(x, y), 0.0, d)
    return d
