"""L7 analytics: ST_* kernels, spatial joins, KNN, tube select,
WPS-style processes (geomesa-spark-sql + geomesa-process analogs)."""

from . import st_functions
from .join import contains_join, dwithin_join, knn
from .processes import (exterior_ring_process, idl_safe_geom_process,
                        knn_process, length_spheroid_process,
                        minmax_process, num_points_process,
                        point_n_process, proximity_process,
                        translate_process, tube_select_process,
                        unique_process)
from .st_functions import (st_antimeridian_safe_geom, st_idl_safe_geom,
                           st_length_spheroid)
from .tube import TubeBuilder, tube_select_mask

__all__ = ["st_functions", "contains_join", "dwithin_join", "knn",
           "exterior_ring_process", "idl_safe_geom_process",
           "knn_process", "length_spheroid_process", "minmax_process",
           "num_points_process", "point_n_process",
           "proximity_process", "translate_process",
           "tube_select_process", "unique_process",
           "st_antimeridian_safe_geom", "st_idl_safe_geom",
           "st_length_spheroid", "TubeBuilder", "tube_select_mask"]
