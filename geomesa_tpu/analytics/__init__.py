"""L7 analytics: ST_* kernels, spatial joins, KNN, tube select,
WPS-style processes (geomesa-spark-sql + geomesa-process analogs)."""

from . import st_functions
from .join import contains_join, dwithin_join, knn
from .processes import (knn_process, minmax_process, proximity_process,
                        tube_select_process, unique_process)
from .tube import TubeBuilder, tube_select_mask

__all__ = ["st_functions", "contains_join", "dwithin_join", "knn",
           "knn_process", "minmax_process", "proximity_process",
           "tube_select_process", "unique_process", "TubeBuilder",
           "tube_select_mask"]
