"""Spatial joins on device: the ST_DWithin / ST_Contains join kernels.

The reference runs spatial joins via Spark: spatially-partitioned RDDs +
a per-cell sweepline (GeoMesaSparkSQL.scala:312-360, SQLRules
SpatialJoinStrategy:270). On TPU the join is a tiled device kernel:

- the small side (query points / polygons) is padded to a fixed chunk;
- the large side streams through the VPU in one fused program per chunk
  computing the (n x chunk) predicate matrix;
- borderline pairs (within the f32 error band of the threshold) are
  re-checked on host in f64, so results are exact.

Counting and pair-collection both avoid materializing the full bool
matrix on the host: counts reduce on device; pair extraction pulls only
per-chunk hit masks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()

from ..utils.fp import f32_band as _f32_band

__all__ = ["dwithin_join", "contains_join", "knn"]


@jax.jit
def _dwithin_matrices(px, py, qx, qy, qvalid, r2_hi, r2_lo, nrows):
    """(n,) x (k,) -> definite-hit and uncertain-band bool matrices."""
    dx = px[:, None] - qx[None, :]
    dy = py[:, None] - qy[None, :]
    d2 = dx * dx + dy * dy                       # f32, error-banded
    rv = (jnp.arange(px.shape[0]) < nrows)[:, None]
    definite = (d2 <= r2_lo) & qvalid[None, :] & rv
    maybe = (d2 <= r2_hi) & ~definite & qvalid[None, :] & rv
    return definite, maybe


@jax.jit
def _dwithin_counts_all(px, py, qxm, qym, validm, r2_hi, r2_lo, nrows):
    """ALL query chunks in one dispatch: (nchunks, chunk) query tiles
    map over the device sequentially; only the (nchunks, chunk) count
    grids come back. One kernel launch per join, not one per chunk —
    per-dispatch latency (and, under a remote-device tunnel, a network
    round trip) otherwise dominates the scan itself."""
    rv = (jnp.arange(px.shape[0]) < nrows)[:, None]

    def one(args):
        qx, qy, valid = args
        dx = px[:, None] - qx[None, :]
        dy = py[:, None] - qy[None, :]
        d2 = dx * dx + dy * dy
        definite = (d2 <= r2_lo) & valid[None, :] & rv
        maybe = (d2 <= r2_hi) & ~definite & valid[None, :] & rv
        return (jnp.sum(definite, axis=0, dtype=jnp.int32),
                jnp.sum(maybe, axis=0, dtype=jnp.int32))

    return jax.lax.map(one, (qxm, qym, validm))


@jax.jit
def _sorted_by_x(px, nrows):
    """(xs, order): px sorted ascending with its permutation, padded
    rows pushed to +inf so they land at the tail. One dispatch."""
    key = jnp.where(jnp.arange(px.shape[0]) < nrows, px, jnp.inf)
    order = jnp.argsort(key)
    return key[order], order


# device x-sort LRU keyed by the coordinate buffer identity: a store's
# resident column re-resolves bands across many join calls, and the
# sort is the dominant per-call cost. Strong refs keep the keys' ids
# stable; the bound keeps pinned memory to a few tables.
_XSORT_CACHE: list = []


def _sorted_by_x_cached(pxj, nrows, cacheable):
    """`cacheable` is True only for caller-owned resident arrays: a
    per-call upload gets a fresh buffer identity every time, so caching
    it could never hit — it would only evict store entries and pin dead
    device copies."""
    for i, (ref, rn, xs, order) in enumerate(_XSORT_CACHE):
        if ref is pxj and rn == nrows:
            _XSORT_CACHE.append(_XSORT_CACHE.pop(i))
            return xs, order
    xs, order = _sorted_by_x(pxj, np.int32(nrows))
    if cacheable:
        _XSORT_CACHE.append((pxj, nrows, xs, order))
        if len(_XSORT_CACHE) > 4:
            _XSORT_CACHE.pop(0)
    return xs, order


@jax.jit
def _slab_bounds(xs, qb, w):
    """Both slab edges in ONE program: a cold call pays one executable
    load instead of two (each load costs seconds over the tunnel)."""
    los = jnp.searchsorted(xs, qb - w, side="left")
    his = jnp.searchsorted(xs, qb + w, side="right")
    return jnp.stack([los, his])


def _slab_cand_mask(xs, order, los, widths, qxc, qyc, px, py, r2_hi,
                    smax):
    """The shared in-band candidate grid (ONE body for the count and
    compact kernels — the two must never desynchronize)."""
    pos = jnp.clip(los[:, None] + jnp.arange(smax)[None, :], 0,
                   xs.shape[0] - 1)
    rows = order[pos]
    valid = jnp.arange(smax)[None, :] < widths[:, None]
    dx = px[rows] - qxc[:, None]
    dy = py[rows] - qyc[:, None]
    return valid & (dx * dx + dy * dy <= r2_hi)


@functools.partial(jax.jit, static_argnames=("smax",))
def _slab_cand_count(xs, order, los, widths, qxc, qyc, px, py, r2_hi,
                     smax):
    """Count of in-band slab candidates for a chunk of queries — the
    device side of pair materialization (fetching the full slab grid
    over a thin transport costs more than the whole join)."""
    return jnp.sum(_slab_cand_mask(xs, order, los, widths, qxc, qyc,
                                   px, py, r2_hi, smax),
                   dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("smax", "cap"))
def _slab_cand_flat(xs, order, los, widths, qxc, qyc, px, py, r2_hi,
                    smax, cap):
    """Flat (query, slab-col) indices of the in-band candidates,
    compacted on device to ``cap`` slots (-1 padded): transfers are
    O(candidates), never O(grid)."""
    cand = _slab_cand_mask(xs, order, los, widths, qxc, qyc, px, py,
                           r2_hi, smax)
    return jnp.flatnonzero(cand.ravel(), size=cap, fill_value=-1)


@functools.partial(jax.jit, static_argnames=("smax",))
def _slab_rows(xs, order, los, smax):
    """Row ids of up to smax sorted positions starting at each lo —
    the x-slab candidate gather for a batch of banded queries."""
    pos = los[:, None] + jnp.arange(smax)[None, :]
    pos = jnp.clip(pos, 0, xs.shape[0] - 1)
    return order[pos]


# total padded slab-grid ids per gather dispatch (64MB of int32): wide
# radii chunk the banded queries instead of materializing a
# (len(banded), max_width) grid in one shot
_SLAB_GRID_CAP = 1 << 24


def _slab_setup(pxj, n, cacheable, q_x64, radius_deg, r2_hi):
    """Shared slab-phase setup (ONE copy for the banded count
    resolution and pair materialization): device x-sort, slab
    half-width = radius + f32 rounding + band, batched searchsorted.
    Returns (xs, order, los, widths)."""
    xs, order = _sorted_by_x_cached(pxj, n, cacheable)
    eps = float(np.sqrt(max(r2_hi, 0.0))) - radius_deg + 1e-4
    w = radius_deg + eps
    lohi = np.asarray(_slab_bounds(
        xs, jnp.asarray(q_x64.astype(np.float32)), np.float32(w)))
    return xs, order, lohi[0], lohi[1] - lohi[0]


def _resolve_band_counts(pxj, px64, py64, qx64, qy64, banded,
                         radius_deg, r2_hi, n, counts, cacheable):
    """Exact f64 resolution of queries with in-band pairs.

    The candidate set per banded query is its x-slab |x - qx| <= r+eps:
    px sorts ON DEVICE once (f32, padded rows to +inf), a batched
    searchsorted finds every slab, and padded gathers pull just the
    slab row ids to the host for a vectorized f64 distance check — no
    O(n) host work, no (k, n) band matrix. Gathers are bounded at
    _SLAB_GRID_CAP ids each, so wide radii chunk rather than allocate
    a queries x max-width grid."""
    xs, order, los, widths = _slab_setup(pxj, n, cacheable,
                                         qx64[banded], radius_deg,
                                         r2_hi)
    if not len(widths) or widths.max() == 0:
        return
    smax = 1 << int(widths.max() - 1).bit_length()  # pow2: few compiles
    r2 = radius_deg * radius_deg
    qchunk = max(1, _SLAB_GRID_CAP // smax)
    for s in range(0, len(banded), qchunk):
        sel = slice(s, s + qchunk)
        rows = np.asarray(_slab_rows(xs, order,
                                     jnp.asarray(los[sel]), smax))
        for i, qj in enumerate(banded[sel]):
            rr = rows[i, : widths[s + i]]
            rr = rr[rr < n]
            d2 = ((px64[rr] - qx64[qj]) ** 2
                  + (py64[rr] - qy64[qj]) ** 2)
            counts[qj] = int((d2 <= r2).sum())


def _as_device_f32(px64, py64, device_xy):
    """The join's large side on device: adopt caller-provided resident
    f32 columns (e.g. a store's scan_data.xhi/yhi, which are exactly
    f32(x)/f32(y) of the two-float split and may be capacity-padded
    past n) or upload once."""
    if device_xy is not None:
        pxj, pyj = device_xy
        return jnp.asarray(pxj), jnp.asarray(pyj)
    return (jnp.asarray(px64.astype(np.float32)),
            jnp.asarray(py64.astype(np.float32)))


def dwithin_join(px: np.ndarray, py: np.ndarray,
                 qx: np.ndarray, qy: np.ndarray,
                 radius_deg: float, chunk: int = 256,
                 counts_only: bool = False,
                 device_xy=None):
    """Radius join: for each query point, the points within radius_deg
    (planar degrees, matching the rewritten-DWithin semantics).

    Returns (counts[k], pairs) where pairs is an (m, 2) int array of
    (point_idx, query_idx), or (counts, None) with counts_only.

    ``counts_only`` reduces per-query counts fully on device (chunked
    by ``chunk`` queries per dispatch) with only banded queries
    resolved via x-slabs. The pairs path ignores ``chunk``: it runs
    entirely on x-slab candidates — in-band hits compact ON DEVICE and
    only O(candidates) indices cross to the host (a dense verdict
    grid would cost gigabytes of device->host transfer at 100k+ rows
    per side), then exact f64 filters the f32 band.

    ``device_xy`` passes already-device-resident f32 coordinate arrays
    for the large side (possibly capacity-padded beyond len(px); padded
    rows never match). Without it the coordinates upload per call —
    fine for one-off joins, but a store-backed caller should hand over
    its resident columns.
    """
    px64 = np.asarray(px, np.float64)
    py64 = np.asarray(py, np.float64)
    qx64 = np.asarray(qx, np.float64)
    qy64 = np.asarray(qy, np.float64)
    pxj, pyj = _as_device_f32(px64, py64, device_xy)
    n, k = len(px64), len(qx64)
    span = 360.0
    r2_hi, r2_lo = _f32_band(radius_deg, span)
    r2 = radius_deg * radius_deg

    counts = np.zeros(k, dtype=np.int64)
    pair_chunks: list[np.ndarray] = []

    if counts_only:
        nchunks = (k + chunk - 1) // chunk
        qxm = np.zeros((nchunks, chunk), np.float32)
        qym = np.zeros((nchunks, chunk), np.float32)
        validm = np.zeros((nchunks, chunk), bool)
        qxm.ravel()[:k] = qx64
        qym.ravel()[:k] = qy64
        validm.ravel()[:k] = True
        def_counts, band_counts = _dwithin_counts_all(
            pxj, pyj, jnp.asarray(qxm), jnp.asarray(qym),
            jnp.asarray(validm), np.float32(r2_hi), np.float32(r2_lo),
            np.int32(n))
        counts[:] = np.asarray(def_counts).ravel()[:k]
        band_counts = np.asarray(band_counts).ravel()[:k]
        # queries with in-band pairs re-resolve exactly from their
        # device-gathered x-slab candidates (see _resolve_band_counts)
        banded = np.flatnonzero(band_counts)
        if len(banded):
            _resolve_band_counts(pxj, px64, py64, qx64, qy64, banded,
                                 radius_deg, r2_hi, n, counts,
                                 cacheable=device_xy is not None)
        return counts, None

    # pair materialization via bounded x-slabs (same candidate shape as
    # _resolve_band_counts): the old path pulled a DENSE (n, chunk)
    # verdict matrix to the host per chunk — at 100k+ rows per side
    # that is gigabytes of device->host transfer; slabs move only
    # O(candidates) and the exact f64 check vectorizes over the grid
    if n == 0 or k == 0:
        return counts, np.empty((0, 2), dtype=np.int64)
    xs, order, los, widths = _slab_setup(pxj, n, device_xy is not None,
                                         qx64, radius_deg, r2_hi)
    if not len(widths) or widths.max() == 0:
        return counts, np.empty((0, 2), dtype=np.int64)
    smax = 1 << int(widths.max() - 1).bit_length()
    qchunk = max(1, _SLAB_GRID_CAP // smax)
    order_h = np.asarray(order)  # host copy (n int32) for row lookup
    for s in range(0, k, qchunk):
        end = min(s + qchunk, k)
        losj = jnp.asarray(los[s:end])
        wj = jnp.asarray(widths[s:end])
        qxc = jnp.asarray(qx64[s:end].astype(np.float32))
        qyc = jnp.asarray(qy64[s:end].astype(np.float32))
        total = int(_slab_cand_count(xs, order, losj, wj, qxc, qyc,
                                     pxj, pyj, np.float32(r2_hi), smax))
        if not total:
            continue
        cap = 1 << (total - 1).bit_length()
        flat = np.asarray(_slab_cand_flat(
            xs, order, losj, wj, qxc, qyc, pxj, pyj,
            np.float32(r2_hi), smax, cap))
        flat = flat[flat >= 0]
        qi = flat // smax
        ci = flat - qi * smax
        rows = order_h[np.minimum(los[s + qi] + ci, len(order_h) - 1)]
        ok = rows < n
        rows, qi = rows[ok], qi[ok]
        # exact f64 check on just the fetched candidates (the in-band
        # f32 verdict over-approximates)
        exact = ((px64[rows] - qx64[s + qi]) ** 2
                 + (py64[rows] - qy64[s + qi]) ** 2) <= r2
        if exact.any():
            pair_chunks.append(np.stack(
                [rows[exact], s + qi[exact]], axis=1).astype(np.int64))

    pairs = (np.concatenate(pair_chunks, axis=0) if pair_chunks
             else np.empty((0, 2), dtype=np.int64))
    if len(pairs):
        counts[:] = np.bincount(pairs[:, 1], minlength=k)
    return counts, pairs


def contains_join(polygons, px: np.ndarray, py: np.ndarray,
                  counts_only: bool = False):
    """ST_Contains join: points vs many polygons (BASELINE config #5).

    Device kernel: bbox prefilter matrix on device per polygon chunk;
    exact point-in-polygon (vectorized host f64, reference evaluator)
    only for points passing the prefilter of each polygon.
    """
    from .st_functions import contains_points
    px = np.asarray(px, np.float64)
    py = np.asarray(py, np.float64)
    k = len(polygons)
    counts = np.zeros(k, dtype=np.int64)
    pairs: list[np.ndarray] = []
    boxes = np.array([p.envelope.as_tuple() for p in polygons], np.float64)

    pxj = jnp.asarray(px.astype(np.float32))
    pyj = jnp.asarray(py.astype(np.float32))

    @jax.jit
    def prefilter(bx):
        # conservative f32 bbox test: widen by one ulp-scale epsilon
        eps = np.float32(1e-4)
        return ((pxj[:, None] >= bx[None, :, 0] - eps)
                & (pxj[:, None] <= bx[None, :, 2] + eps)
                & (pyj[:, None] >= bx[None, :, 1] - eps)
                & (pyj[:, None] <= bx[None, :, 3] + eps))

    chunk = 64
    for start in range(0, k, chunk):
        end = min(start + chunk, k)
        bx = np.zeros((chunk, 4), np.float32)
        bx[: end - start] = boxes[start:end]
        bx[end - start:] = [1e9, 1e9, -1e9, -1e9]
        cand = np.asarray(prefilter(jnp.asarray(bx)))
        for j in range(end - start):
            rows = np.flatnonzero(cand[:, j])
            if len(rows) == 0:
                continue
            poly = polygons[start + j]
            if len(rows) >= 2_000_000:
                # dense case: device crossing-number kernel with exact
                # host recheck only in the edge band (scan/gscan.py).
                # Below this the vectorized host test beats the
                # dispatch round trip (same crossover as the store's
                # _DEVICE_PIP_ROWS)
                from ..scan.gscan import points_in_polygon
                hit = points_in_polygon(px[rows], py[rows], poly)
            else:
                hit = contains_points(poly, px[rows], py[rows])
            rows = rows[hit]
            counts[start + j] = len(rows)
            if not counts_only and len(rows):
                pairs.append(np.stack(
                    [rows, np.full(len(rows), start + j)], axis=1))
    if counts_only:
        return counts, None
    return counts, (np.concatenate(pairs, axis=0) if pairs
                    else np.empty((0, 2), dtype=np.int64))


@functools.partial(jax.jit, static_argnames=("k",))
def _knn_kernel(px, py, qx, qy, k: int, nrows=None):
    d2 = (px - qx) ** 2 + (py - qy) ** 2
    if nrows is not None:
        # capacity-padded resident columns: padded rows never win
        d2 = jnp.where(jnp.arange(px.shape[0]) < nrows, d2, jnp.inf)
    n = d2.shape[0]
    bs = 16384
    if n > 4 * bs:
        # two-stage exact top-k: per-block top-k batched over blocks
        # (the vectorized shape the TPU sorts fast), then a final
        # top-k over nb*k candidates — the single flat top_k over
        # 50M+ elements lowers to a full-array sort and dominates the
        # whole query
        nb = (n + bs - 1) // bs
        pad = nb * bs - n
        d2p = jnp.pad(d2, (0, pad), constant_values=jnp.inf)
        kb = min(k, bs)
        neg, loc = jax.lax.top_k(-d2p.reshape(nb, bs), kb)
        cand_idx = (jnp.arange(nb)[:, None] * bs + loc).ravel()
        neg2, loc2 = jax.lax.top_k(neg.ravel(), k)
        return -neg2, cand_idx[loc2]
    neg, idx = jax.lax.top_k(-d2, k)
    return -neg, idx


def knn(px: np.ndarray, py: np.ndarray, qx: float, qy: float,
        k: int, device_xy=None) -> tuple[np.ndarray, np.ndarray]:
    """k nearest points to (qx, qy): full-scan distance + device top_k.

    The reference's KNNQuery iteratively expands a geohash spiral
    (process/knn/KNNQuery.scala:27) to avoid touching all rows; at TPU
    scan rates the full scan IS the fast path — one fused kernel, no
    iteration. Returns (distances_deg, indices) sorted ascending.

    f32 distances can tie/misorder within ~1e-5 deg; the top-(k + pad)
    candidates re-rank on host in f64 for exact order. ``device_xy``
    passes resident f32 columns (see dwithin_join) so a store-backed
    KNN never re-uploads its table.
    """
    pad = min(len(px), k + 32)
    pxj, pyj = _as_device_f32(np.asarray(px, np.float64),
                              np.asarray(py, np.float64), device_xy)
    d2, idx = _knn_kernel(pxj, pyj, np.float32(qx), np.float32(qy),
                          pad, np.int32(len(px)))
    idx = np.asarray(idx)
    dx = np.asarray(px, np.float64)[idx] - qx
    dy = np.asarray(py, np.float64)[idx] - qy
    exact = np.sqrt(dx * dx + dy * dy)
    order = np.argsort(exact, kind="stable")[:k]
    return exact[order], idx[order]
