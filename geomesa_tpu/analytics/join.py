"""Spatial joins on device: the ST_DWithin / ST_Contains join kernels.

The reference runs spatial joins via Spark: spatially-partitioned RDDs +
a per-cell sweepline (GeoMesaSparkSQL.scala:312-360, SQLRules
SpatialJoinStrategy:270). On TPU the join is a tiled device kernel:

- the small side (query points / polygons) is padded to a fixed chunk;
- the large side streams through the VPU in one fused program per chunk
  computing the (n x chunk) predicate matrix;
- borderline pairs (within the f32 error band of the threshold) are
  re-checked on host in f64, so results are exact.

Counting and pair-collection both avoid materializing the full bool
matrix on the host: counts reduce on device; pair extraction pulls only
per-chunk hit masks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcache import ensure_compile_cache

ensure_compile_cache()

from ..scan.gscan import EDGE_EPS
from ..scan.zscan import next_pow2, stack_points
from ..utils.fp import f32_band as _f32_band

__all__ = ["dwithin_join", "contains_join", "knn", "knn_batched",
           "pack_polygon_batch", "prewarm_join_kernels", "psum_counts"]


def psum_counts(leg_counts) -> int:
    """psum-style reduce of per-shard join match counts: the z-prefix
    partition of the scattered side is disjoint and covering, so the
    cluster-wide broadcast-join count is exactly the sum of leg
    counts — the host-side analog of a ``jax.lax.psum`` over the
    shard axis."""
    return int(sum(int(c) for c in leg_counts))


@jax.jit
def _dwithin_matrices(px, py, qx, qy, qvalid, r2_hi, r2_lo, nrows):
    """(n,) x (k,) -> definite-hit and uncertain-band bool matrices."""
    dx = px[:, None] - qx[None, :]
    dy = py[:, None] - qy[None, :]
    d2 = dx * dx + dy * dy                       # f32, error-banded
    rv = (jnp.arange(px.shape[0]) < nrows)[:, None]
    definite = (d2 <= r2_lo) & qvalid[None, :] & rv
    maybe = (d2 <= r2_hi) & ~definite & qvalid[None, :] & rv
    return definite, maybe


@jax.jit
def _dwithin_counts_all(px, py, qxm, qym, validm, r2_hi, r2_lo, nrows):
    """ALL query chunks in one dispatch: (nchunks, chunk) query tiles
    map over the device sequentially; only the (nchunks, chunk) count
    grids come back. One kernel launch per join, not one per chunk —
    per-dispatch latency (and, under a remote-device tunnel, a network
    round trip) otherwise dominates the scan itself."""
    rv = (jnp.arange(px.shape[0]) < nrows)[:, None]

    def one(args):
        qx, qy, valid = args
        dx = px[:, None] - qx[None, :]
        dy = py[:, None] - qy[None, :]
        d2 = dx * dx + dy * dy
        definite = (d2 <= r2_lo) & valid[None, :] & rv
        maybe = (d2 <= r2_hi) & ~definite & valid[None, :] & rv
        return (jnp.sum(definite, axis=0, dtype=jnp.int32),
                jnp.sum(maybe, axis=0, dtype=jnp.int32))

    return jax.lax.map(one, (qxm, qym, validm))


@jax.jit
def _sorted_by_x(px, nrows):
    """(xs, order): px sorted ascending with its permutation, padded
    rows pushed to +inf so they land at the tail. One dispatch."""
    key = jnp.where(jnp.arange(px.shape[0]) < nrows, px, jnp.inf)
    order = jnp.argsort(key)
    return key[order], order


# device x-sort LRU keyed by the coordinate buffer identity: a store's
# resident column re-resolves bands across many join calls, and the
# sort is the dominant per-call cost. Strong refs keep the keys' ids
# stable; the bound keeps pinned memory to a few tables.
_XSORT_CACHE: list = []


def _sorted_by_x_cached(pxj, nrows, cacheable):
    """`cacheable` is True only for caller-owned resident arrays: a
    per-call upload gets a fresh buffer identity every time, so caching
    it could never hit — it would only evict store entries and pin dead
    device copies."""
    for i, (ref, rn, xs, order) in enumerate(_XSORT_CACHE):
        if ref is pxj and rn == nrows:
            _XSORT_CACHE.append(_XSORT_CACHE.pop(i))
            return xs, order
    xs, order = _sorted_by_x(pxj, np.int32(nrows))
    if cacheable:
        _XSORT_CACHE.append((pxj, nrows, xs, order))
        if len(_XSORT_CACHE) > 4:
            _XSORT_CACHE.pop(0)
    return xs, order


@jax.jit
def _slab_bounds(xs, qb, w):
    """Both slab edges in ONE program: a cold call pays one executable
    load instead of two (each load costs seconds over the tunnel)."""
    los = jnp.searchsorted(xs, qb - w, side="left")
    his = jnp.searchsorted(xs, qb + w, side="right")
    return jnp.stack([los, his])


def _slab_cand_mask(xs, order, los, widths, qxc, qyc, px, py, r2_hi,
                    smax):
    """The shared in-band candidate grid (ONE body for the count and
    compact kernels — the two must never desynchronize)."""
    pos = jnp.clip(los[:, None] + jnp.arange(smax)[None, :], 0,
                   xs.shape[0] - 1)
    rows = order[pos]
    valid = jnp.arange(smax)[None, :] < widths[:, None]
    dx = px[rows] - qxc[:, None]
    dy = py[rows] - qyc[:, None]
    return valid & (dx * dx + dy * dy <= r2_hi)


@functools.partial(jax.jit, static_argnames=("smax",))
def _slab_cand_count(xs, order, los, widths, qxc, qyc, px, py, r2_hi,
                     smax):
    """Count of in-band slab candidates for a chunk of queries — the
    device side of pair materialization (fetching the full slab grid
    over a thin transport costs more than the whole join)."""
    return jnp.sum(_slab_cand_mask(xs, order, los, widths, qxc, qyc,
                                   px, py, r2_hi, smax),
                   dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("smax", "cap"))
def _slab_cand_flat(xs, order, los, widths, qxc, qyc, px, py, r2_hi,
                    smax, cap):
    """Flat (query, slab-col) indices of the in-band candidates,
    compacted on device to ``cap`` slots (-1 padded): transfers are
    O(candidates), never O(grid)."""
    cand = _slab_cand_mask(xs, order, los, widths, qxc, qyc, px, py,
                           r2_hi, smax)
    return jnp.flatnonzero(cand.ravel(), size=cap, fill_value=-1)


@functools.partial(jax.jit, static_argnames=("smax",))
def _slab_rows(xs, order, los, smax):
    """Row ids of up to smax sorted positions starting at each lo —
    the x-slab candidate gather for a batch of banded queries."""
    pos = los[:, None] + jnp.arange(smax)[None, :]
    pos = jnp.clip(pos, 0, xs.shape[0] - 1)
    return order[pos]


# total padded slab-grid ids per gather dispatch (64MB of int32): wide
# radii chunk the banded queries instead of materializing a
# (len(banded), max_width) grid in one shot
_SLAB_GRID_CAP = 1 << 24


def _slab_setup(pxj, n, cacheable, q_x64, radius_deg, r2_hi):
    """Shared slab-phase setup (ONE copy for the banded count
    resolution and pair materialization): device x-sort, slab
    half-width = radius + f32 rounding + band, batched searchsorted.
    Returns (xs, order, los, widths)."""
    xs, order = _sorted_by_x_cached(pxj, n, cacheable)
    eps = float(np.sqrt(max(r2_hi, 0.0))) - radius_deg + 1e-4
    w = radius_deg + eps
    lohi = np.asarray(_slab_bounds(
        xs, jnp.asarray(q_x64.astype(np.float32)), np.float32(w)))
    return xs, order, lohi[0], lohi[1] - lohi[0]


def _resolve_band_counts(pxj, px64, py64, qx64, qy64, banded,
                         radius_deg, r2_hi, n, counts, cacheable):
    """Exact f64 resolution of queries with in-band pairs.

    The candidate set per banded query is its x-slab |x - qx| <= r+eps:
    px sorts ON DEVICE once (f32, padded rows to +inf), a batched
    searchsorted finds every slab, and padded gathers pull just the
    slab row ids to the host for a vectorized f64 distance check — no
    O(n) host work, no (k, n) band matrix. Gathers are bounded at
    _SLAB_GRID_CAP ids each, so wide radii chunk rather than allocate
    a queries x max-width grid."""
    xs, order, los, widths = _slab_setup(pxj, n, cacheable,
                                         qx64[banded], radius_deg,
                                         r2_hi)
    if not len(widths) or widths.max() == 0:
        return
    smax = 1 << int(widths.max() - 1).bit_length()  # pow2: few compiles
    r2 = radius_deg * radius_deg
    qchunk = max(1, _SLAB_GRID_CAP // smax)
    for s in range(0, len(banded), qchunk):
        sel = slice(s, s + qchunk)
        rows = np.asarray(_slab_rows(xs, order,
                                     jnp.asarray(los[sel]), smax))
        for i, qj in enumerate(banded[sel]):
            rr = rows[i, : widths[s + i]]
            rr = rr[rr < n]
            d2 = ((px64[rr] - qx64[qj]) ** 2
                  + (py64[rr] - qy64[qj]) ** 2)
            counts[qj] = int((d2 <= r2).sum())


def _as_device_f32(px64, py64, device_xy):
    """The join's large side on device: adopt caller-provided resident
    f32 columns (e.g. a store's scan_data.xhi/yhi, which are exactly
    f32(x)/f32(y) of the two-float split and may be capacity-padded
    past n) or upload once."""
    if device_xy is not None:
        pxj, pyj = device_xy
        return jnp.asarray(pxj), jnp.asarray(pyj)
    return (jnp.asarray(px64.astype(np.float32)),
            jnp.asarray(py64.astype(np.float32)))


def dwithin_join(px: np.ndarray, py: np.ndarray,
                 qx: np.ndarray, qy: np.ndarray,
                 radius_deg: float, chunk: int = 256,
                 counts_only: bool = False,
                 device_xy=None):
    """Radius join: for each query point, the points within radius_deg
    (planar degrees, matching the rewritten-DWithin semantics).

    Returns (counts[k], pairs) where pairs is an (m, 2) int array of
    (point_idx, query_idx), or (counts, None) with counts_only.

    ``counts_only`` reduces per-query counts fully on device (chunked
    by ``chunk`` queries per dispatch) with only banded queries
    resolved via x-slabs. The pairs path ignores ``chunk``: it runs
    entirely on x-slab candidates — in-band hits compact ON DEVICE and
    only O(candidates) indices cross to the host (a dense verdict
    grid would cost gigabytes of device->host transfer at 100k+ rows
    per side), then exact f64 filters the f32 band.

    ``device_xy`` passes already-device-resident f32 coordinate arrays
    for the large side (possibly capacity-padded beyond len(px); padded
    rows never match). Without it the coordinates upload per call —
    fine for one-off joins, but a store-backed caller should hand over
    its resident columns.
    """
    px64 = np.asarray(px, np.float64)
    py64 = np.asarray(py, np.float64)
    qx64 = np.asarray(qx, np.float64)
    qy64 = np.asarray(qy, np.float64)
    pxj, pyj = _as_device_f32(px64, py64, device_xy)
    n, k = len(px64), len(qx64)
    span = 360.0
    r2_hi, r2_lo = _f32_band(radius_deg, span)
    r2 = radius_deg * radius_deg

    counts = np.zeros(k, dtype=np.int64)
    pair_chunks: list[np.ndarray] = []

    if counts_only:
        nchunks = (k + chunk - 1) // chunk
        qxm = np.zeros((nchunks, chunk), np.float32)
        qym = np.zeros((nchunks, chunk), np.float32)
        validm = np.zeros((nchunks, chunk), bool)
        qxm.ravel()[:k] = qx64
        qym.ravel()[:k] = qy64
        validm.ravel()[:k] = True
        def_counts, band_counts = _dwithin_counts_all(
            pxj, pyj, jnp.asarray(qxm), jnp.asarray(qym),
            jnp.asarray(validm), np.float32(r2_hi), np.float32(r2_lo),
            np.int32(n))
        counts[:] = np.asarray(def_counts).ravel()[:k]
        band_counts = np.asarray(band_counts).ravel()[:k]
        # queries with in-band pairs re-resolve exactly from their
        # device-gathered x-slab candidates (see _resolve_band_counts)
        banded = np.flatnonzero(band_counts)
        if len(banded):
            _resolve_band_counts(pxj, px64, py64, qx64, qy64, banded,
                                 radius_deg, r2_hi, n, counts,
                                 cacheable=device_xy is not None)
        return counts, None

    # pair materialization via bounded x-slabs (same candidate shape as
    # _resolve_band_counts): the old path pulled a DENSE (n, chunk)
    # verdict matrix to the host per chunk — at 100k+ rows per side
    # that is gigabytes of device->host transfer; slabs move only
    # O(candidates) and the exact f64 check vectorizes over the grid
    if n == 0 or k == 0:
        return counts, np.empty((0, 2), dtype=np.int64)
    xs, order, los, widths = _slab_setup(pxj, n, device_xy is not None,
                                         qx64, radius_deg, r2_hi)
    if not len(widths) or widths.max() == 0:
        return counts, np.empty((0, 2), dtype=np.int64)
    smax = 1 << int(widths.max() - 1).bit_length()
    qchunk = max(1, _SLAB_GRID_CAP // smax)
    order_h = np.asarray(order)  # host copy (n int32) for row lookup
    for s in range(0, k, qchunk):
        end = min(s + qchunk, k)
        losj = jnp.asarray(los[s:end])
        wj = jnp.asarray(widths[s:end])
        qxc = jnp.asarray(qx64[s:end].astype(np.float32))
        qyc = jnp.asarray(qy64[s:end].astype(np.float32))
        total = int(_slab_cand_count(xs, order, losj, wj, qxc, qyc,
                                     pxj, pyj, np.float32(r2_hi), smax))
        if not total:
            continue
        cap = 1 << (total - 1).bit_length()
        flat = np.asarray(_slab_cand_flat(
            xs, order, losj, wj, qxc, qyc, pxj, pyj,
            np.float32(r2_hi), smax, cap))
        flat = flat[flat >= 0]
        qi = flat // smax
        ci = flat - qi * smax
        rows = order_h[np.minimum(los[s + qi] + ci, len(order_h) - 1)]
        ok = rows < n
        rows, qi = rows[ok], qi[ok]
        # exact f64 check on just the fetched candidates (the in-band
        # f32 verdict over-approximates)
        exact = ((px64[rows] - qx64[s + qi]) ** 2
                 + (py64[rows] - qy64[s + qi]) ** 2) <= r2
        if exact.any():
            pair_chunks.append(np.stack(
                [rows[exact], s + qi[exact]], axis=1).astype(np.int64))

    pairs = (np.concatenate(pair_chunks, axis=0) if pair_chunks
             else np.empty((0, 2), dtype=np.int64))
    if len(pairs):
        counts[:] = np.bincount(pairs[:, 1], minlength=k)
    return counts, pairs


# -- ST_Contains join ------------------------------------------------------

def _poly_edges(poly) -> np.ndarray:
    """One polygon/multipolygon's rings as an (e, 4) f64 segment list
    [x0 y0 x1 y1] — scan/gscan.pack_polygon's packing, host-side.
    Holes are included: crossing-number parity handles them uniformly.
    """
    rings: list[np.ndarray] = []
    for p in getattr(poly, "parts", [poly]):
        rings.append(np.asarray(p.shell, np.float64))
        for h in getattr(p, "holes", []):
            rings.append(np.asarray(h, np.float64))
    segs = []
    for ring in rings:
        a = ring[:-1] if np.allclose(ring[0], ring[-1]) else ring
        b = np.roll(a, -1, axis=0)
        segs.append(np.concatenate([a, b], axis=1))
    return (np.concatenate(segs, axis=0) if segs
            else np.zeros((0, 4), np.float64))


def _poly_pad(k: int) -> int:
    """Polygon-batch shape class: pow2 up to 1024, then the next 1024
    multiple — bounds padding waste at large k while keeping the
    compile-cache class family small."""
    return next_pow2(k) if k <= 1024 else ((k + 1023) // 1024) * 1024


def pack_polygon_batch(polygons, pad_to: int | None = None):
    """Stack every polygon's edges into one batched-geometry layout:
    (kp, ne, 4) f32 edges + (kp, ne) valid + (kp, 4) f32 envelopes,
    pow2-padded on the edge dim and padded to ``pad_to`` polygons.
    Padding rows carry an inverted envelope and no edges — they match
    nothing. Shared by the slab kernel and the mesh shard_map kernel.
    """
    k = len(polygons)
    kp = max(pad_to or k, k, 1)
    elist = [_poly_edges(p) for p in polygons]
    ne = next_pow2(max((len(e) for e in elist), default=1) or 1)
    edges = np.zeros((kp, ne, 4), np.float32)
    evalid = np.zeros((kp, ne), dtype=bool)
    boxes = np.full((kp, 4), 1e9, np.float32)
    boxes[:, 2:] = -1e9
    for i, e in enumerate(elist):
        edges[i, : len(e)] = e
        evalid[i, : len(e)] = True
        boxes[i] = polygons[i].envelope.as_tuple()
    return edges, evalid, boxes


def _pip_body(x, y, edges, evalid):
    """f32 crossing-number + uncertainty band for a coordinate block vs
    ONE polygon's padded edges — scan/gscan._pip_kernel's arithmetic,
    kept identical so both device PIP paths share one exactness
    contract (band rows re-check on host in f64)."""
    x0 = edges[None, :, 0]
    y0 = edges[None, :, 1]
    x1 = edges[None, :, 2]
    y1 = edges[None, :, 3]
    pxc = x[:, None]
    pyc = y[:, None]
    cond = (y0 > pyc) != (y1 > pyc)
    dy = jnp.where(y1 == y0, jnp.float32(1e-30), y1 - y0)
    xint = x0 + (pyc - y0) * (x1 - x0) / dy
    cross = cond & (pxc < xint) & evalid[None, :]
    inside = (jnp.sum(cross, axis=1) % 2) == 1

    ex = x1 - x0
    ey = y1 - y0
    len2 = ex * ex + ey * ey
    t = jnp.clip(((pxc - x0) * ex + (pyc - y0) * ey)
                 / jnp.where(len2 == 0, jnp.float32(1.0), len2), 0.0, 1.0)
    dxv = pxc - (x0 + t * ex)
    dyv = pyc - (y0 + t * ey)
    d2 = dxv * dxv + dyv * dyv
    d2 = jnp.where(evalid[None, :], d2, jnp.float32(np.inf))
    band = jnp.min(d2, axis=1) < jnp.float32(EDGE_EPS * EDGE_EPS)
    return inside, band


@functools.partial(jax.jit, static_argnames=("smax", "band_cap"))
def _contains_counts_all(xs, order, los, widths, boxes, edges, evalid,
                         px, py, nrows, smax, band_cap):
    """ALL polygons in ONE dispatch: lax.map over the padded polygon
    batch; each step gathers its x-slab candidates, runs the bbox test
    and the f32 crossing-number PIP, and reduces on device to
    (definite_count, band_count, up to band_cap band row ids). Only
    O(kp * band_cap) scalars cross the tunnel — never the (n, k)
    verdict matrix that made the old path transfer-bound."""
    eps = jnp.float32(EDGE_EPS)
    cols = jnp.arange(smax)

    def one(args):
        lo, width, bx, e, ev = args
        pos = jnp.clip(lo + cols, 0, xs.shape[0] - 1)
        rows = order[pos]
        x = px[rows]
        y = py[rows]
        ok = (cols < width) & (rows < nrows)
        inbox = (ok & (x >= bx[0] - eps) & (x <= bx[2] + eps)
                 & (y >= bx[1] - eps) & (y <= bx[3] + eps))
        inside, band = _pip_body(x, y, e, ev)
        definite = inbox & inside & ~band
        banded = inbox & band
        bpos = jnp.flatnonzero(banded, size=band_cap, fill_value=-1)
        brow = jnp.where(bpos >= 0,
                         rows[jnp.clip(bpos, 0, smax - 1)], -1)
        return (jnp.sum(definite, dtype=jnp.int32),
                jnp.sum(banded, dtype=jnp.int32),
                brow.astype(jnp.int32))

    return jax.lax.map(one, (los, widths, boxes, edges, evalid))


@functools.partial(jax.jit, static_argnames=("smax", "cap"))
def _contains_band_rows(xs, order, lo, width, bx, e, ev, px, py, nrows,
                        smax, cap):
    """Band-row re-extraction for ONE polygon whose band overflowed the
    batched kernel's band_cap (rare: band rows are points within
    EDGE_EPS of the boundary)."""
    eps = jnp.float32(EDGE_EPS)
    cols = jnp.arange(smax)
    pos = jnp.clip(lo + cols, 0, xs.shape[0] - 1)
    rows = order[pos]
    x = px[rows]
    y = py[rows]
    ok = (cols < width) & (rows < nrows)
    inbox = (ok & (x >= bx[0] - eps) & (x <= bx[2] + eps)
             & (y >= bx[1] - eps) & (y <= bx[3] + eps))
    _, band = _pip_body(x, y, e, ev)
    bpos = jnp.flatnonzero(inbox & band, size=cap, fill_value=-1)
    return jnp.where(bpos >= 0, rows[jnp.clip(bpos, 0, smax - 1)], -1)


def _contains_cand_mask(xs, order, los, widths, boxes, px, py, nrows,
                        smax):
    """Shared bbox-candidate grid for the pairs path (the count and
    compact kernels must never desynchronize — same contract as
    _slab_cand_mask)."""
    eps = jnp.float32(EDGE_EPS)
    pos = jnp.clip(los[:, None] + jnp.arange(smax)[None, :], 0,
                   xs.shape[0] - 1)
    rows = order[pos]
    x = px[rows]
    y = py[rows]
    ok = ((jnp.arange(smax)[None, :] < widths[:, None])
          & (rows < nrows))
    return (ok & (x >= boxes[:, None, 0] - eps)
            & (x <= boxes[:, None, 2] + eps)
            & (y >= boxes[:, None, 1] - eps)
            & (y <= boxes[:, None, 3] + eps))


@functools.partial(jax.jit, static_argnames=("smax",))
def _contains_cand_count(xs, order, los, widths, boxes, px, py, nrows,
                         smax):
    return jnp.sum(_contains_cand_mask(xs, order, los, widths, boxes,
                                       px, py, nrows, smax),
                   dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("smax", "cap"))
def _contains_cand_flat(xs, order, los, widths, boxes, px, py, nrows,
                        smax, cap):
    cand = _contains_cand_mask(xs, order, los, widths, boxes, px, py,
                               nrows, smax)
    return jnp.flatnonzero(cand.ravel(), size=cap, fill_value=-1)


def _contains_slab_setup(xs, boxes64):
    """Per-polygon x-slabs from envelope centers: slab half-width =
    envelope half-width + 2*EDGE_EPS, which dominates both the bbox
    widening eps and the f32 rounding of center/half (~1.5e-5 deg), so
    every point passing the widened f32 bbox test lies in its slab."""
    cxs = (boxes64[:, 0] + boxes64[:, 2]) * 0.5
    half = (boxes64[:, 2] - boxes64[:, 0]) * 0.5 + 2.0 * EDGE_EPS
    lohi = np.asarray(_slab_bounds(
        xs, jnp.asarray(cxs.astype(np.float32)),
        jnp.asarray(half.astype(np.float32))))
    return lohi[0], lohi[1] - lohi[0]


def contains_join(polygons, px: np.ndarray, py: np.ndarray,
                  counts_only: bool = False, device_xy=None):
    """ST_Contains join: points vs many polygons (BASELINE config #5).

    Counts path: ONE fused dispatch — lax.map over the pow2-padded
    polygon batch; per polygon an x-slab candidate gather (the dwithin
    slab machinery; the device x-sort caches per resident buffer), the
    f32 crossing-number PIP with gscan's EDGE_EPS uncertainty band, and
    a device reduce to (definite, band) counts plus band row ids. Only
    O(k) counts and O(band) rows cross to the host; band rows re-check
    in exact f64 (closed-boundary contains_points semantics), so counts
    are exact by the same contract as scan/gscan.points_in_polygon.
    The replaced implementation fetched a dense (n, 64) bbox matrix to
    the host per polygon chunk — gigabytes of device->host transfer at
    100M rows, which is what regressed config 5.

    Pairs path: device count-then-compact of bbox candidates per slab
    grid chunk (O(candidates) transfer), exact host f64 PIP per
    candidate.

    ``device_xy`` passes resident f32 columns (see dwithin_join).
    """
    from .st_functions import contains_points
    px64 = np.asarray(px, np.float64)
    py64 = np.asarray(py, np.float64)
    k = len(polygons)
    n = len(px64)
    counts = np.zeros(k, dtype=np.int64)
    empty = None if counts_only else np.empty((0, 2), dtype=np.int64)
    if k == 0 or n == 0:
        return counts, empty

    boxes64 = np.array([p.envelope.as_tuple() for p in polygons],
                       np.float64).reshape(k, 4)
    pxj, pyj = _as_device_f32(px64, py64, device_xy)
    xs, order = _sorted_by_x_cached(pxj, n, device_xy is not None)
    los, widths = _contains_slab_setup(xs, boxes64)
    wmax = int(widths.max()) if len(widths) else 0
    if wmax == 0:
        return counts, empty
    smax = 1 << (wmax - 1).bit_length()

    if counts_only:
        kp = _poly_pad(k)
        edges, evalid, boxes32 = pack_polygon_batch(polygons, pad_to=kp)
        losp = np.zeros(kp, los.dtype)
        widthsp = np.zeros(kp, widths.dtype)
        losp[:k] = los
        widthsp[:k] = widths
        band_cap = 256
        dc, bc, brows = _contains_counts_all(
            xs, order, jnp.asarray(losp), jnp.asarray(widthsp),
            jnp.asarray(boxes32), jnp.asarray(edges),
            jnp.asarray(evalid), pxj, pyj, np.int32(n), smax, band_cap)
        counts[:] = np.asarray(dc)[:k]
        bc = np.asarray(bc)[:k]
        brows = np.asarray(brows)[:k]
        for j in np.flatnonzero(bc):
            rows_j = brows[j]
            rows_j = rows_j[rows_j >= 0]
            if int(bc[j]) > band_cap:
                cap = 1 << (int(bc[j]) - 1).bit_length()
                rows_j = np.asarray(_contains_band_rows(
                    xs, order, np.int32(los[j]), np.int32(widths[j]),
                    jnp.asarray(boxes32[j]), jnp.asarray(edges[j]),
                    jnp.asarray(evalid[j]), pxj, pyj, np.int32(n),
                    smax, cap))
                rows_j = rows_j[rows_j >= 0]
            hit = contains_points(polygons[j], px64[rows_j],
                                  py64[rows_j])
            counts[j] += int(hit.sum())
        return counts, None

    # pairs: bbox candidates compact on device per slab-grid chunk,
    # then the exact host PIP decides each candidate in f64 (no band
    # machinery needed — every candidate is checked exactly)
    pair_chunks: list[np.ndarray] = []
    qchunk = max(1, _SLAB_GRID_CAP // smax)
    order_h = np.asarray(order)
    boxes32 = boxes64.astype(np.float32)
    for s in range(0, k, qchunk):
        end = min(s + qchunk, k)
        losj = jnp.asarray(los[s:end])
        wj = jnp.asarray(widths[s:end])
        bxj = jnp.asarray(boxes32[s:end])
        total = int(_contains_cand_count(xs, order, losj, wj, bxj,
                                         pxj, pyj, np.int32(n), smax))
        if not total:
            continue
        cap = 1 << (total - 1).bit_length()
        flat = np.asarray(_contains_cand_flat(
            xs, order, losj, wj, bxj, pxj, pyj, np.int32(n), smax, cap))
        flat = flat[flat >= 0]
        qi = flat // smax
        ci = flat - qi * smax
        rows = order_h[np.minimum(los[s + qi] + ci, len(order_h) - 1)]
        ok = rows < n
        rows, qi = rows[ok], qi[ok]
        for j in range(s, end):
            sel = rows[qi == j - s]
            if not len(sel):
                continue
            hit = contains_points(polygons[j], px64[sel], py64[sel])
            sel = sel[hit]
            counts[j] = len(sel)
            if len(sel):
                pair_chunks.append(np.stack(
                    [sel, np.full(len(sel), j)], axis=1).astype(np.int64))
    pairs = (np.concatenate(pair_chunks, axis=0) if pair_chunks
             else np.empty((0, 2), dtype=np.int64))
    return counts, pairs


@functools.partial(jax.jit, static_argnames=("k",))
def _knn_kernel(px, py, qx, qy, k: int, nrows):
    """Fused MULTI-query top-k: qx/qy are a pow2-padded (Q,) query
    batch; lax.map runs the per-query two-stage top-k sequentially
    inside ONE compiled program, so a Q-query KNN pays one kernel
    launch (one tunnel round trip) instead of Q. The body compiles once
    per (capacity, Q-class, k-class) triple and keys stably into the
    persistent compilation cache."""
    rv = jnp.arange(px.shape[0]) < nrows

    def one(q):
        qxi, qyi = q
        d2 = (px - qxi) ** 2 + (py - qyi) ** 2
        # capacity-padded resident columns: padded rows never win
        d2 = jnp.where(rv, d2, jnp.inf)
        n = d2.shape[0]
        bs = 16384
        if n > 4 * bs:
            # two-stage exact top-k: per-block top-k batched over
            # blocks (the vectorized shape the TPU sorts fast), then a
            # final top-k over nb*k candidates — a single flat top_k
            # over 50M+ elements lowers to a full-array sort and
            # dominates the whole query
            nb = (n + bs - 1) // bs
            pad = nb * bs - n
            d2p = jnp.pad(d2, (0, pad), constant_values=jnp.inf)
            kb = min(k, bs)
            neg, loc = jax.lax.top_k(-d2p.reshape(nb, bs), kb)
            cand_idx = (jnp.arange(nb)[:, None] * bs + loc).ravel()
            neg2, loc2 = jax.lax.top_k(neg.ravel(), k)
            return -neg2, cand_idx[loc2]
        neg, idx = jax.lax.top_k(-d2, k)
        return -neg, idx

    return jax.lax.map(one, (qx, qy))


def knn_batched(px: np.ndarray, py: np.ndarray,
                qx, qy, k: int, device_xy=None
                ) -> tuple[np.ndarray, np.ndarray]:
    """Multi-query KNN: ONE fused device dispatch answers all Q query
    points (the reference KNearestNeighborSearchProcess takes a
    *collection* of query features for the same reason — per-query
    overhead dominates). Returns (distances (Q, k), indices (Q, k)),
    each row ascending by exact f64 distance.

    The query batch pads to a pow2 (scan/zscan.stack_points) and the
    candidate count to the pow2 class next_pow2(k + 32), so every
    (capacity, Q, k) shape class keys stably into the persistent
    compilation cache and a prewarmed table answers its first query
    without compiling.

    Ties are ID-STABLE: XLA's top_k prefers the lower index on equal
    values, and the host f64 re-rank sorts (distance, id)
    lexicographically — equal-distance points at the k boundary resolve
    to the smallest row ids, deterministically, in the batched and
    single-query paths alike.
    """
    px64 = np.asarray(px, np.float64)
    py64 = np.asarray(py, np.float64)
    qx64 = np.atleast_1d(np.asarray(qx, np.float64))
    qy64 = np.atleast_1d(np.asarray(qy, np.float64))
    nq = len(qx64)
    n = len(px64)
    k = min(k, n)
    if nq == 0 or k <= 0:
        return (np.zeros((nq, max(k, 0))),
                np.zeros((nq, max(k, 0)), np.int64))
    pxj, pyj = _as_device_f32(px64, py64, device_xy)
    kpad = min(next_pow2(k + 32), int(pxj.shape[0]))
    qxp, qyp, _ = stack_points(qx64, qy64)
    d2, idx = _knn_kernel(pxj, pyj, jnp.asarray(qxp), jnp.asarray(qyp),
                          kpad, np.int32(n))
    idx = np.asarray(idx)[:nq].astype(np.int64)
    # f32 distances can tie/misorder within ~1e-5 deg: the k + 32
    # candidate slack absorbs the misordering and the host re-ranks the
    # window in f64. Capacity padding can surface idx >= n only when
    # kpad exceeds n; those slots rank last and never reach the first
    # k <= n positions.
    safe = np.minimum(idx, n - 1)
    dx = px64[safe] - qx64[:, None]
    dy = py64[safe] - qy64[:, None]
    exact = np.sqrt(dx * dx + dy * dy)
    exact[idx >= n] = np.inf
    dists = np.empty((nq, k), np.float64)
    ids = np.empty((nq, k), np.int64)
    for i in range(nq):
        top = np.lexsort((idx[i], exact[i]))[:k]
        dists[i] = exact[i][top]
        ids[i] = idx[i][top]
    return dists, ids


def knn(px: np.ndarray, py: np.ndarray, qx: float, qy: float,
        k: int, device_xy=None) -> tuple[np.ndarray, np.ndarray]:
    """k nearest points to (qx, qy): full-scan distance + device top_k.

    The reference's KNNQuery iteratively expands a geohash spiral
    (process/knn/KNNQuery.scala:27) to avoid touching all rows; at TPU
    scan rates the full scan IS the fast path — one fused kernel, no
    iteration. Returns (distances_deg, indices) sorted ascending.

    This is the batched path with Q = 1 (same kernel shape classes,
    same id-stable tiebreak — see knn_batched). ``device_xy`` passes
    resident f32 columns (see dwithin_join) so a store-backed KNN
    never re-uploads its table.
    """
    d, ids = knn_batched(px, py, float(qx), float(qy), k,
                         device_xy=device_xy)
    return d[0], ids[0]


def prewarm_join_kernels(px64, py64, device_xy=None,
                         radius_deg: float = 0.25,
                         query_counts=(1024,), knn_batches=(1, 8),
                         knn_k: int = 100) -> None:
    """Compile (or load from the persistent compilation cache) the
    dwithin/KNN kernel family for this table's capacity class.

    Called from DataStore ingest (``geomesa.join.prewarm``) the way the
    z-scan path eagerly builds its index, so the FIRST join/KNN query
    pays a cache hit instead of a multi-second XLA compile. Dummy
    queries spread across the x-domain so the slab width — and its pow2
    shape class — matches what domain-wide query batches see. The
    dwithin counts kernel's shape class is (ceil(nq/256), 256); the
    1024 default compiles the four-chunk class the canonical 1k-query
    join workload lands in.
    """
    n = len(px64)
    if n == 0:
        return
    from ..obs.runtime import runtime
    cap = 1 << max(int(n - 1).bit_length(), 0)
    for nq in query_counts:
        qx = np.linspace(-170.0, 170.0, nq)
        qy = np.zeros(nq)
        # a prewarm IS the compile for its shape class: report it as a
        # miss so the runtime plane sees where traces come from
        runtime.note_plan_probe("join", ("dwithin", cap, int(nq)),
                                hit=False)
        dwithin_join(px64, py64, qx, qy, radius_deg, counts_only=True,
                     device_xy=device_xy)
    for q in knn_batches:
        runtime.note_plan_probe("join", ("knn", cap, int(q)), hit=False)
        knn_batched(px64, py64, np.zeros(q), np.zeros(q),
                    min(knn_k, n), device_xy=device_xy)
