"""Spatial joins on device: the ST_DWithin / ST_Contains join kernels.

The reference runs spatial joins via Spark: spatially-partitioned RDDs +
a per-cell sweepline (GeoMesaSparkSQL.scala:312-360, SQLRules
SpatialJoinStrategy:270). On TPU the join is a tiled device kernel:

- the small side (query points / polygons) is padded to a fixed chunk;
- the large side streams through the VPU in one fused program per chunk
  computing the (n x chunk) predicate matrix;
- borderline pairs (within the f32 error band of the threshold) are
  re-checked on host in f64, so results are exact.

Counting and pair-collection both avoid materializing the full bool
matrix on the host: counts reduce on device; pair extraction pulls only
per-chunk hit masks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.fp import f32_band as _f32_band

__all__ = ["dwithin_join", "contains_join", "knn"]


@jax.jit
def _dwithin_matrices(px, py, qx, qy, qvalid, r2_hi, r2_lo):
    """(n,) x (k,) -> definite-hit and uncertain-band bool matrices."""
    dx = px[:, None] - qx[None, :]
    dy = py[:, None] - qy[None, :]
    d2 = dx * dx + dy * dy                       # f32, error-banded
    definite = (d2 <= r2_lo) & qvalid[None, :]
    maybe = (d2 <= r2_hi) & ~definite & qvalid[None, :]
    return definite, maybe


@jax.jit
def _dwithin_count_reduce(px, py, qx, qy, qvalid, r2_hi, r2_lo):
    """Counts-only form: the (n, k) matrix never leaves the device —
    only per-query definite counts and band counts come back."""
    definite, maybe = _dwithin_matrices(px, py, qx, qy, qvalid, r2_hi, r2_lo)
    return (jnp.sum(definite, axis=0, dtype=jnp.int32),
            jnp.sum(maybe, axis=0, dtype=jnp.int32))


def dwithin_join(px: np.ndarray, py: np.ndarray,
                 qx: np.ndarray, qy: np.ndarray,
                 radius_deg: float, chunk: int = 256,
                 counts_only: bool = False):
    """Radius join: for each query point, the points within radius_deg
    (planar degrees, matching the rewritten-DWithin semantics).

    Returns (counts[k], pairs) where pairs is an (m, 2) int array of
    (point_idx, query_idx), or (counts, None) with counts_only.
    """
    px64 = np.asarray(px, np.float64)
    py64 = np.asarray(py, np.float64)
    qx64 = np.asarray(qx, np.float64)
    qy64 = np.asarray(qy, np.float64)
    pxj = jnp.asarray(px64.astype(np.float32))
    pyj = jnp.asarray(py64.astype(np.float32))
    n, k = len(px64), len(qx64)
    span = 360.0
    r2_hi, r2_lo = _f32_band(radius_deg, span)
    r2 = radius_deg * radius_deg

    # band queries re-resolve in exact f64 on host over just the points
    # inside the query's x-slab (sorted-x binary search, built lazily on
    # first band), not the whole table — at large n nearly every query
    # has >= 1 banded pair, so an O(n)-per-query host pass would
    # dominate the device scan
    sorted_x: list = []
    eps = float(np.sqrt(max(r2_hi, 0.0))) - radius_deg + 1e-9

    def exact_count(qj: int) -> int:
        if not sorted_x:
            order = np.argsort(px64, kind="stable")
            sorted_x.append((order, px64[order]))
        xorder, xs = sorted_x[0]
        lo = np.searchsorted(xs, qx64[qj] - radius_deg - eps)
        hi = np.searchsorted(xs, qx64[qj] + radius_deg + eps, side="right")
        rows = xorder[lo:hi]
        d2 = ((px64[rows] - qx64[qj]) ** 2 + (py64[rows] - qy64[qj]) ** 2)
        return int((d2 <= r2).sum())

    counts = np.zeros(k, dtype=np.int64)
    pair_chunks: list[np.ndarray] = []

    for start in range(0, k, chunk):
        end = min(start + chunk, k)
        cqx = np.zeros(chunk, np.float32)
        cqy = np.zeros(chunk, np.float32)
        valid = np.zeros(chunk, bool)
        cqx[: end - start] = qx64[start:end]
        cqy[: end - start] = qy64[start:end]
        valid[: end - start] = True
        args = (pxj, pyj, jnp.asarray(cqx), jnp.asarray(cqy),
                jnp.asarray(valid), np.float32(r2_hi), np.float32(r2_lo))
        if counts_only:
            def_counts, band_counts = _dwithin_count_reduce(*args)
            def_counts = np.asarray(def_counts)[: end - start]
            band_counts = np.asarray(band_counts)[: end - start]
            counts[start:end] += def_counts
            # only queries with band pairs need exact resolution
            for j in np.flatnonzero(band_counts):
                counts[start + j] = exact_count(start + j)
            continue
        definite, maybe = _dwithin_matrices(*args)
        definite = np.array(definite)  # writable host copy
        maybe = np.asarray(maybe)
        # resolve the uncertain band exactly on host (tiny)
        mi, mj = np.nonzero(maybe)
        if len(mi):
            exact = ((px64[mi] - qx64[start + mj]) ** 2
                     + (py64[mi] - qy64[start + mj]) ** 2) <= r2
            definite[mi[exact], mj[exact]] = True
        counts[start:end] += definite.sum(axis=0)[: end - start]
        pi, pj = np.nonzero(definite)
        if len(pi):
            pair_chunks.append(
                np.stack([pi, start + pj], axis=1).astype(np.int64))

    if counts_only:
        return counts, None
    pairs = (np.concatenate(pair_chunks, axis=0) if pair_chunks
             else np.empty((0, 2), dtype=np.int64))
    return counts, pairs


def contains_join(polygons, px: np.ndarray, py: np.ndarray,
                  counts_only: bool = False):
    """ST_Contains join: points vs many polygons (BASELINE config #5).

    Device kernel: bbox prefilter matrix on device per polygon chunk;
    exact point-in-polygon (vectorized host f64, reference evaluator)
    only for points passing the prefilter of each polygon.
    """
    from .st_functions import contains_points
    px = np.asarray(px, np.float64)
    py = np.asarray(py, np.float64)
    k = len(polygons)
    counts = np.zeros(k, dtype=np.int64)
    pairs: list[np.ndarray] = []
    boxes = np.array([p.envelope.as_tuple() for p in polygons], np.float64)

    pxj = jnp.asarray(px.astype(np.float32))
    pyj = jnp.asarray(py.astype(np.float32))

    @jax.jit
    def prefilter(bx):
        # conservative f32 bbox test: widen by one ulp-scale epsilon
        eps = np.float32(1e-4)
        return ((pxj[:, None] >= bx[None, :, 0] - eps)
                & (pxj[:, None] <= bx[None, :, 2] + eps)
                & (pyj[:, None] >= bx[None, :, 1] - eps)
                & (pyj[:, None] <= bx[None, :, 3] + eps))

    chunk = 64
    for start in range(0, k, chunk):
        end = min(start + chunk, k)
        bx = np.zeros((chunk, 4), np.float32)
        bx[: end - start] = boxes[start:end]
        bx[end - start:] = [1e9, 1e9, -1e9, -1e9]
        cand = np.asarray(prefilter(jnp.asarray(bx)))
        for j in range(end - start):
            rows = np.flatnonzero(cand[:, j])
            if len(rows) == 0:
                continue
            poly = polygons[start + j]
            if len(rows) >= 4096:
                # dense case: device crossing-number kernel with exact
                # host recheck only in the edge band (scan/gscan.py)
                from ..scan.gscan import points_in_polygon
                hit = points_in_polygon(px[rows], py[rows], poly)
            else:
                hit = contains_points(poly, px[rows], py[rows])
            rows = rows[hit]
            counts[start + j] = len(rows)
            if not counts_only and len(rows):
                pairs.append(np.stack(
                    [rows, np.full(len(rows), start + j)], axis=1))
    if counts_only:
        return counts, None
    return counts, (np.concatenate(pairs, axis=0) if pairs
                    else np.empty((0, 2), dtype=np.int64))


@functools.partial(jax.jit, static_argnames=("k",))
def _knn_kernel(px, py, qx, qy, k: int):
    d2 = (px - qx) ** 2 + (py - qy) ** 2
    neg, idx = jax.lax.top_k(-d2, k)
    return -neg, idx


def knn(px: np.ndarray, py: np.ndarray, qx: float, qy: float,
        k: int) -> tuple[np.ndarray, np.ndarray]:
    """k nearest points to (qx, qy): full-scan distance + device top_k.

    The reference's KNNQuery iteratively expands a geohash spiral
    (process/knn/KNNQuery.scala:27) to avoid touching all rows; at TPU
    scan rates the full scan IS the fast path — one fused kernel, no
    iteration. Returns (distances_deg, indices) sorted ascending.

    f32 distances can tie/misorder within ~1e-5 deg; the top-(k + pad)
    candidates re-rank on host in f64 for exact order.
    """
    pad = min(len(px), k + 32)
    d2, idx = _knn_kernel(
        jnp.asarray(np.asarray(px, np.float32)),
        jnp.asarray(np.asarray(py, np.float32)),
        np.float32(qx), np.float32(qy), pad)
    idx = np.asarray(idx)
    dx = np.asarray(px, np.float64)[idx] - qx
    dy = np.asarray(py, np.float64)[idx] - qy
    exact = np.sqrt(dx * dx + dy * dy)
    order = np.argsort(exact, kind="stable")[:k]
    return exact[order], idx[order]
