"""Analytic processes over a datastore (geomesa-process analogs,
SURVEY.md 2.3): KNN search, proximity search, unique values, min/max,
tube select — each the WPS-process API shape minus GeoServer."""

from __future__ import annotations

import numpy as np

from ..features.batch import PointColumn
from ..index.api import Query
from ..stats import EnumerationStat, MinMax
from .join import dwithin_join, knn
from .tube import TubeBuilder, tube_select_mask

__all__ = ["knn_process", "knn_spiral_process", "proximity_process",
           "unique_process", "minmax_process", "tube_select_process"]


def _point_cols(store, type_name):
    st = store._state(type_name)
    if st.batch is None or st.n == 0:
        return st, None
    col = st.batch.col(st.sft.geom_field)
    if not isinstance(col, PointColumn):
        raise TypeError("process requires a point geometry type")
    return st, col


def knn_process(store, type_name: str, qx: float, qy: float, k: int,
                ecql=None):
    """KNearestNeighborSearchProcess (knn/KNearestNeighborSearchProcess.scala:30):
    k nearest features to the query point, optionally pre-filtered."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object), np.empty(0)
    if ecql is not None:
        res = store.query(Query(type_name, ecql))
        sub = res.batch
        if sub is None or sub.n == 0:
            return np.empty(0, object), np.empty(0)
        scol = sub.col(st.sft.geom_field)
        d, idx = knn(scol.x, scol.y, qx, qy, min(k, sub.n))
        return sub.ids[idx], d
    d, idx = knn(col.x, col.y, qx, qy, min(k, st.n))
    return st.batch.ids[idx], d


def knn_spiral_process(store, type_name: str, qx: float, qy: float, k: int,
                       estimated_distance: float = 1.0):
    """Geohash-spiral KNN (knn/KNNQuery.scala:27,34-81): iterate cells
    outward from the query point in distance order, run a bbox query per
    cell, keep a bounded PQ, and cut the spiral at the kth distance.

    The device-kernel ``knn_process`` scans the whole column in one
    fused top-k — usually faster on-chip; the spiral bounds work when
    the store is huge and the query local (the reference's reason too).
    """
    from ..geohash import (BoundedNearestNeighbors, GeoHashSpiral,
                           decode_bbox, precision_for_radius)
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object), np.empty(0)
    prec = precision_for_radius(estimated_distance)
    spiral = GeoHashSpiral(qx, qy, prec)
    nn = BoundedNearestNeighbors(k)
    for cell in spiral:
        xmin, ymin, xmax, ymax = decode_bbox(cell)
        res = store.query(Query(
            type_name, f"BBOX({st.sft.geom_field}, "
                       f"{xmin}, {ymin}, {xmax}, {ymax})"))
        if res.batch is not None and res.batch.n:
            c = res.batch.col(st.sft.geom_field)
            d = np.hypot(c.x - qx, c.y - qy)
            for dist, fid in zip(d, res.batch.ids):
                nn.offer(float(dist), fid)
        if nn.full:
            spiral.update_max_distance(nn.max_distance)
    pairs = nn.result()
    return (np.array([p[1] for p in pairs], dtype=object),
            np.array([p[0] for p in pairs]))


def proximity_process(store, type_name: str, qx, qy,
                      radius_deg: float, counts_only: bool = False):
    """ProximitySearchProcess (query/ProximitySearchProcess.scala:32):
    features within radius of any of the query points."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return (np.zeros(len(np.atleast_1d(qx)), np.int64), None)
    counts, pairs = dwithin_join(col.x, col.y, np.atleast_1d(qx),
                                 np.atleast_1d(qy), radius_deg,
                                 counts_only=counts_only)
    if counts_only:
        return counts, None
    ids = st.batch.ids[np.unique(pairs[:, 0])] if len(pairs) else \
        np.empty(0, object)
    return counts, ids


def unique_process(store, type_name: str, attribute: str, ecql=None):
    """UniqueProcess: distinct attribute values with counts."""
    stat = store.stats_query(type_name, f"Enumeration({attribute})", ecql)
    assert isinstance(stat, EnumerationStat)
    return dict(stat.counts)


def minmax_process(store, type_name: str, attribute: str, ecql=None):
    """MinMaxProcess: attribute bounds over matching features."""
    stat = store.stats_query(type_name, f"MinMax({attribute})", ecql)
    assert isinstance(stat, MinMax)
    return stat.min, stat.max


def tube_select_process(store, type_name: str, track_x, track_y,
                        track_millis, buffer_deg: float,
                        bin_millis: int = 3_600_000, max_bins: int = 256):
    """TubeSelectProcess: features inside the space-time tube around the
    track. Returns matched feature ids."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object)
    st.ensure_index()
    if st.scan_data is None:
        raise TypeError("tube select requires a point-indexed store")
    boxes, intervals = TubeBuilder(buffer_deg, bin_millis,
                                   max_bins).build(track_x, track_y,
                                                   track_millis)
    mask = tube_select_mask(st.scan_data, boxes, intervals)
    return st.batch.ids[np.flatnonzero(mask)]
