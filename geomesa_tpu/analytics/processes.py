"""Analytic processes over a datastore (geomesa-process analogs,
SURVEY.md 2.3): KNN search, proximity search, unique values, min/max,
tube select — each the WPS-process API shape minus GeoServer."""

from __future__ import annotations

import numpy as np

from ..features.batch import PointColumn
from ..index.api import Query
from ..stats import EnumerationStat, MinMax
from .join import contains_join, dwithin_join, knn, knn_batched
from .tube import TubeBuilder, tube_select_mask

__all__ = ["knn_process", "knn_batch_process", "contains_process",
           "knn_spiral_process", "proximity_process",
           "unique_process", "minmax_process", "tube_select_process",
           "sampling_process", "query_process", "join_process",
           "point2point_process", "track_label_process",
           "route_search_process", "hash_attribute_process",
           "arrow_conversion_process", "bin_conversion_process",
           "length_spheroid_process", "geohash_process",
           "geohash_decode_process"]


def _point_cols(store, type_name):
    st = store._state(type_name)
    if st.batch is None or st.n == 0:
        return st, None
    col = st.batch.col(st.sft.geom_field)
    if not isinstance(col, PointColumn):
        raise TypeError("process requires a point geometry type")
    return st, col


def _resident_xy(st):
    """The type's device-resident f32 coordinate columns (built by
    ensure_index), so processes scan without re-uploading the table."""
    try:
        st.ensure_index()
    except Exception:
        return None
    sd = getattr(st, "scan_data", None)
    return None if sd is None else (sd.xhi, sd.yhi)


def _knn_zring(st, col, qx: float, qy: float, k: int):
    """Z-index ring-expansion KNN: the reference's iterative geohash
    spiral (knn/KNNQuery.scala:27-81) with its distance-bounded cut
    (knn/GeoHashSpiral.scala:53,80), re-keyed to the z2 sorted index —
    grow a box around the query until it provably contains the k
    nearest (the kth candidate distance fits inside the box radius),
    then exact f64 top-k over just the in-box rows. Touches O(rows
    near q), never the full table. Returns (distances, rows) ascending,
    or None when the index is unavailable / the region is too dense for
    the host tier (caller falls back to the fused device scan)."""
    if k <= 0:
        return np.empty(0), np.empty(0, dtype=np.int64)
    try:
        st.ensure_index()
    except Exception:
        return None
    zi = st.zindex
    if zi is None or st.n == 0:
        return None
    from ..index.zkeys import search_rows
    # initial radius sized so the box holds ~2k points at the GLOBAL
    # density (8k/pi in the box); local density deviations just mean an
    # extra doubling or a one-round shrink via the dk bound
    rho = max(st.n, 1) / (360.0 * 180.0)
    r = float(np.sqrt(2.0 * k / (np.pi * rho)))
    cap = 2_000_000  # host-tier ceiling; denser regions use the kernel
    for _ in range(64):
        if (qx - r <= -180.0 and qx + r >= 180.0
                and qy - r <= -90.0 and qy + r >= 90.0):
            # ring covers the world: the candidate set is the whole
            # table, which is exactly what the fused kernel is for
            return None
        box = (max(qx - r, -180.0), max(qy - r, -90.0),
               min(qx + r, 180.0), min(qy + r, 90.0))
        # cache=False: these boxes never repeat — they must not flush
        # the decomposition cache serving repeated store queries
        kind, rows = search_rows(zi, "z2", [box], [], cap, cap,
                                 cache=False)
        if kind != "exact":
            return None
        if len(rows) >= k:
            dx = col.x[rows] - qx
            dy = col.y[rows] - qy
            d2 = dx * dx + dy * dy
            # (distance, row) tiebreak, same as the fused kernel: an
            # argpartition cut picks an arbitrary member of a distance
            # tie at the k boundary, so gather every candidate within
            # the kth distance first, then break ties on row id
            part = (np.argpartition(d2, k - 1)[:k]
                    if len(rows) > k else np.arange(len(rows)))
            kth = d2[part].max()
            cand = np.flatnonzero(d2 <= kth)
            top = cand[np.lexsort((rows[cand], d2[cand]))[:k]]
            dk = float(np.sqrt(d2[top].max()))
            if dk <= r:
                return np.sqrt(d2[top]), rows[top]
            # candidates found but the kth may lie outside the box:
            # one more round with the proven cover radius
            r = dk * (1.0 + 1e-12)
        else:
            r *= 2.0
    return None


def knn_process(store, type_name: str, qx, qy, k: int, ecql=None):
    """KNearestNeighborSearchProcess (knn/KNearestNeighborSearchProcess.scala:30):
    k nearest features to the query point, optionally pre-filtered.

    ``qx``/``qy`` may be arrays — the reference process takes a
    *collection* of query features; a multi-query call routes through
    the fused batched dispatch (knn_batch_process) and returns a list
    of (ids, distances) pairs, one per query point."""
    if np.ndim(qx) > 0:
        return knn_batch_process(store, type_name, qx, qy, k, ecql=ecql)
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object), np.empty(0)
    if ecql is not None:
        res = store.query(Query(type_name, ecql))
        sub = res.batch
        if sub is None or sub.n == 0:
            return np.empty(0, object), np.empty(0)
        scol = sub.col(st.sft.geom_field)
        d, idx = knn(scol.x, scol.y, qx, qy, min(k, sub.n))
        return sub.ids[idx], d
    pruned = _knn_zring(st, col, qx, qy, min(k, st.n))
    if pruned is not None:
        d, rows = pruned
        return st.batch.ids[rows], d
    d, idx = knn(col.x, col.y, qx, qy, min(k, st.n),
                 device_xy=_resident_xy(st))
    return st.batch.ids[idx], d


def knn_batch_process(store, type_name: str, qx, qy, k: int, ecql=None):
    """Batched KNN: ONE fused device dispatch answers every query point
    (analytics/join.knn_batched) against the resident coordinate
    columns — Q queries cost one kernel launch + one transfer instead
    of Q round trips. Returns [(ids, distances), ...] per query,
    distances ascending with the id-stable tiebreak."""
    qx = np.atleast_1d(np.asarray(qx, np.float64))
    qy = np.atleast_1d(np.asarray(qy, np.float64))
    st, col = _point_cols(store, type_name)
    if col is None:
        return [(np.empty(0, object), np.empty(0)) for _ in qx]
    if ecql is not None:
        res = store.query(Query(type_name, ecql))
        sub = res.batch
        if sub is None or sub.n == 0:
            return [(np.empty(0, object), np.empty(0)) for _ in qx]
        scol = sub.col(st.sft.geom_field)
        d, idx = knn_batched(scol.x, scol.y, qx, qy, min(k, sub.n))
        return [(sub.ids[idx[i]], d[i]) for i in range(len(qx))]
    d, idx = knn_batched(col.x, col.y, qx, qy, min(k, st.n),
                         device_xy=_resident_xy(st))
    return [(st.batch.ids[idx[i]], d[i]) for i in range(len(qx))]


def contains_process(store, type_name: str, polygons,
                     counts_only: bool = True):
    """Batched ST_Contains over the resident point columns: counts (and
    optionally matching feature ids) per polygon via the fused x-slab +
    crossing-number kernel (analytics/join.contains_join) — the
    points-vs-polygons join surface BASELINE config #5 measures.
    Returns (counts, None) or (counts, [ids_per_polygon, ...])."""
    st, col = _point_cols(store, type_name)
    k = len(polygons)
    if col is None:
        return (np.zeros(k, np.int64),
                None if counts_only else [np.empty(0, object)] * k)
    counts, pairs = contains_join(polygons, col.x, col.y,
                                  counts_only=counts_only,
                                  device_xy=_resident_xy(st))
    if counts_only:
        return counts, None
    ids = []
    for j in range(k):
        rows = pairs[pairs[:, 1] == j, 0] if len(pairs) else \
            np.empty(0, np.int64)
        ids.append(st.batch.ids[rows])
    return counts, ids


def knn_spiral_process(store, type_name: str, qx: float, qy: float, k: int,
                       estimated_distance: float = 1.0):
    """Geohash-spiral KNN (knn/KNNQuery.scala:27,34-81): iterate cells
    outward from the query point in distance order, run a bbox query per
    cell, keep a bounded PQ, and cut the spiral at the kth distance.

    The device-kernel ``knn_process`` scans the whole column in one
    fused top-k — usually faster on-chip; the spiral bounds work when
    the store is huge and the query local (the reference's reason too).
    """
    from ..geohash import (BoundedNearestNeighbors, GeoHashSpiral,
                           decode_bbox, precision_for_radius)
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object), np.empty(0)
    prec = precision_for_radius(estimated_distance)
    spiral = GeoHashSpiral(qx, qy, prec)
    nn = BoundedNearestNeighbors(k)
    for cell in spiral:
        xmin, ymin, xmax, ymax = decode_bbox(cell)
        res = store.query(Query(
            type_name, f"BBOX({st.sft.geom_field}, "
                       f"{xmin}, {ymin}, {xmax}, {ymax})"))
        if res.batch is not None and res.batch.n:
            c = res.batch.col(st.sft.geom_field)
            d = np.hypot(c.x - qx, c.y - qy)
            for dist, fid in zip(d, res.batch.ids):
                nn.offer(float(dist), fid)
        if nn.full:
            spiral.update_max_distance(nn.max_distance)
    pairs = nn.result()
    return (np.array([p[1] for p in pairs], dtype=object),
            np.array([p[0] for p in pairs]))


def proximity_process(store, type_name: str, qx, qy,
                      radius_deg: float, counts_only: bool = False):
    """ProximitySearchProcess (query/ProximitySearchProcess.scala:32):
    features within radius of any of the query points."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return (np.zeros(len(np.atleast_1d(qx)), np.int64), None)
    counts, pairs = dwithin_join(col.x, col.y, np.atleast_1d(qx),
                                 np.atleast_1d(qy), radius_deg,
                                 counts_only=counts_only,
                                 device_xy=_resident_xy(st))
    if counts_only:
        return counts, None
    ids = st.batch.ids[np.unique(pairs[:, 0])] if len(pairs) else \
        np.empty(0, object)
    return counts, ids


def unique_process(store, type_name: str, attribute: str, ecql=None):
    """UniqueProcess: distinct attribute values with counts."""
    stat = store.stats_query(type_name, f"Enumeration({attribute})", ecql)
    assert isinstance(stat, EnumerationStat)
    return dict(stat.counts)


def minmax_process(store, type_name: str, attribute: str, ecql=None):
    """MinMaxProcess: attribute bounds over matching features."""
    stat = store.stats_query(type_name, f"MinMax({attribute})", ecql)
    assert isinstance(stat, MinMax)
    return stat.min, stat.max


def tube_select_process(store, type_name: str, track_x, track_y,
                        track_millis, buffer_deg: float,
                        bin_millis: int = 3_600_000, max_bins: int = 256):
    """TubeSelectProcess: features inside the space-time tube around the
    track. Returns matched feature ids."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object)
    st.ensure_index()
    if st.scan_data is None:
        raise TypeError("tube select requires a point-indexed store")
    boxes, intervals = TubeBuilder(buffer_deg, bin_millis,
                                   max_bins).build(track_x, track_y,
                                                   track_millis)
    mask = tube_select_mask(st.scan_data, boxes, intervals)
    return st.batch.ids[np.flatnonzero(mask)]


def sampling_process(store, type_name: str, ecql=None, rate: float = 0.1,
                     by: str | None = None):
    """SamplingProcess (process/vector/SamplingProcess): thin the result
    set to ~rate, optionally per `by`-attribute group."""
    from ..index.api import QueryHints
    q = Query(type_name, ecql or "INCLUDE")
    q.hints[QueryHints.SAMPLING] = rate
    if by is not None:
        q.hints[QueryHints.SAMPLE_BY] = by
    return store.query(q)


def query_process(store, type_name: str, ecql):
    """QueryProcess (process/query/QueryProcess): pass-through query —
    the WPS chaining primitive."""
    return store.query(Query(type_name, ecql))


def join_process(store, primary_type: str, join_type: str,
                 attribute: str, join_attribute: str | None = None,
                 ecql=None):
    """JoinProcess (process/query/JoinProcess): attribute equi-join —
    features of `join_type` whose `join_attribute` matches a value of
    `attribute` in the (optionally filtered) primary features."""
    join_attribute = join_attribute or attribute
    res = store.query(Query(primary_type, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return store.query(Query(join_type, "EXCLUDE"))
    col = res.batch.col(attribute)
    vals = {col.value(i) for i in range(res.batch.n)} - {None}
    if not vals:
        return store.query(Query(join_type, "EXCLUDE"))
    quoted = ", ".join(
        "'" + v.replace("'", "''") + "'" if isinstance(v, str) else str(v)
        for v in sorted(vals))
    return store.query(Query(join_type, f"{join_attribute} IN ({quoted})"))


def point2point_process(store, type_name: str, group_by: str,
                        sort_by: str | None = None, ecql=None):
    """Point2PointProcess (process/vector/Point2PointProcess): connect
    each group's time-ordered points into line segments. Returns
    {group: (k, 2, 2) segment array [[x0,y0],[x1,y1]]}."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return {}
    q = Query(type_name, ecql or "INCLUDE")
    q.sort_by = sort_by or st.sft.dtg_field  # store sorts the results
    res = store.query(q)
    if res.batch is None or res.n == 0:
        return {}
    batch = res.batch
    gcol = batch.col(st.sft.geom_field)
    keys = np.array([batch.col(group_by).value(i) for i in range(batch.n)],
                    dtype=object)
    order = np.arange(batch.n)
    out = {}
    for g in set(keys.tolist()):
        rows = order[keys[order] == g]
        if len(rows) < 2:
            continue
        xs, ys = gcol.x[rows], gcol.y[rows]
        segs = np.stack([np.stack([xs[:-1], ys[:-1]], axis=1),
                         np.stack([xs[1:], ys[1:]], axis=1)], axis=1)
        out[g] = segs
    return out


def track_label_process(store, type_name: str, track: str, label: str,
                        ecql=None):
    """TrackLabelProcess (process/vector/TrackLabelProcess): reduce each
    track to its most recent point + label attribute. Returns
    {track: (x, y, label_value)}."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return {}
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return {}
    batch = res.batch
    gcol = batch.col(st.sft.geom_field)
    tvals = np.array([batch.col(track).value(i) for i in range(batch.n)],
                     dtype=object)
    dtg = st.sft.dtg_field
    ms = (batch.col(dtg).millis if dtg is not None
          else np.arange(batch.n, dtype=np.int64))
    out = {}
    for t in set(tvals.tolist()):
        rows = np.flatnonzero(tvals == t)
        last = rows[np.argmax(ms[rows])]
        out[t] = (float(gcol.x[last]), float(gcol.y[last]),
                  batch.col(label).value(int(last)))
    return out


def route_search_process(store, type_name: str, route_x, route_y,
                         buffer_deg: float, ecql=None):
    """RouteSearchProcess (process/query/RouteSearchProcess): features
    within buffer_deg of a route polyline — the TubeBuilder's gap-fill
    densification + the device DWithin join against route vertices."""
    st, col = _point_cols(store, type_name)
    if col is None:
        return np.empty(0, object)
    route_x = np.asarray(route_x, np.float64)
    route_y = np.asarray(route_y, np.float64)
    # densify the polyline so vertex spacing <= buffer (LineGapFill
    # analog, tube/TubeBuilder.scala:182): the DWithin join against the
    # dense vertices then covers the whole route corridor
    dxs, dys = [route_x[:1]], [route_y[:1]]
    for i in range(len(route_x) - 1):
        seg = np.hypot(route_x[i + 1] - route_x[i],
                       route_y[i + 1] - route_y[i])
        steps = max(int(np.ceil(seg / max(buffer_deg, 1e-9))), 1)
        t = np.linspace(0, 1, steps + 1)[1:]
        dxs.append(route_x[i] + t * (route_x[i + 1] - route_x[i]))
        dys.append(route_y[i] + t * (route_y[i + 1] - route_y[i]))
    dx = np.concatenate(dxs)
    dy = np.concatenate(dys)
    if ecql is not None:
        res = store.query(Query(type_name, ecql))
        if res.batch is None or res.n == 0:
            return np.empty(0, object)
        batch = res.batch
        pcol = batch.col(st.sft.geom_field)
    else:
        batch = st.batch
        pcol = col
    # vertex prefilter radius covers the corridor between vertices
    # (worst case: point at buffer from a segment midpoint), then the
    # exact distance-to-polyline check runs on candidates only
    r_pre = float(np.hypot(buffer_deg, buffer_deg / 2))
    _, pairs = dwithin_join(pcol.x, pcol.y, dx, dy, r_pre)
    hit = np.zeros(batch.n, dtype=bool)
    if len(pairs):
        cand = np.unique(pairs[:, 0])
        if len(route_x) < 2:
            # degenerate single-vertex route: plain radius test
            d2 = ((pcol.x[cand] - route_x[0]) ** 2
                  + (pcol.y[cand] - route_y[0]) ** 2)
            keep = d2 <= buffer_deg * buffer_deg
        else:
            from ..geometry.base import _point_segments_dist2
            coords = np.stack([route_x, route_y], axis=1)
            keep = np.array([
                _point_segments_dist2(pcol.x[i], pcol.y[i], coords)
                <= buffer_deg * buffer_deg for i in cand])
        hit[cand[keep]] = True
    return batch.ids[hit]


def hash_attribute_process(store, type_name: str, attribute: str,
                           modulo: int, ecql=None) -> np.ndarray:
    """HashAttributeProcess (process/transform/HashAttributeProcess):
    stable per-feature hash of an attribute mod `modulo` (coloring /
    partitioning aid)."""
    from ..scan.aggregations import _id_hashes
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, np.int64)
    col = res.batch.col(attribute)
    vals = np.array([str(col.value(i)) for i in range(res.batch.n)],
                    dtype=object)
    # java String.hashCode (shared with the BIN encoder) mod modulo;
    # numpy % with a positive divisor is non-negative
    return _id_hashes(vals).astype(np.int64) % modulo


def arrow_conversion_process(store, type_name: str, ecql=None) -> bytes:
    """ArrowConversionProcess (process/transform/ArrowConversionProcess
    :38): query results as Arrow IPC stream bytes."""
    import io

    import pyarrow as pa
    rb = store.arrow_query(type_name, ecql or "INCLUDE")
    if rb is None:  # empty result: stream with the schema, zero batches
        from ..features.batch import FeatureBatch
        sft = store.get_schema(type_name)
        rb = FeatureBatch.from_dict(
            sft, [], {a.name: [] for a in sft.attributes}).to_arrow()
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def bin_conversion_process(store, type_name: str, ecql=None,
                           track: str | None = None,
                           label: str | None = None) -> bytes:
    """BinConversionProcess (process/transform/BinConversionProcess):
    query results as BIN records."""
    return store.bin_query(type_name, ecql or "INCLUDE", track=track,
                           label=label)


def length_spheroid_process(store, type_name: str, attribute: str,
                            ecql=None) -> np.ndarray:
    """Per-feature WGS84 geodesic length of a geometry attribute
    (process form of ST_LengthSpheroid); NaN for null geometries."""
    from .st_functions import st_length_spheroid
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, np.float64)
    col = res.batch.col(attribute)
    return np.array([st_length_spheroid(g) if (g := col.value(i)) is not None
                     else np.nan for i in range(res.batch.n)], np.float64)


def geohash_process(store, type_name: str, attribute: str,
                    prec: int = 25, ecql=None) -> np.ndarray:
    """Per-feature geohash of a geometry attribute at ``prec`` bits
    (process form of ST_GeoHash); None for null geometries."""
    from .st_functions import st_geohash
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, object)
    col = res.batch.col(attribute)
    return np.array([st_geohash(g, prec) if (g := col.value(i)) is not None
                     else None for i in range(res.batch.n)], object)


def geohash_decode_process(hashes, prec: int | None = None) -> np.ndarray:
    """Geohash strings back to cell-bbox polygons (process form of
    ST_GeomFromGeoHash); None passes through."""
    from .st_functions import st_geom_from_geohash
    return np.array([st_geom_from_geohash(h, prec) if h is not None
                     else None for h in np.asarray(hashes, object)],
                    object)


def point_n_process(store, type_name: str, attribute: str, n: int,
                    ecql=None) -> np.ndarray:
    """Per-feature n-th vertex of a LineString attribute (process form
    of ST_PointN); None for nulls / non-lines / out of range."""
    from .st_functions import st_point_n
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, object)
    col = res.batch.col(attribute)
    return np.array([st_point_n(g, n) if (g := col.value(i)) is not None
                     else None for i in range(res.batch.n)], object)


def exterior_ring_process(store, type_name: str, attribute: str,
                          ecql=None) -> np.ndarray:
    """Per-feature polygon shell as a LineString (process form of
    ST_ExteriorRing); None for nulls / non-polygons."""
    from .st_functions import st_exterior_ring
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, object)
    col = res.batch.col(attribute)
    return np.array([st_exterior_ring(g) if (g := col.value(i)) is not None
                     else None for i in range(res.batch.n)], object)


def num_points_process(store, type_name: str, attribute: str,
                       ecql=None) -> np.ndarray:
    """Per-feature vertex count (process form of ST_NumPoints); -1 for
    null geometries (int column, no NaN slot)."""
    from .st_functions import st_num_points
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, np.int64)
    col = res.batch.col(attribute)
    return np.array([st_num_points(g) if (g := col.value(i)) is not None
                     else -1 for i in range(res.batch.n)], np.int64)


def translate_process(store, type_name: str, attribute: str,
                      dx: float, dy: float, ecql=None) -> np.ndarray:
    """Per-feature geometry shifted by (dx, dy) (process form of
    ST_Translate); None for null geometries."""
    from .st_functions import st_translate
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, object)
    col = res.batch.col(attribute)
    return np.array([st_translate(g, dx, dy)
                     if (g := col.value(i)) is not None
                     else None for i in range(res.batch.n)], object)


def idl_safe_geom_process(store, type_name: str, attribute: str,
                          ecql=None) -> np.ndarray:
    """Per-feature dateline-safe geometry (process form of
    ST_IdlSafeGeom, the st_antimeridianSafeGeom alias); None for null
    geometries."""
    from .st_functions import st_idl_safe_geom
    res = store.query(Query(type_name, ecql or "INCLUDE"))
    if res.batch is None or res.n == 0:
        return np.empty(0, object)
    col = res.batch.col(attribute)
    return np.array([st_idl_safe_geom(g)
                     if (g := col.value(i)) is not None
                     else None for i in range(res.batch.n)], object)
