"""Spatial partitioning for parallel joins (geomesa-spark-sql analog).

The reference spatially partitions both sides of an ST join so matching
cells join pairwise (GeoMesaSparkSQL.scala:228-289 `spatiallyPartition`,
RelationUtils.spatiallyPartition:457 grid / weighted envelopes,
sql/IndexPartitioner.scala:13), then zipPartitions runs a sweepline per
cell (GeoMesaJoinRelation:312). Here partitions are envelope lists,
assignment is a vectorized kernel, and the per-cell join runs the fused
device kernels (analytics/join.py) cell-by-cell — cells are the outer
(host) loop, the inner loops are XLA.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .join import dwithin_join

__all__ = ["grid_partitions", "quadtree_partitions", "assign_partitions",
           "IndexPartitioner", "partitioned_dwithin_join"]


def grid_partitions(envelope, nx: int, ny: int) -> np.ndarray:
    """(nx*ny, 4) equal-size grid envelopes covering `envelope`
    (RelationUtils equal-grid partitioning)."""
    xmin, ymin, xmax, ymax = (float(v) for v in envelope)
    xs = np.linspace(xmin, xmax, nx + 1)
    ys = np.linspace(ymin, ymax, ny + 1)
    cells = [(xs[i], ys[j], xs[i + 1], ys[j + 1])
             for j in range(ny) for i in range(nx)]
    return np.asarray(cells)


def quadtree_partitions(x, y, target_per_cell: int = 10_000,
                        max_level: int = 12,
                        sample: int = 100_000) -> np.ndarray:
    """Weighted quadtree from a data sample: refine cells until each
    holds <= target (the weighted-envelope strategy,
    GeoMesaSparkSQL.scala:252-289). Returns (n_cells, 4) envelopes
    covering the data's bbox exactly."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if len(x) > sample:
        idx = np.random.default_rng(0).choice(len(x), sample, replace=False)
        x, y = x[idx], y[idx]
    xmin, xmax = float(x.min()), float(x.max())
    ymin, ymax = float(y.min()), float(y.max())
    # expand a hair so max points fall strictly inside
    ex = (xmax - xmin or 1.0) * 1e-9
    ey = (ymax - ymin or 1.0) * 1e-9
    out: list = []
    stack = [(xmin, ymin, xmax + ex, ymax + ey, 0,
              np.arange(len(x)))]
    while stack:
        x0, y0, x1, y1, lvl, idx = stack.pop()
        if len(idx) <= target_per_cell or lvl >= max_level:
            out.append((x0, y0, x1, y1))
            continue
        mx, my = (x0 + x1) / 2, (y0 + y1) / 2
        right = x[idx] >= mx
        top = y[idx] >= my
        for quad, (qx0, qy0, qx1, qy1) in (
                (idx[~right & ~top], (x0, y0, mx, my)),
                (idx[right & ~top], (mx, y0, x1, my)),
                (idx[~right & top], (x0, my, mx, y1)),
                (idx[right & top], (mx, my, x1, y1))):
            stack.append((qx0, qy0, qx1, qy1, lvl + 1, quad))
    return np.asarray(out)


def assign_partitions(x, y, envelopes: np.ndarray) -> np.ndarray:
    """Partition index per point (-1 if in no cell). Cells are
    half-open [x0, x1) x [y0, y1) so assignment is unique for grid and
    quadtree layouts."""
    x = np.asarray(x, dtype=np.float64)[:, None]
    y = np.asarray(y, dtype=np.float64)[:, None]
    e = np.asarray(envelopes, dtype=np.float64)[None, :, :]
    inside = ((x >= e[:, :, 0]) & (x < e[:, :, 2])
              & (y >= e[:, :, 1]) & (y < e[:, :, 3]))
    hit = inside.argmax(axis=1)
    return np.where(inside.any(axis=1), hit, -1).astype(np.int64)


@dataclasses.dataclass
class IndexPartitioner:
    """Partition router: index i -> partition i (IndexPartitioner.scala:13);
    exists so pre-assigned partition ids shuffle straight through."""
    num_partitions: int

    def partition(self, key: int) -> int:
        if not 0 <= key < self.num_partitions:
            raise KeyError(f"partition {key} out of range")
        return int(key)


def partitioned_dwithin_join(xa, ya, xb, yb, radius_deg: float,
                             envelopes: np.ndarray | None = None,
                             target_per_cell: int = 50_000):
    """Distance join via spatial partitioning: side A partitions by cell,
    side B replicates into every cell its radius-buffer touches (the
    reference covers the same with partition-envelope overlap in
    SpatialJoinStrategy), then each cell joins with the fused device
    kernel. Returns (n_pairs, 2) [a_idx, b_idx] global indices.
    """
    xa = np.asarray(xa, dtype=np.float64)
    ya = np.asarray(ya, dtype=np.float64)
    xb = np.asarray(xb, dtype=np.float64)
    yb = np.asarray(yb, dtype=np.float64)
    if envelopes is None:
        envelopes = quadtree_partitions(
            np.concatenate([xa, xb]), np.concatenate([ya, yb]),
            target_per_cell=target_per_cell)
    pa = assign_partitions(xa, ya, envelopes)
    pairs = []
    e = np.asarray(envelopes, dtype=np.float64)

    def pad_pow2(x, y, fill):
        """Pad to the next power of two with far-away points: per-cell
        sizes vary, and every distinct size is a fresh XLA compile —
        pow2 buckets make the shapes repeat so the kernel compiles
        O(log n) times total instead of once per cell. The two sides
        pad to OPPOSITE far corners — same-corner pads would x-slab
        match each other and blow the kernel's slab width up to the
        pad count."""
        n = len(x)
        cap = 1 << max(n - 1, 1).bit_length()
        if cap == n:
            return x, y, n
        xp = np.full(cap, fill)
        yp = np.full(cap, fill)
        xp[:n] = x
        yp[:n] = y
        return xp, yp, n

    for c in range(len(e)):
        ia = np.flatnonzero(pa == c)
        if not len(ia):
            continue
        x0, y0, x1, y1 = e[c]
        ib = np.flatnonzero((xb >= x0 - radius_deg) & (xb < x1 + radius_deg)
                            & (yb >= y0 - radius_deg) & (yb < y1 + radius_deg))
        if not len(ib):
            continue
        axp, ayp, na = pad_pow2(xa[ia], ya[ia], 1e9)
        bxp, byp, nb = pad_pow2(xb[ib], yb[ib], -1e9)
        _, local = dwithin_join(axp, ayp, bxp, byp, radius_deg)
        if len(local):
            keep = (local[:, 0] < na) & (local[:, 1] < nb)
            local = local[keep]
        if len(local):
            pairs.append(np.stack([ia[local[:, 0]], ib[local[:, 1]]], axis=1))
    if not pairs:
        return np.empty((0, 2), dtype=np.int64)
    out = np.concatenate(pairs)
    return out[np.lexsort((out[:, 1], out[:, 0]))]
