"""Leaflet rendering DSL (geomesa-jupyter analog:
jupyter/Leaflet.scala:11 — the `L` object renders features/layers as a
self-contained HTML/JS snippet for notebook display).

    html = L.render([
        L.GeoJsonLayer(features, style={"color": "#2266cc"}),
        L.HeatmapLayer(grid, bbox),
        L.Circle(-75.1, 38.2, 5000),
    ], center=(-75, 38), zoom=6)

The output embeds data inline and references the Leaflet CDN, matching
the reference's notebook workflow (rendering happens client-side).
"""

from __future__ import annotations

import json
from typing import Any, Iterable

import numpy as np

__all__ = ["L"]

_PAGE = """<div id="{div_id}" style="height:{height}px"></div>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<script>
(function() {{
  var map = L.map('{div_id}').setView([{lat}, {lon}], {zoom});
  L.tileLayer('https://tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
              {{maxZoom: 19}}).addTo(map);
{layers}
}})();
</script>"""


class _Layer:
    def to_js(self, var: str) -> str:
        raise NotImplementedError


class GeoJsonLayer(_Layer):
    def __init__(self, features, style: dict | None = None):
        from ..geometry import Geometry
        from ..geometry.geojson import to_geojson
        feats = []
        for f in features:
            if isinstance(f, Geometry):
                feats.append({"type": "Feature",
                              "geometry": to_geojson(f), "properties": {}})
            elif isinstance(f, dict) and "geometry" in f:
                feats.append(f)
            else:
                raise TypeError("GeoJsonLayer wants geometries or features")
        self.collection = {"type": "FeatureCollection", "features": feats}
        self.style = style or {}

    def to_js(self, var: str) -> str:
        return (f"  var {var} = L.geoJSON({json.dumps(self.collection)}, "
                f"{{style: function() {{ return "
                f"{json.dumps(self.style)}; }}}}).addTo(map);")


class PointsLayer(_Layer):
    """Circle markers from coordinate arrays (fast path for big batches)."""

    def __init__(self, x, y, radius: int = 3, color: str = "#cc3311",
                 max_points: int = 10000):
        x = np.asarray(x, dtype=float)
        y = np.asarray(y, dtype=float)
        if len(x) > max_points:  # thin for the browser
            step = int(np.ceil(len(x) / max_points))
            x, y = x[::step], y[::step]
        self.coords = np.stack([y, x], axis=1).round(6).tolist()
        self.radius = radius
        self.color = color

    def to_js(self, var: str) -> str:
        return (f"  var {var}_pts = {json.dumps(self.coords)};\n"
                f"  var {var} = L.layerGroup({var}_pts.map(function(c) {{\n"
                f"    return L.circleMarker(c, {{radius: {self.radius}, "
                f"color: {json.dumps(self.color)}, weight: 1}});\n"
                f"  }})).addTo(map);")


class HeatmapLayer(_Layer):
    """Density grid -> translucent colored rectangles (the DensityProcess
    output rendered without plugin dependencies)."""

    def __init__(self, grid, bbox, color: str = "#cc3311",
                 opacity_max: float = 0.8):
        self.grid = np.asarray(grid, dtype=float)
        self.bbox = tuple(float(v) for v in bbox)
        self.color = color
        self.opacity_max = opacity_max

    def to_js(self, var: str) -> str:
        h, w = self.grid.shape
        x0, y0, x1, y1 = self.bbox
        top = float(self.grid.max()) or 1.0
        cells = []
        sx, sy = (x1 - x0) / w, (y1 - y0) / h
        for r, c in zip(*np.nonzero(self.grid)):
            cells.append([round(y0 + r * sy, 6), round(x0 + c * sx, 6),
                          round(float(self.grid[r, c]) / top, 4)])
        return (f"  var {var}_cells = {json.dumps(cells)};\n"
                f"  var {var} = L.layerGroup({var}_cells.map(function(e) {{\n"
                f"    return L.rectangle([[e[0], e[1]], "
                f"[e[0] + {sy:.8f}, e[1] + {sx:.8f}]], "
                f"{{stroke: false, fillColor: {json.dumps(self.color)}, "
                f"fillOpacity: e[2] * {self.opacity_max}}});\n"
                f"  }})).addTo(map);")


class Circle(_Layer):
    def __init__(self, x: float, y: float, radius_m: float,
                 color: str = "#2266cc"):
        self.x, self.y, self.radius_m, self.color = x, y, radius_m, color

    def to_js(self, var: str) -> str:
        return (f"  var {var} = L.circle([{self.y}, {self.x}], "
                f"{{radius: {self.radius_m}, "
                f"color: {json.dumps(self.color)}}}).addTo(map);")


class _LDsl:
    """The `L` entry point (mirrors the reference's `L` object)."""

    GeoJsonLayer = GeoJsonLayer
    PointsLayer = PointsLayer
    HeatmapLayer = HeatmapLayer
    Circle = Circle

    _counter = 0

    def render(self, layers: Iterable[_Layer], center=(0.0, 0.0),
               zoom: int = 3, height: int = 500) -> str:
        _LDsl._counter += 1
        div_id = f"geomesa_map_{_LDsl._counter}"
        js = "\n".join(layer.to_js(f"lyr{i}")
                       for i, layer in enumerate(layers))
        return _PAGE.format(div_id=div_id, height=height,
                            lon=float(center[0]), lat=float(center[1]),
                            zoom=zoom, layers=js)

    def display(self, layers, **kw):  # pragma: no cover - notebook only
        from IPython.display import HTML
        return HTML(self.render(layers, **kw))


L = _LDsl()
