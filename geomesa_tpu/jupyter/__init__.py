"""Notebook visualization (geomesa-jupyter analog)."""

from .leaflet import L

__all__ = ["L"]
